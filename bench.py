"""Headline benchmark: encrypted logistic-regression training, Pima-shaped
(10 DPs x 768 distinct records each, 8 features, K=2, 450 GD iterations),
WITH the verification pipeline on: DP encode+encrypt + range-proof creation
-> collective aggregation (+ proof) -> key switch (+ proofs) -> VN
verification of every proof -> audit-block commit -> querier decrypt -> GD.

Baselines (BASELINE.md, reference TIFS/logRegV2.py:9-14, Go/CPU):
  proofs ON  total: 12.2 s   (exec 1.2 + proof overhead 10.9 + decode 0.12)
  exec-only  total: ~1.32 s  (exec + decode, no proofs)

Un-killable-record contract (round-3 VERDICT #2): this script prints
EXACTLY ONE JSON line to stdout and exits 0 under every failure mode we
can anticipate —
  * backend-init failure (r03: TPU 'UNAVAILABLE' before any try block):
    the backend is probed in a SUBPROCESS with bounded retry/backoff
    before any in-process JAX dispatch; persistent unavailability emits an
    honest labeled JSON.
  * SIGTERM/SIGINT mid-run (driver budget): a signal handler emits a
    labeled JSON before exiting (the r02 failure mode).
  * import/other errors: the __main__ guard emits a labeled JSON.
The proofs-on benchmark runs FIRST and the headline JSON prints
immediately after the first successful timed run; extra runs and the
exec-only number are bonus stderr diagnostics after the JSON is out.
"""
import faulthandler
import json
import os
import signal
import subprocess
import sys
import time

# live stack dumps on demand (kill -USR1 <pid>) and periodic stall traces:
# round-3 debugging found the process wedged at 0% CPU with no evidence
faulthandler.register(signal.SIGUSR1, file=sys.stderr)
faulthandler.dump_traceback_later(900, repeat=True, file=sys.stderr)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PROOFS_S = 12.2
BASELINE_EXEC_S = 1.32
RANGES = (16, 5)     # reference simulation preset 18 (drynx_simul.go case 18)

# --no-verify-cache: the UNDEDUPED control run (round-4 VERDICT task 10).
# The default headline lets co-located VNs share one VerifyCache — identical
# payloads verify once per process, matching the reference's
# parallel-machines accounting (each of its VNs verifies on its own box
# simultaneously; dedup factor: 9 keyswitch verifies -> 1, 3 joint-range ->
# 1). This flag DISABLES the cache (VerifyCache maxsize=0) so every
# delivery recomputes — all 9 keyswitch verifies run — and the true
# single-chip SERIAL cost of all verifications lands beside the headline.
NO_DEDUP = "--no-verify-cache" in sys.argv

_t0 = time.time()
_JSON_DONE = False


def log(msg):
    print(f"[{time.time() - _t0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def emit(obj) -> None:
    """The ONE-JSON-line contract: first call wins, later calls are logs."""
    global _JSON_DONE
    if _JSON_DONE:
        log(f"suppressed extra JSON (contract is one line): {obj}")
        return
    _JSON_DONE = True
    print(json.dumps(obj), flush=True)


def _signal_exit(signum, frame):
    """Driver timeout/abort (SIGTERM) or ^C: the record must still parse.
    Uses os.write (async-signal-safe) — print() inside a handler raises
    'reentrant call' if the signal lands mid-print on the main thread."""
    global _JSON_DONE
    if not _JSON_DONE:
        _JSON_DONE = True
        line = json.dumps({
            "metric": "bench_interrupted_before_headline",
            "value": round(time.time() - _t0, 1), "unit": "s_elapsed",
            "vs_baseline": 0.0, "signal": int(signum)}) + "\n"
        os.write(1, line.encode())
    faulthandler.dump_traceback(file=sys.stderr)
    os._exit(0)


signal.signal(signal.SIGTERM, _signal_exit)
signal.signal(signal.SIGINT, _signal_exit)


def probe_backend(max_tries: int = 2, attempt_timeout: float = 300.0,
                  total_budget: float = 620.0) -> bool:
    """Pre-flight the JAX backend in a SUBPROCESS with bounded retry: the
    r03 record died on an init-time 'UNAVAILABLE' raised by the first
    in-process dispatch — before any try/except could save the JSON.
    Probing out-of-process keeps a poisoned backend-init state out of this
    process and lets a transiently-unavailable chip recover.

    The TOTAL probe wall time is hard-capped (round-4 VERDICT weak #1: the
    old 4x600s budget outlived the driver's ~30 min SIGTERM, so a down
    tunnel recorded `bench_interrupted_before_headline` instead of the
    honest `bench_failed_tpu_unavailable`). 2x300s + one short backoff
    stays well inside any plausible driver window, and per-attempt elapsed
    is logged so a 5-min-hanging jax.devices() is distinguishable from a
    fast refusal."""
    probe_t0 = time.time()
    for i in range(max_tries):
        left = total_budget - (time.time() - probe_t0)
        if left <= 5.0:
            log(f"probe budget exhausted ({total_budget:.0f}s total cap)")
            break
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d=jax.devices(); print(d[0].platform)"],
                capture_output=True, text=True,
                timeout=min(attempt_timeout, left))
            dt = time.time() - t0
            if r.returncode == 0:
                log(f"backend probe ok in {dt:.0f}s: {r.stdout.strip()}")
                return True
            log(f"backend probe attempt {i + 1}/{max_tries} rc={r.returncode}"
                f" after {dt:.0f}s: {r.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {i + 1}/{max_tries} timed out "
                f"after {time.time() - t0:.0f}s")
        if i + 1 < max_tries:   # no pointless backoff after the last try
            time.sleep(10.0)
    return False


def bench_exec():
    """Exec-only path: the fully-jitted single-chip pipeline."""
    import jax
    import numpy as np

    from drynx_tpu import flagship
    from drynx_tpu.crypto import elgamal as eg

    num_dps, n_servers = 10, 3
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    setup = flagship.SurveySetup.create(n_servers=n_servers, dlog_limit=10000)
    fn = jax.jit(flagship.build_pipeline(setup, params))

    stats, enc_rs, _, k2 = flagship.make_inputs(X, y, params, num_dps)
    V = stats.shape[1]
    ks_rs = eg.random_scalars(k2, (n_servers, V))

    # warmup / compile
    w, dec, found = fn(stats, enc_rs, ks_rs)
    jax.block_until_ready(w)
    assert bool(np.all(np.asarray(found))), "discrete-log lookup failed"
    clear = np.asarray(stats).sum(axis=0)
    np.testing.assert_array_equal(np.asarray(dec), clear)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        w, dec, found = fn(stats, enc_rs, ks_rs)
        jax.block_until_ready(w)
        best = min(best, time.perf_counter() - t0)
    return best


def _proofs_on_cluster():
    import numpy as np

    from drynx_tpu import flagship
    from drynx_tpu.models import logreg as lr
    from drynx_tpu.service.service import LocalCluster

    num_dps = 10
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    cluster = LocalCluster(n_cns=3, n_dps=num_dps, n_vns=3, seed=4,
                           dlog_limit=10000,
                           share_verify_cache=not NO_DEDUP)
    clear_stats = []
    for i, dp in enumerate(cluster.dps.values()):
        Xi, yi = lr.shard_for_dp(X, y, i, num_dps)
        dp.data = (Xi, yi)
        clear_stats.append(np.asarray(lr.encode_clear(Xi, yi, params)))
    clear_sum = np.stack(clear_stats).sum(axis=0)

    V = params.num_coeffs()
    sq = cluster.generate_survey_query(
        "log_reg", proofs=1, lr_params=params,
        ranges=[RANGES] * V, thresholds=1.0)
    return cluster, sq, clear_sum


def main():
    """Proofs-on first; print the headline JSON after the FIRST timed run.

    ALL JAX-touching work (including cluster construction — the r03 crash
    site) lives inside the try blocks; the only code outside them is pure
    host bookkeeping."""
    if not probe_backend():
        emit({"metric": "bench_failed_tpu_unavailable",
              "value": 0.0, "unit": "s", "vs_baseline": 0.0})
        return

    try:
        import numpy as np

        from drynx_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()

        from drynx_tpu import compilecache as cc
        from drynx_tpu.proofs import requests as rq
        from drynx_tpu.utils.timers import PhaseTimers

        PhaseTimers.echo = True  # stream phase completions to stderr live
        cc.CompileStats.echo = True  # per-program AOT rows to stderr live
        cc.install_cache_listener()  # count persistent-cache hits

        log("building proofs-on cluster (3 CN / 10 DP / 3 VN, "
            "thresholds=1.0)")
        cluster, sq, clear_sum = _proofs_on_cluster()

        def run():
            # Successive surveys over the same seed re-send byte-identical
            # payloads, so a timed run after warmup would verify NOTHING —
            # every verdict would be a VerifyCache hit from the previous
            # run and the headline would silently exclude verification
            # compute. Clearing the caches keeps the WITHIN-run cross-VN
            # dedup (the disclosed vn_verify_dedup factor) while forcing
            # every proof type to actually verify in the timed window.
            if cluster.vns is not None:
                for vn in cluster.vns.vns:
                    vn.verify_cache.clear()
            t0 = time.perf_counter()
            res = cluster.run_survey(sq)
            dt = time.perf_counter() - t0
            assert res.block is not None, "no audit block committed"
            codes = set(res.block.data.bitmap.values())
            assert codes == {rq.BM_TRUE}, f"dirty bitmap codes: {codes}"
            np.testing.assert_array_equal(res.decrypted.values, clear_sum)
            assert np.all(np.isfinite(res.result))
            return dt, res

        def timers(res):
            return ", ".join(f"{k}={v:.3f}s" for k, v in res.timers.items())

        log("proofs-on warmup (compile) run starting")
        dt, res = run()
        log(f"proofs-on warmup done in {dt:.1f}s; timers: {timers(res)}")
        dt, res = run()
        log(f"proofs-on timed run 1: {dt:.4f}s; timers: {timers(res)}")
    except Exception as e:  # keep the bench record honest but non-empty
        import traceback

        log("proofs-on bench FAILED: " + traceback.format_exc(limit=8))
        log(f"falling back to the exec-only metric (proofs-on error: {e!r})")
        try:
            exec_best = bench_exec()
            log(f"exec-only best {exec_best:.4f}s")
            emit({
                "metric": "encrypted_logreg_pima_10dp_EXEC_ONLY_seconds"
                          "_proofs_on_run_failed",
                "value": round(exec_best, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_EXEC_S / exec_best, 2),
            })
        except Exception as e2:  # the ONE-JSON-line contract must survive
            log("exec-only fallback ALSO failed: "
                + traceback.format_exc(limit=8))
            emit({
                "metric": "bench_failed_both_paths",
                "value": 0.0, "unit": "s", "vs_baseline": 0.0,
                "error": f"{e!r}; fallback: {e2!r}"[:400],
            })
        return

    # The deliverable: print NOW, before any bonus measurement can time out.
    emit({
        "metric": "encrypted_logreg_pima_10dp_proofs_on_total_seconds"
                  + ("_undeduped" if NO_DEDUP else ""),
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_PROOFS_S / dt, 2),
        # co-located VNs share one VerifyCache unless --no-verify-cache:
        # 9 keyswitch verifies -> 1 compute, 3 joint-range -> 1 (the
        # reference's VNs do this same work in PARALLEL on separate boxes)
        "vn_verify_dedup": not NO_DEDUP,
        # per-VN verify caches are cleared before the timed window (see
        # run() above), so verification compute is inside the measurement
        "verify_cache_cleared": True,
        # AOT precompile accounting (drynx_tpu/compilecache): how many
        # programs the main-thread warmup dispatched before the timed
        # window, and how many came out of the persistent XLA cache
        **cc.STATS.headline(),
    })
    log(f"headline recorded: proofs-on {dt:.4f}s = "
        f"{BASELINE_PROOFS_S / dt:.1f}x vs the 12.2s proofs-on baseline")

    # Bonus diagnostics (stderr only, best-effort).
    try:
        dt2, res = run()
        log(f"proofs-on timed run 2: {dt2:.4f}s; timers: {timers(res)}")
        exec_best = bench_exec()
        log(f"exec-only best {exec_best:.4f}s  "
            f"(vs {BASELINE_EXEC_S}s exec baseline: "
            f"{BASELINE_EXEC_S / exec_best:.1f}x)")
    except Exception as e:
        log(f"bonus diagnostics failed (headline already out): {e!r}")


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # truly last-resort: record must parse
        if not isinstance(e, SystemExit):
            import traceback

            log("bench top-level failure: " + traceback.format_exc(limit=8))
            emit({"metric": "bench_failed_toplevel", "value": 0.0,
                  "unit": "s", "vs_baseline": 0.0, "error": repr(e)[:400]})
    finally:
        if not _JSON_DONE:
            emit({"metric": "bench_exited_without_headline", "value": 0.0,
                  "unit": "s", "vs_baseline": 0.0})
        sys.exit(0)
