"""Headline benchmark: encrypted logistic-regression training, Pima-shaped
(10 DPs x 768 distinct records each, 8 features, K=2, 450 GD iterations),
WITH the verification pipeline on: DP encode+encrypt + range-proof creation
-> collective aggregation (+ proof) -> key switch (+ proofs) -> VN
verification of every proof -> audit-block commit -> querier decrypt -> GD.

Baselines (BASELINE.md, reference TIFS/logRegV2.py:9-14, Go/CPU):
  proofs ON  total: 12.2 s   (exec 1.2 + proof overhead 10.9 + decode 0.12)
  exec-only  total: ~1.32 s  (exec + decode, no proofs)

Structure (round-3 VERDICT #1): the PROOFS-ON benchmark runs FIRST and the
headline JSON prints immediately after the first successful timed run, so a
driver-budget timeout cannot erase the result. Extra timed runs and the
exec-only number are bonus stderr diagnostics after the JSON is out. Exactly
ONE JSON line is printed to stdout either way.
"""
import faulthandler
import json
import os
import signal
import sys
import time

import numpy as np

# live stack dumps on demand (kill -USR1 <pid>) and periodic stall traces:
# round-3 debugging found the process wedged at 0% CPU with no evidence
faulthandler.register(signal.SIGUSR1, file=sys.stderr)
faulthandler.dump_traceback_later(900, repeat=True, file=sys.stderr)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from drynx_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

BASELINE_PROOFS_S = 12.2
BASELINE_EXEC_S = 1.32
RANGES = (16, 5)     # reference simulation preset 18 (drynx_simul.go case 18)

_t0 = time.time()


def log(msg):
    print(f"[{time.time() - _t0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def bench_exec():
    """Exec-only path: the fully-jitted single-chip pipeline."""
    import jax

    from drynx_tpu import flagship
    from drynx_tpu.crypto import elgamal as eg

    num_dps, n_servers = 10, 3
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    setup = flagship.SurveySetup.create(n_servers=n_servers, dlog_limit=10000)
    fn = jax.jit(flagship.build_pipeline(setup, params))

    stats, enc_rs, _, k2 = flagship.make_inputs(X, y, params, num_dps)
    V = stats.shape[1]
    ks_rs = eg.random_scalars(k2, (n_servers, V))

    # warmup / compile
    w, dec, found = fn(stats, enc_rs, ks_rs)
    jax.block_until_ready(w)
    assert bool(np.all(np.asarray(found))), "discrete-log lookup failed"
    clear = np.asarray(stats).sum(axis=0)
    np.testing.assert_array_equal(np.asarray(dec), clear)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        w, dec, found = fn(stats, enc_rs, ks_rs)
        jax.block_until_ready(w)
        best = min(best, time.perf_counter() - t0)
    return best


def _proofs_on_cluster():
    from drynx_tpu import flagship
    from drynx_tpu.models import logreg as lr
    from drynx_tpu.service.service import LocalCluster

    num_dps = 10
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    cluster = LocalCluster(n_cns=3, n_dps=num_dps, n_vns=3, seed=4,
                           dlog_limit=10000)
    clear_stats = []
    for i, dp in enumerate(cluster.dps.values()):
        Xi, yi = lr.shard_for_dp(X, y, i, num_dps)
        dp.data = (Xi, yi)
        clear_stats.append(np.asarray(lr.encode_clear(Xi, yi, params)))
    clear_sum = np.stack(clear_stats).sum(axis=0)

    V = params.num_coeffs()
    sq = cluster.generate_survey_query(
        "log_reg", proofs=1, lr_params=params,
        ranges=[RANGES] * V, thresholds=1.0)
    return cluster, sq, clear_sum


def main():
    """Proofs-on first; print the headline JSON after the FIRST timed run."""
    from drynx_tpu.proofs import requests as rq
    from drynx_tpu.utils.timers import PhaseTimers

    PhaseTimers.echo = True  # stream phase completions to stderr live

    log("building proofs-on cluster (3 CN / 10 DP / 3 VN, thresholds=1.0)")
    cluster, sq, clear_sum = _proofs_on_cluster()

    def run():
        t0 = time.perf_counter()
        res = cluster.run_survey(sq)
        dt = time.perf_counter() - t0
        assert res.block is not None, "no audit block committed"
        codes = set(res.block.data.bitmap.values())
        assert codes == {rq.BM_TRUE}, f"dirty bitmap codes: {codes}"
        np.testing.assert_array_equal(res.decrypted.values, clear_sum)
        assert np.all(np.isfinite(res.result))
        return dt, res

    def timers(res):
        return ", ".join(f"{k}={v:.3f}s" for k, v in res.timers.items())

    try:
        log("proofs-on warmup (compile) run starting")
        dt, res = run()
        log(f"proofs-on warmup done in {dt:.1f}s; timers: {timers(res)}")
        dt, res = run()
        log(f"proofs-on timed run 1: {dt:.4f}s; timers: {timers(res)}")
    except Exception as e:  # keep the bench record honest but non-empty
        import traceback

        log("proofs-on bench FAILED: " + traceback.format_exc(limit=8))
        log(f"falling back to the exec-only metric (proofs-on error: {e!r})")
        try:
            exec_best = bench_exec()
            log(f"exec-only best {exec_best:.4f}s")
            print(json.dumps({
                "metric": "encrypted_logreg_pima_10dp_EXEC_ONLY_seconds"
                          "_proofs_on_run_failed",
                "value": round(exec_best, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_EXEC_S / exec_best, 2),
            }))
        except Exception as e2:  # the ONE-JSON-line contract must survive
            log("exec-only fallback ALSO failed: "
                + traceback.format_exc(limit=8))
            print(json.dumps({
                "metric": "bench_failed_both_paths",
                "value": 0.0, "unit": "s", "vs_baseline": 0.0,
                "error": f"{e!r}; fallback: {e2!r}"[:400],
            }))
        return

    # The deliverable: print NOW, before any bonus measurement can time out.
    print(json.dumps({
        "metric": "encrypted_logreg_pima_10dp_proofs_on_total_seconds",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_PROOFS_S / dt, 2),
    }), flush=True)
    log(f"headline recorded: proofs-on {dt:.4f}s = "
        f"{BASELINE_PROOFS_S / dt:.1f}x vs the 12.2s proofs-on baseline")

    # Bonus diagnostics (stderr only, best-effort).
    try:
        dt2, res = run()
        log(f"proofs-on timed run 2: {dt2:.4f}s; timers: {timers(res)}")
        exec_best = bench_exec()
        log(f"exec-only best {exec_best:.4f}s  "
            f"(vs {BASELINE_EXEC_S}s exec baseline: "
            f"{BASELINE_EXEC_S / exec_best:.1f}x)")
    except Exception as e:
        log(f"bonus diagnostics failed (headline already out): {e!r}")


if __name__ == "__main__":
    main()
