"""Headline benchmark: encrypted logistic-regression training, Pima-shaped
(10 DPs x 768 distinct records each, 8 features, K=2, 450 GD iterations),
WITH the verification pipeline on: DP encode+encrypt + range-proof creation
-> collective aggregation (+ proof) -> key switch (+ proofs) -> VN
verification of every proof -> audit-block commit -> querier decrypt -> GD.

Baselines (BASELINE.md, reference TIFS/logRegV2.py:9-14, Go/CPU):
  proofs ON  total: 12.2 s   (exec 1.2 + proof overhead 10.9 + decode 0.12)
  exec-only  total: ~1.32 s  (exec + decode, no proofs)

SUPERVISOR architecture (round-5 VERDICT weak #1): five rounds of bench
attempts died to segfaults/timeouts INSIDE the measured process — no
amount of in-process "un-killable one-JSON-line" armor survives a SIGSEGV
in a kernel dispatch. So the process that prints the record is no longer
the process that crashes:

  * the PARENT (this script, no args) never imports jax. It probes the
    backend, probes persistent-cache deserialization (both in supervised
    children), runs the measurement in a CHILD process, and emits EXACTLY
    ONE labeled JSON line on stdout for every child outcome — clean exit,
    nonzero rc, segfault, timeout (the same pattern as
    __graft_entry__.py dryrun children).
  * the CHILD (`--measure-child`) does all JAX work and writes a
    PROGRESSIVE record file (--record-path) at each stage — starting ->
    cluster_built -> warmup_done -> complete/failed — carrying phase
    timers, compile_cache_* attribution and per-shard proof-plane timers,
    so even a segfaulted run is attributable from JSON alone.
  * the persistent-cache contradiction (VERDICT weak #3: drynx_tpu's
    __init__ warns the cache segfaults on deserialize while this bench
    enabled it blindly) is resolved by MEASUREMENT: `--cache-probe-child`
    compiles-and-serializes into a fresh cache dir, a second probe child
    must deserialize out of it; only an "ok" verdict turns the cache on
    for the measured child (DRYNX_JAX_CACHE env), and the verdict is
    recorded in the headline JSON either way.
"""
import faulthandler
import json
import os
import shutil
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PROOFS_S = 12.2
BASELINE_EXEC_S = 1.32
RANGES = (16, 5)     # reference simulation preset 18 (drynx_simul.go case 18)

# --no-verify-cache: the UNDEDUPED control run (round-4 VERDICT task 10).
# The default headline lets co-located VNs share one VerifyCache — identical
# payloads verify once per process, matching the reference's
# parallel-machines accounting (each of its VNs verifies on its own box
# simultaneously; dedup factor: 9 keyswitch verifies -> 1, 3 joint-range ->
# 1). This flag DISABLES the cache (VerifyCache maxsize=0) so every
# delivery recomputes — all 9 keyswitch verifies run — and the true
# single-chip SERIAL cost of all verifications lands beside the headline.
NO_DEDUP = "--no-verify-cache" in sys.argv

_t0 = time.time()
_JSON_DONE = False
_CURRENT_CHILD = None       # Popen of the running child (signal forwarding)
_RECORD_PATH = None         # child mode: where progressive records go

CHILD_TIMEOUT_S = float(os.environ.get("DRYNX_BENCH_CHILD_TIMEOUT_S", 3300))
PROBE_TIMEOUT_S = float(os.environ.get("DRYNX_BENCH_PROBE_TIMEOUT_S", 600))
CACHE_DIR = ".jax_cache"            # measured child's cache (verdict-gated)
CACHE_PROBE_DIR = ".jax_cache_probe"


def log(msg):
    print(f"[{time.time() - _t0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def emit(obj) -> None:
    """The ONE-JSON-line contract: first call wins, later calls are logs."""
    global _JSON_DONE
    if _JSON_DONE:
        log(f"suppressed extra JSON (contract is one line): {obj}")
        return
    _JSON_DONE = True
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# Supervisor plumbing (parent side — no jax anywhere on these paths)
# ---------------------------------------------------------------------------

def _arm_supervisor():
    """Parent signal/faulthandler armor: a driver SIGTERM mid-run still
    produces the labeled JSON (and the child is killed, not orphaned)."""
    faulthandler.register(signal.SIGUSR1, file=sys.stderr)

    def _signal_exit(signum, frame):
        # os.write: async-signal-safe; print() inside a handler raises
        # 'reentrant call' if the signal lands mid-print on the main thread
        global _JSON_DONE
        if not _JSON_DONE:
            _JSON_DONE = True
            line = json.dumps({
                "metric": "bench_interrupted_before_headline",
                "value": round(time.time() - _t0, 1), "unit": "s_elapsed",
                "vs_baseline": 0.0, "signal": int(signum)}) + "\n"
            os.write(1, line.encode())
        child = _CURRENT_CHILD
        if child is not None:
            try:
                child.kill()
            except OSError:
                pass
        os._exit(0)

    signal.signal(signal.SIGTERM, _signal_exit)
    signal.signal(signal.SIGINT, _signal_exit)


def supervise_child(cmd, timeout_s, env=None):
    """Run cmd to completion under this supervisor.

    Returns (outcome, rc, elapsed_s, stdout_text) with outcome one of
    "ok" | "rc:<n>" | "signal:<NAME>" | "timeout". stderr is inherited
    (live logs stay visible); stdout is captured so a chatty child can
    never violate the parent's one-JSON-line contract."""
    global _CURRENT_CHILD
    t0 = time.time()
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE, text=True)
    _CURRENT_CHILD = proc
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        return "timeout", None, time.time() - t0, out or ""
    finally:
        _CURRENT_CHILD = None
    rc = proc.returncode
    if rc == 0:
        outcome = "ok"
    elif rc < 0:
        try:
            outcome = "signal:" + signal.Signals(-rc).name
        except ValueError:
            outcome = f"signal:{-rc}"
    else:
        outcome = f"rc:{rc}"
    return outcome, rc, time.time() - t0, out or ""


def read_record(path):
    """Best-effort read of the child's progressive record file."""
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


def cache_verdict(first, second):
    """Map the two cache-probe child outcomes to a verdict string.

    first/second: (outcome, rc) from supervise_child; second is None when
    the first probe already failed. Probe children exit 0 when the
    persistent-cache listener saw a HIT, 7 on no hit (expected for the
    first, compile-and-serialize, run). Only "ok" enables the cache for
    the measured child."""
    f_out, f_rc = first
    if f_out == "timeout":
        return "write_timeout"
    if f_out.startswith("signal:"):
        return "write_crash"
    if f_rc not in (0, 7):
        return "write_failed"
    if second is None:
        return "write_failed"
    s_out, s_rc = second
    if s_out == "timeout":
        return "deserialize_timeout"
    if s_out.startswith("signal:"):
        return "deserialize_crash"
    if s_rc == 0:
        return "ok"
    if s_rc == 7:
        return "no_hit"
    return "deserialize_error"


def probe_persistent_cache():
    """Measure, in supervised children, whether the persistent XLA cache
    round-trips on this backend (write then deserialize) — the answer the
    repo has so far only ASSUMED in opposite directions."""
    here = os.path.dirname(os.path.abspath(__file__))
    probe_dir = os.path.join(here, CACHE_PROBE_DIR)
    shutil.rmtree(probe_dir, ignore_errors=True)
    env = dict(os.environ)
    env["DRYNX_JAX_CACHE"] = probe_dir
    cmd = [sys.executable, os.path.abspath(__file__), "--cache-probe-child"]

    first = supervise_child(cmd, PROBE_TIMEOUT_S, env=env)
    log(f"cache probe write pass: outcome={first[0]} in {first[2]:.0f}s")
    second = None
    if first[0] in ("ok", "rc:7"):
        second = supervise_child(cmd, PROBE_TIMEOUT_S, env=env)
        log(f"cache probe read pass: outcome={second[0]} in {second[2]:.0f}s")
    verdict = cache_verdict((first[0], first[1]),
                            None if second is None else (second[0], second[1]))
    log(f"persistent-cache verdict: {verdict}")
    return verdict


def probe_backend(max_tries: int = 2, attempt_timeout: float = 300.0,
                  total_budget: float = 620.0) -> bool:
    """Pre-flight the JAX backend in a SUBPROCESS with bounded retry: the
    r03 record died on an init-time 'UNAVAILABLE' raised by the first
    in-process dispatch — before any try/except could save the JSON.
    Probing out-of-process keeps a poisoned backend-init state out of this
    process and lets a transiently-unavailable chip recover.

    The TOTAL probe wall time is hard-capped (round-4 VERDICT weak #1: the
    old 4x600s budget outlived the driver's ~30 min SIGTERM, so a down
    tunnel recorded `bench_interrupted_before_headline` instead of the
    honest `bench_failed_tpu_unavailable`). 2x300s + one short backoff
    stays well inside any plausible driver window, and per-attempt elapsed
    is logged so a 5-min-hanging jax.devices() is distinguishable from a
    fast refusal."""
    probe_t0 = time.time()
    for i in range(max_tries):
        left = total_budget - (time.time() - probe_t0)
        if left <= 5.0:
            log(f"probe budget exhausted ({total_budget:.0f}s total cap)")
            break
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d=jax.devices(); print(d[0].platform)"],
                capture_output=True, text=True,
                timeout=min(attempt_timeout, left))
            dt = time.time() - t0
            if r.returncode == 0:
                log(f"backend probe ok in {dt:.0f}s: {r.stdout.strip()}")
                return True
            log(f"backend probe attempt {i + 1}/{max_tries} rc={r.returncode}"
                f" after {dt:.0f}s: {r.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {i + 1}/{max_tries} timed out "
                f"after {time.time() - t0:.0f}s")
        if i + 1 < max_tries:   # no pointless backoff after the last try
            time.sleep(10.0)
    return False


def supervisor_result(outcome, rc, elapsed_s, record, cache_probe):
    """Build the parent's ONE JSON object from a measured-child outcome and
    its last progressive record (pure — unit-tested with stub children).

    A child that completed writes stage="complete" with the metric fields;
    anything else becomes a labeled failure metric carrying the last stage
    reached plus whatever timers/attribution the record accumulated."""
    sup = {"child_outcome": outcome,
           "child_rc": rc,
           "child_elapsed_s": round(elapsed_s, 1),
           "persistent_cache_probe": cache_probe}
    rec = dict(record or {})
    stage = rec.pop("stage", None)
    if outcome == "ok" and stage == "complete" and "metric" in rec:
        rec.update(sup)
        return rec
    if outcome == "ok":
        metric = "bench_child_exited_without_headline"
    elif outcome == "timeout":
        metric = "bench_child_timeout"
    elif outcome.startswith("signal:"):
        metric = "bench_child_killed_" + outcome.split(":", 1)[1].lower()
    else:
        metric = "bench_child_failed_" + outcome.replace(":", "")
    rec.pop("metric", None)
    rec.pop("value", None)
    rec.pop("unit", None)
    rec.pop("vs_baseline", None)
    return {"metric": metric, "value": round(elapsed_s, 1),
            "unit": "s_elapsed", "vs_baseline": 0.0,
            "last_stage": stage or "none", **rec, **sup}


def main_supervisor():
    """Parent: probe backend + cache, supervise the measured child, emit."""
    _arm_supervisor()
    if not probe_backend():
        emit({"metric": "bench_failed_tpu_unavailable",
              "value": 0.0, "unit": "s", "vs_baseline": 0.0})
        return

    cache_probe = probe_persistent_cache()

    here = os.path.dirname(os.path.abspath(__file__))
    record_path = os.path.join(here, ".bench_record.json")
    try:
        os.remove(record_path)
    except OSError:
        pass
    env = dict(os.environ)
    if cache_probe == "ok":
        env["DRYNX_JAX_CACHE"] = os.path.join(here, CACHE_DIR)
    else:
        # the measured child must NOT enable what the probe says crashes
        env["DRYNX_JAX_CACHE"] = "off"
    cmd = [sys.executable, os.path.abspath(__file__), "--measure-child",
           "--record-path", record_path]
    if NO_DEDUP:
        cmd.append("--no-verify-cache")

    log(f"starting measured child (timeout {CHILD_TIMEOUT_S:.0f}s, "
        f"cache={'on' if cache_probe == 'ok' else 'off'})")
    outcome, rc, elapsed, _out = supervise_child(cmd, CHILD_TIMEOUT_S,
                                                 env=env)
    log(f"measured child done: outcome={outcome} in {elapsed:.0f}s")
    emit(supervisor_result(outcome, rc, elapsed, read_record(record_path),
                           cache_probe))


# ---------------------------------------------------------------------------
# Child side (all jax work lives below; parent never imports these paths)
# ---------------------------------------------------------------------------

def write_record(obj) -> None:
    """Progressive child record: atomic replace so the parent never reads a
    torn write, even if this process dies mid-dump."""
    if _RECORD_PATH is None:
        return
    obj = dict(obj)
    obj.setdefault("elapsed_s", round(time.time() - _t0, 1))
    tmp = _RECORD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, _RECORD_PATH)


def _arm_child():
    """Child armor: stack dumps on demand/stall + a SIGTERM record update
    (the parent still emits the JSON line — the child only files evidence).
    """
    faulthandler.register(signal.SIGUSR1, file=sys.stderr)
    faulthandler.dump_traceback_later(900, repeat=True, file=sys.stderr)

    def _sig(signum, frame):
        write_record({"stage": "interrupted", "signal": int(signum)})
        faulthandler.dump_traceback(file=sys.stderr)
        os._exit(0)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)


def _cache_probe_child() -> int:
    """Compile two representative programs with the persistent cache on
    (DRYNX_JAX_CACHE env, applied by drynx_tpu.__init__). Exit 0 iff the
    cache listener saw a deserialization HIT (second run), 7 on a clean
    miss (first run), nonzero on any error; a segfault surfaces as the
    child's signal rc. The probed classes: one bucketed crypto op at the
    bench bucket and one fused exec jit — the two program families whose
    CPU executables got large enough to crash jaxlib's deserializer."""
    import jax

    # the probe must serialize regardless of compile speed
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    import jax.numpy as jnp

    from drynx_tpu import compilecache as cc
    from drynx_tpu.crypto import batching as B

    cc.install_cache_listener()
    x = jnp.zeros((2048, 16), dtype=jnp.uint32)
    jax.block_until_ready(B.fn_add(x, x))

    from drynx_tpu.service import service as svc

    a = jnp.zeros((10, 9, 2, 3, 16), dtype=jnp.uint32)
    jax.block_until_ready(svc._fused_agg(a))

    hits = cc.STATS.listener_hits
    log(f"cache probe child: listener hits={hits}")
    return 0 if hits > 0 else 7


def bench_exec():
    """Exec-only path: the fully-jitted single-chip pipeline."""
    import jax
    import numpy as np

    from drynx_tpu import flagship
    from drynx_tpu.crypto import elgamal as eg

    num_dps, n_servers = 10, 3
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    setup = flagship.SurveySetup.create(n_servers=n_servers, dlog_limit=10000)
    fn = jax.jit(flagship.build_pipeline(setup, params))

    stats, enc_rs, _, k2 = flagship.make_inputs(X, y, params, num_dps)
    V = stats.shape[1]
    ks_rs = eg.random_scalars(k2, (n_servers, V))

    # warmup / compile
    w, dec, found = fn(stats, enc_rs, ks_rs)
    jax.block_until_ready(w)
    assert bool(np.all(np.asarray(found))), "discrete-log lookup failed"
    clear = np.asarray(stats).sum(axis=0)
    np.testing.assert_array_equal(np.asarray(dec), clear)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        w, dec, found = fn(stats, enc_rs, ks_rs)
        jax.block_until_ready(w)
        best = min(best, time.perf_counter() - t0)
    return best


def _proofs_on_cluster():
    import numpy as np

    from drynx_tpu import flagship
    from drynx_tpu.models import logreg as lr
    from drynx_tpu.service.service import LocalCluster

    num_dps = 10
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    cluster = LocalCluster(n_cns=3, n_dps=num_dps, n_vns=3, seed=4,
                           dlog_limit=10000,
                           share_verify_cache=not NO_DEDUP)
    clear_stats = []
    for i, dp in enumerate(cluster.dps.values()):
        Xi, yi = lr.shard_for_dp(X, y, i, num_dps)
        dp.data = (Xi, yi)
        clear_stats.append(np.asarray(lr.encode_clear(Xi, yi, params)))
    clear_sum = np.stack(clear_stats).sum(axis=0)

    V = params.num_coeffs()
    sq = cluster.generate_survey_query(
        "log_reg", proofs=1, lr_params=params,
        ranges=[RANGES] * V, thresholds=1.0)
    return cluster, sq, clear_sum


def _attribution(cc, res=None):
    """The shared record payload: AOT/compile-cache accounting, survey
    phase timers and per-shard proof-plane timers — everything needed to
    attribute a slow (or dead) run from JSON alone."""
    from drynx_tpu.parallel import proof_plane as plane

    out = dict(cc.STATS.headline())
    out["proof_plane_shards"] = plane.n_shards()
    out["shard_timers"] = plane.timers_snapshot()
    if res is not None:
        out["phase_timers"] = {k: round(v, 4)
                               for k, v in res.timers.items()}
    return out


def main_child():
    """Proofs-on first; file the headline record after the FIRST timed run.

    The parent emits the JSON — this process only writes the progressive
    record. Its exception handling mirrors the old in-process bench: a
    proofs-on failure still tries the exec-only fallback, and both
    failures file a 'failed' record (the parent labels the emitted line
    from child rc + record)."""
    _arm_child()
    write_record({"stage": "starting"})
    try:
        import numpy as np

        from drynx_tpu import compilecache as cc
        from drynx_tpu.proofs import requests as rq
        from drynx_tpu.utils.timers import PhaseTimers

        PhaseTimers.echo = True  # stream phase completions to stderr live
        cc.CompileStats.echo = True  # per-program AOT rows to stderr live
        cc.install_cache_listener()  # count persistent-cache hits

        # persistent cache: env-driven (DRYNX_JAX_CACHE from the parent,
        # set only on an "ok" probe verdict) — drynx_tpu.__init__ applied
        # it before any backend touch. No unconditional enable here: that
        # was the round-5 contradiction.
        import jax

        log(f"persistent cache dir: "
            f"{jax.config.jax_compilation_cache_dir or '(off)'}")

        log("building proofs-on cluster (3 CN / 10 DP / 3 VN, "
            "thresholds=1.0)")
        cluster, sq, clear_sum = _proofs_on_cluster()
        write_record({"stage": "cluster_built", **_attribution(cc)})

        def run():
            # Successive surveys over the same seed re-send byte-identical
            # payloads, so a timed run after warmup would verify NOTHING —
            # every verdict would be a VerifyCache hit from the previous
            # run and the headline would silently exclude verification
            # compute. Clearing the caches keeps the WITHIN-run cross-VN
            # dedup (the disclosed vn_verify_dedup factor) while forcing
            # every proof type to actually verify in the timed window.
            if cluster.vns is not None:
                for vn in cluster.vns.vns:
                    vn.verify_cache.clear()
            t0 = time.perf_counter()
            res = cluster.run_survey(sq)
            dt = time.perf_counter() - t0
            assert res.block is not None, "no audit block committed"
            codes = set(res.block.data.bitmap.values())
            assert codes == {rq.BM_TRUE}, f"dirty bitmap codes: {codes}"
            np.testing.assert_array_equal(res.decrypted.values, clear_sum)
            assert np.all(np.isfinite(res.result))
            return dt, res

        def timers(res):
            return ", ".join(f"{k}={v:.3f}s" for k, v in res.timers.items())

        log("proofs-on warmup (compile) run starting")
        dt, res = run()
        log(f"proofs-on warmup done in {dt:.1f}s; timers: {timers(res)}")
        write_record({"stage": "warmup_done", "warmup_s": round(dt, 2),
                      **_attribution(cc, res)})
        dt, res = run()
        log(f"proofs-on timed run 1: {dt:.4f}s; timers: {timers(res)}")
    except Exception as e:  # keep the bench record honest but non-empty
        import traceback

        log("proofs-on bench FAILED: " + traceback.format_exc(limit=8))
        log(f"falling back to the exec-only metric (proofs-on error: {e!r})")
        try:
            exec_best = bench_exec()
            log(f"exec-only best {exec_best:.4f}s")
            write_record({
                "stage": "complete",
                "metric": "encrypted_logreg_pima_10dp_EXEC_ONLY_seconds"
                          "_proofs_on_run_failed",
                "value": round(exec_best, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_EXEC_S / exec_best, 2),
                "proofs_on_error": repr(e)[:400],
            })
        except Exception as e2:
            log("exec-only fallback ALSO failed: "
                + traceback.format_exc(limit=8))
            write_record({
                "stage": "failed",
                "error": f"{e!r}; fallback: {e2!r}"[:400],
            })
            return 1
        return 0

    # The deliverable: file NOW, before any bonus measurement can die.
    write_record({
        "stage": "complete",
        "metric": "encrypted_logreg_pima_10dp_proofs_on_total_seconds"
                  + ("_undeduped" if NO_DEDUP else ""),
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_PROOFS_S / dt, 2),
        # co-located VNs share one VerifyCache unless --no-verify-cache:
        # 9 keyswitch verifies -> 1 compute, 3 joint-range -> 1 (the
        # reference's VNs do this same work in PARALLEL on separate boxes)
        "vn_verify_dedup": not NO_DEDUP,
        # per-VN verify caches are cleared before the timed window (see
        # run() above), so verification compute is inside the measurement
        "verify_cache_cleared": True,
        **_attribution(cc, res),
    })
    log(f"headline recorded: proofs-on {dt:.4f}s = "
        f"{BASELINE_PROOFS_S / dt:.1f}x vs the 12.2s proofs-on baseline")

    # Bonus diagnostics (stderr only, best-effort).
    try:
        dt2, res = run()
        log(f"proofs-on timed run 2: {dt2:.4f}s; timers: {timers(res)}")
        exec_best = bench_exec()
        log(f"exec-only best {exec_best:.4f}s  "
            f"(vs {BASELINE_EXEC_S}s exec baseline: "
            f"{BASELINE_EXEC_S / exec_best:.1f}x)")
    except Exception as e:
        log(f"bonus diagnostics failed (headline already out): {e!r}")
    return 0


if __name__ == "__main__":
    if "--cache-probe-child" in sys.argv:
        sys.exit(_cache_probe_child())
    elif "--measure-child" in sys.argv:
        if "--record-path" in sys.argv:
            _RECORD_PATH = sys.argv[sys.argv.index("--record-path") + 1]
        try:
            rc = main_child()
        except BaseException as e:  # file evidence; parent labels the line
            if isinstance(e, SystemExit):
                raise
            import traceback

            log("bench child top-level failure: "
                + traceback.format_exc(limit=8))
            write_record({"stage": "failed", "error": repr(e)[:400]})
            rc = 1
        sys.exit(rc)
    else:
        try:
            main_supervisor()
        except BaseException as e:  # truly last-resort: record must parse
            if not isinstance(e, SystemExit):
                import traceback

                log("bench supervisor failure: "
                    + traceback.format_exc(limit=8))
                emit({"metric": "bench_failed_toplevel", "value": 0.0,
                      "unit": "s", "vs_baseline": 0.0,
                      "error": repr(e)[:400]})
        finally:
            if not _JSON_DONE:
                emit({"metric": "bench_exited_without_headline",
                      "value": 0.0, "unit": "s", "vs_baseline": 0.0})
            sys.exit(0)
