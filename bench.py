"""Headline benchmark: encrypted logistic-regression training, Pima-shaped
(10 DPs x 768 records, 8 features, K=2, 450 GD iterations), end to end:
DP encode+encrypt -> collective aggregation -> key switch -> querier decrypt
-> gradient descent. Baseline: reference Go/CPU total 12.2 s
(BASELINE.md, TIFS/logRegV2.py:9-14).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = baseline_seconds / measured_seconds (higher is better).
"""
import json
import time

import numpy as np

BASELINE_S = 12.2


def main():
    import jax

    from drynx_tpu import flagship
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.models import logreg as lr

    num_dps, n_servers = 10, 3
    X, y, params = flagship.pima_shaped_problem(
        num_dps=num_dps, n_records=768, d=8, max_iterations=450)
    setup = flagship.SurveySetup.create(n_servers=n_servers, dlog_limit=10000)
    fn = jax.jit(flagship.build_pipeline(setup, params))

    # Host-side encode of per-DP stats is part of the DP phase; include it in
    # the timed region via a pre-built callable (it is jax/numpy work too).
    stats, enc_rs, _, k2 = flagship.make_inputs(X, y, params, num_dps)
    V = stats.shape[1]
    ks_rs = eg.random_scalars(k2, (n_servers, V))

    # warmup / compile
    w, dec, found = fn(stats, enc_rs, ks_rs)
    jax.block_until_ready(w)
    assert bool(np.all(np.asarray(found))), "discrete-log lookup failed"

    # exactness invariant: decrypted aggregate == clear sum of DP stats
    clear = np.asarray(stats).sum(axis=0)
    np.testing.assert_array_equal(np.asarray(dec), clear)

    runs = 3
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        w, dec, found = fn(stats, enc_rs, ks_rs)
        jax.block_until_ready(w)
        best = min(best, time.perf_counter() - t0)

    print(json.dumps({
        "metric": "encrypted_logreg_pima_10dp_total_seconds",
        "value": round(best, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_S / best, 2),
    }))


if __name__ == "__main__":
    main()
