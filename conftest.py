"""Root conftest: keep pytest.ini's xdist addopts harmless without xdist.

pytest.ini passes ``-n 2 --dist loadfile --max-worker-restart=6`` so local
runs parallelise when pytest-xdist is available. On boxes without xdist (or
under ``-p no:xdist``) those flags would be a usage error before a single
test collects. Register them as inert options in that case; when the real
plugin is present its registration wins and ours raises ValueError, which
we swallow.
"""
import pytest


def pytest_addoption(parser):
    group = parser.getgroup("xdist-shim")
    for args, kwargs in (
        # _addoption: lowercase short options are reserved for pytest core,
        # and xdist itself registers -n the same way.
        (("-n", "--numprocesses"), {"dest": "_shim_numprocesses"}),
        (("--dist",), {"dest": "_shim_dist"}),
        (("--max-worker-restart",), {"dest": "_shim_max_worker_restart"}),
    ):
        try:
            group._addoption(*args, action="store", default=None, **kwargs)
        except ValueError:
            pass  # pytest-xdist already registered the real option
