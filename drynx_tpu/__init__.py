"""drynx_tpu — TPU-native decentralized, privacy-preserving, verifiable
statistical-query and ML-training framework (capabilities of cgrigis/drynx,
re-designed for JAX/XLA/Pallas/pjit).

64-bit integer support is required for exact statistics vectors and limb
packing (the crypto path itself is pure uint32 limb math); float kernels in
the training path explicitly request float32/bfloat16, so enabling x64 here
does not put float64 on the TPU hot path.
"""
import os

# Opt-in runtime lock-order recorder (analysis/locktrace.py): patch
# threading.Lock/RLock BEFORE anything in this package creates one, so
# every named_lock in the tree is traced. The chaos cross-check in
# tests/test_concurrency_analysis.py runs a real server drain under this
# and asserts observed acquisition order ⊆ the static lock-order graph.
if os.environ.get("DRYNX_LOCK_TRACE", "0") == "1":
    from .analysis import locktrace as _locktrace
    _locktrace.install()

# Opt-in runtime determinism recorder (analysis/dettrace.py): arm it
# BEFORE any byte-identity sink (ProofDB.put, transcript serialization,
# journal appends) can fire, so every write of the process is hashed.
# The chaos cross-check in tests/test_determinism_analysis.py runs the
# same proofs-on survey twice with one seed under this and asserts the
# per-sink write multisets are identical — the dynamic half of the
# static nondeterminism-taint pass (analysis/determinism.py).
if os.environ.get("DRYNX_DET_TRACE", "0") == "1":
    from .analysis import dettrace as _dettrace
    _dettrace.install()

# Opt-in runtime protocol recorder (analysis/prototrace.py): arm it
# BEFORE any resource lifecycle (pool slab consumption, ConnPool
# checkouts, pane seals, checkpoint saves) can fire, so every
# instance's event sequence is captured from creation. The chaos
# cross-check in tests/test_typestate_analysis.py drives a proofs-on
# survey plus a pool consume/crash-recover cycle under this and
# asserts every observed sequence is accepted by the declared automata
# — the dynamic half of the static typestate pass (analysis/typestate.py).
if os.environ.get("DRYNX_PROTO_TRACE", "0") == "1":
    from .analysis import prototrace as _prototrace
    _prototrace.install()

# Lint-only fast path: the static analyzer (python -m drynx_tpu.analysis)
# is deliberately jax-free, but importing its parent package triggers
# ~0.4s of accelerator setup below. DRYNX_SKIP_JAX_INIT=1 skips ALL of it
# — only safe for processes that never execute jax code (the pre-commit
# lint tier in scripts/check.sh sets it).
if os.environ.get("DRYNX_SKIP_JAX_INIT", "0") == "1":
    jax = None
else:
    import jax

if jax is not None:
    jax.config.update("jax_enable_x64", True)

# Pin the backend from JAX_PLATFORMS HERE — before any crypto module's
# import-time jnp op can initialize a backend. The env var alone is not
# enough: a sitecustomize-registered accelerator plugin snapshots it before
# user code runs and can hijack backend resolution, so a DOWN tunnel hangs
# the first dispatch even with JAX_PLATFORMS=cpu in the env. Pinning at
# package import covers every entrypoint (CLI, scripts, tests).
_plat = os.environ.get("JAX_PLATFORMS")
if _plat and jax is not None:
    jax.config.update("jax_platforms", _plat)

# Persistent XLA compilation cache: OPT-IN via DRYNX_JAX_CACHE=<dir>.
# Disabled by default because jaxlib has been observed to segfault when
# deserializing the very large crypto-kernel executables back out of the
# cache (crash in compilation_cache.get_executable_and_time). The framework
# instead keeps compiles rare by design: rolled limb loops (small graphs,
# crypto/field.py) and per-bucket jits reused in-process (crypto/batching.py).
# bench.py no longer assumes either way: its supervisor PROBES the
# round-trip in throwaway children (write pass + deserialize pass,
# bench.py probe_persistent_cache) and sets this env var for the measured
# child only on an "ok" verdict; the verdict lands in the bench record as
# `persistent_cache_probe`.
_cache = os.environ.get("DRYNX_JAX_CACHE", "")
if jax is not None and _cache and _cache != "off" \
        and not jax.config.jax_compilation_cache_dir:
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# Serialize XLA compiles process-wide AND run each on a dedicated
# fresh-stacked thread. Two reasons, both observed killing processes:
#   1. Two Python threads entering XLA's CPU backend_compile concurrently
#      segfault/abort the compiler under load (this framework is
#      deliberately multi-threaded at the service layer — VN verifiers,
#      proof threads, TCP handlers).
#   2. Even a SINGLE compile segfaults in a long-lived process once the
#      MAIN thread's stack has grown into an adjacent mapping (XLA's CPU
#      pipeline recurses deeply on the crypto graphs; pytest workers died
#      mid-suite on compiles that pass in isolation).
# Running the compile on a fresh thread with an explicit 512 MB stack gives
# every compile a clean, collision-free stack; the lock keeps them one at a
# time. Compiles are rare and cached — the thread spawn is noise.
# Kill-switch: DRYNX_NO_COMPILE_LOCK=1.
if jax is not None and os.environ.get("DRYNX_NO_COMPILE_LOCK", "0") != "1":
    try:
        import threading as _threading

        from jax._src import compiler as _jax_compiler

        from .resilience.policy import named_lock as _named_lock

        _orig_bcl = _jax_compiler.backend_compile_and_load
        _compile_lock = _named_lock("compile_lock")
        _COMPILE_STACK = 512 * 1024 * 1024

        def _locked_backend_compile(*args, **kwargs):
            with _compile_lock:
                box: dict = {}

                def run():
                    try:
                        box["v"] = _orig_bcl(*args, **kwargs)
                    except BaseException as e:   # re-raised on the caller
                        box["e"] = e

                old = _threading.stack_size(_COMPILE_STACK)
                try:
                    t = _threading.Thread(target=run, name="drynx-compile")
                    t.start()
                finally:
                    _threading.stack_size(old)
                t.join()
                if "e" in box:
                    raise box["e"]
                return box["v"]

        _jax_compiler.backend_compile_and_load = _locked_backend_compile
    except Exception:   # jax internals moved: lose the guard, not the app
        pass
