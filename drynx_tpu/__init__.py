"""drynx_tpu — TPU-native decentralized, privacy-preserving, verifiable
statistical-query and ML-training framework (capabilities of cgrigis/drynx,
re-designed for JAX/XLA/Pallas/pjit).

64-bit integer support is required for exact statistics vectors and limb
packing (the crypto path itself is pure uint32 limb math); float kernels in
the training path explicitly request float32/bfloat16, so enabling x64 here
does not put float64 on the TPU hot path.
"""
import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: OPT-IN via DRYNX_JAX_CACHE=<dir>.
# Disabled by default because jaxlib has been observed to segfault when
# deserializing the very large crypto-kernel executables back out of the
# cache (crash in compilation_cache.get_executable_and_time). The framework
# instead keeps compiles rare by design: rolled limb loops (small graphs,
# crypto/field.py) and per-bucket jits reused in-process (crypto/batching.py).
_cache = os.environ.get("DRYNX_JAX_CACHE", "")
if _cache and _cache != "off" and not jax.config.jax_compilation_cache_dir:
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
