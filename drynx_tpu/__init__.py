"""drynx_tpu — TPU-native decentralized, privacy-preserving, verifiable
statistical-query and ML-training framework (capabilities of cgrigis/drynx,
re-designed for JAX/XLA/Pallas/pjit).

64-bit integer support is required for exact statistics vectors and limb
packing (the crypto path itself is pure uint32 limb math); float kernels in
the training path explicitly request float32/bfloat16, so enabling x64 here
does not put float64 on the TPU hot path.
"""
import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: OPT-IN via DRYNX_JAX_CACHE=<dir>.
# Disabled by default because jaxlib has been observed to segfault when
# deserializing the very large crypto-kernel executables back out of the
# cache (crash in compilation_cache.get_executable_and_time). The framework
# instead keeps compiles rare by design: rolled limb loops (small graphs,
# crypto/field.py) and per-bucket jits reused in-process (crypto/batching.py).
_cache = os.environ.get("DRYNX_JAX_CACHE", "")
if _cache and _cache != "off" and not jax.config.jax_compilation_cache_dir:
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# Serialize XLA compiles process-wide. This framework is deliberately
# multi-threaded at the service layer (VN verifiers, proof threads, TCP
# handlers), and two Python threads entering XLA's CPU backend_compile
# concurrently segfault/abort it under load (observed killing pytest
# workers; the tunneled TPU compile service has also failed under
# concurrent compiles). Compiles are rare and cached — serializing them
# costs nothing; kill-switch DRYNX_NO_COMPILE_LOCK=1.
if os.environ.get("DRYNX_NO_COMPILE_LOCK", "0") != "1":
    try:
        import threading as _threading

        from jax._src import compiler as _jax_compiler

        _orig_bcl = _jax_compiler.backend_compile_and_load
        _compile_lock = _threading.Lock()

        def _locked_backend_compile(*args, **kwargs):
            with _compile_lock:
                return _orig_bcl(*args, **kwargs)

        _jax_compiler.backend_compile_and_load = _locked_backend_compile
    except Exception:   # jax internals moved: lose the guard, not the app
        pass
