"""drynx_tpu — TPU-native decentralized, privacy-preserving, verifiable
statistical-query and ML-training framework (capabilities of cgrigis/drynx,
re-designed for JAX/XLA/Pallas/pjit).

64-bit integer support is required for exact statistics vectors and limb
packing (the crypto path itself is pure uint32 limb math); float kernels in
the training path explicitly request float32/bfloat16, so enabling x64 here
does not put float64 on the TPU hot path.
"""
import jax

jax.config.update("jax_enable_x64", True)
