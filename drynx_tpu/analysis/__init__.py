"""drynx_tpu.analysis — AST-based lint pass enforcing the repo's JAX/crypto
invariants (jit-global-capture, unsafe-pickle, implicit-dtype,
host-sync-in-hot-path, env-read-into-trace, secret-logging).

Run ``python -m drynx_tpu.analysis`` or see ANALYSIS.md. Deliberately
jax-free so the linter works even when the accelerator stack is broken.
"""
from .core import (REPO_ROOT, RULES, BaselineEntry, Finding, ModuleInfo,
                   Rule, analyze_paths, analyze_source, apply_baseline,
                   load_baseline)
from . import rules as _rules  # noqa: F401  (populate the registry)
from .cli import DEFAULT_BASELINE, main

__all__ = ["REPO_ROOT", "RULES", "BaselineEntry", "Finding", "ModuleInfo",
           "Rule", "analyze_paths", "analyze_source", "apply_baseline",
           "load_baseline", "DEFAULT_BASELINE", "main"]
