"""drynx_tpu.analysis — AST-based lint pass enforcing the repo's JAX/crypto
invariants (jit-global-capture, cross-module-flag-capture, unsafe-pickle,
implicit-dtype, host-sync-in-hot-path, pallas-operand-dtype,
env-read-into-trace, secret-logging, hardcoded-timeout, thread-trace,
unguarded-shared-mutation, lock-order-inversion,
blocking-call-under-lock, nondet-flow-to-transcript,
unordered-iteration-at-sink, atomic-durable-write,
slab-consumption-order, conn-checkout-discipline, seal-commit-once).

Per-module rules walk one file; ``[project]`` rules get a
:class:`ProjectInfo` (import graph + callgraph over the whole package).
Run ``python -m drynx_tpu.analysis`` or see ANALYSIS.md. Deliberately
jax-free so the linter works even when the accelerator stack is broken.
"""
from .core import (REPO_ROOT, RULES, BaselineEntry, Finding, ModuleInfo,
                   Rule, analyze_paths, analyze_source, apply_baseline,
                   load_baseline, module_info_for)
from .project import ProjectInfo, ProjectRule, analyze_project
from .dataflow import Dataflow, Secret, dataflow_for
from .concurrency import Concurrency, concurrency_for
from .determinism import Determinism, determinism_for
from .typestate import Typestate, typestate_for
from .sarif import to_sarif
from . import rules as _rules  # noqa: F401  (populate the registry)
from .cli import DEFAULT_BASELINE, main

__all__ = ["REPO_ROOT", "RULES", "BaselineEntry", "Finding", "ModuleInfo",
           "Rule", "ProjectInfo", "ProjectRule", "Dataflow", "Secret",
           "Concurrency", "concurrency_for",
           "Determinism", "determinism_for",
           "Typestate", "typestate_for",
           "analyze_paths", "analyze_project", "analyze_source",
           "apply_baseline", "dataflow_for", "load_baseline",
           "module_info_for", "to_sarif", "DEFAULT_BASELINE", "main"]
