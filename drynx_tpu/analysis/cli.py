"""CLI for the static analyzer.

    python -m drynx_tpu.analysis [paths...]        # lint (default: drynx_tpu/)
    python -m drynx_tpu.analysis --list-rules
    python -m drynx_tpu.analysis --format json drynx_tpu/crypto

Exit codes: 0 = clean (all findings baselined/suppressed), 1 = unbaselined
findings (or stale baseline entries under --strict-baseline), 2 = usage.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .core import (REPO_ROOT, RULES, analyze_paths, apply_baseline,
                   load_baseline)
from . import rules as _rules  # noqa: F401  (register the rule set)

DEFAULT_BASELINE = REPO_ROOT / "LINT_BASELINE.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m drynx_tpu.analysis",
        description="AST lint pass enforcing drynx-tpu's JAX/crypto "
                    "invariants (see ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", type=Path,
                    default=None, help="files/dirs to scan "
                    "(default: the drynx_tpu package)")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help=f"baseline JSON (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="fail when baseline entries no longer match "
                         "anything (prune reminder)")
    ap.add_argument("--rule", action="append", dest="rules", metavar="ID",
                    help="run only this rule (repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print(f"{rid}: {rule.summary}")
        return 0

    for rid in args.rules or ():
        if rid not in RULES:
            print(f"unknown rule {rid!r}; --list-rules shows the registry",
                  file=sys.stderr)
            return 2

    paths = args.paths or [REPO_ROOT / "drynx_tpu"]
    for p in paths:
        if not Path(p).exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    findings = analyze_paths(paths, rules=args.rules)
    baseline = [] if args.no_baseline else load_baseline(args.baseline)
    unbaselined, matched, stale = apply_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in unbaselined],
            "baselined": matched,
            "stale_baseline_entries": [e.__dict__ for e in stale],
        }, indent=2))
    else:
        for f in unbaselined:
            print(f.render())
        for e in stale:
            print(f"stale baseline entry (prune it): [{e.rule}] {e.file}: "
                  f"{e.line_text!r}", file=sys.stderr)
        summary = (f"{len(unbaselined)} finding(s)"
                   f" ({matched} baselined) in {len(set(f.file for f in findings))or 0} "
                   f"file(s) with findings")
        print(summary, file=sys.stderr)

    if unbaselined:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
