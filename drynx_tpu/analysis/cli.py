"""CLI for the static analyzer.

    python -m drynx_tpu.analysis [paths...]        # lint (default: drynx_tpu/)
    python -m drynx_tpu.analysis --list-rules
    python -m drynx_tpu.analysis --format json drynx_tpu/crypto
    python -m drynx_tpu.analysis --changed-only    # pre-commit fast tier

By default the whole-program pass runs too (import graph + callgraph, the
``[project]`` rules); ``--no-project`` restricts to the per-module rules.
Project findings carry a call chain, rendered as indented text and as a
stable ``call_chain`` list in ``--format json``.

Exit codes: 0 = clean (all findings baselined/suppressed), 1 = unbaselined
findings (or stale baseline entries under --strict-baseline), 2 = usage.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .core import (REPO_ROOT, RULES, analyze_paths, apply_baseline,
                   load_baseline)
from .project import analyze_project
from . import rules as _rules  # noqa: F401  (register the rule set)

try:  # keep the linter usable even if the resilience package breaks
    from ..resilience.policy import SUBPROCESS_TIMEOUT_S
except Exception:  # pragma: no cover
    SUBPROCESS_TIMEOUT_S = 30.0

DEFAULT_BASELINE = REPO_ROOT / "LINT_BASELINE.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m drynx_tpu.analysis",
        description="AST lint pass enforcing drynx-tpu's JAX/crypto "
                    "invariants (see ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", type=Path,
                    default=None, help="files/dirs to scan "
                    "(default: the drynx_tpu package)")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help=f"baseline JSON (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="fail when baseline entries no longer match "
                         "anything (prune reminder)")
    ap.add_argument("--rule", action="append", dest="rules", metavar="ID",
                    help="run only this rule (repeatable)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--list-rules", action="store_true")
    proj = ap.add_mutually_exclusive_group()
    proj.add_argument("--project", dest="project", action="store_true",
                      default=True,
                      help="run the whole-program pass too (default)")
    proj.add_argument("--no-project", dest="project", action="store_false",
                      help="per-module rules only (no import graph / "
                           "callgraph)")
    ap.add_argument("--changed-only", action="store_true",
                    help="fast tier: restrict reporting to the *impacted "
                         "set* of the files changed vs git HEAD — the "
                         "changed files plus their transitive importers "
                         "via the reverse import graph (full scan fallback "
                         "when git is unavailable)")
    return ap


def _changed_files() -> Optional[List[Path]]:
    """Python files changed vs HEAD (staged + unstaged + untracked), or
    None when git is unavailable / not a repo."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, cwd=REPO_ROOT,
            timeout=SUBPROCESS_TIMEOUT_S)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, cwd=REPO_ROOT,
            timeout=SUBPROCESS_TIMEOUT_S)
        names = diff.stdout.splitlines()
        if untracked.returncode == 0:
            names += untracked.stdout.splitlines()
    except (OSError, subprocess.SubprocessError):
        return None
    out = []
    for n in dict.fromkeys(names):
        p = REPO_ROOT / n
        if n.endswith(".py") and p.exists():
            out.append(p)
    return out


def _relpath(p: Path) -> str:
    try:
        return p.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.resolve().as_posix()


def _is_under(p: Path, root: Path) -> bool:
    try:
        p.resolve().relative_to(Path(root).resolve())
        return True
    except ValueError:
        return False


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        # grouped by backing engine: the lexical per-module rules, the
        # callgraph [project] rules, then one section per whole-program
        # engine (dataflow/concurrency/determinism/typestate)
        order = ["lint", "project", "dataflow", "concurrency",
                 "determinism", "typestate"]
        by_engine: Dict[str, List[Tuple[str, object]]] = {}
        for rid, rule in sorted(RULES.items()):
            by_engine.setdefault(rule.engine, []).append((rid, rule))
        for engine in order + sorted(set(by_engine) - set(order)):
            if engine not in by_engine:
                continue
            print(f"[{engine}]")
            for rid, rule in by_engine[engine]:
                mark = " [project]" if rule.project else ""
                if rule.seed_only:
                    mark += " [seed-only]"
                print(f"  {rid}{mark}: {rule.summary}")
        return 0

    for rid in args.rules or ():
        if rid not in RULES:
            print(f"unknown rule {rid!r}; --list-rules shows the registry",
                  file=sys.stderr)
            return 2

    project_mode = args.project
    changed_rel: Optional[List[str]] = None
    if args.changed_only:
        changed = _changed_files()
        if changed is not None:
            # the deliberately-broken lint fixtures must not redden the
            # pre-commit tier when they themselves are edited
            changed = [p for p in changed
                       if "tests/fixtures" not in _relpath(p)]
        if changed is None:
            print("git unavailable; falling back to a full scan",
                  file=sys.stderr)
            paths = args.paths or [REPO_ROOT / "drynx_tpu"]
        elif not changed:
            print("no changed python files", file=sys.stderr)
            return 0
        else:
            # scan the whole package (graphs/summaries need it), but
            # report only the impacted set: changed files + transitive
            # importers via the reverse import graph. Changed files
            # outside the package scan roots are linted too.
            scan = args.paths or [REPO_ROOT / "drynx_tpu"]
            paths = list(scan) + [p for p in changed
                                  if not any(_is_under(p, s)
                                             for s in scan)]
            changed_rel = [_relpath(p) for p in changed]
            if not project_mode:
                paths = changed
    else:
        paths = args.paths or [REPO_ROOT / "drynx_tpu"]
    for p in paths:
        if not Path(p).exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    if project_mode:
        findings = analyze_project(paths, rules=args.rules,
                                   changed=changed_rel)
    else:
        findings = analyze_paths(paths, rules=args.rules)
    baseline = [] if args.no_baseline else load_baseline(args.baseline)
    unbaselined, matched, stale = apply_baseline(findings, baseline)

    if args.format == "sarif":
        from .sarif import to_sarif
        print(json.dumps(to_sarif(unbaselined), indent=2))
    elif args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in unbaselined],
            "baselined": matched,
            "stale_baseline_entries": [e.__dict__ for e in stale],
        }, indent=2))
    else:
        for f in unbaselined:
            print(f.render())
        for e in stale:
            print(f"stale baseline entry (prune it): [{e.rule}] {e.file}: "
                  f"{e.line_text!r}", file=sys.stderr)
        summary = (f"{len(unbaselined)} finding(s) ({matched} baselined) in "
                   f"{len({f.file for f in findings})} file(s) with findings")
        print(summary, file=sys.stderr)

    if unbaselined:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
