"""Concurrency analysis: thread entries, lock sets, lock order, races.

The serving stack is genuinely concurrent — verify-worker pools, drain
threads, ``fan_out`` RPC workers, async proof-delivery threads, pooled
sockets — and every thread-safety claim so far was a hand audit. This
engine turns those claims into machine-checked facts over the PR-4
project graphs, in four stages:

1. **thread-entry discovery** — every concurrent entry point:
   ``threading.Thread(target=...)`` (name, ``self.method``, lambda and
   wrapper-factory forms), ``threading.Timer``, executor
   ``.submit``/``.map``, and the repo's ``fan_out(entries, mk, call)``
   dispatcher whose ``call`` argument (default ``call_entry``) runs on a
   pool of ``FAN_OUT_WORKERS`` threads. Entries spawned in a loop, from
   an executor or by ``fan_out`` are *multi-instance*: they race with
   themselves, not just with other entries.

2. **shared-state inference** — over everything reachable from the
   entries along the callgraph, mutations of module globals (``global``
   rebinds, aug-assigns, subscript stores), class attributes
   (``self.x = ...`` outside ``__init__``) and container mutators (the
   PR-5 dataflow mutator set). A state mutated from two different
   entries — or from one multi-instance entry — is *shared*.

3. **lock-set analysis** — flow-sensitive tracking of ``with lock:``
   regions and bare ``acquire()``/``release()`` pairs, joined by
   intersection across ``if``/``else`` and ``try`` branches, propagated
   interprocedurally (the held set at a call site flows into the
   callee). A shared mutation site whose lock set shares nothing with
   some other concurrent context's lock set for the same state is an
   ``unguarded-shared-mutation``.

4. **lock-order graph** — every nested acquisition records an edge
   (outer, inner) with its entry and call chain; a cycle across the
   union graph is the classic deadlock shape (``lock-order-inversion``),
   rendered as a SARIF codeFlow via the usual chain hops. Re-acquiring
   an ``RLock`` already held never forms a self-edge. Alongside, a
   blocking call (socket/frame I/O, ``time.sleep``, subprocess, bare
   ``join()``) reachable while any lock is held is a
   ``blocking-call-under-lock`` — the latency hazard that invisibly
   serializes the serving tier.

Lock identity: a lock built via ``resilience.policy.named_lock("name")``
is keyed on that literal — the same name the runtime recorder
(:mod:`.locktrace`) reports, which is what lets the chaos cross-check
assert observed acquisition order is a subgraph of this graph. Unnamed
locks get positional ids (``module:Class.attr`` / ``module:NAME``);
attribute chains that escape static reach (``self.cluster._proof_lock``)
fall back to a unique leaf-name match over the known lock definitions.

Known over-approximations (documented in ANALYSIS.md): per-instance
class locks alias by class, dynamically dispatched handlers are invisible
to the callgraph, and a loop body's acquisitions are assumed released by
loop exit. The engine errs toward flagging; dual-anchor ``noqa`` (at the
site or the entry) absorbs deliberate exceptions.

Still pure ``ast``, still no jax import. The whole run is memoized on
the project content fingerprint like the PR-5 dataflow engine.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, _dotted, _local_bindings
from .dataflow import (RawFinding, _MUTATOR_LEAVES, project_fingerprint)
from .graph import FuncNode, ModuleGraph, _calls_with_scope, _own_returns
from .project import ProjectInfo, chain_hop

_MAX_DEPTH = 8

# Container mutators: the PR-5 dataflow modeling plus the removal half.
_MUTATORS = _MUTATOR_LEAVES | {"pop", "popleft", "popitem", "clear",
                               "remove", "discard"}

_LOCK_CTORS = {"Lock", "RLock"}

# Blocking leaves. Full dotted names where the leaf alone is too generic
# (`subprocess.run` vs every other `run`); method/function leaves where
# the name is specific enough on its own.
_BLOCKING_DOTTED = {"time.sleep", "subprocess.run", "subprocess.call",
                    "subprocess.check_output", "subprocess.check_call",
                    "subprocess.Popen", "socket.create_connection",
                    "select.select"}
_BLOCKING_LEAVES = {"recv_frame", "send_frame", "recv_msg", "send_msg",
                    "sendall", "recv", "recv_into", "accept"}
_BLOCKING_DOTTED_LEAVES = {d.split(".")[-1] for d in _BLOCKING_DOTTED}
# `t.join()` / `q.join()` with no positional args blocks; `sep.join(xs)`
# does not — the argument count is the discriminator.
_BLOCKING_NOARG_METHODS = {"join"}


def _is_drynx_pkg(mod: ModuleInfo) -> bool:
    # same opt-in as rules.py (local copy: rules.py imports this module)
    return (mod.relpath.startswith("drynx_tpu/")
            or "/drynx_tpu/" in mod.relpath
            or "lintpkg" in mod.relpath)


@dataclasses.dataclass(frozen=True)
class LockDef:
    """One statically known lock object."""
    lock_id: str                 # diagnostic name or positional id
    reentrant: bool
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class ThreadEntry:
    """One concurrent entry point (a function some thread runs)."""
    fid: str
    kind: str                    # thread-target|timer|executor|fan-out
    file: str                    # spawn-site file
    line: int                    # spawn-site line
    multi: bool                  # may run >1 instance concurrently


@dataclasses.dataclass(frozen=True)
class EdgeWitness:
    entry: str                   # entry fid that exhibits the order
    file: str                    # inner acquisition site
    line: int
    chain: Tuple[str, ...]       # entry -> ... -> outer acq -> inner acq


def _lock_ctor(call: ast.AST) -> Optional[Tuple[str, bool]]:
    """(name_literal_or_None, reentrant) when ``call`` constructs a lock:
    ``threading.Lock()`` / ``RLock()`` / ``named_lock("x"[, reentrant=])``.
    Returned name is the named_lock literal, or "" for anonymous."""
    if not isinstance(call, ast.Call):
        return None
    d = _dotted(call.func) or ""
    leaf = d.split(".")[-1]
    if leaf in _LOCK_CTORS:
        return "", leaf == "RLock"
    if leaf == "named_lock":
        name = ""
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            name = call.args[0].value
        reentrant = any(
            kw.arg == "reentrant" and isinstance(kw.value, ast.Constant)
            and bool(kw.value.value) for kw in call.keywords)
        return name, reentrant
    return None


class Concurrency:
    """Whole-program concurrency facts over a :class:`ProjectInfo`.

    After :meth:`run`: ``entries`` (fid -> ThreadEntry), ``lock_defs``
    (lock_id -> LockDef), ``lock_order`` ((outer, inner) -> EdgeWitness)
    and the three raw finding lists the project rules consume."""

    def __init__(self, project: ProjectInfo):
        self.project = project
        self.entries: Dict[str, ThreadEntry] = {}
        self.lock_defs: Dict[str, LockDef] = {}
        # (module, name) -> lock_id for module-global locks
        self._module_locks: Dict[Tuple[str, str], str] = {}
        # (module, class, attr) -> lock_id for self-attribute locks
        self._attr_locks: Dict[Tuple[str, str, str], str] = {}
        # leaf attr/name -> {lock_id}: fallback for self.obj._leaf chains
        self._leaf_index: Dict[str, Set[str]] = {}
        # top-level class names per module (to read Class from fn.qual)
        self._classes: Dict[str, Set[str]] = {}
        # state -> entry fid -> [(file, line, held, chain)]
        self.mut_sites: Dict[str, Dict[str, List[
            Tuple[str, int, FrozenSet[str], Tuple[str, ...]]]]] = {}
        self.lock_order: Dict[Tuple[str, str], EdgeWitness] = {}
        # (file, line) -> (leaf, held, chain)
        self._blocking: Dict[Tuple[str, int],
                             Tuple[str, FrozenSet[str],
                                   Tuple[str, ...]]] = {}
        self.unguarded_raw: List[RawFinding] = []
        self.cycle_raw: List[RawFinding] = []
        self.blocking_raw: List[RawFinding] = []
        # fid -> (locals, global-decls, {id(call): callee}) — a function
        # is re-walked once per distinct held set, but its AST facts
        # never change
        self._fn_facts: Dict[str, Tuple[Set[str], Set[str],
                                        Dict[int, str]]] = {}

    # -- driver -----------------------------------------------------------

    def run(self) -> "Concurrency":
        self._collect_locks()
        self._collect_entries()
        self._record_muts = True
        for fid in sorted(self.entries):
            fn = self.project.calls.functions.get(fid)
            if fn is not None:
                self._walk_entry(self.entries[fid], fn)
        # Supplemental whole-program pass: every function is ALSO a
        # synchronous (caller-thread) context. Shared-state inference
        # stays entry-scoped (the main thread reaching everything would
        # drown the race rule), but lock-order edges and blocking calls
        # must cover code the entry walk can't resolve — method calls on
        # unknown receivers (`conn.call(...)`), dynamic handler dispatch
        # — or the runtime recorder would observe acquisition edges the
        # static graph lacks and the dynamic-subgraph cross-check would
        # be unsound.
        self._record_muts = False
        self._visited = set()
        for fid in sorted(self.project.calls.functions):
            fn = self.project.calls.functions[fid]
            mg = self.project.graphs[fn.module]
            if not _is_drynx_pkg(mg.info):
                continue
            # the held set stays empty until the root itself acquires,
            # and every acquirer is its own root — so only functions
            # whose body can acquire are worth walking
            if not _acquires_syntactically(fn.node):
                continue
            self._entry = ThreadEntry(fid, "sync", mg.info.relpath,
                                      fn.node.lineno, False)
            self._walk_fn(fn, frozenset(),
                          (chain_hop(mg.info.relpath, fn.node.lineno,
                                     fn.qual),), 0)
        self._emit_unguarded()
        self._emit_cycles()
        self._emit_blocking()
        return self

    # -- stage 0: lock definitions ----------------------------------------

    def _add_lock(self, lock_id: str, reentrant: bool, mg: ModuleGraph,
                  lineno: int, leaf: str) -> None:
        if lock_id not in self.lock_defs:
            self.lock_defs[lock_id] = LockDef(lock_id, reentrant,
                                              mg.info.relpath, lineno)
        self._leaf_index.setdefault(leaf, set()).add(lock_id)

    def _collect_locks(self) -> None:
        for dotted in sorted(self.project.graphs):
            mg = self.project.graphs[dotted]
            if not _is_drynx_pkg(mg.info):
                continue
            self._classes[dotted] = {
                n.name for n in mg.info.tree.body
                if isinstance(n, ast.ClassDef)}
            # module-level NAME = Lock()/named_lock()
            for name, assigns in mg.info.module_assigns.items():
                for a in assigns:
                    got = _lock_ctor(a.value)
                    if got is None:
                        continue
                    lit, reentrant = got
                    lock_id = lit or f"{dotted}:{name}"
                    self._module_locks[(dotted, name)] = lock_id
                    self._add_lock(lock_id, reentrant, mg, a.lineno, name)
            # self.attr = Lock()/named_lock() in any method of a class
            for qual, fn in mg.functions.items():
                cls = qual.split(".")[0]
                if cls not in self._classes[dotted] or "." not in qual:
                    continue
                for stmt in ast.walk(fn.node):
                    if not isinstance(stmt, ast.Assign):
                        continue
                    got = _lock_ctor(stmt.value)
                    if got is None:
                        continue
                    for t in stmt.targets:
                        d = _dotted(t)
                        if not d or not d.startswith("self.") \
                                or d.count(".") != 1:
                            continue
                        attr = d.split(".")[1]
                        lit, reentrant = got
                        lock_id = lit or f"{dotted}:{cls}.{attr}"
                        self._attr_locks[(dotted, cls, attr)] = lock_id
                        self._add_lock(lock_id, reentrant, mg,
                                       stmt.lineno, attr)

    # -- stage 1: thread entries ------------------------------------------

    def _note_entry(self, fn: Optional[FuncNode], kind: str,
                    mg: ModuleGraph, lineno: int, multi: bool) -> None:
        if fn is None:
            return
        prev = self.entries.get(fn.fid)
        if prev is None:
            self.entries[fn.fid] = ThreadEntry(
                fn.fid, kind, mg.info.relpath, lineno, multi)
        elif multi and not prev.multi:
            self.entries[fn.fid] = dataclasses.replace(prev, multi=True)

    def _callable_target(self, mg: ModuleGraph, scope: Sequence[str],
                         expr: ast.AST) -> List[FuncNode]:
        """FuncNodes a thread-target expression may run: a name, a
        ``self.method`` reference, a lambda's callees, or a wrapper
        factory call returning a nested worker function."""
        calls = self.project.calls
        if isinstance(expr, ast.Name):
            fn = calls._resolve_name(mg, scope, expr.id)
            return [fn] if fn is not None else []
        if isinstance(expr, ast.Attribute):
            fn = calls._resolve_attribute(mg, expr)
            return [fn] if fn is not None else []
        if isinstance(expr, ast.Lambda):
            out = []
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    got = calls.resolve_call(mg, scope, sub)
                    if got is not None:
                        out.append(got)
            return out
        if isinstance(expr, ast.Call):
            # wrapper factory: target=make_worker(cfg) — the entry is the
            # nested function make_worker returns
            factory = calls.resolve_call(mg, scope, expr)
            if factory is None:
                return []
            fmg = self.project.graphs[factory.module]
            out = []
            for ret in _own_returns(factory.node):
                if isinstance(ret.value, ast.Name):
                    qual = f"{factory.qual}.{ret.value.id}"
                    nested = fmg.functions.get(qual)
                    if nested is not None:
                        out.append(nested)
            return out
        return []

    _ENTRY_LEAVES = frozenset({"Thread", "Timer", "submit", "map",
                               "fan_out"})

    def _collect_entries(self) -> None:
        for dotted in sorted(self.project.graphs):
            mg = self.project.graphs[dotted]
            if not _is_drynx_pkg(mg.info):
                continue
            loops: Optional[Set[int]] = None  # computed on first match
            for scope, call in _calls_with_scope(mg):
                leaf = _leaf_of(call.func)
                if leaf not in self._ENTRY_LEAVES:
                    continue
                d = _dotted(call.func) or ""
                if loops is None:
                    loops = _loop_lines(mg.info.tree)
                in_loop = call.lineno in loops
                if leaf in ("Thread", "Timer") and (
                        d in ("Thread", "Timer")
                        or d.startswith("threading.")):
                    target = next((kw.value for kw in call.keywords
                                   if kw.arg in ("target", "function")),
                                  None)
                    if target is None and leaf == "Timer" \
                            and len(call.args) >= 2:
                        target = call.args[1]
                    for fn in self._callable_target(mg, scope, target) \
                            if target is not None else []:
                        self._note_entry(fn, "timer" if leaf == "Timer"
                                         else "thread-target",
                                         mg, call.lineno, in_loop)
                elif leaf in ("submit", "map") \
                        and isinstance(call.func, ast.Attribute) \
                        and call.args:
                    for fn in self._callable_target(mg, scope,
                                                    call.args[0]):
                        self._note_entry(fn, "executor", mg,
                                         call.lineno, True)
                elif leaf == "fan_out":
                    # fan_out(entries, make_msg, call=..., ...): the call
                    # argument runs on FAN_OUT_WORKERS pool threads;
                    # default is call_entry in the defining module
                    target = next((kw.value for kw in call.keywords
                                   if kw.arg == "call"), None)
                    if target is None and len(call.args) >= 3:
                        target = call.args[2]
                    if target is not None:
                        for fn in self._callable_target(mg, scope, target):
                            self._note_entry(fn, "fan-out", mg,
                                             call.lineno, True)
                    else:
                        fan = self.project.calls.resolve_call(mg, scope,
                                                              call)
                        if fan is not None:
                            fmg = self.project.graphs[fan.module]
                            self._note_entry(
                                fmg.lookup_function("call_entry"),
                                "fan-out", mg, call.lineno, True)

    # -- stage 2+3: the interprocedural walk ------------------------------

    def _walk_entry(self, entry: ThreadEntry, fn: FuncNode) -> None:
        mg = self.project.graphs[fn.module]
        chain = (chain_hop(entry.file, entry.line,
                           f"thread entry {fn.qual}"),)
        self._entry = entry
        self._visited: Set[Tuple[str, FrozenSet[str]]] = set()
        self._walk_fn(fn, frozenset(), chain, 0)

    def _walk_fn(self, fn: FuncNode, held: FrozenSet[str],
                 chain: Tuple[str, ...], depth: int) -> None:
        key = (fn.fid, held)
        if key in self._visited or depth > _MAX_DEPTH:
            return
        self._visited.add(key)
        mg = self.project.graphs[fn.module]
        ctx = _FnCtx(self, mg, fn, chain, depth)
        ctx.exec_stmts(fn.node.body, held)

    # -- recording (called from _FnCtx) -----------------------------------

    def _record_mutation(self, state: str, file: str, line: int,
                         held: FrozenSet[str],
                         chain: Tuple[str, ...]) -> None:
        if not self._record_muts:
            return
        guard = frozenset(l for l in held if not l.startswith("local:"))
        per_entry = self.mut_sites.setdefault(state, {})
        per_entry.setdefault(self._entry.fid, []).append(
            (file, line, guard, chain))

    def _record_edge(self, outer: str, inner: str, file: str, line: int,
                     chain: Tuple[str, ...]) -> None:
        if outer == inner:
            return  # RLock re-entry / idempotent with — never a self-edge
        self.lock_order.setdefault(
            (outer, inner),
            EdgeWitness(self._entry.fid, file, line, chain))

    def _record_blocking(self, leaf: str, file: str, line: int,
                         held: FrozenSet[str],
                         chain: Tuple[str, ...]) -> None:
        self._blocking.setdefault((file, line), (leaf, held, chain))

    # -- lock resolution ---------------------------------------------------

    def _resolve_lock(self, mg: ModuleGraph, fn: FuncNode,
                      aliases: Dict[str, str],
                      expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if not d:
            return None
        parts = d.split(".")
        cls = fn.qual.split(".")[0]
        if cls not in self._classes.get(mg.dotted, ()):
            cls = ""
        if len(parts) == 1:
            name = parts[0]
            if name in aliases:
                return aliases[name]
            dm, dn, _ = self.project.imports.resolve(mg.dotted, name)
            return self._module_locks.get((dm, dn))
        if parts[0] in ("self", "cls"):
            attr = parts[-1]
            if len(parts) == 2 and cls:
                got = self._attr_locks.get((mg.dotted, cls, attr))
                if got is not None:
                    return got
                if "lock" in attr.lower():
                    return f"{mg.dotted}:{cls}.{attr}"
                return None
            # longer chain (self.cluster._proof_device_lock): unique
            # leaf-name match over the known defs, else a leaf-keyed id
            ids = self._leaf_index.get(attr, ())
            if len(ids) == 1:
                return next(iter(ids))
            if "lock" in attr.lower():
                return f"attr:{attr}"
            return None
        if len(parts) == 2:
            target = self.project.imports.module_for_alias(mg.dotted,
                                                           parts[0])
            if target is not None:
                got = self._module_locks.get((target, parts[1]))
                if got is not None:
                    return got
        attr = parts[-1]
        ids = self._leaf_index.get(attr, ())
        if len(ids) == 1:
            return next(iter(ids))
        if "lock" in attr.lower():
            return f"attr:{attr}"
        return None

    def _is_reentrant(self, lock_id: str) -> bool:
        d = self.lock_defs.get(lock_id)
        return d is not None and d.reentrant

    # -- stage 4: findings -------------------------------------------------

    def _entry_label(self, fid: str) -> str:
        e = self.entries[fid]
        mult = " x N" if e.multi else ""
        return f"{fid.split(':', 1)[-1]} ({e.kind}{mult})"

    def _emit_unguarded(self) -> None:
        for state in sorted(self.mut_sites):
            per_entry = self.mut_sites[state]
            weight = sum(2 if self.entries[f].multi else 1
                         for f in per_entry)
            if weight < 2:
                continue
            # per-entry lock set: provably held at EVERY mutation of the
            # state from that entry
            locksets = {f: frozenset.intersection(
                *[h for _, _, h, _ in sites])
                for f, sites in per_entry.items()}
            contexts = sorted(per_entry)
            for fid in contexts:
                others = [locksets[o] for o in contexts if o != fid]
                if self.entries[fid].multi:
                    others.append(locksets[fid])
                mine = locksets[fid]
                if others and all(mine & o for o in others):
                    continue
                reported: Set[Tuple[str, int]] = set()
                for file, line, held, chain in per_entry[fid]:
                    if (file, line) in reported:
                        continue
                    reported.add((file, line))
                    names = ", ".join(sorted(held)) or "no lock"
                    ents = ", ".join(self._entry_label(f)
                                     for f in contexts)
                    self.unguarded_raw.append(RawFinding(
                        file=file, line=line,
                        message=(
                            f"shared state '{state}' is mutated from "
                            f"{len(contexts)} concurrent context(s) "
                            f"[{ents}] holding {names} here — no lock "
                            f"is common to all mutating threads"),
                        chain=chain + (chain_hop(file, line,
                                                 f"mutates {state}"),),
                        anchors=self._anchors(chain, file, line)))
        self.unguarded_raw.sort(key=lambda r: (r.file, r.line))

    def _emit_cycles(self) -> None:
        # union lock-order graph over non-local locks; a cycle means two
        # threads can each hold one lock while waiting for the other
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.lock_order:
            if a.startswith("local:") or b.startswith("local:"):
                continue
            graph.setdefault(a, set()).add(b)
        seen_cycles: Set[FrozenSet[str]] = set()
        for start in sorted(graph):
            cycle = _find_cycle(graph, start)
            if cycle is None or frozenset(cycle) in seen_cycles:
                continue
            seen_cycles.add(frozenset(cycle))
            edges = [(cycle[i], cycle[(i + 1) % len(cycle)])
                     for i in range(len(cycle))]
            chain: List[str] = []
            anchors: List[Tuple[str, int]] = []
            for e in edges:
                w = self.lock_order[e]
                for hop in w.chain:
                    if hop not in chain:
                        chain.append(hop)
                chain.append(chain_hop(w.file, w.line,
                                       f"acquires {e[1]} while "
                                       f"holding {e[0]}"))
                anchors.append((w.file, w.line))
            w0 = self.lock_order[edges[0]]
            order = " -> ".join(cycle + [cycle[0]])
            self.cycle_raw.append(RawFinding(
                file=w0.file, line=w0.line,
                message=(f"lock-order inversion: {order} — different "
                         f"threads acquire these locks in conflicting "
                         f"order (deadlock when they interleave)"),
                chain=tuple(chain[:12]),
                anchors=tuple(anchors)))
        self.cycle_raw.sort(key=lambda r: (r.file, r.line))

    def _emit_blocking(self) -> None:
        for (file, line) in sorted(self._blocking):
            leaf, held, chain = self._blocking[(file, line)]
            names = ", ".join(sorted(held))
            self.blocking_raw.append(RawFinding(
                file=file, line=line,
                message=(f"blocking call '{leaf}' while holding "
                         f"[{names}] — every thread contending on the "
                         f"lock serializes behind this wait"),
                chain=chain + (chain_hop(file, line, f"{leaf}()"),),
                anchors=self._anchors(chain, file, line)))

    @staticmethod
    def _anchors(chain: Tuple[str, ...], file: str,
                 line: int) -> Tuple[Tuple[str, int], ...]:
        """Dual anchors: the site plus the entry hop (suppressible at
        either)."""
        out = [(file, line)]
        if chain:
            first = chain[0].split(":", 2)
            if len(first) == 3 and first[1].isdigit():
                out.append((first[0], int(first[1])))
        return tuple(out)

    # -- cross-validation surface ------------------------------------------

    def named_lock_edges(self) -> Set[Tuple[str, str]]:
        """Acquisition-order edges between *named* locks (ids that carry
        no positional ``module:``/``attr:``/``local:`` shape) — the
        static side of the DRYNX_LOCK_TRACE runtime cross-check."""
        def named(lid: str) -> bool:
            return ":" not in lid and "." not in lid
        return {(a, b) for (a, b) in self.lock_order
                if named(a) and named(b)}


# -- flow-sensitive statement executor --------------------------------------

class _FnCtx:
    """Executes one function body with a held-lock set, recording
    mutations, acquisition edges and blocking calls; recurses into
    resolvable callees with the held set at the call site."""

    def __init__(self, eng: Concurrency, mg: ModuleGraph, fn: FuncNode,
                 chain: Tuple[str, ...], depth: int):
        self.eng = eng
        self.mg = mg
        self.fn = fn
        self.chain = chain
        self.depth = depth
        self.rel = mg.info.relpath
        self.aliases: Dict[str, str] = {}
        facts = eng._fn_facts.get(fn.fid)
        if facts is None:
            globals_decl: Set[str] = set()
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Global):
                    globals_decl.update(sub.names)
            facts = (_local_bindings(fn.node), globals_decl,
                     {id(s.node): s.callee
                      for s in eng.project.calls.callees(fn.fid)})
            eng._fn_facts[fn.fid] = facts
        self.locals, self.globals_decl, self.sites = facts
        self.is_init = fn.qual.split(".")[-1] == "__init__"

    # -- statements --------------------------------------------------------

    def exec_stmts(self, stmts: Sequence[ast.stmt],
                   held: FrozenSet[str]) -> FrozenSet[str]:
        for stmt in stmts:
            held = self.exec_stmt(stmt, held)
        return held

    def exec_stmt(self, stmt: ast.stmt,
                  held: FrozenSet[str]) -> FrozenSet[str]:
        if isinstance(stmt, ast.With):
            locks: List[str] = []
            for item in stmt.items:
                self.scan_expr(item.context_expr, held)
                lid = self.eng._resolve_lock(self.mg, self.fn,
                                             self.aliases,
                                             item.context_expr)
                if lid is not None:
                    for h in held | frozenset(locks):
                        self.eng._record_edge(
                            h, lid, self.rel, item.context_expr.lineno,
                            self.chain + (chain_hop(
                                self.rel, item.context_expr.lineno,
                                f"with {lid}"),))
                    locks.append(lid)
            self.exec_stmts(stmt.body, held | frozenset(locks))
            return held
        if isinstance(stmt, ast.If):
            self.scan_expr(stmt.test, held)
            h1 = self.exec_stmts(stmt.body, held)
            h2 = self.exec_stmts(stmt.orelse, held)
            return h1 & h2
        if isinstance(stmt, ast.Try):
            hb = self.exec_stmts(stmt.body, held)
            out = self.exec_stmts(stmt.orelse, hb) if stmt.orelse else hb
            for handler in stmt.handlers:
                out = out & self.exec_stmts(handler.body, held)
            if stmt.finalbody:
                out = self.exec_stmts(stmt.finalbody, out)
            return out
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.scan_expr(stmt.iter, held)
            self.mutation_target(stmt.target, held)
            self.exec_stmts(stmt.body, held)
            self.exec_stmts(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self.scan_expr(stmt.test, held)
            self.exec_stmts(stmt.body, held)
            self.exec_stmts(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return held  # nested defs are their own callgraph nodes
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            if value is not None:
                held = self.scan_expr(value, held)
            for t in targets:
                self.mutation_target(t, held)
            # lock aliasing: x = <lock expr> / x = Lock()
            if isinstance(stmt, ast.Assign) and value is not None \
                    and len(targets) == 1 \
                    and isinstance(targets[0], ast.Name):
                name = targets[0].id
                ctor = _lock_ctor(value)
                if ctor is not None:
                    lit, reentrant = ctor
                    lid = lit or f"local:{self.fn.fid}:{name}"
                    self.aliases[name] = lid
                    if lid not in self.eng.lock_defs:
                        self.eng.lock_defs[lid] = LockDef(
                            lid, reentrant, self.rel, stmt.lineno)
                else:
                    lid = self.eng._resolve_lock(self.mg, self.fn,
                                                 self.aliases, value)
                    if lid is not None:
                        self.aliases[name] = lid
            return held
        if isinstance(stmt, ast.Expr):
            return self.scan_expr(stmt.value, held)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                self.scan_expr(child, held)
            return held
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                held = self.scan_expr(child, held)
            elif isinstance(child, ast.stmt):
                held = self.exec_stmt(child, held)
        return held

    # -- expressions -------------------------------------------------------

    def scan_expr(self, expr: ast.AST,
                  held: FrozenSet[str]) -> FrozenSet[str]:
        """Visit calls in an expression (not into nested defs/lambdas);
        returns the possibly-updated held set (bare acquire/release)."""
        for node in _expr_calls(expr):
            held = self.visit_call(node, held)
        return held

    def visit_call(self, call: ast.Call,
                   held: FrozenSet[str]) -> FrozenSet[str]:
        leaf = _leaf_of(call.func)
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            if leaf in ("acquire", "release"):
                lid = self.eng._resolve_lock(self.mg, self.fn,
                                             self.aliases, recv)
                if lid is not None:
                    if leaf == "acquire":
                        for h in held:
                            self.eng._record_edge(
                                h, lid, self.rel, call.lineno,
                                self.chain + (chain_hop(
                                    self.rel, call.lineno,
                                    f"{lid}.acquire()"),))
                        return held | {lid}
                    return held - {lid}
            if leaf in _MUTATORS:
                state = self.state_of(recv)
                if state is not None:
                    self.eng._record_mutation(state, self.rel,
                                              call.lineno, held,
                                              self.chain)
        if held and not self.is_init:
            blocking = (leaf in _BLOCKING_LEAVES
                        or (leaf in _BLOCKING_DOTTED_LEAVES
                            and (_dotted(call.func) or "")
                            in _BLOCKING_DOTTED)
                        or (leaf in _BLOCKING_NOARG_METHODS
                            and isinstance(call.func, ast.Attribute)
                            and not call.args))
            if blocking:
                self.eng._record_blocking(leaf, self.rel, call.lineno,
                                          held, self.chain)
        # interprocedural hop
        callee_fid = self.sites.get(id(call))
        if callee_fid is not None \
                and callee_fid not in self.eng.entries:
            callee = self.eng.project.calls.functions.get(callee_fid)
            if callee is not None:
                hop = chain_hop(self.rel, call.lineno, callee.qual)
                self.eng._walk_fn(callee, held, self.chain + (hop,),
                                  self.depth + 1)
        return held

    # -- shared-state targets ----------------------------------------------

    def mutation_target(self, target: ast.AST,
                        held: FrozenSet[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.mutation_target(el, held)
            return
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Starred):
            node = node.value
        state = self.state_of(node, store=target is node)
        if state is not None:
            self.eng._record_mutation(state, self.rel, target.lineno,
                                      held, self.chain)

    def state_of(self, node: ast.AST,
                 store: bool = False) -> Optional[str]:
        """Canonical shared-state id for a mutated expression root:
        ``module:NAME`` for module globals, ``module:Class.attr`` for
        instance/class attributes; None for locals and unknowns."""
        if isinstance(node, ast.Name):
            name = node.id
            if store:
                # plain `x = ...` rebinding is a local unless global-decl
                if name not in self.globals_decl:
                    return None
            else:
                # container/subscript mutation through a name: global if
                # not locally bound and defined at module level
                if name in self.locals and name not in self.globals_decl:
                    return None
                if name not in self.globals_decl \
                        and name not in self.mg.info.module_assigns \
                        and name not in self.mg.froms:
                    return None
            dm, dn, _ = self.eng.project.imports.resolve(self.mg.dotted,
                                                         name)
            if dm not in self.eng.project.graphs:
                return None
            return f"{dm}:{dn}"
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if not d:
                return None
            parts = d.split(".")
            if parts[0] in ("self", "cls") and len(parts) == 2:
                if self.is_init:
                    return None  # construction happens-before publication
                cls = self.fn.qual.split(".")[0]
                if cls not in self.eng._classes.get(self.mg.dotted, ()):
                    return None
                return f"{self.mg.dotted}:{cls}.{parts[1]}"
            if len(parts) == 2:
                target = self.eng.project.imports.module_for_alias(
                    self.mg.dotted, parts[0])
                if target is not None \
                        and target in self.eng.project.graphs:
                    return f"{target}:{parts[1]}"
        return None


# -- small AST helpers -------------------------------------------------------

def _expr_calls(expr: ast.AST):
    """Call nodes in an expression, outermost-first, not descending into
    lambdas or comprehension-free nested defs."""
    out: List[ast.Call] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            return
        if isinstance(n, ast.Call):
            out.append(n)
        for c in ast.iter_child_nodes(n):
            visit(c)

    visit(expr)
    return out


def _leaf_of(func: ast.AST) -> str:
    """Last dotted component of a call target without building the whole
    dotted string — the hot path looks at every call in the package."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _acquires_syntactically(fn: ast.AST) -> bool:
    """Cheap prefilter: the function's own body (not nested defs) has a
    ``with`` statement or an ``.acquire()`` call — the only statements
    that can make the held set non-empty."""
    def visit(node: ast.AST) -> bool:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                return True
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr == "acquire":
                return True
            if visit(child):
                return True
        return False

    return visit(fn)


def _loop_lines(tree: ast.AST) -> Set[int]:
    """Line numbers lexically inside a For/While body (spawn-in-a-loop
    detection for multi-instance entries)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            end = getattr(node, "end_lineno", None)
            if end is not None:
                out.update(range(node.lineno, end + 1))
    return out


def _find_cycle(graph: Dict[str, Set[str]],
                start: str) -> Optional[List[str]]:
    """Shortest simple cycle through ``start`` (BFS over the digraph),
    as the node list [start, ..., last] with last -> start implied."""
    from collections import deque

    queue = deque([(start, [start])])
    seen = {start}
    while queue:
        node, path = queue.popleft()
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                return path
            if nxt not in seen:
                seen.add(nxt)
                queue.append((nxt, path + [nxt]))
    return None


# -- memoized entry point ----------------------------------------------------

_CC_CACHE: Dict[str, Concurrency] = {}
_CC_CACHE_MAX = 8


def concurrency_for(project: ProjectInfo) -> Concurrency:
    """The (memoized) engine run for a project — the three consuming
    rules, repeated analyze_project calls and the lock-trace cross-check
    all share one run per tree version. The result is whole-program;
    ``--changed-only`` focus filtering happens in the rules."""
    fp = project_fingerprint(project)
    eng = _CC_CACHE.get(fp)
    if eng is None:
        if len(_CC_CACHE) >= _CC_CACHE_MAX:
            _CC_CACHE.clear()
        eng = Concurrency(project).run()
        _CC_CACHE[fp] = eng
    return eng
