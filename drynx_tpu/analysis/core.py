"""AST lint framework enforcing the repo's JAX/crypto invariants.

Drynx's security argument rests on every node computing over ciphertexts
correctly; in this port the equivalent hazards are *silent* Python/JAX bug
classes — jit traces capturing mutable module globals, raw ``pickle.loads``
on attacker-controlled proof bytes, implicit-dtype arrays corrupting uint32
limb arithmetic. The rules in :mod:`.rules` mechanically block those classes
in CI so later perf PRs can refactor the crypto freely.

Framework pieces:

* :class:`Finding` — one violation, ``file:line`` + rule id + message.
* :class:`Rule` + :func:`register` — the rule registry (:data:`RULES`).
* :class:`ModuleInfo` — parsed file + the shared derived facts rules need
  (jit-decorated functions, pallas-call sites, env-derived module globals).
* inline suppression — ``# drynx: noqa[rule-id]`` (or bare ``noqa`` for all
  rules) on the offending line.
* baseline — ``LINT_BASELINE.json`` grandfathers pre-existing findings.
  Entries are keyed on (rule, file, stripped line text) rather than line
  numbers so unrelated edits don't invalidate them; each carries a ``why``.

No jax import here: the analyzer must run (and fail loudly) even on a box
where the accelerator stack is broken.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# Repo root = parent of the drynx_tpu package (this file is
# drynx_tpu/analysis/core.py). Baseline keys and reported paths are
# relative to it so results are stable regardless of the caller's cwd.
REPO_ROOT = Path(__file__).resolve().parents[2]

_NOQA_RE = re.compile(r"#\s*drynx:\s*noqa(?:\[([A-Za-z0-9_,\- ]+)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # posix path relative to REPO_ROOT when possible
    line: int          # 1-based
    message: str
    line_text: str     # stripped source line (baseline key component)
    # Project-rule extras. call_chain renders the path that made the finding
    # fire ("file:line:symbol" hops — import chain for flag taint, call
    # chain for transitive host syncs, dtype-proof trail for pallas
    # operands). anchors are extra (file, line) suppression points: a
    # callgraph finding is suppressible at the sync site OR the jit entry.
    call_chain: Tuple[str, ...] = ()
    anchors: Tuple[Tuple[str, int], ...] = ()

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.line_text)

    def render(self) -> str:
        out = f"{self.file}:{self.line}: [{self.rule}] {self.message}"
        if self.call_chain:
            out += "\n    call chain: " + " -> ".join(self.call_chain)
        return out

    def to_json(self) -> Dict[str, object]:
        """Stable JSON shape for --format json (call_chain always a list)."""
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "line_text": self.line_text,
                "call_chain": list(self.call_chain)}


class Rule:
    """Base class: subclasses set ``id``/``summary`` and implement run()."""

    id: str = ""
    summary: str = ""
    project = False    # ProjectRule flips this; --list-rules marks it
    engine = "lint"    # which analysis engine backs the rule; --list-rules
    #                    groups by it (lint/project/dataflow/concurrency/
    #                    determinism/typestate)
    seed_only = False  # kept as a seed list for a dataflow successor rule
    absorbs: Tuple[str, ...] = ()  # rule ids this rule's findings dedupe

    def run(self, mod: "ModuleInfo") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: "ModuleInfo", node: ast.AST, message: str,
                call_chain: Sequence[str] = (),
                anchors: Sequence[Tuple[str, int]] = ()) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.id, file=mod.relpath, line=line,
                       message=message, line_text=mod.line_text(line),
                       call_chain=tuple(call_chain), anchors=tuple(anchors))


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    RULES[rule.id] = rule
    return cls


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _contains_env_read(node: ast.AST) -> bool:
    """True when the subtree reads os.environ / os.getenv."""
    for sub in ast.walk(node):
        d = _dotted(sub)
        if d and (d.startswith("os.environ") or d == "os.getenv"):
            return True
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    """jax.jit / pjit / partial(jax.jit, ...) / jax.jit(...) shapes."""
    d = _dotted(dec)
    if d in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return True
    if isinstance(dec, ast.Call):
        fd = _dotted(dec.func)
        if fd in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return True
        if fd in ("functools.partial", "partial") and dec.args:
            return _is_jit_decorator(dec.args[0])
    return False


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside the function (params + assignment targets),
    used to tell a captured module global from a local shadow."""
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                            ast.For, ast.comprehension)):
            tgts = []
            if isinstance(sub, ast.Assign):
                tgts = sub.targets
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                tgts = [sub.target]
            elif isinstance(sub, ast.For):
                tgts = [sub.target]
            else:
                tgts = [sub.target]
            for t in tgts:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        bound.add(n.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub is not fn:
            bound.add(sub.name)
        elif isinstance(sub, ast.Global):
            bound.difference_update(sub.names)
    return bound


class ModuleInfo:
    """One parsed source file + the derived facts the rules share."""

    def __init__(self, source: str, relpath: str):
        self.source = source
        self.relpath = relpath
        self.lines = source.splitlines()
        self.content_hash = hashlib.sha256(source.encode("utf-8")).hexdigest()
        self.tree = ast.parse(source)
        self._analyze()

    # -- derived facts ----------------------------------------------------

    def _analyze(self) -> None:
        self.functions: List[ast.AST] = [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        self.jit_functions = [
            f for f in self.functions
            if any(_is_jit_decorator(d) for d in f.decorator_list)]

        # Functions that build a pallas_call: their bodies are evaluated at
        # trace time and the kernel config (e.g. interpret=FLAG) is baked
        # into the jit trace of whichever caller jits them. One DFS over
        # the module with an enclosing-function stack — re-walking every
        # function subtree is quadratic under nesting. The same pass
        # collects `global` declarations for the rebound set below.
        pallas_set: Set[ast.AST] = set()
        global_names: Set[str] = set()

        def _scan(node: ast.AST, stack: Tuple[ast.AST, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call):
                    d = _dotted(child.func)
                    if d and d.split(".")[-1] == "pallas_call":
                        pallas_set.update(stack)
                elif isinstance(child, ast.Global) and stack:
                    global_names.update(child.names)
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _scan(child, stack + (child,))
                else:
                    _scan(child, stack)

        _scan(self.tree, ())
        self.pallas_functions = [f for f in self.functions
                                 if f in pallas_set]
        self.traced_functions = list(dict.fromkeys(
            self.jit_functions + self.pallas_functions))

        # Module-level simple assignments: name -> [assign nodes]
        self.module_assigns: Dict[str, List[ast.Assign]] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_assigns.setdefault(t.id, []).append(node)

        # Names whose import-time value comes from the process environment.
        self.env_derived: Dict[str, ast.Assign] = {
            name: assigns[0]
            for name, assigns in self.module_assigns.items()
            if any(_contains_env_read(a.value) for a in assigns)}

        # Names rebound at runtime (multiple module-level assigns, or a
        # `global` declaration inside any function).
        self.rebound: Set[str] = {
            name for name, assigns in self.module_assigns.items()
            if len(assigns) > 1}
        self.rebound.update(global_names)

    # -- helpers ----------------------------------------------------------

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def noqa_match(self, rule: str, line: int) -> bool:
        raw = ""
        if 1 <= line <= len(self.lines):
            raw = self.lines[line - 1]
        m = _NOQA_RE.search(raw)
        if not m:
            return False
        if m.group(1) is None:
            return True
        allowed = {r.strip() for r in m.group(1).split(",")}
        return rule in allowed

    def suppressed(self, finding: Finding) -> bool:
        if self.noqa_match(finding.rule, finding.line):
            return True
        # same-file extra anchors (jit entry of a callgraph finding)
        return any(file == self.relpath and self.noqa_match(finding.rule, ln)
                   for file, ln in finding.anchors)


# ---------------------------------------------------------------------------
# Scanning
# ---------------------------------------------------------------------------

# Parse results are cached on (content sha256, relpath): the per-module pass
# and the project pass (and repeated runs in one process, e.g. the test
# suite) share one ModuleInfo per file version instead of re-parsing.
_INFO_CACHE: Dict[Tuple[str, str], "ModuleInfo"] = {}
_INFO_CACHE_MAX = 4096


def module_info_for(source: str, relpath: str) -> "ModuleInfo":
    """ModuleInfo for (source, relpath), memoized on the content hash.
    Raises SyntaxError like the constructor."""
    key = (hashlib.sha256(source.encode("utf-8")).hexdigest(), relpath)
    info = _INFO_CACHE.get(key)
    if info is None:
        if len(_INFO_CACHE) >= _INFO_CACHE_MAX:
            _INFO_CACHE.clear()
        info = ModuleInfo(source, relpath)
        _INFO_CACHE[key] = info
    return info


def suppressed_at(finding: Finding, modules: Dict[str, "ModuleInfo"]) -> bool:
    """True when a noqa for the rule sits on the finding line OR on any of
    its extra anchors (e.g. the jit entry of a callgraph finding)."""
    for file, line in ((finding.file, finding.line), *finding.anchors):
        mod = modules.get(file)
        if mod is not None and mod.noqa_match(finding.rule, line):
            return True
    return False


def _rel(path: Path) -> str:
    p = path.resolve()
    try:
        return p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


def iter_py_files(paths: Sequence[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def analyze_source(source: str, relpath: str,
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run (a subset of) the rules over one source string. Test entrypoint;
    also the per-file worker for analyze_paths."""
    from . import rules as _rules  # noqa: F401  (side effect: registration)

    try:
        mod = module_info_for(source, relpath)
    except SyntaxError as e:
        return [Finding(rule="parse-error", file=relpath,
                        line=e.lineno or 1,
                        message=f"file does not parse: {e.msg}",
                        line_text="")]
    selected = (RULES.values() if rules is None
                else [RULES[r] for r in rules])
    out: List[Finding] = []
    for rule in selected:
        for f in rule.run(mod):
            if not mod.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out


def analyze_paths(paths: Sequence[Path],
                  rules: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            source = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="parse-error", file=_rel(path), line=1,
                message=f"unreadable file: {e}", line_text=""))
            continue
        findings.extend(analyze_source(source, _rel(path), rules=rules))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BaselineEntry:
    rule: str
    file: str
    line_text: str
    count: int
    why: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.line_text)


def load_baseline(path: Path) -> List[BaselineEntry]:
    path = Path(path)
    if not path.exists():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    entries = []
    for e in data.get("entries", []):
        entries.append(BaselineEntry(
            rule=e["rule"], file=e["file"], line_text=e["line_text"],
            count=int(e.get("count", 1)), why=e.get("why", "")))
    return entries


def apply_baseline(findings: Sequence[Finding],
                   baseline: Sequence[BaselineEntry],
                   ) -> Tuple[List[Finding], int, List[BaselineEntry]]:
    """Returns (unbaselined findings, #matched, stale entries).

    A stale entry matched fewer findings than its count — the debt it
    grandfathers no longer exists and the entry should be pruned.
    """
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        budget[e.key()] = budget.get(e.key(), 0) + e.count
    remaining = dict(budget)
    unmatched: List[Finding] = []
    matched = 0
    for f in findings:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            matched += 1
        else:
            unmatched.append(f)
    stale: List[BaselineEntry] = []
    for e in baseline:
        if remaining.get(e.key(), 0) > 0:
            stale.append(e)
            remaining[e.key()] = 0
    return unmatched, matched, stale
