"""Value-level dataflow: an AST abstract interpreter over two lattices.

The project rules built in PR 4 track *flags* (import-graph taint) and
*dtype proofs* (the pallas operand prover walks definitions backwards).
This module tracks **values** forward, per binding, on two small lattices:

* **dtype** — ``uint32 | other | unknown``, threaded through pytree
  packing/unpacking, tuple/dict construction, dataclass fields and the
  ``stack/concat/reshape/where`` dtype-preserving chains. The interesting
  event is **laundering**: a value that was provably uint32 losing the
  dtype (``astype(float32)``, float-constant arithmetic, true division)
  and then reaching a Mosaic/jit kernel or a serialization point — the
  exact class of bug that corrupts Montgomery carries silently.

* **secrecy** — ``secret | public``, seeded at *definition sites* (ElGamal
  ``keygen()``, ``secrets.randbelow()`` nonce draws, DP cleartext loads)
  rather than by identifier regex, and reported when a secret value
  reaches ``print``/``log.*``/TOML output/exception messages/``send``.

Functions get interprocedural :class:`Summary` objects (param -> return
lattice transfer plus "param reaches sink" records) computed lazily along
the PR 4 callgraph and memoized; the whole engine result is cached on a
content-hash fingerprint of the project (:func:`dataflow_for`), so the two
consuming rules share one run and re-runs in one process are free.

Suppression composes with the usual anchors: every chain hop of a finding
is an anchor, so ``# drynx: noqa[rule]`` works at the source *or* the
sink. Additionally ``# drynx: declassify[secret]`` (or ``[dtype]``) on an
assignment line forces the assigned value public / un-laundered — the
documented way to mark protocol outputs (Schnorr ``s``, ciphertexts) that
are public by construction.

Still pure ``ast``, still no jax import.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple, Union

from .core import _dotted
from .graph import FuncNode
from .project import ProjectInfo, chain_hop

# -- lattices ---------------------------------------------------------------

DT_UINT32 = "uint32"
DT_OTHER = "other"
DT_UNKNOWN = "unknown"

SEC_PUBLIC = "public"
SEC_SECRET = "secret"

_MAX_CHAIN = 8

_DECLASSIFY_RE = re.compile(r"#\s*drynx:\s*declassify\[([a-z,\s]+)\]")

# Sink tables (deliberately local copies: rules.py imports this module).
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log", "lvl", "lvl1", "lvl2", "lvl3"}
_LOGGER_NAMES = {"log", "logging", "logger", "_logger", "LOG", "LOGGER"}
_SEND_LEAVES = {"send_msg", "send", "sendall", "sendto", "broadcast"}
_DUMP_LEAVES = {"dump", "dumps"}

# Secrecy seeds: callee leaf name -> what the value is.
_NONCE_LEAVES = {"randbelow"}
_CLEARTEXT_LEAVES = {"load_csv", "loadtxt", "genfromtxt"}

# Container mutation methods: ``xs.append(secret)`` taints the binding of
# ``xs`` itself (container-sensitive secrecy — the dual of the existing
# ``d[k] = v`` subscript-assign rule), so a later ``log.info(xs)`` still
# sees the taint even though no assignment statement touched ``xs``.
_MUTATOR_LEAVES = {"append", "appendleft", "add", "insert", "extend",
                   "update", "setdefault"}

# Introspection builtins whose result is public whatever goes in (a
# length/type/id does not reveal the value), and digest methods — hashing
# IS the redaction the secret-flow findings ask for, so it declassifies.
_PUBLIC_FUNCS = {"len", "bool", "type", "id", "isinstance", "issubclass",
                 "hash", "callable"}
_DIGEST_LEAVES = {"hexdigest", "digest"}

_UINT32_DTYPES = {"jnp.uint32", "np.uint32", "numpy.uint32",
                  "jax.numpy.uint32"}
_ARRAY_ROOTS = {"jnp", "np", "numpy", "jax"}
_CTOR_DTYPE_POS = {"array": 1, "asarray": 1, "zeros": 1, "ones": 1,
                   "empty": 1, "full": 2, "arange": 3}
_FRESH_CTORS = {"zeros", "ones", "empty", "full", "arange"}
_PRESERVING_FUNCS = {"transpose", "reshape", "concatenate", "stack",
                     "broadcast_to", "tile", "repeat", "flip", "roll",
                     "moveaxis", "swapaxes", "expand_dims", "squeeze",
                     "ravel", "pad", "zeros_like", "ones_like",
                     "empty_like", "full_like", "flipud", "rot90"}
_PRESERVING_METHODS = {"reshape", "transpose", "ravel", "squeeze",
                       "swapaxes", "copy", "flatten", "block_until_ready"}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}


class Secret:
    """Annotation-only marker: ``sk: Secret[int]`` (or the string form
    ``"Secret[int]"``) seeds the secrecy lattice at the annotation site —
    the way to declare a secret that the definition-site seeds (keygen /
    randbelow / cleartext loads) cannot see, e.g. a key passed in from a
    caller outside the analyzed tree. Erased at runtime: subscripting
    returns the class itself, so the annotation costs nothing."""

    def __class_getitem__(cls, _item):
        return cls


def _is_secret_ann(ann: Optional[ast.AST]) -> bool:
    """True for ``Secret[...]`` / ``x.Secret[...]`` / bare ``Secret`` and
    their string-literal forms."""
    if ann is None:
        return False
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        head = ann.value.split("[", 1)[0]
        return head.split(".")[-1].strip() == "Secret"
    node = ann.value if isinstance(ann, ast.Subscript) else ann
    return (_dotted(node) or "").split(".")[-1] == "Secret"


def _is_uint32_dtype(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) and expr.value == "uint32":
        return True
    return _dotted(expr) in _UINT32_DTYPES


def _dtype_scope(relpath: str) -> bool:
    """ciphertext-dtype-launder fires in crypto/parallel (+ the fixture)."""
    marked = f"/{relpath}"
    return ("/crypto/" in marked or "/parallel/" in marked
            or "lintpkg" in relpath)


def _secret_scope(relpath: str) -> bool:
    """secret-flow-to-sink fires package-wide (+ the fixture)."""
    return (relpath.startswith("drynx_tpu/") or "/drynx_tpu/" in relpath
            or "lintpkg" in relpath)


def _cap(chain: Tuple[str, ...]) -> Tuple[str, ...]:
    return chain if len(chain) <= _MAX_CHAIN else chain[:_MAX_CHAIN]


# -- abstract values --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AV:
    """One lattice point. ``*_src`` fields are *symbolic*: they name the
    parameter indices of the function being summarized whose concrete
    values (at a call site) decide the concrete lattice point — this is
    what makes summaries transfer functions instead of constants."""
    dtype: str = DT_UNKNOWN
    secrecy: str = SEC_PUBLIC
    laundered: bool = False
    dtype_src: Optional[int] = None
    secret_src: FrozenSet[int] = frozenset()
    launders_src: FrozenSet[int] = frozenset()
    dtype_chain: Tuple[str, ...] = ()
    secret_chain: Tuple[str, ...] = ()


TOP = AV()


@dataclasses.dataclass
class TupleVal:
    elts: Tuple["ValT", ...]


@dataclasses.dataclass
class ObjVal:
    cls: Tuple[str, str]                # (module dotted, ClassName)
    fields: Dict[str, "ValT"]


ValT = Union[AV, TupleVal, ObjVal]


def join_av(a: AV, b: AV) -> AV:
    if a is b:
        return a
    if a.laundered and a.dtype_chain:
        dchain = a.dtype_chain
    elif b.laundered and b.dtype_chain:
        dchain = b.dtype_chain
    else:
        dchain = a.dtype_chain or b.dtype_chain
    if a.secrecy == SEC_SECRET and a.secret_chain:
        schain = a.secret_chain
    elif b.secrecy == SEC_SECRET and b.secret_chain:
        schain = b.secret_chain
    else:
        schain = a.secret_chain or b.secret_chain
    return AV(
        dtype=a.dtype if a.dtype == b.dtype else DT_UNKNOWN,
        secrecy=(SEC_SECRET if SEC_SECRET in (a.secrecy, b.secrecy)
                 else SEC_PUBLIC),
        laundered=a.laundered or b.laundered,
        dtype_src=a.dtype_src if a.dtype_src == b.dtype_src else None,
        secret_src=a.secret_src | b.secret_src,
        launders_src=a.launders_src | b.launders_src,
        dtype_chain=dchain, secret_chain=schain)


def collapse(v: ValT) -> AV:
    """Join all leaves of a structured value into one AV."""
    if isinstance(v, AV):
        return v
    if isinstance(v, TupleVal):
        if not v.elts:
            return TOP
        out = collapse(v.elts[0])
        for e in v.elts[1:]:
            out = join_av(out, collapse(e))
        return out
    vals = list(v.fields.values())
    if not vals:
        return TOP
    out = collapse(vals[0])
    for e in vals[1:]:
        out = join_av(out, collapse(e))
    return out


def shallow(v: ValT) -> AV:
    """Like collapse, but an object's fields do NOT taint the object —
    used for unknown-call passthrough so a NodeIdentity flowing through
    helper calls doesn't turn everything it touches secret."""
    if isinstance(v, AV):
        return v
    if isinstance(v, TupleVal):
        out = TOP
        for e in v.elts:
            out = join_av(out, shallow(e))
        return out
    return TOP


def join_val(a: ValT, b: ValT) -> ValT:
    if isinstance(a, TupleVal) and isinstance(b, TupleVal) \
            and len(a.elts) == len(b.elts):
        return TupleVal(tuple(join_val(x, y)
                              for x, y in zip(a.elts, b.elts)))
    if isinstance(a, ObjVal) and isinstance(b, ObjVal) and a.cls == b.cls:
        merged: Dict[str, ValT] = dict(a.fields)
        for k, v in b.fields.items():
            merged[k] = join_val(merged[k], v) if k in merged else v
        return ObjVal(a.cls, merged)
    return join_av(collapse(a), collapse(b))


def _value_json(v: ValT) -> Dict[str, object]:
    """Stable JSON for the golden-summary tests."""
    if isinstance(v, TupleVal):
        return {"tuple": [_value_json(e) for e in v.elts]}
    if isinstance(v, ObjVal):
        return {"object": f"{v.cls[0]}:{v.cls[1]}",
                "fields": {k: _value_json(x)
                           for k, x in sorted(v.fields.items())}}
    return {"dtype": v.dtype, "secrecy": v.secrecy,
            "laundered": v.laundered, "dtype_src": v.dtype_src,
            "secret_src": sorted(v.secret_src),
            "launders_src": sorted(v.launders_src)}


# -- summaries --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ParamSink:
    """"Parameter ``param`` reaches a sink inside this function": fired at
    call sites that pass a concretely-secret (kind=secret), concretely
    laundered (dtype-pass) or concretely-uint32 (dtype-launder) value."""
    param: int
    kind: str                     # "secret" | "dtype-pass" | "dtype-launder"
    chain: Tuple[str, ...]        # hops from inside the callee to the sink
    message: str


@dataclasses.dataclass
class Summary:
    fid: str
    params: Tuple[str, ...]
    ret: ValT
    sinks: Tuple[ParamSink, ...]

    def to_json(self) -> Dict[str, object]:
        return {"params": list(self.params), "ret": _value_json(self.ret),
                "sinks": [{"param": s.param, "kind": s.kind,
                           "message": s.message} for s in self.sinks]}


_EMPTY = Summary("", (), TOP, ())


@dataclasses.dataclass(frozen=True)
class RawFinding:
    file: str
    line: int
    message: str
    chain: Tuple[str, ...]
    anchors: Tuple[Tuple[str, int], ...]


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    module: str
    name: str
    fields: Tuple[str, ...]       # ordered AnnAssign names (dataclass ctor)
    is_dataclass: bool


# -- the engine -------------------------------------------------------------

class Dataflow:
    """Whole-program value dataflow over a :class:`ProjectInfo`.

    Two passes: pass 1 populates per-class field states (``self.x = ...``
    assignments seen in any method) and warms summaries; pass 2 recomputes
    with stable class fields and records the raw findings. ``secret_raw``
    and ``dtype_raw`` are consumed by the two project rules."""

    def __init__(self, project: ProjectInfo):
        self.project = project
        self.classes: Dict[Tuple[str, str], ClassSpec] = {}
        self.ctor_index: Dict[str, List[Tuple[str, str]]] = {}
        self.class_fields: Dict[Tuple[str, str], Dict[str, AV]] = {}
        self.summaries: Dict[str, Summary] = {}
        self._computing: Set[str] = set()
        self.recording = False
        self.secret_raw: List[RawFinding] = []
        self.dtype_raw: List[RawFinding] = []
        self._seen_sites: Set[Tuple[str, str, int]] = set()
        self.runs = 0                     # cache-hit observability
        self._collect_classes()

    # -- classes ----------------------------------------------------------

    def _collect_classes(self) -> None:
        for dotted, mg in self.project.graphs.items():
            for node in ast.walk(mg.info.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                fields = tuple(
                    s.target.id for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name))
                is_dc = any(
                    (_dotted(d.func if isinstance(d, ast.Call) else d)
                     or "").split(".")[-1] == "dataclass"
                    for d in node.decorator_list)
                is_dc = is_dc or any(
                    (_dotted(b) or "").split(".")[-1] == "NamedTuple"
                    for b in node.bases)
                key = (dotted, node.name)
                self.classes[key] = ClassSpec(dotted, node.name, fields,
                                              is_dc)
                self.ctor_index.setdefault(node.name, []).append(key)

    def class_for_ctor(self, module: str, name: str
                       ) -> Optional[Tuple[str, str]]:
        """(module, ClassName) a constructor call resolves to: same-module
        class first, then a unique bare-name match project-wide."""
        if (module, name) in self.classes:
            return (module, name)
        cands = self.ctor_index.get(name, [])
        return cands[0] if len(cands) == 1 else None

    # -- driver -----------------------------------------------------------

    def run(self, focus: Optional[Set[str]] = None) -> None:
        """Interpret every function (or, focused, only those defined in
        the ``focus`` relpaths — callees elsewhere are still pulled in
        lazily through their summaries) and record raw findings."""
        self.runs += 1
        fids = sorted(self.project.calls.functions)
        if focus is not None:
            fids = [fid for fid in fids
                    if self._relpath_of(fid) in focus]
        for final in (False, True):
            self.recording = final
            self.summaries.clear()
            self._seen_sites.clear()
            self.secret_raw, self.dtype_raw = [], []
            for fid in fids:
                self.summary_for(fid)
        self.secret_raw.sort(key=lambda r: (r.file, r.line))
        self.dtype_raw.sort(key=lambda r: (r.file, r.line))

    def _relpath_of(self, fid: str) -> str:
        mg = self.project.graphs.get(fid.split(":", 1)[0])
        return mg.info.relpath if mg is not None else ""

    def summary_for(self, fid: str) -> Summary:
        got = self.summaries.get(fid)
        if got is not None:
            return got
        fn = self.project.calls.functions.get(fid)
        if fn is None or fid in self._computing:
            return _EMPTY                 # unknown / recursion cut
        self._computing.add(fid)
        try:
            summ = _Interp(self, fn).run()
        except RecursionError:            # pathological nesting: give up
            summ = Summary(fid, (), TOP, ())
        finally:
            self._computing.discard(fid)
        self.summaries[fid] = summ
        return summ

    def record(self, kind: str, file: str, line: int, message: str,
               chain: Tuple[str, ...]) -> None:
        if not self.recording:
            return
        in_scope = _secret_scope(file) if kind == "secret" \
            else _dtype_scope(file)
        if not in_scope:
            return
        key = (kind, file, line)
        if key in self._seen_sites:
            return
        self._seen_sites.add(key)
        anchors: List[Tuple[str, int]] = []
        for hop in chain:
            parts = hop.split(":", 2)
            if len(parts) == 3 and parts[1].isdigit():
                anchors.append((parts[0], int(parts[1])))
        raw = RawFinding(file, line, message, _cap(chain),
                         tuple(dict.fromkeys(anchors)))
        (self.secret_raw if kind == "secret" else self.dtype_raw).append(raw)

    def summaries_json(self, module: str) -> Dict[str, object]:
        """Golden-test surface: summaries of one module's functions."""
        return {fid: s.to_json() for fid, s in sorted(self.summaries.items())
                if fid.split(":", 1)[0] == module}


# -- the interpreter --------------------------------------------------------

class _Interp:
    def __init__(self, df: Dataflow, fn: FuncNode):
        self.df = df
        self.fn = fn
        self.mg = df.project.graphs[fn.module]
        self.info = self.mg.info
        self.rel = self.info.relpath
        self.sites = {id(s.node): s.callee
                      for s in df.project.calls.callees(fn.fid)}
        self.env: Dict[str, ValT] = {}
        self.params: List[str] = []
        a = fn.node.args
        idx = 0
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            if arg.arg in ("self", "cls"):
                key = self._self_class()
                self.env[arg.arg] = ObjVal(key, {}) if key else TOP
                continue
            av = AV(dtype_src=idx, secret_src=frozenset((idx,)))
            if _is_secret_ann(arg.annotation):
                hop = chain_hop(self.rel, arg.lineno,
                                f"Secret[...] annotated parameter "
                                f"'{arg.arg}'")
                av = dataclasses.replace(av, secrecy=SEC_SECRET,
                                         secret_chain=(hop,))
            self.env[arg.arg] = av
            self.params.append(arg.arg)
            idx += 1
        if a.vararg:
            self.env[a.vararg.arg] = TOP
        if a.kwarg:
            self.env[a.kwarg.arg] = TOP
        self.returns: List[ValT] = []
        self.sinks: List[ParamSink] = []

    def _self_class(self) -> Optional[Tuple[str, str]]:
        parts = self.fn.qual.split(".")
        if len(parts) >= 2 and (self.fn.module, parts[-2]) in self.df.classes:
            return (self.fn.module, parts[-2])
        return None

    def run(self) -> Summary:
        for stmt in self.fn.node.body:
            self.exec_stmt(stmt)
        if not self.returns:
            ret: ValT = TOP
        else:
            ret = self.returns[0]
            for r in self.returns[1:]:
                ret = join_val(ret, r)
        return Summary(self.fn.fid, tuple(self.params), ret,
                       tuple(dict.fromkeys(self.sinks)))

    # -- statements -------------------------------------------------------

    def exec_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            v = self._declassify(self.eval(stmt.value), stmt.lineno)
            for t in stmt.targets:
                self.assign(t, v)
        elif isinstance(stmt, ast.AnnAssign):
            secret_ann = _is_secret_ann(stmt.annotation)
            if stmt.value is not None:
                v = self._declassify(self.eval(stmt.value), stmt.lineno)
            elif secret_ann:
                # declaration-only form (``sk: Secret[int]``): bind the
                # seed so later reads of the name carry it
                v = TOP
            else:
                return
            if secret_ann:
                v = self._mark_secret(v, stmt.lineno,
                                      "Secret[...] annotated binding")
            self.assign(stmt.target, v)
        elif isinstance(stmt, ast.AugAssign):
            cur = TOP
            if isinstance(stmt.target, ast.Name):
                cur = self.env.get(stmt.target.id, TOP)
            v = self._binop_result(collapse(cur),
                                   collapse(self.eval(stmt.value)),
                                   stmt.op, stmt.value, stmt.lineno)
            self.assign(stmt.target, self._declassify(v, stmt.lineno))
        elif isinstance(stmt, ast.Return):
            v = self.eval(stmt.value) if stmt.value is not None else TOP
            self.returns.append(self._declassify(v, stmt.lineno))
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
        elif isinstance(stmt, ast.If):
            self.eval(stmt.test)
            saved = dict(self.env)
            for s in stmt.body:
                self.exec_stmt(s)
            after_body = self.env
            self.env = dict(saved)
            for s in stmt.orelse:
                self.exec_stmt(s)
            merged = dict(self.env)
            for k, v in after_body.items():
                merged[k] = join_val(merged[k], v) if k in merged else v
            self.env = merged
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval(stmt.iter)
            self.assign(stmt.target, self._element_of(it))
            for s in stmt.body:
                self.exec_stmt(s)
            for s in stmt.orelse:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.While):
            self.eval(stmt.test)
            for s in stmt.body:
                self.exec_stmt(s)
            for s in stmt.orelse:
                self.exec_stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                v = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, v)
            for s in stmt.body:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body:
                self.exec_stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self.exec_stmt(s)
            for s in stmt.orelse:
                self.exec_stmt(s)
            for s in stmt.finalbody:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.Raise):
            self._exec_raise(stmt)
        elif isinstance(stmt, ast.Assert):
            self.eval(stmt.test)
            if stmt.msg is not None:
                self.eval(stmt.msg)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
        # FunctionDef/ClassDef/Import/Pass/etc: no value flow to model

    def _exec_raise(self, stmt: ast.Raise) -> None:
        if stmt.exc is None:
            return
        if not isinstance(stmt.exc, ast.Call):
            self.eval(stmt.exc)
            return
        exc_name = (_dotted(stmt.exc.func) or "Exception").split(".")[-1]
        for arg in list(stmt.exc.args) + [k.value for k in stmt.exc.keywords]:
            c = collapse(self.eval(arg))
            hop = chain_hop(self.rel, stmt.lineno,
                            f"raise {exc_name}(...) message")
            if c.secrecy == SEC_SECRET:
                self.df.record(
                    "secret", self.rel, stmt.lineno,
                    "secret value reaches an exception message — tracebacks "
                    "cross trust boundaries; redact or hash it",
                    c.secret_chain + (hop,))
            for p in c.secret_src:
                self.sinks.append(ParamSink(
                    p, "secret", c.secret_chain + (hop,),
                    "exception message"))

    def assign(self, target: ast.AST, v: ValT) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = v
        elif isinstance(target, ast.Starred):
            self.assign(target.value, collapse(v))
        elif isinstance(target, (ast.Tuple, ast.List)):
            plain = [t for t in target.elts
                     if not isinstance(t, ast.Starred)]
            if isinstance(v, TupleVal) and len(plain) == len(target.elts) \
                    and len(v.elts) == len(target.elts):
                for t, e in zip(target.elts, v.elts):
                    self.assign(t, e)
            else:
                c = self._element_of(v)
                for t in target.elts:
                    self.assign(t, c)
        elif isinstance(target, ast.Attribute):
            base = target.value
            if isinstance(base, ast.Name):
                obj = self.env.get(base.id)
                if isinstance(obj, ObjVal):
                    cv = v if not isinstance(v, AV) else v
                    obj.fields[target.attr] = (
                        join_val(obj.fields[target.attr], cv)
                        if target.attr in obj.fields else cv)
                    if base.id in ("self", "cls"):
                        conc = self._concrete(collapse(v))
                        cf = self.df.class_fields.setdefault(obj.cls, {})
                        cf[target.attr] = (join_av(cf[target.attr], conc)
                                           if target.attr in cf else conc)
        elif isinstance(target, ast.Subscript):
            if isinstance(target.value, ast.Name):
                name = target.value.id
                old = self.env.get(name, TOP)
                self.env[name] = join_av(collapse(old), collapse(v))

    @staticmethod
    def _concrete(av: AV) -> AV:
        """Strip symbolic param indices (they are meaningless outside the
        function being summarized) before persisting into class state."""
        return dataclasses.replace(av, dtype_src=None,
                                   secret_src=frozenset(),
                                   launders_src=frozenset())

    @staticmethod
    def _fold(vals: List[AV]) -> AV:
        """Join a list of AVs without a TOP seed (TOP's unknown dtype is
        absorbing, so folding from it would drop every dtype fact)."""
        if not vals:
            return TOP
        out = vals[0]
        for v in vals[1:]:
            out = join_av(out, v)
        return out

    @staticmethod
    def _element_of(v: ValT) -> ValT:
        """The value of one element when iterating/unpacking ``v``:
        iterating a uint32 array yields uint32 rows; iterating a secret
        list yields secret elements."""
        if isinstance(v, TupleVal):
            return collapse(v)
        if isinstance(v, ObjVal):
            return TOP
        return v

    def _mark_secret(self, v: ValT, lineno: int, what: str) -> ValT:
        """Structurally force ``v`` secret (annotation seeds). Leaves that
        are already secret keep their original, more precise chain."""
        hop = chain_hop(self.rel, lineno, what)

        def mark(x: ValT) -> ValT:
            if isinstance(x, TupleVal):
                return TupleVal(tuple(mark(e) for e in x.elts))
            if isinstance(x, ObjVal):
                return ObjVal(x.cls, {k: mark(e)
                                      for k, e in x.fields.items()})
            if x.secrecy == SEC_SECRET:
                return x
            return dataclasses.replace(
                x, secrecy=SEC_SECRET,
                secret_chain=_cap(x.secret_chain + (hop,)))

        return mark(v)

    def _declassify(self, v: ValT, lineno: int) -> ValT:
        if not (1 <= lineno <= len(self.info.lines)):
            return v
        m = _DECLASSIFY_RE.search(self.info.lines[lineno - 1])
        if not m:
            return v
        kinds = {k.strip() for k in m.group(1).split(",")}

        def scrub(x: ValT) -> ValT:
            if isinstance(x, TupleVal):
                return TupleVal(tuple(scrub(e) for e in x.elts))
            if isinstance(x, ObjVal):
                return ObjVal(x.cls, {k: scrub(e)
                                      for k, e in x.fields.items()})
            out = x
            if "secret" in kinds:
                out = dataclasses.replace(out, secrecy=SEC_PUBLIC,
                                          secret_src=frozenset(),
                                          secret_chain=())
            if "dtype" in kinds:
                out = dataclasses.replace(out, laundered=False,
                                          launders_src=frozenset(),
                                          dtype_chain=())
            return out

        return scrub(v)

    # -- expressions ------------------------------------------------------

    def eval(self, node: Optional[ast.AST]) -> ValT:
        if node is None:
            return TOP
        if isinstance(node, ast.Constant):
            if isinstance(node.value, float):
                return AV(dtype=DT_OTHER)
            return TOP
        if isinstance(node, ast.Name):
            return self.env.get(node.id, TOP)
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupleVal(tuple(
                collapse(self.eval(e.value)) if isinstance(e, ast.Starred)
                else self.eval(e) for e in node.elts))
        if isinstance(node, ast.Dict):
            # fold from the first value, not TOP: TOP's unknown dtype is
            # absorbing and would erase a uint32 pin carried by the values
            vals = [collapse(self.eval(v)) for v in node.values]
            return self._fold(vals)
        if isinstance(node, ast.Set):
            vals = [collapse(self.eval(v)) for v in node.elts]
            return self._fold(vals)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for comp in node.generators:
                self.assign(comp.target,
                            self._element_of(self.eval(comp.iter)))
                for cond in comp.ifs:
                    self.eval(cond)
            return collapse(self.eval(node.elt))
        if isinstance(node, ast.DictComp):
            for comp in node.generators:
                self.assign(comp.target,
                            self._element_of(self.eval(comp.iter)))
                for cond in comp.ifs:
                    self.eval(cond)
            self.eval(node.key)
            return collapse(self.eval(node.value))
        if isinstance(node, ast.JoinedStr):
            # an f-string is a str whatever it embeds; secrecy still taints
            emb = self._fold([collapse(self.eval(v)) for v in node.values])
            return AV(dtype=DT_OTHER, secrecy=emb.secrecy,
                      secret_src=emb.secret_src,
                      secret_chain=emb.secret_chain)
        if isinstance(node, ast.FormattedValue):
            return collapse(self.eval(node.value))
        if isinstance(node, ast.BoolOp):
            return self._fold([collapse(self.eval(v))
                               for v in node.values])
        if isinstance(node, ast.Compare):
            self.eval(node.left)
            for c in node.comparators:
                self.eval(c)
            return TOP
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return join_val(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, ast.BinOp):
            return self._binop_result(
                collapse(self.eval(node.left)),
                collapse(self.eval(node.right)),
                node.op, node.right, node.lineno, left_node=node.left)
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            self.eval(node.slice)
            if isinstance(base, TupleVal) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, int) \
                    and -len(base.elts) <= node.slice.value < len(base.elts):
                return base.elts[node.slice.value]
            if isinstance(base, AV):
                return base           # indexing preserves dtype/secrecy
            return collapse(base)
        if isinstance(node, ast.Attribute):
            return self._attr(node)
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self.eval(node.value)
        if isinstance(node, ast.Yield):
            if node.value is not None:
                self.returns.append(self.eval(node.value))
            return TOP
        if isinstance(node, ast.NamedExpr):
            v = self.eval(node.value)
            self.assign(node.target, v)
            return v
        if isinstance(node, ast.Lambda):
            return TOP
        return TOP

    def _attr(self, node: ast.Attribute) -> ValT:
        base = self.eval(node.value)
        if node.attr in _SHAPE_ATTRS:
            return AV(dtype=DT_OTHER)
        if isinstance(base, ObjVal):
            if node.attr in base.fields:
                return base.fields[node.attr]
            cf = self.df.class_fields.get(base.cls, {})
            return cf.get(node.attr, TOP)
        b = collapse(base)
        if node.attr == "T":
            return b                  # transpose preserves everything
        # attribute of a secret object is secret; dtype is unknown
        return AV(secrecy=b.secrecy, secret_src=b.secret_src,
                  secret_chain=b.secret_chain)

    def _binop_result(self, lv: AV, rv: AV, op: ast.AST,
                      right_node: ast.AST, lineno: int,
                      left_node: Optional[ast.AST] = None) -> AV:
        def is_int_const(n: Optional[ast.AST]) -> bool:
            return (isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                    and not isinstance(n.value, bool))

        def is_float_const(n: Optional[ast.AST]) -> bool:
            return (isinstance(n, ast.Constant)
                    and isinstance(n.value, float))

        joined = join_av(lv, rv)
        # pick the "array side" dtype view: an int constant operand never
        # promotes a uint32 array under x64-off
        sides = []
        if not is_int_const(left_node) and not is_float_const(left_node):
            sides.append(lv)
        if not is_int_const(right_node) and not is_float_const(right_node):
            sides.append(rv)
        if not sides:
            sides = [lv, rv]
        arr = sides[0]
        for s in sides[1:]:
            arr = join_av(arr, s)
        launders = (isinstance(op, ast.Div)
                    or is_float_const(right_node)
                    or is_float_const(left_node))
        if launders:
            what = ("true division launders uint32"
                    if isinstance(op, ast.Div)
                    else "float arithmetic launders uint32")
            arr = self._launder(arr, lineno, what)
        return dataclasses.replace(
            arr, secrecy=joined.secrecy, secret_src=joined.secret_src,
            secret_chain=joined.secret_chain)

    def _launder(self, v: AV, lineno: int, what: str) -> AV:
        hop = chain_hop(self.rel, lineno, what)
        if v.dtype == DT_UINT32:
            return dataclasses.replace(
                v, dtype=DT_OTHER, laundered=True, dtype_src=None,
                launders_src=frozenset(),
                dtype_chain=_cap(v.dtype_chain + (hop,)))
        extra = (frozenset((v.dtype_src,)) if v.dtype_src is not None
                 else frozenset())
        ls = v.launders_src | extra
        chain = (_cap(v.dtype_chain + (hop,))
                 if (v.laundered or ls) else v.dtype_chain)
        return dataclasses.replace(v, dtype=DT_OTHER, dtype_src=None,
                                   launders_src=ls, dtype_chain=chain)

    def _pin_uint32(self, v: AV, lineno: int, what: str) -> AV:
        hop = chain_hop(self.rel, lineno, what)
        return AV(dtype=DT_UINT32, secrecy=v.secrecy, laundered=False,
                  dtype_src=None, secret_src=v.secret_src,
                  launders_src=frozenset(), dtype_chain=(hop,),
                  secret_chain=v.secret_chain)

    # -- calls ------------------------------------------------------------

    def eval_call(self, call: ast.Call) -> ValT:
        if isinstance(call.func, ast.Attribute):
            leaf = call.func.attr
        elif isinstance(call.func, ast.Name):
            leaf = call.func.id
        else:
            leaf = ""
        d = _dotted(call.func) or ""
        recv: Optional[ValT] = None
        if isinstance(call.func, ast.Attribute):
            recv = self.eval(call.func.value)
        argvals: List[ValT] = [
            self.eval(a.value) if isinstance(a, ast.Starred)
            else self.eval(a) for a in call.args]
        kwvals: Dict[Optional[str], ValT] = {
            kw.arg: self.eval(kw.value) for kw in call.keywords}

        self._check_secret_sinks(call, d, leaf, recv, argvals, kwvals)
        self._check_dtype_sinks(call, leaf, recv, argvals)

        if (leaf in _MUTATOR_LEAVES and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in self.env):
            # container-sensitive secrecy: xs.append(secret) re-binds xs
            # with the argument's taint joined in (mirrors the
            # subscript-assign rule in ``assign``)
            folded = self._fold([collapse(v)
                                 for v in argvals + list(kwvals.values())])
            if folded.secrecy == SEC_SECRET or folded.secret_src:
                name = call.func.value.id
                hop = chain_hop(self.rel, call.lineno,
                                f".{leaf}() into container '{name}'")
                self.env[name] = join_av(
                    collapse(self.env[name]),
                    AV(secrecy=folded.secrecy,
                       secret_src=folded.secret_src,
                       secret_chain=_cap(folded.secret_chain + (hop,))))

        seeded = self._seed(call, d, leaf)
        if seeded is not None:
            return seeded
        if isinstance(call.func, ast.Name) and leaf in _PUBLIC_FUNCS:
            return AV(dtype=DT_OTHER)
        if isinstance(call.func, ast.Attribute) and leaf in _DIGEST_LEAVES:
            return AV(dtype=DT_OTHER)
        transferred = self._dtype_transfer(call, d, leaf, recv, argvals,
                                           kwvals)
        if transferred is not None:
            return transferred
        tree = self._pytree(call, d, leaf, argvals)
        if tree is not None:
            return tree
        ctor = self._ctor(call, leaf, argvals, kwvals)
        if ctor is not None:
            return ctor

        fid = self.sites.get(id(call))
        if fid is not None:
            return self._apply_summary(fid, call, argvals, kwvals)

        # unknown call: taint-through (shallow — objects don't leak their
        # fields through helpers), dtype gives up, laundering is dropped
        out = TOP
        for v in argvals + list(kwvals.values()):
            out = join_av(out, shallow(v))
        if recv is not None:
            out = join_av(out, shallow(recv))
        return AV(secrecy=out.secrecy, secret_src=out.secret_src,
                  secret_chain=out.secret_chain)

    # -- sinks ------------------------------------------------------------

    def _secret_sink_name(self, call: ast.Call, d: str,
                          leaf: str) -> Optional[str]:
        if isinstance(call.func, ast.Name) and leaf == "print":
            return "print()"
        if isinstance(call.func, ast.Attribute):
            if leaf in _LOG_METHODS:
                root = call.func.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in _LOGGER_NAMES:
                    return f"{d or leaf}() log output"
            if leaf in _SEND_LEAVES:
                return f".{leaf}() transport send"
        if leaf in _DUMP_LEAVES:
            return f"{d or leaf}() serialized output"
        return None

    def _check_secret_sinks(self, call: ast.Call, d: str, leaf: str,
                            recv: Optional[ValT], argvals: List[ValT],
                            kwvals: Dict[Optional[str], ValT]) -> None:
        sink = self._secret_sink_name(call, d, leaf)
        vals = list(argvals) + list(kwvals.values())
        if leaf in ("tobytes", "to_bytes") and recv is not None:
            sink = f".{leaf}() serialization"
            vals = vals + [recv]
        if sink is None:
            return
        hop = chain_hop(self.rel, call.lineno, sink)
        for v in vals:
            c = collapse(v)
            if c.secrecy == SEC_SECRET:
                origin = (c.secret_chain[0].rsplit(":", 1)[-1]
                          if c.secret_chain else "secret value")
                self.df.record(
                    "secret", self.rel, call.lineno,
                    f"secret value ({origin}) reaches {sink} — redact or "
                    f"hash it before it crosses a trust boundary",
                    c.secret_chain + (hop,))
            for p in c.secret_src:
                self.sinks.append(ParamSink(p, "secret",
                                            c.secret_chain + (hop,), sink))

    def _check_dtype_sinks(self, call: ast.Call, leaf: str,
                           recv: Optional[ValT],
                           argvals: List[ValT]) -> None:
        # pl.pallas_call(...)(operands...) — the outer call's args
        kernel: Optional[str] = None
        if isinstance(call.func, ast.Call):
            inner = (_dotted(call.func.func) or "").split(".")[-1]
            if inner == "pallas_call":
                kernel = "pallas_call kernel"
        fid = self.sites.get(id(call))
        if kernel is None and fid is not None \
                and fid in self.df.project.calls.traced_entries:
            kernel = f"jit kernel '{fid.rsplit(':', 1)[-1]}'"
        if kernel is not None:
            hop = chain_hop(self.rel, call.lineno, kernel)
            for i, v in enumerate(argvals):
                c = collapse(v)
                if c.laundered:
                    self.df.record(
                        "dtype", self.rel, call.lineno,
                        f"operand {i} of {kernel} was uint32 and lost the "
                        f"dtype on the way (laundered) — re-pin with "
                        f"jnp.asarray(..., jnp.uint32) at the boundary",
                        c.dtype_chain + (hop,))
                for p in c.launders_src:
                    self.sinks.append(ParamSink(
                        p, "dtype-launder", c.dtype_chain + (hop,), kernel))
                if c.dtype_src is not None:
                    self.sinks.append(ParamSink(
                        c.dtype_src, "dtype-pass", (hop,), kernel))
        if leaf in ("tobytes", "to_bytes") and recv is not None:
            c = collapse(recv)
            hop = chain_hop(self.rel, call.lineno, f".{leaf}() serialization")
            if c.laundered:
                self.df.record(
                    "dtype", self.rel, call.lineno,
                    "a laundered uint32 limb array is serialized — the "
                    "byte transcript silently changes; re-pin the dtype "
                    "first",
                    c.dtype_chain + (hop,))
            for p in c.launders_src:
                self.sinks.append(ParamSink(
                    p, "dtype-launder", c.dtype_chain + (hop,),
                    "serialization"))

    # -- seeds ------------------------------------------------------------

    def _seed(self, call: ast.Call, d: str, leaf: str) -> Optional[ValT]:
        if leaf == "keygen":
            hop = chain_hop(self.rel, call.lineno,
                            "keygen() ElGamal secret key")
            return TupleVal((AV(secrecy=SEC_SECRET, secret_chain=(hop,)),
                             TOP))
        if leaf in _NONCE_LEAVES:
            hop = chain_hop(self.rel, call.lineno,
                            f"{d or leaf}() nonce draw")
            return AV(secrecy=SEC_SECRET, secret_chain=(hop,))
        if leaf in _CLEARTEXT_LEAVES:
            hop = chain_hop(self.rel, call.lineno,
                            f"{d or leaf}() DP cleartext load")
            return AV(secrecy=SEC_SECRET, secret_chain=(hop,))
        return None

    # -- dtype transfer ---------------------------------------------------

    def _dtype_transfer(self, call: ast.Call, d: str, leaf: str,
                        recv: Optional[ValT], argvals: List[ValT],
                        kwvals: Dict[Optional[str], ValT]
                        ) -> Optional[ValT]:
        if leaf == "astype" and isinstance(call.func, ast.Attribute) \
                and recv is not None and call.args:
            r = collapse(recv)
            if _is_uint32_dtype(call.args[0]):
                return self._pin_uint32(r, call.lineno, ".astype(uint32)")
            dt = _dotted(call.args[0]) or "<dtype>"
            return self._launder(r, call.lineno, f".astype({dt})")
        root = d.split(".")[0] if "." in d else ""
        if root in _ARRAY_ROOTS and leaf in _CTOR_DTYPE_POS:
            dtype = next((kw.value for kw in call.keywords
                          if kw.arg == "dtype"), None)
            pos = _CTOR_DTYPE_POS[leaf]
            if dtype is None and len(call.args) > pos:
                dtype = call.args[pos]
            src = collapse(argvals[0]) if argvals else TOP
            if dtype is not None:
                if _is_uint32_dtype(dtype):
                    return self._pin_uint32(src, call.lineno,
                                            f"{d}(dtype=uint32)")
                if leaf in _FRESH_CTORS:
                    return AV(dtype=DT_OTHER)
                dt = _dotted(dtype) or "<dtype>"
                return self._launder(src, call.lineno, f"{d}(dtype={dt})")
            if leaf in ("array", "asarray") and argvals:
                return src            # no dtype: preserves the input's
            return AV()               # fresh ctor, inferred dtype
        if root in _ARRAY_ROOTS and leaf == "where" and len(argvals) == 3:
            return join_av(collapse(argvals[1]), collapse(argvals[2]))
        if root in _ARRAY_ROOTS and leaf in _PRESERVING_FUNCS and argvals:
            return collapse(argvals[0])
        if isinstance(call.func, ast.Attribute) and recv is not None \
                and leaf in _PRESERVING_METHODS and not call.args:
            return collapse(recv)
        return None

    # -- pytrees ----------------------------------------------------------

    def _pytree(self, call: ast.Call, d: str, leaf: str,
                argvals: List[ValT]) -> Optional[ValT]:
        treeish = "tree" in d or leaf in ("tree_flatten", "tree_unflatten",
                                          "tree_map")
        if not treeish:
            return None
        if leaf in ("flatten", "tree_flatten") and argvals:
            # (leaves, treedef): every leaf joins the packed value
            return TupleVal((collapse(argvals[0]), TOP))
        if leaf in ("unflatten", "tree_unflatten") and argvals:
            return collapse(argvals[-1])
        if leaf in ("map", "tree_map") and len(argvals) >= 2:
            return self._fold([collapse(v) for v in argvals[1:]])
        return None

    # -- constructors -----------------------------------------------------

    def _ctor(self, call: ast.Call, leaf: str, argvals: List[ValT],
              kwvals: Dict[Optional[str], ValT]) -> Optional[ValT]:
        if not leaf or not leaf[:1].isupper():
            return None
        key = self.df.class_for_ctor(self.fn.module, leaf)
        if key is None:
            return None
        spec = self.df.classes[key]
        fields: Dict[str, ValT] = {}
        for name, v in zip(spec.fields, argvals):
            fields[name] = v
        for kwname, v in kwvals.items():
            if kwname is not None:
                fields[kwname] = v
        return ObjVal(key, fields)

    # -- summary application ----------------------------------------------

    def _apply_summary(self, fid: str, call: ast.Call, argvals: List[ValT],
                       kwvals: Dict[Optional[str], ValT]) -> ValT:
        summ = self.df.summary_for(fid)
        callee = self.df.project.calls.functions.get(fid)
        qual = callee.qual if callee is not None else fid

        has_star = (any(isinstance(a, ast.Starred) for a in call.args)
                    or None in kwvals)

        def arg_for(j: int) -> ValT:
            if has_star or j >= len(summ.params):
                return TOP
            name = summ.params[j]
            if name in kwvals:
                return kwvals[name]           # type: ignore[index]
            if j < len(argvals):
                return argvals[j]
            return TOP

        # fire / propagate the callee's param sinks
        for ps in summ.sinks:
            av = collapse(arg_for(ps.param))
            pname = (summ.params[ps.param]
                     if ps.param < len(summ.params) else f"#{ps.param}")
            call_hop = chain_hop(self.rel, call.lineno,
                                 f"{qual}({pname})")
            if ps.kind == "secret":
                if av.secrecy == SEC_SECRET:
                    self.df.record(
                        "secret", self.rel, call.lineno,
                        f"secret value passed to '{qual}' reaches "
                        f"{ps.message} inside it",
                        av.secret_chain + (call_hop,) + ps.chain)
                for p in av.secret_src:
                    self.sinks.append(ParamSink(
                        p, "secret",
                        av.secret_chain + (call_hop,) + ps.chain,
                        ps.message))
            elif ps.kind == "dtype-pass":
                if av.laundered:
                    self.df.record(
                        "dtype", self.rel, call.lineno,
                        f"laundered uint32 value passed to '{qual}' "
                        f"reaches {ps.message} inside it — re-pin with "
                        f"jnp.asarray(..., jnp.uint32)",
                        av.dtype_chain + (call_hop,) + ps.chain)
                for p in av.launders_src:
                    self.sinks.append(ParamSink(
                        p, "dtype-launder",
                        av.dtype_chain + (call_hop,) + ps.chain,
                        ps.message))
                if av.dtype_src is not None:
                    self.sinks.append(ParamSink(
                        av.dtype_src, "dtype-pass",
                        (call_hop,) + ps.chain, ps.message))
            elif ps.kind == "dtype-launder":
                if av.dtype == DT_UINT32:
                    self.df.record(
                        "dtype", self.rel, call.lineno,
                        f"uint32 value passed to '{qual}' is laundered "
                        f"inside it and reaches {ps.message} — pin the "
                        f"dtype at the boundary",
                        av.dtype_chain + (call_hop,) + ps.chain)
                if av.dtype_src is not None:
                    self.sinks.append(ParamSink(
                        av.dtype_src, "dtype-launder",
                        (call_hop,) + ps.chain, ps.message))

        call_hop = chain_hop(self.rel, call.lineno, f"{qual}()")

        def map_leaf(av: AV) -> AV:
            out = av
            if av.dtype_src is not None:
                src = collapse(arg_for(av.dtype_src))
                out = dataclasses.replace(
                    out, dtype=src.dtype, dtype_src=src.dtype_src,
                    laundered=out.laundered or src.laundered,
                    launders_src=out.launders_src | src.launders_src,
                    dtype_chain=_cap(src.dtype_chain + out.dtype_chain))
            if av.secret_src:
                srcs = [collapse(arg_for(j)) for j in sorted(av.secret_src)]
                hot = next((s for s in srcs if s.secrecy == SEC_SECRET),
                           None)
                sym = frozenset().union(*(s.secret_src for s in srcs)) \
                    if srcs else frozenset()
                if hot is not None:
                    out = dataclasses.replace(
                        out, secrecy=SEC_SECRET, secret_src=sym,
                        secret_chain=_cap(hot.secret_chain + (call_hop,)
                                          + out.secret_chain))
                else:
                    out = dataclasses.replace(out, secret_src=sym)
            if av.launders_src:
                lsym = set(out.launders_src - av.launders_src)
                fired = None
                for j in sorted(av.launders_src):
                    src = collapse(arg_for(j))
                    if src.dtype == DT_UINT32 and fired is None:
                        fired = src
                    if src.dtype_src is not None:
                        lsym.add(src.dtype_src)
                if fired is not None:
                    out = dataclasses.replace(
                        out, laundered=True,
                        launders_src=frozenset(lsym),
                        dtype_chain=_cap(fired.dtype_chain + (call_hop,)
                                         + out.dtype_chain))
                else:
                    out = dataclasses.replace(out,
                                              launders_src=frozenset(lsym))
            return out

        def map_value(v: ValT) -> ValT:
            if isinstance(v, TupleVal):
                return TupleVal(tuple(map_value(e) for e in v.elts))
            if isinstance(v, ObjVal):
                return ObjVal(v.cls, {k: map_value(e)
                                      for k, e in v.fields.items()})
            return map_leaf(v)

        return map_value(summ.ret)


# -- project-fingerprint cache ----------------------------------------------

_DF_CACHE: Dict[str, Dataflow] = {}
_DF_CACHE_MAX = 8


def project_fingerprint(project: ProjectInfo) -> str:
    h = hashlib.sha256()
    for rel in sorted(project.modules):
        h.update(rel.encode("utf-8"))
        h.update(project.modules[rel].content_hash.encode("utf-8"))
    return h.hexdigest()


def dataflow_for(project: ProjectInfo,
                 focus: Optional[Set[str]] = None) -> Dataflow:
    """The (memoized) engine run for a project: both consuming rules — and
    repeated analyze_project calls over unchanged sources — share one.
    A focused run (--changed-only) caches under its own key: it only
    interprets functions defined in the focus relpaths."""
    fp = project_fingerprint(project)
    if focus is not None:
        fp = hashlib.sha256(
            (fp + "|" + "\n".join(sorted(focus))).encode("utf-8")
        ).hexdigest()
    df = _DF_CACHE.get(fp)
    if df is None:
        if len(_DF_CACHE) >= _DF_CACHE_MAX:
            _DF_CACHE.clear()
        df = Dataflow(project)
        df.run(focus)
        _DF_CACHE[fp] = df
    return df
