"""Determinism analysis: nondeterminism taint over byte-identity sinks.

Byte-identical transcripts are the repo's load-bearing equivalence
claim — restarts, topologies, worker counts and decode modes must all
reproduce the exact bytes a VN is audited against — yet that property
was only ever checked dynamically, one seed and one configuration at a
time. This engine proves it statically: a flow-sensitive,
interprocedural taint pass over the PR-4 project graphs, in the shape
of the PR-5 dataflow and PR-14 concurrency engines.

**Sources** seed taint at

* wall-clock reads — ``time.time``/``monotonic``/``perf_counter`` and
  ``datetime.now``/``utcnow`` *when the value flows to data* (a clock
  value compared against a deadline is control, not data, and stays
  clean);
* unseeded RNG — ``os.urandom``, ``secrets.*``, ``uuid.*``,
  module-level ``random.*``, ``random.Random()`` with no seed,
  ``np.random.*`` global state and ``default_rng()`` without a seed
  (``jax.random.PRNGKey(x)`` needs no special case: it is tainted
  exactly when ``x`` is);
* identity — ``id()``, ``hash()`` of interned-unstable values under
  hash randomization, ``os.getpid()``;
* order hazards — ``os.listdir``/``glob``/``iterdir`` without
  ``sorted`` (filesystem order), ``set`` construction (iteration order
  varies under hash randomization; plain dicts are insertion-ordered
  and deterministic), and ``as_completed`` thread-completion order.

**Launders** clear taint: ``sorted(...)`` and the canonicalizers
``canon_points``/``fold_cts`` clear *order* kinds (a sorted list of
wall-clock stamps is still wall-clock); order-insensitive reductions
(``len``/``sum``/``min``/``max``/``any``/``all``) clear order kinds;
index-addressed stores (``results[i] = v`` — the roster-order
``fan_out`` gather) clear order kinds because final container state is
placement- not arrival-ordered; ``fold_in`` from a deterministic key
derives deterministic randomness (and is recorded as a launder site);
and the explicit ``# drynx: deterministic[reason]`` marker declares a
deliberate exception at the source or the sink line.

**Sinks** are the byte-identity surfaces of the real tree: transcript
serialization (``survey_transcript``/``transcript_digest``), digest
computations (``hashlib.*``), ProofDB / ``pane:`` / ``ckpt:`` writes
(2-arg ``.put``), skipchain ``chain.append``/``create_genesis``, wire
v2 frame encode (``encode_frame``/``_encode_v2``), and the fsync'd
journal lines (``_ledger_append`` — EpsilonLedger and the pool store).

Two finding kinds feed the project rules: a *value*-kind taint
(wall-clock/rng/identity) reaching a sink argument is
``nondet-flow-to-transcript``; an *order*-kind taint (listing /
set-order / thread-order) reaching a sink argument — or a sink call
lexically inside a loop whose iterate is order-tainted, where the
*write order* itself is nondeterministic — is
``unordered-iteration-at-sink``. Both carry call chains rendered as
SARIF codeFlows, with dual anchors (sink + source) so ``noqa`` works
at either end, exactly like ``secret-flow-to-sink``.

Known over-approximations (see ANALYSIS.md): any tainted argument
taints an unresolvable call's result (method calls on tainted
receivers included); container mutators inside an order-tainted loop
taint the container; comparisons are control, not data. Known
under-approximations: sink-bearing callees invoked with *untainted*
arguments from inside an unordered loop are not flagged (only direct
sink calls and tainted-argument flows are), and closures over tainted
locals are invisible. Still pure ``ast``, still no jax import; the
whole run is memoized on the project content fingerprint.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, _dotted, _local_bindings
from .dataflow import RawFinding, project_fingerprint
from .graph import FuncNode, ModuleGraph
from .project import ProjectInfo, chain_hop

_MAX_DEPTH = 8

_DETERMINISTIC_RE = re.compile(r"#\s*drynx:\s*deterministic\[([^\]]+)\]")

# Taint kinds. Value kinds poison the bytes themselves; order kinds
# poison the sequence in which deterministic bytes are combined.
VALUE_KINDS = frozenset({"wall-clock", "rng", "identity"})
ORDER_KINDS = frozenset({"listing", "set-order", "thread-order"})

# -- source tables ----------------------------------------------------------

_WALLCLOCK_DOTTED = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}
_RNG_DOTTED = {"os.urandom", "os.getrandom"}
_RNG_PREFIXES = ("secrets.", "uuid.")
# module-level random.* functions draw from the unseeded global
# Mersenne state; random.Random(seed) instances are handled separately
_RANDOM_MODULE_FNS = {
    "random", "randint", "randrange", "choice", "choices", "sample",
    "shuffle", "getrandbits", "randbytes", "gauss", "uniform",
    "betavariate", "expovariate", "normalvariate",
}
_IDENTITY_DOTTED = {"os.getpid"}
_LISTING_DOTTED = {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
_LISTING_LEAVES = {"glob", "iglob", "iterdir", "rglob", "listdir",
                   "scandir"}
_THREAD_ORDER_LEAVES = {"as_completed"}

# -- launder tables ---------------------------------------------------------

# clear ORDER kinds, keep value kinds
_ORDER_LAUNDER_BUILTINS = {"sorted"}
_ORDER_INSENSITIVE = {"len", "sum", "min", "max", "any", "all"}
_CANON_LEAVES = {"canon_points", "fold_cts"}
# deterministic key derivation: passthrough (tainted key -> tainted
# child key), but a recognized launder construct worth recording
_FOLD_LEAVES = {"fold_in"}
_SET_CTORS = {"set", "frozenset"}

# -- sink tables ------------------------------------------------------------

_DIGEST_LEAVES = {"sha256", "sha384", "sha512", "sha1", "md5",
                  "sha3_256", "sha3_384", "sha3_512",
                  "blake2b", "blake2s"}
_TRANSCRIPT_LEAVES = {"survey_transcript", "transcript_digest"}
_JOURNAL_LEAVES = {"_ledger_append"}
_WIRE_LEAVES = {"encode_frame", "_encode_v2"}
_CHAIN_LEAVES = {"append", "create_genesis"}

# container mutators whose call order IS the container order
_ORDERED_MUTATORS = {"append", "add", "extend", "insert", "update",
                     "appendleft", "write"}


def _is_drynx_pkg(mod: ModuleInfo) -> bool:
    return (mod.relpath.startswith("drynx_tpu/")
            or "/drynx_tpu/" in mod.relpath
            or "lintpkg" in mod.relpath)


@dataclasses.dataclass(frozen=True)
class Taint:
    """One nondeterministic value (or a parameter sentinel)."""
    kind: str                       # VALUE_KINDS | ORDER_KINDS |
    #                                 "set-value" | "param"
    source: str = ""                # human description of the origin
    chain: Tuple[str, ...] = ()     # chain hops, source first
    param: str = ""                 # for kind == "param"

    @property
    def is_param(self) -> bool:
        return self.kind == "param"

    @property
    def is_order(self) -> bool:
        return self.kind in ORDER_KINDS or self.kind == "set-value"

    @property
    def is_value(self) -> bool:
        return self.kind in VALUE_KINDS


def _join(*taints: Optional[Taint]) -> Optional[Taint]:
    """Combine taints of an expression: first real taint wins (value
    kinds preferred over order kinds, both over param sentinels)."""
    best: Optional[Taint] = None
    for t in taints:
        if t is None:
            continue
        if best is None:
            best = t
        elif best.is_param and not t.is_param:
            best = t
        elif (not best.is_value) and t.is_value:
            best = t
    return best


def _strip_order(t: Optional[Taint]) -> Tuple[Optional[Taint], bool]:
    """Remove order kinds; returns (remaining taint, stripped?)."""
    if t is not None and t.is_order:
        return None, True
    return t, False


@dataclasses.dataclass(frozen=True)
class ParamSink:
    """A callee parameter that flows into a sink inside the callee."""
    param: str
    label: str                   # sink label
    leaf: str                    # sink callable leaf name
    file: str                    # sink site
    line: int
    hops: Tuple[str, ...]        # hops inside the callee, call-order


@dataclasses.dataclass
class FnSummary:
    params: Tuple[str, ...] = ()
    ret: Optional[Taint] = None            # fresh taint returned
    ret_params: FrozenSet[str] = frozenset()   # params reaching return
    param_sinks: Tuple[ParamSink, ...] = ()


_EMPTY_SUMMARY = FnSummary()


# -- the engine -------------------------------------------------------------

class Determinism:
    """Whole-program nondeterminism-taint pass over a ProjectInfo."""

    def __init__(self, project: ProjectInfo,
                 focus: Optional[FrozenSet[str]] = None):
        self.project = project
        self.focus = focus          # relpaths to walk (None = all)
        self.nondet_raw: List[RawFinding] = []
        self.unordered_raw: List[RawFinding] = []
        # recognized surfaces, for the non-vacuity cross-checks
        self.sink_sites: Dict[Tuple[str, int], str] = {}
        self.launder_sites: Dict[Tuple[str, int], str] = {}
        self.source_sites: Dict[Tuple[str, int], str] = {}
        self.marker_sites: Dict[Tuple[str, int], str] = {}
        self._summaries: Dict[str, FnSummary] = {}
        self._inflight: Set[str] = set()
        # fid -> (locals, {id(call): callee fid})
        self._fn_facts: Dict[str, Tuple[Set[str], Dict[int, str]]] = {}
        self._seen: Set[Tuple[str, int, str, str]] = set()

    # -- driver -----------------------------------------------------------

    def run(self) -> "Determinism":
        for fid in sorted(self.project.calls.functions):
            fn = self.project.calls.functions[fid]
            mg = self.project.graphs[fn.module]
            if not _is_drynx_pkg(mg.info):
                continue
            if self.focus is not None and \
                    mg.info.relpath not in self.focus:
                continue
            self._summary(fid, 0)
        self.nondet_raw.sort(key=lambda r: (r.file, r.line, r.message))
        self.unordered_raw.sort(key=lambda r: (r.file, r.line, r.message))
        return self

    # -- summaries --------------------------------------------------------

    def _summary(self, fid: str, depth: int) -> FnSummary:
        summ = self._summaries.get(fid)
        if summ is not None:
            return summ
        if fid in self._inflight or depth > _MAX_DEPTH:
            return _EMPTY_SUMMARY
        fn = self.project.calls.functions.get(fid)
        if fn is None:
            return _EMPTY_SUMMARY
        mg = self.project.graphs.get(fn.module)
        if mg is None or not _is_drynx_pkg(mg.info):
            return _EMPTY_SUMMARY
        self._inflight.add(fid)
        try:
            ctx = _DetCtx(self, mg, fn, depth)
            summ = ctx.walk()
        finally:
            self._inflight.discard(fid)
        self._summaries[fid] = summ
        return summ

    # -- emission ---------------------------------------------------------

    def marked(self, info: ModuleInfo, line: int) -> Optional[str]:
        """The ``deterministic[reason]`` marker text governing a line:
        on the line itself, or in the comment block directly above it
        (long call lines keep their markers readable)."""
        if not (0 < line <= len(info.lines)):
            return None
        m = _DETERMINISTIC_RE.search(info.lines[line - 1])
        prev = line - 1
        while m is None and prev >= 1 and \
                info.lines[prev - 1].lstrip().startswith("#"):
            m = _DETERMINISTIC_RE.search(info.lines[prev - 1])
            prev -= 1
        if m is None:
            return None
        self.marker_sites[(info.relpath, line)] = m.group(1).strip()
        self.launder_sites.setdefault((info.relpath, line), "marker")
        return m.group(1).strip()

    def emit(self, info: ModuleInfo, line: int, label: str, leaf: str,
             taint: Taint, ordered_write: bool = False) -> None:
        if self.marked(info, line) is not None:
            return
        src = taint.chain[0] if taint.chain else ""
        key = (info.relpath, line, taint.kind, src)
        if key in self._seen:
            return
        self._seen.add(key)
        chain = taint.chain + (chain_hop(info.relpath, line,
                                         f"{leaf}() [{label} sink]"),)
        if ordered_write:
            msg = (f"{label} sink '{leaf}' runs inside a loop over "
                   f"{taint.source} — the write order follows "
                   f"{taint.kind} order, so the bytes differ run to "
                   f"run; sort the iterate or buffer and sort")
        elif taint.is_order:
            msg = (f"unordered value ({taint.kind}: {taint.source}) "
                   f"reaches {label} sink '{leaf}' — serialize through "
                   f"sorted(...) or a canonicalizer first")
        else:
            msg = (f"nondeterministic value ({taint.kind}: "
                   f"{taint.source}) flows into {label} sink '{leaf}' "
                   f"— byte-identity surfaces must derive from survey "
                   f"inputs; launder it or mark the deliberate "
                   f"exception '# drynx: deterministic[reason]'")
        raw = RawFinding(file=info.relpath, line=line, message=msg,
                         chain=chain,
                         anchors=self._anchors(chain, info.relpath,
                                               line))
        if taint.is_order or ordered_write:
            self.unordered_raw.append(raw)
        else:
            self.nondet_raw.append(raw)

    @staticmethod
    def _anchors(chain: Tuple[str, ...], file: str,
                 line: int) -> Tuple[Tuple[str, int], ...]:
        """Dual anchors: the sink site plus the source hop
        (suppressible at either)."""
        out = [(file, line)]
        if chain:
            first = chain[0].split(":", 2)
            if len(first) == 3 and first[1].isdigit():
                out.append((first[0], int(first[1])))
        return tuple(out)


# -- flow-sensitive function walker -----------------------------------------

class _DetCtx:
    """Executes one function body with a taint environment, recording
    sink flows; parameters are seeded as sentinels so one walk yields
    both the local findings and the interprocedural summary."""

    def __init__(self, eng: Determinism, mg: ModuleGraph, fn: FuncNode,
                 depth: int):
        self.eng = eng
        self.mg = mg
        self.fn = fn
        self.depth = depth
        self.rel = mg.info.relpath
        self.info = mg.info
        facts = eng._fn_facts.get(fn.fid)
        if facts is None:
            facts = (_local_bindings(fn.node),
                     {id(s.node): s.callee
                      for s in eng.project.calls.callees(fn.fid)})
            eng._fn_facts[fn.fid] = facts
        self.locals, self.sites = facts
        self.env: Dict[str, Taint] = {}
        self.order_stack: List[Taint] = []
        a = fn.node.args
        self.params: Tuple[str, ...] = tuple(
            p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs))
        for p in self.params:
            self.env[p] = Taint("param", source=f"param {p}", param=p)
        self.ret: Optional[Taint] = None
        self.ret_params: Set[str] = set()
        self.param_sinks: List[ParamSink] = []

    def walk(self) -> FnSummary:
        self.exec_stmts(self.fn.node.body)
        return FnSummary(params=self.params, ret=self.ret,
                         ret_params=frozenset(self.ret_params),
                         param_sinks=tuple(self.param_sinks))

    # -- statements --------------------------------------------------------

    def exec_stmts(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt)

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            t = self.eval_expr(stmt.value)
            for tgt in stmt.targets:
                self._bind(tgt, t)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self.eval_expr(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            t = self.eval_expr(stmt.value)
            name = _dotted(stmt.target)
            if name is not None:
                self.env[name] = _join(self.env.get(name), t) or \
                    self.env.get(name) or t
                if self.env[name] is None:
                    self.env.pop(name, None)
        elif isinstance(stmt, ast.Expr):
            self.eval_expr(stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                t = self.eval_expr(stmt.value)
                if t is not None:
                    if t.is_param:
                        self.ret_params.add(t.param)
                    else:
                        self.ret = _join(self.ret, t)
        elif isinstance(stmt, (ast.If, ast.While)):
            self.eval_expr(stmt.test)
            self.exec_stmts(stmt.body)
            self.exec_stmts(stmt.orelse)
        elif isinstance(stmt, ast.For):
            self._exec_for(stmt)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                t = self.eval_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t)
            self.exec_stmts(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.exec_stmts(stmt.body)
            for h in stmt.handlers:
                self.exec_stmts(h.body)
            self.exec_stmts(stmt.orelse)
            self.exec_stmts(stmt.finalbody)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self.eval_expr(sub)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                name = _dotted(tgt)
                if name is not None:
                    self.env.pop(name, None)
        # nested defs/classes are their own callgraph nodes; skip

    def _exec_for(self, stmt: ast.For) -> None:
        t = self.eval_expr(stmt.iter)
        loop_taint: Optional[Taint] = None
        if t is not None and t.kind == "set-value":
            hop = chain_hop(self.rel, stmt.iter.lineno,
                            "iterate set")
            loop_taint = Taint("set-order", source=t.source or "a set",
                              chain=t.chain + (hop,))
        elif t is not None and t.is_order:
            loop_taint = t
        if loop_taint is not None:
            self._bind(stmt.target, loop_taint)
            self.order_stack.append(loop_taint)
            try:
                self.exec_stmts(stmt.body)
            finally:
                self.order_stack.pop()
        else:
            # value/param taints: the element values carry the taint
            self._bind(stmt.target, t)
            self.exec_stmts(stmt.body)
        self.exec_stmts(stmt.orelse)

    def _bind(self, tgt: ast.expr, t: Optional[Taint]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind(el, t)
            return
        if isinstance(tgt, ast.Starred):
            self._bind(tgt.value, t)
            return
        if isinstance(tgt, ast.Subscript):
            # index-addressed store: final container state is
            # placement-ordered, not arrival-ordered — this is the
            # roster-order fan_out gather launder
            t, stripped = _strip_order(t)
            if stripped:
                self.eng.launder_sites[(self.rel, tgt.lineno)] = \
                    "indexed-store"
            name = _dotted(tgt.value)
            if name is not None and t is not None:
                self.env[name] = _join(self.env.get(name), t)
            return
        name = _dotted(tgt)
        if name is None:
            return
        if t is None:
            self.env.pop(name, None)
        else:
            self.env[name] = t

    # -- expressions -------------------------------------------------------

    def eval_expr(self, e: Optional[ast.expr]) -> Optional[Taint]:
        if e is None:
            return None
        if isinstance(e, ast.Name):
            return self.env.get(e.id)
        if isinstance(e, ast.Attribute):
            dotted = _dotted(e)
            if dotted is not None and dotted in self.env:
                return self.env[dotted]
            return self.eval_expr(e.value)
        if isinstance(e, ast.Call):
            return self.visit_call(e)
        if isinstance(e, ast.BinOp):
            return _join(self.eval_expr(e.left), self.eval_expr(e.right))
        if isinstance(e, ast.BoolOp):
            return _join(*[self.eval_expr(v) for v in e.values])
        if isinstance(e, ast.UnaryOp):
            return self.eval_expr(e.operand)
        if isinstance(e, ast.Compare):
            # comparisons yield control booleans, not data bytes
            self.eval_expr(e.left)
            for c in e.comparators:
                self.eval_expr(c)
            return None
        if isinstance(e, ast.IfExp):
            self.eval_expr(e.test)
            return _join(self.eval_expr(e.body),
                         self.eval_expr(e.orelse))
        if isinstance(e, ast.JoinedStr):
            return _join(*[self.eval_expr(v) for v in e.values])
        if isinstance(e, ast.FormattedValue):
            return self.eval_expr(e.value)
        if isinstance(e, ast.Subscript):
            return _join(self.eval_expr(e.value),
                         self.eval_expr(e.slice))
        if isinstance(e, ast.Starred):
            return self.eval_expr(e.value)
        if isinstance(e, (ast.List, ast.Tuple)):
            return _join(*[self.eval_expr(el) for el in e.elts])
        if isinstance(e, ast.Set):
            inner = _join(*[self.eval_expr(el) for el in e.elts])
            return self._as_set(inner, e.lineno, "a set literal")
        if isinstance(e, ast.Dict):
            return _join(*[self.eval_expr(v) for v in e.values
                           if v is not None],
                         *[self.eval_expr(k) for k in e.keys
                           if k is not None])
        if isinstance(e, (ast.ListComp, ast.GeneratorExp, ast.SetComp,
                          ast.DictComp)):
            return self._eval_comp(e)
        if isinstance(e, (ast.Await, ast.YieldFrom)):
            return self.eval_expr(e.value)
        if isinstance(e, ast.NamedExpr):
            t = self.eval_expr(e.value)
            self._bind(e.target, t)
            return t
        return None

    def _as_set(self, inner: Optional[Taint], lineno: int,
                what: str) -> Taint:
        if inner is not None and inner.is_value:
            return inner                      # value taint dominates
        chain = (inner.chain if inner is not None else
                 (chain_hop(self.rel, lineno, what),))
        return Taint("set-value", source=what, chain=chain)

    def _eval_comp(self, e: ast.expr) -> Optional[Taint]:
        order: Optional[Taint] = None
        elt_env: List[Optional[Taint]] = []
        for gen in e.generators:
            t = self.eval_expr(gen.iter)
            if t is not None and (t.is_order or t.kind == "set-value"):
                order = _join(order, t)
                self._bind(gen.target, t)
            else:
                self._bind(gen.target, t)
        if isinstance(e, ast.DictComp):
            elt_env.append(self.eval_expr(e.key))
            elt_env.append(self.eval_expr(e.value))
        else:
            elt_env.append(self.eval_expr(e.elt))
        inner = _join(*elt_env)
        if isinstance(e, ast.SetComp):
            return self._as_set(_join(inner, order), e.lineno,
                                "a set comprehension")
        if isinstance(e, ast.DictComp):
            return inner                      # dicts insertion-ordered
        return _join(inner, order)

    # -- calls -------------------------------------------------------------

    def visit_call(self, call: ast.Call) -> Optional[Taint]:
        args_t: List[Tuple[Optional[str], Optional[Taint]]] = []
        for a in call.args:
            args_t.append((None, self.eval_expr(a)))
        for kw in call.keywords:
            args_t.append((kw.arg, self.eval_expr(kw.value)))
        recv_t: Optional[Taint] = None
        if isinstance(call.func, ast.Attribute):
            recv_t = self.eval_expr(call.func.value)
        dotted = _dotted(call.func)
        leaf = dotted.split(".")[-1] if dotted else ""

        label = self._sink_label(call, dotted, leaf)
        if label is not None:
            self._check_sink(call, label, leaf, args_t)

        t = self._source_taint(call, dotted, leaf, args_t)
        if t is not None:
            return t

        t = self._launder(call, dotted, leaf, args_t)
        if t is not NotImplemented:
            return t

        callee_fid = self.sites.get(id(call))
        if callee_fid is not None:
            return self._call_summary(call, callee_fid, leaf, args_t,
                                      recv_t)

        joined = _join(*[t for _, t in args_t], recv_t)
        if joined is not None and isinstance(call.func, ast.Attribute) \
                and leaf in _ORDERED_MUTATORS and joined.is_order:
            # building a container in nondeterministic call order
            name = _dotted(call.func.value)
            if name is not None:
                self.env[name] = _join(self.env.get(name), joined)
        if self.order_stack and isinstance(call.func, ast.Attribute) \
                and leaf in _ORDERED_MUTATORS:
            name = _dotted(call.func.value)
            if name is not None:
                self.env[name] = _join(self.env.get(name),
                                       self.order_stack[-1])
        return joined

    # -- sinks -------------------------------------------------------------

    def _sink_label(self, call: ast.Call, dotted: Optional[str],
                    leaf: str) -> Optional[str]:
        if dotted and (dotted.startswith("hashlib.")
                       or leaf in _DIGEST_LEAVES):
            return "digest"
        if leaf in _TRANSCRIPT_LEAVES:
            return "transcript"
        if leaf in _JOURNAL_LEAVES:
            return "journal"
        if leaf in _WIRE_LEAVES:
            return "wire-encode"
        if isinstance(call.func, ast.Attribute):
            if leaf == "put" and len(call.args) == 2:
                return "db-write"
            if leaf in _CHAIN_LEAVES:
                recv = _dotted(call.func.value)
                if recv is not None and \
                        recv.split(".")[-1] == "chain":
                    return "skipchain"
        return None

    def _check_sink(self, call: ast.Call, label: str, leaf: str,
                    args_t: Sequence[Tuple[Optional[str],
                                           Optional[Taint]]]) -> None:
        self.eng.sink_sites[(self.rel, call.lineno)] = label
        for _, t in args_t:
            if t is None:
                continue
            if t.is_param:
                hop = chain_hop(self.rel, call.lineno,
                                f"{leaf}() [{label} sink]")
                self.param_sinks.append(ParamSink(
                    param=t.param, label=label, leaf=leaf,
                    file=self.rel, line=call.lineno,
                    hops=t.chain + (hop,)))
            elif t.is_value or t.is_order:
                self.eng.emit(self.info, call.lineno, label, leaf, t)
        if self.order_stack:
            self.eng.emit(self.info, call.lineno, label, leaf,
                          self.order_stack[-1], ordered_write=True)

    # -- sources -----------------------------------------------------------

    def _source_taint(self, call: ast.Call, dotted: Optional[str],
                      leaf: str,
                      args_t: Sequence[Tuple[Optional[str],
                                             Optional[Taint]]]
                      ) -> Optional[Taint]:
        kind: Optional[str] = None
        desc = f"{dotted or leaf}()"
        if dotted in _WALLCLOCK_DOTTED:
            kind = "wall-clock"
        elif dotted in _RNG_DOTTED or \
                (dotted and dotted.startswith(_RNG_PREFIXES)):
            kind = "rng"
        elif dotted and dotted.startswith(("random.", "np.random.",
                                           "numpy.random.")):
            if leaf == "Random" or leaf == "default_rng":
                if not call.args and not call.keywords:
                    kind = "rng"
                    desc = f"unseeded {dotted}()"
                # seeded instances stay clean (arg taint propagates
                # via the default join below if the seed is tainted)
            elif leaf == "SystemRandom":
                kind = "rng"
            elif leaf in _RANDOM_MODULE_FNS:
                kind = "rng"
                desc = f"global-state {dotted}()"
        elif dotted in _IDENTITY_DOTTED:
            kind = "identity"
        elif isinstance(call.func, ast.Name) and \
                call.func.id in ("id", "hash") and \
                call.func.id not in self.locals:
            kind = "identity"
            desc = (f"{call.func.id}() under "
                    f"{'hash randomization' if call.func.id == 'hash' else 'address reuse'}")
        elif dotted in _LISTING_DOTTED or leaf in _LISTING_LEAVES:
            kind = "listing"
            desc = f"unsorted {dotted or leaf}()"
        elif leaf in _THREAD_ORDER_LEAVES:
            kind = "thread-order"
            desc = "as_completed() thread-completion order"
        elif leaf in _SET_CTORS and isinstance(call.func, ast.Name) \
                and leaf not in self.locals:
            inner = _join(*[t for _, t in args_t])
            inner, stripped = _strip_order(inner)
            if stripped:
                self.eng.launder_sites[(self.rel, call.lineno)] = \
                    "set-membership"
            if inner is not None and inner.is_value:
                return inner
            return self._as_set(inner, call.lineno,
                                f"{leaf}(...) construction")
        if kind is None:
            return None
        if self.eng.marked(self.info, call.lineno) is not None:
            return None
        self.eng.source_sites[(self.rel, call.lineno)] = kind
        hop = chain_hop(self.rel, call.lineno, f"{desc} [{kind}]")
        return Taint(kind, source=desc, chain=(hop,))

    # -- launders ----------------------------------------------------------

    def _launder(self, call: ast.Call, dotted: Optional[str], leaf: str,
                 args_t: Sequence[Tuple[Optional[str],
                                        Optional[Taint]]]):
        """Returns a taint (or None) when the call is a recognized
        launder; NotImplemented otherwise."""
        joined = _join(*[t for _, t in args_t])
        if leaf in _ORDER_LAUNDER_BUILTINS and \
                isinstance(call.func, ast.Name):
            self.eng.launder_sites[(self.rel, call.lineno)] = "sorted"
            t, _ = _strip_order(joined)
            return t
        if leaf in _CANON_LEAVES:
            self.eng.launder_sites[(self.rel, call.lineno)] = \
                "canonicalize"
            t, _ = _strip_order(joined)
            return t
        if leaf in _FOLD_LEAVES:
            self.eng.launder_sites[(self.rel, call.lineno)] = "fold_in"
            return joined            # deterministic derivation: the
            #                          result is tainted iff the key is
        if leaf in _ORDER_INSENSITIVE and \
                isinstance(call.func, ast.Name) and \
                leaf not in self.locals:
            t, stripped = _strip_order(joined)
            if stripped:
                self.eng.launder_sites[(self.rel, call.lineno)] = \
                    "order-insensitive"
            return t
        return NotImplemented

    # -- interprocedural ---------------------------------------------------

    def _call_summary(self, call: ast.Call, callee_fid: str, leaf: str,
                      args_t: Sequence[Tuple[Optional[str],
                                             Optional[Taint]]],
                      recv_t: Optional[Taint]) -> Optional[Taint]:
        summ = self.eng._summary(callee_fid, self.depth + 1)
        if not summ.params and summ.ret is None:
            return _join(*[t for _, t in args_t], recv_t)
        # map caller arguments onto callee parameter names
        is_method = (isinstance(call.func, ast.Attribute)
                     and bool(summ.params)
                     and summ.params[0] in ("self", "cls"))
        by_param: Dict[str, Optional[Taint]] = {}
        if is_method:
            by_param[summ.params[0]] = recv_t
        offset = 1 if is_method else 0
        pos = [t for name, t in args_t if name is None]
        for i, t in enumerate(pos):
            if offset + i < len(summ.params):
                by_param[summ.params[offset + i]] = t
        for name, t in args_t:
            if name is not None:
                by_param[name] = t
        # parameter -> sink flows inside the callee
        for ps in summ.param_sinks:
            t = by_param.get(ps.param)
            if t is None or t.is_param:
                if t is not None and t.is_param:
                    # forwardings: extend our own summary
                    self.param_sinks.append(ParamSink(
                        param=t.param, label=ps.label, leaf=ps.leaf,
                        file=ps.file, line=ps.line,
                        hops=(chain_hop(self.rel, call.lineno,
                                        f"{leaf}(...)"),) + ps.hops))
                continue
            if not (t.is_value or t.is_order):
                continue
            info = self.eng.project.modules.get(ps.file)
            if info is None:
                continue
            carried = Taint(t.kind, source=t.source,
                            chain=t.chain + (chain_hop(
                                self.rel, call.lineno,
                                f"{leaf}(...)"),) + ps.hops[:-1])
            self.eng.emit(info, ps.line, ps.label, ps.leaf, carried)
        # result taint: fresh taint returned by the callee, plus any
        # passthrough parameter whose argument is tainted
        out = summ.ret
        if out is not None:
            out = Taint(out.kind, source=out.source,
                        chain=out.chain + (chain_hop(
                            self.rel, call.lineno,
                            f"{leaf}() returns {out.kind}"),),
                        param=out.param)
        for p in summ.ret_params:
            out = _join(out, by_param.get(p))
        if callee_fid.split(".")[-1] == "__init__":
            # constructed object carries its argument taints
            out = _join(out, *[t for _, t in args_t])
        return out


# -- memoized entry point ----------------------------------------------------

_DET_CACHE: Dict[str, Determinism] = {}
_DET_CACHE_MAX = 8


def determinism_for(project: ProjectInfo,
                    focus: Optional[FrozenSet[str]] = None
                    ) -> Determinism:
    """The (memoized) engine run for a project. ``focus`` narrows the
    walked module set for ``--changed-only`` (summaries for callees
    outside the focus are still computed on demand); focused runs are
    cached under a salted key like :func:`dataflow_for`."""
    fp = project_fingerprint(project)
    if focus is not None:
        fp = fp + "|" + ",".join(sorted(focus))
    eng = _DET_CACHE.get(fp)
    if eng is None:
        if len(_DET_CACHE) >= _DET_CACHE_MAX:
            _DET_CACHE.clear()
        eng = Determinism(project, focus=focus).run()
        _DET_CACHE[fp] = eng
    return eng
