"""Opt-in runtime determinism recorder: the dynamic half of the
determinism cross-check.

``DRYNX_DET_TRACE=1`` makes :mod:`drynx_tpu` call :func:`install` at
import time; the byte-identity sinks the static pass reasons about
(:mod:`.determinism`) then report every write here: ``ProofDB.put``
(which covers ``pane:``/``ckpt:`` blobs, skipchain blocks and
checkpoint persistence), transcript serialization, and the fsync'd
EpsilonLedger / pool-store journal lines. Each write is reduced to a
sha256 hexdigest and stored as a **multiset per (sink, key)** — thread
interleaving may reorder arrivals, but two same-seed runs must produce
the same multiset of bytes at every key or the byte-identity claim is
false.

The chaos-marker test in tests/test_determinism_analysis.py runs the
same proofs-on survey twice with the same seed under this recorder and
asserts (a) :func:`divergence` of the two snapshots is empty, and (b)
the statically-declared *laundered* sinks (transcript lines sorted
before hashing, journal records canonicalized with ``sort_keys``)
actually produced identical bytes — the runtime proof that the
launder table in the static pass is honest. Keys that are
nondeterministic **by declared design** (skipchain block bodies embed
the wall-clock ``sample_time`` that ``server/transcript.py``
deliberately excludes) are exempted by prefix, mirroring the
``# drynx: deterministic[...]`` markers at their sources.

Process-global and deliberately simple: one dict, O(1) work per write,
no payload retention (hashes only). Not for production — for tests.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Dict, Iterable, List, Set, Tuple

_RECORDS: Dict[Tuple[str, str], List[str]] = {}
_LAUNDERED: Set[Tuple[str, str]] = set()
_GUARD = threading.Lock()                # created pre-install: untraced
_WRITES = 0
_INSTALLED = False


def install() -> None:
    global _INSTALLED
    _INSTALLED = True


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def reset() -> None:
    global _WRITES
    with _GUARD:
        _RECORDS.clear()
        _LAUNDERED.clear()
        _WRITES = 0


def record(sink: str, key: str, blob: bytes,
           laundered: bool = False) -> None:
    """One sink write: ``sink`` names the surface (``proofdb``,
    ``transcript``, ``epsilon.journal``, ``pool.journal``), ``key``
    the address within it, ``blob`` the exact bytes written.
    ``laundered=True`` declares the bytes passed a canonicalization
    the static pass credits (sorted lines, sort_keys json) — the
    two-run check asserts those specifically, not just globally."""
    if not _INSTALLED:
        return
    global _WRITES
    h = hashlib.sha256(blob).hexdigest()
    with _GUARD:
        _RECORDS.setdefault((sink, key), []).append(h)
        if laundered:
            _LAUNDERED.add((sink, key))
        _WRITES += 1


def write_count() -> int:
    return _WRITES


def snapshot() -> Dict[str, object]:
    """JSON-able state for cross-process comparison: per-key sorted
    hash multisets plus the laundered key set."""
    with _GUARD:
        return {
            "records": {f"{s}:{k}": sorted(v)
                        for (s, k), v in _RECORDS.items()},
            "laundered": sorted(f"{s}:{k}" for s, k in _LAUNDERED),
            "writes": _WRITES,
        }


def laundered_keys() -> Set[str]:
    with _GUARD:
        return {f"{s}:{k}" for s, k in _LAUNDERED}


def divergence(snap_a: Dict[str, object], snap_b: Dict[str, object],
               exempt: Iterable[str] = ()) -> List[str]:
    """Keys whose write multisets differ between two snapshots,
    excluding keys under any ``exempt`` prefix (declared-nondet
    surfaces like skipchain block bodies). A key present in only one
    run diverges too — same-seed runs must visit the same sinks."""
    ex = tuple(exempt)
    ra = dict(snap_a.get("records", {}))
    rb = dict(snap_b.get("records", {}))
    out = []
    for key in sorted(set(ra) | set(rb)):
        if any(key.startswith(p) for p in ex):
            continue
        if ra.get(key) != rb.get(key):
            out.append(key)
    return out
