"""Whole-program graphs for the project-level lint rules.

Two structures, both pure-``ast`` (no jax import, same contract as the rest
of :mod:`drynx_tpu.analysis`):

* **import graph** — per module, where each local name came from:
  ``from x import y [as z]`` bindings and ``import x.y [as z]`` module
  aliases, with relative imports resolved against the module's package.
  ``resolve_import`` follows re-export chains (``a`` imports from ``b``
  which imports from ``c``) to the *defining* module, returning the hop
  list so findings can render the chain.

* **callgraph** — edges between module-level (and nested) functions:
  direct ``f()`` calls, ``mod.f()`` calls through module aliases,
  ``self.m()`` method calls within a class, and the repo's trace-entry
  factories — ``jax.jit(f)`` / ``bucketed(f, ...)`` / ``shard_map(f, ...)``
  — whose function argument becomes a *traced entry* (its body runs at
  trace time even though it carries no decorator).

Both are deliberately approximate (a linter, not an interpreter): unknown
receivers, dynamic dispatch and star-imports resolve to nothing rather
than to everything.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, _dotted

# Factory leaves whose first function argument is traced (body runs at
# trace time): jax.jit/pjit, batching.bucketed, shard_map.
WRAPPER_FACTORIES = {"jit", "pjit", "bucketed", "shard_map"}


def module_name(relpath: str) -> str:
    """Dotted module name for a repo-relative posix path.
    ``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` -> ``a.b``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


@dataclasses.dataclass(frozen=True)
class ImportBinding:
    """Local name <- (module, name) from a ``from module import name``."""
    target_module: str
    target_name: str
    lineno: int


@dataclasses.dataclass(frozen=True)
class ModuleAlias:
    """Local alias <- module from an ``import module [as alias]``."""
    target_module: str
    lineno: int


@dataclasses.dataclass
class FuncNode:
    module: str                 # dotted module name
    qual: str                   # dotted nesting, e.g. "Cls.m" or "outer.inner"
    node: ast.AST               # FunctionDef / AsyncFunctionDef

    @property
    def fid(self) -> str:
        return f"{self.module}:{self.qual}"


class ModuleGraph:
    """Per-module slice of the graphs: import bindings + function table."""

    def __init__(self, info: ModuleInfo, dotted: str, is_package: bool):
        self.info = info
        self.dotted = dotted
        self.is_package = is_package
        # local name -> binding (walked over the WHOLE tree: the repo
        # imports lazily inside functions to break cycles)
        self.froms: Dict[str, ImportBinding] = {}
        self.aliases: Dict[str, ModuleAlias] = {}
        self.functions: Dict[str, FuncNode] = {}       # qual -> node
        self.by_name: Dict[str, List[str]] = {}        # bare name -> [quals]
        self._collect_imports()
        self._collect_functions()

    # -- imports ----------------------------------------------------------

    def _package(self, level: int) -> Optional[str]:
        """Base package for a level-N relative import, or None if it
        escapes the scanned tree."""
        base = self.dotted if self.is_package else (
            self.dotted.rsplit(".", 1)[0] if "." in self.dotted else "")
        for _ in range(level - 1):
            if "." not in base:
                return base or None
            base = base.rsplit(".", 1)[0]
        return base or None

    def _collect_imports(self) -> None:
        for node in ast.walk(self.info.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._package(node.level)
                    if base is None:
                        continue
                    target = f"{base}.{node.module}" if node.module else base
                else:
                    target = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.froms.setdefault(
                        a.asname or a.name,
                        ImportBinding(target, a.name, node.lineno))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases.setdefault(
                            a.asname, ModuleAlias(a.name, node.lineno))
                    else:
                        # `import a.b.c` binds the ROOT name `a`
                        root = a.name.split(".")[0]
                        self.aliases.setdefault(
                            root, ModuleAlias(root, node.lineno))

    # -- functions --------------------------------------------------------

    def _collect_functions(self) -> None:
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    fn = FuncNode(self.dotted, qual, child)
                    self.functions[qual] = fn
                    self.by_name.setdefault(child.name, []).append(qual)
                    visit(child, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.info.tree, "")

    def lookup_function(self, name: str) -> Optional[FuncNode]:
        """Bare name -> the outermost function with that name, if any."""
        quals = self.by_name.get(name)
        if not quals:
            return None
        qual = min(quals, key=lambda q: (q.count("."), q))
        return self.functions[qual]


class ImportGraph:
    """Cross-module name resolution over the scanned module set."""

    def __init__(self, modules: Dict[str, ModuleGraph]):
        self.modules = modules
        # Dotted-suffix index: running the linter on a subtree (or the
        # fixture package) gives relpath-derived names like
        # `tests.fixtures.lintpkg.flags` while the sources say
        # `lintpkg.flags` — a unique suffix still resolves. Ambiguous
        # suffixes map to None.
        self._suffix: Dict[str, Optional[str]] = {}
        for name in modules:
            parts = name.split(".")
            for i in range(len(parts)):
                suf = ".".join(parts[i:])
                if suf in self._suffix and self._suffix[suf] != name:
                    self._suffix[suf] = None
                else:
                    self._suffix[suf] = name

    def canon(self, name: str) -> Optional[str]:
        """Canonical scanned-module name for a dotted import target:
        exact match, else unique dotted-suffix match, else None."""
        if name in self.modules:
            return name
        return self._suffix.get(name)

    def resolve(self, module: str, name: str,
                ) -> Tuple[str, str, List[Tuple[str, int]]]:
        """Follow ``from x import y`` chains from (module, name) to the
        defining module. Returns (def_module, def_name, hops) where hops
        are (module_relpath, import_lineno) pairs, outermost first. When
        the name is not an import binding (or leaves the scanned set),
        the walk stops at the last resolvable module."""
        hops: List[Tuple[str, int]] = []
        seen: Set[Tuple[str, str]] = set()
        while True:
            mg = self.modules.get(module)
            if mg is None or (module, name) in seen:
                return module, name, hops
            seen.add((module, name))
            b = mg.froms.get(name)
            if b is None:
                return module, name, hops
            hops.append((mg.info.relpath, b.lineno))
            target = self.canon(b.target_module)
            if target is None:
                return b.target_module, b.target_name, hops
            # `from pkg import mod` binds a submodule, not a symbol
            sub = self.canon(f"{target}.{b.target_name}")
            if sub is not None and \
                    b.target_name not in _symbols(self.modules[target]):
                return sub, "", hops
            module, name = target, b.target_name

    def module_for_alias(self, module: str, alias: str) -> Optional[str]:
        """Local alias -> dotted module it names (``import x.y as z`` or
        ``from pkg import mod``)."""
        mg = self.modules.get(module)
        if mg is None:
            return None
        a = mg.aliases.get(alias)
        if a is not None:
            return self.canon(a.target_module) or a.target_module
        b = mg.froms.get(alias)
        if b is not None:
            target = self.canon(b.target_module)
            if target is not None and \
                    b.target_name not in _symbols(self.modules[target]):
                sub = self.canon(f"{target}.{b.target_name}")
                return sub or f"{target}.{b.target_name}"
        return None


def _symbols(mg: ModuleGraph) -> Set[str]:
    """Names a module defines (assigns, functions, classes, imports)."""
    out = set(mg.info.module_assigns)
    out.update(mg.by_name)
    out.update(mg.froms)
    out.update(mg.aliases)
    for node in mg.info.tree.body:
        if isinstance(node, ast.ClassDef):
            out.add(node.name)
    return out


@dataclasses.dataclass(frozen=True)
class CallSite:
    caller: str                 # fid
    callee: str                 # fid
    node: ast.Call
    lineno: int


class CallGraph:
    """Function-level call edges + the traced-entry set."""

    def __init__(self, modules: Dict[str, ModuleGraph], imports: ImportGraph):
        self.modules = modules
        self.imports = imports
        self.functions: Dict[str, FuncNode] = {}
        for mg in modules.values():
            for fn in mg.functions.values():
                self.functions[fn.fid] = fn
        self.calls: Dict[str, List[CallSite]] = {}
        # fid -> module-level names bound to its wrapped form
        # (g = jax.jit(f) makes a call to g an edge to f)
        self._wrapper_bindings: Dict[str, Dict[str, str]] = {}
        self.traced_entries: Set[str] = set()
        self._mark_decorated_entries()
        self._mark_wrapped_entries()
        self._build_edges()

    # -- traced entries ---------------------------------------------------

    def _mark_decorated_entries(self) -> None:
        for mg in self.modules.values():
            traced = set(map(id, mg.info.traced_functions))
            for fn in mg.functions.values():
                if id(fn.node) in traced:
                    self.traced_entries.add(fn.fid)

    def _wrapped_function(self, mg: ModuleGraph, scope: Sequence[str],
                          expr: ast.AST) -> Optional[FuncNode]:
        """The FuncNode a wrapper factory argument refers to, if any."""
        if isinstance(expr, ast.Name):
            return self._resolve_name(mg, scope, expr.id)
        if isinstance(expr, ast.Attribute):
            return self._resolve_attribute(mg, expr)
        if isinstance(expr, ast.Call):
            # nested factory composition: jax.jit(shard_map(f, ...))
            d = (_dotted(expr.func) or "").split(".")[-1]
            if d in WRAPPER_FACTORIES and expr.args:
                return self._wrapped_function(mg, scope, expr.args[0])
        return None

    def _mark_wrapped_entries(self) -> None:
        for mg in self.modules.values():
            for scope, call in _calls_with_scope(mg):
                leaf = (_dotted(call.func) or "").split(".")[-1]
                if leaf not in WRAPPER_FACTORIES or not call.args:
                    continue
                arg = call.args[0]
                if isinstance(arg, ast.Lambda):
                    # bucketed(lambda p, k: C.f(p, k)): the functions the
                    # lambda body calls are the trace-time bodies
                    for sub in ast.walk(arg.body):
                        if isinstance(sub, ast.Call):
                            fn = self._wrapped_function(mg, scope, sub.func)
                            if fn is not None:
                                self.traced_entries.add(fn.fid)
                    continue
                fn = self._wrapped_function(mg, scope, arg)
                if fn is not None:
                    self.traced_entries.add(fn.fid)

        # module-level `g = jax.jit(f)` / `g = bucketed(f, ...)`: calls to
        # g are edges to f
        for mg in self.modules.values():
            binds: Dict[str, str] = {}
            for node in mg.info.tree.body:
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                leaf = (_dotted(node.value.func) or "").split(".")[-1]
                if leaf not in WRAPPER_FACTORIES or not node.value.args:
                    continue
                fn = self._wrapped_function(mg, (), node.value.args[0])
                if fn is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        binds[t.id] = fn.fid
            if binds:
                self._wrapper_bindings[mg.dotted] = binds

    # -- resolution -------------------------------------------------------

    def _resolve_name(self, mg: ModuleGraph, scope: Sequence[str],
                      name: str) -> Optional[FuncNode]:
        # nested def in an enclosing scope first, outermost module def next
        for depth in range(len(scope), 0, -1):
            qual = ".".join((*scope[:depth], name))
            if qual in mg.functions:
                return mg.functions[qual]
        fn = mg.lookup_function(name)
        if fn is not None and "." not in fn.qual:
            return fn
        # imported function (through any number of re-export hops)
        def_mod, def_name, _ = self.imports.resolve(mg.dotted, name)
        target = self.modules.get(def_mod)
        if target is not None and def_mod != mg.dotted:
            got = target.lookup_function(def_name)
            if got is not None and "." not in got.qual:
                return got
        return fn

    def _resolve_attribute(self, mg: ModuleGraph,
                           attr: ast.Attribute) -> Optional[FuncNode]:
        d = _dotted(attr)
        if not d:
            return None
        parts = d.split(".")
        # self.m() inside class C -> C.m in this module
        if parts[0] in ("self", "cls") and len(parts) == 2:
            for qual, fn in mg.functions.items():
                if qual.endswith(f".{parts[1]}") and qual.count(".") >= 1:
                    return fn
            return None
        # module alias: longest alias prefix, then a function in it
        for cut in range(len(parts) - 1, 0, -1):
            alias = ".".join(parts[:cut])
            if cut == 1:
                target = self.imports.module_for_alias(mg.dotted, alias) \
                    or self.imports.canon(alias)
            else:
                target = self.imports.canon(alias)
            if target is None:
                continue
            rest = parts[cut:]
            # absolute dotted path may include submodules: a.b.c.f
            while len(rest) > 1:
                nxt = self.imports.canon(f"{target}.{rest[0]}")
                if nxt is None:
                    break
                target, rest = nxt, rest[1:]
            tm = self.modules.get(target)
            if tm is not None and len(rest) == 1:
                got = tm.lookup_function(rest[0])
                if got is not None and "." not in got.qual:
                    return got
        return None

    def resolve_call(self, mg: ModuleGraph, scope: Sequence[str],
                     call: ast.Call) -> Optional[FuncNode]:
        if isinstance(call.func, ast.Name):
            binds = self._wrapper_bindings.get(mg.dotted, {})
            if call.func.id in binds:
                return self.functions.get(binds[call.func.id])
            return self._resolve_name(mg, scope, call.func.id)
        if isinstance(call.func, ast.Attribute):
            return self._resolve_attribute(mg, call.func)
        return None

    # -- edges ------------------------------------------------------------

    def _build_edges(self) -> None:
        for mg in self.modules.values():
            for fn in mg.functions.values():
                scope = tuple(fn.qual.split(".")[:-1])
                sites: List[CallSite] = []
                for call in _own_calls(fn.node):
                    callee = self.resolve_call(
                        mg, (*scope, fn.qual.split(".")[-1]), call)
                    if callee is not None and callee.fid != fn.fid:
                        sites.append(CallSite(fn.fid, callee.fid, call,
                                              call.lineno))
                if sites:
                    self.calls[fn.fid] = sites

    def callees(self, fid: str) -> List[CallSite]:
        return self.calls.get(fid, [])


def _own_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Call nodes lexically in fn's body, NOT descending into nested
    function/class definitions (those are their own callgraph nodes)."""
    def visit(node: ast.AST) -> Iterator[ast.Call]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from visit(child)

    yield from visit(fn)


def _own_returns(fn: ast.AST) -> Iterator[ast.Return]:
    """Return statements lexically in fn's body, not in nested defs."""
    def visit(node: ast.AST) -> Iterator[ast.Return]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Return):
                yield child
            yield from visit(child)

    yield from visit(fn)


def _calls_with_scope(mg: ModuleGraph) -> Iterator[Tuple[Tuple[str, ...],
                                                         ast.Call]]:
    """(enclosing function scope, Call) for every call in the module."""
    def visit(node: ast.AST, scope: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            ns = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ns = scope + (child.name,)
            elif isinstance(child, ast.Call):
                yield scope, child
            yield from visit(child, ns)

    yield from visit(mg.info.tree, ())
