"""Opt-in runtime lock-order recorder: the dynamic half of the
concurrency cross-check.

``DRYNX_LOCK_TRACE=1`` makes :mod:`drynx_tpu` call :func:`install` at
import time, replacing ``threading.Lock``/``threading.RLock`` with
factories that return thin wrappers. Every wrapper keeps the usual lock
semantics (``with``, ``acquire(blocking, timeout)``, re-entrancy for
RLock) and additionally maintains a per-thread stack of currently held
locks. When a thread acquires lock B while holding lock A and *both*
carry diagnostic names (``resilience.policy.named_lock``), the ordered
edge ``(name_A, name_B)`` is recorded.

The chaos-marker test in tests/test_concurrency_analysis.py runs a real
2-worker ``SurveyServer`` drain under this recorder and asserts the
observed edge set is a **subgraph of the static lock-order graph** from
:mod:`.concurrency` — the static analysis must over-approximate what the
runtime actually does, or its cycle verdicts are worthless. Unnamed
locks (jax internals, stdlib queues, per-entry cache locks) participate
in the held stack but never in edges: the contract is only claimed for
the named locks the analysis reasons about.

Process-global and deliberately simple: one edge set, no per-thread
output, O(held locks) work per acquire. Not for production — for tests.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Set, Tuple

from ..resilience.policy import LOCK_NAMES

_ORIG_LOCK = None          # threading.Lock before install()
_ORIG_RLOCK = None
_EDGES: Set[Tuple[str, str]] = set()
_EDGES_GUARD = threading.Lock()          # created pre-install: untraced
_STACKS = threading.local()
_ACQUIRES = 0                            # total traced acquisitions


def _stack() -> List[int]:
    try:
        return _STACKS.held
    except AttributeError:
        _STACKS.held = []
        return _STACKS.held


class _TracedLock:
    """Wrapper around a real Lock/RLock recording acquisition order."""

    def __init__(self, inner, reentrant: bool):
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._on_acquired()
        return got

    def _on_acquired(self) -> None:
        global _ACQUIRES
        stack = _stack()
        me = id(self)
        if not (self._reentrant and me in stack):
            my_name = LOCK_NAMES.get(me)
            with _EDGES_GUARD:
                _ACQUIRES += 1
                if my_name is not None:
                    for held in stack:
                        if held == me:
                            continue
                        held_name = LOCK_NAMES.get(held)
                        if held_name is not None \
                                and held_name != my_name:
                            _EDGES.add((held_name, my_name))
        stack.append(me)

    def release(self) -> None:
        stack = _stack()
        me = id(self)
        # remove the most recent entry for this lock (non-LIFO release
        # is legal for plain locks)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == me:
                del stack[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name):
        # Condition() probes _is_owned/_acquire_restore/_release_save;
        # forwarding keeps RLock-backed conditions working and lets a
        # plain Lock raise AttributeError so Condition takes its
        # fallback path, exactly as untraced.
        return getattr(self._inner, name)


def install() -> None:
    """Patch threading.Lock/RLock with tracing factories (idempotent)."""
    global _ORIG_LOCK, _ORIG_RLOCK
    if _ORIG_LOCK is not None:
        return
    _ORIG_LOCK = threading.Lock
    _ORIG_RLOCK = threading.RLock

    def lock_factory():
        return _TracedLock(_ORIG_LOCK(), reentrant=False)

    def rlock_factory():
        return _TracedLock(_ORIG_RLOCK(), reentrant=True)

    threading.Lock = lock_factory
    threading.RLock = rlock_factory


def uninstall() -> None:
    global _ORIG_LOCK, _ORIG_RLOCK
    if _ORIG_LOCK is None:
        return
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _ORIG_LOCK = _ORIG_RLOCK = None


def installed() -> bool:
    return _ORIG_LOCK is not None


def observed_edges() -> Set[Tuple[str, str]]:
    """Ordered (outer_name, inner_name) pairs seen so far."""
    with _EDGES_GUARD:
        return set(_EDGES)


def acquisition_count() -> int:
    """Traced acquisitions so far — the non-vacuity signal (a recorder
    that saw zero acquisitions proves nothing)."""
    with _EDGES_GUARD:
        return _ACQUIRES


def reset() -> None:
    global _ACQUIRES
    with _EDGES_GUARD:
        _EDGES.clear()
        _ACQUIRES = 0
