"""Project-level analysis: ProjectInfo, ProjectRule and the driver.

``analyze_project`` parses every file once (content-hash cached, shared
with the per-module pass), builds the import graph and callgraph from
:mod:`.graph`, then runs the per-module rules file by file AND the
project rules over the whole :class:`ProjectInfo`. Project findings may
carry a ``call_chain`` (rendered in text/JSON output) and extra
``anchors`` — a callgraph finding is suppressible with ``# drynx:
noqa[rule]`` at the sync site *or* at the jit entry it is reachable from.

Still pure ``ast``, still no jax import.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence, Set,
                    Tuple)

from .core import (RULES, Finding, ModuleInfo, Rule, _dotted, _rel,
                   iter_py_files, module_info_for, suppressed_at)
from .graph import CallGraph, ImportGraph, ModuleGraph, module_name


@dataclasses.dataclass(frozen=True)
class FlagOrigin:
    """Where a mutable flag is actually defined + why it is mutable."""
    module: str                      # defining module (dotted)
    relpath: str                     # defining file
    name: str                        # name in the defining module
    lineno: int                      # definition line
    reason: str                      # "env" | "rebound" | "rebound-externally"
    hops: Tuple[Tuple[str, int], ...]  # import chain (relpath, lineno)


class ProjectInfo:
    """The whole scanned package: per-file ModuleInfo + both graphs."""

    def __init__(self, infos: Sequence[ModuleInfo]):
        self.modules: Dict[str, ModuleInfo] = {i.relpath: i for i in infos}
        self.graphs: Dict[str, ModuleGraph] = {}
        for info in infos:
            dotted = module_name(info.relpath)
            is_pkg = info.relpath.endswith("__init__.py")
            self.graphs[dotted] = ModuleGraph(info, dotted, is_pkg)
        self.by_relpath: Dict[str, ModuleGraph] = {
            mg.info.relpath: mg for mg in self.graphs.values()}
        self.imports = ImportGraph(self.graphs)
        self.calls = CallGraph(self.graphs, self.imports)
        self.external_rebinds = self._collect_external_rebinds()

    # -- construction -----------------------------------------------------

    @classmethod
    def from_sources(cls, pairs: Iterable[Tuple[str, str]]) -> "ProjectInfo":
        """Build from (relpath, source) pairs — the test entrypoint."""
        return cls([module_info_for(src, rel) for rel, src in pairs])

    @classmethod
    def from_paths(cls, paths: Sequence[Path],
                   ) -> Tuple["ProjectInfo", List[Finding]]:
        """Build from files/dirs; unparseable files come back as
        parse-error findings instead of ProjectInfo members."""
        infos: List[ModuleInfo] = []
        errors: List[Finding] = []
        for path in iter_py_files(paths):
            rel = _rel(path)
            try:
                source = path.read_text(encoding="utf-8")
                infos.append(module_info_for(source, rel))
            except (OSError, UnicodeDecodeError) as e:
                errors.append(Finding(rule="parse-error", file=rel, line=1,
                                      message=f"unreadable file: {e}",
                                      line_text=""))
            except SyntaxError as e:
                errors.append(Finding(rule="parse-error", file=rel,
                                      line=e.lineno or 1,
                                      message=f"file does not parse: {e.msg}",
                                      line_text=""))
        return cls(infos), errors

    # -- derived facts ----------------------------------------------------

    def _collect_external_rebinds(self) -> Dict[str, Set[str]]:
        """module dotted -> attribute names some OTHER module assigns on it
        (`po.INTERPRET = True` style): mutable even if the defining module
        never rebinds them itself."""
        out: Dict[str, Set[str]] = {}
        for mg in self.graphs.values():
            for node in ast.walk(mg.info.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not isinstance(t, ast.Attribute):
                        continue
                    d = _dotted(t)
                    if not d or d.count(".") != 1:
                        continue
                    alias, attr = d.split(".")
                    target = self.imports.module_for_alias(mg.dotted, alias)
                    if target is not None and target != mg.dotted:
                        out.setdefault(target, set()).add(attr)
        return out

    def flag_origin(self, module: str, name: str) -> Optional[FlagOrigin]:
        """Resolve (module, name) through import chains; return a
        FlagOrigin iff the *defining* binding is mutable (env-derived,
        rebound in its module, or attribute-rebound from outside)."""
        def_mod, def_name, hops = self.imports.resolve(module, name)
        mg = self.graphs.get(def_mod)
        if mg is None or not def_name:
            return None
        info = mg.info
        reason = None
        lineno = 1
        if def_name in info.env_derived:
            reason, lineno = "env", info.env_derived[def_name].lineno
        elif def_name in info.rebound:
            reason = "rebound"
            assigns = info.module_assigns.get(def_name)
            lineno = assigns[0].lineno if assigns else 1
        elif def_name in self.external_rebinds.get(def_mod, ()):
            reason = "rebound-externally"
            assigns = info.module_assigns.get(def_name)
            lineno = assigns[0].lineno if assigns else 1
        if reason is None:
            return None
        return FlagOrigin(module=def_mod, relpath=info.relpath, name=def_name,
                          lineno=lineno, reason=reason, hops=tuple(hops))

    def in_focus(self, relpath: str) -> bool:
        """True when the file is inside the current focus set (or no
        focus is active). ``--changed-only`` narrows the focus to the
        impacted set; rules consult this to skip out-of-focus modules."""
        focus = getattr(self, "focus", None)
        return focus is None or relpath in focus

    def impacted_relpaths(self, changed: Iterable[str]) -> Set[str]:
        """The *impacted set* of a change: the changed files plus every
        transitive importer (reverse import-graph closure) — a change to
        params.py re-runs the project rules on everything that imports
        it, directly or through re-exports."""
        rev: Dict[str, Set[str]] = {}
        for dotted, mg in self.graphs.items():
            deps: Set[str] = set()
            for b in mg.froms.values():
                t = self.imports.canon(b.target_module)
                if t is not None:
                    deps.add(t)
                    # `from pkg import mod` may bind a submodule
                    sub = self.imports.canon(f"{t}.{b.target_name}")
                    if sub is not None:
                        deps.add(sub)
            for a in mg.aliases.values():
                t = self.imports.canon(a.target_module)
                if t is not None:
                    deps.add(t)
            for dep in deps:
                rev.setdefault(dep, set()).add(dotted)
        by_rel = {mg.info.relpath: dotted
                  for dotted, mg in self.graphs.items()}
        queue = [by_rel[rel] for rel in changed if rel in by_rel]
        seen: Set[str] = set(queue)
        while queue:
            cur = queue.pop()
            for importer in rev.get(cur, ()):
                if importer not in seen:
                    seen.add(importer)
                    queue.append(importer)
        out = {self.graphs[d].info.relpath for d in seen}
        out.update(rel for rel in changed if rel in self.modules)
        return out

    # -- golden-test shape -------------------------------------------------

    def to_json(self) -> Dict[str, object]:
        """Deterministic JSON view of both graphs (golden-test surface).
        Only structure — no AST nodes, no absolute paths."""
        imports: Dict[str, object] = {}
        for dotted in sorted(self.graphs):
            mg = self.graphs[dotted]
            imports[dotted] = {
                "file": mg.info.relpath,
                "froms": {n: {"module": b.target_module,
                              "name": b.target_name, "line": b.lineno}
                          for n, b in sorted(mg.froms.items())},
                "aliases": {n: {"module": a.target_module, "line": a.lineno}
                            for n, a in sorted(mg.aliases.items())},
            }
        callgraph = {
            fid: sorted({s.callee for s in sites})
            for fid, sites in sorted(self.calls.calls.items())}
        return {"imports": imports, "callgraph": callgraph,
                "traced_entries": sorted(self.calls.traced_entries)}


class ProjectRule(Rule):
    """A rule that needs the whole project. ``run`` (per-module) defaults
    to nothing; subclasses that keep a lexical component may override both.
    ``--list-rules`` marks these ``[project]``."""

    project = True
    engine = "project"

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        raise NotImplementedError


def chain_hop(relpath: str, lineno: int, symbol: str) -> str:
    """One rendered call-chain hop: ``file:line:symbol``."""
    return f"{relpath}:{lineno}:{symbol}"


def analyze_project(paths: Sequence[Path],
                    rules: Optional[Iterable[str]] = None,
                    changed: Optional[Sequence[str]] = None,
                    ) -> List[Finding]:
    """Whole-program pass: per-module rules on every file + project rules
    over the ProjectInfo, noqa applied at the finding line or any anchor.

    ``changed`` (relpaths) narrows the pass to the *impacted set* — the
    changed files plus their transitive importers via the reverse import
    graph. The graphs and summaries are still built whole-program (a
    partial file set has no meaningful import graph); only reporting and
    the per-module scan are restricted, which is what keeps
    ``--changed-only`` under the fast-tier budget.
    """
    from . import rules as _rules  # noqa: F401  (side effect: registration)

    project, findings = ProjectInfo.from_paths(paths)
    focus: Optional[Set[str]] = None
    if changed is not None:
        focus = project.impacted_relpaths(changed)
        project.focus = focus
        findings = [f for f in findings if f.file in focus]
    selected = list(RULES.values() if rules is None
                    else [RULES[r] for r in rules])
    for relpath in sorted(project.modules):
        if focus is not None and relpath not in focus:
            continue
        mod = project.modules[relpath]
        for rule in selected:
            findings.extend(rule.run(mod))
    for rule in selected:
        if rule.project:
            found = rule.run_project(project)
            findings.extend(f for f in found
                            if focus is None or f.file in focus)
    findings = [f for f in findings
                if not suppressed_at(f, project.modules)]
    # absorb: a rule may declare it supersedes another's findings at the
    # same (file, line) — the dataflow secret-flow rule wins over the
    # regex seed rule so one leak is reported once.
    winners: Dict[str, Set[Tuple[str, int]]] = {}
    for f in findings:
        rule = RULES.get(f.rule)
        for victim in getattr(rule, "absorbs", ()):
            winners.setdefault(victim, set()).add((f.file, f.line))
    findings = [f for f in findings
                if (f.file, f.line) not in winners.get(f.rule, set())]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
