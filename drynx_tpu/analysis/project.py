"""Project-level analysis: ProjectInfo, ProjectRule and the driver.

``analyze_project`` parses every file once (content-hash cached, shared
with the per-module pass), builds the import graph and callgraph from
:mod:`.graph`, then runs the per-module rules file by file AND the
project rules over the whole :class:`ProjectInfo`. Project findings may
carry a ``call_chain`` (rendered in text/JSON output) and extra
``anchors`` — a callgraph finding is suppressible with ``# drynx:
noqa[rule]`` at the sync site *or* at the jit entry it is reachable from.

Still pure ``ast``, still no jax import.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence, Set,
                    Tuple)

from .core import (RULES, Finding, ModuleInfo, Rule, _dotted, _rel,
                   iter_py_files, module_info_for, suppressed_at)
from .graph import CallGraph, ImportGraph, ModuleGraph, module_name


@dataclasses.dataclass(frozen=True)
class FlagOrigin:
    """Where a mutable flag is actually defined + why it is mutable."""
    module: str                      # defining module (dotted)
    relpath: str                     # defining file
    name: str                        # name in the defining module
    lineno: int                      # definition line
    reason: str                      # "env" | "rebound" | "rebound-externally"
    hops: Tuple[Tuple[str, int], ...]  # import chain (relpath, lineno)


class ProjectInfo:
    """The whole scanned package: per-file ModuleInfo + both graphs."""

    def __init__(self, infos: Sequence[ModuleInfo]):
        self.modules: Dict[str, ModuleInfo] = {i.relpath: i for i in infos}
        self.graphs: Dict[str, ModuleGraph] = {}
        for info in infos:
            dotted = module_name(info.relpath)
            is_pkg = info.relpath.endswith("__init__.py")
            self.graphs[dotted] = ModuleGraph(info, dotted, is_pkg)
        self.by_relpath: Dict[str, ModuleGraph] = {
            mg.info.relpath: mg for mg in self.graphs.values()}
        self.imports = ImportGraph(self.graphs)
        self.calls = CallGraph(self.graphs, self.imports)
        self.external_rebinds = self._collect_external_rebinds()

    # -- construction -----------------------------------------------------

    @classmethod
    def from_sources(cls, pairs: Iterable[Tuple[str, str]]) -> "ProjectInfo":
        """Build from (relpath, source) pairs — the test entrypoint."""
        return cls([module_info_for(src, rel) for rel, src in pairs])

    @classmethod
    def from_paths(cls, paths: Sequence[Path],
                   ) -> Tuple["ProjectInfo", List[Finding]]:
        """Build from files/dirs; unparseable files come back as
        parse-error findings instead of ProjectInfo members."""
        infos: List[ModuleInfo] = []
        errors: List[Finding] = []
        for path in iter_py_files(paths):
            rel = _rel(path)
            try:
                source = path.read_text(encoding="utf-8")
                infos.append(module_info_for(source, rel))
            except (OSError, UnicodeDecodeError) as e:
                errors.append(Finding(rule="parse-error", file=rel, line=1,
                                      message=f"unreadable file: {e}",
                                      line_text=""))
            except SyntaxError as e:
                errors.append(Finding(rule="parse-error", file=rel,
                                      line=e.lineno or 1,
                                      message=f"file does not parse: {e.msg}",
                                      line_text=""))
        return cls(infos), errors

    # -- derived facts ----------------------------------------------------

    def _collect_external_rebinds(self) -> Dict[str, Set[str]]:
        """module dotted -> attribute names some OTHER module assigns on it
        (`po.INTERPRET = True` style): mutable even if the defining module
        never rebinds them itself."""
        out: Dict[str, Set[str]] = {}
        for mg in self.graphs.values():
            for node in ast.walk(mg.info.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not isinstance(t, ast.Attribute):
                        continue
                    d = _dotted(t)
                    if not d or d.count(".") != 1:
                        continue
                    alias, attr = d.split(".")
                    target = self.imports.module_for_alias(mg.dotted, alias)
                    if target is not None and target != mg.dotted:
                        out.setdefault(target, set()).add(attr)
        return out

    def flag_origin(self, module: str, name: str) -> Optional[FlagOrigin]:
        """Resolve (module, name) through import chains; return a
        FlagOrigin iff the *defining* binding is mutable (env-derived,
        rebound in its module, or attribute-rebound from outside)."""
        def_mod, def_name, hops = self.imports.resolve(module, name)
        mg = self.graphs.get(def_mod)
        if mg is None or not def_name:
            return None
        info = mg.info
        reason = None
        lineno = 1
        if def_name in info.env_derived:
            reason, lineno = "env", info.env_derived[def_name].lineno
        elif def_name in info.rebound:
            reason = "rebound"
            assigns = info.module_assigns.get(def_name)
            lineno = assigns[0].lineno if assigns else 1
        elif def_name in self.external_rebinds.get(def_mod, ()):
            reason = "rebound-externally"
            assigns = info.module_assigns.get(def_name)
            lineno = assigns[0].lineno if assigns else 1
        if reason is None:
            return None
        return FlagOrigin(module=def_mod, relpath=info.relpath, name=def_name,
                          lineno=lineno, reason=reason, hops=tuple(hops))

    # -- golden-test shape -------------------------------------------------

    def to_json(self) -> Dict[str, object]:
        """Deterministic JSON view of both graphs (golden-test surface).
        Only structure — no AST nodes, no absolute paths."""
        imports: Dict[str, object] = {}
        for dotted in sorted(self.graphs):
            mg = self.graphs[dotted]
            imports[dotted] = {
                "file": mg.info.relpath,
                "froms": {n: {"module": b.target_module,
                              "name": b.target_name, "line": b.lineno}
                          for n, b in sorted(mg.froms.items())},
                "aliases": {n: {"module": a.target_module, "line": a.lineno}
                            for n, a in sorted(mg.aliases.items())},
            }
        callgraph = {
            fid: sorted({s.callee for s in sites})
            for fid, sites in sorted(self.calls.calls.items())}
        return {"imports": imports, "callgraph": callgraph,
                "traced_entries": sorted(self.calls.traced_entries)}


class ProjectRule(Rule):
    """A rule that needs the whole project. ``run`` (per-module) defaults
    to nothing; subclasses that keep a lexical component may override both.
    ``--list-rules`` marks these ``[project]``."""

    project = True

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        raise NotImplementedError


def chain_hop(relpath: str, lineno: int, symbol: str) -> str:
    """One rendered call-chain hop: ``file:line:symbol``."""
    return f"{relpath}:{lineno}:{symbol}"


def analyze_project(paths: Sequence[Path],
                    rules: Optional[Iterable[str]] = None,
                    ) -> List[Finding]:
    """Whole-program pass: per-module rules on every file + project rules
    over the ProjectInfo, noqa applied at the finding line or any anchor."""
    from . import rules as _rules  # noqa: F401  (side effect: registration)

    project, findings = ProjectInfo.from_paths(paths)
    selected = list(RULES.values() if rules is None
                    else [RULES[r] for r in rules])
    for relpath in sorted(project.modules):
        mod = project.modules[relpath]
        for rule in selected:
            findings.extend(rule.run(mod))
    for rule in selected:
        if rule.project:
            findings.extend(rule.run_project(project))
    findings = [f for f in findings
                if not suppressed_at(f, project.modules)]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
