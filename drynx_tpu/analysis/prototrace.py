"""Opt-in runtime protocol recorder: the dynamic half of the
typestate cross-check.

``DRYNX_PROTO_TRACE=1`` makes :mod:`drynx_tpu` call :func:`install` at
import time; the transition sites the static pass reasons about
(:mod:`.typestate`) then report every lifecycle event here, tagged
with a per-instance token: the pool store's tmp-write → fsync →
rename idiom and slab claim → journal → read → unlink sequence, the
``ConnPool`` checkout/return/discard cycle (plus ``Conn.call`` uses),
pane seal and proof-commit, and ``SurveyCheckpoint`` phase-enter /
save. Each instance accumulates an ordered event list.

The chaos-marker test in tests/test_typestate_analysis.py drives a
proofs-on survey plus a pool consume/crash-recover cycle under this
recorder and asserts every observed per-instance sequence is
**accepted by the declared automaton** (:func:`violations` empty) and
that the run was non-vacuous (≥3 protocols exercised, ≥20 instances)
— the runtime proof that the automata shipped as project rules
describe what the code actually does, not a convenient fiction.

The runtime DFAs here deliberately re-state the static tables in
dynamic vocabulary: the static engine reasons about *may*-states over
all paths, the recorder sees the one concrete path taken, so its
acceptance check is a plain DFA run with no joins. Process-global and
deliberately simple: one dict, O(1) work per event, no payload
retention. Not for production — for tests.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, Iterable, List, Mapping, Tuple

_EVENTS: Dict[Tuple[str, str], List[str]] = {}
_GUARD = threading.Lock()                # created pre-install: untraced
_COUNTER = itertools.count(1)
_INSTALLED = False


def install() -> None:
    global _INSTALLED
    _INSTALLED = True


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def reset() -> None:
    with _GUARD:
        _EVENTS.clear()


def new_instance(proto: str) -> str:
    """A fresh per-resource token (``conn:17``). Cheap enough to mint
    unconditionally at instrumented creation sites; nothing is stored
    until the first :func:`record`."""
    return f"{proto}:{next(_COUNTER)}"


def record(instance: str, event: str) -> None:
    """One lifecycle event on one instance. ``instance`` is a token
    from :func:`new_instance`; ``event`` is the automaton vocabulary
    (``open``/``write``/``fsync``/``rename``, ``claim``/``journal``/
    ``read``/``unlink``, ``checkout``/``use``/``put``/``discard``/
    ``close``, ``seal``/``commit``/``ctor``/``load``/``enter``/
    ``save``)."""
    if not _INSTALLED:
        return
    proto = instance.split(":", 1)[0]
    with _GUARD:
        _EVENTS.setdefault((proto, instance), []).append(event)


def event_count() -> int:
    with _GUARD:
        return sum(len(v) for v in _EVENTS.values())


def snapshot() -> Dict[str, object]:
    """JSON-able state for cross-process conformance checking: the
    ordered event sequence per instance."""
    with _GUARD:
        return {
            "instances": {inst: list(seq)
                          for (_p, inst), seq in _EVENTS.items()},
        }


# -- runtime DFAs ------------------------------------------------------------
#
# state -> event -> state; None start key gives the start state. A
# missing (state, event) pair is a rejection. These are the dynamic
# counterparts of typestate.PROTOCOLS: one concrete path, no joins,
# no unborn/poisoned bookkeeping.

AUTOMATA: Dict[str, Mapping[str, Mapping[str, str]]] = {
    "atomic": {
        "": {"open": "open"},
        "open": {"write": "dirty", "fsync": "open",
                 "close": "closed-synced"},
        "dirty": {"write": "dirty", "fsync": "synced"},
        "synced": {"write": "dirty", "fsync": "synced",
                   "close": "closed-synced"},
        "closed-synced": {"rename": "published"},
        "published": {},
    },
    "journal": {
        # append-only fsync'd journal lines (declared-replay paths):
        # any number of append->fsync pairs
        "": {"append": "appended"},
        "appended": {"fsync": "flushed"},
        "flushed": {"append": "appended"},
    },
    "slab": {
        "": {"claim": "claimed"},
        "claimed": {"journal": "journaled"},
        "journaled": {"read": "read"},
        "read": {"read": "read", "unlink": "consumed"},
        "consumed": {},
    },
    "conn": {
        "": {"checkout": "checked-out"},
        "checked-out": {"use": "checked-out", "put": "returned",
                        "discard": "discarded", "close": "closed",
                        "timeout": "suspect"},
        "suspect": {"discard": "discarded", "close": "closed"},
        # a pooled conn can fail its health probe at the next get and
        # be discarded without ever being re-checked-out
        "returned": {"discard": "discarded"},
        "discarded": {},
        "closed": {},
    },
    "seal": {
        "": {"seal": "sealed", "commit": "committed"},
        "sealed": {},
        "committed": {},
    },
    "ckpt": {
        "": {"ctor": "fresh", "load": "resumed"},
        "fresh": {"enter": "entered", "save": "written"},
        "resumed": {"enter": "entered"},
        "entered": {"enter": "entered", "save": "written"},
        "written": {"enter": "entered", "save": "written"},
    },
}

# states a finished sequence may legally stop in (mid-protocol stops
# are fine for conn/ckpt/journal — the process outlives the test
# window — but a slab must not stop between claim and unlink, and an
# atomic tmp write must publish)
ACCEPT_STOP: Dict[str, frozenset] = {
    "atomic": frozenset({"published"}),
    "journal": frozenset({"appended", "flushed"}),
    "slab": frozenset({"consumed"}),
    # "suspect" stops are legal: a conn broken by a transport fault is
    # simply abandoned by chaos/crash paths — reuse-after-timeout is
    # still caught because "suspect" has no "use"/"put" transitions
    "conn": frozenset({"checked-out", "returned", "discarded",
                       "closed", "suspect"}),
    "seal": frozenset({"sealed", "committed"}),
    "ckpt": frozenset({"fresh", "resumed", "entered", "written"}),
}


def accepts(proto: str, events: Iterable[str]) -> Tuple[bool, str]:
    """Run one concrete event sequence through the declared DFA.
    Returns (accepted, explanation)."""
    dfa = AUTOMATA.get(proto)
    if dfa is None:
        return False, f"unknown protocol {proto!r}"
    state = ""
    for i, ev in enumerate(events):
        nxt = dfa.get(state, {}).get(ev)
        if nxt is None:
            return False, (f"event {i} {ev!r} rejected in state "
                           f"{state or 'start'!r}")
        state = nxt
    if state not in ACCEPT_STOP.get(proto, frozenset()):
        return False, f"stopped in non-accepting state {state!r}"
    return True, ""


def violations(snap: Dict[str, object]) -> List[str]:
    """Instances whose observed sequence the declared automaton
    rejects — one human-readable line each, empty = conformant."""
    out = []
    insts = snap.get("instances", {})
    for inst in sorted(insts):
        proto = inst.split(":", 1)[0]
        ok, why = accepts(proto, insts[inst])
        if not ok:
            out.append(f"{inst}: {why} (seq={insts[inst]})")
    return out


def coverage(snap: Dict[str, object]) -> Dict[str, int]:
    """Instances observed per protocol — the non-vacuity surface."""
    counts: Dict[str, int] = {}
    for inst in snap.get("instances", {}):
        proto = inst.split(":", 1)[0]
        counts[proto] = counts.get(proto, 0) + 1
    return counts
