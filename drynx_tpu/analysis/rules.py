"""The repo-specific lint rules (see ANALYSIS.md for the full rationale).

Every rule is a static approximation: it must be cheap, zero-dependency
(no jax import) and err toward flagging — suppressions (`# drynx:
noqa[rule]`) and the committed baseline absorb deliberate exceptions.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .core import (Finding, ModuleInfo, Rule, _contains_env_read, _dotted,
                   _local_bindings, register)
from .graph import FuncNode, _own_calls, _own_returns
from .project import ProjectInfo, ProjectRule, chain_hop

_SECRET_RE = re.compile(
    r"(^|_)(sk|secret|secrets|priv|privkey|private(_?key)?)(_|$)|secret",
    re.IGNORECASE)

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log", "lvl", "lvl1", "lvl2", "lvl3"}
_LOGGER_NAMES = {"log", "logging", "logger", "_logger", "LOG", "LOGGER"}


def _in_scope(mod: ModuleInfo, *parts: str) -> bool:
    return any(f"/{p}/" in f"/{mod.relpath}" for p in parts)


def _is_drynx_pkg(mod: ModuleInfo) -> bool:
    # lintpkg is the test fixture package: it opts into the scoped rules so
    # the project-level pass can be exercised end-to-end from the CLI.
    return (mod.relpath.startswith("drynx_tpu/")
            or "/drynx_tpu/" in mod.relpath
            or "lintpkg" in mod.relpath)


# ---------------------------------------------------------------------------
@register
class JitGlobalCapture(Rule):
    """A @jax.jit function (or a pallas_call builder — its body runs at
    trace time) reading a *mutable* module global bakes the value into the
    trace cache, keyed only on shapes/static args. Flipping the flag later
    (monkeypatch, kill-switch) silently reuses stale traces — exactly the
    INTERPRET trace-cache leak in ADVICE.md. Pass such values as static
    arguments, or accept the capture explicitly via the baseline + a
    cache-clearing teardown. This rule covers flags defined in the SAME
    module; imported ones are handled by cross-module-flag-capture, which
    propagates real mutability through the import graph instead of the
    old KNOWN_MUTABLE_FLAGS allowlist."""

    id = "jit-global-capture"
    summary = ("jit-traced code reads a mutable module-level flag; the value "
               "is frozen into the trace cache at first call")

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        mutable = set(mod.env_derived) | mod.rebound
        if not mutable:
            return
        for fn in mod.traced_functions:
            local = _local_bindings(fn)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mutable and sub.id not in local):
                    yield self.finding(
                        mod, sub,
                        f"trace-time capture of mutable module global "
                        f"'{sub.id}' in '{fn.name}' — value is frozen into "
                        f"the jit/pallas trace cache")


# ---------------------------------------------------------------------------
@register
class UnsafePickle(Rule):
    """VNs deserialize proof bodies sent by the very parties they exist to
    distrust; `pickle.loads` on those bytes is remote code execution via a
    crafted __reduce__. All deserialization must go through the restricted
    unpickler in proofs/safe_pickle.py (the only file allowed here)."""

    id = "unsafe-pickle"
    summary = ("raw pickle.load(s)/Unpickler outside proofs/safe_pickle.py "
               "— RCE on attacker-controlled bytes")

    _ALLOWED_SUFFIX = "proofs/safe_pickle.py"

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.relpath.endswith(self._ALLOWED_SUFFIX):
            return
        # track `from pickle import loads [as x]`
        from_pickle: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module == "pickle":
                for a in node.names:
                    if a.name in ("loads", "load", "Unpickler"):
                        from_pickle.add(a.asname or a.name)
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            bad = (d in ("pickle.loads", "pickle.load", "pickle.Unpickler")
                   or (isinstance(sub.func, ast.Name)
                       and sub.func.id in from_pickle))
            if bad:
                yield self.finding(
                    mod, sub,
                    f"'{d or sub.func.id}' on untrusted bytes is arbitrary "
                    f"code execution; use proofs.safe_pickle.safe_loads")


# ---------------------------------------------------------------------------
@register
class ImplicitDtype(Rule):
    """The crypto/proof layers are exact uint32 limb arithmetic with
    jax_enable_x64 on: a dtype-inferred array (weak int64/float64) silently
    corrupts Montgomery carries or changes a hash transcript. Array
    constructors inside crypto/ and proofs/ must pin their dtype."""

    id = "implicit-dtype"
    summary = ("jnp array constructor without an explicit dtype inside "
               "crypto/ or proofs/ — inferred dtypes corrupt limb math")

    # positional index at which dtype may appear
    _CTORS = {"jnp.array": 1, "jnp.asarray": 1, "jnp.zeros": 1,
              "jnp.ones": 1, "jnp.empty": 1, "jnp.full": 2}

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (_is_drynx_pkg(mod) and _in_scope(mod, "crypto", "proofs")):
            return
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d not in self._CTORS:
                continue
            if any(k.arg == "dtype" for k in sub.keywords):
                continue
            if len(sub.args) > self._CTORS[d]:
                continue  # dtype passed positionally
            yield self.finding(
                mod, sub,
                f"'{d}' without explicit dtype — pin it (uint32 limb "
                f"tensors / exact-int statistics must not rely on "
                f"inference)")


# ---------------------------------------------------------------------------
@register
class HostRoundtripInDecode(Rule):
    """A value materialized on the host with ``np.asarray(...)`` and
    immediately re-uploaded via ``jnp.asarray`` / ``jax.device_put`` is
    the host round-trip the device-direct data path removed: the wire /
    staging layers (service/, parallel/) should hand device consumers a
    device array directly (transport.unpack_array_device, proof_plane
    put_shard) instead of copying through host memory. Flags the nested
    form ``jnp.asarray(np.asarray(x))`` and the two-statement form
    ``v = np.asarray(...)`` followed by ``jnp.asarray(v)``."""

    id = "host-roundtrip-in-decode"
    summary = ("np.asarray(...) immediately re-uploaded with jnp.asarray/"
               "device_put inside service/ or parallel/ — use the "
               "device-direct decode path instead of a host round-trip")

    _HOST = {"np.asarray", "numpy.asarray"}
    _DEVICE = {"jnp.asarray", "jax.numpy.asarray", "jax.device_put",
               "device_put"}

    def _is_host_call(self, node) -> bool:
        return (isinstance(node, ast.Call)
                and _dotted(node.func) in self._HOST)

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (_is_drynx_pkg(mod)
                and _in_scope(mod, "service", "parallel")):
            return
        for sub in ast.walk(mod.tree):
            # nested form: device sink taking a host materialization as
            # its first argument
            if isinstance(sub, ast.Call) \
                    and _dotted(sub.func) in self._DEVICE \
                    and sub.args and self._is_host_call(sub.args[0]):
                yield self.finding(
                    mod, sub,
                    f"'{_dotted(sub.func)}(np.asarray(...))' round-trips "
                    f"through host memory — decode/stage straight to "
                    f"device (unpack_array_device / put_shard)")
            # two-statement form: v = np.asarray(...); <device sink>(v)
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(sub, field, None)
                if not isinstance(stmts, list):
                    continue
                for prev, nxt in zip(stmts, stmts[1:]):
                    if not (isinstance(prev, ast.Assign)
                            and len(prev.targets) == 1
                            and isinstance(prev.targets[0], ast.Name)
                            and self._is_host_call(prev.value)):
                        continue
                    name = prev.targets[0].id
                    for call in ast.walk(nxt):
                        if isinstance(call, ast.Call) \
                                and _dotted(call.func) in self._DEVICE \
                                and call.args \
                                and isinstance(call.args[0], ast.Name) \
                                and call.args[0].id == name:
                            yield self.finding(
                                mod, call,
                                f"'{name} = np.asarray(...)' is "
                                f"immediately re-uploaded by "
                                f"'{_dotted(call.func)}({name})' — a "
                                f"host round-trip the device-direct "
                                f"path avoids")
                            break


# ---------------------------------------------------------------------------
@register
class HostSyncInHotPath(ProjectRule):
    """Inside jit-traced crypto/parallel code, float()/int()/bool()/
    np.asarray() on a traced value either crashes at trace time or forces a
    device->host sync that serializes the pipeline; .block_until_ready()
    inside a trace is always a mistake. Heuristic taint: function
    parameters (minus static_argnames) and locals derived from them.

    The per-module pass (``run``) checks jit/pallas bodies lexically. The
    project pass (``run_project``) follows the callgraph: a sync inside a
    plain helper *transitively reachable* from a jit entry fires too, with
    the call chain rendered and the finding suppressible at the sync site
    OR the entry. Reads of ``.shape/.ndim/.dtype/.size`` are host metadata
    and never taint."""

    id = "host-sync-in-hot-path"
    summary = ("host-synchronizing call on a traced value inside (or "
               "transitively reachable from) jitted crypto/ or parallel/ "
               "code")

    _HOST_CASTS = {"float", "int", "bool"}
    _HOST_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    _SYNC_METHODS = {"block_until_ready", "item", "tolist"}

    _SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
    _MAX_DEPTH = 5

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (_is_drynx_pkg(mod) and _in_scope(mod, "crypto", "parallel")):
            return
        for fn in mod.traced_functions:
            tainted = self._tainted_names(fn)
            for sub, what in self._body_syncs(fn, tainted):
                yield self.finding(
                    mod, sub,
                    f"'{what}' on a traced value inside jit-traced "
                    f"'{fn.name}' — crashes at trace time or forces a "
                    f"device->host sync")

    def _body_syncs(self, fn: ast.AST, tainted: Set[str],
                    ) -> Iterator[Tuple[ast.Call, str]]:
        """(call node, rendered sink) for every host sync on a tainted
        value lexically in fn (nested defs included: they close over the
        same traced values)."""
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in self._SYNC_METHODS):
                if sub.func.attr == "block_until_ready" or \
                        self._refs_tainted(sub.func.value, tainted):
                    yield sub, f".{sub.func.attr}()"
                continue
            name = d if d in self._HOST_FUNCS else (
                sub.func.id if isinstance(sub.func, ast.Name)
                and sub.func.id in self._HOST_CASTS else None)
            if name and any(self._refs_tainted(a, tainted)
                            for a in sub.args):
                yield sub, f"{name}()"

    # -- project pass: follow the callgraph out of jit entries ------------

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        reported: Set[Tuple[str, int]] = set()
        for fid in sorted(project.calls.traced_entries):
            entry = project.calls.functions.get(fid)
            if entry is None:
                continue
            mg = project.graphs[entry.module]
            if not (_is_drynx_pkg(mg.info)
                    and _in_scope(mg.info, "crypto", "parallel")):
                continue
            # decorator-marked entries' own bodies are covered lexically by
            # run(); wrapper-marked ones (g = jax.jit(f), bucketed(f)) are
            # not, so include their bodies here.
            decorated = any(entry.node is f for f in mg.info.traced_functions)
            chain = [chain_hop(mg.info.relpath, entry.node.lineno,
                               entry.qual)]
            anchors = ((mg.info.relpath, entry.node.lineno),)
            yield from self._walk_entry(
                project, entry, frozenset(self._tainted_names(entry.node)),
                chain, anchors, include_body=not decorated,
                reported=reported, visited=set(), depth=0)

    def _walk_entry(self, project: ProjectInfo, fn: FuncNode,
                    tainted_params: FrozenSet[str], chain: List[str],
                    anchors: Tuple[Tuple[str, int], ...], include_body: bool,
                    reported: Set[Tuple[str, int]],
                    visited: Set[Tuple[str, FrozenSet[str]]], depth: int,
                    ) -> Iterator[Finding]:
        key = (fn.fid, tainted_params)
        if key in visited or depth > self._MAX_DEPTH:
            return
        visited.add(key)
        mg = project.graphs[fn.module]
        tainted = self._propagate(fn.node, set(tainted_params))
        if include_body:
            for sub, what in self._body_syncs(fn.node, tainted):
                site = (mg.info.relpath, sub.lineno)
                if site in reported:
                    continue
                reported.add(site)
                full = chain + [chain_hop(mg.info.relpath, sub.lineno, what)]
                yield self.finding(
                    mg.info, sub,
                    f"'{what}' on a traced value in '{fn.qual}', reachable "
                    f"from jit entry '{chain[0].rsplit(':', 1)[-1]}' — "
                    f"forces a device->host sync inside the trace",
                    call_chain=full, anchors=anchors)
        for site in project.calls.callees(fn.fid):
            callee = project.calls.functions.get(site.callee)
            if callee is None or callee.fid in project.calls.traced_entries:
                continue  # traced callees are analyzed as their own entries
            passed = self._callee_taint(site.node, callee.node, tainted)
            if not passed:
                continue
            hop = chain_hop(mg.info.relpath, site.lineno, callee.qual)
            yield from self._walk_entry(
                project, callee, frozenset(passed), chain + [hop], anchors,
                include_body=True, reported=reported, visited=visited,
                depth=depth + 1)

    def _callee_taint(self, call: ast.Call, callee: ast.AST,
                      tainted: Set[str]) -> Set[str]:
        """Callee parameter names that receive tainted arguments."""
        args = callee.args
        params = [a.arg for a in (args.posonlyargs + args.args)
                  if a.arg != "self"]
        static = self._static_args(callee)
        out: Set[str] = set()
        splat = False
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                splat = splat or self._refs_tainted(a, tainted)
                continue
            if self._refs_tainted(a, tainted) and i < len(params):
                out.add(params[i])
        for kw in call.keywords:
            if kw.arg is None:
                splat = splat or self._refs_tainted(kw.value, tainted)
            elif self._refs_tainted(kw.value, tainted):
                out.add(kw.arg)
        if splat:
            out.update(params)
        return out - static

    @staticmethod
    def _static_args(fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) \
                                and isinstance(n.value, str):
                            out.add(n.value)
        return out

    def _tainted_names(self, fn: ast.AST) -> Set[str]:
        static = self._static_args(fn)
        args = fn.args
        start = {a.arg for a in
                 (args.posonlyargs + args.args + args.kwonlyargs)
                 if a.arg not in static and a.arg != "self"}
        return self._propagate(fn, start)

    def _propagate(self, fn: ast.AST, tainted: Set[str]) -> Set[str]:
        # one forward pass of simple propagation through assignments
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) \
                    and self._refs_tainted(stmt.value, tainted):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        return tainted

    @classmethod
    def _refs_tainted(cls, node: ast.AST, tainted: Set[str]) -> bool:
        # x.shape / x.ndim / x.dtype / x.size are host-side metadata: code
        # like `int(np.prod(x.shape[:3]))` never syncs the device buffer.
        def walk(n: ast.AST) -> bool:
            if isinstance(n, ast.Attribute) and n.attr in cls._SHAPE_ATTRS:
                return False
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            return any(walk(c) for c in ast.iter_child_nodes(n))
        return walk(node)


# ---------------------------------------------------------------------------
@register
class EnvReadIntoTrace(Rule):
    """`X = os.environ[...]` at import time, with X read inside jit-traced
    code, wires process environment into compiled artifacts: two processes
    with different env silently compute different programs from the same
    call site, and tests that mutate the env (or monkeypatch X) leave stale
    traces behind. Thread such config through as explicit (static)
    arguments instead. Fires at the assignment; the use sites are covered
    by jit-global-capture."""

    id = "env-read-into-trace"
    summary = ("import-time os.environ read whose value flows into "
               "jit-traced code")

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        used_in_trace: Dict[str, List[str]] = {}
        for fn in mod.traced_functions:
            local = _local_bindings(fn)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mod.env_derived
                        and sub.id not in local):
                    used_in_trace.setdefault(sub.id, []).append(fn.name)
        for name, fns in sorted(used_in_trace.items()):
            node = mod.env_derived[name]
            yield self.finding(
                mod, node,
                f"import-time environment read bound to '{name}' is "
                f"captured by jit-traced code ({', '.join(sorted(set(fns)))})"
            )
        # direct env reads lexically inside traced functions
        for fn in mod.traced_functions:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Attribute, ast.Call)):
                    d = _dotted(sub if isinstance(sub, ast.Attribute)
                                else sub.func)
                    if d and (d.startswith("os.environ") or d == "os.getenv"):
                        yield self.finding(
                            mod, sub,
                            f"os.environ read inside jit-traced "
                            f"'{fn.name}' is evaluated once at trace time")
                        break


# ---------------------------------------------------------------------------
@register
class SecretLogging(Rule):
    """Secret-key material (ElGamal secrets, Schnorr nonces) must never hit
    a log stream or stdout: logs cross trust boundaries (CI artifacts,
    shared hosts) that the ciphertexts are specifically protecting the data
    from. Flags print()/log.*/logging calls whose arguments reference a
    secret-shaped identifier.

    Kept as the *seed list* for the dataflow successor
    ``secret-flow-to-sink`` (which tracks actual values from keygen/nonce
    definition sites instead of matching names): where both fire on the
    same line, the dataflow finding wins and this one is absorbed."""

    id = "secret-logging"
    seed_only = True
    summary = "print/log call referencing secret-key material"

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            if not self._is_log_sink(sub):
                continue
            ident = self._secret_ident(sub)
            if ident:
                yield self.finding(
                    mod, sub,
                    f"'{ident}' looks like secret-key material flowing "
                    f"into a log/print sink")

    @staticmethod
    def _is_log_sink(call: ast.Call) -> bool:
        if isinstance(call.func, ast.Name) and call.func.id == "print":
            return True
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _LOG_METHODS:
            root = call.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) and root.id in _LOGGER_NAMES
        return False

    @classmethod
    def _secret_ident(cls, call: ast.Call) -> str:
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for n in ast.walk(arg):
                name = None
                if isinstance(n, ast.Name):
                    name = n.id
                elif isinstance(n, ast.Attribute):
                    name = n.attr
                if name and _SECRET_RE.search(name):
                    return name
        return ""


# ---------------------------------------------------------------------------
@register
class HardcodedTimeout(Rule):
    """Retry/timeout numbers scattered as bare literals made failure
    behavior unauditable: nobody could say how long a dead DP stalls a
    survey without reading every call site (the pre-resilience state of
    node.py/api.py/service.py). Every such number must be a named constant
    in drynx_tpu/resilience/policy.py — that module is the single place
    the rule exempts. Fires on: timeout=/retries= keyword literals,
    timeout-ish parameter defaults, sleep/wait calls with literal
    durations, and `.get("...timeout...", <literal>)` fallbacks.

    The network plane (PR 10) added a second family of tuning knobs with
    the same auditability problem: fan-out worker counts and connection-
    pool bounds (workers=/max_workers=/max_idle=/pool_size=). A bare
    ``max_workers=8`` decides how hard a survey hammers a roster exactly
    like a bare ``timeout=900`` decides how long it stalls — both live as
    named constants in resilience/policy.py (FAN_OUT_WORKERS,
    CONN_POOL_MAX_IDLE).

    The tree overlay (PR 11) added a third family: tree fanout and pool
    caps (fanout=/tree_fanout=/pool_max=), surfaced as the
    DRYNX_TREE_FANOUT / DRYNX_TOPOLOGY / DRYNX_CONN_POOL_MAX env knobs.
    A literal ``fanout=8`` shapes dispatch depth — and a numeric literal
    fallback in ``.get("DRYNX_CONN_POOL_MAX", 1024)`` silently forks the
    default away from policy — so both route through TREE_FANOUT_MIN/MAX
    and CONN_POOL_MAX instead (env fallbacks stay string-typed, which
    this rule ignores by design).

    Saturation serving (PR 12) added the admission-control family:
    verify-worker pool width, per-tenant quotas, shed thresholds, and
    retry-after hint bounds (workers=/quota=/shed_fraction=/
    retry_after_*=), surfaced as the DRYNX_VERIFY_WORKERS /
    DRYNX_TENANT_QUOTA / DRYNX_SHED_FRACTION env knobs. A literal
    ``tenant_quota=8`` decides when a tenant starts seeing typed
    rejections exactly like a bare timeout decides when a caller gives
    up — the defaults live in policy.py (VERIFY_WORKERS, TENANT_QUOTA,
    SHED_FRACTION, SHED_RETRY_MIN_S/MAX_S).

    Streaming surveys (PR 18) added the window/pane/epsilon family:
    pane width, window span, per-advance privacy spend and slide pacing
    (pane_width=/window_panes=/epsilon_budget=/epsilon_per_advance=/
    slide_pacing=), surfaced as the DRYNX_PANE_WIDTH /
    DRYNX_STREAM_WINDOW / DRYNX_EPSILON_BUDGET /
    DRYNX_EPSILON_PER_ADVANCE / DRYNX_SLIDE_PACING env knobs. A literal
    ``epsilon_budget=1.0`` is a PRIVACY bound — a fork of that default
    away from policy is strictly worse than an unauditable timeout —
    and a literal ``pane_width=4096`` silently re-shapes every proof
    blob a stream caches; the defaults live in policy.py (PANE_WIDTH,
    STREAM_WINDOW_PANES, EPSILON_BUDGET, EPSILON_PER_ADVANCE,
    SLIDE_PACING_S)."""

    id = "hardcoded-timeout"
    summary = ("bare numeric timeout/retry/worker-pool literal outside "
               "drynx_tpu/resilience/ — name it in resilience/policy.py")

    _SLEEPY = {"sleep", "wait", "join"}

    @staticmethod
    def _timeoutish(name: str) -> bool:
        n = name.lower()
        return ("timeout" in n or n == "retries" or n.endswith("_retries")
                or n.endswith("deadline")
                or n == "workers" or n.endswith("_workers")
                or n == "max_idle" or n.endswith("_idle")
                or n == "pool_size" or n.endswith("_pool_size")
                or n == "fanout" or n.endswith("_fanout")
                or n == "pool_max" or n.endswith("_pool_max")
                or n == "quota" or n.endswith("_quota")
                # NB: substring "shed" would also match "finished"
                or n == "shed" or n.startswith("shed_")
                or n.endswith("_shed") or "shed_fraction" in n
                or "retry_after" in n
                # streaming knobs: substring matches so the env-var forms
                # (DRYNX_PANE_WIDTH, DRYNX_STREAM_WINDOW, ...) fire in
                # .get() fallbacks too; bare "epsilon" stays unmatched —
                # it is a common math variable name
                or "pane_width" in n
                or "window_panes" in n or "stream_window" in n
                or "epsilon_budget" in n or "epsilon_per_advance" in n
                or n.endswith("_epsilon")
                or "slide_pacing" in n)

    @staticmethod
    def _nonzero_num(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, (int, float))
                and not isinstance(node.value, bool)
                and node.value != 0)

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not _is_drynx_pkg(mod) or _in_scope(mod, "resilience"):
            return
        for sub in ast.walk(mod.tree):
            if isinstance(sub, ast.Call):
                yield from self._check_call(mod, sub)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_defaults(mod, sub)

    def _check_call(self, mod: ModuleInfo, call: ast.Call):
        for kw in call.keywords:
            if kw.arg and self._timeoutish(kw.arg) \
                    and self._nonzero_num(kw.value):
                yield self.finding(
                    mod, call,
                    f"literal {kw.arg}={kw.value.value!r} — use a named "
                    f"constant from drynx_tpu/resilience/policy.py")
                return
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in self._SLEEPY and call.args \
                    and self._nonzero_num(call.args[0]):
                yield self.finding(
                    mod, call,
                    f"literal duration in '.{call.func.attr}"
                    f"({call.args[0].value!r})' — use a named constant "
                    f"from drynx_tpu/resilience/policy.py")
                return
            if (call.func.attr == "get" and len(call.args) >= 2
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                    and self._timeoutish(call.args[0].value)
                    and self._nonzero_num(call.args[1])):
                yield self.finding(
                    mod, call,
                    f"literal fallback in .get({call.args[0].value!r}, "
                    f"{call.args[1].value!r}) — use a named constant from "
                    f"drynx_tpu/resilience/policy.py")

    def _check_defaults(self, mod: ModuleInfo, fn):
        args = fn.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if self._timeoutish(a.arg) and self._nonzero_num(d):
                yield self.finding(
                    mod, d,
                    f"literal default {a.arg}={d.value!r} in '{fn.name}' — "
                    f"use a named constant from "
                    f"drynx_tpu/resilience/policy.py")
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and self._timeoutish(a.arg) \
                    and self._nonzero_num(d):
                yield self.finding(
                    mod, d,
                    f"literal default {a.arg}={d.value!r} in '{fn.name}' — "
                    f"use a named constant from "
                    f"drynx_tpu/resilience/policy.py")


# ---------------------------------------------------------------------------
@register
class ThreadTrace(Rule):
    """First-touch jit tracing from a worker thread is the r05 segfault
    class: partial_eval recurses roughly one C frame per traced equation,
    the pairing kernels trace >10k equations, and non-main threads get half
    the main thread's C stack — the process dies in the interpreter with no
    Python traceback. All first-touch tracing must happen on the main
    thread (the compilecache warmup) or under the shared compile lock.
    Flags `threading.Thread(target=f)` where `f` is a function defined in
    this module whose body calls a trace entry — a jit/pallas-decorated
    function, a `bucketed(...)`/`jax.jit(...)`-bound name, or a bucketed-op
    attribute — outside a `with <...lock...>:` block."""

    id = "thread-trace"
    summary = ("threading.Thread target reaches a jit/trace entry point "
               "outside a compile lock — first-touch tracing off the main "
               "thread can overflow the worker's C stack")

    _ENTRY_FACTORIES = {"bucketed", "jit", "pjit"}

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        entries = self._trace_entry_names(mod)
        if not entries:
            return
        defs = {f.name: f for f in mod.functions}
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d not in ("threading.Thread", "Thread"):
                continue
            target = next((kw.value for kw in sub.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            if isinstance(target, ast.Lambda):
                hit = self._unlocked_entry_call(target.body, entries,
                                                under_lock=False)
                if hit:
                    yield self.finding(
                        mod, sub,
                        f"Thread target lambda calls trace entry "
                        f"'{hit}' — first-touch tracing off the main "
                        f"thread (warm it via drynx_tpu.compilecache or "
                        f"wrap in the compile lock)")
                continue
            if not isinstance(target, ast.Name) or target.id not in defs:
                continue  # dynamic/imported target: out of static reach
            fn = defs[target.id]
            hit = None
            for stmt in fn.body:
                hit = self._unlocked_entry_call(stmt, entries,
                                                under_lock=False)
                if hit:
                    break
            if hit:
                yield self.finding(
                    mod, sub,
                    f"Thread target '{fn.name}' calls trace entry "
                    f"'{hit}' outside a compile lock — first-touch "
                    f"tracing off the main thread (warm it via "
                    f"drynx_tpu.compilecache or wrap in the compile lock)")

    def _trace_entry_names(self, mod: ModuleInfo) -> Set[str]:
        names = {f.name for f in mod.traced_functions}
        # names bound to bucketed(...)/jax.jit(...) factory calls anywhere
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Assign) \
                    or not isinstance(sub.value, ast.Call):
                continue
            d = _dotted(sub.value.func) or ""
            if d.split(".")[-1] in self._ENTRY_FACTORIES:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    @classmethod
    def _unlocked_entry_call(cls, node: ast.AST, entries: Set[str],
                             under_lock: bool) -> Optional[str]:
        """Name of the first trace-entry call NOT under a lock-ish `with`,
        else None. Recursion tracks `with ...lock...:` ancestry — ast.walk
        can't, it loses parents."""
        if isinstance(node, ast.With):
            locked = under_lock or any(
                "lock" in (_dotted(item.context_expr) or "").lower()
                for item in node.items)
            for child in node.body:
                hit = cls._unlocked_entry_call(child, entries, locked)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Call) and not under_lock:
            d = _dotted(node.func)
            leaf = (d or "").split(".")[-1]
            if leaf in entries:
                return leaf
        for child in ast.iter_child_nodes(node):
            hit = cls._unlocked_entry_call(child, entries, under_lock)
            if hit:
                return hit
        return None


# ---------------------------------------------------------------------------
@register
class CrossModuleFlagCapture(ProjectRule):
    """The import-graph version of jit-global-capture: a flag assigned
    from os.environ or rebound at runtime *anywhere* in the project, then
    imported (through any number of re-export hops) or read via a module
    alias, taints every jit/pallas body that reads it — the read is
    evaluated once at trace time and frozen into the cache. This replaces
    the old KNOWN_MUTABLE_FLAGS allowlist with real propagation: only
    flags that are actually mutable at their definition fire."""

    id = "cross-module-flag-capture"
    summary = ("jit/pallas-traced code reads a mutable flag defined in "
               "another module (env-derived or rebound) — frozen into the "
               "trace cache")

    _REASONS = {"env": "assigned from os.environ",
                "rebound": "rebound at runtime",
                "rebound-externally": "attribute-rebound from another module"}

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        for dotted in sorted(project.graphs):
            mg = project.graphs[dotted]
            info = mg.info
            if not info.traced_functions or not project.in_focus(
                    info.relpath):
                continue
            for fn in info.traced_functions:
                local = _local_bindings(fn)
                seen: Set[str] = set()
                for sub in ast.walk(fn):
                    hit = self._mutable_read(project, dotted, local, sub)
                    if hit is None:
                        continue
                    token, origin = hit
                    if token in seen or origin.module == dotted:
                        continue
                    seen.add(token)
                    chain = [chain_hop(info.relpath, sub.lineno, token)]
                    chain += [chain_hop(rel, ln, "import")
                              for rel, ln in origin.hops]
                    chain.append(chain_hop(
                        origin.relpath, origin.lineno,
                        f"{origin.name} ({self._REASONS[origin.reason]})"))
                    yield self.finding(
                        info, sub,
                        f"trace-time capture of mutable flag '{token}' in "
                        f"'{fn.name}' — defined in {origin.module} and "
                        f"{self._REASONS[origin.reason]}; the value is "
                        f"frozen into the jit/pallas trace cache",
                        call_chain=chain,
                        anchors=((origin.relpath, origin.lineno),))

    @staticmethod
    def _mutable_read(project, dotted, local, sub):
        """(rendered token, FlagOrigin) when `sub` is a Load of a mutable
        cross-module flag, else None."""
        mg = project.graphs[dotted]
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id not in local and sub.id in mg.froms:
            origin = project.flag_origin(dotted, sub.id)
            if origin is not None:
                return sub.id, origin
        elif isinstance(sub, ast.Attribute) and isinstance(sub.ctx, ast.Load):
            d = _dotted(sub)
            if d and d.count(".") == 1:
                alias, attr = d.split(".")
                if alias not in local:
                    target = project.imports.module_for_alias(dotted, alias)
                    if target is not None and target != dotted \
                            and target in project.graphs:
                        origin = project.flag_origin(target, attr)
                        if origin is not None:
                            return d, origin
        return None


# ---------------------------------------------------------------------------
_UINT32_DTYPES = {"jnp.uint32", "np.uint32", "numpy.uint32",
                  "jax.numpy.uint32"}


def _is_uint32_dtype(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) and expr.value == "uint32":
        return True
    return _dotted(expr) in _UINT32_DTYPES


@register
class PallasOperandDtype(ProjectRule):
    """Mosaic kernels in this repo are exact uint32 limb arithmetic with
    x64 disabled: a pallas_call operand that arrives as weak int32/float32
    (or x64-demoted int64) silently truncates limbs inside the kernel.
    Every ``pl.pallas_call(...)(operands...)`` operand must be *provably*
    uint32: a literal dtype at the constructor, a dtype-preserving chain
    (reshape/transpose/indexing/uint32 arithmetic) rooted at one, a
    callgraph hop through a helper whose returns pin uint32 (e.g.
    ``_pad_lanes``), or — for operands that are function parameters — a
    reverse hop proving every project call site passes uint32."""

    id = "pallas-operand-dtype"
    summary = ("pl.pallas_call operand not provably uint32 — weak/implicit "
               "dtypes miscompile the Mosaic limb kernels")

    _CTOR_DTYPE_POS = {"array": 1, "asarray": 1, "zeros": 1, "ones": 1,
                       "empty": 1, "full": 2}
    _ARRAY_NS = {"jnp", "np", "numpy", "jax.numpy"}
    # dtype(out) == dtype(arg0)
    _PRESERVING_FUNCS = {"transpose", "reshape", "concatenate", "stack",
                         "broadcast_to", "tile", "repeat", "flip", "roll",
                         "moveaxis", "swapaxes", "expand_dims", "squeeze",
                         "ravel", "pad", "zeros_like", "ones_like",
                         "empty_like", "full_like", "flipud", "rot90"}
    _PRESERVING_METHODS = {"reshape", "transpose", "ravel", "squeeze",
                           "swapaxes", "copy", "flatten"}
    _MAX_DEPTH = 8

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        self._pins_memo: Dict[Tuple[str, Optional[int]], bool] = {}
        self._ctx_memo: Dict[Tuple[str, int], tuple] = {}
        for dotted in sorted(project.graphs):
            mg = project.graphs[dotted]
            info = mg.info
            if not (_is_drynx_pkg(info)
                    and _in_scope(info, "crypto", "parallel")
                    and project.in_focus(info.relpath)):
                continue
            for qual in sorted(mg.functions):
                fn = mg.functions[qual]
                for call in _own_calls(fn.node):
                    if not (isinstance(call.func, ast.Call)
                            and (_dotted(call.func.func) or ""
                                 ).split(".")[-1] == "pallas_call"):
                        continue
                    for i, op in enumerate(call.args):
                        trail = [chain_hop(info.relpath, call.lineno,
                                           f"pallas_call operand {i}")]
                        if self._prove(project, fn, op, trail, 0, set()):
                            continue
                        try:
                            src = ast.unparse(op)
                        except Exception:
                            src = "<operand>"
                        if len(src) > 48:
                            src = src[:45] + "..."
                        yield self.finding(
                            info, op,
                            f"pallas_call operand {i} ('{src}') in "
                            f"'{fn.qual}' is not provably uint32 — coerce "
                            f"with jnp.asarray(..., jnp.uint32) or pin the "
                            f"dtype in the producing helper",
                            call_chain=trail[:6],
                            anchors=((info.relpath, call.lineno),))

    # -- the prover -------------------------------------------------------

    def _ctx(self, project: ProjectInfo, fn: FuncNode):
        """(assigns, params, sites) for a function: last simple assignment
        per name, parameter names, and call-node -> callee-fid map."""
        key = (fn.fid, id(fn.node))
        cached = self._ctx_memo.get(key)
        if cached is not None:
            return cached
        assigns: Dict[str, tuple] = {}
        for stmt in ast.walk(fn.node):
            if not isinstance(stmt, ast.Assign):
                continue
            t = stmt.targets[0] if len(stmt.targets) == 1 else None
            if isinstance(t, ast.Name):
                assigns[t.id] = ("expr", stmt.value, stmt.lineno)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for idx, el in enumerate(t.elts):
                    if isinstance(el, ast.Name):
                        assigns[el.id] = ("unpack", idx, stmt.value,
                                          stmt.lineno)
        a = fn.node.args
        params = [x.arg for x in (a.posonlyargs + a.args) if x.arg != "self"]
        sites = {id(s.node): s.callee
                 for s in project.calls.callees(fn.fid)}
        self._ctx_memo[key] = (assigns, params, sites)
        return assigns, params, sites

    def _prove(self, project: ProjectInfo, fn: FuncNode, expr: ast.AST,
               trail: List[str], depth: int, visiting: Set[tuple]) -> bool:
        if depth > self._MAX_DEPTH:
            return False
        mg = project.graphs[fn.module]
        rel = mg.info.relpath
        assigns, params, sites = self._ctx(project, fn)

        if isinstance(expr, ast.Starred):
            return self._prove(project, fn, expr.value, trail, depth,
                               visiting)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self._prove(project, fn, e, trail, depth + 1,
                                   visiting) for e in expr.elts)
        if isinstance(expr, ast.Subscript):
            return self._prove(project, fn, expr.value, trail, depth + 1,
                               visiting)
        if isinstance(expr, ast.IfExp):
            return (self._prove(project, fn, expr.body, trail, depth + 1,
                                visiting)
                    and self._prove(project, fn, expr.orelse, trail,
                                    depth + 1, visiting))
        if isinstance(expr, ast.BinOp):
            # uint32 op uint32 stays uint32; weak python int literals do
            # not promote it under x64-off
            ops = [expr.left, expr.right]
            arr = [o for o in ops if not (isinstance(o, ast.Constant)
                                          and isinstance(o.value, int))]
            return bool(arr) and all(
                self._prove(project, fn, o, trail, depth + 1, visiting)
                for o in arr)
        if isinstance(expr, ast.Attribute):
            if expr.attr == "T":
                return self._prove(project, fn, expr.value, trail,
                                   depth + 1, visiting)
            return False
        if isinstance(expr, ast.Name):
            got = assigns.get(expr.id)
            if got is not None:
                if got[0] == "expr":
                    trail.append(chain_hop(rel, got[2],
                                           f"{expr.id} = ..."))
                    return self._prove(project, fn, got[1], trail,
                                       depth + 1, visiting)
                _, idx, value, lineno = got
                trail.append(chain_hop(rel, lineno,
                                       f"{expr.id} = ...[{idx}]"))
                return self._prove_unpack(project, fn, value, idx, trail,
                                          depth + 1, visiting)
            if expr.id in params:
                return self._param_proven(project, fn, expr.id, trail,
                                          depth + 1, visiting)
            return self._module_const_proven(project, fn.module, expr.id,
                                             trail, depth + 1, visiting)
        if isinstance(expr, ast.Call):
            return self._prove_call(project, fn, expr, trail, depth,
                                    visiting, sites)
        return False

    def _prove_call(self, project, fn, call, trail, depth, visiting, sites):
        mg = project.graphs[fn.module]
        rel = mg.info.relpath
        d = _dotted(call.func) or ""
        leaf = d.split(".")[-1]
        root = d.rsplit(".", 1)[0] if "." in d else ""
        if isinstance(call.func, ast.Attribute) and root not in self._ARRAY_NS:
            # method call on an expression
            if call.func.attr == "astype":
                if call.args and _is_uint32_dtype(call.args[0]):
                    trail.append(chain_hop(rel, call.lineno,
                                           ".astype(uint32)"))
                    return True
                return False
            if call.func.attr in self._PRESERVING_METHODS:
                return self._prove(project, fn, call.func.value, trail,
                                   depth + 1, visiting)
        if root in self._ARRAY_NS:
            dtype = next((kw.value for kw in call.keywords
                          if kw.arg == "dtype"), None)
            pos = self._CTOR_DTYPE_POS.get(leaf)
            if dtype is None and pos is not None and len(call.args) > pos:
                dtype = call.args[pos]
            if dtype is not None:
                if _is_uint32_dtype(dtype):
                    trail.append(chain_hop(rel, call.lineno,
                                           f"{d}(dtype=uint32)"))
                    return True
                return False
            if leaf in ("array", "asarray") and call.args:
                # no dtype: preserves the input's dtype
                return self._prove(project, fn, call.args[0], trail,
                                   depth + 1, visiting)
            if leaf in self._PRESERVING_FUNCS and call.args:
                return self._prove(project, fn, call.args[0], trail,
                                   depth + 1, visiting)
            return False
        # callgraph hop: a project function whose returns pin uint32
        callee_fid = sites.get(id(call))
        if callee_fid is not None:
            callee = project.calls.functions[callee_fid]
            if self._fn_pins(project, callee, None, depth + 1, visiting):
                trail.append(chain_hop(
                    project.graphs[callee.module].info.relpath,
                    callee.node.lineno, f"{callee.qual}() pins uint32"))
                return True
        return False

    def _prove_unpack(self, project, fn, value, idx, trail, depth, visiting):
        """`a, b = <value>` — prove element idx of the rhs."""
        if isinstance(value, (ast.Tuple, ast.List)):
            if idx < len(value.elts):
                return self._prove(project, fn, value.elts[idx], trail,
                                   depth, visiting)
            return False
        if isinstance(value, ast.Call):
            _, _, sites = self._ctx(project, fn)
            callee_fid = sites.get(id(value))
            if callee_fid is not None:
                callee = project.calls.functions[callee_fid]
                if self._fn_pins(project, callee, idx, depth, visiting):
                    trail.append(chain_hop(
                        project.graphs[callee.module].info.relpath,
                        callee.node.lineno,
                        f"{callee.qual}()[{idx}] pins uint32"))
                    return True
        return False

    def _fn_pins(self, project, fn: FuncNode, idx, depth, visiting) -> bool:
        """True when every return of fn is provably uint32 (element idx of
        tuple returns when idx is not None), regardless of its inputs."""
        key = (fn.fid, idx)
        if key in self._pins_memo:
            return self._pins_memo[key]
        vkey = ("pins", fn.fid, idx)
        if vkey in visiting or depth > self._MAX_DEPTH:
            return False
        visiting.add(vkey)
        returns = [r.value for r in _own_returns(fn.node)
                   if r.value is not None]
        ok = bool(returns)
        for r in returns:
            if idx is not None:
                if isinstance(r, (ast.Tuple, ast.List)) and idx < len(r.elts):
                    ok = ok and self._prove(project, fn, r.elts[idx],
                                            [], depth + 1, visiting)
                else:
                    ok = ok and self._prove_unpack(project, fn, r, idx,
                                                   [], depth + 1, visiting)
            else:
                ok = ok and self._prove(project, fn, r, [], depth + 1,
                                        visiting)
            if not ok:
                break
        visiting.discard(vkey)
        self._pins_memo[key] = ok
        return ok

    def _param_proven(self, project, fn: FuncNode, pname, trail, depth,
                      visiting) -> bool:
        """Reverse hop: every project call site of fn passes a provably
        uint32 value for parameter pname."""
        vkey = ("param", fn.fid, pname)
        if vkey in visiting or depth > self._MAX_DEPTH:
            return False
        visiting.add(vkey)
        try:
            a = fn.node.args
            pos_params = [x.arg for x in (a.posonlyargs + a.args)]
            pidx = pos_params.index(pname) if pname in pos_params else None
            callers = [(cfid, s) for cfid, ss in project.calls.calls.items()
                       for s in ss if s.callee == fn.fid]
            if not callers:
                return False
            for cfid, site in callers:
                caller = project.calls.functions[cfid]
                arg = next((kw.value for kw in site.node.keywords
                            if kw.arg == pname), None)
                if arg is None and pidx is not None \
                        and pidx < len(site.node.args):
                    arg = site.node.args[pidx]
                if arg is None:
                    # default value used
                    ndef = len(a.defaults)
                    di = pidx - (len(pos_params) - ndef) \
                        if pidx is not None else -1
                    if not (0 <= di < ndef and self._prove(
                            project, fn, a.defaults[di], [], depth + 1,
                            visiting)):
                        return False
                    continue
                if isinstance(arg, ast.Starred) or not self._prove(
                        project, caller, arg, [], depth + 1, visiting):
                    return False
            trail.append(chain_hop(
                project.graphs[fn.module].info.relpath, fn.node.lineno,
                f"{fn.qual}({pname}) uint32 at all "
                f"{len(callers)} call site(s)"))
            return True
        finally:
            visiting.discard(vkey)

    def _module_const_proven(self, project, module, name, trail, depth,
                             visiting) -> bool:
        """Module-level constant (possibly imported) provably uint32."""
        vkey = ("mod", module, name)
        if vkey in visiting or depth > self._MAX_DEPTH:
            return False
        visiting.add(vkey)
        try:
            def_mod, def_name, _hops = project.imports.resolve(module, name)
            mg = project.graphs.get(def_mod)
            if mg is None or not def_name:
                return False
            node = mg.info.env_derived.get(def_name)
            if node is not None:
                return False  # env-derived is never a provable dtype
            assigns = mg.info.module_assigns.get(def_name)
            if not assigns or len(assigns) != 1:
                return False
            ok = self._prove_module_expr(project, mg, assigns[0].value,
                                         trail, depth + 1, visiting)
            if ok:
                trail.append(chain_hop(mg.info.relpath, assigns[0].lineno,
                                       f"{def_name} pins uint32"))
            return ok
        finally:
            visiting.discard(vkey)

    def _prove_module_expr(self, project, mg, expr, trail, depth,
                           visiting) -> bool:
        """Prove a module-level expression: no params, no local assigns —
        reuse the ctor/preserving logic via a synthetic module-scope
        FuncNode whose body is empty."""
        shim = FuncNode(mg.dotted, "<module>",
                        ast.parse("def _m():\n    pass").body[0])
        return self._prove(project, shim, expr, trail, depth, visiting)


# ---------------------------------------------------------------------------
# Value-level dataflow rules (drynx_tpu/analysis/dataflow.py): both are
# thin wrappers over one shared engine run — dataflow_for() memoizes on a
# content-hash fingerprint of the whole project, so the abstract
# interpreter executes once per tree version no matter how many rules (or
# repeated analyze_project calls) consume it.

def _raw_to_finding(rule_id: str, project: ProjectInfo, raw) -> Finding:
    mod = project.modules.get(raw.file)
    return Finding(rule=rule_id, file=raw.file, line=raw.line,
                   message=raw.message,
                   line_text=mod.line_text(raw.line) if mod else "",
                   call_chain=raw.chain, anchors=raw.anchors)


@register
class CiphertextDtypeLaunder(ProjectRule):
    """A ciphertext limb array that was provably uint32 loses the dtype
    (``astype(float32)``, float-constant arithmetic, true division —
    often hidden inside a pytree flatten/transform/unflatten round trip)
    and then reaches a pallas/jit kernel or a serialization point. The
    kernels compute exact Montgomery limb arithmetic: one weak promotion
    silently corrupts carries and changes the proof transcript. The
    finding renders the whole value-flow chain (pin site, laundering hop,
    sink) and is suppressible at any hop; re-pinning with
    ``jnp.asarray(..., jnp.uint32)`` at the boundary clears the taint,
    and ``# drynx: declassify[dtype]`` marks deliberate byte-packing."""

    id = "ciphertext-dtype-launder"
    engine = "dataflow"
    summary = ("uint32 limb value reaches a pallas/jit kernel or "
               "serialization after a dtype-laundering hop (value "
               "dataflow)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .dataflow import dataflow_for
        df = dataflow_for(project, getattr(project, "focus", None))
        for raw in df.dtype_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class SecretFlowToSink(ProjectRule):
    """The dataflow successor to the regex ``secret-logging`` rule:
    secrecy is seeded at *definition sites* — ``keygen()`` (ElGamal
    secret), ``secrets.randbelow()`` (Schnorr nonce), DP cleartext loads —
    and propagated per value through assignments, tuples, dataclass
    fields, f-strings and interprocedural summaries. It fires when a
    secret value reaches ``print``/``log.*``/TOML-or-serialized
    output/exception messages/transport ``send`` calls, with the full
    value-flow chain rendered. Where the regex rule flags the same line,
    this finding absorbs it (one leak, one report). Deliberate key-store
    writes are ``noqa``'d with a reason; protocol outputs that are public
    by construction are marked ``# drynx: declassify[secret]`` at the
    defining assignment."""

    id = "secret-flow-to-sink"
    engine = "dataflow"
    summary = ("secret value (keygen/nonce/DP cleartext) reaches a "
               "log/print/serialization/exception/send sink (value "
               "dataflow)")
    absorbs = ("secret-logging",)

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .dataflow import dataflow_for
        df = dataflow_for(project, getattr(project, "focus", None))
        for raw in df.secret_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


# ---------------------------------------------------------------------------
# Concurrency rules (drynx_tpu/analysis/concurrency.py): three thin
# wrappers over one shared engine run — concurrency_for() memoizes on the
# same content-hash fingerprint as the dataflow engine, so thread-entry
# discovery, the interprocedural lock-set walk and the lock-order graph
# are computed once per tree version for all three rules (and for the
# DRYNX_LOCK_TRACE runtime cross-check).

@register
class UnguardedSharedMutation(ProjectRule):
    """A module global, class attribute or shared container is mutated
    from two concurrent contexts (thread targets, executor submissions,
    ``fan_out`` worker callables, timers — or one multi-instance entry
    racing with itself) and the lock sets provably held at the mutation
    sites share no common lock. That is the textbook data race: lost
    counter increments, torn dict updates, iteration-during-mutation.
    The finding names every mutating context and the locks each holds;
    it is suppressible at the mutation site *or* at the thread entry
    (dual anchors). Fix by guarding all mutating paths with one named
    lock (see ``resilience.policy.named_lock``)."""

    id = "unguarded-shared-mutation"
    engine = "concurrency"
    summary = ("shared state mutated from multiple thread contexts with "
               "no common lock held (interprocedural lock-set analysis)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .concurrency import concurrency_for
        cc = concurrency_for(project)
        for raw in cc.unguarded_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class LockOrderInversion(ProjectRule):
    """Two locks are acquired in opposite nesting orders on different
    code paths — the classic ABBA deadlock: each thread holds one lock
    and blocks forever waiting for the other. The engine records every
    nested acquisition (``with`` or bare ``acquire()``) per thread entry,
    unions the edges into a lock-order graph over the stable diagnostic
    lock names, and reports each cycle once with the full acquisition
    chain rendered as a SARIF codeFlow (one threadFlow location per
    hop). Re-entering an ``RLock`` already held is not an edge. Fix by
    picking one global order (document it next to the named_lock defs)
    or collapsing to a single lock."""

    id = "lock-order-inversion"
    engine = "concurrency"
    summary = ("named locks acquired in conflicting order on different "
               "paths — ABBA deadlock cycle in the lock-order graph")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .concurrency import concurrency_for
        cc = concurrency_for(project)
        for raw in cc.cycle_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class BlockingCallUnderLock(ProjectRule):
    """A blocking operation — socket/frame I/O (``recv_msg``,
    ``send_frame``, ``sendall``...), ``time.sleep``, subprocess spawns,
    a bare ``join()`` — is reachable while a lock is held. Under load
    every thread contending on that lock serializes behind the wait:
    with the proof-device lock or a ConnPool lock this invisibly
    flattens the serving tier to one in-flight operation. The finding
    carries the interprocedural path from the thread entry to the call.
    Fix by moving the wait outside the critical section (snapshot under
    the lock, operate after release); where the serialization *is* the
    design — e.g. a per-connection lock serializing one socket
    conversation — suppress at the site with a reason."""

    id = "blocking-call-under-lock"
    engine = "concurrency"
    summary = ("socket/sleep/subprocess/join reachable while holding a "
               "lock — serializes every contending thread")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .concurrency import concurrency_for
        cc = concurrency_for(project)
        for raw in cc.blocking_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


# ---------------------------------------------------------------------------
# Determinism rules (drynx_tpu/analysis/determinism.py): two thin
# wrappers over one shared nondeterminism-taint run — determinism_for()
# memoizes on the same content-hash fingerprint, so the interprocedural
# source->sink walk is computed once per tree version for both rules
# (and for the DRYNX_DET_TRACE runtime cross-check).

@register
class NondetFlowToTranscript(ProjectRule):
    """A nondeterministic *value* — a wall-clock read, unseeded RNG
    draw, or object identity (``id()``/``hash()`` under hash
    randomization) — flows into a byte-identity sink: transcript
    serialization, a digest, a ProofDB/``pane:``/``ckpt:`` write, a
    skipchain append, a wire v2 frame encode, or an fsync'd journal
    line. Those surfaces back the repo's byte-identical-transcript
    equivalence claims, so any such flow makes two same-seed runs
    diverge. The finding carries the full source->sink chain as a
    SARIF codeFlow with dual anchors (suppressible at the source or
    the sink). Fix by deriving the value from survey inputs (seeded
    ``fold_in``); a *deliberate* nondeterministic surface — e.g. a
    block's wall-clock ``sample_time``, excluded from the transcript
    by design — is declared with ``# drynx: deterministic[reason]``
    at the source line."""

    id = "nondet-flow-to-transcript"
    engine = "determinism"
    summary = ("wall-clock/RNG/identity value flows into a "
               "byte-identity sink (transcript, digest, ProofDB, "
               "skipchain, wire encode, journal)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .determinism import determinism_for
        det = determinism_for(project, getattr(project, "focus", None))
        for raw in det.nondet_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class UnorderedIterationAtSink(ProjectRule):
    """Bytes reach a byte-identity sink in a nondeterministic *order*:
    a value derived from an unsorted directory listing, a ``set``'s
    iteration order, or thread-completion order (``as_completed``) is
    written to a sink — or the sink call itself sits inside a loop
    over such an iterate, so the write sequence varies run to run even
    though each individual write is deterministic. Fix by sorting the
    iterate (``sorted(...)`` with a total key), canonicalizing
    (``canon_points``/``fold_cts``), or gathering into an
    index-addressed structure (the roster-order ``fan_out`` result
    list) before serializing."""

    id = "unordered-iteration-at-sink"
    engine = "determinism"
    summary = ("listing/set/thread-completion order reaches a "
               "byte-identity sink — write order varies run to run")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .determinism import determinism_for
        det = determinism_for(project, getattr(project, "focus", None))
        for raw in det.unordered_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)

# ---------------------------------------------------------------------------
# Typestate rules (drynx_tpu/analysis/typestate.py): four thin wrappers
# over one shared resource-lifecycle run — typestate_for() memoizes on
# the same content-hash fingerprint, so the interprocedural automaton
# walk (instance tracking through parameters, returns, aliases, branch
# joins and try/finally edges) is computed once per tree version for
# all four protocols (and for the DRYNX_PROTO_TRACE runtime cross-check).

@register
class AtomicDurableWrite(ProjectRule):
    """A durable artifact (ledger/journal/checkpoint/bench/slab/.npz
    path) is written without the crash-consistent tmp-write -> fsync ->
    rename protocol: an in-place ``open(final, "w")``, a rename before
    the data hit the disk (no ``os.fsync`` between the last write and
    the publish), a write after the file was already published, or a
    tmp file that is flushed but never renamed into place. Any of these
    can leave a torn or missing artifact after a crash — the pool
    store's replay and the proof transcript both assume publishes are
    all-or-nothing. Append-mode opens of durable paths are only legal
    in modules that declare a replay routine (the journal idiom).
    Fix with the ``_atomic_write_npz`` shape; a deliberately relaxed
    write (scratch diagnostics) is declared with
    ``# drynx: protocol[reason]`` at the open or the violation site."""

    id = "atomic-durable-write"
    engine = "typestate"
    summary = ("durable-path write skips the tmp-write -> fsync -> "
               "rename crash-consistency protocol (typestate)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .typestate import typestate_for
        ts = typestate_for(project, getattr(project, "focus", None))
        for raw in ts.atomic_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class SlabConsumptionOrder(ProjectRule):
    """A claimed pool slab (the ``os.rename`` claim-move that fences
    out concurrent consumers) is consumed out of order: read before its
    consumption was journaled in the fsync'd ledger, unlinked before it
    was read, or claimed and then leaked without the final unlink. The
    ledger append IS the commit point — a crash between claim and
    append must leave evidence for replay, so reading or deleting
    first reintroduces the double-spend/lost-slab windows the pool
    store's recovery protocol exists to close. The required order is
    claim-rename -> ledger append -> read -> unlink, machine-checked
    per instance across calls and exception edges."""

    id = "slab-consumption-order"
    engine = "typestate"
    summary = ("claimed slab read/unlinked before the fsync'd ledger "
               "append, or never unlinked (typestate)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .typestate import typestate_for
        ts = typestate_for(project, getattr(project, "focus", None))
        for raw in ts.slab_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class ConnCheckoutDiscipline(ProjectRule):
    """A connection checked out of a ``ConnPool`` (or constructed
    directly) fails to reach exactly one terminal — ``put``/``discard``
    back to the pool or ``close`` — on some path, including exception
    edges: a return/raise that abandons the socket, or a conn that a
    transport failure (``CallTimeout``/``TransportError``/``OSError``
    handler) marked suspect being reused or returned to the pool as if
    healthy. Leaks starve the pool under load; returning a suspect
    conn poisons a later checkout with a dead socket. The walker
    tracks each instance through helper calls, aliases and
    try/finally, so release-in-a-helper and retry-loop idioms are
    recognized; the finding's codeFlow shows the path that leaks."""

    id = "conn-checkout-discipline"
    engine = "typestate"
    summary = ("pool conn misses put/discard/close on some path, or is "
               "reused after a transport failure (typestate)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .typestate import typestate_for
        ts = typestate_for(project, getattr(project, "focus", None))
        for raw in ts.conn_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)


@register
class SealCommitOnce(ProjectRule):
    """A streaming pane is sealed twice under one pane key, a pane's
    proof blob is committed twice, or a checkpoint loaded for resume is
    saved again without re-entering a phase (a blind save would
    overwrite the resume evidence — the ``phase_entries`` counters —
    with stale state). Seal and commit are at-most-once per instance
    per path: the VN verify cache and the epsilon ledger both key on
    the pane identity, so a double seal double-charges and a double
    commit forks the audit trail. The checkpoint clause enforces
    load -> enter -> save ordering per ``SurveyCheckpoint`` instance."""

    id = "seal-commit-once"
    engine = "typestate"
    summary = ("pane sealed/committed twice under one key, or a "
               "resumed checkpoint saved without re-entering a phase "
               "(typestate)")

    def run_project(self, project: ProjectInfo) -> Iterator[Finding]:
        from .typestate import typestate_for
        ts = typestate_for(project, getattr(project, "focus", None))
        for raw in ts.seal_raw:
            if project.in_focus(raw.file):
                yield _raw_to_finding(self.id, project, raw)
