"""The repo-specific lint rules (see ANALYSIS.md for the full rationale).

Every rule is a static approximation: it must be cheap, zero-dependency
(no jax import) and err toward flagging — suppressions (`# drynx:
noqa[rule]`) and the committed baseline absorb deliberate exceptions.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set

from .core import (Finding, ModuleInfo, Rule, _contains_env_read, _dotted,
                   _local_bindings, register)

# Flags mutated at runtime by tests/kill-switches even when a module only
# *imports* them (e.g. pallas_pairing re-exports pallas_ops.INTERPRET and
# tests monkeypatch both copies).
KNOWN_MUTABLE_FLAGS = {"INTERPRET", "ENABLED", "UNROLL"}

_SECRET_RE = re.compile(
    r"(^|_)(sk|secret|secrets|priv|privkey|private(_?key)?)(_|$)|secret",
    re.IGNORECASE)

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log", "lvl", "lvl1", "lvl2", "lvl3"}
_LOGGER_NAMES = {"log", "logging", "logger", "_logger", "LOG", "LOGGER"}


def _in_scope(mod: ModuleInfo, *parts: str) -> bool:
    return any(f"/{p}/" in f"/{mod.relpath}" for p in parts)


def _is_drynx_pkg(mod: ModuleInfo) -> bool:
    return mod.relpath.startswith("drynx_tpu/") or "/drynx_tpu/" in mod.relpath


# ---------------------------------------------------------------------------
@register
class JitGlobalCapture(Rule):
    """A @jax.jit function (or a pallas_call builder — its body runs at
    trace time) reading a *mutable* module global bakes the value into the
    trace cache, keyed only on shapes/static args. Flipping the flag later
    (monkeypatch, kill-switch) silently reuses stale traces — exactly the
    INTERPRET trace-cache leak in ADVICE.md. Pass such values as static
    arguments, or accept the capture explicitly via the baseline + a
    cache-clearing teardown."""

    id = "jit-global-capture"
    summary = ("jit-traced code reads a mutable module-level flag; the value "
               "is frozen into the trace cache at first call")

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        mutable = (set(mod.env_derived) | mod.rebound |
                   (KNOWN_MUTABLE_FLAGS &
                    _imported_or_assigned_names(mod)))
        if not mutable:
            return
        for fn in mod.traced_functions:
            local = _local_bindings(fn)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mutable and sub.id not in local):
                    yield self.finding(
                        mod, sub,
                        f"trace-time capture of mutable module global "
                        f"'{sub.id}' in '{fn.name}' — value is frozen into "
                        f"the jit/pallas trace cache")


def _imported_or_assigned_names(mod: ModuleInfo) -> Set[str]:
    names = set(mod.module_assigns)
    for node in mod.tree.body:
        if isinstance(node, ast.ImportFrom):
            names.update(a.asname or a.name for a in node.names)
    return names


# ---------------------------------------------------------------------------
@register
class UnsafePickle(Rule):
    """VNs deserialize proof bodies sent by the very parties they exist to
    distrust; `pickle.loads` on those bytes is remote code execution via a
    crafted __reduce__. All deserialization must go through the restricted
    unpickler in proofs/safe_pickle.py (the only file allowed here)."""

    id = "unsafe-pickle"
    summary = ("raw pickle.load(s)/Unpickler outside proofs/safe_pickle.py "
               "— RCE on attacker-controlled bytes")

    _ALLOWED_SUFFIX = "proofs/safe_pickle.py"

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.relpath.endswith(self._ALLOWED_SUFFIX):
            return
        # track `from pickle import loads [as x]`
        from_pickle: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module == "pickle":
                for a in node.names:
                    if a.name in ("loads", "load", "Unpickler"):
                        from_pickle.add(a.asname or a.name)
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            bad = (d in ("pickle.loads", "pickle.load", "pickle.Unpickler")
                   or (isinstance(sub.func, ast.Name)
                       and sub.func.id in from_pickle))
            if bad:
                yield self.finding(
                    mod, sub,
                    f"'{d or sub.func.id}' on untrusted bytes is arbitrary "
                    f"code execution; use proofs.safe_pickle.safe_loads")


# ---------------------------------------------------------------------------
@register
class ImplicitDtype(Rule):
    """The crypto/proof layers are exact uint32 limb arithmetic with
    jax_enable_x64 on: a dtype-inferred array (weak int64/float64) silently
    corrupts Montgomery carries or changes a hash transcript. Array
    constructors inside crypto/ and proofs/ must pin their dtype."""

    id = "implicit-dtype"
    summary = ("jnp array constructor without an explicit dtype inside "
               "crypto/ or proofs/ — inferred dtypes corrupt limb math")

    # positional index at which dtype may appear
    _CTORS = {"jnp.array": 1, "jnp.asarray": 1, "jnp.zeros": 1,
              "jnp.ones": 1, "jnp.empty": 1, "jnp.full": 2}

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (_is_drynx_pkg(mod) and _in_scope(mod, "crypto", "proofs")):
            return
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d not in self._CTORS:
                continue
            if any(k.arg == "dtype" for k in sub.keywords):
                continue
            if len(sub.args) > self._CTORS[d]:
                continue  # dtype passed positionally
            yield self.finding(
                mod, sub,
                f"'{d}' without explicit dtype — pin it (uint32 limb "
                f"tensors / exact-int statistics must not rely on "
                f"inference)")


# ---------------------------------------------------------------------------
@register
class HostSyncInHotPath(Rule):
    """Inside jit-traced crypto/parallel code, float()/int()/bool()/
    np.asarray() on a traced value either crashes at trace time or forces a
    device->host sync that serializes the pipeline; .block_until_ready()
    inside a trace is always a mistake. Heuristic taint: function
    parameters (minus static_argnames) and locals derived from them."""

    id = "host-sync-in-hot-path"
    summary = ("host-synchronizing call on a traced value inside jitted "
               "crypto/ or parallel/ code")

    _HOST_CASTS = {"float", "int", "bool"}
    _HOST_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    _SYNC_METHODS = {"block_until_ready", "item", "tolist"}

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (_is_drynx_pkg(mod) and _in_scope(mod, "crypto", "parallel")):
            return
        for fn in mod.traced_functions:
            tainted = self._tainted_names(fn)
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                d = _dotted(sub.func)
                if (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in self._SYNC_METHODS):
                    if sub.func.attr == "block_until_ready" or \
                            self._refs_tainted(sub.func.value, tainted):
                        yield self.finding(
                            mod, sub,
                            f"'.{sub.func.attr}()' inside jit-traced "
                            f"'{fn.name}' forces a host sync")
                    continue
                name = d if d in self._HOST_FUNCS else (
                    sub.func.id if isinstance(sub.func, ast.Name)
                    and sub.func.id in self._HOST_CASTS else None)
                if name and any(self._refs_tainted(a, tainted)
                                for a in sub.args):
                    yield self.finding(
                        mod, sub,
                        f"'{name}()' on a traced value inside jit-traced "
                        f"'{fn.name}' — crashes at trace time or forces a "
                        f"device->host sync")

    @staticmethod
    def _static_args(fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) \
                                and isinstance(n.value, str):
                            out.add(n.value)
        return out

    def _tainted_names(self, fn: ast.AST) -> Set[str]:
        static = self._static_args(fn)
        args = fn.args
        tainted = {a.arg for a in
                   (args.posonlyargs + args.args + args.kwonlyargs)
                   if a.arg not in static and a.arg != "self"}
        # one forward pass of simple propagation through assignments
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) \
                    and self._refs_tainted(stmt.value, tainted):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        return tainted

    @staticmethod
    def _refs_tainted(node: ast.AST, tainted: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(node))


# ---------------------------------------------------------------------------
@register
class EnvReadIntoTrace(Rule):
    """`X = os.environ[...]` at import time, with X read inside jit-traced
    code, wires process environment into compiled artifacts: two processes
    with different env silently compute different programs from the same
    call site, and tests that mutate the env (or monkeypatch X) leave stale
    traces behind. Thread such config through as explicit (static)
    arguments instead. Fires at the assignment; the use sites are covered
    by jit-global-capture."""

    id = "env-read-into-trace"
    summary = ("import-time os.environ read whose value flows into "
               "jit-traced code")

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        used_in_trace: Dict[str, List[str]] = {}
        for fn in mod.traced_functions:
            local = _local_bindings(fn)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mod.env_derived
                        and sub.id not in local):
                    used_in_trace.setdefault(sub.id, []).append(fn.name)
        for name, fns in sorted(used_in_trace.items()):
            node = mod.env_derived[name]
            yield self.finding(
                mod, node,
                f"import-time environment read bound to '{name}' is "
                f"captured by jit-traced code ({', '.join(sorted(set(fns)))})"
            )
        # direct env reads lexically inside traced functions
        for fn in mod.traced_functions:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Attribute, ast.Call)):
                    d = _dotted(sub if isinstance(sub, ast.Attribute)
                                else sub.func)
                    if d and (d.startswith("os.environ") or d == "os.getenv"):
                        yield self.finding(
                            mod, sub,
                            f"os.environ read inside jit-traced "
                            f"'{fn.name}' is evaluated once at trace time")
                        break


# ---------------------------------------------------------------------------
@register
class SecretLogging(Rule):
    """Secret-key material (ElGamal secrets, Schnorr nonces) must never hit
    a log stream or stdout: logs cross trust boundaries (CI artifacts,
    shared hosts) that the ciphertexts are specifically protecting the data
    from. Flags print()/log.*/logging calls whose arguments reference a
    secret-shaped identifier."""

    id = "secret-logging"
    summary = "print/log call referencing secret-key material"

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            if not self._is_log_sink(sub):
                continue
            ident = self._secret_ident(sub)
            if ident:
                yield self.finding(
                    mod, sub,
                    f"'{ident}' looks like secret-key material flowing "
                    f"into a log/print sink")

    @staticmethod
    def _is_log_sink(call: ast.Call) -> bool:
        if isinstance(call.func, ast.Name) and call.func.id == "print":
            return True
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _LOG_METHODS:
            root = call.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) and root.id in _LOGGER_NAMES
        return False

    @classmethod
    def _secret_ident(cls, call: ast.Call) -> str:
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for n in ast.walk(arg):
                name = None
                if isinstance(n, ast.Name):
                    name = n.id
                elif isinstance(n, ast.Attribute):
                    name = n.attr
                if name and _SECRET_RE.search(name):
                    return name
        return ""


# ---------------------------------------------------------------------------
@register
class HardcodedTimeout(Rule):
    """Retry/timeout numbers scattered as bare literals made failure
    behavior unauditable: nobody could say how long a dead DP stalls a
    survey without reading every call site (the pre-resilience state of
    node.py/api.py/service.py). Every such number must be a named constant
    in drynx_tpu/resilience/policy.py — that module is the single place
    the rule exempts. Fires on: timeout=/retries= keyword literals,
    timeout-ish parameter defaults, sleep/wait calls with literal
    durations, and `.get("...timeout...", <literal>)` fallbacks."""

    id = "hardcoded-timeout"
    summary = ("bare numeric timeout/retry literal outside "
               "drynx_tpu/resilience/ — name it in resilience/policy.py")

    _SLEEPY = {"sleep", "wait", "join"}

    @staticmethod
    def _timeoutish(name: str) -> bool:
        n = name.lower()
        return ("timeout" in n or n == "retries" or n.endswith("_retries")
                or n.endswith("deadline"))

    @staticmethod
    def _nonzero_num(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, (int, float))
                and not isinstance(node.value, bool)
                and node.value != 0)

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not _is_drynx_pkg(mod) or _in_scope(mod, "resilience"):
            return
        for sub in ast.walk(mod.tree):
            if isinstance(sub, ast.Call):
                yield from self._check_call(mod, sub)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_defaults(mod, sub)

    def _check_call(self, mod: ModuleInfo, call: ast.Call):
        for kw in call.keywords:
            if kw.arg and self._timeoutish(kw.arg) \
                    and self._nonzero_num(kw.value):
                yield self.finding(
                    mod, call,
                    f"literal {kw.arg}={kw.value.value!r} — use a named "
                    f"constant from drynx_tpu/resilience/policy.py")
                return
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in self._SLEEPY and call.args \
                    and self._nonzero_num(call.args[0]):
                yield self.finding(
                    mod, call,
                    f"literal duration in '.{call.func.attr}"
                    f"({call.args[0].value!r})' — use a named constant "
                    f"from drynx_tpu/resilience/policy.py")
                return
            if (call.func.attr == "get" and len(call.args) >= 2
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                    and self._timeoutish(call.args[0].value)
                    and self._nonzero_num(call.args[1])):
                yield self.finding(
                    mod, call,
                    f"literal fallback in .get({call.args[0].value!r}, "
                    f"{call.args[1].value!r}) — use a named constant from "
                    f"drynx_tpu/resilience/policy.py")

    def _check_defaults(self, mod: ModuleInfo, fn):
        args = fn.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if self._timeoutish(a.arg) and self._nonzero_num(d):
                yield self.finding(
                    mod, d,
                    f"literal default {a.arg}={d.value!r} in '{fn.name}' — "
                    f"use a named constant from "
                    f"drynx_tpu/resilience/policy.py")
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and self._timeoutish(a.arg) \
                    and self._nonzero_num(d):
                yield self.finding(
                    mod, d,
                    f"literal default {a.arg}={d.value!r} in '{fn.name}' — "
                    f"use a named constant from "
                    f"drynx_tpu/resilience/policy.py")


# ---------------------------------------------------------------------------
@register
class ThreadTrace(Rule):
    """First-touch jit tracing from a worker thread is the r05 segfault
    class: partial_eval recurses roughly one C frame per traced equation,
    the pairing kernels trace >10k equations, and non-main threads get half
    the main thread's C stack — the process dies in the interpreter with no
    Python traceback. All first-touch tracing must happen on the main
    thread (the compilecache warmup) or under the shared compile lock.
    Flags `threading.Thread(target=f)` where `f` is a function defined in
    this module whose body calls a trace entry — a jit/pallas-decorated
    function, a `bucketed(...)`/`jax.jit(...)`-bound name, or a bucketed-op
    attribute — outside a `with <...lock...>:` block."""

    id = "thread-trace"
    summary = ("threading.Thread target reaches a jit/trace entry point "
               "outside a compile lock — first-touch tracing off the main "
               "thread can overflow the worker's C stack")

    _ENTRY_FACTORIES = {"bucketed", "jit", "pjit"}

    def run(self, mod: ModuleInfo) -> Iterator[Finding]:
        entries = self._trace_entry_names(mod)
        if not entries:
            return
        defs = {f.name: f for f in mod.functions}
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d not in ("threading.Thread", "Thread"):
                continue
            target = next((kw.value for kw in sub.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            if isinstance(target, ast.Lambda):
                hit = self._unlocked_entry_call(target.body, entries,
                                                under_lock=False)
                if hit:
                    yield self.finding(
                        mod, sub,
                        f"Thread target lambda calls trace entry "
                        f"'{hit}' — first-touch tracing off the main "
                        f"thread (warm it via drynx_tpu.compilecache or "
                        f"wrap in the compile lock)")
                continue
            if not isinstance(target, ast.Name) or target.id not in defs:
                continue  # dynamic/imported target: out of static reach
            fn = defs[target.id]
            hit = None
            for stmt in fn.body:
                hit = self._unlocked_entry_call(stmt, entries,
                                                under_lock=False)
                if hit:
                    break
            if hit:
                yield self.finding(
                    mod, sub,
                    f"Thread target '{fn.name}' calls trace entry "
                    f"'{hit}' outside a compile lock — first-touch "
                    f"tracing off the main thread (warm it via "
                    f"drynx_tpu.compilecache or wrap in the compile lock)")

    def _trace_entry_names(self, mod: ModuleInfo) -> Set[str]:
        names = {f.name for f in mod.traced_functions}
        # names bound to bucketed(...)/jax.jit(...) factory calls anywhere
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Assign) \
                    or not isinstance(sub.value, ast.Call):
                continue
            d = _dotted(sub.value.func) or ""
            if d.split(".")[-1] in self._ENTRY_FACTORIES:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    @classmethod
    def _unlocked_entry_call(cls, node: ast.AST, entries: Set[str],
                             under_lock: bool) -> Optional[str]:
        """Name of the first trace-entry call NOT under a lock-ish `with`,
        else None. Recursion tracks `with ...lock...:` ancestry — ast.walk
        can't, it loses parents."""
        if isinstance(node, ast.With):
            locked = under_lock or any(
                "lock" in (_dotted(item.context_expr) or "").lower()
                for item in node.items)
            for child in node.body:
                hit = cls._unlocked_entry_call(child, entries, locked)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Call) and not under_lock:
            d = _dotted(node.func)
            leaf = (d or "").split(".")[-1]
            if leaf in entries:
                return leaf
        for child in ast.iter_child_nodes(node):
            hit = cls._unlocked_entry_call(child, entries, under_lock)
            if hit:
                return hit
        return None
