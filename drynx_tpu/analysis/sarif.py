"""SARIF 2.1.0 output for CI PR annotation.

``python -m drynx_tpu.analysis --format sarif`` emits one run with the
triggered rules' metadata and every finding as a ``result``; findings
that carry a call/value chain render it as a SARIF ``codeFlow`` (one
``threadFlow`` whose locations are the chain hops), so code-scanning UIs
show the same pin -> launder -> sink / read -> import -> definition
trails the text output renders as ``call chain:`` lines.

Pure stdlib, deterministic output (rules and findings arrive sorted).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .core import RULES, Finding

_TOOL_NAME = "drynx-tpu-analysis"
_SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _parse_hop(hop: str) -> Optional[Tuple[str, int, str]]:
    """``file:line:symbol`` -> parts (symbol may itself contain colons)."""
    parts = hop.split(":", 2)
    if len(parts) == 3 and parts[1].isdigit():
        return parts[0], int(parts[1]), parts[2]
    return None


def _location(file: str, line: int,
              message: Optional[str] = None) -> Dict[str, object]:
    loc: Dict[str, object] = {
        "physicalLocation": {
            "artifactLocation": {"uri": file},
            "region": {"startLine": max(1, line)},
        }
    }
    if message is not None:
        loc["message"] = {"text": message}
    return loc


def to_sarif(findings: Sequence[Finding]) -> Dict[str, object]:
    """A complete SARIF log dict for ``json.dumps``."""
    rule_ids = sorted({f.rule for f in findings})
    rules_meta: List[Dict[str, object]] = []
    for rid in rule_ids:
        rule = RULES.get(rid)
        meta: Dict[str, object] = {"id": rid}
        if rule is not None and rule.summary:
            meta["shortDescription"] = {"text": rule.summary}
        rules_meta.append(meta)
    index = {rid: i for i, rid in enumerate(rule_ids)}

    results: List[Dict[str, object]] = []
    for f in findings:
        res: Dict[str, object] = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [_location(f.file, f.line)],
        }
        hops = [h for h in (_parse_hop(h) for h in f.call_chain)
                if h is not None]
        if hops:
            res["codeFlows"] = [{
                "threadFlows": [{
                    "locations": [
                        {"location": _location(file, line, symbol)}
                        for file, line, symbol in hops],
                }],
            }]
        results.append(res)

    return {
        "$schema": _SARIF_SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": _TOOL_NAME,
                "informationUri":
                    "https://github.com/drynx-tpu/drynx-tpu",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }
