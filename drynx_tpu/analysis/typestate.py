"""Typestate analysis: resource-lifecycle protocols, machine-checked.

The last big correctness surface the prover family did not cover is
*resource lifecycles*: the crash-consistency and exactly-once
invariants that PRs 9/17/18 only ever enforced dynamically. This
engine proves them statically — a whole-program, flow-sensitive
typestate pass over the PR-4 project graphs, in the shape of the PR-5
dataflow, PR-14 concurrency and PR-19 determinism engines.

Protocols are declarative finite automata over operations on a
tracked *resource instance*: a creation site starts an instance in
the protocol's start state, recognized transition calls move it
through the automaton, and non-accepting states at an exit (return,
raise, function end, rebind) — or an explicit error transition — are
findings. The engine tracks instances interprocedurally (parameter
passthrough, return propagation, aliasing through locals and tuple
unpacks), joins automaton states at branch merges (a may-analysis:
an instance *may* be in any state of its set; an exit passes if ANY
state is accepting — biased against false positives — while an
explicit error transition reports if ANY live state rejects, because
the dirty arm of a join is a real crash window even when a sibling
arm is clean), and walks loop bodies twice as a fixpoint
approximation. Every finding carries
the observed transition sites as a SARIF codeFlow with dual anchors
(violation site + creation site) so ``noqa`` works at either end.

The four shipped protocols:

* **atomic** (``atomic-durable-write``) — a write landing on a
  durable path (journal/ledger/ckpt/bench/slab/.npz/.db/proof
  hints in the path expression) must follow the tmp-file write →
  ``fsync`` → ``os.rename``/``os.replace`` idiom. Direct
  open-for-append is allowed only on journal paths whose module
  declares torn-tail-tolerant replay (a ``*replay*`` function);
  opening a durable path ``"w"`` in place and writing it is an
  error, as is publishing a tmp file whose bytes were never fsync'd.
* **slab** (``slab-consumption-order``) — the PR-9 single-consumption
  contract: claim-rename → fsync'd ledger append → read → unlink.
  Reading before the consume event is journaled, unlinking before
  the read, or leaving a claimed slab behind on a normal exit are
  all flagged (crash paths are exempt: the recovery sweep owns them).
* **conn** (``conn-checkout-discipline``) — a ``ConnPool`` checkout
  must reach exactly one of return-to-pool (``put``) or desync
  discard/close on every path *including exception edges*; after a
  transport-failure handler entry the conn is *suspect* and must be
  discarded, never reused or returned.
* **seal** (``seal-commit-once``) — the PR-17/18 exactly-once
  contracts: a pane key is sealed (2-arg ``put``) at most once per
  instance per path, the pane proof-commit call is reachable at most
  once per path, and a checkpoint *loaded* from the store must
  re-enter a phase before it is saved again (a blind re-save
  overwrites the only evidence of where the resume started).

Known over-approximations (see ANALYSIS.md): path classification is
textual (hints in the unparsed path expression / enclosing function
name); ambient events (``_ledger_append``) apply to every live
claimed slab; branch joins union states. Known under-approximations:
an instance passed to an unresolved call (or stored into an
attribute/container) escapes and is no longer exit-checked; an
instance created inside a ``try`` body is *unborn* on the handler
edge, so leaks of try-created instances on exception paths are
invisible. Still pure ``ast``, still no jax import; the whole run is
memoized on the project content fingerprint and focusable for
``--changed-only``.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import (Dict, FrozenSet, List, Mapping, Optional, Sequence,
                    Set, Tuple)

from .core import ModuleInfo, _dotted, _local_bindings
from .dataflow import RawFinding, project_fingerprint
from .graph import FuncNode, ModuleGraph
from .project import ProjectInfo, chain_hop

_MAX_DEPTH = 8

_PROTOCOL_RE = re.compile(r"#\s*drynx:\s*protocol\[([^\]]+)\]")

# -- path / context classification tables ------------------------------------

# a path expression containing one of these is a *durable* surface
DURABLE_HINTS = ("jsonl", "journal", "ledger", "ckpt", "checkpoint",
                 "bench", "slab", ".npz", ".db", "proof", "record")
# ...and one of these marks a scratch file headed for an atomic publish
TMP_HINTS = ("tmp", "temp")
# an enclosing function whose name carries one of these is writing a
# durable artifact even when the path variable itself is bland
FN_DURABLE_HINTS = ("atomic", "journal", "ledger", "checkpoint", "ckpt",
                    "persist", "npz", "durable", "seal", "record")

# accepted-by-delegation creation sites: calls that *are* the idiom
_DELEGATED_ATOMIC = {"_atomic_write_npz"}
_JOURNAL_LEAVES = {"_ledger_append"}
_DB_CTORS = {"ProofDB"}

# leaves through which an open handle is written as an *argument*
_HANDLE_WRITE_LEAVES = {"dump", "save", "savez", "savez_compressed",
                        "write", "pack_into"}
# leaves that read a claimed slab path
_SLAB_READ_LEAVES = {"open", "load", "mmap", "memmap", "fromfile",
                     "read_bytes", "_load_npz_mapped"}

# exception names whose handler entry marks a checked-out conn suspect
_SUSPECT_EXC = {"CallTimeout", "TransportError", "ConnectError",
                "OSError", "ConnectionError", "BrokenPipeError",
                "timeout", "socket.timeout"}

# commit calls reachable at most once per path per function walk
_ONCE_LEAVES = {"_deliver_pane_proofs"}

# tokens worth recording on parameter sentinels for caller replay
_SENTINEL_TOKENS = {"put", "discard", "close", "use", "enter", "save",
                    "write", "fsync"}


def _is_drynx_pkg(mod: ModuleInfo) -> bool:
    return (mod.relpath.startswith("drynx_tpu/")
            or "/drynx_tpu/" in mod.relpath
            or "lintpkg" in mod.relpath)


def _unparse(e: Optional[ast.expr]) -> str:
    if e is None:
        return ""
    # memoized on the node: alias/argument texts are re-rendered at
    # every event match and the ASTs outlive the engine run
    s = getattr(e, "_ts_unparse", None)
    if s is None:
        try:
            s = ast.unparse(e)
        except Exception:  # pragma: no cover - malformed synthetic nodes
            s = ""
        try:
            e._ts_unparse = s
        except Exception:  # pragma: no cover - slotted synthetic nodes
            pass
    return s


# -- declarative automata ----------------------------------------------------

# Transition tables: token -> state -> next state. A next state
# prefixed "!" is an error transition (the message follows the "!").
# Unknown (token, state) pairs are identity; the special "unborn"
# state (instance may not exist on this path) absorbs every token,
# and "poisoned" (already reported) absorbs every token and accepts.

@dataclasses.dataclass(frozen=True)
class Protocol:
    key: str                              # short key used in raws
    title: str                            # human protocol name
    accepting: FrozenSet[str]
    table: Mapping[str, Mapping[str, str]]
    exit_error: str = ""                  # "" = every exit accepted
    exit_on_raise: bool = False           # also check on raise edges


_ATOMIC = Protocol(
    key="atomic",
    title="atomic-durable-write",
    accepting=frozenset({"published", "journal", "replay-read",
                         "delegated", "relaxed"}),
    table={
        "write": {
            "open": "dirty", "dirty": "dirty", "synced": "dirty",
            "relaxed": "relaxed",
            "in-place": ("!durable path written in place — write a "
                         "tmp file, fsync, then os.replace onto the "
                         "durable path"),
            "published": ("!tmp handle written after the file was "
                          "published"),
        },
        "fsync": {
            "open": "synced", "dirty": "synced", "synced": "synced",
            "relaxed": "relaxed", "in-place": "in-place",
        },
        "close": {
            "open": "closed-synced", "dirty": "closed-dirty",
            "synced": "closed-synced", "relaxed": "relaxed",
            "in-place": "in-place",
        },
        "rename": {
            "synced": "published", "closed-synced": "published",
            "open": "published", "relaxed": "relaxed",
            "dirty": ("!tmp file renamed onto the durable path "
                      "before fsync — a crash can publish a torn "
                      "file"),
            "closed-dirty": ("!tmp file renamed onto the durable "
                             "path before fsync — a crash can "
                             "publish a torn file"),
        },
    },
    exit_error=("durable tmp write never published — the path must "
                "reach os.replace/os.rename after fsync on every "
                "normal exit"),
)

_SLAB = Protocol(
    key="slab",
    title="slab-consumption-order",
    accepting=frozenset({"consumed"}),
    table={
        "ledger": {
            "claimed": "journaled", "journaled": "journaled",
            "read": "read",
        },
        "read": {
            "journaled": "read", "read": "read",
            "claimed": ("!claimed slab read before the consume "
                        "event is journaled — a crash between read "
                        "and append double-spends the slab"),
        },
        "unlink": {
            "read": "consumed",
            "journaled": "!slab unlinked before it was read",
            "claimed": ("!slab unlinked before the consume event "
                        "is journaled"),
        },
    },
    exit_error=("claimed slab never unlinked on this path — the "
                "claim-rename leaves a .claimed orphan the recovery "
                "sweep must garbage-collect"),
)

_CONN = Protocol(
    key="conn",
    title="conn-checkout-discipline",
    accepting=frozenset({"returned", "discarded", "closed"}),
    table={
        "use": {
            "checked-out": "checked-out",
            "suspect": ("!conn reused after a transport failure — "
                        "the stream may be desynchronized "
                        "(half-sent frame); discard it"),
            "returned": "!conn used after it was returned to the pool",
            "discarded": "!conn used after it was discarded",
            "closed": "!conn used after close",
        },
        "put": {
            "checked-out": "returned",
            "suspect": ("!conn returned to the pool after a "
                        "transport failure — a desynchronized "
                        "stream poisons the next checkout; discard "
                        "it instead"),
            "returned": "!conn returned to the pool twice",
        },
        "discard": {
            "checked-out": "discarded", "suspect": "discarded",
            "returned": "discarded", "closed": "discarded",
        },
        "close": {
            "checked-out": "closed", "suspect": "closed",
            "returned": "closed", "discarded": "closed",
        },
    },
    exit_error=("conn checkout leaks on this path — every path "
                "(including exception edges) must reach exactly one "
                "of pool.put / pool.discard / close"),
    exit_on_raise=True,
)

_SEAL = Protocol(
    key="seal",
    title="seal-commit-once",
    accepting=frozenset({"fresh", "sealed", "fresh-ck", "resumed-ck",
                         "entered-ck", "written-ck"}),
    table={
        "seal": {
            "fresh": "sealed",
            "sealed": ("!pane/checkpoint key written twice on one "
                       "path — seal and proof-commit transitions "
                       "are exactly-once per instance"),
        },
        "enter": {
            "fresh-ck": "entered-ck", "resumed-ck": "entered-ck",
            "entered-ck": "entered-ck", "written-ck": "entered-ck",
        },
        "save": {
            "fresh-ck": "written-ck", "entered-ck": "written-ck",
            "written-ck": "written-ck",
            "resumed-ck": ("!checkpoint loaded from the store is "
                           "re-saved without re-entering a phase — "
                           "the blind overwrite destroys the only "
                           "record of where the resume started"),
        },
    },
)

PROTOCOLS: Dict[str, Protocol] = {p.key: p for p in
                                  (_ATOMIC, _SLAB, _CONN, _SEAL)}


# -- resource instances ------------------------------------------------------

class Resource:
    """One abstract instance of a protocol'd resource. State lives in
    the walker (snapshot/restored around branches); the instance
    itself carries only identity and immutable creation facts."""

    __slots__ = ("proto", "origin", "desc", "aliases", "escaped",
                 "param")

    def __init__(self, proto: Optional[Protocol], origin: Tuple[str, int],
                 desc: str, aliases: FrozenSet[str] = frozenset(),
                 param: str = ""):
        self.proto = proto
        self.origin = origin
        self.desc = desc
        self.aliases = aliases
        self.escaped = False
        self.param = param          # non-empty: a parameter sentinel

    @property
    def is_sentinel(self) -> bool:
        return bool(self.param)


_EMPTY: FrozenSet[Resource] = frozenset()


@dataclasses.dataclass
class FnSummary:
    params: Tuple[str, ...] = ()
    # (param, token, hop) transitions applied to a parameter, in
    # observed order — replayed onto the caller's argument instance
    param_events: Tuple[Tuple[str, str, str], ...] = ()
    param_escapes: FrozenSet[str] = frozenset()
    ret_params: FrozenSet[str] = frozenset()
    # fresh instances the callee creates and returns:
    # (proto key, exit states, chain, desc)
    ret_new: Tuple[Tuple[str, FrozenSet[str], Tuple[str, ...], str],
                   ...] = ()


_EMPTY_SUMMARY = FnSummary()


# -- the engine -------------------------------------------------------------

class Typestate:
    """Whole-program typestate pass over a ProjectInfo."""

    def __init__(self, project: ProjectInfo,
                 focus: Optional[FrozenSet[str]] = None):
        self.project = project
        self.focus = focus
        self.atomic_raw: List[RawFinding] = []
        self.slab_raw: List[RawFinding] = []
        self.conn_raw: List[RawFinding] = []
        self.seal_raw: List[RawFinding] = []
        # recognized surfaces, for the non-vacuity cross-checks
        self.creation_sites: Dict[Tuple[str, int], str] = {}
        self.transition_sites: Dict[Tuple[str, int], str] = {}
        self.marker_sites: Dict[Tuple[str, int], str] = {}
        self._summaries: Dict[str, FnSummary] = {}
        self._inflight: Set[str] = set()
        self._fn_facts: Dict[str, Tuple[Set[str], Dict[int, str]]] = {}
        self._seen: Set[Tuple[str, int, str, Tuple[str, int]]] = set()
        self._replay_mods: Dict[str, bool] = {}

    # -- driver -----------------------------------------------------------

    def run(self) -> "Typestate":
        for fid in sorted(self.project.calls.functions):
            fn = self.project.calls.functions[fid]
            mg = self.project.graphs[fn.module]
            if not _is_drynx_pkg(mg.info):
                continue
            if self.focus is not None and \
                    mg.info.relpath not in self.focus:
                continue
            self._summary(fid, 0)
        for raws in (self.atomic_raw, self.slab_raw, self.conn_raw,
                     self.seal_raw):
            raws.sort(key=lambda r: (r.file, r.line, r.message))
        return self

    def protocols_covered(self) -> Set[str]:
        return {v.split(":", 1)[0] for v in self.creation_sites.values()}

    # -- summaries --------------------------------------------------------

    def _summary(self, fid: str, depth: int) -> FnSummary:
        summ = self._summaries.get(fid)
        if summ is not None:
            return summ
        if fid in self._inflight or depth > _MAX_DEPTH:
            return _EMPTY_SUMMARY
        fn = self.project.calls.functions.get(fid)
        if fn is None:
            return _EMPTY_SUMMARY
        mg = self.project.graphs.get(fn.module)
        if mg is None or not _is_drynx_pkg(mg.info):
            return _EMPTY_SUMMARY
        self._inflight.add(fid)
        try:
            ctx = _TsCtx(self, mg, fn, depth)
            summ = ctx.walk()
        finally:
            self._inflight.discard(fid)
        self._summaries[fid] = summ
        return summ

    def module_declares_replay(self, relpath: str) -> bool:
        """Append-mode journals are legal only where a replay routine
        proves the on-disk format tolerates a torn tail."""
        got = self._replay_mods.get(relpath)
        if got is not None:
            return got
        info = self.project.modules.get(relpath)
        got = False
        if info is not None:
            for n in ast.walk(info.tree):
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) and \
                        "replay" in n.name.lower():
                    got = True
                    break
        self._replay_mods[relpath] = got
        return got

    # -- emission ---------------------------------------------------------

    def marked(self, relpath: str, line: int) -> Optional[str]:
        """The ``protocol[reason]`` marker governing a line: on the
        line itself or in the comment block directly above it."""
        info = self.project.modules.get(relpath)
        if info is None or not (0 < line <= len(info.lines)):
            return None
        m = _PROTOCOL_RE.search(info.lines[line - 1])
        prev = line - 1
        while m is None and prev >= 1 and \
                info.lines[prev - 1].lstrip().startswith("#"):
            m = _PROTOCOL_RE.search(info.lines[prev - 1])
            prev -= 1
        if m is None:
            return None
        self.marker_sites[(relpath, line)] = m.group(1).strip()
        return m.group(1).strip()

    def emit(self, proto: Protocol, relpath: str, line: int, msg: str,
             chain: Tuple[str, ...], origin: Tuple[str, int]) -> None:
        if self.marked(relpath, line) is not None:
            return
        if self.marked(origin[0], origin[1]) is not None:
            return
        key = (relpath, line, proto.key, origin)
        if key in self._seen:
            return
        self._seen.add(key)
        anchors: Tuple[Tuple[str, int], ...] = ((relpath, line),)
        if origin != (relpath, line):
            anchors = anchors + (origin,)
        raw = RawFinding(file=relpath, line=line, message=msg,
                         chain=chain, anchors=anchors)
        {"atomic": self.atomic_raw, "slab": self.slab_raw,
         "conn": self.conn_raw, "seal": self.seal_raw}[proto.key].append(raw)


# -- flow-sensitive function walker -----------------------------------------

class _TsCtx:
    """Executes one function body tracking resource instances through
    their protocol automata; parameters are seeded as sentinels so one
    walk yields both local findings and the interprocedural summary."""

    def __init__(self, eng: Typestate, mg: ModuleGraph, fn: FuncNode,
                 depth: int):
        self.eng = eng
        self.mg = mg
        self.fn = fn
        self.depth = depth
        self.rel = mg.info.relpath
        self.info = mg.info
        facts = eng._fn_facts.get(fn.fid)
        if facts is None:
            # slot 0 (bound-name set) is filled lazily: it is only
            # consulted for the builtin-`open` shadow check, and the
            # full binding walk is the engine's hottest cost
            facts = [None,
                     {id(s.node): s.callee
                      for s in eng.project.calls.callees(fn.fid)}]
            eng._fn_facts[fn.fid] = facts
        self._facts = facts
        self.sites = facts[1]
        self.env: Dict[str, FrozenSet[Resource]] = {}
        a = fn.node.args
        self.params: Tuple[str, ...] = tuple(
            p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs))
        for p in self.params:
            self.env[p] = frozenset(
                {Resource(None, (self.rel, fn.node.lineno),
                          f"param {p}", param=p)})
        # local name -> raw RHS node, for path-hint classification
        # (flow-insensitive on purpose: hints, not semantics)
        self.texts: Dict[str, ast.expr] = {}
        # per-path automaton state (snapshot/restored around branches)
        self.states: Dict[Resource, FrozenSet[str]] = {}
        self.chains: Dict[Resource, Tuple[str, ...]] = {}
        # commit-once sites observed on the current path: leaf -> lines
        self.once: Dict[str, FrozenSet[int]] = {}
        self.param_events: List[Tuple[str, str, str]] = []
        self.param_escapes: Set[str] = set()
        self.ret_params: Set[str] = set()
        self.ret_new: List[Tuple[str, FrozenSet[str], Tuple[str, ...],
                                 str]] = []
        # finalbodies of enclosing try statements: a return/raise runs
        # them before the frame exits, so exit checks credit them
        self.finally_stack: List[Sequence[ast.stmt]] = []

    @property
    def locals(self) -> Set[str]:
        if self._facts[0] is None:
            self._facts[0] = _local_bindings(self.fn.node)
        return self._facts[0]

    def walk(self) -> FnSummary:
        self.exec_stmts(self.fn.node.body)
        last = self.fn.node.body[-1] if self.fn.node.body \
            else self.fn.node
        self.exit_check("end", getattr(last, "lineno",
                                       self.fn.node.lineno))
        return FnSummary(params=self.params,
                         param_events=tuple(self.param_events),
                         param_escapes=frozenset(self.param_escapes),
                         ret_params=frozenset(self.ret_params),
                         ret_new=tuple(self.ret_new))

    # -- path-state plumbing -----------------------------------------------

    def _snap(self):
        return (dict(self.states), dict(self.chains), dict(self.once),
                dict(self.env))

    def _restore(self, snap) -> None:
        self.states, self.chains, self.once, self.env = (
            dict(snap[0]), dict(snap[1]), dict(snap[2]), dict(snap[3]))

    def _merge(self, *snaps) -> None:
        """Union-join path states (and env alias sets) after a branch."""
        states: Dict[Resource, FrozenSet[str]] = {}
        chains: Dict[Resource, Tuple[str, ...]] = {}
        once: Dict[str, FrozenSet[int]] = {}
        env: Dict[str, FrozenSet[Resource]] = {}
        for st, ch, on, en in snaps:
            for r, s in st.items():
                states[r] = states.get(r, frozenset()) | s
            for r, c in ch.items():
                if len(c) > len(chains.get(r, ())):
                    chains[r] = c
            for leaf, lines in on.items():
                once[leaf] = once.get(leaf, frozenset()) | lines
            for name, rs in en.items():
                env[name] = env.get(name, frozenset()) | rs
        self.states, self.chains, self.once, self.env = (states, chains,
                                                         once, env)

    def new_resource(self, proto: Protocol, line: int, desc: str,
                     start: FrozenSet[str],
                     aliases: FrozenSet[str] = frozenset(),
                     chain: Tuple[str, ...] = ()) -> Resource:
        r = Resource(proto, (self.rel, line), desc, aliases=aliases)
        self.states[r] = start
        self.chains[r] = chain or (chain_hop(self.rel, line,
                                             f"{desc} [{proto.key}]"),)
        self.eng.creation_sites.setdefault(
            (self.rel, line), f"{proto.key}:{desc}")
        return r

    def apply(self, res: Resource, token: str, line: int,
              leaf: str) -> None:
        """Drive one instance through one automaton transition."""
        if res.is_sentinel:
            if token in _SENTINEL_TOKENS:
                hop = chain_hop(self.rel, line, f"{leaf}() [{token}]")
                self.param_events.append((res.param, token, hop))
            return
        proto = res.proto
        if proto is None:
            return
        tab = proto.table.get(token)
        if tab is None:
            return
        cur = self.states.get(res)
        if cur is None:
            return
        self.eng.transition_sites.setdefault(
            (self.rel, line), f"{proto.key}:{token}")
        nxt: Set[str] = set()
        errors: List[str] = []
        for s in cur:
            if s in ("unborn", "poisoned"):
                nxt.add(s)
                continue
            to = tab.get(s, s)
            if to.startswith("!"):
                errors.append(to[1:])
            else:
                nxt.add(to)
        hop = chain_hop(self.rel, line, f"{leaf}() [{token}]")
        if errors:
            # may-error: some path state rejects the event — the dirty
            # arm of a branch join is a real crash window even when a
            # sibling arm accepts. Poison only if no live state survives
            # (surviving states carry the instance forward; the emit
            # dedup key stops repeat reports at this site).
            self.eng.emit(proto, self.rel, line, errors[0],
                          self.chains.get(res, ()) + (hop,), res.origin)
            if not (nxt - {"unborn"}):
                nxt.add("poisoned")
        self.states[res] = frozenset(nxt) if nxt else frozenset(cur)
        self.chains[res] = self.chains.get(res, ()) + (hop,)

    def _once_event(self, leaf: str, line: int) -> None:
        seen = self.once.get(leaf, frozenset())
        if any(ln != line for ln in seen):
            hop = chain_hop(self.rel, line, f"{leaf}() [commit]")
            prior = min(ln for ln in seen if ln != line)
            self.eng.emit(
                _SEAL, self.rel, line,
                f"'{leaf}' reached twice on one path (first at line "
                f"{prior}) — pane proof-commit is exactly-once per "
                f"pane", (chain_hop(self.rel, prior,
                                    f"{leaf}() [commit]"), hop),
                (self.rel, prior))
        self.once[leaf] = seen | {line}
        self.eng.transition_sites.setdefault((self.rel, line),
                                             "seal:commit")

    def _exit_via_finally(self, kind: str, line: int) -> None:
        """Exit-check after replaying pending ``finally`` bodies on a
        throwaway copy of the path state (``try: return conn.call(m)
        finally: conn.close()`` is a clean exit)."""
        if not self.finally_stack:
            self.exit_check(kind, line)
            return
        snap = self._snap()
        stack, self.finally_stack = self.finally_stack, []
        try:
            for fin in reversed(stack):
                self.exec_stmts(fin)
            self.exit_check(kind, line)
        finally:
            self.finally_stack = stack
            self._restore(snap)

    def exit_check(self, kind: str, line: int) -> None:
        """May-accept exit discipline: flag instances none of whose
        possible states is accepting (or unborn/poisoned)."""
        for res, sts in list(self.states.items()):
            if res.escaped or res.is_sentinel or res.proto is None:
                continue
            proto = res.proto
            if not proto.exit_error:
                continue
            if kind == "raise" and not proto.exit_on_raise:
                continue
            if sts & (proto.accepting | {"unborn", "poisoned"}):
                continue
            hop = chain_hop(self.rel, line,
                            f"{kind} [{'/'.join(sorted(sts))}]")
            self.eng.emit(proto, self.rel, line, proto.exit_error,
                          self.chains.get(res, ()) + (hop,), res.origin)
            self.states[res] = sts | {"poisoned"}

    def _escape(self, rs: Optional[FrozenSet[Resource]]) -> None:
        for r in rs or _EMPTY:
            if r.is_sentinel:
                self.param_escapes.add(r.param)
            else:
                r.escaped = True

    # -- statements --------------------------------------------------------

    def exec_stmts(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt)

    def _hint_text(self, node: Optional[ast.expr],
                   _depth: int = 0) -> str:
        """Lowered text of an expression for hint classification, with
        names transitively expanded through the simple local string
        assignments seen so far — so ``tmp = final + ".tmp"`` carries
        the durable hint of ``final`` into ``open(tmp, "w")``.
        Expansion is lazy (assigns record the raw RHS node) and
        depth-capped against self-referential rebinds."""
        if node is None:
            return ""
        text = _unparse(node).lower()
        if _depth < 4:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    ref = self.texts.get(sub.id)
                    if ref is not None and ref is not node:
                        text += " " + self._hint_text(ref, _depth + 1)
        return text

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            rs = self.eval_expr(stmt.value)
            for tgt in stmt.targets:
                self._bind(tgt, rs, stmt.lineno)
                # record the raw RHS for hint classification; _hint_text
                # expands it lazily at the few lookup sites
                if isinstance(tgt, ast.Name) and not isinstance(
                        stmt.value, (ast.Lambda, ast.ListComp,
                                     ast.SetComp, ast.DictComp,
                                     ast.GeneratorExp)):
                    self.texts[tgt.id] = stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self.eval_expr(stmt.value),
                           stmt.lineno)
        elif isinstance(stmt, ast.AugAssign):
            self.eval_expr(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self.eval_expr(stmt.value)
        elif isinstance(stmt, ast.Return):
            rs = self.eval_expr(stmt.value) if stmt.value is not None \
                else None
            for r in rs or _EMPTY:
                if r.is_sentinel:
                    self.ret_params.add(r.param)
                elif r.proto is not None:
                    r.escaped = True
                    if r.proto.key in ("conn", "seal"):
                        self.ret_new.append(
                            (r.proto.key,
                             self.states.get(r, frozenset()),
                             self.chains.get(r, ()), r.desc))
            self._exit_via_finally("return", stmt.lineno)
        elif isinstance(stmt, ast.If):
            self.eval_expr(stmt.test)
            snap = self._snap()
            self.exec_stmts(stmt.body)
            after_body = self._snap()
            self._restore(snap)
            self.exec_stmts(stmt.orelse)
            self._merge(after_body, self._snap())
        elif isinstance(stmt, (ast.For, ast.While)):
            self._exec_loop(stmt)
        elif isinstance(stmt, ast.With):
            self._exec_with(stmt)
        elif isinstance(stmt, ast.Try):
            self._exec_try(stmt)
        elif isinstance(stmt, ast.Raise):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self._escape(self.eval_expr(sub))
            self._exit_via_finally("raise", stmt.lineno)
        elif isinstance(stmt, ast.Assert):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self.eval_expr(sub)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                name = _dotted(tgt)
                if name is not None:
                    self.env.pop(name, None)
        # nested defs/classes are their own callgraph nodes; skip

    def _exec_loop(self, stmt) -> None:
        pre = self._snap()
        if isinstance(stmt, ast.For):
            self._bind(stmt.target, self.eval_expr(stmt.iter),
                       stmt.lineno)
        else:
            self.eval_expr(stmt.test)
        # two passes approximate the fixpoint (second pass sees
        # loop-carried states); join with the zero-trip path
        self.exec_stmts(stmt.body)
        self.exec_stmts(stmt.body)
        self._merge(pre, self._snap())
        self.exec_stmts(stmt.orelse)

    def _exec_with(self, stmt: ast.With) -> None:
        bound: List[FrozenSet[Resource]] = []
        for item in stmt.items:
            rs = self.eval_expr(item.context_expr)
            if item.optional_vars is not None:
                self._bind(item.optional_vars, rs, stmt.lineno)
            bound.append(rs or _EMPTY)
        self.exec_stmts(stmt.body)
        last = stmt.body[-1] if stmt.body else stmt
        for rs in reversed(bound):
            for r in rs:
                self.apply(r, "close", getattr(last, "lineno",
                                               stmt.lineno), "with-exit")

    def _exec_try(self, stmt: ast.Try) -> None:
        if stmt.finalbody:
            self.finally_stack.append(stmt.finalbody)
        pre_rids = set(self.states)
        union = self._snap()
        for sub in stmt.body:
            self.exec_stmt(sub)
            merged_from = (union, self._snap())
            keep = self._snap()
            self._merge(*merged_from)
            union = self._snap()
            self._restore(keep)
        post_body = self._snap()
        exits = []
        for h in stmt.handlers:
            self._restore(union)
            # instances created inside the body may not exist yet on
            # the handler edge: they are *unborn* there
            for r in list(self.states):
                if r not in pre_rids:
                    self.states[r] = self.states[r] | {"unborn"}
            self._mark_suspects(h)
            if h.name:
                self.env[h.name] = frozenset()
            self.exec_stmts(h.body)
            if not (h.body and isinstance(h.body[-1],
                                          (ast.Raise, ast.Continue))):
                exits.append(self._snap())
        self._restore(post_body)
        self.exec_stmts(stmt.orelse)
        exits.append(self._snap())
        self._merge(*exits)
        if stmt.finalbody:
            self.finally_stack.pop()
        self.exec_stmts(stmt.finalbody)

    def _mark_suspects(self, h: ast.ExceptHandler) -> None:
        names: Set[str] = set()
        types = h.type.elts if isinstance(h.type, ast.Tuple) else \
            ([h.type] if h.type is not None else [])
        for t in types:
            d = _dotted(t)
            if d:
                names.add(d)
                names.add(d.split(".")[-1])
        if not (names & _SUSPECT_EXC):
            return
        for r in list(self.states):
            if r.proto is _CONN and "checked-out" in self.states[r]:
                self.states[r] = (self.states[r] - {"checked-out"}) \
                    | {"suspect"}
                self.chains[r] = self.chains.get(r, ()) + (chain_hop(
                    self.rel, h.lineno,
                    f"except {'/'.join(sorted(names & _SUSPECT_EXC))} "
                    f"[suspect]"),)

    def _bind(self, tgt: ast.expr, rs: Optional[FrozenSet[Resource]],
              line: int) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind(el, rs, line)
            return
        if isinstance(tgt, ast.Starred):
            self._bind(tgt.value, rs, line)
            return
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            # stored beyond the frame: the instance escapes the walk
            self._escape(rs)
            return
        name = _dotted(tgt)
        if name is None:
            self._escape(rs)
            return
        old = self.env.get(name, _EMPTY)
        new = rs or _EMPTY
        # rebinding over a live non-accepting instance drops the only
        # reference: an in-scope leak
        for r in old - new:
            if r.escaped or r.is_sentinel or r.proto is None or \
                    not r.proto.exit_error:
                continue
            if any(r in others for n, others in self.env.items()
                   if n != name):
                continue
            sts = self.states.get(r)
            if sts is None or sts & (r.proto.accepting
                                     | {"unborn", "poisoned"}):
                continue
            hop = chain_hop(self.rel, line, "rebind [reference lost]")
            self.eng.emit(r.proto, self.rel, line, r.proto.exit_error,
                          self.chains.get(r, ()) + (hop,), r.origin)
            self.states[r] = sts | {"poisoned"}
        if new:
            self.env[name] = new
        else:
            self.env.pop(name, None)

    # -- expressions -------------------------------------------------------

    def eval_expr(self, e: Optional[ast.expr]
                  ) -> Optional[FrozenSet[Resource]]:
        if e is None:
            return None
        if isinstance(e, ast.Name):
            return self.env.get(e.id)
        if isinstance(e, ast.Attribute):
            dotted = _dotted(e)
            if dotted is not None and dotted in self.env:
                return self.env[dotted]
            self.eval_expr(e.value)
            return None                 # projection: not the resource
        if isinstance(e, ast.Call):
            return self.visit_call(e)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            out: Set[Resource] = set()
            for el in e.elts:
                out |= self.eval_expr(el) or _EMPTY
            return frozenset(out) or None
        if isinstance(e, ast.Starred):
            return self.eval_expr(e.value)
        if isinstance(e, ast.IfExp):
            self.eval_expr(e.test)
            return frozenset((self.eval_expr(e.body) or _EMPTY)
                             | (self.eval_expr(e.orelse) or _EMPTY)) \
                or None
        if isinstance(e, ast.BoolOp):
            out = set()
            for v in e.values:
                out |= self.eval_expr(v) or _EMPTY
            return frozenset(out) or None
        if isinstance(e, ast.NamedExpr):
            rs = self.eval_expr(e.value)
            self._bind(e.target, rs, e.lineno)
            return rs
        if isinstance(e, (ast.Await, ast.YieldFrom)):
            return self.eval_expr(e.value)
        if isinstance(e, ast.Yield):
            self._escape(self.eval_expr(e.value))
            return None
        if isinstance(e, (ast.BinOp, ast.UnaryOp, ast.Compare,
                          ast.JoinedStr, ast.FormattedValue,
                          ast.Subscript, ast.Dict, ast.ListComp,
                          ast.GeneratorExp, ast.SetComp, ast.DictComp,
                          ast.Lambda)):
            for sub in ast.iter_child_nodes(e):
                if isinstance(sub, ast.expr):
                    self.eval_expr(sub)
            return None
        return None

    # -- calls -------------------------------------------------------------

    def visit_call(self, call: ast.Call
                   ) -> Optional[FrozenSet[Resource]]:
        args_r: List[Tuple[Optional[str], Optional[FrozenSet[Resource]],
                           ast.expr]] = []
        for a in call.args:
            args_r.append((None, self.eval_expr(a), a))
        for kw in call.keywords:
            args_r.append((kw.arg, self.eval_expr(kw.value), kw.value))
        recv_r: Optional[FrozenSet[Resource]] = None
        recv_name: Optional[str] = None
        if isinstance(call.func, ast.Attribute):
            recv_name = _dotted(call.func.value)
            if recv_name is not None and recv_name in self.env:
                recv_r = self.env[recv_name]
            else:
                self.eval_expr(call.func.value)
        dotted = _dotted(call.func)
        leaf = dotted.split(".")[-1] if dotted else ""
        line = call.lineno

        # handle passthrough: os.fsync(f.fileno()) addresses f
        if leaf == "fileno" and recv_r:
            return recv_r

        matched = self._match_events(call, dotted, leaf, line, args_r,
                                     recv_r, recv_name)
        created = self._match_creations(call, dotted, leaf, line,
                                        args_r, recv_name)
        if created is not None:
            return created
        if matched:
            return None

        callee_fid = self.sites.get(id(call))
        if callee_fid is not None:
            return self._call_summary(call, callee_fid, leaf, line,
                                      args_r, recv_r)

        # unresolved call: argument instances may be stored anywhere —
        # they escape (receivers of method calls do not)
        for _, rs, _node in args_r:
            self._escape(rs)
        return None

    # -- event matchers ----------------------------------------------------

    def _match_events(self, call, dotted, leaf, line, args_r, recv_r,
                      recv_name) -> bool:
        matched = False
        pos = [rs for name, rs, _ in args_r if name is None]

        # ambient journal append: journals every live claimed slab and
        # is itself an accepted-by-delegation durable write
        if leaf in _JOURNAL_LEAVES:
            for r in list(self.states):
                if r.proto is _SLAB:
                    self.apply(r, "ledger", line, leaf)
            self.eng.creation_sites.setdefault(
                (self.rel, line), "atomic:journal-append (delegated)")
            self.eng.transition_sites.setdefault((self.rel, line),
                                                 "atomic:ledger")
            return True

        if leaf in _DELEGATED_ATOMIC:
            self.eng.creation_sites.setdefault(
                (self.rel, line), "atomic:tmp-fsync-rename (delegated)")
            return True

        if leaf in _ONCE_LEAVES:
            self._once_event(leaf, line)
            return True

        # os.rename/os.replace: publishes a tmp file (atomic) —
        # claim-renames are creations, handled by the creation matcher
        if dotted in ("os.rename", "os.replace") and len(call.args) == 2:
            src = _unparse(call.args[0])
            for r in list(self.states):
                if r.proto is _ATOMIC and src and src in r.aliases:
                    self.apply(r, "rename", line, leaf)
                    matched = True

        # slab alias events: text-addressed (the claimed path is a
        # string, not a tracked object); arg texts are rendered only
        # for the leaves that can consume them — unparse dominates the
        # walk otherwise
        if leaf in ("unlink", "remove") or leaf in _SLAB_READ_LEAVES:
            arg_texts = [_unparse(node) for name, _, node in args_r]
            if leaf in ("unlink", "remove") and arg_texts:
                for r in list(self.states):
                    if r.proto is _SLAB and arg_texts[0] in r.aliases:
                        self.apply(r, "unlink", line, leaf)
                        matched = True
            if leaf in _SLAB_READ_LEAVES:
                for r in list(self.states):
                    if r.proto is _SLAB and \
                            any(t in r.aliases for t in arg_texts if t):
                        self.apply(r, "read", line, leaf)
                        matched = True

        # pool return/discard: arg-addressed, one positional argument
        if leaf == "put" and len(call.args) == 1 and not call.keywords \
                and pos and pos[0]:
            for r in pos[0]:
                if r.is_sentinel or r.proto is _CONN:
                    self.apply(r, "put", line, leaf)
                    matched = True
        if leaf == "discard" and call.args and pos and pos[0]:
            for r in pos[0]:
                if r.is_sentinel or r.proto is _CONN:
                    self.apply(r, "discard", line, leaf)
                    matched = True

        # 2-arg put: seals the key given as the first argument
        if leaf == "put" and len(call.args) == 2:
            sealed = False
            for r in (pos[0] or _EMPTY) if pos else _EMPTY:
                if r.is_sentinel or r.proto is _SEAL:
                    self.apply(r, "seal", line, leaf)
                    sealed = True
                    matched = True
            if not sealed:
                key_text = self._hint_text(call.args[0])
                if any(h in key_text for h in ("ckpt", "pane")):
                    r = self.new_resource(
                        _SEAL, line, "keyed durable write",
                        frozenset({"sealed"}))
                    r.escaped = True
                    self.eng.transition_sites.setdefault(
                        (self.rel, line), "seal:seal")
                    matched = True

        # receiver-addressed transitions
        if recv_r:
            token = {"call": "use", "close": "close", "enter": "enter",
                     "save": "save", "write": "write",
                     "writelines": "write"}.get(leaf)
            if token is not None:
                for r in recv_r:
                    self.apply(r, token, line, leaf)
                matched = True

        # handle-as-argument writes and fsync
        if leaf in _HANDLE_WRITE_LEAVES:
            for rs in pos:
                for r in rs or _EMPTY:
                    if r.is_sentinel or r.proto is _ATOMIC:
                        self.apply(r, "write", line, leaf)
                        matched = True
        if dotted == "os.fsync" and pos and pos[0]:
            for r in pos[0]:
                self.apply(r, "fsync", line, leaf)
            matched = True
        return matched

    # -- creation matchers -------------------------------------------------

    def _match_creations(self, call, dotted, leaf, line, args_r,
                         recv_name) -> Optional[FrozenSet[Resource]]:
        n_args = len(call.args) + len(call.keywords)

        # builtin open(): classify the path expression
        if isinstance(call.func, ast.Name) and call.func.id == "open" \
                and "open" not in self.locals and call.args:
            return self._open_resource(call, line)

        # pool checkout / direct conn construction
        if leaf == "get" and recv_name is not None and n_args >= 2 and \
                recv_name.split(".")[-1].lower().endswith("pool"):
            r = self.new_resource(
                _CONN, line, f"{recv_name}.get checkout",
                frozenset({"checked-out"}))
            return frozenset({r})
        if leaf == "Conn" and n_args >= 2:
            r = self.new_resource(_CONN, line, "Conn(...) construction",
                                  frozenset({"checked-out"}))
            return frozenset({r})

        # claim-rename starts a slab consumption
        if dotted in ("os.rename", "os.replace") and \
                len(call.args) == 2 and \
                "claim" in self._hint_text(call.args[1]):
            dst = call.args[1]
            aliases = {_unparse(dst)}
            if isinstance(dst, ast.Name):
                aliases.add(dst.id)
            r = self.new_resource(_SLAB, line, "claim-rename",
                                  frozenset({"claimed"}),
                                  aliases=frozenset(a for a in aliases
                                                    if a))
            return frozenset({r})

        # pane seal keys and checkpoints
        if leaf == "pane_key":
            r = self.new_resource(_SEAL, line, "pane key",
                                  frozenset({"fresh"}))
            return frozenset({r})
        if leaf == "SurveyCheckpoint" and \
                isinstance(call.func, ast.Name):
            r = self.new_resource(_SEAL, line, "fresh checkpoint",
                                  frozenset({"fresh-ck"}))
            return frozenset({r})
        if leaf == "load" and recv_name is not None and \
                recv_name.split(".")[-1] == "SurveyCheckpoint":
            r = self.new_resource(_SEAL, line, "loaded checkpoint",
                                  frozenset({"resumed-ck"}))
            return frozenset({r})

        # delegated durable stores (coverage: the store owns the idiom)
        if leaf in _DB_CTORS:
            self.eng.creation_sites.setdefault(
                (self.rel, line), "atomic:durable store (delegated)")
            return None
        return None

    def _open_resource(self, call: ast.Call,
                       line: int) -> Optional[FrozenSet[Resource]]:
        path_node = call.args[0]
        path_text = self._hint_text(path_node)
        mode = "r"
        if len(call.args) >= 2 and \
                isinstance(call.args[1], ast.Constant) and \
                isinstance(call.args[1].value, str):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        durable = any(h in path_text for h in DURABLE_HINTS)
        tmpish = any(h in path_text for h in TMP_HINTS)
        fn_durable = any(h in self.fn.node.name.lower()
                         for h in FN_DURABLE_HINTS)
        aliases = {_unparse(path_node)}
        if isinstance(path_node, ast.Name):
            aliases.add(path_node.id)
        aliases = frozenset(a for a in aliases if a)

        if "w" in mode or "x" in mode:
            if tmpish and (durable or fn_durable):
                r = self.new_resource(_ATOMIC, line, "tmp-file open",
                                      frozenset({"open"}),
                                      aliases=aliases)
                return frozenset({r})
            if durable and not tmpish:
                r = self.new_resource(_ATOMIC, line,
                                      "in-place durable open",
                                      frozenset({"in-place"}),
                                      aliases=aliases)
                return frozenset({r})
            r = self.new_resource(_ATOMIC, line, "scratch open",
                                  frozenset({"relaxed"}),
                                  aliases=aliases)
            return frozenset({r})
        if "a" in mode:
            if durable:
                if self.eng.module_declares_replay(self.rel):
                    r = self.new_resource(
                        _ATOMIC, line, "declared-replay journal append",
                        frozenset({"journal"}), aliases=aliases)
                    return frozenset({r})
                r = self.new_resource(_ATOMIC, line,
                                      "journal append", frozenset(),
                                      aliases=aliases)
                self.states[r] = frozenset({"poisoned"})
                self.eng.emit(
                    _ATOMIC, self.rel, line,
                    "append-mode open on a durable path in a module "
                    "with no torn-tail replay routine — a crash "
                    "mid-append leaves an unreadable tail; add a "
                    "*_replay loader or write tmp → fsync → "
                    "os.replace", self.chains[r], r.origin)
                return frozenset({r})
            r = self.new_resource(_ATOMIC, line, "scratch append",
                                  frozenset({"relaxed"}),
                                  aliases=aliases)
            return frozenset({r})
        if durable:
            r = self.new_resource(_ATOMIC, line, "replay read",
                                  frozenset({"replay-read"}),
                                  aliases=aliases)
            return frozenset({r})
        return None

    # -- interprocedural ---------------------------------------------------

    def _call_summary(self, call: ast.Call, callee_fid: str, leaf: str,
                      line: int,
                      args_r, recv_r) -> Optional[FrozenSet[Resource]]:
        summ = self.eng._summary(callee_fid, self.depth + 1)
        if summ is _EMPTY_SUMMARY or (not summ.param_events
                                      and not summ.param_escapes
                                      and not summ.ret_params
                                      and not summ.ret_new):
            for _, rs, _node in args_r:
                self._escape(rs)
            return None
        is_method = (isinstance(call.func, ast.Attribute)
                     and bool(summ.params)
                     and summ.params[0] in ("self", "cls"))
        by_param: Dict[str, Optional[FrozenSet[Resource]]] = {}
        if is_method:
            by_param[summ.params[0]] = recv_r
        offset = 1 if is_method else 0
        pos = [rs for name, rs, _ in args_r if name is None]
        for i, rs in enumerate(pos):
            if offset + i < len(summ.params):
                by_param[summ.params[offset + i]] = rs
        for name, rs, _node in args_r:
            if name is not None:
                by_param[name] = rs
        call_hop = chain_hop(self.rel, line, f"{leaf}(...)")
        for param, token, hop in summ.param_events:
            for r in by_param.get(param) or _EMPTY:
                if r.is_sentinel:
                    if token in _SENTINEL_TOKENS:
                        self.param_events.append((r.param, token, hop))
                else:
                    self.chains[r] = self.chains.get(r, ()) + (call_hop,)
                    self.apply(r, token, line, leaf)
        for param in summ.param_escapes:
            self._escape(by_param.get(param))
        out: Set[Resource] = set()
        for param in summ.ret_params:
            out |= by_param.get(param) or _EMPTY
        for proto_key, sts, chain, desc in summ.ret_new:
            proto = PROTOCOLS[proto_key]
            r = self.new_resource(
                proto, line, desc,
                sts or frozenset({"poisoned"}),
                chain=chain + (chain_hop(self.rel, line,
                                         f"{leaf}() returns {desc}"),))
            out.add(r)
        return frozenset(out) or None


# -- memoized entry point ----------------------------------------------------

_TS_CACHE: Dict[str, Typestate] = {}
_TS_CACHE_MAX = 8


def typestate_for(project: ProjectInfo,
                  focus: Optional[FrozenSet[str]] = None) -> Typestate:
    """The (memoized) engine run for a project. ``focus`` narrows the
    walked module set for ``--changed-only`` (summaries for callees
    outside the focus are still computed on demand); focused runs are
    cached under a salted key like :func:`dataflow_for`."""
    fp = project_fingerprint(project)
    if focus is not None:
        fp = fp + "|" + ",".join(sorted(focus))
    eng = _TS_CACHE.get(fp)
    if eng is None:
        if len(_TS_CACHE) >= _TS_CACHE_MAX:
            _TS_CACHE.clear()
        eng = Typestate(project, focus=focus).run()
        _TS_CACHE[fp] = eng
    return eng
