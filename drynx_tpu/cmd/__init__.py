"""CLI entry points (reference cmd/server, cmd/client): TOML-over-stdin/
stdout config pipeline driving surveys."""
