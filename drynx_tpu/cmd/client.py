"""Client CLI: composable TOML config pipeline over stdin/stdout.

Mirrors the reference cmd/client (main.go:33-69, network.go, survey.go):

  network new                      -> empty network config on stdout
  network add-node --role cn ...   -> appends a node (reads cfg on stdin)
  network set-client               -> attaches a fresh querier keypair
  survey new --operation sum ...   -> adds the survey section
  survey run                       -> runs the survey against the network

Two network modes:
  * remote  — nodes are running `server run` processes (TCP control plane)
  * local   — `survey run --local` spins an in-process LocalCluster with the
              configured role counts (the reference's 3-node demo wiring,
              cmd/client/survey.go:96-104)
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..crypto import elgamal as eg
from . import toml_io


def _read_cfg() -> dict:
    text = sys.stdin.read()
    return toml_io.loads(text) if text.strip() else {}


def _emit(cfg: dict) -> int:
    sys.stdout.write(toml_io.dumps(cfg))
    return 0


def cmd_network_new(args) -> int:
    return _emit({"nodes": []})


def cmd_network_add_node(args) -> int:
    cfg = _read_cfg()
    nodes = cfg.setdefault("nodes", [])
    host, _, port = args.address.partition(":")
    node = {"name": args.name or f"{args.role}{len(nodes)}",
            "role": args.role, "host": host or "127.0.0.1",
            "port": int(port or 0)}
    if args.public:
        x, _, y = args.public.partition(",")
        node["public_x"], node["public_y"] = x, y
    nodes.append(node)
    return _emit(cfg)


def cmd_network_set_client(args) -> int:
    cfg = _read_cfg()
    rng = np.random.default_rng()
    secret, public = eg.keygen(rng)
    cfg["client"] = {"secret": hex(secret), "public_x": hex(public[0]),
                     "public_y": hex(public[1])}
    # writing the freshly generated keypair to the operator's config is
    # this command's whole purpose (key-store TOML, never logged)
    return _emit(cfg)  # drynx: noqa[secret-flow-to-sink]


def cmd_survey_new(args) -> int:
    cfg = _read_cfg()
    cfg["survey"] = {"operation": args.operation, "query_min": args.min,
                     "query_max": args.max, "proofs": bool(args.proofs),
                     "obfuscation": bool(args.obfuscation)}
    if args.operation == "log_reg":
        cfg["survey"]["lr"] = {
            "features": args.lr_features, "records": args.lr_records,
            "k": args.lr_k, "precision": args.lr_precision,
            "iterations": args.lr_iterations, "step": args.lr_step,
            "lambda": args.lr_lambda}
    return _emit(cfg)


def _lr_params_of(sv: dict):
    from ..models.logreg import LRParams

    lr_cfg = sv.get("lr", {})
    if not lr_cfg.get("features") or not lr_cfg.get("records"):
        raise SystemExit(
            "log_reg survey config is missing its lr section — re-run "
            "`survey new --operation log_reg --lr-features D --lr-records N`")
    return LRParams(
        k=int(lr_cfg.get("k", 2)),
        precision=float(lr_cfg.get("precision", 1e2)),
        lambda_=float(lr_cfg.get("lambda", 1.0)),
        step=float(lr_cfg.get("step", 0.1)),
        max_iterations=int(lr_cfg.get("iterations", 25)),
        n_features=int(lr_cfg["features"]),
        n_records=int(lr_cfg["records"]))


def cmd_survey_set_operation(args) -> int:
    cfg = _read_cfg()
    cfg.setdefault("survey", {})["operation"] = args.operation
    return _emit(cfg)


def cmd_survey_run(args) -> int:
    cfg = _read_cfg()
    sv = cfg.get("survey", {})
    op = sv.get("operation", "sum")
    qmin, qmax = int(sv.get("query_min", 0)), int(sv.get("query_max", 0))

    if args.local:
        from ..service.api import DrynxClient
        from ..service.service import LocalCluster

        roles = [n.get("role") for n in cfg.get("nodes", [])]
        cluster = LocalCluster(
            n_cns=max(roles.count("cn"), 1),
            n_dps=max(roles.count("dp"), 1),
            n_vns=roles.count("vn") if sv.get("proofs") else 0,
            dlog_limit=int(sv.get("dlog_limit", 10000)))
        client = DrynxClient(cluster)
        if args.serve > 1:
            # standing-server mode: N copies of the survey through the
            # scheduler (SERVER.md) — equal shapes batch at the VNs
            from ..server import SurveyServer

            server = SurveyServer(cluster, max_batch=args.serve)
            admissions = {}
            for i in range(args.serve):
                sq = client.generate_survey_query(
                    op, query_min=qmin, query_max=qmax,
                    proofs=1 if sv.get("proofs") else 0,
                    obfuscation=bool(sv.get("obfuscation", False)),
                    survey_id=f"cli{i}")
                admissions[sq.survey_id] = server.submit(sq)
            results = server.drain()
            out = {"operation": op, "surveys": {}}
            ok = True
            for sid, a in admissions.items():
                res = results.get(sid)
                if isinstance(res, Exception):
                    out["surveys"][sid] = {"lane": a.lane,
                                           "error": str(res)}
                    ok = False
                    continue
                entry = {"lane": a.lane, "result": _jsonable(res.result)}
                if res.block is not None:
                    entry["bitmap_ok"] = all(
                        v == 1 for v in res.block.data.bitmap.values())
                    ok = ok and entry["bitmap_ok"]
                out["surveys"][sid] = entry
            print(json.dumps(out))
            return 0 if ok else 1
        sq = client.generate_survey_query(
            op, query_min=qmin, query_max=qmax,
            proofs=1 if sv.get("proofs") else 0,
            obfuscation=bool(sv.get("obfuscation", False)))
        res = client.send_survey_query(sq)
        out = {"survey_id": res.survey_id, "operation": op,
               "result": _jsonable(res.result)}
        if res.block is not None:
            out["block_hash"] = res.block.hash()
            out["bitmap_ok"] = all(v == 1
                                   for v in res.block.data.bitmap.values())
        print(json.dumps(out))
        return 0

    # remote mode: drive running server processes
    from ..service.node import RemoteClient, Roster, RosterEntry
    from ..service.transport import Conn

    entries = []
    for n in cfg.get("nodes", []):
        pub = (int(n["public_x"], 16), int(n["public_y"], 16))
        entries.append(RosterEntry(name=n["name"], role=n["role"],
                                   host=n["host"], port=int(n["port"]),
                                   public=pub))
    roster = Roster(entries)
    client = RemoteClient(roster)
    client.broadcast_roster()
    lr_params = _lr_params_of(sv) if op == "log_reg" else None
    ranges = None
    if op == "log_reg" and sv.get("proofs"):
        # uniform spec; the signed-offset shift (u^l/2) keeps negative
        # fixed-point coefficients inside the proved range
        ranges = [(16, 5)] * lr_params.num_coeffs()
    if sv.get("proofs"):
        from ..resilience import policy as rp

        result, block = client.run_survey(
            op, query_min=qmin, query_max=qmax, proofs=True,
            obfuscation=bool(sv.get("obfuscation", False)),
            lr_params=lr_params, ranges=ranges,
            timeout=float(sv.get("proof_timeout",
                                 2 * rp.COLD_COMPILE_WAIT_S)))
        bitmap = block.get("bitmap", {})
        print(json.dumps({"operation": op, "result": _jsonable(result),
                          "block_hash": block.get("block_hash"),
                          "bitmap_ok": bool(bitmap) and
                          all(v == 1 for v in bitmap.values())}))
        return 0
    result = client.run_survey(op, query_min=qmin, query_max=qmax,
                               lr_params=lr_params)
    print(json.dumps({"operation": op, "result": _jsonable(result)}))
    return 0


def _jsonable(x):
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    return x


def main(argv=None) -> int:
    from ..utils.backend import pin_platform_from_env

    pin_platform_from_env()   # a down TPU tunnel must not hang CPU clients
    p = argparse.ArgumentParser(prog="drynx-client")
    sub = p.add_subparsers(dest="group", required=True)

    net = sub.add_parser("network").add_subparsers(dest="cmd", required=True)
    n_new = net.add_parser("new")
    n_new.set_defaults(fn=cmd_network_new)
    n_add = net.add_parser("add-node")
    n_add.add_argument("--role", required=True, choices=["cn", "dp", "vn"])
    n_add.add_argument("--name", default=None)
    n_add.add_argument("--address", default="127.0.0.1:0")
    n_add.add_argument("--public", default=None,
                       help="x,y affine ints (hex) for remote nodes")
    n_add.set_defaults(fn=cmd_network_add_node)
    n_set = net.add_parser("set-client")
    n_set.set_defaults(fn=cmd_network_set_client)

    srv = sub.add_parser("survey").add_subparsers(dest="cmd", required=True)
    s_new = srv.add_parser("new")
    s_new.add_argument("--operation", default="sum")
    s_new.add_argument("--min", type=int, default=0)
    s_new.add_argument("--max", type=int, default=0)
    s_new.add_argument("--proofs", action="store_true")
    s_new.add_argument("--obfuscation", action="store_true")
    s_new.add_argument("--lr-features", type=int, default=0,
                       help="log_reg: number of features d")
    s_new.add_argument("--lr-records", type=int, default=0,
                       help="log_reg: TOTAL records across all DPs (N)")
    s_new.add_argument("--lr-k", type=int, default=2)
    s_new.add_argument("--lr-precision", type=float, default=1e2)
    s_new.add_argument("--lr-iterations", type=int, default=25)
    s_new.add_argument("--lr-step", type=float, default=0.1)
    s_new.add_argument("--lr-lambda", type=float, default=1.0)
    s_new.set_defaults(fn=cmd_survey_new)
    s_op = srv.add_parser("set-operation")
    s_op.add_argument("--operation", required=True)
    s_op.set_defaults(fn=cmd_survey_set_operation)
    s_run = srv.add_parser("run")
    s_run.add_argument("--local", action="store_true")
    s_run.add_argument("--serve", type=int, default=1, metavar="N",
                       help="local only: submit N copies of the survey "
                            "through the standing SurveyServer scheduler "
                            "(batched verification; see SERVER.md)")
    s_run.set_defaults(fn=cmd_survey_run)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
