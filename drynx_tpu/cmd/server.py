"""Server CLI: `gen` emits a node config (TOML, stdout); `run` boots the node.

Mirrors the reference cmd/server (main.go:42-126): `server gen` creates the
keypair + address config on stdout; `server run` reads the config from stdin
and serves until killed. One binary, role decided by the roster
(cmd/README.md:13-18).

Usage:
  python -m drynx_tpu.cmd.server gen --address 127.0.0.1:7000 --name cn0
  python -m drynx_tpu.cmd.server run < node.toml
"""
from __future__ import annotations

import argparse
import sys

import numpy as np

from ..crypto import elgamal as eg
from . import toml_io


def cmd_gen(args) -> int:
    host, _, port = args.address.partition(":")
    rng = np.random.default_rng()
    secret, public = eg.keygen(rng)
    cfg = {"node": {
        "name": args.name,
        "host": host or "127.0.0.1",
        "port": int(port or 0),
        "secret": hex(secret),
        "public_x": hex(public[0]),
        "public_y": hex(public[1]),
    }}
    # emitting the generated node keypair as TOML is this command's whole
    # purpose (key-store file, operator-only stdout)
    sys.stdout.write(toml_io.dumps(cfg))  # drynx: noqa[secret-flow-to-sink]
    return 0


def cmd_run(args) -> int:
    import os

    from ..service.node import DrynxNode

    # Tree-role wiring: the overlay is derived from the dialed roster, so a
    # relay needs no config — but the *dispatching* root reads these knobs,
    # and any process may become root for a survey it initiates. CLI flags
    # land in the env so service/topology.py sees one source of truth.
    if args.topology:
        os.environ["DRYNX_TOPOLOGY"] = args.topology
    if args.tree_fanout:
        os.environ["DRYNX_TREE_FANOUT"] = str(args.tree_fanout)

    cfg = toml_io.loads(sys.stdin.read())["node"]
    data = None
    if args.lr_data:
        # (X, y) DP data for log_reg surveys: CSV, label in column 0
        # (reference LoadData, lib/encoding/logistic_regression.go:1275)
        from ..models import logreg as lr

        data = lr.load_csv(args.lr_data)
    elif args.data:
        data = np.loadtxt(args.data, dtype=np.int64, ndmin=1)
    pool = None
    if args.pool:
        from ..pool import CryptoPool

        pool = CryptoPool(args.pool)
    node = DrynxNode(cfg["name"], int(cfg["secret"], 16),
                     (int(cfg["public_x"], 16), int(cfg["public_y"], 16)),
                     host=cfg.get("host", "127.0.0.1"),
                     port=int(cfg.get("port", 0)), data=data,
                     db_path=args.db, pool=pool)
    print(f"drynx node {cfg['name']} listening on "
          f"{node.address[0]}:{node.address[1]}", file=sys.stderr, flush=True)
    try:
        node.server.serve_forever()
    except KeyboardInterrupt:
        node.stop()
    return 0


def main(argv=None) -> int:
    from ..utils.backend import pin_platform_from_env

    pin_platform_from_env()   # a down TPU tunnel must not hang CPU nodes
    p = argparse.ArgumentParser(prog="drynx-server")
    sub = p.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("gen", help="generate node config TOML on stdout")
    g.add_argument("--address", default="127.0.0.1:0")
    g.add_argument("--name", default="node")
    g.set_defaults(fn=cmd_gen)
    r = sub.add_parser("run", help="run node from config TOML on stdin")
    r.add_argument("--data", default=None,
                   help="path to this DP's local data (one int per line)")
    r.add_argument("--lr-data", default=None,
                   help="path to this DP's (X, y) CSV for log_reg surveys "
                        "(label in column 0)")
    r.add_argument("--db", default=None,
                   help="proof/skipchain DB path (VN role)")
    r.add_argument("--pool", default=None,
                   help="crypto-pool directory (CryptoPool): DRO slabs "
                        "for shuffle contributions + persisted sig/fb "
                        "tables warm-start this process. $DRYNX_POOL_DIR "
                        "is the env equivalent.")
    r.add_argument("--topology", default=None, choices=["tree", "star"],
                   help="survey dispatch overlay when this node roots a "
                        "survey: tree (default) relays contributions up a "
                        "roster-derived forest; star is the flat fan-out "
                        "kill-switch. $DRYNX_TOPOLOGY is the env "
                        "equivalent.")
    r.add_argument("--tree-fanout", type=int, default=None,
                   help="tree branching factor override (else "
                        "ceil(sqrt(n)) clamped to policy bounds). "
                        "$DRYNX_TREE_FANOUT is the env equivalent.")
    r.set_defaults(fn=cmd_run)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
