"""Minimal TOML emit/parse for the CLI config pipeline.

The reference pipes TOML configs through stdin/stdout between composable
commands (cmd/README.md:7-9, cmd/client/config.go:14-123). Python ships a
TOML reader (tomllib) but no writer, so a small emitter for our config shape
(tables, arrays of tables, scalar/list values) lives here.
"""
from __future__ import annotations

try:
    import tomllib  # python >= 3.11
except ModuleNotFoundError:
    import tomli as tomllib  # same API; tomllib is tomli vendored


def loads(text: str) -> dict:
    return tomllib.loads(text)


def _fmt_val(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt_val(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value {type(v)}")


def _emit_table(out: list, name: str, item: dict, is_array: bool) -> None:
    out.append("")
    out.append(f"[[{name}]]" if is_array else f"[{name}]")
    nested = []
    for k, v in item.items():
        if isinstance(v, dict):
            if is_array:
                # [[name]] + [name.k] would attach to the LAST array element
                # in TOML semantics — ambiguous; nothing in the config shape
                # needs it
                raise TypeError(
                    f"nested table {k!r} inside array-of-tables {name!r}")
            nested.append((f"{name}.{k}", v))
        else:
            out.append(f"{k} = {_fmt_val(v)}")
    for sub, v in nested:
        _emit_table(out, sub, v, False)   # dotted header: [survey.lr]


def dumps(d: dict) -> str:
    out = []
    tables = []
    for k, v in d.items():
        if isinstance(v, dict):
            tables.append((k, [v], False))
        elif isinstance(v, list) and v and all(isinstance(x, dict) for x in v):
            tables.append((k, v, True))
        else:
            out.append(f"{k} = {_fmt_val(v)}")
    for name, items, is_array in tables:
        for item in items:
            _emit_table(out, name, item, is_array)
    return "\n".join(out) + "\n"


__all__ = ["loads", "dumps"]
