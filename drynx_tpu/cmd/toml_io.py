"""Minimal TOML emit/parse for the CLI config pipeline.

The reference pipes TOML configs through stdin/stdout between composable
commands (cmd/README.md:7-9, cmd/client/config.go:14-123). Python ships a
TOML reader (tomllib) but no writer, so a small emitter for our config shape
(tables, arrays of tables, scalar/list values) lives here.
"""
from __future__ import annotations

import tomllib


def loads(text: str) -> dict:
    return tomllib.loads(text)


def _fmt_val(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt_val(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value {type(v)}")


def dumps(d: dict) -> str:
    out = []
    tables = []
    for k, v in d.items():
        if isinstance(v, dict):
            tables.append((k, [v], False))
        elif isinstance(v, list) and v and all(isinstance(x, dict) for x in v):
            tables.append((k, v, True))
        else:
            out.append(f"{k} = {_fmt_val(v)}")
    for name, items, is_array in tables:
        for item in items:
            out.append("")
            out.append(f"[[{name}]]" if is_array else f"[{name}]")
            for k, v in item.items():
                out.append(f"{k} = {_fmt_val(v)}")
    return "\n".join(out) + "\n"


__all__ = ["loads", "dumps"]
