"""Kernel precompile + trace-dedup layer (see registry.py docstring).

Public surface:
  Profile, BENCH          — survey shape parameters for the program set
  build_registry(profile) — enumerate ProgramSpecs (no tracing)
  precompile(profile)     — serial trace/lower/compile driver
  trace_guard()           — recursion-limit + thread-stack-size guard
  WORKER_OPS, worker_specs — the verify-worker dispatch set (the ops a
                            server verify worker may jit-dispatch; the
                            compile lane's execute filter on CPU)
  STATS, CompileStats     — per-program timings + persistent-cache counters

CLI: python -m drynx_tpu.precompile [--dry-run]
"""
from .registry import (BENCH, WORKER_OPS, Profile, ProgramSpec,
                       build_registry, precompile, trace_guard,
                       worker_specs)
from .stats import STATS, CompileStats, install_cache_listener

__all__ = ["BENCH", "WORKER_OPS", "Profile", "ProgramSpec",
           "build_registry", "precompile", "trace_guard", "worker_specs",
           "STATS", "CompileStats", "install_cache_listener"]
