"""Kernel precompile + trace-dedup layer (see registry.py docstring).

Public surface:
  Profile, BENCH          — survey shape parameters for the program set
  build_registry(profile) — enumerate ProgramSpecs (no tracing)
  precompile(profile)     — serial trace/lower/compile driver
  trace_guard()           — recursion-limit + thread-stack-size guard
  STATS, CompileStats     — per-program timings + persistent-cache counters

CLI: python -m drynx_tpu.precompile [--dry-run]
"""
from .registry import (BENCH, Profile, ProgramSpec, build_registry,
                       precompile, trace_guard)
from .stats import STATS, CompileStats, install_cache_listener

__all__ = ["BENCH", "Profile", "ProgramSpec", "build_registry",
           "precompile", "trace_guard", "STATS", "CompileStats",
           "install_cache_listener"]
