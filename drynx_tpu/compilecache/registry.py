"""AOT kernel precompile registry: the finite program set of a proofs-on
survey, declared as (kernel, bucket shape, dtype) entries.

A cold process used to discover every program lazily, mid-survey, from
whichever thread touched it first — tens of minutes of serialized trace +
compile inside the timed bench window, and (worse) first-touch TRACING on
`_async_proof` / dp_lists worker threads, whose default 8 MB C stacks
overflow under partial_eval's recursion on the pairing kernels (the r05
segfault class, service.py:500). This registry makes the program set
explicit so it can be driven SERIALLY, on the MAIN thread, before any
survey starts:

  * the `bucketed()` crypto family (crypto/batching.py BUCKETED_OPS) at
    the bucket sizes a proofs-on survey dispatches,
  * the raw Pallas pairing entry points (miller / windowed-pow /
    mulreduce8) at their flat dispatch shapes,
  * the range-proof create/verify compositions (covered through the
    bucketed primitives they dispatch — _commit_kernel, _response_kernel,
    _verify_kernel and the RLC prelude are pure compositions),
  * the fused exec pipeline (service._fused_enc/_agg/_ks/_dec).

`jax.jit(...).lower(...).compile()` on each entry feeds the persistent XLA
cache (utils/cache.py), so the next process pays lowering only. On CPU,
`--dry-run` traces + lowers exactly the programs the CPU backend would
dispatch (host-oracle detours and Pallas-only kernels are enumerated but
skipped) — a fast structural check that every registered program still
traces.

Batch sizes derive from a Profile (defaults = the flagship bench survey:
3 CNs, 10 DPs, V=9 logreg coefficients, (u=16, l=5) ranges). They are the
canonical POST-bucketing shapes, so nearby survey configurations land on
the same executables.
"""
from __future__ import annotations

import dataclasses
import sys
import time
from typing import Callable

from .stats import STATS, CompileStats, install_cache_listener

NL = 16  # limbs per field element (crypto/params.py)


@dataclasses.dataclass(frozen=True)
class Profile:
    """Survey shape parameters the program set derives from."""

    n_cns: int = 3
    n_dps: int = 10
    n_values: int = 9       # V: logreg num_coeffs for pima d=8
    u: int = 16             # range-proof base
    l: int = 5              # range-proof digits
    dlog_limit: int = 10000
    n_shards: int = 1       # proof-plane shards (parallel/proof_plane.py);
                            # >1 adds the per-shard program set
    n_queue: int = 1        # cross-survey batch width (drynx_tpu/server):
                            # >1 adds the cross-survey verify program set
                            # at n_queue-concatenated batch sizes
    n_buckets: int = 0      # bucket-grid width of a grid-op survey
                            # (min/max/frequency_count/union/inter:
                            # n_values == n_buckets, ranges (u=2, l=1)).
                            # Above encoding/tiles.TILE_THRESHOLD the
                            # tiled dispatch path engages and adds the
                            # tile-shard program set (_bucket_schemas)
                            # plus fused enc at tile slab widths. 0 (the
                            # default) = non-grid survey, no extra
                            # programs, so plain registries stay a subset
                            # of bucket-grid ones (test_precompile.py).
    n_noise: int = 0        # DRO noise-list size of a diffp survey: > 0
                            # adds the pool/DRO slab program set
                            # (_pool_specs) at parallel/dro.slab_widths —
                            # the raw jits the precompute/refill and
                            # shuffle paths dispatch. 0 (default) = no
                            # diffp, no extra programs, so plain
                            # registries stay a subset of pooled ones
                            # (test_precompile.py enforces both
                            # directions, mirroring n_buckets).
    n_fold: int = 0         # tree-overlay fold stack height
                            # (service/topology.fold_cts): the LARGEST
                            # (k, V) ciphertext stack one node folds —
                            # 1 + tree fanout at a relay hop, or the
                            # root's top-level partial count. > 1 adds
                            # ct_add at the halving fold widths plus the
                            # canon g1_normalize batch (_fold_schemas).
                            # 0 (default) = star dispatch, no extra
                            # programs, so star registries stay a subset
                            # of tree ones (test_precompile.py pattern).
    n_pane: int = 0         # streaming-survey window width in PANES
                            # (service/streaming.StreamEngine): > 1 adds
                            # the pane-delta program set — the raw
                            # ct_add/ct_sub jits of the window delta
                            # chain at the (V,) window shape
                            # (_pane_specs) plus the first advance's
                            # pane-stack fold, bucketed ct_add at the
                            # halving widths of n_pane (_pane_schemas).
                            # 0 (default) = one-shot survey, no extra
                            # programs, so one-shot registries stay a
                            # subset of streaming ones
                            # (test_precompile.py enforces both
                            # directions, mirroring n_fold).


BENCH = Profile()


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One AOT program: zero-arg lower()/call() thunks + dispatch metadata.

    lower() returns a jax.stages.Lowered (AOT: .compile() feeds the
    persistent cache WITHOUT executing — but does NOT warm the jit's own
    dispatch cache). call() dispatches the program the way runtime does —
    it is the only way to guarantee later calls at these shapes re-use a
    cached trace instead of retracing (LocalCluster warmup uses it)."""

    name: str               # e.g. "bucketed:pair@2048"
    op: str                 # registry family key (BUCKETED_OPS name, ...)
    kind: str               # "bucketed" | "pallas" | "fused" | "pool" |
                            # "wire" | "pane"
    phase: str              # survey phase that dispatches it (doc only)
    lower: Callable[[], object]
    dispatched: Callable[[], bool]
    call: Callable[[], object] | None = None
    family: str = ""        # gate family: "device" | "g1" | "pairing" |
                            # "pallas" (the server's compile lane executes
                            # just the cheap device family on CPU)


# ---------------------------------------------------------------------------
# Backend dispatch predicates (must mirror crypto/batching.py host_dispatch)
# ---------------------------------------------------------------------------

def _pallas_on() -> bool:
    from ..crypto import pallas_ops as po

    return po.available()


def _kernel_route_pairing() -> bool:
    """True iff the pairing-family bucketed kernels actually dispatch
    (host_dispatch detours them to the host oracle on CPU)."""
    from ..crypto import host_oracle as ho

    return not (ho.ENABLED and not _pallas_on())


def _kernel_route_g1() -> bool:
    """G1/G2 family: detours to host only when the NATIVE library built
    (gate=npair.available in batching._build)."""
    from ..crypto import host_oracle as ho
    from ..crypto import native_pairing as npair

    return not (ho.ENABLED and not _pallas_on() and npair.available())


_GATES = {
    "device": lambda: True,
    "pairing": _kernel_route_pairing,
    "g1": _kernel_route_g1,
    "pallas": _pallas_on,
}


# ---------------------------------------------------------------------------
# Example-argument templates (zeros: trace/lower/compile never execute)
# ---------------------------------------------------------------------------

def _z(shape, dtype=None):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype or jnp.uint32)


def _scalar(b):
    return _z((b, NL))


def _g1(b):
    return _z((b, 3, NL))


def _g2(b):
    return _z((b, 3, 2, NL))


def _gt(b):
    return _z((b, 6, 2, NL))


def _ct(b):
    return _z((b, 2, 3, NL))


def _coord(b):
    return _z((b, NL))


def _fp2c(b):
    return _z((b, 2, NL))


def _i64(b):
    import jax.numpy as jnp
    import numpy as np

    # canonicalized like service.run_survey's jnp.asarray(dp_stats)
    return jnp.asarray(np.zeros((b,), dtype=np.int64))


def _fb_table():
    return _z((64, 16, 3, NL))          # eg.FixedBase.table


def _pow_tables(p: Profile):
    return _z((p.n_cns * p.u, 64, 16, 6, 2, NL))  # sig_gt_pow_tables


# Each bucketed entry: op -> (args builder(profile, B), batch exprs, phase,
# gate). Batch exprs are evaluated on the profile; the wrapper's bucket_of
# canonicalizes them, and entries landing on the same bucket dedupe.
_B_SCHEMAS: list = [
    # --- DataCollection / DRO / keyswitch helpers (device everywhere) ---
    ("encrypt", lambda p, b: (_fb_table(), _fb_table(), _scalar(b),
                              _scalar(b)),
     [lambda p: p.n_dps * p.n_values], "DataCollection", "device"),
    ("int_to_scalar", lambda p, b: (_i64(b),),
     [lambda p: p.n_dps * p.n_values * p.l], "RangeProofCreate", "device"),
    ("ct_add", lambda p, b: (_ct(b), _ct(b)),
     [lambda p: p.n_values], "Aggregation", "device"),
    ("ct_scalar_mul", lambda p, b: (_ct(b), _scalar(b)),
     [lambda p: p.n_values], "Obfuscation", "device"),
    ("decrypt_point", lambda p, b: (_ct(b), _scalar(b)),
     [lambda p: p.n_values], "Decryption", "device"),
    ("is_infinity", lambda p, b: (_g1(b),),
     [lambda p: p.n_values], "Decryption", "device"),
    ("table_lookup",
     lambda p, b: (_z((2 * p.dlog_limit,)), _z((2 * p.dlog_limit, NL)),
                   _z((2 * p.dlog_limit,)),
                   _z((2 * p.dlog_limit,), "int32"), _g1(b)),
     [lambda p: p.n_values], "Decryption", "device"),
    # --- scalar-field (mod n) family: creation + response + RLC weights ---
    ("fn_add", lambda p, b: (_scalar(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "device"),
    ("fn_sub", lambda p, b: (_scalar(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "device"),
    ("fn_neg", lambda p, b: (_scalar(b),),
     [lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "device"),
    ("fn_mul_plain", lambda p, b: (_scalar(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "device"),
    ("fn_mont_mul", lambda p, b: (_scalar(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "device"),
    # --- canonical byte encoders (wire format, proofs/encoding.py) ---
    ("from_mont_p", lambda p, b: (_scalar(b),),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofWire", "device"),
    ("to_mont_p", lambda p, b: (_scalar(b),),
     # encode batch, plus the per-payload DECODE shapes (_g1/_g2/_gt
     # _from_bytes): commit x|y at 2V, d at V, each G2 component at
     # ns*V*l, the GT response at 12*ns*V*l — the verify worker of
     # drynx_tpu/server deserializes payloads off the main thread, so
     # these buckets must be registry-warmable
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l,
      lambda p: 2 * p.n_values, lambda p: p.n_values,
      lambda p: p.n_cns * p.n_values * p.l,
      lambda p: 12 * p.n_cns * p.n_values * p.l],
     "RangeProofWire", "device"),
    # --- G1/G2 family (host-native detour on CPU when the lib built) ---
    ("g1_add", lambda p, b: (_g1(b), _g1(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "g1"),
    ("g1_neg", lambda p, b: (_g1(b),),
     [lambda p: p.n_dps * p.n_values], "RangeProofVerify", "g1"),
    ("g1_scalar_mul", lambda p, b: (_g1(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_cns * p.n_dps * p.n_values],
     "RangeProofVerify", "g1"),
    ("g1_scalar_mul64", lambda p, b: (_g1(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values], "RangeProofVerify", "g1"),
    ("g1_eq", lambda p, b: (_g1(b), _g1(b)),
     [lambda p: p.n_dps * p.n_values], "RangeProofVerify", "g1"),
    ("g1_normalize", lambda p, b: (_g1(b),),
     [lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "g1"),
    # canonical aggregate (topology.canon_points): the root normalizes the
    # folded (V, 2, 3, NL) ciphertext sum — 2*V flattened points — in
    # BOTH dispatch topologies, so this is a base program, not a tree one
    ("g1_normalize", lambda p, b: (_g1(b),),
     [lambda p: 2 * p.n_values], "Aggregation", "g1"),
    ("fixed_base_mul", lambda p, b: (_fb_table(), _scalar(b)),
     [lambda p: p.n_dps * p.n_values,
      lambda p: p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "g1"),
    ("g2_scalar_mul", lambda p, b: (_g2(b), _scalar(b)),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "g1"),
    ("g2_normalize", lambda p, b: (_g2(b),),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "g1"),
    # --- pairing family (host-oracle detour on CPU) ---
    ("pair", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofVerify", "pairing"),
    ("miller", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
     [lambda p: p.n_cns * p.u], "SigTableSetup", "pairing"),
    ("gt_pow", lambda p, b: (_gt(b), _scalar(b)),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "pairing"),
    ("gt_pow64", lambda p, b: (_gt(b), _scalar(b)),
     [lambda p: p.n_dps * p.n_values], "RangeProofVerify", "pairing"),
    ("gt_pow128", lambda p, b: (_gt(b), _scalar(b)),
     [lambda p: 1], "GTOrderGate", "pairing"),
    ("final_exp", lambda p, b: (_gt(b),),
     [lambda p: 1], "RangeProofVerify", "pairing"),
    ("gt_mul", lambda p, b: (_gt(b), _gt(b)),
     [lambda p: p.n_dps * p.n_values * p.l,
      lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "pairing"),
    # --- pure-device GT helpers ---
    ("gt_eq", lambda p, b: (_gt(b), _gt(b)),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofVerify", "device"),
    ("gt_frob1", lambda p, b: (_gt(b),),
     [lambda p: 1], "GTMembershipGate", "device"),
    ("gt_frob2", lambda p, b: (_gt(b),),
     [lambda p: 1], "GTMembershipGate", "device"),
    # --- Pallas-only bucketed ops (lazy wrappers in proofs/range_proof) ---
    ("gt_pow_fixed_multi",
     lambda p, b: (_pow_tables(p), _z((b,), "int32"), _scalar(b)),
     [lambda p: p.n_cns * p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "pallas"),
    ("gt_pow_gtb", lambda p, b: (_scalar(b),),
     [lambda p: p.n_dps * p.n_values * p.l],
     "RangeProofCreate", "pallas"),
]

def _shard_schemas(p: Profile) -> list:
    """The per-shard program set of the mesh proof plane — the SAME bucketed
    ops as the full-batch schemas, at the smaller per-shard batch sizes the
    chunked dispatch hits (parallel/proof_mesh.rlc_total_shards slices the
    flat ns*V*l digit batch; proofs/range_proof._commit_kernel_sharded
    slices the dp-flattened value axis V = n_dps*n_values). Empty when the
    profile is single-shard, so single-device registries are a subset of
    sharded ones (test_precompile.py enforces both directions)."""
    if p.n_shards <= 1:
        return []

    def cdiv(a, k):
        return -(-a // k)

    # verify shard: slice of the flattened ns*V*l joint digit batch
    vs = lambda p: cdiv(p.n_cns * p.n_dps * p.n_values * p.l, p.n_shards)
    # creation shard: slice of the dp-flattened value axis
    cs = lambda p: cdiv(p.n_dps * p.n_values, p.n_shards)
    csl = lambda p: cs(p) * p.l
    ncsl = lambda p: p.n_cns * cs(p) * p.l
    return [
        # --- rlc_total_shards per-shard body ---
        ("miller", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
         [vs], "RangeProofVerifyShard", "pairing"),
        ("gt_pow64", lambda p, b: (_gt(b), _scalar(b)),
         [vs], "RangeProofVerifyShard", "pairing"),
        # --- _commit_kernel per-shard body (D / V_pts / a stages) ---
        ("fn_add", lambda p, b: (_scalar(b), _scalar(b)),
         [cs], "RangeProofCreateShard", "device"),
        ("fn_neg", lambda p, b: (_scalar(b),),
         [csl, ncsl], "RangeProofCreateShard", "device"),
        ("fn_mul_plain", lambda p, b: (_scalar(b), _scalar(b)),
         [ncsl], "RangeProofCreateShard", "device"),
        ("fn_mont_mul", lambda p, b: (_scalar(b), _scalar(b)),
         [csl], "RangeProofCreateShard", "device"),
        ("fixed_base_mul", lambda p, b: (_fb_table(), _scalar(b)),
         [cs, csl], "RangeProofCreateShard", "g1"),
        ("g1_add", lambda p, b: (_g1(b), _g1(b)),
         [cs], "RangeProofCreateShard", "g1"),
        ("g1_normalize", lambda p, b: (_g1(b),),
         [csl], "RangeProofCreateShard", "g1"),
        ("g2_scalar_mul", lambda p, b: (_g2(b), _scalar(b)),
         [ncsl], "RangeProofCreateShard", "g1"),
        ("g2_normalize", lambda p, b: (_g2(b),),
         [ncsl], "RangeProofCreateShard", "g1"),
        ("pair", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
         [ncsl], "RangeProofCreateShard", "pairing"),
        ("gt_pow", lambda p, b: (_gt(b), _scalar(b)),
         [ncsl], "RangeProofCreateShard", "pairing"),
        ("gt_mul", lambda p, b: (_gt(b), _gt(b)),
         [ncsl], "RangeProofCreateShard", "pairing"),
        ("gt_pow_fixed_multi",
         lambda p, b: (_pow_tables(p), _z((b,), "int32"), _scalar(b)),
         [ncsl], "RangeProofCreateShard", "pallas"),
        ("gt_pow_gtb", lambda p, b: (_scalar(b),),
         [csl], "RangeProofCreateShard", "pallas"),
    ]


def _fold_schemas(p: Profile) -> list:
    """The tree-overlay fold program set (service/topology.fold_cts): a
    relay — or the tree root — folds a (k, V) ciphertext stack with
    tree_reduce_add, dispatching ct_add at the halving widths of k, then
    canonicalizes via g1_normalize over the flattened 2*V point batch.
    ``n_fold`` is the largest such k the deployment folds (1 + tree
    fanout at a relay hop, or the root's partial count). Empty when
    n_fold <= 1, so star registries stay a subset of tree ones
    (tests/test_precompile.py pattern for optional axes)."""
    if p.n_fold <= 1:
        return []
    widths = []
    n = p.n_fold
    while n > 1:
        widths.append(n // 2)        # batch of one tree_reduce_add level
        n = n // 2 + (n % 2)
    batches = sorted({w * p.n_values for w in widths})
    return [
        ("ct_add", lambda p, b: (_ct(b), _ct(b)),
         [(lambda p, bb=bb: bb) for bb in batches], "TreeFold", "device"),
        ("g1_normalize", lambda p, b: (_g1(b),),
         [lambda p: 2 * p.n_values], "TreeFold", "g1"),
    ]


def _pane_schemas(p: Profile) -> list:
    """The window-fold program set of a streaming survey's FIRST advance
    (service/streaming.StreamEngine): before the delta chain takes over,
    the initial window aggregate folds the (n_pane, V) pane stack with
    topology.fold_cts — bucketed ct_add at the halving widths of n_pane
    (the canon g1_normalize at 2*V is already a base program). Empty when
    n_pane <= 1, so one-shot registries stay a subset of streaming ones
    (tests/test_precompile.py enforces both directions)."""
    if p.n_pane <= 1:
        return []
    widths = []
    n = p.n_pane
    while n > 1:
        widths.append(n // 2)        # batch of one tree_reduce_add level
        n = n // 2 + (n % 2)
    batches = sorted({w * p.n_values for w in widths})
    return [
        ("ct_add", lambda p, b: (_ct(b), _ct(b)),
         [(lambda p, bb=bb: bb) for bb in batches], "PaneFold", "device"),
    ]


def _bucket_schemas(p: Profile) -> list:
    """The bucket-tile program set of a grid-op survey (min/max/
    frequency_count/union/inter). Above encoding/tiles.TILE_THRESHOLD the
    create path tiles its commit stage: proofs/range_proof.
    create_range_proofs dispatches _commit_kernel_sharded with
    k = max(n_shards, tiles.proof_tile_shards(V, tiles.tile_width()))
    over the dp-flattened value axis V = n_dps * n_buckets (for grid ops
    every bucket is one (u=2, l=1) value, so n_values == n_buckets).
    Same bucketed ops as the creation-shard family, at the tile-derived
    per-shard batch sizes. Empty when n_buckets <= 0 or the grid sits
    below the tile threshold, so plain registries are a subset of
    bucket-grid ones (tests/test_precompile.py enforces both
    directions, mirroring the n_shards / n_queue contracts)."""
    if p.n_buckets <= 0:
        return []
    from ..encoding import tiles as _tiles

    V = p.n_dps * p.n_buckets
    t = _tiles.auto_tile(V)
    if not t:
        return []
    k = max(p.n_shards, _tiles.proof_tile_shards(V, t))
    if k <= 1:
        return []

    def cdiv(a, kk):
        return -(-a // kk)

    # tile shard: slice of the dp-flattened bucket-value axis
    ts = lambda p: cdiv(p.n_dps * p.n_buckets, k)
    tsl = lambda p: ts(p) * p.l
    ntsl = lambda p: p.n_cns * ts(p) * p.l
    return [
        ("fn_add", lambda p, b: (_scalar(b), _scalar(b)),
         [ts], "RangeProofCreateTile", "device"),
        ("fn_neg", lambda p, b: (_scalar(b),),
         [tsl, ntsl], "RangeProofCreateTile", "device"),
        ("fn_mul_plain", lambda p, b: (_scalar(b), _scalar(b)),
         [ntsl], "RangeProofCreateTile", "device"),
        ("fn_mont_mul", lambda p, b: (_scalar(b), _scalar(b)),
         [tsl], "RangeProofCreateTile", "device"),
        ("int_to_scalar", lambda p, b: (_i64(b),),
         [tsl], "RangeProofCreateTile", "device"),
        ("fixed_base_mul", lambda p, b: (_fb_table(), _scalar(b)),
         [ts, tsl], "RangeProofCreateTile", "g1"),
        ("g1_add", lambda p, b: (_g1(b), _g1(b)),
         [ts], "RangeProofCreateTile", "g1"),
        ("g1_normalize", lambda p, b: (_g1(b),),
         [tsl], "RangeProofCreateTile", "g1"),
        ("g2_scalar_mul", lambda p, b: (_g2(b), _scalar(b)),
         [ntsl], "RangeProofCreateTile", "g1"),
        ("g2_normalize", lambda p, b: (_g2(b),),
         [ntsl], "RangeProofCreateTile", "g1"),
        ("pair", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
         [ntsl], "RangeProofCreateTile", "pairing"),
        ("gt_pow", lambda p, b: (_gt(b), _scalar(b)),
         [ntsl], "RangeProofCreateTile", "pairing"),
        ("gt_mul", lambda p, b: (_gt(b), _gt(b)),
         [ntsl], "RangeProofCreateTile", "pairing"),
        ("gt_pow_fixed_multi",
         lambda p, b: (_pow_tables(p), _z((b,), "int32"), _scalar(b)),
         [ntsl], "RangeProofCreateTile", "pallas"),
        ("gt_pow_gtb", lambda p, b: (_scalar(b),),
         [tsl], "RangeProofCreateTile", "pallas"),
    ]


def _queue_schemas(p: Profile) -> list:
    """The cross-survey verify program set of the standing survey server
    (drynx_tpu/server): `n_queue` equal-shape surveys' joint digit batches
    concatenated along the value axis verify in ONE RLC dispatch
    (proofs/range_proof.verify_cross_survey_payloads_joint ->
    parallel/proof_mesh.rlc_total_shards at phase CrossSurveyVerifyShard).
    Same bucketed ops as the verify schemas, at the n_queue-scaled batch
    sizes. Empty when n_queue <= 1, so single-survey registries are a
    subset of queued ones (tests/test_precompile.py enforces both
    directions, mirroring the n_shards contract)."""
    if p.n_queue <= 1:
        return []

    def cdiv(a, k):
        return -(-a // k)

    # value axis of the cross-survey concatenation, and its digit batch
    qv = lambda p: p.n_queue * p.n_dps * p.n_values
    qd = lambda p: p.n_cns * qv(p) * p.l
    # per-shard slice of the concatenated digit batch (chunked dispatch)
    qs = lambda p: cdiv(qd(p), max(1, p.n_shards))
    return [
        # --- rlc_prelude over the concatenation (D eq, challenge, weights)
        ("fn_add", lambda p, b: (_scalar(b), _scalar(b)),
         [qv, lambda p: qv(p) * p.l, qd], "CrossSurveyVerify", "device"),
        ("fn_sub", lambda p, b: (_scalar(b), _scalar(b)),
         [qd], "CrossSurveyVerify", "device"),
        ("fn_neg", lambda p, b: (_scalar(b),),
         [lambda p: qv(p) * p.l, qd], "CrossSurveyVerify", "device"),
        ("fn_mul_plain", lambda p, b: (_scalar(b), _scalar(b)),
         [qd], "CrossSurveyVerify", "device"),
        # rlc weights + challenge recompute over the concatenation (the
        # only family the verify worker dispatches as jits on CPU — the
        # g1/pairing families host-detour there, so warming these is what
        # keeps the pipeline's verify thread trace-free)
        ("int_to_scalar", lambda p, b: (_i64(b),),
         [qv, lambda p: qv(p) * p.l, qd], "CrossSurveyVerify", "device"),
        ("to_mont_p", lambda p, b: (_scalar(b),),
         [qv, lambda p: qv(p) * p.l, qd], "CrossSurveyVerify", "device"),
        ("from_mont_p", lambda p, b: (_scalar(b),),
         [qv, lambda p: qv(p) * p.l, qd], "CrossSurveyVerify", "device"),
        # --- _g1_prep + the single-device fallback verifier ---
        ("g1_neg", lambda p, b: (_g1(b),),
         [qv], "CrossSurveyVerify", "g1"),
        ("g1_scalar_mul", lambda p, b: (_g1(b), _scalar(b)),
         [qv, lambda p: p.n_cns * qv(p)], "CrossSurveyVerify", "g1"),
        ("g1_scalar_mul64", lambda p, b: (_g1(b), _scalar(b)),
         [qv, qd], "CrossSurveyVerify", "g1"),
        ("g1_add", lambda p, b: (_g1(b), _g1(b)),
         [qd], "CrossSurveyVerify", "g1"),
        ("g1_normalize", lambda p, b: (_g1(b),),
         [qd], "CrossSurveyVerify", "g1"),
        ("g2_normalize", lambda p, b: (_g2(b),),
         [qd], "CrossSurveyVerify", "g1"),
        ("fixed_base_mul", lambda p, b: (_fb_table(), _scalar(b)),
         [lambda p: qv(p) * p.l], "CrossSurveyVerify", "g1"),
        ("pair", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
         [qd], "CrossSurveyVerify", "pairing"),
        ("gt_pow64", lambda p, b: (_gt(b), _scalar(b)),
         [qv], "CrossSurveyVerify", "pairing"),
        # --- rlc_total_shards per-shard body over the concatenation ---
        ("miller", lambda p, b: (_coord(b), _coord(b), _fp2c(b), _fp2c(b)),
         [qs], "CrossSurveyVerifyShard", "pairing"),
        ("gt_pow64", lambda p, b: (_gt(b), _scalar(b)),
         [qs], "CrossSurveyVerifyShard", "pairing"),
    ]


# Raw Pallas flat entry points the bucketed family dispatches internally on
# TPU. Registered explicitly so their Mosaic compiles land in the
# persistent cache even for call sites outside bucketed wrappers
# (g2.scalar_mul, fp12 pow paths, gt_pow_fixed's mulreduce passes).
_FLAT = 2048  # the pairing family's max_bucket: every big batch chunks to it


def _pallas_specs(p: Profile) -> list:
    def miller(do="lower"):
        from ..crypto import pallas_pairing as pp

        args = (_coord(_FLAT), _coord(_FLAT), _fp2c(_FLAT), _fp2c(_FLAT))
        if do == "call":
            return pp.miller_flat(*args)
        return pp._miller_flat.lower(*args, interpret=False)

    def wpow(n_bits, do="lower"):
        def go():
            from ..crypto import pallas_pairing as pp

            if do == "call":
                return pp.f12_wpow_flat(_gt(_FLAT), _scalar(_FLAT),
                                        n_bits=n_bits, cyc=True)
            return pp._f12_wpow_flat.lower(
                _gt(_FLAT), _scalar(_FLAT), n_bits=n_bits, wbits=3,
                cyc=True, interpret=False)
        return go

    def mulreduce8(do="lower"):
        from ..crypto import pallas_pairing as pp

        g = _z((_FLAT, 8, 6, 2, NL))
        if do == "call":
            return pp.f12_mulreduce8_flat(g)
        return pp._f12_mulreduce8_flat.lower(g, interpret=False)

    return [
        ProgramSpec(f"pallas:miller_flat@{_FLAT}", "miller_flat", "pallas",
                    "Pairing", miller, _pallas_on,
                    lambda: miller("call")),
        ProgramSpec(f"pallas:f12_wpow_flat@{_FLAT}/63c", "f12_wpow_flat",
                    "pallas", "RangeProofVerify", wpow(63), _pallas_on,
                    wpow(63, "call")),
        ProgramSpec(f"pallas:f12_wpow_flat@{_FLAT}/128c", "f12_wpow_flat",
                    "pallas", "GTOrderGate", wpow(128), _pallas_on,
                    wpow(128, "call")),
        ProgramSpec(f"pallas:f12_wpow_flat@{_FLAT}/256c", "f12_wpow_flat",
                    "pallas", "RangeProofCreate", wpow(256), _pallas_on,
                    wpow(256, "call")),
        ProgramSpec(f"pallas:f12_mulreduce8_flat@{_FLAT}",
                    "f12_mulreduce8_flat", "pallas", "RangeProofCreate",
                    mulreduce8, _pallas_on, lambda: mulreduce8("call")),
    ]


def _fused_specs(p: Profile) -> list:
    """The fused exec pipeline (service.py module-level jits), at the exact
    survey shapes run_survey dispatches."""
    V, nd, nc, T = p.n_values, p.n_dps, p.n_cns, 2 * p.dlog_limit

    def enc_at(w):
        def go(do="lower"):
            import jax.numpy as jnp
            import numpy as np

            from ..service import service as svc

            args = (_fb_table(),
                    jnp.asarray(np.zeros((nd, w), dtype=np.int64)),
                    _z((nd, w, NL)))
            return (svc._fused_enc(*args) if do == "call"
                    else svc._fused_enc.lower(*args))
        return go

    enc = enc_at(V)

    def agg(do="lower"):
        from ..service import service as svc

        a = (_z((nd, V, 2, 3, NL)),)
        return (svc._fused_agg(*a) if do == "call"
                else svc._fused_agg.lower(*a))

    def ks(do="lower"):
        import jax.numpy as jnp

        from ..service import service as svc

        args = (_fb_table(), _z((V, 2, 3, NL)), _z((nc, V, NL)),
                _z((nc, NL)), jnp.asarray(0, dtype=jnp.int64))
        return (svc._fused_ks(*args) if do == "call"
                else svc._fused_ks.lower(*args))

    def dec(do="lower"):
        from ..service import service as svc

        args = (_z((V, 2, 3, NL)), _z((NL,)), _z((T,)), _z((T, NL)),
                _z((T,)), _z((T,), "int32"))
        return (svc._fused_dec(*args) if do == "call"
                else svc._fused_dec.lower(*args))

    mk = lambda nm, th, ph: ProgramSpec(f"fused:{nm}", nm, "fused", ph, th,
                                        lambda: True,
                                        lambda th=th: th("call"))
    specs = [mk("enc", enc, "DataCollection"),
             mk("agg", agg, "Aggregation"),
             mk("ks", ks, "KeySwitching"), mk("dec", dec, "Decryption")]
    if p.n_buckets > 0:
        # chunked encrypt of a grid survey: service.execute_survey slabs
        # the (nd, n_buckets) stats through _fused_enc at plan_tiles
        # widths (balanced tiling => at most 2 distinct widths)
        from ..encoding import tiles as _tiles

        t = _tiles.auto_tile(p.n_buckets)
        if t:
            widths = sorted({b - a for a, b
                             in _tiles.plan_tiles(p.n_buckets, t).tiles})
            for w in widths:
                th = enc_at(w)
                specs.append(ProgramSpec(
                    f"fused:enc@{w}", "enc", "fused",
                    "DataCollectionTile", th, lambda: True,
                    lambda th=th: th("call")))
    return specs


def _pool_specs(p: Profile) -> list:
    """The DRO pool/slab program set of a diffp survey (Profile.n_noise):
    the RAW jits `parallel.dro` dispatches for precompute (pool refill),
    noise encryption and the shuffle re-randomization — certified at the
    exact slab widths `dro.slab_widths` chunks n_noise into, plus the
    monolithic n_noise width (encrypt_noise / the unchunked path). Empty
    when n_noise <= 0, so non-diffp registries are a subset of pooled
    ones (tests/test_precompile.py enforces both directions)."""
    if p.n_noise <= 0:
        return []
    from ..parallel import dro as _dro

    widths = sorted(set(_dro.slab_widths(p.n_noise)) | {p.n_noise})

    def enc_at(w):
        def go(do="lower"):
            from ..crypto import elgamal as eg

            args = (_fb_table(), _fb_table(), _scalar(w), _scalar(w))
            return (eg.encrypt_with_tables(*args) if do == "call"
                    else eg.encrypt_with_tables.lower(*args))
        return go

    def i2s_at(w):
        def go(do="lower"):
            from ..crypto import elgamal as eg

            args = (_i64(w),)
            return (eg.int_to_scalar(*args) if do == "call"
                    else eg.int_to_scalar.lower(*args))
        return go

    def add_at(w):
        def go(do="lower"):
            from ..crypto import elgamal as eg

            args = (_ct(w), _ct(w))
            return (eg.ct_add(*args) if do == "call"
                    else eg.ct_add.lower(*args))
        return go

    specs = []
    for w in widths:
        for nm, th in (("encrypt_with_tables", enc_at(w)),
                       ("int_to_scalar", i2s_at(w)),
                       ("ct_add", add_at(w))):
            specs.append(ProgramSpec(
                f"pool:{nm}@{w}", nm, "pool", "DROPool", th,
                lambda: True, lambda th=th: th("call"),
                family="device"))
    return specs


def _pane_specs(p: Profile) -> list:
    """The pane-delta program set of a streaming survey
    (service/streaming.StreamEngine.advance): every steady-state window
    slide dispatches the RAW ciphertext jits ``eg.ct_add`` / ``eg.ct_sub``
    at the standing (V, 2, 3, NL) window-aggregate shape — one call per
    added / expired pane. Raw, not bucketed: the delta chain runs
    elementwise on the window tensor, so the jits trace at exactly that
    shape (the bucketed ct_add family only covers the batch-flattened
    widths). Empty when n_pane <= 1, so one-shot registries stay a
    subset of streaming ones (tests/test_precompile.py enforces both
    directions)."""
    if p.n_pane <= 1:
        return []
    V = p.n_values

    def at(nm):
        def go(do="lower"):
            from ..crypto import elgamal as eg

            fn = getattr(eg, nm)
            args = (_ct(V), _ct(V))
            return fn(*args) if do == "call" else fn.lower(*args)
        return go

    specs = []
    for nm in ("ct_add", "ct_sub"):
        th = at(nm)
        specs.append(ProgramSpec(
            f"pane:{nm}@{V}", nm, "pane", "PaneDelta", th,
            lambda: True, lambda th=th: th("call"), family="device"))
    return specs


# canonical flat width the wire widen programs lower at: the program is
# elementwise so any width certifies the pipeline; 4096 matches the pool
# slab width (the largest steady-state wire tensor)
_WIRE_WIDEN_FLAT = 4096


def _wire_specs(p: Profile) -> list:
    """The device-direct decode's on-device widen programs: one jitted
    astype per (narrow, wide) integer dtype pair the v2 wire can ship
    (transport.widen_pairs). Profile-independent — every survey decodes
    frames — so they appear in every registry and never perturb the
    subset/identity contracts of the optional axes."""
    from ..service import transport as T

    specs = []
    for narrow, wide in T.widen_pairs():
        def th(do="lower", narrow=narrow, wide=wide):
            from ..service import transport as T

            prog = T.widen_program(narrow, wide)
            arg = _z((_WIRE_WIDEN_FLAT,), narrow)
            return prog(arg) if do == "call" else prog.lower(arg)

        specs.append(ProgramSpec(
            f"wire:widen@{narrow}->{wide}", "widen", "wire",
            "WireDecode", th, lambda: True,
            lambda th=th: th("call"), family="device"))
    return specs


def build_registry(profile: Profile = BENCH) -> list[ProgramSpec]:
    """Enumerate the proofs-on program set for `profile`.

    Entries landing on the same (op, bucket) dedupe; the returned order is
    cheap-first (fn family before pairings) so an interrupted precompile
    still banks the most programs per second."""
    from ..crypto import batching as B
    from ..proofs import range_proof as rp

    # force-build the lazy bucketed wrappers so BUCKETED_OPS is complete
    # (the gtB table build is host work — TPU path only)
    rp.aot_register_bucketed(build_gtb_table=_pallas_on())

    specs: dict[str, ProgramSpec] = {}
    for op, args_fn, batches, phase, gate in (
            _B_SCHEMAS + _shard_schemas(profile)
            + _queue_schemas(profile) + _bucket_schemas(profile)
            + _fold_schemas(profile) + _pane_schemas(profile)):
        w = B.BUCKETED_OPS.get(op)
        for bexpr in batches:
            batch = int(bexpr(profile))
            if w is not None:
                bucket = w.bucket_of(batch)
            else:
                # lazy Pallas-only op not built on this backend: name by
                # its known (min=32, max=2048) bucket config
                bucket = min(max(32, 1 << (batch - 1).bit_length()), 2048)
            name = f"bucketed:{op}@{bucket}"
            if name in specs:
                continue

            def lower(op=op, args_fn=args_fn, bucket=bucket):
                from ..crypto.batching import BUCKETED_OPS

                return BUCKETED_OPS[op].lower(*args_fn(profile, bucket))

            def call(op=op, args_fn=args_fn, bucket=bucket):
                from ..crypto.batching import BUCKETED_OPS

                return BUCKETED_OPS[op](*args_fn(profile, bucket))

            specs[name] = ProgramSpec(name, op, "bucketed", phase, lower,
                                      _GATES[gate], call, family=gate)
    for s in (_pallas_specs(profile) + _fused_specs(profile)
              + _pool_specs(profile) + _pane_specs(profile)
              + _wire_specs(profile)):
        specs[s.name] = s
    return list(specs.values())


# The program set a verify WORKER thread dispatches as real jits on CPU:
# the mod-p/mod-n scalar family used by payload deserialization
# (to_mont_p in _g1/_g2/_gt _from_bytes), the RLC weights (int_to_scalar,
# fn_*), and the wire encoders. The g1/pairing families host-detour on
# CPU and everything else dispatches from the drain thread. The registry
# owns this set so the server's compile lane (which executes exactly
# these during a lower-mode pass) and the warm-coverage test stay in
# lockstep with the schemas above — a worker POOL of any width shares
# the process-wide dispatch caches, so warming the set once covers every
# worker (tests/test_precompile.py asserts the coverage).
WORKER_OPS = frozenset({
    "fn_add", "fn_sub", "fn_neg", "fn_mul_plain", "fn_mont_mul",
    "int_to_scalar", "to_mont_p", "from_mont_p",
})


def worker_specs(profile: Profile) -> list:
    """The registry subset a verify worker may dispatch (device-family
    programs over WORKER_OPS) — the server's execute filter during a CPU
    lower-mode compile pass."""
    return [s for s in build_registry(profile)
            if s.family == "device" and s.op in WORKER_OPS]


# ---------------------------------------------------------------------------
# Serial driver
# ---------------------------------------------------------------------------

def precompile(profile: Profile = BENCH, mode: str = "compile",
               stats: CompileStats | None = None,
               log: Callable[[str], None] | None = None,
               only: Callable[[ProgramSpec], bool] | None = None
               ) -> CompileStats:
    """Drive every dispatched program, SERIALLY.

    ``only`` filters the registry before driving it (e.g. the standing
    server's CPU compile lane lower-passes everything, then EXECUTES just
    the ``family == "device"`` programs — the single family the verify
    worker would otherwise first-trace off the main thread).

    mode:
      "lower"   — trace + lower only (--dry-run; CPU-safe, no executable)
      "compile" — AOT .lower().compile(): feeds the persistent XLA cache
                  without executing (the CLI default). NOTE this does NOT
                  warm the jits' own dispatch caches — runtime calls still
                  trace once (cheap) and then hit the persistent cache.
      "execute" — dispatch each program exactly like runtime does, with
                  zero-valued canonical-shape inputs. The only mode that
                  leaves the dispatch caches warm, so later survey calls
                  at these shapes perform ZERO tracing — LocalCluster's
                  main-thread warmup uses it.

    Serial is load-bearing: XLA's CPU compiler has segfaulted under
    concurrent compiles (service._async_proof docstring), and the
    persistent-cache write path assumes one writer per key."""
    assert mode in ("lower", "compile", "execute"), mode
    import jax

    stats = stats or STATS
    listener = install_cache_listener()
    if log is None:
        log = lambda m: print(f"[precompile] {m}", file=sys.stderr,
                              flush=True)
    specs = build_registry(profile)
    if only is not None:
        specs = [s for s in specs if only(s)]
    log(f"{len(specs)} programs registered (mode={mode})")
    errors = 0
    for spec in specs:
        if not spec.dispatched():
            stats.record(spec.name, "skipped",
                         detail="not dispatched on this backend")
            continue
        t0 = time.perf_counter()
        try:
            h0 = stats.listener_hits
            if mode == "execute":
                jax.block_until_ready(spec.call())
                t1 = time.perf_counter()
                cache = None
                if listener:
                    cache = ("hit" if stats.listener_hits > h0
                             else "miss")
                stats.record(spec.name, "executed", lower_s=t1 - t0,
                             cache=cache)
                continue
            lowered = spec.lower()
            t1 = time.perf_counter()
            if mode == "lower":
                stats.record(spec.name, "lowered", lower_s=t1 - t0)
                continue
            lowered.compile()
            t2 = time.perf_counter()
            cache = None
            if listener:
                cache = "hit" if stats.listener_hits > h0 else "miss"
            stats.record(spec.name, "compiled", lower_s=t1 - t0,
                         compile_s=t2 - t1, cache=cache)
        except Exception as e:  # record + keep going; CLI exits nonzero
            errors += 1
            stats.record(spec.name, "error",
                         lower_s=time.perf_counter() - t0,
                         detail=f"{type(e).__name__}: {e}")
    t = stats.totals()
    log(f"done: {t['compiled']} compiled / {t['executed']} executed / "
        f"{t['lowered']} lowered / {t['skipped']} skipped / "
        f"{errors} errors; lower {t['lower_seconds']:.1f}s compile "
        f"{t['compile_seconds']:.1f}s")
    return stats


# ---------------------------------------------------------------------------
# Trace-safety guard (the r05 segfault class)
# ---------------------------------------------------------------------------

_GUARDED = False


def trace_guard(min_recursion: int = 20000,
                stack_bytes: int = 64 * 1024 * 1024) -> None:
    """Make first-touch tracing survivable anywhere it happens.

    partial_eval recurses ~1 Python frame per traced equation; the pairing
    kernels reach >10k frames. Two failure modes guarded here:
      * RecursionError on the MAIN thread (recursion limit too low),
      * a C-STACK overflow (segfault, not an exception) on WORKER threads,
        whose default 8 MB stacks are half the main thread's — the r05
        crash tracing pair_flat from a dp_lists proof thread.
    threading.stack_size applies to threads created AFTER this call, so
    LocalCluster runs it in __init__, before any _async_proof thread."""
    global _GUARDED
    if _GUARDED:
        return
    if sys.getrecursionlimit() < min_recursion:
        sys.setrecursionlimit(min_recursion)
    try:
        import threading

        threading.stack_size(stack_bytes)
    except (ValueError, RuntimeError, OverflowError):
        pass  # platform cap; recursion limit still protects the main thread
    _GUARDED = True
