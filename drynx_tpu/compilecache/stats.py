"""Compile-cache observability: per-program trace/lower/compile timings.

The precompile driver (registry.py) is SERIAL by design, so per-program
rows are recorded in dispatch order and persistent-cache hits can be
attributed to the program whose .compile() triggered them. With the class
flag `echo` set (bench --verbose, the CLI), every program prints to stderr
as it finishes — a killed cold-start run still shows where the wall went,
the same rationale as PhaseTimers.echo (utils/timers.py).
"""
from __future__ import annotations

import sys
import threading

from ..resilience.policy import named_lock


class CompileStats:
    """Thread-safe per-program AOT accounting + persistent-cache counters.

    Row statuses:
      compiled  — AOT traced+lowered+compiled (persistent cache fed)
      executed  — dispatched like runtime (dispatch caches warm; the
                  LocalCluster main-thread warmup mode)
      lowered   — traced+lowered only (--dry-run)
      skipped   — enumerated, but the current backend would not dispatch it
                  (e.g. host-oracle detours on CPU, Pallas-only ops)
      error     — trace/lower/compile raised
    """

    echo = False

    def __init__(self):
        self._lock = named_lock("compilestats_lock")
        self.rows: dict[str, dict] = {}
        self.persistent_hits = 0
        self.persistent_misses = 0
        # raw event count from the jax.monitoring listener; the serial
        # driver diffs it around each .compile() to classify hit/miss
        self.listener_hits = 0

    def record(self, name: str, status: str, lower_s: float = 0.0,
               compile_s: float = 0.0, cache: str | None = None,
               detail: str = "") -> None:
        with self._lock:
            self.rows[name] = {"status": status, "lower_s": lower_s,
                               "compile_s": compile_s, "cache": cache,
                               "detail": detail}
            if cache == "hit":
                self.persistent_hits += 1
            elif cache == "miss":
                self.persistent_misses += 1
        if CompileStats.echo:
            extra = f" cache={cache}" if cache else ""
            extra += f" ({detail})" if detail else ""
            print(f"    [aot] {name}: {status} lower={lower_s:.3f}s "
                  f"compile={compile_s:.3f}s{extra}", file=sys.stderr,
                  flush=True)

    def count(self, status: str) -> int:
        with self._lock:
            return sum(1 for r in self.rows.values()
                       if r["status"] == status)

    def totals(self) -> dict:
        with self._lock:
            rows = list(self.rows.values())
        return {
            "programs": len(rows),
            "compiled": sum(1 for r in rows if r["status"] == "compiled"),
            "executed": sum(1 for r in rows if r["status"] == "executed"),
            "lowered": sum(1 for r in rows if r["status"] == "lowered"),
            "skipped": sum(1 for r in rows if r["status"] == "skipped"),
            "errors": sum(1 for r in rows if r["status"] == "error"),
            "lower_seconds": sum(r["lower_s"] for r in rows),
            "compile_seconds": sum(r["compile_s"] for r in rows),
            "persistent_hits": self.persistent_hits,
            "persistent_misses": self.persistent_misses,
        }

    def headline(self) -> dict:
        """Bonus keys for the bench headline JSON (bench.py)."""
        t = self.totals()
        return {
            "compile_cache_programs": t["programs"],
            "compile_cache_compiled": t["compiled"] + t["executed"],
            "compile_cache_skipped": t["skipped"],
            "compile_cache_trace_lower_seconds": round(
                t["lower_seconds"], 3),
            "compile_cache_compile_seconds": round(
                t["compile_seconds"], 3),
            "compile_cache_persistent_hits": t["persistent_hits"],
            "compile_cache_persistent_misses": t["persistent_misses"],
        }

    def table(self) -> str:
        """Human-readable per-program report (CLI output)."""
        with self._lock:
            rows = sorted(self.rows.items())
        if not rows:
            return "(no programs recorded)"
        w = max(len(n) for n, _ in rows)
        lines = [f"{'program':<{w}}  {'status':<9} {'lower_s':>8} "
                 f"{'compile_s':>9}  cache"]
        for n, r in rows:
            lines.append(
                f"{n:<{w}}  {r['status']:<9} {r['lower_s']:>8.3f} "
                f"{r['compile_s']:>9.3f}  {r['cache'] or '-'}")
        t = self.totals()
        lines.append(
            f"-- {t['programs']} programs: {t['compiled']} compiled, "
            f"{t['executed']} executed, "
            f"{t['lowered']} lowered, {t['skipped']} skipped, "
            f"{t['errors']} errors; lower {t['lower_seconds']:.1f}s, "
            f"compile {t['compile_seconds']:.1f}s, persistent cache "
            f"{t['persistent_hits']} hits / {t['persistent_misses']} misses")
        return "\n".join(lines)


# Process-global collector: LocalCluster warmup and the CLI both feed it,
# bench.py reads .headline() into the bonus JSON keys.
STATS = CompileStats()

_LISTENER_INSTALLED = False


def install_cache_listener() -> bool:
    """Count persistent-compilation-cache hits via jax.monitoring.

    jax records '/jax/compilation_cache/cache_hits' events on every
    persistent-cache deserialization. Best-effort: older/newer jax may
    rename the event or drop the API — the driver then falls back to
    attributing 'miss' to every compile (still correct for cold runs)."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring

        def _on_event(event: str, **_kw) -> None:
            if "compilation_cache" in event and "hit" in event:
                with STATS._lock:
                    STATS.listener_hits += 1

        monitoring.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True
    except Exception:
        return False
    return True
