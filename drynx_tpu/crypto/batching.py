"""Batch-shape canonicalization: compile heavy kernels once per size bucket.

Every proof kernel batches over some (ns, V, l, ...) shape that varies per
query; jitting a monolithic kernel per configuration would recompile the
256-step crypto scans for every new shape. Instead the proof layer calls
these wrappers, which flatten all leading batch dims into one axis, pad it
up to a power-of-two bucket (edge-padding with real values, so no degenerate
inputs), invoke the jitted kernel on the canonical shape, and slice the
result back. Each kernel therefore compiles O(log max_batch) times total,
across all call sites and queries.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _next_bucket(b: int, min_bucket: int = 8) -> int:
    p = min_bucket
    while p < b:
        p *= 2
    return p


def _trace_mode():
    """Hashable snapshot of the process state that changes WHAT a kernel
    trace means: the interpret flags (tests monkeypatch both modules).
    Each mode gets its own jax.jit object, so flipping INTERPRET can never
    reuse a trace built under the other mode — the leak that used to force
    jax.clear_caches() teardowns in the interpret-mode test fixtures."""
    from . import pallas_ops as po
    from . import pallas_pairing as pp

    return (bool(po.INTERPRET), bool(pp.INTERPRET))


def _freeze(tree):
    leaves, treedef = jax.tree.flatten(tree)
    return (tuple(leaves), treedef)


# (fn, tail_ranks, out_tail_ranks, min_bucket, max_bucket) -> wrapper.
# Keyed on fn IDENTITY: a second bucketed() call on the same function with
# the same config returns the SAME wrapper, so every call site (range_proof
# lazy wrappers, the precompile registry, tests) shares one jit cache and
# each program traces once per process instead of once per call site.
_BUCKETED_MEMO: dict = {}
# name -> wrapper, for the precompile registry's enumeration
BUCKETED_OPS: dict = {}

# Optional trace-entry hook: called as TRACE_HOOK(op_name) each time an
# inner jit actually TRACES its function (jit cache miss). Bucketed fn
# bodies run only at trace time, so this observes real retraces — tests
# use it to assert trace dedup and that no tracing happens off the main
# thread (tests/test_batching.py, tests/test_service_tracing.py).
TRACE_HOOK = None


def bucketed(fn, tail_ranks, out_tail_ranks, min_bucket: int = 8,
             max_bucket: int | None = None, name: str | None = None):
    """Wrap fn so all leading batch dims are flattened + bucket-padded.

    The wrapped fn is jitted as ONE executable per bucket size, so repeated
    calls (any batch shape) reuse the in-process jit cache. min_bucket sets
    the smallest bucket — raise it for compile-heavy kernels (pairings) so a
    single compile serves every small batch. max_bucket CAPS the bucket:
    larger batches run as sequential max_bucket-sized chunks, so one
    compiled executable serves arbitrarily large batches (the whole-survey
    joint proof paths would otherwise mint fresh 16k-element compiles).

    NOTE on the persistent compilation cache: the CPU test suite keeps it
    OFF (jaxlib segfaulted deserializing very large CPU-backend executables
    — crash in compilation_cache.get_executable_and_time; see
    tests/conftest.py). The TPU bench/entry paths DO enable it
    (drynx_tpu/utils/cache.py) — TPU executables round-trip fine and the
    cache cuts the ~60-90 min cold-process Mosaic compile bill to
    lowering time only.

    tail_ranks: pytree matching fn's positional args, each leaf an int = the
    rank of that argument's per-element (non-batch) suffix, or -1 to pass the
    argument through untouched (constant tables etc., not batched).
    out_tail_ranks: pytree matching fn's output, same meaning.

    Wrappers are MEMOIZED on (fn, tail_ranks, out_tail_ranks, min_bucket,
    max_bucket): a second call with the same config returns the same wrapper
    object, so each (op, bucket) program traces once per process no matter
    how many call sites build it. `name` registers the wrapper in
    BUCKETED_OPS for the precompile registry (drynx_tpu/compilecache).
    """
    key = (fn, _freeze(tail_ranks), _freeze(out_tail_ranks),
           min_bucket, max_bucket)
    cached = _BUCKETED_MEMO.get(key)
    if cached is not None:
        if name:
            BUCKETED_OPS.setdefault(name, cached)
        return cached

    jits: dict = {}  # trace mode -> jax.jit object (own trace cache)
    hook_name = name or getattr(fn, "__qualname__", "?")

    def _traced_fn(*a, **k):
        hook = TRACE_HOOK
        if hook is not None:
            hook(hook_name)
        return fn(*a, **k)

    def _jit():
        mode = _trace_mode()
        j = jits.get(mode)
        if j is None:
            j = jits[mode] = jax.jit(_traced_fn)
        return j

    def _canon(args):
        """Flatten leading batch dims and pad to the bucket — the exact
        canonical shapes the inner jit sees at runtime."""
        leaves, treedef = jax.tree.flatten(tuple(args),
                                           is_leaf=lambda x: x is None)
        ranks = jax.tree.flatten(tail_ranks)[0]
        assert len(leaves) == len(ranks), (len(leaves), len(ranks))
        # generic pytree leaves: caller's dtypes pass through unchanged
        leaves = [jnp.asarray(l) for l in leaves]  # drynx: noqa[implicit-dtype]
        batch = jnp.broadcast_shapes(
            *[l.shape[: l.ndim - r] for l, r in zip(leaves, ranks)
              if r >= 0])
        B = int(np.prod(batch)) if batch else 1
        Bp = _next_bucket(B, min_bucket)

        flat = []
        for l, r in zip(leaves, ranks):
            if r < 0:
                flat.append(l)
                continue
            tail = l.shape[l.ndim - r:] if r else ()
            lb = jnp.broadcast_to(l, batch + tail).reshape((B,) + tail)
            if Bp != B:
                pad = jnp.broadcast_to(lb[:1], (Bp - B,) + tail)
                lb = jnp.concatenate([lb, pad], axis=0)
            flat.append(lb)
        return treedef, ranks, flat, batch, B, Bp

    def wrapped(*args):
        treedef, ranks, flat, batch, B, Bp = _canon(args)
        fn_ = _jit()

        out_ranks = jax.tree.flatten(out_tail_ranks)[0]
        if max_bucket is not None and Bp > max_bucket:
            chunks = []
            for s in range(0, Bp, max_bucket):
                part = [l if r < 0 else l[s:s + max_bucket]
                        for l, r in zip(flat, ranks)]
                chunks.append(fn_(*treedef.unflatten(part)))
            chunk_leaves = [jax.tree.flatten(c)[0] for c in chunks]
            out_def = jax.tree.flatten(chunks[0])[1]
            out_leaves = [jnp.concatenate([c[i] for c in chunk_leaves], 0)
                          for i in range(len(chunk_leaves[0]))]
        else:
            out = fn_(*treedef.unflatten(flat))
            out_leaves, out_def = jax.tree.flatten(out)

        res = []
        for o, r in zip(out_leaves, out_ranks):
            o = o[:B]
            tail = o.shape[1:]
            res.append(o.reshape(batch + tail))
        return out_def.unflatten(res)

    def lower(*args):
        """AOT entry: trace + lower the inner jit at the exact canonical
        (bucketed) shapes `wrapped(*args)` would dispatch, WITHOUT
        executing. Returns the jax.stages.Lowered; .compile() on it feeds
        the persistent compilation cache (drynx_tpu/compilecache)."""
        treedef, ranks, flat, batch, B, Bp = _canon(args)
        if max_bucket is not None and Bp > max_bucket:
            flat = [l if r < 0 else l[:max_bucket]
                    for l, r in zip(flat, ranks)]
        return _jit().lower(*treedef.unflatten(flat))

    def bucket_of(B: int) -> int:
        b = _next_bucket(int(B), min_bucket)
        return b if max_bucket is None else min(b, max_bucket)

    wrapped.lower = lower
    wrapped.bucket_of = bucket_of
    wrapped.config = {"tail_ranks": tail_ranks,
                      "out_tail_ranks": out_tail_ranks,
                      "min_bucket": min_bucket, "max_bucket": max_bucket}
    _BUCKETED_MEMO[key] = wrapped
    if name:
        BUCKETED_OPS.setdefault(name, wrapped)
    return wrapped


def host_dispatch(host_fn, tail_ranks, kernel_wrapped, gate=None):
    """Route a crypto-family op to the host backend when Pallas is
    unavailable (crypto/host_oracle.py -> the native C++ library or the
    pure-Python oracle — zero XLA compile, the round-3 CPU compile bill
    was hours per process), else to the bucketed kernel. The host path
    flattens/broadcasts all leading batch dims to one axis.

    tail_ranks: per-arg rank of the non-batch suffix; -1 passes the arg
    through untouched (constant tables). gate: optional predicate checked
    at call time — when false the kernel path is used (e.g. the G1 family
    only detours to host when the NATIVE library built; the Python oracle
    would lose to XLA there). Tuple-returning host fns are supported
    (each element reshaped to the batch)."""

    def wrapped(*args):
        from . import host_oracle as ho
        from . import pallas_ops as po

        if not (ho.ENABLED and not po.available()):
            return kernel_wrapped(*args)
        if gate is not None and not gate():
            return kernel_wrapped(*args)
        if any(isinstance(a, jax.core.Tracer) for a in args):
            # inside a jit/shard_map trace np.asarray would raise
            # TracerArrayConversionError — the kernel path traces fine
            return kernel_wrapped(*args)
        arrs = [a if r < 0 else np.asarray(a)
                for a, r in zip(args, tail_ranks)]
        batch = jnp.broadcast_shapes(
            *[a.shape[: a.ndim - r] for a, r in zip(arrs, tail_ranks)
              if r >= 0])
        flat = []
        for a, r in zip(arrs, tail_ranks):
            if r < 0:
                flat.append(a)
                continue
            tail = a.shape[a.ndim - r:] if r else ()
            flat.append(np.ascontiguousarray(
                np.broadcast_to(a, batch + tail)).reshape((-1,) + tail))
        out = host_fn(*flat)
        if isinstance(out, tuple):
            return tuple(jnp.asarray(o.reshape(batch + o.shape[1:]))  # drynx: noqa[implicit-dtype]
                         for o in out)
        # host_fn already returns concrete numpy arrays; keep their dtypes
        return jnp.asarray(out.reshape(batch + out.shape[1:]))  # drynx: noqa[implicit-dtype]

    return wrapped


def tree_reduce_add(tensor, add_fn, axis: int = 0):
    """Log-depth reduction of `tensor` along `axis` with a batched group-add.

    The on-chip analogue of the reference's n-ary CN aggregation tree
    (services/service.go:676); works for points and ciphertexts alike.
    """
    t = jnp.moveaxis(jnp.asarray(tensor), axis, 0)  # drynx: noqa[implicit-dtype]
    n = int(t.shape[0])
    while n > 1:
        half = n // 2
        red = add_fn(t[: 2 * half : 2], t[1 : 2 * half : 2])
        t = jnp.concatenate([red, t[-1:]], axis=0) if n % 2 else red
        n = int(t.shape[0])
    return t[0]


# ---------------------------------------------------------------------------
# Bucketed views of the hot kernels (imported lazily to avoid cycles)
# ---------------------------------------------------------------------------

def _build():
    from . import curve as C
    from . import g2 as G2
    from . import fp12 as F12
    from . import pairing as PAIR
    from . import elgamal as eg
    from . import field as F
    from .field import FN

    from . import host_oracle as _ho_early
    from . import native_pairing as npair

    g = globals()
    # G1 family: on CPU (no Pallas) detour to the native C++ library when
    # it built — gated on npair.available because the PYTHON oracle would
    # lose to the XLA kernels here, unlike the pairing family
    _ng = npair.available
    g["g1_add"] = host_dispatch(
        _ho_early.g1_add_host, (2, 2),
        bucketed(C.add, (2, 2), 2, max_bucket=4096, name="g1_add"),
        gate=_ng)
    g["g1_neg"] = host_dispatch(
        _ho_early.g1_neg_host, (2,),
        bucketed(C.neg, (2,), 2, max_bucket=4096, name="g1_neg"), gate=_ng)
    g["g1_scalar_mul"] = host_dispatch(
        _ho_early.g1_scalar_mul_host, (2, 1),
        bucketed(C.scalar_mul, (2, 1), 2, max_bucket=4096,
                 name="g1_scalar_mul"), gate=_ng)
    g["g1_eq"] = host_dispatch(
        _ho_early.g1_eq_host, (2, 2),
        bucketed(C.eq, (2, 2), 0, max_bucket=4096, name="g1_eq"), gate=_ng)
    g["g1_normalize"] = host_dispatch(
        _ho_early.g1_normalize_host, (2,),
        bucketed(C.normalize, (2,), (1, 1, 0), max_bucket=4096,
                 name="g1_normalize"), gate=_ng)
    g["g2_scalar_mul"] = host_dispatch(
        _ho_early.g2_scalar_mul_host, (3, 1),
        bucketed(G2.scalar_mul, (3, 1), 3, min_bucket=32,
                 max_bucket=2048, name="g2_scalar_mul"), gate=_ng)
    g["g2_normalize"] = host_dispatch(
        _ho_early.g2_normalize_host, (3,),
        bucketed(G2.normalize, (3,), (2, 2, 0),
                 min_bucket=32, max_bucket=2048, name="g2_normalize"),
        gate=_ng)
    g["fixed_base_mul"] = host_dispatch(
        _ho_early.fixed_base_mul_host, (-1, 1),
        bucketed(eg.fixed_base_mul, (-1, 1), 2, max_bucket=4096,
                 name="fixed_base_mul"), gate=_ng)
    from . import pallas_ops as po
    from . import pallas_pairing as ppair

    def _pair_fn(px, py, qx, qy):
        # Mosaic pairing kernels on TPU (the jnp rolled-loop pairing runs
        # seconds per batch on hardware — loop overhead, not compute)
        if po.available():
            return ppair.pair_flat(px, py, qx, qy)
        return PAIR.pair((px, py), (qx, qy))

    def _gt_pow_fn(f, k):
        if po.available():
            # windowed kernel with CYCLOTOMIC squarings (2x per squaring):
            # every gt_pow call site feeds pairing outputs (sig_gt_table /
            # gt_base), which live in GΦ12 by construction. Wire-provided
            # GT elements go through gt_pow64 + gt_membership_ok instead.
            return ppair.f12_wpow_flat(f, k, cyc=True)
        return F12.pow_var(f, k)

    def _gt_mul_fn(a, b):
        if po.available():
            return ppair.f12_mul_flat(a, b)
        return F12.mul(a, b)

    def _miller_fn(px, py, qx, qy):
        if po.available():
            return ppair.miller_flat(px, py, qx, qy)
        return PAIR.miller_loop((px, py), (qx, qy))

    def _gt_pow128_fn(f, k):
        # 128-bit exponents (the order-n gate's t-1 = p - n): half the
        # ladder of the generic 256-bit gt_pow. cyc=True is safe because
        # gt_order_ok only runs AFTER gt_membership_ok (GΦ12 members).
        if po.available():
            return ppair.f12_wpow_flat(f, k, n_bits=128, cyc=True)
        return F12.pow_var(f, k, n_bits=128)

    def _gt_pow64_fn(f, k):
        # short exponents (RLC verification weights < 2^62): 21 windows;
        # n_bits=63 deliberately matches the final-exp u-chain pows so a
        # shared (n_bits, wbits) jit entry can be reused at equal shapes.
        # cyc=True: callers (RLC verify) gate wire GT elements through
        # gt_membership_ok first, so cyclotomic squarings are valid.
        if po.available():
            return ppair.f12_wpow_flat(f, k, n_bits=63, cyc=True)
        return F12.pow_var(f, k)

    def _gt_frob2_fn(f):
        if po.available():
            return ppair.f12_slotmul_flat(f, "frob2")
        return PAIR._frob2(f)

    def _gt_frob1_fn(f):
        if po.available():
            return ppair.f12_slotmul_flat(f, "frob1")
        return PAIR._frob1(f)

    def _final_exp_fn(f):
        if po.available():
            return ppair.final_exp_flat(f)
        return PAIR.final_exp(f)

    from . import host_oracle as ho

    g["pair"] = host_dispatch(
        ho.pair_host, (1, 1, 2, 2),
        bucketed(_pair_fn, (1, 1, 2, 2), 3, min_bucket=32, max_bucket=2048,
                 name="pair"))
    g["gt_frob2"] = bucketed(_gt_frob2_fn, (3,), 3, min_bucket=32,
                             max_bucket=2048, name="gt_frob2")
    g["gt_frob1"] = bucketed(_gt_frob1_fn, (3,), 3, min_bucket=32,
                             max_bucket=2048, name="gt_frob1")
    g["g1_scalar_mul64"] = host_dispatch(
        ho.g1_scalar_mul64_host, (2, 1),
        bucketed(lambda p, k: C.scalar_mul_short(p, k, 64), (2, 1), 2,
                 max_bucket=4096, name="g1_scalar_mul64"), gate=_ng)
    g["miller"] = host_dispatch(
        ho.miller_host, (1, 1, 2, 2),
        bucketed(_miller_fn, (1, 1, 2, 2), 3, min_bucket=32,
                 max_bucket=2048, name="miller"))
    g["gt_pow"] = host_dispatch(
        ho.gt_pow_host, (3, 1),
        bucketed(_gt_pow_fn, (3, 1), 3, min_bucket=32, max_bucket=2048,
                 name="gt_pow"))
    g["gt_pow64"] = host_dispatch(
        ho.gt_pow_host, (3, 1),
        bucketed(_gt_pow64_fn, (3, 1), 3, min_bucket=32, max_bucket=2048,
                 name="gt_pow64"))
    g["gt_pow128"] = host_dispatch(
        ho.gt_pow_host, (3, 1),
        bucketed(_gt_pow128_fn, (3, 1), 3, min_bucket=32, max_bucket=2048,
                 name="gt_pow128"))
    g["final_exp"] = host_dispatch(
        ho.final_exp_host, (3,),
        bucketed(_final_exp_fn, (3,), 3, min_bucket=8, max_bucket=2048,
                 name="final_exp"))
    g["gt_mul"] = host_dispatch(
        ho.gt_mul_host, (3, 3),
        bucketed(_gt_mul_fn, (3, 3), 3, min_bucket=32, max_bucket=2048,
                 name="gt_mul"))
    g["gt_eq"] = bucketed(F12.eq, (3, 3), 0, min_bucket=32, max_bucket=2048,
                          name="gt_eq")
    g["fn_add"] = bucketed(lambda a, b: F.add(a, b, FN), (1, 1), 1,
                           name="fn_add")
    g["fn_sub"] = bucketed(lambda a, b: F.sub(a, b, FN), (1, 1), 1,
                           name="fn_sub")
    g["fn_neg"] = bucketed(lambda a: F.neg(a, FN), (1,), 1, name="fn_neg")
    g["fn_mul_plain"] = bucketed(
        lambda a, b: F.mont_mul(F.to_mont(a, FN), b, FN), (1, 1), 1,
        name="fn_mul_plain")
    g["fn_mont_mul"] = bucketed(lambda a, b: F.mont_mul(a, b, FN), (1, 1), 1,
                                name="fn_mont_mul")
    # ElGamal layer (ciphertext tail = (2, 3, 16))
    g["encrypt"] = bucketed(eg.encrypt_with_tables, (-1, -1, 1, 1), 3,
                            name="encrypt")
    g["int_to_scalar"] = bucketed(eg.int_to_scalar, (0,), 1,
                                  name="int_to_scalar")
    g["table_lookup"] = bucketed(eg._table_lookup, (-1, -1, -1, -1, 2),
                                 (0, 0), name="table_lookup")
    g["ct_add"] = bucketed(eg.ct_add, (3, 3), 3, name="ct_add")
    g["ct_scalar_mul"] = bucketed(eg.ct_scalar_mul, (3, 1), 3,
                                  name="ct_scalar_mul")
    g["decrypt_point"] = bucketed(eg.decrypt_point, (3, 1), 2,
                                  name="decrypt_point")
    g["is_infinity"] = bucketed(C.is_infinity, (2,), 0, name="is_infinity")
    # Montgomery -> plain conversion for the canonical byte encoders
    # (proofs/encoding.py): unbucketed they re-compile per raw tensor
    # shape — the Fermat inverse in normalize is a 256-step scan
    g["from_mont_p"] = bucketed(lambda x: F.from_mont(x, F.FP), (1,), 1,
                                max_bucket=8192, name="from_mont_p")
    g["to_mont_p"] = bucketed(lambda x: F.to_mont(x, F.FP), (1,), 1,
                              max_bucket=8192, name="to_mont_p")


def gt_order_ok(a) -> bool:
    """True iff EVERY element of `a` (..., 6, 2, 16) has order dividing n —
    i.e. lies in the real GT, not just the cyclotomic supergroup.

    gt_membership_ok only proves GΦ12 membership, and GΦ12 has order
    Φ12(p) = n·c where for this curve the cofactor c is divisible by 13 and
    2749 (verified by tests/test_pairing.py). A commit-first forger can
    therefore multiply an honest `a` by a 13th root of unity BEFORE the
    Fiat-Shamir hash — passing the challenge binding, the D equation, and
    the GΦ12 gate — and survive a randomized-linear-combination verify with
    probability 1/13 per weight draw (round-4 advisor finding). This gate
    closes that: for n = p+1-t,
        frob1(a) == a^(t-1)  ⇔  a^(p-(t-1)) = a^n = 1
    — the exact order-n check at the cost of one Frobenius plus one
    (t-1)-bit (128-bit) pow per element instead of a 256-bit pow.
    Callers MUST gate `a` through gt_membership_ok FIRST: the TPU pow path
    uses cyclotomic squarings, which are only the squaring map on GΦ12."""
    from . import host_oracle as ho
    from . import pallas_ops as po
    from . import params

    t1 = params.P - params.N                             # t-1 = p - n
    if ho.ENABLED and not po.available():
        from . import native_pairing as npair
        from . import refimpl

        flat = np.asarray(a).reshape(-1, 6, 2, params.NUM_LIMBS)
        if npair.available():  # bit-identical C++ backend
            return bool(np.all(npair.gt_order_check_batch(flat)))
        from .host_oracle import _fp12_frob, _fp12_to_ref

        for i in range(flat.shape[0]):
            f = _fp12_to_ref(flat[i])
            # cyclotomic squarings are valid here: the caller contract
            # (gt_membership_ok first) puts f in GΦ12
            if _fp12_frob(f, 1) != refimpl.fp12_cyc_pow(f, t1):
                return False
        return True
    flat = jnp.asarray(a, dtype=jnp.uint32).reshape(-1, 6, 2, params.NUM_LIMBS)
    k = jnp.asarray(np.asarray(params.to_limbs(t1), dtype=np.uint32), dtype=jnp.uint32)
    lhs = gt_frob1(flat)
    rhs = gt_pow128(flat, jnp.broadcast_to(k, (flat.shape[0],) + k.shape))
    return bool(np.all(np.asarray(gt_eq(lhs, rhs))))


def gt_membership_ok(a) -> bool:
    """True iff EVERY element of `a` (..., 6, 2, 16) lies in GΦ12(p):
    z^(p^4)·z == z^(p^2)  ⇔  z^(p^4 - p^2 + 1) = 1.

    Honest GT elements (pairing outputs after the final exponentiation) are
    always members. The check gates WIRE-provided GT elements before any
    cyclotomic-squaring pow chain runs on them — outside GΦ12 the
    Granger-Scott formulas compute an unrelated function, so a forger must
    not reach them. Cost: two Frobenius maps + one mul + one compare over
    the batch (a handful of constant Fp2 muls per element)."""
    from . import params

    flat = jnp.asarray(a, dtype=jnp.uint32).reshape(-1, 6, 2, params.NUM_LIMBS)
    z2 = gt_frob2(flat)
    z4 = gt_frob2(z2)
    lhs = gt_mul(z4, flat)
    return bool(np.all(np.asarray(gt_eq(lhs, z2))))


def gt_reduce_prod(x):
    """Product of N GT elements: (N, 6, 2, 16) -> (6, 2, 16).

    TPU path pads with Montgomery ones to the next power of 8 and applies
    the 8-way product kernel log8(N) times (4 dispatches for N <= 4096);
    fallback is a log2 tree of gt_mul."""
    from . import fp12 as F12
    from . import pallas_ops as po
    from . import pallas_pairing as ppair

    x = jnp.asarray(x, dtype=jnp.uint32)
    N = int(x.shape[0])
    if N == 1:
        return x[0]
    if not po.available():
        return tree_reduce_add(x, gt_mul, axis=0)
    target = 8
    while target < N:
        target *= 8
    if target != N:
        x = jnp.concatenate([x, F12.one((target - N,))], axis=0)
    while x.shape[0] > 1:
        x = ppair.f12_mulreduce8_flat(x.reshape(-1, 8, 6, 2, 16))
    return x[0]


_build()

__all__ = ["bucketed", "BUCKETED_OPS", "tree_reduce_add", "gt_reduce_prod",
           "gt_membership_ok", "gt_order_ok", "g1_add",
           "g1_neg", "g1_scalar_mul", "g1_scalar_mul64", "g1_eq",
           "g1_normalize", "g2_scalar_mul", "g2_normalize", "fixed_base_mul",
           "pair", "miller", "gt_pow", "gt_pow64", "gt_pow128", "gt_frob1",
           "gt_frob2", "final_exp",
           "gt_mul", "gt_eq", "fn_add", "fn_sub", "fn_neg",
           "fn_mul_plain", "fn_mont_mul", "encrypt", "int_to_scalar",
           "table_lookup", "ct_add", "ct_scalar_mul", "decrypt_point",
           "is_infinity"]
