"""Batched G1 (bn256, y^2 = x^3 + 3) group ops on TPU.

Replaces kyber's per-object point arithmetic (used throughout the reference,
e.g. ElGamal ops in unlynx CipherText, obfuscation scalar mults at
protocols/obfuscation_protocol.go:241-243) with fixed-shape, branch-free
Jacobian-coordinate tensor math over the Montgomery field layer.

Point representation: uint32 array (..., 3, 16) = (X, Y, Z) Jacobian limbs in
Montgomery form; the point at infinity has Z == 0 (X/Y arbitrary nonzero).
Scalar multiplication is a 256-step `lax.scan` (double-and-add-always with
selects — constant shape, constant time), replacing data-dependent loops.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import field as F
from . import params, refimpl
from .field import FP
from .params import NUM_LIMBS


# ---------------------------------------------------------------------------
# Host helpers: oracle (affine int) <-> device (Jacobian limbs)
# ---------------------------------------------------------------------------

def from_ref(pt) -> np.ndarray:
    """Oracle affine point (or None) -> (3, 16) Jacobian Montgomery limbs."""
    if pt is None:
        x, y, z = 1, 1, 0
    else:
        x, y = pt
        z = 1
    mont = lambda v: params.to_limbs(v * params.R % params.P)
    return np.asarray([mont(x), mont(y), mont(z)], dtype=np.uint32)


def from_ref_batch(pts) -> np.ndarray:
    return np.stack([from_ref(p) for p in pts])


def to_ref(pt):
    """(..., 3, 16) device point(s) -> oracle affine point / list of points."""
    mx, my, inf = normalize(jnp.asarray(pt, dtype=jnp.uint32))
    aff_x = np.asarray(F.from_mont(mx, FP))
    aff_y = np.asarray(F.from_mont(my, FP))
    inf = np.asarray(inf)
    xs, ys = F.to_int(aff_x), F.to_int(aff_y)
    if np.asarray(inf).ndim == 0:
        return None if bool(inf) else (int(xs), int(ys))
    flat_inf = np.asarray(inf).reshape(-1)
    flat_x = np.asarray(xs, dtype=object).reshape(-1)
    flat_y = np.asarray(ys, dtype=object).reshape(-1)
    out = [None if i else (int(x), int(y)) for i, x, y in zip(flat_inf, flat_x, flat_y)]
    return out


# ---------------------------------------------------------------------------
# Device constants
# ---------------------------------------------------------------------------

def _const(pt):
    return jnp.asarray(from_ref(pt), dtype=jnp.uint32)


def infinity(batch_shape=()):
    base = jnp.asarray(from_ref(None), dtype=jnp.uint32)
    return jnp.broadcast_to(base, batch_shape + (3, NUM_LIMBS))


G1_GEN = _const(refimpl.G1)


# ---------------------------------------------------------------------------
# Group law
# ---------------------------------------------------------------------------

def is_infinity(p):
    return F.is_zero(p[..., 2, :])


@jax.jit
def double(p):
    """Jacobian doubling (a = 0): dbl-2009-l formulas."""
    X, Y, Z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    mul = lambda a, b: F.mont_mul(a, b, FP)
    A = mul(X, X)
    B = mul(Y, Y)
    C = mul(B, B)
    t = F.sub(mul(F.add(X, B), F.add(X, B)), F.add(A, C))
    D = F.add(t, t)
    E = F.add(F.add(A, A), A)
    Fv = mul(E, E)
    X3 = F.sub(Fv, F.add(D, D))
    C8 = F.add(F.add(F.add(C, C), F.add(C, C)), F.add(F.add(C, C), F.add(C, C)))
    Y3 = F.sub(mul(E, F.sub(D, X3)), C8)
    YZ = mul(Y, Z)
    Z3 = F.add(YZ, YZ)
    return jnp.stack([X3, Y3, Z3], axis=-2)


@jax.jit
def add(p, q):
    """Complete Jacobian addition via selects (add-2007-bl + edge cases)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    X2, Y2, Z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    mul = lambda a, b: F.mont_mul(a, b, FP)

    Z1Z1 = mul(Z1, Z1)
    Z2Z2 = mul(Z2, Z2)
    U1 = mul(X1, Z2Z2)
    U2 = mul(X2, Z1Z1)
    S1 = mul(Y1, mul(Z2, Z2Z2))
    S2 = mul(Y2, mul(Z1, Z1Z1))
    H = F.sub(U2, U1)
    HH = F.add(H, H)
    I = mul(HH, HH)
    J = mul(H, I)
    r = F.sub(S2, S1)
    r = F.add(r, r)
    V = mul(U1, I)
    X3 = F.sub(F.sub(mul(r, r), J), F.add(V, V))
    SJ = mul(S1, J)
    Y3 = F.sub(mul(r, F.sub(V, X3)), F.add(SJ, SJ))
    ZZ = F.sub(F.sub(mul(F.add(Z1, Z2), F.add(Z1, Z2)), Z1Z1), Z2Z2)
    Z3 = mul(ZZ, H)
    res_add = jnp.stack([X3, Y3, Z3], axis=-2)

    res_dbl = double(p)

    p_inf = is_infinity(p)
    q_inf = is_infinity(q)
    h_zero = F.is_zero(H)
    r_zero = F.is_zero(r)

    sel = lambda c, t, f: jnp.where(c[..., None, None], t, f)
    out = sel(h_zero & r_zero & ~p_inf & ~q_inf, res_dbl, res_add)
    out = sel(h_zero & ~r_zero & ~p_inf & ~q_inf,
              infinity(out.shape[:-2]), out)
    out = sel(q_inf, p, out)
    out = sel(p_inf, q, out)
    return out


@jax.jit
def neg(p):
    Y = F.neg(p[..., 1, :], FP)
    return p.at[..., 1, :].set(Y)


def scalar_mul(p, k_limbs):
    """k * P. k_limbs: (..., 16) plain (non-Montgomery) scalar limbs.

    Dispatches to the Pallas ladder kernel on TPU (whole windowed ladder in
    one kernel, limbs on sublanes / batch on lanes — crypto/pallas_ops.py);
    elsewhere, the compact 256-step jnp ladder below (see its docstring for
    why the fallback is deliberately NOT windowed). Replaces kyber Point.Mul
    at e.g. reference lib/range/range_proof.go:326 and the ElGamal
    key-switch/decrypt sites.
    """
    from . import pallas_ops as po

    if po.available():
        batch = jnp.broadcast_shapes(p.shape[:-2], k_limbs.shape[:-1])
        pb = jnp.broadcast_to(p, batch + (3, NUM_LIMBS))
        kb = jnp.broadcast_to(k_limbs, batch + (NUM_LIMBS,))
        out = po.scalar_mul_flat(pb.reshape((-1, 3, NUM_LIMBS)),
                                 kb.reshape((-1, NUM_LIMBS)))
        return out.reshape(batch + (3, NUM_LIMBS))
    return _scalar_mul_jnp(p, k_limbs)


def scalar_mul_short(p, k_limbs, n_bits: int = 64):
    """k * P for SHORT scalars (k < 2^n_bits, e.g. the 62-bit RLC
    verification weights): the Pallas ladder runs ceil(n_bits/4) windows
    instead of 64 — 4x fewer ladder steps at n_bits=64. Semantics equal
    scalar_mul for in-range k; out-of-range high bits are simply ignored."""
    from . import pallas_ops as po

    if po.available():
        batch = jnp.broadcast_shapes(p.shape[:-2], k_limbs.shape[:-1])
        pb = jnp.broadcast_to(p, batch + (3, NUM_LIMBS))
        kb = jnp.broadcast_to(k_limbs, batch + (NUM_LIMBS,))
        out = po.scalar_mul_flat(pb.reshape((-1, 3, NUM_LIMBS)),
                                 kb.reshape((-1, NUM_LIMBS)),
                                 n_windows=(n_bits + 3) // 4)
        return out.reshape(batch + (3, NUM_LIMBS))
    return _scalar_mul_jnp_short(p, k_limbs, n_bits)


@partial(jax.jit, static_argnames="n_bits")
def _scalar_mul_jnp_short(p, k_limbs, n_bits: int):
    """Truncated fallback ladder: scan only the low n_bits (LSB-first)."""
    bits = (k_limbs[..., :, None]
            >> jnp.arange(params.LIMB_BITS, dtype=jnp.uint32)) & 1
    bits = bits.reshape(bits.shape[:-2] + (256,))[..., :n_bits]
    bits_t = jnp.moveaxis(bits, -1, 0)

    batch = jnp.broadcast_shapes(p.shape[:-2], k_limbs.shape[:-1])
    acc0 = infinity(batch)
    base0 = jnp.broadcast_to(p, batch + (3, NUM_LIMBS))

    def step(state, bit):
        acc, base = state
        acc2 = add(acc, base)
        acc = jnp.where(bit[..., None, None] == 1, acc2, acc)
        base = double(base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (acc0, base0), bits_t)
    return acc


@jax.jit
def _scalar_mul_jnp(p, k_limbs):
    """Fallback ladder: 256-step double-and-add-always scan (constant shape,
    constant time). Deliberately the COMPACT graph, not the windowed ladder:
    this path only runs where Pallas doesn't (CPU tests), and XLA's CPU
    pipeline both compiles the windowed ladder's 16-entry table graph for
    minutes per jit and has been seen segfaulting under the accumulated
    compile load. The TPU hot path is the Pallas windowed kernel above."""
    bits = (k_limbs[..., :, None]
            >> jnp.arange(params.LIMB_BITS, dtype=jnp.uint32)) & 1
    bits = bits.reshape(bits.shape[:-2] + (256,))
    bits_t = jnp.moveaxis(bits, -1, 0)  # (256, ...)

    batch = jnp.broadcast_shapes(p.shape[:-2], k_limbs.shape[:-1])
    acc0 = infinity(batch)
    base0 = jnp.broadcast_to(p, batch + (3, NUM_LIMBS))

    def step(state, bit):
        acc, base = state
        acc2 = add(acc, base)
        acc = jnp.where(bit[..., None, None] == 1, acc2, acc)
        base = double(base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (acc0, base0), bits_t)
    return acc


@jax.jit
def normalize(p):
    """Jacobian -> affine: returns (x, y, is_inf). x,y Montgomery limbs."""
    from . import pallas_ops as po

    X, Y, Z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    inf = F.is_zero(Z)
    # avoid inv(0): substitute 1 for Z at infinity
    Zsafe = jnp.where(inf[..., None], FP.one_mont, Z)
    if po.available():
        # per-lane Fermat inversion kernel: the Montgomery-trick batch
        # inversion scans sequentially over the BATCH axis (slow on TPU)
        from . import pallas_pairing as ppair

        Zi = ppair.fp_inv_flat(Zsafe.reshape(-1, 16)).reshape(Zsafe.shape)
    else:
        Zi = F.batch_inv(Zsafe, FP)
    Zi2 = F.mont_mul(Zi, Zi, FP)
    x = F.mont_mul(X, Zi2, FP)
    y = F.mont_mul(Y, F.mont_mul(Zi, Zi2, FP), FP)
    return x, y, inf


@jax.jit
def eq(p, q):
    """Point equality in Jacobian coords (cross-multiplied, no inversion)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    X2, Y2, Z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    mul = lambda a, b: F.mont_mul(a, b, FP)
    Z1Z1, Z2Z2 = mul(Z1, Z1), mul(Z2, Z2)
    same_x = F.eq(mul(X1, Z2Z2), mul(X2, Z1Z1))
    same_y = F.eq(mul(Y1, mul(Z2, Z2Z2)), mul(Y2, mul(Z1, Z1Z1)))
    p_inf, q_inf = is_infinity(p), is_infinity(q)
    return (p_inf & q_inf) | (~p_inf & ~q_inf & same_x & same_y)


def scalars_from_ints(ks) -> np.ndarray:
    """Python ints -> plain (non-Montgomery) scalar limb arrays mod N."""
    if isinstance(ks, (int,)):
        return F.from_int(ks % params.N)
    return F.from_int([k % params.N for k in ks])


__all__ = [
    "from_ref", "from_ref_batch", "to_ref", "infinity", "G1_GEN",
    "is_infinity", "double", "add", "neg", "scalar_mul", "scalar_mul_short",
    "normalize", "eq",
    "scalars_from_ints",
]
