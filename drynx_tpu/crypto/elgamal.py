"""Batched additively-homomorphic ElGamal over bn256 G1 — the TPU workhorse.

Replaces unlynx's `CipherText{K = rB, C = mB + rP}` object layer (used across
the reference, e.g. lib/encoding/sum.go:24, lib/structs.go:403) with
fixed-shape limb tensors:

    ciphertext  : uint32 (..., 2, 3, 16)   — [K, C] Jacobian points
    scalar      : uint32 (..., 16)          — plain (non-Montgomery) mod-n limbs

All ops batch over leading dims and are jit-safe. Encryption returns the
blinding scalars r (mirroring unlynx `EncryptIntGetR`, needed by the range
proofs, reference lib/range/range_proof.go:61-69).

Discrete-log decryption mirrors unlynx `CreateDecryptionTable` /
`DecryptIntWithNeg` (reference services/api.go:49-50 builds the table with
limit 10000, including negatives): a host-precomputed table of m*B for
m in [-limit, limit], looked up on device via sorted-key binary search.

Fixed-base scalar multiplication uses 4-bit-window precomputed tables (the
base point B and survey keys are long-lived), cutting a 256-step
double-and-add scan to a 64-step add-only scan.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import curve as C
from . import field as F
from . import params, refimpl
from .field import FN, FP
from .params import LIMB_BITS, LIMB_MASK, NUM_LIMBS

WINDOW_BITS = 4
NUM_WINDOWS = 256 // WINDOW_BITS  # 64
WINDOW_SIZE = 1 << WINDOW_BITS    # 16


# ---------------------------------------------------------------------------
# Key generation (host-side; keys are few and long-lived)
# ---------------------------------------------------------------------------

def keygen(rng: np.random.Generator):
    """Return (secret int mod n, public point as host affine ints).

    Secrets are uniform mod n (512 random bits reduced, bias 2^-256) —
    structured/short secrets would be kangaroo-attackable."""
    x = int.from_bytes(rng.bytes(64), "little") % (params.N - 1) + 1
    return x, refimpl.g1_mul(refimpl.G1, x)


def secret_to_limbs(x: int) -> np.ndarray:
    return F.from_int(x % params.N)


# ---------------------------------------------------------------------------
# Fixed-base precomputation (host build, device lookup)
# ---------------------------------------------------------------------------

class FixedBase:
    """4-bit-window fixed-base table for one long-lived base point.

    table[w, d] = d * 16^w * P  as (64, 16, 3, 16) Jacobian Montgomery limbs.
    """

    def __init__(self, point_affine):
        rows = []
        base = point_affine  # affine int pair or None
        for _w in range(NUM_WINDOWS):
            row = [None]
            acc = None
            for _d in range(WINDOW_SIZE - 1):
                acc = refimpl.g1_add(acc, base)
                row.append(acc)
            rows.append(C.from_ref_batch(row))
            # advance base by 16x
            for _ in range(WINDOW_BITS):
                base = refimpl.g1_add(base, base)
        self.table = jnp.asarray(np.stack(rows), dtype=jnp.uint32)  # (64, 16, 3, 16)

    @classmethod
    def from_table(cls, table) -> "FixedBase":
        """Rehydrate from a persisted (64, 16, 3, 16) table, skipping the
        host EC ladder build (crypto-pool fb tenant)."""
        fb = cls.__new__(cls)
        fb.table = jnp.asarray(table, dtype=jnp.uint32)
        return fb

    def mul(self, k_limbs):
        return fixed_base_mul(self.table, k_limbs)


def fixed_base_mul(table, k_limbs, n_windows: int = NUM_WINDOWS):
    """k * P via windowed lookup-and-add. k_limbs: (..., 16) plain scalars.

    64 point additions instead of 256 double-and-add steps; `n_windows`
    truncates the ladder for scalars known to be small (k < 16^n_windows —
    e.g. 16 windows cover any nonnegative int64 plaintext). On TPU the
    whole ladder runs as one Pallas kernel (crypto/pallas_ops.py)."""
    from . import pallas_ops as po

    if po.available():
        batch = k_limbs.shape[:-1]
        out = po.fixed_base_mul_flat(table,
                                     k_limbs.reshape((-1, NUM_LIMBS)),
                                     n_windows=n_windows)
        return out.reshape(batch + (3, NUM_LIMBS))
    return _fixed_base_mul_jnp(table, k_limbs, n_windows)


@partial(jax.jit, static_argnames="n_windows")
def _fixed_base_mul_jnp(table, k_limbs, n_windows: int = NUM_WINDOWS):
    # 4 windows per 16-bit limb -> (..., 64) digit array, little-endian.
    shifts = jnp.arange(0, LIMB_BITS, WINDOW_BITS, dtype=jnp.uint32)  # (4,)
    digits = (k_limbs[..., :, None] >> shifts) & jnp.uint32(WINDOW_SIZE - 1)
    digits = digits.reshape(digits.shape[:-2] + (NUM_WINDOWS,))
    digits_t = jnp.moveaxis(digits, -1, 0)[:n_windows]  # (W, ...)

    batch = digits.shape[:-1]
    acc0 = C.infinity(batch)

    def step(acc, wd):
        w, digit = wd
        row = table[w]                    # (16, 3, 16)
        pt = jnp.take(row, digit, axis=0)  # (..., 3, 16)
        return C.add(acc, pt), None

    ws = jnp.arange(n_windows, dtype=jnp.uint32)
    acc, _ = jax.lax.scan(step, acc0, (ws, digits_t))
    return acc


BASE_TABLE = FixedBase(refimpl.G1)


# ---------------------------------------------------------------------------
# Scalars: randomness + small-int embedding
# ---------------------------------------------------------------------------

def random_scalars(key, shape=()):
    """Uniform scalars mod n as plain limbs (..., 16), via 512-bit reduction."""
    bits = jax.random.bits(key, shape + (2 * NUM_LIMBS,), dtype=jnp.uint32)
    limbs = bits & jnp.uint32(LIMB_MASK)
    lo, hi = limbs[..., :NUM_LIMBS], limbs[..., NUM_LIMBS:]
    return F.reduce_512(hi, lo, FN)


_N_LIMBS_DEV = None


def _n_limbs():
    global _N_LIMBS_DEV
    if _N_LIMBS_DEV is None:
        # numpy (not jnp): caching a device array created during a trace
        # would leak a tracer into the cache
        _N_LIMBS_DEV = np.asarray(params.to_limbs(params.N), dtype=np.uint32)
    return _N_LIMBS_DEV


@jax.jit
def int_to_scalar(v):
    """Signed int32/int64 array (...,) -> mod-n scalar limbs (..., 16).

    Negative values map to n - |v| (the reference encodes negatives the same
    way via kyber's SetInt64, e.g. lib/encoding/logistic_regression.go:406).
    """
    v = v.astype(jnp.int64) if v.dtype != jnp.int64 else v
    mag = jnp.abs(v).astype(jnp.uint64)
    limbs = jnp.zeros(v.shape + (NUM_LIMBS,), dtype=jnp.uint32)
    for k in range(4):  # |v| < 2^63 fits in 4 limbs
        limbs = limbs.at[..., k].set(
            (mag >> jnp.uint64(LIMB_BITS * k)).astype(jnp.uint32)
            & jnp.uint32(LIMB_MASK)
        )
    negl, _ = F._sub_limbs(jnp.broadcast_to(_n_limbs(), limbs.shape), limbs)
    is_zero = F.is_zero(limbs)
    neg = jnp.where(is_zero[..., None], limbs, negl)
    return jnp.where((v < 0)[..., None], neg, limbs)


# ---------------------------------------------------------------------------
# Core ElGamal ops
# ---------------------------------------------------------------------------

# host EC ladder builds this process actually paid (the pool restart
# test asserts this stays flat when the store is warm)
FB_BUILD_COUNT = 0


def pub_table(pub_affine) -> FixedBase:
    """Precompute the fixed-base table for a public key (host affine ints).

    Consults the active crypto pool (drynx_tpu.pool) when one is set:
    tables are content-addressed by the affine point, so a warm store
    skips the ~0.4 s host EC ladder build per long-lived key."""
    global FB_BUILD_COUNT
    import hashlib

    from .. import pool as pool_mod

    store = pool_mod.active_pool()
    dig = None
    if store is not None and pub_affine is not None:
        x, y = pub_affine
        dig = hashlib.sha256(f"{int(x):x},{int(y):x}".encode()).hexdigest()[:16]
        got = store.load_sig("fb", dig)
        if got is not None:
            return FixedBase.from_table(got["table"])
    tbl = FixedBase(pub_affine)
    FB_BUILD_COUNT += 1
    if dig is not None:
        store.save_sig("fb", dig, table=np.asarray(tbl.table))
    return tbl


@jax.jit
def encrypt_with_tables(base_table, pub_tbl, m_scalars, r_scalars):
    """Encrypt m (scalar limbs) with blinding r: (K, C) = (rB, mB + rP)."""
    K = fixed_base_mul(base_table, r_scalars)
    mB = fixed_base_mul(base_table, m_scalars)
    rP = fixed_base_mul(pub_tbl, r_scalars)
    Cc = C.add(mB, rP)
    return jnp.stack([K, Cc], axis=-3)


# int64 plaintexts fit 16 hex digits: |v| < 2^64 = 16^16
SMALL_WINDOWS = 16


@jax.jit
def encrypt_ints_with_tables(base_table, pub_tbl, values, r_scalars):
    """Encrypt SIGNED int64 plaintexts: mB computed as |v|·B over a
    16-window truncated ladder (4x shorter than the full 64), negated
    pointwise for v < 0 — exactly m·B since (n−|v|)·B = −(|v|·B)."""
    values = jnp.asarray(values, dtype=jnp.int64)
    neg = values < 0
    # |v| via two's-complement negate in uint64: exact for ALL int64,
    # including INT64_MIN (where jnp.abs wraps)
    u = values.astype(jnp.uint64)
    mag = jnp.where(neg, ~u + jnp.uint64(1), u)
    limbs = jnp.zeros(values.shape + (NUM_LIMBS,), dtype=jnp.uint32)
    for k in range(4):  # |v| <= 2^63 fits 4 limbs
        limbs = limbs.at[..., k].set(
            (mag >> jnp.uint64(LIMB_BITS * k)).astype(jnp.uint32)
            & jnp.uint32(LIMB_MASK))
    K = fixed_base_mul(base_table, r_scalars)
    mB = fixed_base_mul(base_table, limbs, n_windows=SMALL_WINDOWS)
    mB = jnp.where(neg[..., None, None], C.neg(mB), mB)
    rP = fixed_base_mul(pub_tbl, r_scalars)
    Cc = C.add(mB, rP)
    return jnp.stack([K, Cc], axis=-3)


def encrypt_ints(key, pub_tbl: FixedBase, values, base_tbl: FixedBase = None):
    """Encrypt an int array; returns (ciphertexts (...,2,3,16), r scalars).

    Mirrors unlynx EncryptIntGetR (used at lib/encoding/sum.go:24).
    """
    base_tbl = base_tbl or BASE_TABLE
    values = jnp.asarray(values, dtype=jnp.int64)
    r = random_scalars(key, values.shape)
    ct = encrypt_ints_with_tables(base_tbl.table, pub_tbl.table, values, r)
    return ct, r


@jax.jit
def ct_add(a, b):
    """Homomorphic add (unlynx CipherText.Add)."""
    return C.add(a, b)


@jax.jit
def ct_sub(a, b):
    return C.add(a, C.neg(b))


@jax.jit
def ct_scalar_mul(ct, s_limbs):
    """Multiply BOTH components by scalar s (unlynx MulCipherTextbyScalar,
    reference protocols/obfuscation_protocol.go:241-243)."""
    return C.scalar_mul(ct, s_limbs[..., None, :])


def ct_zero(batch_shape=()):
    return C.infinity(tuple(batch_shape) + (2,))


@jax.jit
def decrypt_point(ct, x_limbs):
    """M = C - x*K. x_limbs: secret scalar limbs (broadcastable)."""
    K = ct[..., 0, :, :]
    Cc = ct[..., 1, :, :]
    xK = C.scalar_mul(K, x_limbs)
    return C.add(Cc, C.neg(xK))


@jax.jit
def decrypt_check_zero(ct, x_limbs):
    """True iff plaintext == 0 (unlynx DecryptCheckZero,
    reference lib/encoding/OR_AND.go:61,114)."""
    return C.is_infinity(decrypt_point(ct, x_limbs))


# ---------------------------------------------------------------------------
# Discrete-log decryption table (host build, device binary-search lookup)
# ---------------------------------------------------------------------------

class DecryptionTable:
    """m*B for m in [-limit, limit] keyed by truncated affine coords.

    Sorted uint32 keys (x low 31 bits << 1 | y parity); device lookup does
    jnp.searchsorted then verifies full x limbs over a small window, so key
    collisions cannot cause wrong answers. Mirrors unlynx
    CreateDecryptionTable + DecryptIntWithNeg (reference services/api.go:49).
    """

    WINDOW = 4

    def __init__(self, limit: int = 10000, base=None):
        base = base or refimpl.G1
        pts, vals = [], []
        acc = None
        for m in range(1, limit + 1):
            acc = refimpl.g1_add(acc, base)
            pts.append(acc)
            vals.append(m)
            pts.append(refimpl.g1_neg(acc))
            vals.append(-m)
        xs = np.zeros((len(pts), NUM_LIMBS), dtype=np.uint32)
        keys = np.zeros(len(pts), dtype=np.uint32)
        for i, (x, y) in enumerate(pts):
            xs[i] = params.to_limbs(x)
            keys[i] = ((x & 0x7FFFFFFF) << 1 | (y & 1)) & 0xFFFFFFFF
        order = np.argsort(keys, kind="stable")
        self.limit = limit
        self.keys = jnp.asarray(keys[order], dtype=jnp.uint32)
        self.xs = jnp.asarray(xs[order], dtype=jnp.uint32)
        self.ysign = jnp.asarray(
            np.asarray([pts[i][1] & 1 for i in order], dtype=np.uint32), dtype=jnp.uint32)
        self.vals = jnp.asarray(np.asarray(vals, dtype=np.int32)[order], dtype=jnp.int32)

    def lookup(self, points):
        """Batched point -> int. Returns (values int32, found bool)."""
        return _table_lookup(self.keys, self.xs, self.ysign, self.vals, points)


@jax.jit
def _table_lookup(keys, xs, ysign, vals, points):
    ax_m, ay_m, inf = C.normalize(points)
    ax = F.from_mont(ax_m, FP)
    ay = F.from_mont(ay_m, FP)
    x31 = (ax[..., 0].astype(jnp.uint32)
           | (ax[..., 1].astype(jnp.uint32) << LIMB_BITS)) & jnp.uint32(0x7FFFFFFF)
    parity = ay[..., 0] & jnp.uint32(1)
    qkey = (x31 << 1) | parity

    pos = jnp.searchsorted(keys, qkey)
    T = keys.shape[0]
    val = jnp.zeros(qkey.shape, dtype=jnp.int32)
    found = jnp.zeros(qkey.shape, dtype=bool)
    for w in range(DecryptionTable.WINDOW):
        idx = jnp.clip(pos + w, 0, T - 1)
        match = (jnp.all(jnp.take(xs, idx, axis=0) == ax, axis=-1)
                 & (jnp.take(ysign, idx, axis=0) == parity))
        val = jnp.where(match & ~found, jnp.take(vals, idx, axis=0), val)
        found = found | match
    val = jnp.where(inf, 0, val)
    found = found | inf
    return val, found


def decrypt_ints(ct, secret: int, table: DecryptionTable):
    """Full decryption: (..., 2, 3, 16) cts -> (int32 values, found flags)."""
    x = jnp.asarray(secret_to_limbs(secret), dtype=jnp.uint32)
    return table.lookup(decrypt_point(ct, x))


# ---------------------------------------------------------------------------
# Host-side oracle mirror (for tests)
# ---------------------------------------------------------------------------

def encrypt_ref(m: int, r: int, pub):
    """Oracle encryption returning affine int points (K, C)."""
    K = refimpl.g1_mul(refimpl.G1, r)
    mB = refimpl.g1_mul(refimpl.G1, m % params.N)
    rP = refimpl.g1_mul(pub, r)
    return K, refimpl.g1_add(mB, rP)


def ct_from_ref(kc) -> np.ndarray:
    K, Cc = kc
    return np.stack([C.from_ref(K), C.from_ref(Cc)])


def ct_to_ref(ct):
    flat = np.asarray(ct).reshape(-1, 3, NUM_LIMBS)
    pts = C.to_ref(jnp.asarray(flat, dtype=jnp.uint32))
    if not isinstance(pts, list):
        pts = [pts]
    out = [(pts[2 * i], pts[2 * i + 1]) for i in range(len(pts) // 2)]
    shape = np.asarray(ct).shape[:-3]
    if shape == ():
        return out[0]
    return out


__all__ = [
    "keygen", "secret_to_limbs", "FixedBase", "fixed_base_mul", "BASE_TABLE",
    "random_scalars", "int_to_scalar", "pub_table", "encrypt_with_tables",
    "encrypt_ints_with_tables", "encrypt_ints", "ct_add", "ct_sub",
    "ct_scalar_mul", "ct_zero",
    "decrypt_point", "decrypt_check_zero", "DecryptionTable", "decrypt_ints",
    "encrypt_ref", "ct_from_ref", "ct_to_ref",
]
