"""Batched modular (Montgomery) arithmetic for TPU — the innermost layer.

Design (TPU-first, see SURVEY.md §7): a 256-bit field element is a vector of
16 little-endian limbs of 16 bits, stored in uint32 lanes so limb products
(16b x 16b = 32b) never overflow.  Everything is fixed-shape, branch-free
(selects only), and batches over arbitrary leading dims — the reference's
per-element goroutine fan-out (unlynx StartParallelize, used at
lib/range/range_proof.go:75 and 30+ sites) becomes plain vectorization here.

Montgomery reduction runs 16 unrolled limb steps with split-half (lo/hi)
accumulation; column magnitudes stay < 2^22, well inside uint32.

Two modulus contexts are provided: FP (the bn256 base field) and FN (the
scalar field), mirroring kyber's (Point, Scalar) split used throughout the
reference (e.g. lib/range/range_proof.go:320-417).
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import params
from .params import LIMB_BITS, LIMB_MASK, NUM_LIMBS

MASK = jnp.uint32(LIMB_MASK)


@dataclasses.dataclass(frozen=True)
class ModCtx:
    """Constants for one modulus (host ints + device arrays)."""

    modulus: int
    nprime: int          # -m^-1 mod 2^16
    r2: int              # R^2 mod m
    name: str

    @property
    def m_limbs(self) -> jnp.ndarray:
        return jnp.asarray(params.to_limbs(self.modulus), dtype=jnp.uint32)

    @property
    def r2_limbs(self) -> jnp.ndarray:
        return jnp.asarray(params.to_limbs(self.r2), dtype=jnp.uint32)

    @property
    def one_mont(self) -> jnp.ndarray:
        """Montgomery representation of 1 (= R mod m)."""
        return jnp.asarray(params.to_limbs(params.R % self.modulus), dtype=jnp.uint32)

    @property
    def zero(self) -> jnp.ndarray:
        return jnp.zeros((NUM_LIMBS,), dtype=jnp.uint32)


FP = ModCtx(params.P, params.NPRIME, params.R2_MOD_P, "Fp")
FN = ModCtx(params.N, params.NPRIME_N, params.R2_MOD_N, "Fn")


# ---------------------------------------------------------------------------
# Host <-> device conversion helpers (numpy; not jitted)
# ---------------------------------------------------------------------------

def from_int(x, batch_shape=()) -> np.ndarray:
    """Python int (or nested list of ints) -> uint32 limb array."""
    arr = np.asarray(x, dtype=object)
    out = np.zeros(arr.shape + (NUM_LIMBS,), dtype=np.uint32)
    for idx in np.ndindex(arr.shape) if arr.shape else [()]:
        v = int(arr[idx]) if arr.shape else int(x)
        for k in range(NUM_LIMBS):
            out[idx + (k,)] = (v >> (LIMB_BITS * k)) & LIMB_MASK
    if batch_shape and not arr.shape:
        out = np.broadcast_to(out, batch_shape + (NUM_LIMBS,)).copy()
    return out


def to_int(limbs) -> "int | np.ndarray":
    """uint32 limb array -> Python int (object ndarray for batches)."""
    a = np.asarray(limbs)
    if a.ndim == 1:
        return params.from_limbs(a)
    flat = a.reshape(-1, NUM_LIMBS)
    out = np.array([params.from_limbs(row) for row in flat], dtype=object)
    return out.reshape(a.shape[:-1])


# ---------------------------------------------------------------------------
# Core limb ops (all jit-safe, batch over leading dims)
# ---------------------------------------------------------------------------

# When set, limb loops are fully unrolled at trace time (bigger XLA graphs,
# slow compiles, fastest TPU execution). Default: rolled lax.scan loops —
# ~16x smaller graphs, which keeps CPU-test compile times sane. Read at CALL
# time by the thin non-jitted public wrappers below and passed into the
# jitted entry points as a STATIC `unroll` argument, so flipping it (tests,
# TPU runs) creates fresh programs instead of silently reusing stale traces.
import os

UNROLL = os.environ.get("DRYNX_FIELD_UNROLL", "0") == "1"


def _carry_chain(cols, out_limbs, unroll: bool = False):
    """Sequential carry propagation down a column array -> out_limbs limbs.

    cols: (..., K) uint32 with values < 2^31. Returns ((..., out_limbs), carry).
    """
    carry0 = jnp.zeros(cols.shape[:-1], dtype=jnp.uint32)
    if unroll:
        outs = []
        carry = carry0
        for k in range(out_limbs):
            v = cols[..., k] + carry
            outs.append(v & MASK)
            carry = v >> LIMB_BITS
        return jnp.stack(outs, axis=-1), carry

    xs = jnp.moveaxis(cols[..., :out_limbs], -1, 0)

    def body(carry, c):
        v = c + carry
        return v >> LIMB_BITS, v & MASK

    carry, outs = jax.lax.scan(body, carry0, xs)
    return jnp.moveaxis(outs, 0, -1), carry


def _sub_limbs(a, b, unroll: bool = False):
    """a - b with borrow chain. Returns (diff_limbs, borrow in {0,1})."""
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (NUM_LIMBS,))
    b = jnp.broadcast_to(b, batch + (NUM_LIMBS,))
    borrow0 = jnp.zeros(batch, dtype=jnp.uint32)
    if unroll:
        outs = []
        borrow = borrow0
        for k in range(NUM_LIMBS):
            v = a[..., k] - b[..., k] - borrow  # uint32 wraparound is fine
            outs.append(v & MASK)
            borrow = (v >> LIMB_BITS) & jnp.uint32(1)  # 1 iff wrapped
        return jnp.stack(outs, axis=-1), borrow

    xs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0))

    def body(borrow, ab):
        av, bv = ab
        v = av - bv - borrow
        return (v >> LIMB_BITS) & jnp.uint32(1), v & MASK

    borrow, outs = jax.lax.scan(body, borrow0, xs)
    return jnp.moveaxis(outs, 0, -1), borrow


def _cond_sub_m(a, ctx: ModCtx, unroll: bool = False):
    """Return a - m if a >= m else a (a < 2m assumed, normalized limbs)."""
    diff, borrow = _sub_limbs(a, ctx.m_limbs, unroll)
    return jnp.where((borrow == 0)[..., None], diff, a)


@partial(jax.jit, static_argnames=("ctx", "unroll"))
def _add(a, b, ctx: ModCtx, unroll: bool):
    cols = a + b  # < 2^17 per limb
    s, carry = _carry_chain(cols, NUM_LIMBS, unroll)
    # a+b < 2m < 2^257: one carry bit possible beyond limb 15. Since m has
    # 256 bits, if carry==1 the value >= 2^256 > m: subtract m once; the
    # borrow from _sub_limbs cancels against carry.
    diff, borrow = _sub_limbs(s, ctx.m_limbs, unroll)
    use_diff = (borrow == 0) | (carry == 1)
    return jnp.where(use_diff[..., None], diff, s)


def add(a, b, ctx: ModCtx = FP):
    """(a + b) mod m; inputs normalized (< m)."""
    return _add(a, b, ctx, UNROLL)


@partial(jax.jit, static_argnames=("ctx", "unroll"))
def _sub(a, b, ctx: ModCtx, unroll: bool):
    diff, borrow = _sub_limbs(a, b, unroll)
    plus_m, _ = _carry_chain(diff + ctx.m_limbs, NUM_LIMBS, unroll)
    return jnp.where((borrow == 1)[..., None], plus_m, diff)


def sub(a, b, ctx: ModCtx = FP):
    """(a - b) mod m; inputs normalized."""
    return _sub(a, b, ctx, UNROLL)


def neg(a, ctx: ModCtx = FP):
    return _sub(jnp.zeros_like(a), a, ctx, UNROLL)


@jax.jit
def is_zero(a):
    """Boolean (...,) — all limbs zero (valid: representation is canonical)."""
    return jnp.all(a == 0, axis=-1)


@jax.jit
def eq(a, b):
    return jnp.all(a == b, axis=-1)


@partial(jax.jit, static_argnames=("ctx", "unroll"))
def _mont_mul(a, b, ctx: ModCtx, unroll: bool):
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (NUM_LIMBS,))
    b = jnp.broadcast_to(b, batch + (NUM_LIMBS,))

    prod = a[..., :, None] * b[..., None, :]  # (..., 16, 16) < 2^32
    lo = prod & MASK
    hi = prod >> LIMB_BITS

    cols = jnp.zeros(batch + (2 * NUM_LIMBS + 1,), dtype=jnp.uint32)
    m_limbs = ctx.m_limbs
    nprime = jnp.uint32(ctx.nprime)

    if unroll:
        for i in range(NUM_LIMBS):
            cols = cols.at[..., i:i + NUM_LIMBS].add(lo[..., i, :])
            cols = cols.at[..., i + 1:i + 1 + NUM_LIMBS].add(hi[..., i, :])
        # col magnitude < 32 * 0xffff < 2^21
        carry = jnp.zeros(batch, dtype=jnp.uint32)
        for i in range(NUM_LIMBS):
            v = cols[..., i] + carry
            mfac = ((v & MASK) * nprime) & MASK
            mp = mfac[..., None] * m_limbs  # (...,16) < 2^32
            mlo = mp & MASK
            mhi = mp >> LIMB_BITS
            carry = (v + mlo[..., 0]) >> LIMB_BITS
            cols = cols.at[..., i + 1:i + NUM_LIMBS].add(mlo[..., 1:])
            cols = cols.at[..., i + 1:i + 1 + NUM_LIMBS].add(mhi)
            # per step adds < 2*0xffff + small carry; total stays < 2^22
    else:
        # rolled variants: same arithmetic, scanned over the 16 limb steps
        # (dynamic slices of STATIC width keep the graph small)
        zcol = jnp.zeros(batch + (1,), dtype=jnp.uint32)
        add17 = (jnp.concatenate([lo, jnp.zeros_like(lo[..., :1])], axis=-1)
                 + jnp.concatenate([jnp.zeros_like(hi[..., :1]), hi], axis=-1))
        add17_t = jnp.moveaxis(add17, -2, 0)  # (16, ..., 17)

        def sbody(cs, xs_i):
            i, addend = xs_i
            seg = jax.lax.dynamic_slice_in_dim(cs, i, NUM_LIMBS + 1, axis=-1)
            return jax.lax.dynamic_update_slice_in_dim(
                cs, seg + addend, i, axis=-1), None

        idx = jnp.arange(NUM_LIMBS, dtype=jnp.int32)
        cols, _ = jax.lax.scan(sbody, cols, (idx, add17_t))

        def rbody(state, i):
            cs, carry = state
            v = jax.lax.dynamic_index_in_dim(cs, i, axis=-1,
                                             keepdims=False) + carry
            mfac = ((v & MASK) * nprime) & MASK
            mp = mfac[..., None] * m_limbs
            mlo = mp & MASK
            mhi = mp >> LIMB_BITS
            carry = (v + mlo[..., 0]) >> LIMB_BITS
            addend = (jnp.concatenate([mlo[..., 1:], jnp.zeros_like(zcol)],
                                      axis=-1) + mhi)
            seg = jax.lax.dynamic_slice_in_dim(cs, i + 1, NUM_LIMBS, axis=-1)
            cs = jax.lax.dynamic_update_slice_in_dim(cs, seg + addend, i + 1,
                                                     axis=-1)
            return (cs, carry), None

        carry0 = jnp.zeros(batch, dtype=jnp.uint32)
        (cols, carry), _ = jax.lax.scan(rbody, (cols, carry0), idx)

    # Result = cols[16..32] + reduction carry folded into column 16; value is
    # < 2m (standard Montgomery bound), so one conditional subtract suffices.
    cols_hi = cols[..., NUM_LIMBS:].at[..., 0].add(carry)
    res, topcarry = _carry_chain(cols_hi[..., :NUM_LIMBS], NUM_LIMBS, unroll)
    top = cols_hi[..., NUM_LIMBS] + topcarry  # 0 or 1 (value < 2m < 2^257)
    diff, borrow = _sub_limbs(res, m_limbs, unroll)
    use_diff = (borrow == 0) | (top > 0)
    return jnp.where(use_diff[..., None], diff, res)


def mont_mul(a, b, ctx: ModCtx = FP):
    """Montgomery product a*b*R^-1 mod m. Inputs/outputs in Montgomery form.

    Schoolbook 512-bit column product with lo/hi split accumulation, then 16
    interleaved Montgomery reduction steps (static offsets; unrolled or
    scanned per the call-time UNROLL flag).
    """
    return _mont_mul(a, b, ctx, UNROLL)


def mont_sqr(a, ctx: ModCtx = FP):
    return _mont_mul(a, a, ctx, UNROLL)


def to_mont(a, ctx: ModCtx = FP):
    return _mont_mul(a, ctx.r2_limbs, ctx, UNROLL)


def from_mont(a, ctx: ModCtx = FP):
    one = jnp.zeros((NUM_LIMBS,), dtype=jnp.uint32).at[0].set(1)
    return _mont_mul(a, one, ctx, UNROLL)


def _exp_bits(e: int, nbits: int) -> np.ndarray:
    return np.asarray([(e >> i) & 1 for i in range(nbits)], dtype=np.uint32)


@partial(jax.jit, static_argnames=("e", "ctx", "nbits", "unroll"))
def _pow_const(a, e: int, ctx: ModCtx, nbits: int, unroll: bool):
    bits = jnp.asarray(_exp_bits(e, nbits), dtype=jnp.uint32)
    one = jnp.broadcast_to(ctx.one_mont, a.shape)

    def step(state, bit):
        acc, base = state
        acc2 = _mont_mul(acc, base, ctx, unroll)
        acc = jnp.where(bit == 1, acc2, acc)  # scalar cond broadcasts
        base = _mont_mul(base, base, ctx, unroll)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (one, a), bits)
    return acc


def pow_const(a, e: int, ctx: ModCtx = FP, nbits: int = 256):
    """a^e mod m for a STATIC exponent e, via right-to-left scan over bits.

    a in Montgomery form; result in Montgomery form.
    """
    return _pow_const(a, e, ctx, nbits, UNROLL)


def inv(a, ctx: ModCtx = FP):
    """a^(m-2) mod m (Fermat). a in Montgomery form. inv(0) = 0."""
    return _pow_const(a, ctx.modulus - 2, ctx, 256, UNROLL)


@partial(jax.jit, static_argnames=("ctx", "unroll"))
def _batch_inv(a, ctx: ModCtx, unroll: bool):
    shape = a.shape
    flat = a.reshape((-1, NUM_LIMBS))
    if flat.shape[0] == 0:
        return a
    mm = partial(_mont_mul, ctx=ctx, unroll=unroll)
    pref = jax.lax.associative_scan(mm, flat)
    suff = jax.lax.associative_scan(mm, flat, reverse=True)
    total_inv = _pow_const(pref[-1], ctx.modulus - 2, ctx, 256, unroll)
    one = jnp.broadcast_to(ctx.one_mont, (1, NUM_LIMBS))
    left = jnp.concatenate([one, pref[:-1]], axis=0)
    right = jnp.concatenate([suff[1:], one], axis=0)
    out = mm(mm(left, right), total_inv)
    return out.reshape(shape)


def batch_inv(a, ctx: ModCtx = FP):
    """Montgomery batch inversion: ONE Fermat inversion + O(n) products for
    the whole batch (all leading dims). Inputs in Montgomery form, must be
    nonzero (a zero poisons the whole batch — callers substitute 1 first,
    as curve.normalize does for points at infinity).

    prefix/suffix products via associative_scan (log-depth), then
    a_i^{-1} = P_{i-1} * S_{i+1} * (P_{n-1})^{-1}.
    """
    return _batch_inv(a, ctx, UNROLL)


@partial(jax.jit, static_argnames=("ctx", "unroll"))
def _reduce_512(hi, lo, ctx: ModCtx, unroll: bool):
    hi_part = _mont_mul(hi, ctx.r2_limbs, ctx, unroll)
    # mont_mul(hi, R2) = hi*R2*R^-1 = hi*R mod m = hi*2^256 mod m. Correct.
    lo_norm = _cond_sub_m(lo, ctx, unroll)
    return _add(hi_part, lo_norm, ctx, unroll)


def reduce_512(hi, lo, ctx: ModCtx = FP):
    """(hi*2^256 + lo) mod m, both 16-limb plain (non-Montgomery) values.

    Used for near-uniform random scalars: 512 random bits mod n has bias
    ~2^-256. hi*2^256 mod m = mont_mul(hi, R2) (since mont_mul multiplies by
    R^-1); then add (lo mod m).
    """
    return _reduce_512(hi, lo, ctx, UNROLL)


__all__ = [
    "ModCtx", "FP", "FN", "MASK",
    "from_int", "to_int",
    "add", "sub", "neg", "is_zero", "eq",
    "mont_mul", "mont_sqr", "to_mont", "from_mont",
    "pow_const", "inv", "batch_inv", "reduce_512",
]
