"""Batched Fp12 = Fp2[w]/(w^6 - XI) arithmetic (flat sextic extension).

Element representation: uint32 (..., 6, 2, 16) — six Fp2 coefficients of
w^0..w^5, Montgomery limbs. Matches refimpl.py's oracle tower.

Inversion uses the quadratic-over-cubic tower view
Fp12 = Fp6[w]/(w^2 - v), Fp6 = Fp2[v]/(v^3 - XI) with the flat coefficient
split a = (c0, c2, c4), b = (c1, c3, c5): 1/(a + w b) = (a - w b)/(a^2 - v b^2).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import fp2 as F2
from .params import NUM_LIMBS


def from_ref(x) -> np.ndarray:
    return np.stack([F2.from_ref(c) for c in x])


def to_ref(x):
    return tuple(F2.to_ref(x[..., k, :, :]) for k in range(6))


def one(batch_shape=()):
    o = jnp.concatenate([F2.one()[None], jnp.zeros((5, 2, NUM_LIMBS),
                                                   dtype=jnp.uint32)])
    return jnp.broadcast_to(o, batch_shape + (6, 2, NUM_LIMBS))


def _split(f):
    return ((f[..., 0, :, :], f[..., 2, :, :], f[..., 4, :, :]),
            (f[..., 1, :, :], f[..., 3, :, :], f[..., 5, :, :]))


def _join(A, B):
    return jnp.stack([A[0], B[0], A[1], B[1], A[2], B[2]], axis=-3)


def mul(a, b):
    """Karatsuba over the Fp6 sub-tower (v = w^2): 3 Fp6 muls of 6 Fp2
    muls each = 18 Fp2 muls (vs 36 schoolbook)."""
    A1, B1 = _split(a)
    A2, B2 = _split(b)
    t0 = _fp6_mul(A1, A2)
    t1 = _fp6_mul(B1, B2)
    t2 = _fp6_mul(_fp6_add(A1, B1), _fp6_add(A2, B2))
    return _join(_fp6_add(t0, _fp6_mul_v(t1)),
                 _fp6_sub(_fp6_sub(t2, t0), t1))


def sqr(a):
    """Complex-method squaring over Fp6: 2 Fp6 muls = 12 Fp2 muls."""
    A, B = _split(a)
    ab = _fp6_mul(A, B)
    t = _fp6_mul(_fp6_add(A, B), _fp6_add(A, _fp6_mul_v(B)))
    c0 = _fp6_sub(_fp6_sub(t, ab), _fp6_mul_v(ab))
    return _join(c0, _fp6_add(ab, ab))


def conj6(a):
    """a^(p^6): negate odd-w coefficients."""
    out = [a[..., k, :, :] if k % 2 == 0 else F2.neg(a[..., k, :, :])
           for k in range(6)]
    return jnp.stack(out, axis=-3)


def eq(a, b):
    return jnp.all(a == b, axis=(-1, -2, -3))


# ---------------------------------------------------------------------------
# Fp6 helpers on coefficient triples (tuples of (..., 2, 16) Fp2 elements)
# ---------------------------------------------------------------------------

def _fp6_mul(a, b):
    """3-way Karatsuba: 6 Fp2 muls (vs 9 schoolbook)."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = F2.mul(a0, b0)
    t1 = F2.mul(a1, b1)
    t2 = F2.mul(a2, b2)
    m01 = F2.mul(F2.add(a0, a1), F2.add(b0, b1))
    m02 = F2.mul(F2.add(a0, a2), F2.add(b0, b2))
    m12 = F2.mul(F2.add(a1, a2), F2.add(b1, b2))
    c0 = F2.add(t0, F2.mul_xi(F2.sub(F2.sub(m12, t1), t2)))
    c1 = F2.add(F2.sub(F2.sub(m01, t0), t1), F2.mul_xi(t2))
    c2 = F2.add(F2.sub(F2.sub(m02, t0), t2), t1)
    return (c0, c1, c2)


def _fp6_add(a, b):
    return tuple(F2.add(x, y) for x, y in zip(a, b))


def _fp6_sub(a, b):
    return tuple(F2.sub(x, y) for x, y in zip(a, b))


def _fp6_mul_v(a):
    """Multiply by v: (a0, a1, a2) -> (XI*a2, a0, a1)."""
    return (F2.mul_xi(a[2]), a[0], a[1])


def _fp6_inv(a):
    a0, a1, a2 = a
    c0 = F2.sub(F2.sqr(a0), F2.mul_xi(F2.mul(a1, a2)))
    c1 = F2.sub(F2.mul_xi(F2.sqr(a2)), F2.mul(a0, a1))
    c2 = F2.sub(F2.sqr(a1), F2.mul(a0, a2))
    t = F2.add(F2.mul(a0, c0),
               F2.mul_xi(F2.add(F2.mul(a1, c2), F2.mul(a2, c1))))
    ti = F2.inv(t)
    return (F2.mul(c0, ti), F2.mul(c1, ti), F2.mul(c2, ti))


def inv(f):
    """Tower inversion: f = a(v) + w*b(v), v = w^2."""
    a = (f[..., 0, :, :], f[..., 2, :, :], f[..., 4, :, :])
    b = (f[..., 1, :, :], f[..., 3, :, :], f[..., 5, :, :])
    norm = _fp6_sub(_fp6_mul(a, a), _fp6_mul_v(_fp6_mul(b, b)))
    ninv = _fp6_inv(norm)
    ra = _fp6_mul(a, ninv)
    rb = _fp6_mul(b, ninv)
    rb = tuple(F2.neg(x) for x in rb)
    return jnp.stack([ra[0], rb[0], ra[1], rb[1], ra[2], rb[2]], axis=-3)


def pow_const(f, e: int):
    """f^e for a STATIC exponent via scan (LSB-first double-and-multiply)."""
    nbits = e.bit_length()
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.uint32)
    acc0 = one(f.shape[:-3])

    def step(state, bit):
        acc, base = state
        acc2 = mul(acc, base)
        acc = jnp.where(bit == 1, acc2, acc)
        base = sqr(base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (acc0, f), bits)
    return acc


@functools.partial(jax.jit, static_argnames="n_bits")
def pow_var(f, k_limbs, n_bits: int = 256):
    """f^k for a VARIABLE mod-n exponent given as plain limbs (..., 16).

    n_bits-step square-and-multiply-always scan (LSB-first; n_bits < 256
    truncates for exponents known short, e.g. 62-bit RLC weights — a 4x
    smaller graph, which matters for the shard_map compile); batches over
    leading dims of both f (..., 6, 2, 16) and k. The range-proof layer
    uses this to turn e(t·B, B2) into gtB^t with one precomputed pairing
    (reference computes the full pairing per element,
    lib/range/range_proof.go:398-404).
    """
    from .params import LIMB_BITS
    bits = (k_limbs[..., :, None]
            >> jnp.arange(LIMB_BITS, dtype=jnp.uint32)) & 1
    bits = bits.reshape(bits.shape[:-2] + (256,))
    if n_bits < 256:
        # lax.slice_in_dim: jnp basic indexing rejects the (static) stop
        # under shard_map tracing with a spurious "must be static" error
        bits = jax.lax.slice_in_dim(bits, 0, n_bits, axis=-1)
    bits_t = jnp.moveaxis(bits, -1, 0)

    batch = jnp.broadcast_shapes(f.shape[:-3], k_limbs.shape[:-1])
    acc0 = one(batch)
    base0 = jnp.broadcast_to(f, batch + f.shape[-3:])

    def step(state, bit):
        acc, base = state
        acc2 = mul(acc, base)
        acc = jnp.where(bit[..., None, None, None] == 1, acc2, acc)
        base = sqr(base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (acc0, base0), bits_t)
    return acc


__all__ = ["from_ref", "to_ref", "one", "mul", "sqr", "conj6", "eq", "inv",
           "pow_const", "pow_var"]
