"""Batched Fp2 = Fp[i]/(i^2+1) arithmetic on limb tensors.

Element representation: uint32 (..., 2, 16) = (a0, a1) Montgomery limbs.
Mirrors the tower choices in params.py / refimpl.py (our own suite — only
internal consistency is required, reference fixes bn256 via kyber at
lib/suite.go:10-20).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field as F
from . import params
from .field import FP
from .params import NUM_LIMBS


def from_ref(a) -> np.ndarray:
    """Oracle (a0, a1) ints -> (2, 16) Montgomery limbs."""
    mont = lambda v: params.to_limbs(v * params.R % params.P)
    return np.asarray([mont(a[0] % params.P), mont(a[1] % params.P)],
                      dtype=np.uint32)


def to_ref(x):
    a = np.asarray(F.to_int(np.asarray(F.from_mont(jnp.asarray(x, dtype=jnp.uint32), FP))))
    if a.ndim == 1:
        return (int(a[0]), int(a[1]))
    return a  # (..., 2) object array


ZERO = jnp.zeros((2, NUM_LIMBS), dtype=jnp.uint32)


def one():
    return jnp.stack([FP.one_mont, FP.zero])


def add(a, b):
    return F.add(a, b, FP)


def sub(a, b):
    return F.sub(a, b, FP)


def neg(a):
    return F.neg(a, FP)


def mul(a, b):
    """Karatsuba: 3 Fp mults."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = F.mont_mul(a0, b0, FP)
    t1 = F.mont_mul(a1, b1, FP)
    t2 = F.mont_mul(F.add(a0, a1, FP), F.add(b0, b1, FP), FP)
    r0 = F.sub(t0, t1, FP)
    r1 = F.sub(F.sub(t2, t0, FP), t1, FP)
    return jnp.stack([r0, r1], axis=-2)


def sqr(a):
    """(a0+a1 i)^2 = (a0+a1)(a0-a1) + 2 a0 a1 i — 2 Fp mults."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    r0 = F.mont_mul(F.add(a0, a1, FP), F.sub(a0, a1, FP), FP)
    t = F.mont_mul(a0, a1, FP)
    r1 = F.add(t, t, FP)
    return jnp.stack([r0, r1], axis=-2)


def mul_fp(a, s):
    """Multiply by an Fp element s (..., 16)."""
    return jnp.stack([F.mont_mul(a[..., 0, :], s, FP),
                      F.mont_mul(a[..., 1, :], s, FP)], axis=-2)


def mul_small(a, k: int):
    """Multiply by a small int constant via repeated adds."""
    out = a
    for _ in range(k - 1):
        out = add(out, a)
    return out


def conj(a):
    return jnp.stack([a[..., 0, :], F.neg(a[..., 1, :], FP)], axis=-2)


def inv(a):
    """1/(a0+a1 i) = (a0 - a1 i)/(a0^2 + a1^2)."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    norm = F.add(F.mont_mul(a0, a0, FP), F.mont_mul(a1, a1, FP), FP)
    ninv = F.inv(norm, FP)
    return jnp.stack([F.mont_mul(a0, ninv, FP),
                      F.neg(F.mont_mul(a1, ninv, FP), FP)], axis=-2)


def eq(a, b):
    return jnp.all(a == b, axis=(-1, -2))


def is_zero(a):
    return jnp.all(a == 0, axis=(-1, -2))


# Device constant: XI (the sextic non-residue defining Fp12 and the twist)
XI_DEV = jnp.asarray(from_ref(params.XI), dtype=jnp.uint32)


def mul_xi(a):
    """Multiply by XI = (xi0 + i). With XI = (x, 1):
    (a0+a1 i)(x+i) = (x a0 - a1) + (a0 + x a1) i."""
    x0, x1 = params.XI
    assert x1 == 1
    a0, a1 = a[..., 0, :], a[..., 1, :]
    if x0 == 1:
        r0 = F.sub(a0, a1, FP)
        r1 = F.add(a0, a1, FP)
    else:
        xs = jnp.asarray(params.to_limbs(x0 * params.R % params.P),
                         dtype=jnp.uint32)
        r0 = F.sub(F.mont_mul(a0, xs, FP), a1, FP)
        r1 = F.add(a0, F.mont_mul(a1, xs, FP), FP)
    return jnp.stack([r0, r1], axis=-2)


__all__ = ["from_ref", "to_ref", "ZERO", "one", "add", "sub", "neg", "mul",
           "sqr", "mul_fp", "mul_small", "conj", "inv", "eq", "is_zero",
           "XI_DEV", "mul_xi"]
