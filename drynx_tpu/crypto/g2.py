"""Batched G2 (sextic twist E'(Fp2): y^2 = x^3 + 3/XI) group ops.

Point representation: uint32 (..., 3, 2, 16) = (X, Y, Z) Jacobian coords,
each an Fp2 element in Montgomery form; infinity has Z == 0.

Used by the range-proof layer: Boneh–Boyen signatures A[k] = (x+k)^-1·B2 live
in G2 and are randomized per proof (V = v·A[digit], reference
lib/range/range_proof.go:392-394).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fp2 as F2
from . import field as F
from . import params, refimpl
from .params import NUM_LIMBS


def from_ref(pt) -> np.ndarray:
    """Oracle twist point ((x0,x1),(y0,y1)) or None -> (3, 2, 16) limbs."""
    if pt is None:
        x, y, z = (1, 0), (1, 0), (0, 0)
    else:
        x, y = pt
        z = (1, 0)
    return np.stack([F2.from_ref(x), F2.from_ref(y), F2.from_ref(z)])


def to_ref(pt):
    x, y, inf = normalize(jnp.asarray(pt, dtype=jnp.uint32))
    if np.asarray(inf).ndim == 0:
        if bool(inf):
            return None
        return (F2.to_ref(x), F2.to_ref(y))
    raise NotImplementedError("batched to_ref: map over leading axis")


def infinity(batch_shape=()):
    base = jnp.asarray(from_ref(None), dtype=jnp.uint32)
    return jnp.broadcast_to(base, batch_shape + (3, 2, NUM_LIMBS))


G2_GEN = jnp.asarray(from_ref(refimpl.G2), dtype=jnp.uint32)


def is_infinity(p):
    return F2.is_zero(p[..., 2, :, :])


@jax.jit
def double(p):
    X, Y, Z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    A = F2.sqr(X)
    B = F2.sqr(Y)
    C = F2.sqr(B)
    t = F2.sub(F2.sqr(F2.add(X, B)), F2.add(A, C))
    D = F2.add(t, t)
    E = F2.add(F2.add(A, A), A)
    Fv = F2.sqr(E)
    X3 = F2.sub(Fv, F2.add(D, D))
    C8 = F2.mul_small(C, 8)
    Y3 = F2.sub(F2.mul(E, F2.sub(D, X3)), C8)
    YZ = F2.mul(Y, Z)
    Z3 = F2.add(YZ, YZ)
    return jnp.stack([X3, Y3, Z3], axis=-3)


@jax.jit
def add(p, q):
    X1, Y1, Z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    X2, Y2, Z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]

    Z1Z1 = F2.sqr(Z1)
    Z2Z2 = F2.sqr(Z2)
    U1 = F2.mul(X1, Z2Z2)
    U2 = F2.mul(X2, Z1Z1)
    S1 = F2.mul(Y1, F2.mul(Z2, Z2Z2))
    S2 = F2.mul(Y2, F2.mul(Z1, Z1Z1))
    H = F2.sub(U2, U1)
    HH = F2.add(H, H)
    I = F2.sqr(HH)
    J = F2.mul(H, I)
    r = F2.sub(S2, S1)
    r = F2.add(r, r)
    V = F2.mul(U1, I)
    X3 = F2.sub(F2.sub(F2.sqr(r), J), F2.add(V, V))
    SJ = F2.mul(S1, J)
    Y3 = F2.sub(F2.mul(r, F2.sub(V, X3)), F2.add(SJ, SJ))
    ZZ = F2.sub(F2.sub(F2.sqr(F2.add(Z1, Z2)), Z1Z1), Z2Z2)
    Z3 = F2.mul(ZZ, H)
    res_add = jnp.stack([X3, Y3, Z3], axis=-3)

    res_dbl = double(p)

    p_inf = is_infinity(p)
    q_inf = is_infinity(q)
    h_zero = F2.is_zero(H)
    r_zero = F2.is_zero(r)

    sel = lambda c, t, f: jnp.where(c[..., None, None, None], t, f)
    out = sel(h_zero & r_zero & ~p_inf & ~q_inf, res_dbl, res_add)
    out = sel(h_zero & ~r_zero & ~p_inf & ~q_inf,
              infinity(out.shape[:-3]), out)
    out = sel(q_inf, p, out)
    out = sel(p_inf, q, out)
    return out


@jax.jit
def neg(p):
    return p.at[..., 1, :, :].set(F2.neg(p[..., 1, :, :]))


def scalar_mul(p, k_limbs):
    """k * Q (k: plain limbs (..., 16)). On TPU: the windowed Pallas ladder
    kernel; elsewhere the 256-step double-and-add-always scan."""
    from . import pallas_ops as po

    if po.available():
        from . import pallas_pairing as ppair

        batch = jnp.broadcast_shapes(p.shape[:-3], k_limbs.shape[:-1])
        pf = jnp.broadcast_to(p, batch + (3, 2, NUM_LIMBS)).reshape(
            -1, 3, 2, NUM_LIMBS)
        kf = jnp.broadcast_to(k_limbs, batch + (NUM_LIMBS,)).reshape(
            -1, NUM_LIMBS)
        return ppair.g2_scalar_mul_flat(pf, kf).reshape(
            batch + (3, 2, NUM_LIMBS))
    return _scalar_mul_jnp(p, k_limbs)


@jax.jit
def _scalar_mul_jnp(p, k_limbs):
    """256-step double-and-add-always scan (portable fallback)."""
    bits = (k_limbs[..., :, None] >> jnp.arange(params.LIMB_BITS, dtype=jnp.uint32)) & 1
    bits = bits.reshape(bits.shape[:-2] + (256,))
    bits_t = jnp.moveaxis(bits, -1, 0)

    batch = jnp.broadcast_shapes(p.shape[:-3], k_limbs.shape[:-1])
    acc0 = infinity(batch)
    base0 = jnp.broadcast_to(p, batch + (3, 2, NUM_LIMBS))

    def step(state, bit):
        acc, base = state
        acc2 = add(acc, base)
        acc = jnp.where(bit[..., None, None, None] == 1, acc2, acc)
        base = double(base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(step, (acc0, base0), bits_t)
    return acc


@jax.jit
def normalize(p):
    """Jacobian -> affine (x, y Fp2 Montgomery limbs, is_inf)."""
    from . import pallas_ops as po

    X, Y, Z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    inf = is_infinity(p)
    Zsafe = jnp.where(inf[..., None, None], F2.one(), Z)
    if po.available():
        from . import pallas_pairing as ppair

        Zi = ppair.f2_inv_flat(
            Zsafe.reshape(-1, 2, NUM_LIMBS)).reshape(Zsafe.shape)
    else:
        Zi = F2.inv(Zsafe)
    Zi2 = F2.sqr(Zi)
    x = F2.mul(X, Zi2)
    y = F2.mul(Y, F2.mul(Zi, Zi2))
    return x, y, inf


@jax.jit
def eq(p, q):
    X1, Y1, Z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    X2, Y2, Z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]
    Z1Z1, Z2Z2 = F2.sqr(Z1), F2.sqr(Z2)
    same_x = F2.eq(F2.mul(X1, Z2Z2), F2.mul(X2, Z1Z1))
    same_y = F2.eq(F2.mul(Y1, F2.mul(Z2, Z2Z2)), F2.mul(Y2, F2.mul(Z1, Z1Z1)))
    p_inf, q_inf = is_infinity(p), is_infinity(q)
    return (p_inf & q_inf) | (~p_inf & ~q_inf & same_x & same_y)


__all__ = ["from_ref", "to_ref", "infinity", "G2_GEN", "is_infinity",
           "double", "add", "neg", "scalar_mul", "normalize", "eq"]
