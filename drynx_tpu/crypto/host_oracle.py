"""Host-oracle fallbacks for the pairing family where Pallas is absent.

Why this exists: on CPU the jnp pairing graphs (65-step Miller scan, the
final-exp pow chains) cost HOURS of XLA compile per process — the round-3
compile bill that timed out benches, left the RLC soundness test unvalidated
for a whole round, and blocked the scaling-grid capture. The pure-Python
oracle (crypto/refimpl.py — the implementation every kernel is validated
against) runs the same math at ~0.1 s per pairing with ZERO compile, which
is faster than the compiled path for every one-shot process we run on CPU
(tests, simulation grid rows).

Exactness: full reduced pairings are implementation-independent. Miller
values differ between implementations by Fp-subfield line factors, which the
final exponentiation kills — and every consumer of bare Miller values here
multiplies them only under a later final_exp (the RLC verifier's shared
final exp), so mixing is safe. GT pows/muls are plain field math.

The TPU path (crypto/pallas_pairing.py) is untouched; kill-switch:
DRYNX_CPU_ORACLE_PAIR=0 restores the jnp fallbacks (compile-heavy).

Layouts mirror crypto/batching.py: Fp limbs are (…, 16) uint32 Montgomery;
G2/Fp2 coords (…, 2, 16); GT (…, 6, 2, 16); exponents (…, 16) PLAIN limbs.
"""
from __future__ import annotations

import os

import numpy as np

from . import params, refimpl

P = params.P
_RINV = pow(params.R, P - 2, P)

ENABLED = os.environ.get("DRYNX_CPU_ORACLE_PAIR", "1") == "1"


# ---------------------------------------------------------------------------
# Limb <-> int conversion (host)
# ---------------------------------------------------------------------------

def _limbs_to_int(limbs) -> int:
    v = 0
    for i, w in enumerate(np.asarray(limbs, dtype=np.uint64)):
        v |= int(w) << (params.LIMB_BITS * i)
    return v


def _mont_to_int(limbs) -> int:
    return _limbs_to_int(limbs) * _RINV % P


def _int_to_mont(v: int) -> np.ndarray:
    return np.asarray(params.to_limbs(v * params.R % P), dtype=np.uint32)


def _fp2_to_int(x):          # (2, 16) Montgomery -> (int, int)
    return (_mont_to_int(x[0]), _mont_to_int(x[1]))


def _fp12_to_ref(f):         # (6, 2, 16) Montgomery -> ref tuple
    return tuple(_fp2_to_int(f[k]) for k in range(6))


def _fp12_from_ref(f) -> np.ndarray:   # ref tuple -> (6, 2, 16) Montgomery
    out = np.empty((6, 2, params.NUM_LIMBS), dtype=np.uint32)
    for k, (c0, c1) in enumerate(f):
        out[k, 0] = _int_to_mont(c0)
        out[k, 1] = _int_to_mont(c1)
    return out


# ---------------------------------------------------------------------------
# Fast final exponentiation (easy part + Olivos/DSD hard part on ints).
# refimpl.final_exp is the naive f^((p^12-1)/n) (~4500 squarings, 0.6 s);
# this is the same chain pairing.py/_hard_part runs on device (~45 ms).
# Parity vs the naive one is asserted in tests/test_pairing.py.
# ---------------------------------------------------------------------------

_FROBC: dict = {}


def _frob_consts(e: int):
    if e not in _FROBC:
        g = refimpl.fp2_pow(params.XI, (P ** e - 1) // 6)
        consts, cur = [], (1, 0)
        for _k in range(6):
            consts.append(cur)
            cur = refimpl.fp2_mul(cur, g)
        _FROBC[e] = consts
    return _FROBC[e]


def _fp12_frob(f, e: int):
    """f^(p^e) on the flat tower (e in {1, 2, 3}); odd e conjugates the
    Fp2 coefficients (p = 3 mod 4) — same math as pairing._frob1/2/3."""
    consts = _frob_consts(e)
    conj = e % 2 == 1
    out = []
    for k in range(6):
        c = f[k]
        if conj:
            c = (c[0], (-c[1]) % P)
        out.append(refimpl.fp2_mul(c, consts[k]))
    return tuple(out)


def final_exp_fast(f):
    """refimpl-exact final exponentiation via easy part + DSD hard part."""
    mul, conj = refimpl.fp12_mul, refimpl.fp12_conj6
    f1 = mul(conj(f), refimpl.fp12_inv(f))
    f2 = mul(_fp12_frob(f1, 2), f1)

    u = params.U
    fx = refimpl.fp12_pow(f2, u)
    fx2 = refimpl.fp12_pow(fx, u)
    fx3 = refimpl.fp12_pow(fx2, u)

    y0 = mul(mul(_fp12_frob(f2, 1), _fp12_frob(f2, 2)), _fp12_frob(f2, 3))
    y1 = conj(f2)
    y2 = _fp12_frob(fx2, 2)
    y3 = conj(_fp12_frob(fx, 1))
    y4 = conj(mul(fx, _fp12_frob(fx2, 1)))
    y5 = conj(fx2)
    y6 = conj(mul(fx3, _fp12_frob(fx3, 1)))

    sqr = refimpl.fp12_sq
    t0 = mul(mul(sqr(y6), y4), y5)
    t1 = mul(mul(y3, y5), t0)
    t0 = mul(t0, y2)
    t1 = mul(sqr(t1), t0)
    t1 = sqr(t1)
    t0b = mul(t1, y1)
    t1 = mul(t1, y0)
    t0b = sqr(t0b)
    return mul(t0b, t1)


# ---------------------------------------------------------------------------
# Batched host ops (loop over N; each element is oracle math)
# ---------------------------------------------------------------------------

def _g1_aff(px, py, i):
    x, y = _mont_to_int(px[i]), _mont_to_int(py[i])
    return None if x == 0 and y == 0 else (x, y)


def _g2_aff(qx, qy, i):
    x, y = _fp2_to_int(qx[i]), _fp2_to_int(qy[i])
    return None if x == (0, 0) and y == (0, 0) else (x, y)


def pair_host(px, py, qx, qy) -> np.ndarray:
    """Full reduced pairing: affine Montgomery inputs -> (N, 6, 2, 16)."""
    from . import native_pairing as npair

    px, py = np.asarray(px), np.asarray(py)
    qx, qy = np.asarray(qx), np.asarray(qy)
    if npair.available():  # bit-identical C++ (tests/test_native_pairing.py)
        return npair.pair_batch(px, py, qx, qy)
    N = px.shape[0]
    out = np.empty((N, 6, 2, params.NUM_LIMBS), dtype=np.uint32)
    for i in range(N):
        p, q = _g1_aff(px, py, i), _g2_aff(qx, qy, i)
        if p is None or q is None:
            out[i] = _fp12_from_ref(refimpl.FP12_ONE)
        else:
            out[i] = _fp12_from_ref(
                final_exp_fast(refimpl.ate_miller_loop(p, q)))
    return out


def miller_host(px, py, qx, qy) -> np.ndarray:
    """Unreduced ate Miller values (consumed only under a later final exp)."""
    from . import native_pairing as npair

    px, py = np.asarray(px), np.asarray(py)
    qx, qy = np.asarray(qx), np.asarray(qy)
    if npair.available():
        return npair.miller_batch(px, py, qx, qy)
    N = px.shape[0]
    out = np.empty((N, 6, 2, params.NUM_LIMBS), dtype=np.uint32)
    for i in range(N):
        p, q = _g1_aff(px, py, i), _g2_aff(qx, qy, i)
        if p is None or q is None:
            out[i] = _fp12_from_ref(refimpl.FP12_ONE)
        else:
            out[i] = _fp12_from_ref(refimpl.ate_miller_loop(p, q))
    return out


def final_exp_host(f) -> np.ndarray:
    from . import native_pairing as npair

    f = np.asarray(f)
    if npair.available():
        return npair.final_exp_batch(f)
    out = np.empty_like(f)
    for i in range(f.shape[0]):
        out[i] = _fp12_from_ref(final_exp_fast(_fp12_to_ref(f[i])))
    return out


def gt_pow_host(f, k) -> np.ndarray:
    """f^k elementwise: f (N, 6, 2, 16) Montgomery, k (N, 16) plain limbs."""
    from . import native_pairing as npair

    f, k = np.asarray(f), np.asarray(k)
    if npair.available():
        return npair.gt_pow_batch(f, k)
    out = np.empty_like(f)
    for i in range(f.shape[0]):
        out[i] = _fp12_from_ref(refimpl.fp12_pow(
            _fp12_to_ref(f[i]), _limbs_to_int(k[i])))
    return out


def gt_mul_host(a, b) -> np.ndarray:
    """Elementwise product: both (N, 6, 2, 16) Montgomery."""
    from . import native_pairing as npair

    a, b = np.asarray(a), np.asarray(b)
    if npair.available():
        return npair.gt_mul_batch(a, b)
    out = np.empty_like(a)
    for i in range(a.shape[0]):
        out[i] = _fp12_from_ref(refimpl.fp12_mul(_fp12_to_ref(a[i]),
                                                 _fp12_to_ref(b[i])))
    return out


# ---------------------------------------------------------------------------
# G1 family (NATIVE-ONLY host path: these are gated on the C++ library —
# the pure-Python fallback would lose to the XLA bucketed kernels, so the
# dispatch gate in batching._build only detours when npair.available()).
# ---------------------------------------------------------------------------

def g1_scalar_mul_host(p, k) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g1_scalar_mul_batch(p, k, 256)


def g1_scalar_mul64_host(p, k) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g1_scalar_mul_batch(p, k, 64)


def g1_add_host(a, b) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g1_add_batch(a, b)


def g1_neg_host(a) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g1_neg_batch(a)


def g1_eq_host(a, b) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g1_eq_batch(a, b)


def g1_normalize_host(p):
    from . import native_pairing as npair

    return npair.g1_normalize_batch(p)


def g2_scalar_mul_host(p, k) -> np.ndarray:
    from . import native_pairing as npair

    return npair.g2_scalar_mul_batch(p, k, 256)


def g2_normalize_host(p):
    from . import native_pairing as npair

    return npair.g2_normalize_batch(p)


def fixed_base_mul_host(table, k) -> np.ndarray:
    """k*Base where Base is recovered from the window table's [0][1] entry
    (table[w][d] = d*16^w*Base — elgamal.FixedBase layout)."""
    from . import native_pairing as npair

    base = np.asarray(table)[0, 1]                      # (3, 16)
    k = np.asarray(k)
    p = np.broadcast_to(base, (k.shape[0],) + base.shape)
    return npair.g1_scalar_mul_batch(np.ascontiguousarray(p), k, 256)


__all__ = ["ENABLED", "pair_host", "miller_host", "final_exp_host",
           "gt_pow_host", "gt_mul_host", "final_exp_fast",
           "g1_scalar_mul_host", "g1_scalar_mul64_host", "g1_add_host",
           "g1_neg_host", "g1_eq_host", "g1_normalize_host",
           "g2_scalar_mul_host", "g2_normalize_host",
           "fixed_base_mul_host"]
