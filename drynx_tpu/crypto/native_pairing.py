"""ctypes binding to the native C++ pairing backend (native/pairing.cpp).

Fills the role the reference's native Go crypto plays on CPU (kyber bn256,
lib/suite.go:10-20): the same optimal-ate math as crypto/refimpl.py — the
C++ mirrors refimpl operation for operation, with all constants generated
from the Python parameters (scripts/gen_native_constants.py) — at native
Montgomery-limb speed. crypto/host_oracle.py dispatches here when the
library is available; the pure-Python oracle remains the fallback and the
authority every backend (this one included) is parity-tested against
(tests/test_native_pairing.py asserts BIT-IDENTICAL outputs, Miller values
included).

Build: on demand with g++ (same pattern as service/store.py / proofdb).
Kill-switch: DRYNX_NATIVE_PAIR=0 disables loading entirely.

Layouts match crypto/batching.py: Fp = (…, 16) uint32 Montgomery limbs
(16 bits per word), G2/Fp2 coords (…, 2, 16), GT (…, 6, 2, 16); exponents
are (…, 16) PLAIN limbs. Infinity points are all-zero coordinates.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..resilience.policy import named_lock

ENABLED = os.environ.get("DRYNX_NATIVE_PAIR", "1") == "1"

_ROOT = os.path.join(os.path.dirname(__file__), "..", "..")
_SRC = os.path.join(_ROOT, "native", "pairing.cpp")
_HDR = os.path.join(_ROOT, "native", "pairing_constants.h")
_LIB_DIR = os.path.join(_ROOT, "native", "build")
_LIB_PATH = os.path.join(_LIB_DIR, "libdxpairing.so")
_BUILD_LOCK = named_lock("pairing_build_lock")
_LIB = None
_LIB_FAILED = False

_U32P = ctypes.POINTER(ctypes.c_uint32)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _load():
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED or not ENABLED:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            from ..utils.native_build import build_native_lib

            build_native_lib([_SRC, _HDR], _LIB_PATH)
            lib = ctypes.CDLL(_LIB_PATH)
            for name, args in [
                ("dx_miller_batch", [_U32P] * 5 + [ctypes.c_uint64]),
                ("dx_pair_batch", [_U32P] * 5 + [ctypes.c_uint64]),
                ("dx_final_exp_batch", [_U32P, _U32P, ctypes.c_uint64]),
                ("dx_gt_pow_batch", [_U32P] * 3 + [ctypes.c_uint64]),
                ("dx_gt_cyc_pow_batch", [_U32P] * 3 + [ctypes.c_uint64]),
                ("dx_gt_mul_batch", [_U32P] * 3 + [ctypes.c_uint64]),
                ("dx_gt_frob_batch",
                 [_U32P, ctypes.c_int32, _U32P, ctypes.c_uint64]),
                ("dx_gt_order_check_batch",
                 [_U32P, _U32P, _U8P, ctypes.c_uint64]),
                ("dx_g1_scalar_mul_batch",
                 [_U32P, _U32P, ctypes.c_int32, _U32P, ctypes.c_uint64]),
                ("dx_g1_add_batch", [_U32P] * 3 + [ctypes.c_uint64]),
                ("dx_g1_neg_batch", [_U32P] * 2 + [ctypes.c_uint64]),
                ("dx_g1_eq_batch", [_U32P, _U32P, _U8P, ctypes.c_uint64]),
                ("dx_g1_normalize_batch",
                 [_U32P, _U32P, _U32P, _U8P, ctypes.c_uint64]),
                ("dx_g2_scalar_mul_batch",
                 [_U32P, _U32P, ctypes.c_int32, _U32P, ctypes.c_uint64]),
                ("dx_g2_normalize_batch",
                 [_U32P, _U32P, _U32P, _U8P, ctypes.c_uint64]),
            ]:
                fn = getattr(lib, name)
                fn.restype = None
                fn.argtypes = args
            _LIB = lib
        except Exception as e:  # no toolchain / build error: Python oracle
            # LOUD fallback: a silent flip to the ~80 ms/op Python path
            # would also skip the whole parity suite (skipif-unavailable)
            import warnings

            detail = ""
            if isinstance(e, subprocess.CalledProcessError):
                detail = (e.stderr or "")[-500:]
            warnings.warn(
                f"native pairing backend unavailable ({e!r}) {detail} — "
                f"falling back to the pure-Python oracle (30-80x slower); "
                f"tests/test_native_pairing.py will SKIP")
            _LIB_FAILED = True
    return _LIB


def available() -> bool:
    return _load() is not None


def _c32(a: np.ndarray):
    return a.ctypes.data_as(_U32P)


def _prep(a, shape_tail) -> np.ndarray:
    a = np.ascontiguousarray(np.asarray(a), dtype=np.uint32)
    if a.shape[-len(shape_tail):] != shape_tail:
        raise ValueError(f"bad tail shape {a.shape} (want *{shape_tail})")
    return a.reshape((-1,) + shape_tail)


def miller_batch(px, py, qx, qy) -> np.ndarray:
    lib = _load()
    px, py = _prep(px, (16,)), _prep(py, (16,))
    qx, qy = _prep(qx, (2, 16)), _prep(qy, (2, 16))
    n = px.shape[0]
    if not (py.shape[0] == n and qx.shape[0] == n and qy.shape[0] == n):
        raise ValueError((px.shape, py.shape, qx.shape, qy.shape))
    out = np.empty((n, 6, 2, 16), dtype=np.uint32)
    lib.dx_miller_batch(_c32(px), _c32(py), _c32(qx), _c32(qy), _c32(out), n)
    return out


def pair_batch(px, py, qx, qy) -> np.ndarray:
    lib = _load()
    px, py = _prep(px, (16,)), _prep(py, (16,))
    qx, qy = _prep(qx, (2, 16)), _prep(qy, (2, 16))
    n = px.shape[0]
    if not (py.shape[0] == n and qx.shape[0] == n and qy.shape[0] == n):
        raise ValueError((px.shape, py.shape, qx.shape, qy.shape))
    out = np.empty((n, 6, 2, 16), dtype=np.uint32)
    lib.dx_pair_batch(_c32(px), _c32(py), _c32(qx), _c32(qy), _c32(out), n)
    return out


def final_exp_batch(f) -> np.ndarray:
    lib = _load()
    f = _prep(f, (6, 2, 16))
    out = np.empty_like(f)
    lib.dx_final_exp_batch(_c32(f), _c32(out), f.shape[0])
    return out


def gt_pow_batch(f, k) -> np.ndarray:
    lib = _load()
    f, k = _prep(f, (6, 2, 16)), _prep(k, (16,))
    if f.shape[0] != k.shape[0]:
        raise ValueError((f.shape, k.shape))
    out = np.empty_like(f)
    lib.dx_gt_pow_batch(_c32(f), _c32(k), _c32(out), f.shape[0])
    return out


def gt_cyc_pow_batch(f, k) -> np.ndarray:
    """Cyclotomic-squaring pow — f MUST be in GΦ12 (callers gate)."""
    lib = _load()
    f, k = _prep(f, (6, 2, 16)), _prep(k, (16,))
    if f.shape[0] != k.shape[0]:
        raise ValueError((f.shape, k.shape))
    out = np.empty_like(f)
    lib.dx_gt_cyc_pow_batch(_c32(f), _c32(k), _c32(out), f.shape[0])
    return out


def gt_mul_batch(a, b) -> np.ndarray:
    lib = _load()
    a, b = _prep(a, (6, 2, 16)), _prep(b, (6, 2, 16))
    if a.shape[0] != b.shape[0]:
        raise ValueError((a.shape, b.shape))
    out = np.empty_like(a)
    lib.dx_gt_mul_batch(_c32(a), _c32(b), _c32(out), a.shape[0])
    return out


def gt_frob_batch(f, e: int) -> np.ndarray:
    lib = _load()
    f = _prep(f, (6, 2, 16))
    out = np.empty_like(f)
    lib.dx_gt_frob_batch(_c32(f), ctypes.c_int32(e), _c32(out), f.shape[0])
    return out


def g1_scalar_mul_batch(p, k, nbits: int = 256) -> np.ndarray:
    """k*P batched: p (…, 3, 16) Jacobian Montgomery, k (…, 16) plain
    limbs (low `nbits` used); output canonical (Z=1 / Z=0-infinity)."""
    lib = _load()
    p, k = _prep(p, (3, 16)), _prep(k, (16,))
    if p.shape[0] != k.shape[0]:
        raise ValueError((p.shape, k.shape))
    out = np.empty_like(p)
    lib.dx_g1_scalar_mul_batch(_c32(p), _c32(k), ctypes.c_int32(nbits),
                               _c32(out), p.shape[0])
    return out


def g1_add_batch(a, b) -> np.ndarray:
    lib = _load()
    a, b = _prep(a, (3, 16)), _prep(b, (3, 16))
    if a.shape[0] != b.shape[0]:
        raise ValueError((a.shape, b.shape))
    out = np.empty_like(a)
    lib.dx_g1_add_batch(_c32(a), _c32(b), _c32(out), a.shape[0])
    return out


def g1_neg_batch(a) -> np.ndarray:
    lib = _load()
    a = _prep(a, (3, 16))
    out = np.empty_like(a)
    lib.dx_g1_neg_batch(_c32(a), _c32(out), a.shape[0])
    return out


def g1_eq_batch(a, b) -> np.ndarray:
    lib = _load()
    a, b = _prep(a, (3, 16)), _prep(b, (3, 16))
    if a.shape[0] != b.shape[0]:
        raise ValueError((a.shape, b.shape))
    ok = np.empty((a.shape[0],), dtype=np.uint8)
    lib.dx_g1_eq_batch(_c32(a), _c32(b), ok.ctypes.data_as(_U8P), a.shape[0])
    return ok.astype(bool)


def g1_normalize_batch(p):
    """(…, 3, 16) -> (x (…, 16), y (…, 16), inf (…,) bool); infinity rows
    get zero coords (the canonical-bytes encoder masks them anyway)."""
    lib = _load()
    p = _prep(p, (3, 16))
    n = p.shape[0]
    x = np.empty((n, 16), dtype=np.uint32)
    y = np.empty((n, 16), dtype=np.uint32)
    inf = np.empty((n,), dtype=np.uint8)
    lib.dx_g1_normalize_batch(_c32(p), _c32(x), _c32(y),
                              inf.ctypes.data_as(_U8P), n)
    return x, y, inf.astype(bool)


def g2_scalar_mul_batch(p, k, nbits: int = 256) -> np.ndarray:
    """k*Q batched: p (…, 3, 2, 16) Jacobian Montgomery twist points,
    k (…, 16) plain limbs; output canonical (Z=1 / Z=0-infinity)."""
    lib = _load()
    p, k = _prep(p, (3, 2, 16)), _prep(k, (16,))
    if p.shape[0] != k.shape[0]:
        raise ValueError((p.shape, k.shape))
    out = np.empty_like(p)
    lib.dx_g2_scalar_mul_batch(_c32(p), _c32(k), ctypes.c_int32(nbits),
                               _c32(out), p.shape[0])
    return out


def g2_normalize_batch(p):
    lib = _load()
    p = _prep(p, (3, 2, 16))
    n = p.shape[0]
    x = np.empty((n, 2, 16), dtype=np.uint32)
    y = np.empty((n, 2, 16), dtype=np.uint32)
    inf = np.empty((n,), dtype=np.uint8)
    lib.dx_g2_normalize_batch(_c32(p), _c32(x), _c32(y),
                              inf.ctypes.data_as(_U8P), n)
    return x, y, inf.astype(bool)


def gt_order_check_batch(f) -> np.ndarray:
    """Order-n gate verdicts: ok[i] = frob1(f_i) == f_i^(p-n)  (⇔ f^n = 1
    within GΦ12 — callers must have gated membership first)."""
    from . import params

    lib = _load()
    f = _prep(f, (6, 2, 16))
    t1 = np.asarray(params.to_limbs(params.P - params.N), dtype=np.uint32)
    ok = np.empty((f.shape[0],), dtype=np.uint8)
    lib.dx_gt_order_check_batch(_c32(f), _c32(t1), ok.ctypes.data_as(_U8P),
                                f.shape[0])
    return ok.astype(bool)


__all__ = ["ENABLED", "available", "miller_batch", "pair_batch",
           "final_exp_batch", "gt_pow_batch", "gt_cyc_pow_batch",
           "gt_mul_batch", "gt_frob_batch", "gt_order_check_batch",
           "g1_scalar_mul_batch", "g1_add_batch", "g1_neg_batch",
           "g1_eq_batch", "g1_normalize_batch",
           "g2_scalar_mul_batch", "g2_normalize_batch"]
