"""Batched reduced Tate pairing e: G1 x G2 -> GT on device.

The kernel behind range-proof creation/verification (reference
lib/range/range_proof.go:396-404 creates a_ij from pairings; :538-546
verifies a_ij = e(c·y, V)·e(−Zphi·B, V)·e(Zv·B, B2) — note both sides are
products of pairings sharing one final exponentiation here).

Design: Miller loop over the STATIC bit pattern of the group order n as a
`lax.scan` with select-gated addition steps (uniform, branch-free); the
accumulator point T stays in Jacobian coordinates over Fp (G1), line values
are sparse Fp12 elements in w-slots {0, 2, 3}; denominators and degenerate
vertical lines are eliminated (any Fp2-subfield factor dies in the final
exponentiation). Final exponentiation: easy part via conj/inv/frobenius^2,
hard part (p^4 - p^2 + 1)/n as a static-exponent scan (to be replaced by the
BN u-chain in a later perf pass).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field as F
from . import fp2 as F2
from . import fp12 as F12
from . import params, refimpl
from .field import FP
from .params import NUM_LIMBS, P, N


# ---------------------------------------------------------------------------
# Sparse line values: (l0 Fp, l2 Fp2, l3 Fp2) = l0 + l2 w^2 + l3 w^3
# ---------------------------------------------------------------------------

def _sparse_mul(f, l0, l2, l3):
    """f * (l0 + l2 w^2 + l3 w^3); l0 is an Fp limb tensor (..., 16)."""
    out = [None] * 6
    acc = [None] * 11

    def accum(k, v):
        acc[k] = v if acc[k] is None else F2.add(acc[k], v)

    for k in range(6):
        fk = f[..., k, :, :]
        accum(k, F2.mul_fp(fk, l0))
        accum(k + 2, F2.mul(fk, l2))
        accum(k + 3, F2.mul(fk, l3))
    for k in range(6):
        out[k] = acc[k]
    for k in range(6, 11):
        if acc[k] is not None:  # slots 9-10 are never produced (l3 max deg 3)
            out[k - 6] = F2.add(out[k - 6], F2.mul_xi(acc[k]))
    return jnp.stack(out, axis=-3)


def _dbl_step(T, xq, yq):
    """Tangent line at Jacobian T evaluated at untwisted Q, then T <- 2T.

    l = (3X^3 - 2Y^2) - 3X^2 Z^2 xq w^2 + 2 Y Z^3 yq w^3   (Fp2-scaled).
    """
    X, Y, Z = T[..., 0, :], T[..., 1, :], T[..., 2, :]
    mm = lambda a, b: F.mont_mul(a, b, FP)
    X2 = mm(X, X)
    Y2 = mm(Y, Y)
    Z2 = mm(Z, Z)
    X3_ = mm(X2, X)
    threeX2 = F.add(F.add(X2, X2, FP), X2, FP)
    l0 = F.sub(F.add(F.add(X3_, X3_, FP), X3_, FP),
               F.add(Y2, Y2, FP), FP)                      # 3X^3 - 2Y^2
    c2 = F.neg(mm(threeX2, Z2), FP)                        # -3X^2 Z^2
    YZ3 = mm(Y, mm(Z, Z2))
    c3 = F.add(YZ3, YZ3, FP)                               # 2 Y Z^3
    l2 = F2.mul_fp(xq, c2)
    l3 = F2.mul_fp(yq, c3)

    from . import curve as C
    return C.double(T), l0, l2, l3


def _add_step(T, P_aff, xq, yq):
    """Line through T and affine P=(xp,yp) evaluated at untwisted Q, plus
    the vertical-degeneracy flag (H == 0 -> line contributes 1).

    H = X - xp Z^2, M = Y - yp Z^3:
    l = (M xp - H Z yp) - M xq w^2 + H Z yq w^3.
    """
    X, Y, Z = T[..., 0, :], T[..., 1, :], T[..., 2, :]
    xp, yp = P_aff
    mm = lambda a, b: F.mont_mul(a, b, FP)
    Z2 = mm(Z, Z)
    H = F.sub(X, mm(xp, Z2), FP)
    M = F.sub(Y, mm(yp, mm(Z, Z2)), FP)
    HZ = mm(H, Z)
    l0 = F.sub(mm(M, xp), mm(HZ, yp), FP)
    l2 = F2.mul_fp(xq, F.neg(M, FP))
    l3 = F2.mul_fp(yq, HZ)
    degenerate = F.is_zero(H)

    from . import curve as C
    # T + P (P affine lifted to Jacobian with Z=1 in Montgomery form)
    P_jac = jnp.stack([xp, yp, jnp.broadcast_to(FP.one_mont, xp.shape)],
                      axis=-2)
    return C.add(T, P_jac), l0, l2, l3, degenerate


_N_BITS = np.asarray([int(b) for b in bin(N)[3:]], dtype=np.uint32)  # MSB-first, skip top bit


def miller_loop(p_aff, q_aff):
    """f_{n,P}(Q). p_aff: (xp, yp) Fp Montgomery limb tensors (..., 16);
    q_aff: (xq, yq) Fp2 Montgomery tensors (..., 2, 16). Batched."""
    xp, yp = p_aff
    xq, yq = q_aff
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    xp = jnp.broadcast_to(xp, batch + (NUM_LIMBS,))
    yp = jnp.broadcast_to(yp, batch + (NUM_LIMBS,))
    xq = jnp.broadcast_to(xq, batch + (2, NUM_LIMBS,))
    yq = jnp.broadcast_to(yq, batch + (2, NUM_LIMBS,))

    T0 = jnp.stack([xp, yp, jnp.broadcast_to(FP.one_mont, xp.shape)], axis=-2)
    f0 = F12.one(batch)
    bits = jnp.asarray(_N_BITS)

    def step(state, bit):
        T, f = state
        f = F12.sqr(f)
        T2, l0, l2, l3 = _dbl_step(T, xq, yq)
        f = _sparse_mul(f, l0, l2, l3)
        T = T2
        # conditional addition step (bit == 1)
        Ta, a0, a2, a3, degen = _add_step(T, (xp, yp), xq, yq)
        fa = _sparse_mul(f, a0, a2, a3)
        fa = jnp.where(degen[..., None, None, None], f, fa)
        f = jnp.where(bit == 1, fa, f)
        T = jnp.where(bit == 1, Ta, T)
        return (T, f), None

    (T, f), _ = jax.lax.scan(step, (T0, f0), bits)
    return f


_EASY_DONE_EXP = (P**4 - P**2 + 1) // N  # hard part of the final exponent


def final_exp(f):
    """f^((p^12-1)/n) = easy part (p^6-1)(p^2+1), then hard part."""
    # f^(p^6-1) = conj(f) * f^-1
    f1 = F12.mul(F12.conj6(f), F12.inv(f))
    # f^(p^2+1) = frob^2(f) * f; frob^2 on our flat tower: c_k -> c_k * g2^k
    f2 = F12.mul(_frob2(f1), f1)
    return F12.pow_const(f2, _EASY_DONE_EXP)


# Frobenius^2 constants: w^(p^2) = w * g2 with g2 = XI^((p^2-1)/6) in Fp2
# (an Fp element actually, since (p^2-1)/6 * 2 ... computed in the oracle).
def _frob2_consts():
    g = refimpl.fp2_pow(params.XI, (P * P - 1) // 6)
    consts = []
    cur = (1, 0)
    for _k in range(6):
        consts.append(F2.from_ref(cur))
        cur = refimpl.fp2_mul(cur, g)
    return jnp.asarray(np.stack(consts))


_FROB2 = _frob2_consts()


def _frob2(f):
    """f^(p^2) on the flat tower: coefficients are Fp2-Frobenius^2-invariant
    (x^(p^2) = x for x in Fp2), so c_k -> c_k * XI^(k(p^2-1)/6)."""
    out = [F2.mul(f[..., k, :, :], _FROB2[k]) for k in range(6)]
    return jnp.stack(out, axis=-3)


def pair(p_aff, q_aff):
    """Reduced Tate pairing, batched. Infinity handling is the caller's
    concern (use select against F12.one())."""
    return final_exp(miller_loop(p_aff, q_aff))


__all__ = ["miller_loop", "final_exp", "pair"]
