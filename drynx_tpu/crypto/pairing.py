"""Batched reduced Tate pairing e: G1 x G2 -> GT on device.

The kernel behind range-proof creation/verification (reference
lib/range/range_proof.go:396-404 creates a_ij from pairings; :538-546
verifies a_ij = e(c·y, V)·e(−Zphi·B, V)·e(Zv·B, B2) — note both sides are
products of pairings sharing one final exponentiation here).

Design: Miller loop over the STATIC bit pattern of the group order n as a
`lax.scan` with select-gated addition steps (uniform, branch-free); the
accumulator point T stays in Jacobian coordinates over Fp (G1), line values
are sparse Fp12 elements in w-slots {0, 2, 3}; denominators and degenerate
vertical lines are eliminated (any Fp2-subfield factor dies in the final
exponentiation). Final exponentiation: easy part via conj/inv/frobenius^2,
hard part (p^4 - p^2 + 1)/n as a static-exponent scan (to be replaced by the
BN u-chain in a later perf pass).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field as F
from . import fp2 as F2
from . import fp12 as F12
from . import params, refimpl
from .field import FP
from .params import NUM_LIMBS, P, N


# ---------------------------------------------------------------------------
# Sparse line values: (l0 Fp, l2 Fp2, l3 Fp2) = l0 + l2 w^2 + l3 w^3
# ---------------------------------------------------------------------------

def _sparse_mul(f, l0, l2, l3):
    """f * (l0 + l2 w^2 + l3 w^3); l0 is an Fp limb tensor (..., 16)."""
    out = [None] * 6
    acc = [None] * 11

    def accum(k, v):
        acc[k] = v if acc[k] is None else F2.add(acc[k], v)

    for k in range(6):
        fk = f[..., k, :, :]
        accum(k, F2.mul_fp(fk, l0))
        accum(k + 2, F2.mul(fk, l2))
        accum(k + 3, F2.mul(fk, l3))
    for k in range(6):
        out[k] = acc[k]
    for k in range(6, 11):
        if acc[k] is not None:  # slots 9-10 are never produced (l3 max deg 3)
            out[k - 6] = F2.add(out[k - 6], F2.mul_xi(acc[k]))
    return jnp.stack(out, axis=-3)


def _dbl_step(T, xq, yq):
    """Tangent line at Jacobian T evaluated at untwisted Q, then T <- 2T.

    l = (3X^3 - 2Y^2) - 3X^2 Z^2 xq w^2 + 2 Y Z^3 yq w^3   (Fp2-scaled).
    """
    X, Y, Z = T[..., 0, :], T[..., 1, :], T[..., 2, :]
    mm = lambda a, b: F.mont_mul(a, b, FP)
    X2 = mm(X, X)
    Y2 = mm(Y, Y)
    Z2 = mm(Z, Z)
    X3_ = mm(X2, X)
    threeX2 = F.add(F.add(X2, X2, FP), X2, FP)
    l0 = F.sub(F.add(F.add(X3_, X3_, FP), X3_, FP),
               F.add(Y2, Y2, FP), FP)                      # 3X^3 - 2Y^2
    c2 = F.neg(mm(threeX2, Z2), FP)                        # -3X^2 Z^2
    YZ3 = mm(Y, mm(Z, Z2))
    c3 = F.add(YZ3, YZ3, FP)                               # 2 Y Z^3
    l2 = F2.mul_fp(xq, c2)
    l3 = F2.mul_fp(yq, c3)

    from . import curve as C
    return C.double(T), l0, l2, l3


def _add_step(T, P_aff, xq, yq):
    """Line through T and affine P=(xp,yp) evaluated at untwisted Q, plus
    the vertical-degeneracy flag (H == 0 -> line contributes 1).

    H = X - xp Z^2, M = Y - yp Z^3:
    l = (M xp - H Z yp) - M xq w^2 + H Z yq w^3.
    """
    X, Y, Z = T[..., 0, :], T[..., 1, :], T[..., 2, :]
    xp, yp = P_aff
    mm = lambda a, b: F.mont_mul(a, b, FP)
    Z2 = mm(Z, Z)
    H = F.sub(X, mm(xp, Z2), FP)
    M = F.sub(Y, mm(yp, mm(Z, Z2)), FP)
    HZ = mm(H, Z)
    l0 = F.sub(mm(M, xp), mm(HZ, yp), FP)
    l2 = F2.mul_fp(xq, F.neg(M, FP))
    l3 = F2.mul_fp(yq, HZ)
    degenerate = F.is_zero(H)

    from . import curve as C
    # T + P (P affine lifted to Jacobian with Z=1 in Montgomery form)
    P_jac = jnp.stack([xp, yp, jnp.broadcast_to(FP.one_mont, xp.shape)],
                      axis=-2)
    return C.add(T, P_jac), l0, l2, l3, degenerate


_N_BITS = np.asarray([int(b) for b in bin(N)[3:]], dtype=np.uint32)  # MSB-first, skip top bit


def miller_loop_tate(p_aff, q_aff):
    """f_{n,P}(Q) (Tate loop; kept as a cross-check — production pairing is
    the optimal ate `miller_loop`, 65 steps instead of 255).
    p_aff: (xp, yp) Fp Montgomery limb tensors (..., 16);
    q_aff: (xq, yq) Fp2 Montgomery tensors (..., 2, 16). Batched."""
    xp, yp = p_aff
    xq, yq = q_aff
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    xp = jnp.broadcast_to(xp, batch + (NUM_LIMBS,))
    yp = jnp.broadcast_to(yp, batch + (NUM_LIMBS,))
    xq = jnp.broadcast_to(xq, batch + (2, NUM_LIMBS,))
    yq = jnp.broadcast_to(yq, batch + (2, NUM_LIMBS,))

    T0 = jnp.stack([xp, yp, jnp.broadcast_to(FP.one_mont, xp.shape)], axis=-2)
    f0 = F12.one(batch)
    bits = jnp.asarray(_N_BITS, dtype=jnp.uint32)

    def step(state, bit):
        T, f = state
        f = F12.sqr(f)
        T2, l0, l2, l3 = _dbl_step(T, xq, yq)
        f = _sparse_mul(f, l0, l2, l3)
        T = T2
        # conditional addition step (bit == 1)
        Ta, a0, a2, a3, degen = _add_step(T, (xp, yp), xq, yq)
        fa = _sparse_mul(f, a0, a2, a3)
        fa = jnp.where(degen[..., None, None, None], f, fa)
        f = jnp.where(bit == 1, fa, f)
        T = jnp.where(bit == 1, Ta, T)
        return (T, f), None

    (T, f), _ = jax.lax.scan(step, (T0, f0), bits)
    return f


# ---------------------------------------------------------------------------
# Optimal ate Miller loop (production): loop over 6u+2 (66 bits -> 65 steps)
# with T on the twist E'(Fp2) and lines evaluated at P in G1, plus the two
# Frobenius correction additions. Line sparsity is w-slots {0, 1, 3}:
#   l(P) = yp*c0 + xp*c1 w + c3 w^3   (ci in Fp2)
# (untwist x ~ w^2, y ~ w^3 puts the slope term on w). Any global Fp2 factor
# of a line dies in the final exponentiation, so lines are denominator-free.
# ---------------------------------------------------------------------------

_ATE_BITS = np.asarray([int(b) for b in bin(6 * params.U + 2)[3:]],
                       dtype=np.uint32)


def _sparse_mul013(f, l0, l1, l3):
    """f * (l0 + l1 w + l3 w^3); l0/l1/l3 are Fp2 tensors (..., 2, 16)."""
    out = [None] * 6
    acc = [None] * 9

    def accum(k, v):
        acc[k] = v if acc[k] is None else F2.add(acc[k], v)

    for k in range(6):
        fk = f[..., k, :, :]
        accum(k, F2.mul(fk, l0))
        accum(k + 1, F2.mul(fk, l1))
        accum(k + 3, F2.mul(fk, l3))
    for k in range(6):
        out[k] = acc[k]
    for k in range(6, 9):
        out[k - 6] = F2.add(out[k - 6], F2.mul_xi(acc[k]))
    return jnp.stack(out, axis=-3)


def _ate_dbl_step(T, xp, yp):
    """Tangent line at Jacobian twist point T evaluated at P, then T <- 2T.

    Scaled by 2YZ^3 (an Fp2 factor, killed by FE):
    l = 2YZ^3 yp - 3X^2 Z^2 xp w + (3X^3 - 2Y^2) w^3.
    (Same polynomials as the Tate _dbl_step with the w-roles mirrored.)
    """
    from . import g2 as G2m

    X, Y, Z = T[..., 0, :, :], T[..., 1, :, :], T[..., 2, :, :]
    X2 = F2.sqr(X)
    Y2 = F2.sqr(Y)
    Z2 = F2.sqr(Z)
    X3 = F2.mul(X2, X)
    threeX2 = F2.add(F2.add(X2, X2), X2)
    l3 = F2.sub(F2.add(F2.add(X3, X3), X3), F2.add(Y2, Y2))
    l1 = F2.mul_fp(F2.neg(F2.mul(threeX2, Z2)), xp)
    YZ3 = F2.mul(Y, F2.mul(Z, Z2))
    l0 = F2.mul_fp(F2.add(YZ3, YZ3), yp)
    return G2m.double(T), l0, l1, l3


def _ate_add_step(T, q_aff, xp, yp):
    """Line through T and affine twist Q evaluated at P, plus T <- T+Q and
    the vertical-degeneracy flag. With H = X - xq Z^2, M = Y - yq Z^3:
    l = HZ yp - M xp w + (M xq - HZ yq) w^3   (scaled by HZ)."""
    from . import g2 as G2m

    X, Y, Z = T[..., 0, :, :], T[..., 1, :, :], T[..., 2, :, :]
    xq, yq = q_aff
    Z2 = F2.sqr(Z)
    H = F2.sub(X, F2.mul(xq, Z2))
    M = F2.sub(Y, F2.mul(yq, F2.mul(Z, Z2)))
    HZ = F2.mul(H, Z)
    l0 = F2.mul_fp(HZ, yp)
    l1 = F2.mul_fp(F2.neg(M), xp)
    l3 = F2.sub(F2.mul(M, xq), F2.mul(HZ, yq))
    degen = F2.is_zero(H)
    one2 = jnp.broadcast_to(F2.one(), xq.shape)
    Q_jac = jnp.stack([xq, yq, one2], axis=-3)
    return G2m.add(T, Q_jac), l0, l1, l3, degen


# G2 Frobenius constants (device copies of the oracle's, refimpl.twist_frob).
_G12_DEV = None
_G13_DEV = None
_G22_DEV = None


def _twist_frob_consts():
    # memoize HOST numpy arrays, NOT jnp: jnp.asarray inside a jit trace
    # returns a tracer, and a tracer cached in a module global escapes its
    # trace — the next caller dies with UnexpectedTracerError (hit when the
    # first pairing of a process runs under a different bucket jit than the
    # second; each call site re-wraps the constant into its own trace)
    global _G12_DEV, _G13_DEV, _G22_DEV
    if _G12_DEV is None:
        _G12_DEV = np.asarray(F2.from_ref(refimpl._G12))
        _G13_DEV = np.asarray(F2.from_ref(refimpl._G13))
        _G22_DEV = np.asarray(F2.from_ref(refimpl._G22))
    return (jnp.asarray(_G12_DEV, dtype=jnp.uint32), jnp.asarray(_G13_DEV, dtype=jnp.uint32),
            jnp.asarray(_G22_DEV, dtype=jnp.uint32))


def miller_loop(p_aff, q_aff):
    """Optimal ate Miller function
    f_{6u+2,Q}(P) * l_{[6u+2]Q,piQ}(P) * l_{[6u+2]Q+piQ,-pi2Q}(P), batched.
    p_aff: (xp, yp) Fp Montgomery limb tensors (..., 16);
    q_aff: (xq, yq) Fp2 Montgomery tensors (..., 2, 16)."""
    xp, yp = p_aff
    xq, yq = q_aff
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    xp = jnp.broadcast_to(xp, batch + (NUM_LIMBS,))
    yp = jnp.broadcast_to(yp, batch + (NUM_LIMBS,))
    xq = jnp.broadcast_to(xq, batch + (2, NUM_LIMBS))
    yq = jnp.broadcast_to(yq, batch + (2, NUM_LIMBS))

    one2 = jnp.broadcast_to(F2.one(), xq.shape)
    T0 = jnp.stack([xq, yq, one2], axis=-3)
    f0 = F12.one(batch)
    bits = jnp.asarray(_ATE_BITS, dtype=jnp.uint32)

    def step(state, bit):
        T, f = state
        f = F12.sqr(f)
        T2, l0, l1, l3 = _ate_dbl_step(T, xp, yp)
        f = _sparse_mul013(f, l0, l1, l3)
        T = T2
        Ta, a0, a1, a3, degen = _ate_add_step(T, (xq, yq), xp, yp)
        fa = _sparse_mul013(f, a0, a1, a3)
        fa = jnp.where(degen[..., None, None, None], f, fa)
        f = jnp.where(bit == 1, fa, f)
        T = jnp.where(bit == 1, Ta, T)
        return (T, f), None

    (T, f), _ = jax.lax.scan(step, (T0, f0), bits)

    # Frobenius corrections: Q1 = pi(Q); -pi^2(Q) = (xq*g22, yq) because
    # XI^((p^2-1)/2) = -1 (XI is a non-square in Fp2).
    g12, g13, g22 = _twist_frob_consts()
    q1 = (F2.mul(F2.conj(xq), g12), F2.mul(F2.conj(yq), g13))
    Ta, a0, a1, a3, degen = _ate_add_step(T, q1, xp, yp)
    fa = _sparse_mul013(f, a0, a1, a3)
    f = jnp.where(degen[..., None, None, None], f, fa)
    T = jnp.where(degen[..., None, None, None], T, Ta)

    nq2 = (F2.mul(xq, g22), yq)
    _, a0, a1, a3, degen = _ate_add_step(T, nq2, xp, yp)
    fa = _sparse_mul013(f, a0, a1, a3)
    f = jnp.where(degen[..., None, None, None], f, fa)
    return f


# Devegili–Scott–Dominguez decomposition of the hard part (verified exactly
# for this curve's u in tests/test_pairing.py):
#   (p^4-p^2+1)/n = p^3 + (6u^2+1)p^2 + (-36u^3-18u^2-12u+1)p
#                   + (-36u^3-30u^2-18u-2)
# evaluated with 3 exponentiations by u (63 bits) + Frobenius + ~13 muls via
# the Olivos vectorial addition chain — replaces the former ~1016-bit
# static-exponent scan (the round-1 perf TODO; reference cost center is
# lib/range/range_proof.go:504-565 pairing verification).
def _hard_part(f):
    """f^((p^4-p^2+1)/n) for f in the cyclotomic subgroup (inverse=conj6)."""
    mul, sqr, conj = F12.mul, F12.sqr, F12.conj6
    fx = F12.pow_const(f, params.U)
    fx2 = F12.pow_const(fx, params.U)
    fx3 = F12.pow_const(fx2, params.U)
    y0 = mul(mul(_frob1(f), _frob2(f)), _frob3(f))
    y1 = conj(f)
    y2 = _frob2(fx2)
    y3 = conj(_frob1(fx))
    y4 = conj(mul(fx, _frob1(fx2)))
    y5 = conj(fx2)
    y6 = conj(mul(fx3, _frob1(fx3)))
    # Olivos chain for y0 * y1^2 * y2^6 * y3^12 * y4^18 * y5^30 * y6^36
    t0 = mul(mul(sqr(y6), y4), y5)
    t1 = mul(mul(y3, y5), t0)
    t0 = mul(t0, y2)
    t1 = mul(sqr(t1), t0)
    t1 = sqr(t1)
    t0 = mul(t1, y1)
    t1 = mul(t1, y0)
    t0 = sqr(t0)
    return mul(t0, t1)


def final_exp(f):
    """f^((p^12-1)/n) = easy part (p^6-1)(p^2+1), then hard part."""
    # f^(p^6-1) = conj(f) * f^-1
    f1 = F12.mul(F12.conj6(f), F12.inv(f))
    # f^(p^2+1) = frob^2(f) * f; frob^2 on our flat tower: c_k -> c_k * g2^k
    f2 = F12.mul(_frob2(f1), f1)
    return _hard_part(f2)


# Frobenius^2 constants: w^(p^2) = w * g2 with g2 = XI^((p^2-1)/6) in Fp2
# (an Fp element actually, since (p^2-1)/6 * 2 ... computed in the oracle).
def _frob2_consts():
    g = refimpl.fp2_pow(params.XI, (P * P - 1) // 6)
    consts = []
    cur = (1, 0)
    for _k in range(6):
        consts.append(F2.from_ref(cur))
        cur = refimpl.fp2_mul(cur, g)
    return jnp.asarray(np.stack(consts), dtype=jnp.uint32)


_FROB2 = _frob2_consts()


def _frob2(f):
    """f^(p^2) on the flat tower: coefficients are Fp2-Frobenius^2-invariant
    (x^(p^2) = x for x in Fp2), so c_k -> c_k * XI^(k(p^2-1)/6)."""
    out = [F2.mul(f[..., k, :, :], _FROB2[k]) for k in range(6)]
    return jnp.stack(out, axis=-3)


# Odd Frobenius powers conjugate the Fp2 coefficients (p = 3 mod 4, so
# i^p = -i and likewise p^3 = 3 mod 4): f^(p^e) = sum conj(c_k) g_e^k w^k
# with g_e = w^(p^e - 1) = XI^((p^e-1)/6) in Fp2.
def _frob_odd_consts(e: int):
    assert (P**e - 1) % 6 == 0
    g = refimpl.fp2_pow(params.XI, (P**e - 1) // 6)
    consts, cur = [], (1, 0)
    for _k in range(6):
        consts.append(F2.from_ref(cur))
        cur = refimpl.fp2_mul(cur, g)
    return jnp.asarray(np.stack(consts), dtype=jnp.uint32)


_FROB1 = _frob_odd_consts(1)
_FROB3 = _frob_odd_consts(3)


def _frob1(f):
    out = [F2.mul(F2.conj(f[..., k, :, :]), _FROB1[k]) for k in range(6)]
    return jnp.stack(out, axis=-3)


def _frob3(f):
    out = [F2.mul(F2.conj(f[..., k, :, :]), _FROB3[k]) for k in range(6)]
    return jnp.stack(out, axis=-3)


def pair(p_aff, q_aff):
    """Reduced OPTIMAL ATE pairing, batched (the Tate loop survives as
    miller_loop_tate for cross-checks). Infinity handling is the caller's
    concern (use select against F12.one())."""
    return final_exp(miller_loop(p_aff, q_aff))


__all__ = ["miller_loop", "miller_loop_tate", "final_exp", "pair"]
