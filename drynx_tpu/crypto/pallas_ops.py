"""Pallas TPU kernels for the hot crypto ops (SURVEY.md §7 stage 1).

The jnp field/curve layers put the 16 scalar limbs on the MINOR axis, so a
(B, 16) uint32 op wastes 7/8 of every 128-wide VPU lane register and every
scan step is a separate XLA op with HBM round-trips. These kernels flip the
layout — limbs on sublanes, batch on lanes — and run the entire windowed
scalar-multiplication ladder in one kernel: table build, digit scan, field
arithmetic all in VMEM/registers. This is the TPU-native replacement for the
per-point goroutine fan-out around kyber Point.Mul in the reference (unlynx
StartParallelize at lib/range/range_proof.go:75 and 30+ sites).

Field elements inside a kernel are (16, B) uint32 traced values (16-bit
limbs, little-endian, Montgomery form for Fp); points are (X, Y, Z) tuples of
those (Jacobian, Z == 0 at infinity) — the same representation as
crypto/field.py / crypto/curve.py, transposed.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import params

NL = params.NUM_LIMBS            # 16
LB = params.LIMB_BITS            # 16
MASK = np.uint32(params.LIMB_MASK)  # numpy literal: safe inside kernels

_M_FP = np.asarray(params.to_limbs(params.P), dtype=np.uint32)
_NPRIME_FP = np.uint32(params.NPRIME)

LANES = 128                      # batch tile width

# DRYNX_PALLAS_INTERPRET=1 runs the kernels through the Pallas interpreter
# (any backend) — used by the CPU test suite to cover the kernel code paths.
# The flag is read at CALL time by the thin non-jitted public wrappers and
# passed into the jitted entry points as a STATIC argument, so flipping it
# (tests monkeypatch the module global) keys a fresh trace instead of
# leaking a stale interpret-mode executable out of the jit cache.
INTERPRET = os.environ.get("DRYNX_PALLAS_INTERPRET", "0") == "1"

# jax.enable_x64 exists as a top-level context manager only on some jax
# versions; on others (e.g. 0.4.37) it lives in jax.experimental.
enable_x64 = getattr(jax, "enable_x64", None) or jax.experimental.enable_x64


# ---------------------------------------------------------------------------
# Field arithmetic on (16, B) tiles (trace-time unrolled; ~16-step chains)
# ---------------------------------------------------------------------------

def _sub_limbs(a, b):
    """a - b with borrow chain. Returns ((16, B), borrow (B,))."""
    outs = []
    borrow = jnp.zeros(a.shape[1:], jnp.uint32)
    for k in range(NL):
        v = a[k] - b[k] - borrow
        outs.append(v & MASK)
        borrow = (v >> LB) & np.uint32(1)
    return jnp.stack(outs), borrow


def _carry16(rows, carry0=None):
    """Propagate carries down 16 rows (values < 2^31). -> ((16,B), carry)."""
    outs = []
    c = jnp.zeros(rows.shape[1:], jnp.uint32) if carry0 is None else carry0
    for k in range(NL):
        v = rows[k] + c
        outs.append(v & MASK)
        c = v >> LB
    return jnp.stack(outs), c


def fadd(a, b, m):
    """(a + b) mod m on (16, B) tiles, inputs normalized."""
    s, carry = _carry16(a + b)
    diff, borrow = _sub_limbs(s, jnp.broadcast_to(m, s.shape))
    use_diff = (borrow == 0) | (carry > 0)
    return jnp.where(use_diff[None, :], diff, s)


def fsub(a, b, m):
    diff, borrow = _sub_limbs(a, b)
    plus_m, _ = _carry16(diff + m)
    return jnp.where((borrow == 1)[None, :], plus_m, diff)


def fis_zero(a):
    """(B,) bool: all 16 limbs zero. Unrolled OR-tree — Mosaic lowers
    boolean sublane reductions through an unsupported float path."""
    orv = a[0]
    for k in range(1, NL):
        orv = orv | a[k]
    return orv == 0


def _padded_add(cols, block, off):
    """cols (33, B) + block (R, B) placed at row offset `off` (static).

    Mosaic has no scatter; static-offset placement is a concat of zero rows.
    """
    R = block.shape[0]
    parts = []
    if off:
        parts.append(jnp.zeros((off,) + block.shape[1:], jnp.uint32))
    parts.append(block)
    tail = cols.shape[0] - off - R
    if tail:
        parts.append(jnp.zeros((tail,) + block.shape[1:], jnp.uint32))
    return cols + jnp.concatenate(parts, axis=0)


def mont_mul(a, b, m, nprime):
    """Montgomery product on (16, B) tiles (same math as field.mont_mul's
    unrolled path: schoolbook columns + 16 interleaved reduction steps)."""
    B = a.shape[1]
    zrow = jnp.zeros((1, B), jnp.uint32)
    cols = jnp.zeros((2 * NL + 1, B), jnp.uint32)
    for j in range(NL):
        p = a * b[j][None, :]        # (16, B), full 32-bit products
        # lo lands in cols[j:j+16], hi in cols[j+1:j+17] -> one (17,B) block
        add17 = (jnp.concatenate([p & MASK, zrow], axis=0)
                 + jnp.concatenate([zrow, p >> LB], axis=0))
        cols = _padded_add(cols, add17, j)
    carry = jnp.zeros((B,), jnp.uint32)
    for i in range(NL):
        v = cols[i] + carry
        mfac = ((v & MASK) * nprime) & MASK
        mp = m * mfac[None, :]       # (16, B)
        mlo = mp & MASK
        carry = (v + mlo[0]) >> LB
        # mlo[1:] lands in cols[i+1:i+16], hi in cols[i+1:i+17]
        add16 = (jnp.concatenate([mlo[1:], zrow], axis=0) + (mp >> LB))
        cols = _padded_add(cols, add16, i + 1)
    res, c = _carry16(cols[NL:2 * NL], carry0=carry)
    top = cols[2 * NL] + c
    diff, borrow = _sub_limbs(res, jnp.broadcast_to(m, res.shape))
    use_diff = (borrow == 0) | (top > 0)
    return jnp.where(use_diff[None, :], diff, res)


# ---------------------------------------------------------------------------
# G1 group law on (X, Y, Z) tuples of (16, B) tiles (mirrors crypto/curve.py)
# ---------------------------------------------------------------------------

def _pt_select(cond, p, q):
    """Per-lane select: cond (B,) bool -> p where true else q."""
    c = cond[None, :]
    return tuple(jnp.where(c, a, b) for a, b in zip(p, q))


def make_group(m_const, nprime):
    """Bind the modulus constants once; returns (double, add_complete)."""
    mul = lambda a, b: mont_mul(a, b, m_const, nprime)
    add_ = lambda a, b: fadd(a, b, m_const)
    sub_ = lambda a, b: fsub(a, b, m_const)

    def pdouble(p):
        X, Y, Z = p
        A = mul(X, X)
        Bv = mul(Y, Y)
        Cv = mul(Bv, Bv)
        t0 = add_(X, Bv)
        t = sub_(mul(t0, t0), add_(A, Cv))
        D = add_(t, t)
        E = add_(add_(A, A), A)
        Fv = mul(E, E)
        X3 = sub_(Fv, add_(D, D))
        C2 = add_(Cv, Cv)
        C4 = add_(C2, C2)
        C8 = add_(C4, C4)
        Y3 = sub_(mul(E, sub_(D, X3)), C8)
        YZ = mul(Y, Z)
        Z3 = add_(YZ, YZ)
        return (X3, Y3, Z3)

    def padd(p, q):
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        Z1Z1 = mul(Z1, Z1)
        Z2Z2 = mul(Z2, Z2)
        U1 = mul(X1, Z2Z2)
        U2 = mul(X2, Z1Z1)
        S1 = mul(Y1, mul(Z2, Z2Z2))
        S2 = mul(Y2, mul(Z1, Z1Z1))
        H = sub_(U2, U1)
        HH = add_(H, H)
        I = mul(HH, HH)
        J = mul(H, I)
        r = sub_(S2, S1)
        r = add_(r, r)
        V = mul(U1, I)
        X3 = sub_(sub_(mul(r, r), J), add_(V, V))
        SJ = mul(S1, J)
        Y3 = sub_(mul(r, sub_(V, X3)), add_(SJ, SJ))
        t1 = add_(Z1, Z2)
        ZZ = sub_(sub_(mul(t1, t1), Z1Z1), Z2Z2)
        Z3 = mul(ZZ, H)
        res = (X3, Y3, Z3)

        p_inf = fis_zero(Z1)
        q_inf = fis_zero(Z2)
        h0 = fis_zero(H)
        r0 = fis_zero(r)
        res = _pt_select(h0 & r0 & ~p_inf & ~q_inf, pdouble(p), res)
        res = _pt_select(h0 & ~r0 & ~p_inf & ~q_inf, _inf_like(p), res)
        res = _pt_select(q_inf, p, res)
        res = _pt_select(p_inf, q, res)
        return res

    return pdouble, padd


def _inf_like(p):
    """Infinity point tiles shaped like p: X=Y=1 (Mont form irrelevant,
    any nonzero works for Z==0 semantics — use 1), Z=0."""
    one_row = jnp.ones((1,) + p[0].shape[1:], jnp.uint32)
    zero_rows = jnp.zeros((NL - 1,) + p[0].shape[1:], jnp.uint32)
    X = jnp.concatenate([one_row, zero_rows], axis=0)
    return (X, X, jnp.zeros_like(p[2]))


# ---------------------------------------------------------------------------
# Windowed scalar-mult kernel: whole ladder in one pallas_call
# ---------------------------------------------------------------------------

def _scalar_mul_kernel(m_ref, np_ref, p_ref, k_ref, o_ref, dig_ref,
                       *, n_windows: int = 64):
    m = m_ref[:]                              # (16, 1) modulus limbs
    nprime = np_ref[0, 0]
    pdouble, padd = make_group(m, nprime)

    P = (p_ref[0], p_ref[1], p_ref[2])        # each (16, B)
    k = k_ref[:]                              # (16, B)

    # table[d] = d*P: T[2k]=dbl(T[k]), T[2k+1]=T[2k]+P (7 dbl + 7 add)
    tab = [_inf_like(P), P]
    for d in range(2, 16):
        tab.append(pdouble(tab[d // 2]) if d % 2 == 0
                   else padd(tab[d - 1], P))
    # stack for per-lane constant-time select: (16, 3, 16, B)
    tabX = jnp.stack([t[0] for t in tab])
    tabY = jnp.stack([t[1] for t in tab])
    tabZ = jnp.stack([t[2] for t in tab])

    # n_windows 4-bit digits, MSB-first rows, staged in a VMEM scratch so
    # the loop body can dynamic-slice them (register arrays cannot be
    # dynamically indexed in Mosaic). n_windows < 64 serves scalars known
    # to be < 16^n_windows (e.g. 62-bit RLC weights: 16 windows, 4x fewer
    # ladder steps than the generic 256-bit path).
    rows = []
    for w in range(n_windows - 1, -1, -1):
        limb, s = divmod(w, 4)
        rows.append((k[limb] >> np.uint32(4 * s)) & np.uint32(0xF))
    dig_ref[:] = jnp.stack(rows)              # (n_windows, B) MSB first

    def select(d):
        # per-lane table lookup via 16 selects (constant-time)
        accX, accY, accZ = tabX[0], tabY[0], tabZ[0]
        for v in range(1, 16):
            mask = (d == v)[None, :]
            accX = jnp.where(mask, tabX[v], accX)
            accY = jnp.where(mask, tabY[v], accY)
            accZ = jnp.where(mask, tabZ[v], accZ)
        return (accX, accY, accZ)

    acc0 = select(dig_ref[0])

    def body(w, acc):
        acc = pdouble(pdouble(pdouble(pdouble(acc))))
        d = dig_ref[pl.ds(w, 1), :][0]
        return padd(acc, select(d))

    # int32 bounds: with jax_enable_x64 a python-int fori_loop carries an
    # i64 induction var, which Mosaic cannot lower
    acc = jax.lax.fori_loop(jnp.int32(1), jnp.int32(n_windows), body, acc0)
    o_ref[0] = acc[0]
    o_ref[1] = acc[1]
    o_ref[2] = acc[2]


@functools.partial(jax.jit, static_argnames=("n_windows", "interpret"))
def _scalar_mul_flat(p, k, n_windows: int, interpret: bool):
    N = p.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    pt = _pad_lanes(jnp.transpose(p, (1, 2, 0)), Np)   # (3, 16, Np)
    kt = _pad_lanes(jnp.transpose(k, (1, 0)), Np)      # (16, Np)

    m_in = jnp.asarray(_M_FP[:, None], dtype=jnp.uint32)
    np_in = jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32)
    # x64 mode would make BlockSpec index maps / loop bounds i64, which
    # Mosaic cannot legalize; every value here is uint32, so drop to x32
    with enable_x64(False):
        out = _pallas_scalar_mul(m_in, np_in, pt, kt, n_tiles, Np,
                                 n_windows, interpret)
    return jnp.transpose(out, (2, 0, 1))[:N]


def scalar_mul_flat(p, k, n_windows: int = 64):
    """k*P batched: p (N, 3, 16) Jacobian Montgomery, k (N, 16) plain
    scalars -> (N, 3, 16). Pads N up to a LANES multiple and tiles.
    n_windows < 64 truncates the ladder for short scalars (k < 16^W)."""
    return _scalar_mul_flat(p, k, n_windows, INTERPRET)


def _pallas_scalar_mul(m_in, np_in, pt, kt, n_tiles, Np, n_windows=64,
                       interpret=False):
    return pl.pallas_call(
        functools.partial(_scalar_mul_kernel, n_windows=n_windows),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((NL, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((NL, LANES), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3, NL, Np), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((n_windows, LANES), jnp.uint32)],
        interpret=interpret,
    )(m_in, np_in, pt, kt)


# ---------------------------------------------------------------------------
# Fixed-base windowed mult kernel: shared (64, 16)-entry table, add-only
# ---------------------------------------------------------------------------

def _fixed_base_kernel(m_ref, np_ref, tab_ref, k_ref, o_ref, dig_ref):
    """tab_ref: (W, 16, 48) — row w holds [16 limbs x (coord c * 16 + digit
    v)] of the precomputed points v * 16^w * P (v=0 row is infinity).
    W table-gather adds, no doubles (the 16^w factors are baked in); W < 64
    serves scalars known to be < 16^W (small plaintexts)."""
    m = m_ref[:]
    nprime = np_ref[0, 0]
    pdouble, padd = make_group(m, nprime)
    k = k_ref[:]                              # (16, B)
    B = k.shape[1]
    W = dig_ref.shape[0]

    rows = []
    for w in range(W):                        # little-endian digit order
        limb, s = divmod(w, 4)
        rows.append((k[limb] >> np.uint32(4 * s)) & np.uint32(0xF))
    dig_ref[:] = jnp.stack(rows)              # (W, B)

    def sel(row, d):
        # row (16, 48) = limbs x (c*16+v); per-lane digit select by splat
        pts = []
        for c in range(3):
            cand = row[:, c * 16:(c + 1) * 16]          # (16, 16)
            acc = jnp.broadcast_to(cand[:, 0:1], (NL, B))
            for v in range(1, 16):
                splat = jnp.broadcast_to(cand[:, v:v + 1], (NL, B))
                acc = jnp.where((d == v)[None, :], splat, acc)
            pts.append(acc)
        return tuple(pts)

    def body(w, acc):
        row = tab_ref[pl.ds(w, 1)][0]         # (16, 48)
        d = dig_ref[pl.ds(w, 1), :][0]        # (B,)
        return padd(acc, sel(row, d))

    zero = jnp.zeros((NL, B), jnp.uint32)
    acc0 = _inf_like((zero, zero, zero))
    acc = jax.lax.fori_loop(jnp.int32(0), jnp.int32(W), body, acc0)
    o_ref[0] = acc[0]
    o_ref[1] = acc[1]
    o_ref[2] = acc[2]


@functools.partial(jax.jit, static_argnames=("n_windows", "interpret"))
def _fixed_base_mul_flat(table, k, n_windows: int, interpret: bool):
    N = k.shape[0]
    W = n_windows
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    kt = _pad_lanes(jnp.transpose(k, (1, 0)), Np)      # (16, Np)
    # (w, v, c, l) -> (w, l, c, v) -> (W, 16, 48); the table comes from the
    # caller (elgamal.FixedBase), so pin uint32 here like _pad_lanes does
    tt = jnp.asarray(jnp.transpose(table[:W], (0, 3, 2, 1)),
                     dtype=jnp.uint32).reshape(W, NL, 48)

    m_in = jnp.asarray(_M_FP[:, None], dtype=jnp.uint32)
    np_in = jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32)
    with enable_x64(False):
        out = pl.pallas_call(
            _fixed_base_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((NL, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((W, NL, 48), lambda i: (0, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((NL, LANES), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((3, NL, Np), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((W, LANES), jnp.uint32)],
            interpret=interpret,
        )(m_in, np_in, tt, kt)
    return jnp.transpose(out, (2, 0, 1))[:N]


def fixed_base_mul_flat(table, k, n_windows: int = 64):
    """k*P via a shared fixed-base window table. table: (64, 16, 3, 16) as
    built by elgamal.FixedBase; k: (N, 16) plain scalars -> (N, 3, 16).
    n_windows < 64 truncates the ladder for small scalars (k < 16^W)."""
    return _fixed_base_mul_flat(table, k, n_windows, INTERPRET)


# ---------------------------------------------------------------------------
# Batched complete point add + R-way reduce kernels
# ---------------------------------------------------------------------------

def _point_add_kernel(m_ref, np_ref, p_ref, q_ref, o_ref):
    m = m_ref[:]
    _, padd = make_group(m, np_ref[0, 0])
    r = padd((p_ref[0], p_ref[1], p_ref[2]),
             (q_ref[0], q_ref[1], q_ref[2]))
    o_ref[0], o_ref[1], o_ref[2] = r


def _point_reduce_kernel(m_ref, np_ref, p_ref, o_ref):
    """p_ref: (R, 3, 16, B) — sum rows 0..R-1 with the complete group add."""
    m = m_ref[:]
    _, padd = make_group(m, np_ref[0, 0])
    R = p_ref.shape[0]
    acc = (p_ref[0, 0], p_ref[0, 1], p_ref[0, 2])
    for r in range(1, R):                     # R is small + static: unroll
        acc = padd(acc, (p_ref[r, 0], p_ref[r, 1], p_ref[r, 2]))
    o_ref[0], o_ref[1], o_ref[2] = acc


def _mk_point_io(n_tiles, Np, extra=None):
    specs = [
        pl.BlockSpec((NL, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
    ]
    if extra:
        specs += extra
    return dict(
        grid=(n_tiles,),
        in_specs=specs,
        out_specs=pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3, NL, Np), jnp.uint32),
    )


def _pad_lanes(x, Np):
    # every Mosaic operand funnels through here: pin uint32 at the choke
    # point so a weak int32/i64 limb tensor can never reach a kernel
    x = jnp.asarray(x, dtype=jnp.uint32)
    N = x.shape[-1]
    if N == Np:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, Np - N)]
    # pin the fill constant: a weak-typed 0 becomes i64 when traced with
    # x64 on, and mixing it into the x64-off pallas operand prep produces
    # a jaxpr that fails MLIR verification at lowering
    return jnp.pad(x, pad, constant_values=np.zeros((), x.dtype))


@functools.partial(jax.jit, static_argnames="interpret")
def _point_add_flat(p, q, interpret: bool):
    N = p.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    pt = _pad_lanes(jnp.transpose(p, (1, 2, 0)), Np)
    qt = _pad_lanes(jnp.transpose(q, (1, 2, 0)), Np)
    m_in = jnp.asarray(_M_FP[:, None], dtype=jnp.uint32)
    np_in = jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32)
    io = _mk_point_io(n_tiles, Np, extra=[
        pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((3, NL, LANES), lambda i: (0, 0, i),
                     memory_space=pltpu.VMEM),
    ])
    with enable_x64(False):
        out = pl.pallas_call(_point_add_kernel, interpret=interpret, **io)(m_in, np_in, pt, qt)
    return jnp.transpose(out, (2, 0, 1))[:N]


def point_add_flat(p, q):
    """Complete add, (N, 3, 16) x (N, 3, 16) -> (N, 3, 16)."""
    return _point_add_flat(p, q, INTERPRET)


@functools.partial(jax.jit, static_argnames="interpret")
def _point_reduce_flat(pts, interpret: bool):
    R, N = pts.shape[0], pts.shape[1]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    pt = _pad_lanes(jnp.transpose(pts, (0, 2, 3, 1)), Np)  # (R,3,16,Np)
    m_in = jnp.asarray(_M_FP[:, None], dtype=jnp.uint32)
    np_in = jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32)
    io = _mk_point_io(n_tiles, Np, extra=[
        pl.BlockSpec((R, 3, NL, LANES), lambda i: (0, 0, 0, i),
                     memory_space=pltpu.VMEM),
    ])
    with enable_x64(False):
        out = pl.pallas_call(_point_reduce_kernel, interpret=interpret, **io)(m_in, np_in, pt)
    return jnp.transpose(out, (2, 0, 1))[:N]


def point_reduce_flat(pts):
    """Group-add reduce over axis 0: (R, N, 3, 16) -> (N, 3, 16), one
    kernel call (replaces log2(R) jnp tree-reduce rounds)."""
    return _point_reduce_flat(pts, INTERPRET)


def available() -> bool:
    """True when the Mosaic TPU path can run here (kill: DRYNX_NO_PALLAS=1)."""
    if os.environ.get("DRYNX_NO_PALLAS", "0") == "1":
        return False
    if INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


__all__ = ["scalar_mul_flat", "fixed_base_mul_flat", "point_add_flat",
           "point_reduce_flat", "mont_mul", "fadd", "fsub", "make_group",
           "available", "LANES"]
