"""Pallas TPU kernels for the pairing pipeline (optimal ate + final exp).

Why: the jnp pairing (crypto/pairing.py) is correct but its rolled limb
loops execute as nested XLA while-loops — ~2.7-5.5s per Miller batch on the
chip regardless of batch size (loop overhead, not compute). Range-proof
creation/verification dispatches tens of thousands of pairings (reference
cost center: lib/range/range_proof.go:504-565, 21.7 s VN phase), so the
pairing must run like the scalar-mul ladders: whole loop inside one Mosaic
kernel, limbs on sublanes, batch on lanes (see crypto/pallas_ops.py).

Kernels:
  miller_flat(p, q)        optimal ate Miller function, batched
  f12_mul_flat(a, b)       one Fp12 product (final-exp glue)
  f12_inv_flat(f)          Fp12 inversion (tower + in-kernel Fermat Fp inv)
  f12_pow_flat(f, k, n)    f^k, square-and-multiply-always over n bit rows
  pair_flat(px, py, qx, qy)  full reduced pairing (miller + final exp),
                           final-exp Frobenius/Olivos glue at jnp level

Math mirrors crypto/pairing.py exactly (same line sparsity {0,1,3}, same
DSD/Olivos hard part); parity is asserted against it in
tests/test_pallas_pairing.py via interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import params
from .pallas_ops import (INTERPRET, LANES, MASK, NL, _M_FP, _NPRIME_FP,
                         enable_x64,
                         _pad_lanes, fadd, fsub, mont_mul)

_XI_A = params.XI[0]          # XI = (3, 1): (x0+x1 i)(3+i)
assert params.XI[1] == 1

_ATE_BITS = [int(b) for b in bin(6 * params.U + 2)[3:]]   # MSB-first, 65
_U_BITS_LSB = [(params.U >> i) & 1 for i in range(params.U.bit_length())]
_PM2_BITS = [int(b) for b in bin(params.P - 2)[2:]]       # MSB-first


def _fermat_inv(x, mul):
    """x^(p-2) via the static square-and-multiply chain — trace-time
    UNROLLED (~383 inlined Montgomery muls). Kept only as the fallback for
    make_fp12 callers that pass no bit rows; the inversion kernels use
    _fermat_inv_rolled, whose jaxpr is two muls in a fori_loop body."""
    acc = x
    for bit in _PM2_BITS[1:]:
        acc = mul(acc, acc)
        if bit:
            acc = mul(acc, x)
    return acc


def _fermat_inv_rolled(x, mul, bits_ref):
    """x^(p-2) as a fori_loop over pre-staged bit rows of p-2 (MSB-first,
    bits_ref (256, B) — broadcast host-side like the Miller ate bits;
    in-kernel constant broadcasts hit the unimplemented Mosaic
    sublane+lane path). Square-and-multiply-ALWAYS with a per-lane select:
    ~127 extra Fp muls per chain, but the traced body is 2 muls instead of
    ~383 — the unrolled chain was the dominant jaxpr cost of every
    inversion kernel (and the r05 C-stack overflow food)."""
    def body(w, acc):
        acc = mul(acc, acc)
        bit = bits_ref[pl.ds(w, 1), :][0]        # (B,)
        accm = mul(acc, x)
        return jnp.where((bit == 1)[None, :], accm, acc)

    return jax.lax.fori_loop(jnp.int32(1), jnp.int32(len(_PM2_BITS)),
                             body, x)


def _pm2_bits_tiles() -> np.ndarray:
    """(256, LANES) uint32: MSB-first bits of p-2, lane-broadcast."""
    return np.broadcast_to(
        np.asarray(_PM2_BITS, dtype=np.uint32)[:, None],
        (len(_PM2_BITS), LANES)).copy()


# ---------------------------------------------------------------------------
# In-kernel Fp2 / Fp12 arithmetic on (16, B) limb tiles
# ---------------------------------------------------------------------------

def make_fp2(m, nprime):
    mul = lambda a, b: mont_mul(a, b, m, nprime)
    add = lambda a, b: fadd(a, b, m)
    sub = lambda a, b: fsub(a, b, m)

    def f2add(a, b):
        return (add(a[0], b[0]), add(a[1], b[1]))

    def f2sub(a, b):
        return (sub(a[0], b[0]), sub(a[1], b[1]))

    def f2neg(a):
        z = jnp.zeros_like(a[0])
        return (sub(z, a[0]), sub(z, a[1]))

    def f2conj(a):
        z = jnp.zeros_like(a[1])
        return (a[0], sub(z, a[1]))

    def f2mul(a, b):
        # Karatsuba over i^2 = -1: 3 Montgomery muls
        t0 = mul(a[0], b[0])
        t1 = mul(a[1], b[1])
        t2 = mul(add(a[0], a[1]), add(b[0], b[1]))
        return (sub(t0, t1), sub(sub(t2, t0), t1))

    def f2sqr(a):
        re = mul(add(a[0], a[1]), sub(a[0], a[1]))
        im2 = mul(a[0], a[1])
        return (re, add(im2, im2))

    def f2mul_fp(a, s):
        return (mul(a[0], s), mul(a[1], s))

    def _mul3(x):
        return add(add(x, x), x)

    def f2mul_xi(a):
        # (x0 + x1 i)(3 + i) = (3x0 - x1) + (x0 + 3x1) i
        return (sub(_mul3(a[0]), a[1]), add(a[0], _mul3(a[1])))

    return dict(add=f2add, sub=f2sub, neg=f2neg, conj=f2conj, mul=f2mul,
                sqr=f2sqr, mul_fp=f2mul_fp, mul_xi=f2mul_xi,
                fp_mul=mul, fp_add=add, fp_sub=sub)


def make_fp12(F2, pm2_bits_ref=None):
    """Fp12 = 6-list of Fp2 pairs; flat tower w^6 = XI (crypto/fp12.py).

    pm2_bits_ref: optional (256, B) bit rows of p-2 (see _pm2_bits_tiles).
    When given, the Fermat Fp inversion inside the tower runs as a rolled
    fori_loop (tiny jaxpr); without it the unrolled chain is used — only
    the inversion kernel actually reaches fp_inv, and it passes the rows.

    Multiplication runs over the Fp6 sub-tower (v = w^2, v^3 = XI;
    f = A(v) + w*B(v) with A = (f0,f2,f4), B = (f1,f3,f5)):
    Karatsuba at both levels gives 3*6 = 18 Fp2 muls per full product
    (vs 36 schoolbook) and 12 per squaring — the pairing/pow kernels are
    Fp2-mul-bound, so this is a direct ~2x on every GT-heavy op.
    """

    # Fp6 helpers on Fp2 triples (crypto/fp12.py:66-110)
    def fp6_mul(a, b):
        # 3-way Karatsuba: 6 Fp2 muls
        t0 = F2["mul"](a[0], b[0])
        t1 = F2["mul"](a[1], b[1])
        t2 = F2["mul"](a[2], b[2])
        m01 = F2["mul"](F2["add"](a[0], a[1]), F2["add"](b[0], b[1]))
        m02 = F2["mul"](F2["add"](a[0], a[2]), F2["add"](b[0], b[2]))
        m12 = F2["mul"](F2["add"](a[1], a[2]), F2["add"](b[1], b[2]))
        c0 = F2["add"](t0, F2["mul_xi"](F2["sub"](F2["sub"](m12, t1), t2)))
        c1 = F2["add"](F2["sub"](F2["sub"](m01, t0), t1), F2["mul_xi"](t2))
        c2 = F2["add"](F2["sub"](F2["sub"](m02, t0), t2), t1)
        return (c0, c1, c2)

    def fp6_add(a, b):
        return tuple(F2["add"](x, y) for x, y in zip(a, b))

    def _split(f):
        return (f[0], f[2], f[4]), (f[1], f[3], f[5])

    def _join(A, B):
        return [A[0], B[0], A[1], B[1], A[2], B[2]]

    def f12mul(a, b):
        A1, B1 = _split(a)
        A2, B2 = _split(b)
        t0 = fp6_mul(A1, A2)
        t1 = fp6_mul(B1, B2)
        t2 = fp6_mul(fp6_add(A1, B1), fp6_add(A2, B2))
        return _join(fp6_add(t0, fp6_mul_v(t1)),
                     fp6_sub(fp6_sub(t2, t0), t1))

    def f12sqr(a):
        # complex-method squaring over Fp6: 2 Fp6 muls = 12 Fp2 muls
        A, B = _split(a)
        ab = fp6_mul(A, B)
        t = fp6_mul(fp6_add(A, B), fp6_add(A, fp6_mul_v(B)))
        c0 = fp6_sub(fp6_sub(t, ab), fp6_mul_v(ab))
        return _join(c0, fp6_add(ab, ab))

    def f12csqr(f):
        """Granger-Scott cyclotomic squaring (eprint 2009/565 §3.2): valid
        ONLY for f in GΦ12(p) — i.e. f^(p^4-p^2+1) = 1, which holds for
        every pairing output after the final exponentiation. 9 Fp2
        squarings (18 Montgomery muls) vs 12 Fp2 muls (36) for the complex
        method — 2x on every squaring in a GT pow chain. Formulas validated
        against the refimpl oracle on the flat tower basis (f_k w^k,
        w^6 = XI): gnark-style coords x0..x5 = f0, f2, f4, f1, f3, f5."""
        f0, f1, f2, f3, f4, f5 = f
        t0 = F2["sqr"](f3)
        t1 = F2["sqr"](f0)
        t6 = F2["sub"](F2["sub"](F2["sqr"](F2["add"](f3, f0)), t0), t1)
        t2 = F2["sqr"](f4)
        t3 = F2["sqr"](f1)
        t7 = F2["sub"](F2["sub"](F2["sqr"](F2["add"](f4, f1)), t2), t3)
        t4 = F2["sqr"](f5)
        t5 = F2["sqr"](f2)
        t8 = F2["mul_xi"](
            F2["sub"](F2["sub"](F2["sqr"](F2["add"](f5, f2)), t4), t5))
        t0 = F2["add"](F2["mul_xi"](t0), t1)
        t2 = F2["add"](F2["mul_xi"](t2), t3)
        t4 = F2["add"](F2["mul_xi"](t4), t5)

        def out_sub(t, x):          # 3t - 2x = 2(t - x) + t
            d = F2["sub"](t, x)
            return F2["add"](F2["add"](d, d), t)

        def out_add(t, x):          # 3t + 2x = 2(t + x) + t
            s = F2["add"](t, x)
            return F2["add"](F2["add"](s, s), t)

        return [out_sub(t0, f0), out_add(t8, f1), out_sub(t2, f2),
                out_add(t6, f3), out_sub(t4, f4), out_add(t7, f5)]

    def f12conj6(a):
        return [a[k] if k % 2 == 0 else F2["neg"](a[k]) for k in range(6)]

    def fp6_sub(a, b):
        return tuple(F2["sub"](x, y) for x, y in zip(a, b))

    def fp6_mul_v(a):
        return (F2["mul_xi"](a[2]), a[0], a[1])

    def fp_inv(x):
        if pm2_bits_ref is not None:
            return _fermat_inv_rolled(x, F2["fp_mul"], pm2_bits_ref)
        return _fermat_inv(x, F2["fp_mul"])

    def f2inv(a):
        n = F2["fp_add"](F2["fp_mul"](a[0], a[0]), F2["fp_mul"](a[1], a[1]))
        ni = fp_inv(n)
        z = jnp.zeros_like(a[1])
        return (F2["fp_mul"](a[0], ni),
                F2["fp_mul"](F2["fp_sub"](z, a[1]), ni))

    def fp6_inv(a):
        a0, a1, a2 = a
        c0 = F2["sub"](F2["sqr"](a0), F2["mul_xi"](F2["mul"](a1, a2)))
        c1 = F2["sub"](F2["mul_xi"](F2["sqr"](a2)), F2["mul"](a0, a1))
        c2 = F2["sub"](F2["sqr"](a1), F2["mul"](a0, a2))
        t = F2["add"](F2["mul"](a0, c0), F2["mul_xi"](
            F2["add"](F2["mul"](a1, c2), F2["mul"](a2, c1))))
        ti = f2inv(t)
        return (F2["mul"](c0, ti), F2["mul"](c1, ti), F2["mul"](c2, ti))

    def f12inv(f):
        a = (f[0], f[2], f[4])
        b = (f[1], f[3], f[5])
        norm = fp6_sub(fp6_mul(a, a), fp6_mul_v(fp6_mul(b, b)))
        ninv = fp6_inv(norm)
        ra = fp6_mul(a, ninv)
        rb = fp6_mul(b, ninv)
        rb = tuple(F2["neg"](x) for x in rb)
        return [ra[0], rb[0], ra[1], rb[1], ra[2], rb[2]]

    def sparse013(f, l0, l1, l3):
        acc = [None] * 9

        def accum(k, v):
            acc[k] = v if acc[k] is None else F2["add"](acc[k], v)

        for k in range(6):
            accum(k, F2["mul"](f[k], l0))
            accum(k + 1, F2["mul"](f[k], l1))
            accum(k + 3, F2["mul"](f[k], l3))
        out = list(acc[:6])
        for k in range(6, 9):
            out[k - 6] = F2["add"](out[k - 6], F2["mul_xi"](acc[k]))
        return out

    return dict(mul=f12mul, sqr=f12sqr, csqr=f12csqr, conj6=f12conj6,
                inv=f12inv, sparse013=sparse013)


def _f12_load(ref):
    """(12, 16, B) ref -> 6-list of Fp2 pairs of (16, B)."""
    return [(ref[2 * k], ref[2 * k + 1]) for k in range(6)]


def _f12_store(o_ref, f):
    for k in range(6):
        o_ref[2 * k] = f[k][0]
        o_ref[2 * k + 1] = f[k][1]


def _f12_one_tiles(one_col, B):
    """Fp12 one from a (16, 1) Montgomery-one column (kernel input — Mosaic
    rejects captured host arrays; see pallas_ops module docstring)."""
    rows = [jnp.broadcast_to(one_col, (NL, B))]
    rows += [jnp.zeros((NL, B), jnp.uint32)] * 11
    return [(rows[2 * k], rows[2 * k + 1]) for k in range(6)]


def _f12_select(cond, a, b):
    """Per-lane select between two Fp12 values; cond (B,) bool."""
    c = cond[None, :]
    return [(jnp.where(c, x[0], y[0]), jnp.where(c, x[1], y[1]))
            for x, y in zip(a, b)]


# ---------------------------------------------------------------------------
# Miller loop kernel
# ---------------------------------------------------------------------------

def _miller_kernel(m_ref, np_ref, g_ref, bits_ref, p_ref, q_ref, o_ref):
    """Optimal ate Miller function (mirrors pairing.miller_loop).

    g_ref: (16, 8) — the three G2-Frobenius Fp2 constants (g12, g13, g22)
    as limb columns, then (one_mont, 0). p_ref: (2, 16, B) G1 affine
    Montgomery; q_ref: (10, 16, B): xq, yq, then the host-precomputed Frobenius
    images q1x, q1y, nq2x (each an Fp2 pair of rows).
    """
    m = m_ref[:]
    nprime = np_ref[0, 0]
    F2 = make_fp2(m, nprime)
    F12 = make_fp12(F2)

    B = p_ref.shape[-1]
    xp, yp = p_ref[0], p_ref[1]
    xq = (q_ref[0], q_ref[1])
    yq = (q_ref[2], q_ref[3])
    # Frobenius images of Q are precomputed host-side (constant-broadcast
    # multiplications inside the kernel hit an unimplemented Mosaic
    # sublane+lane broadcast when mixed with trace-level constant folding)
    q1x = (q_ref[4], q_ref[5])
    q1y = (q_ref[6], q_ref[7])
    nq2x = (q_ref[8], q_ref[9])

    # constants live in a 2D (limbs x columns) block; slicing a lane column
    # then broadcasting is the Mosaic-supported pattern (see the fixed-base
    # kernel's table select)
    one_m = jnp.broadcast_to(g_ref[:, 6:7], (NL, B))


    def dbl_step(T, f):
        X, Y, Z = T
        A = F2["sqr"](X)
        Bv = F2["sqr"](Y)
        zz = F2["sqr"](Z)
        E = F2["add"](F2["add"](A, A), A)              # 3X^2
        AX = F2["mul"](A, X)                           # X^3
        l3 = F2["sub"](F2["add"](F2["add"](AX, AX), AX),
                       F2["add"](Bv, Bv))              # 3X^3 - 2Y^2
        l1 = F2["mul_fp"](F2["neg"](F2["mul"](E, zz)), xp)
        YZ = F2["mul"](Y, Z)
        YZ3 = F2["mul"](YZ, zz)
        l0 = F2["mul_fp"](F2["add"](YZ3, YZ3), yp)
        # point double (same formulas as pallas_ops.make_group pdouble)
        Cv = F2["sqr"](Bv)
        t0 = F2["add"](X, Bv)
        t = F2["sub"](F2["sqr"](t0), F2["add"](A, Cv))
        D = F2["add"](t, t)
        Fv = F2["sqr"](E)
        X3 = F2["sub"](Fv, F2["add"](D, D))
        C2 = F2["add"](Cv, Cv)
        C8 = F2["add"](F2["add"](C2, C2), F2["add"](C2, C2))
        Y3 = F2["sub"](F2["mul"](E, F2["sub"](D, X3)), C8)
        Z3 = F2["add"](YZ, YZ)
        f = F12["sqr"](f)
        f = F12["sparse013"](f, l0, l1, l3)
        return (X3, Y3, Z3), f

    def add_step(T, f, qx, qy):
        """Mixed add T + (qx, qy) with the line through them; the whole line
        may be scaled by any Fp2 factor (killed by the final exponentiation),
        so the madd-convention sign flip is free (pairing.py's line times -1:
        l0 = Hm Z yp, l1 = -r1 xp, l3 = r1 xq - Hm Z yq).

        Vertical degeneracy (Hm = 0: x_T == x_Q, possible only on crafted
        wire points) mirrors the jnp miller_loop: line contributes 1 and the
        point update is skipped — TPU and CPU verifiers must agree."""
        X1, Y1, Z1 = T
        zz = F2["sqr"](Z1)
        U2 = F2["mul"](qx, zz)
        S2 = F2["mul"](qy, F2["mul"](Z1, zz))
        Hm = F2["sub"](U2, X1)
        r1 = F2["sub"](S2, Y1)
        HmZ = F2["mul"](Hm, Z1)
        l0 = F2["mul_fp"](HmZ, yp)
        l1 = F2["mul_fp"](F2["neg"](r1), xp)
        l3 = F2["sub"](F2["mul"](r1, qx), F2["mul"](HmZ, qy))
        f2 = F12["sparse013"](f, l0, l1, l3)
        # madd-2007-bl point addition
        HH = F2["sqr"](Hm)
        I4 = F2["add"](F2["add"](HH, HH), F2["add"](HH, HH))
        J = F2["mul"](Hm, I4)
        rm = F2["add"](r1, r1)
        V = F2["mul"](X1, I4)
        X3 = F2["sub"](F2["sub"](F2["sqr"](rm), J), F2["add"](V, V))
        YJ = F2["mul"](Y1, J)
        Y3 = F2["sub"](F2["mul"](rm, F2["sub"](V, X3)), F2["add"](YJ, YJ))
        Z3 = F2["sub"](F2["sub"](F2["sqr"](F2["add"](Z1, Hm)), zz), HH)
        degen = _f2_is_zero(Hm)
        Tn = tuple((jnp.where(degen[None, :], a[0], b[0]),
                    jnp.where(degen[None, :], a[1], b[1]))
                   for a, b in zip(T, (X3, Y3, Z3)))
        fn = _f12_select(degen, f, f2)
        return Tn, fn

    T0 = (xq, yq, (one_m, jnp.zeros((NL, B), jnp.uint32)))
    f0 = _f12_one_tiles(g_ref[:, 6:7], B)

    def body(w, state):
        T, f = state
        T, f = dbl_step(T, f)
        # bits are pre-broadcast to lanes (scalar->tile broadcasts hit an
        # unimplemented Mosaic "broadcast in both sublanes and lanes" path)
        bit = bits_ref[pl.ds(w, 1), :][0]          # (B,)
        Ta, fa = add_step(T, f, xq, yq)
        cond = bit == 1
        T = tuple((jnp.where(cond[None, :], a[0], b[0]),
                   jnp.where(cond[None, :], a[1], b[1]))
                  for a, b in zip(Ta, T))
        f = _f12_select(cond, fa, f)
        return (T, f)

    T, f = jax.lax.fori_loop(jnp.int32(0), jnp.int32(len(_ATE_BITS)), body,
                             (T0, f0))

    # Frobenius corrections: Q1 = (conj(xq)*g12, conj(yq)*g13);
    # -pi^2(Q) = (xq*g22, yq)  [XI non-square => XI^((p^2-1)/2) = -1]
    T, f = add_step(T, f, q1x, q1y)
    _, f = add_step(T, f, nq2x, yq)
    _f12_store(o_ref, f)


def _twist_frob_tiles() -> np.ndarray:
    """(16, 8): columns = g12_0, g12_1, g13_0, g13_1, g22_0, g22_1 (the G2
    Frobenius Fp2 constants), one_mont, 0 — Montgomery limbs on sublanes."""
    from . import refimpl

    cols = []
    for c in (refimpl._G12, refimpl._G13, refimpl._G22):
        for comp in c:
            cols.append(np.asarray(
                params.to_limbs(comp * params.R % params.P), dtype=np.uint32))
    cols.append(np.asarray(params.to_limbs(params.R % params.P),
                           dtype=np.uint32))
    cols.append(np.zeros(NL, dtype=np.uint32))
    return np.stack(cols, axis=-1)


@functools.partial(jax.jit, static_argnames="interpret")
def _miller_flat(px, py, qx, qy, interpret: bool):
    from . import fp2 as F2j

    N = px.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    p_in = _pad_lanes(jnp.stack([px.T, py.T]), Np)            # (2, 16, Np)
    # host-side Frobenius images of Q (refimpl.twist_frob semantics)
    g12, g13, g22 = _twist_frob_consts_jnp()
    q1x = F2j.mul(F2j.conj(qx), g12)
    q1y = F2j.mul(F2j.conj(qy), g13)
    nq2x = F2j.mul(qx, g22)
    q_in = _pad_lanes(jnp.concatenate(
        [jnp.transpose(t, (1, 2, 0))
         for t in (qx, qy, q1x, q1y, nq2x)], axis=0), Np)     # (10, 16, Np)
    m_in = jnp.asarray(_M_FP[:, None], dtype=jnp.uint32)
    np_in = jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32)
    g_in = jnp.asarray(_twist_frob_tiles(), dtype=jnp.uint32)
    bits_in = jnp.asarray(np.broadcast_to(
        np.asarray(_ATE_BITS, dtype=np.uint32)[:, None],
        (len(_ATE_BITS), LANES)).copy(), dtype=jnp.uint32)

    with enable_x64(False):
        out = pl.pallas_call(
            _miller_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((NL, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((NL, 8), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((len(_ATE_BITS), LANES), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((2, NL, LANES), lambda i: (0, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((10, NL, LANES), lambda i: (0, 0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((12, NL, LANES), lambda i: (0, 0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((12, NL, Np), jnp.uint32),
            interpret=interpret,
        )(m_in, np_in, g_in, bits_in, p_in, q_in)
    return jnp.transpose(out, (2, 0, 1))[:N].reshape(N, 6, 2, NL)


def miller_flat(px, py, qx, qy):
    """Batched ate Miller function.

    px, py: (N, 16) Fp Montgomery; qx, qy: (N, 2, 16) Fp2 Montgomery.
    Returns (N, 6, 2, 16) unreduced Miller value (host layout).
    """
    return _miller_flat(px, py, qx, qy, INTERPRET)


_TF_JNP = None


def _twist_frob_consts_jnp():
    global _TF_JNP
    if _TF_JNP is None:
        from . import fp2 as F2j
        from . import refimpl

        # cache NUMPY (a jnp array materialized inside a jit trace is a
        # tracer — caching it across calls leaks it out of the trace)
        _TF_JNP = tuple(np.asarray(F2j.from_ref(c))
                        for c in (refimpl._G12, refimpl._G13, refimpl._G22))
    return _TF_JNP


# ---------------------------------------------------------------------------
# Fp12 mul / inv / pow kernels (final-exp building blocks + GT ops)
# ---------------------------------------------------------------------------

def _f12_mul_kernel(m_ref, np_ref, a_ref, b_ref, o_ref):
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2)
    _f12_store(o_ref, F12["mul"](_f12_load(a_ref), _f12_load(b_ref)))


def _f12_inv_kernel(m_ref, np_ref, bits_ref, a_ref, o_ref):
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2, pm2_bits_ref=bits_ref)
    _f12_store(o_ref, F12["inv"](_f12_load(a_ref)))


def _f12_pow_kernel(m_ref, np_ref, one_ref, f_ref, k_ref, o_ref, bit_ref,
                    *, n_bits: int):
    """f^k, LSB-first square-and-multiply-always over n_bits bit rows.
    one_ref: (16, 1) Montgomery-one column for the Fp12 identity."""
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2)
    B = f_ref.shape[-1]
    k = k_ref[:]

    rows = []
    for w in range(n_bits):
        limb, s = divmod(w, params.LIMB_BITS)
        rows.append((k[limb] >> np.uint32(s)) & np.uint32(1))
    bit_ref[:] = jnp.stack(rows)                 # (n_bits, B)

    base0 = _f12_load(f_ref)
    acc0 = _f12_one_tiles(one_ref[:], B)

    def body(w, state):
        acc, base = state
        bit = bit_ref[pl.ds(w, 1), :][0]
        acc2 = F12["mul"](acc, base)
        acc = _f12_select(bit == 1, acc2, acc)
        base = F12["sqr"](base)
        return (acc, base)

    acc, _ = jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_bits), body,
                               (acc0, base0))
    _f12_store(o_ref, acc)


def _f12_wpow_kernel(m_ref, np_ref, one_ref, f_ref, k_ref, o_ref, dig_ref,
                     *, n_bits: int, wbits: int, cyc: bool = False):
    """f^k via wbits-wide windows, MSB-first: an in-kernel 2^wbits-entry
    power table, then per window `wbits` squarings + one select-mul.
    With sqr = 12 and mul = 18 Fp2 muls this is ~2.4x over the
    square-and-multiply-always _f12_pow_kernel. wbits=3 keeps the live
    table at 8 Fp12 values — 4-bit windows blow the 16 MB scoped-VMEM
    budget (observed OOM at 17.2 MB). one_ref: (16, 1) Montgomery one.

    cyc=True swaps every squaring (window chain AND table build — all
    operands are powers of the base) for the Granger-Scott cyclotomic
    squaring: 2x cheaper, valid only when f ∈ GΦ12(p). Callers must
    guarantee membership (pairing outputs are; wire-provided GT elements
    are gated by batching.gt_membership_ok first)."""
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2)
    sqr = F12["csqr"] if cyc else F12["sqr"]
    B = f_ref.shape[-1]
    k = k_ref[:]
    n_win = (n_bits + wbits - 1) // wbits
    n_tab = 1 << wbits
    mask = np.uint32(n_tab - 1)

    rows = []
    for w in range(n_win - 1, -1, -1):          # MSB-first
        limb, s = divmod(wbits * w, params.LIMB_BITS)
        d = k[limb] >> np.uint32(s)
        if s + wbits > params.LIMB_BITS and limb + 1 < NL:
            # window straddles a limb boundary
            d = d | (k[limb + 1] << np.uint32(params.LIMB_BITS - s))
        rows.append(d & mask)
    dig_ref[:] = jnp.stack(rows)                # (n_win, B)

    base = _f12_load(f_ref)
    tab = [_f12_one_tiles(one_ref[:], B), base]
    for d in range(2, n_tab):
        tab.append(sqr(tab[d // 2]) if d % 2 == 0
                   else F12["mul"](tab[d - 1], base))

    def select(d):
        acc = tab[0]
        for v in range(1, n_tab):
            acc = _f12_select(d == v, tab[v], acc)
        return acc

    acc0 = select(dig_ref[0])

    def body(w, acc):
        for _ in range(wbits):
            acc = sqr(acc)
        d = dig_ref[pl.ds(w, 1), :][0]
        return F12["mul"](acc, select(d))

    acc = jax.lax.fori_loop(jnp.int32(1), jnp.int32(n_win), body, acc0)
    _f12_store(o_ref, acc)


def _f12_mulreduce8_kernel(m_ref, np_ref, g_ref, o_ref):
    """Product of 8 Fp12 values per lane: g_ref (8, 12, 16, B) -> (12, 16, B).
    Applied twice this reduces the 64 gathered window entries of a
    fixed-base GT exponentiation (gt_pow_fixed) — no squarings at all."""
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2)

    def load(w):
        return [(g_ref[w, 2 * k], g_ref[w, 2 * k + 1]) for k in range(6)]

    acc = load(0)
    for w in range(1, 8):
        acc = F12["mul"](acc, load(w))
    _f12_store(o_ref, acc)


def _f12_slotmul_kernel(m_ref, np_ref, c_ref, a_ref, o_ref,
                        *, conj_fp2: bool):
    """out[k] = (conj(a[k]) if conj_fp2 else a[k]) * c[k] — the shape of
    every Frobenius power on the flat tower (pairing._frob1/2/3) and of
    conj6 (constants (+-1)^k, conj_fp2=False). c_ref: (12, 16, LANES),
    constants pre-broadcast across lanes on the host (in-kernel constant
    broadcasts hit the unimplemented Mosaic sublane+lane path)."""
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    f = _f12_load(a_ref)
    out = []
    for k in range(6):
        c = (c_ref[2 * k], c_ref[2 * k + 1])
        x = F2["conj"](f[k]) if conj_fp2 else f[k]
        out.append(F2["mul"](x, c))
    _f12_store(o_ref, out)


_FROB_TILES = {}


def _frob_tiles(which) -> np.ndarray:
    """(12, 16, LANES) Montgomery Fp2 constants for frob1/2/3 or conj6,
    pre-broadcast across lanes."""
    if which in _FROB_TILES:
        return _FROB_TILES[which]
    from . import refimpl

    if which == "conj6":
        consts = [(1, 0) if k % 2 == 0 else (params.P - 1, 0)
                  for k in range(6)]
    else:
        e = {"frob1": 1, "frob2": 2, "frob3": 3}[which]
        g = refimpl.fp2_pow(params.XI, (params.P ** e - 1) // 6)
        consts, cur = [], (1, 0)
        for _k in range(6):
            consts.append(cur)
            cur = refimpl.fp2_mul(cur, g)
    rows = []
    for c in consts:
        for comp in c:
            rows.append(np.asarray(
                params.to_limbs(comp * params.R % params.P), dtype=np.uint32))
    _FROB_TILES[which] = np.broadcast_to(
        np.stack(rows)[:, :, None], (12, NL, LANES)).copy()
    return _FROB_TILES[which]


@functools.partial(jax.jit, static_argnames=("which", "interpret"))
def _f12_slotmul_flat(a, which: str, interpret: bool):
    N = a.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    c_in = jnp.asarray(_frob_tiles(which), dtype=jnp.uint32)
    io = _f12_io(n_tiles, Np, 1)
    io["in_specs"].insert(2, pl.BlockSpec((12, NL, LANES),
                                          lambda i: (0, 0, 0),
                                          memory_space=pltpu.VMEM))
    conj_fp2 = which in ("frob1", "frob3")
    with enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_f12_slotmul_kernel, conj_fp2=conj_fp2),
            interpret=interpret, **io)(m_in, np_in, c_in, _to_tiles(a, Np))
    return _from_tiles(out, N)


def f12_slotmul_flat(a, which: str):
    """Frobenius^e / conj6 on (N, 6, 2, 16): which in
    {frob1, frob2, frob3, conj6}."""
    return _f12_slotmul_flat(a, which, INTERPRET)


def _f12_io(n_tiles, Np, n_inputs):
    specs = [
        pl.BlockSpec((NL, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
    ]
    specs += [pl.BlockSpec((12, NL, LANES), lambda i: (0, 0, i),
                           memory_space=pltpu.VMEM)] * n_inputs
    return dict(
        grid=(n_tiles,),
        in_specs=specs,
        out_specs=pl.BlockSpec((12, NL, LANES), lambda i: (0, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12, NL, Np), jnp.uint32),
    )


def _to_tiles(f, Np):
    """(N, 6, 2, 16) -> (12, 16, Np)."""
    N = f.shape[0]
    return _pad_lanes(jnp.transpose(f.reshape(N, 12, NL), (1, 2, 0)), Np)


def _from_tiles(t, N):
    return jnp.transpose(t, (2, 0, 1))[:N].reshape(N, 6, 2, NL)


def _mnp():
    return (jnp.asarray(_M_FP[:, None], dtype=jnp.uint32),
            jnp.asarray([[_NPRIME_FP]], dtype=jnp.uint32))


@functools.partial(jax.jit, static_argnames="interpret")
def _f12_mul_flat(a, b, interpret: bool):
    N = a.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    with enable_x64(False):
        out = pl.pallas_call(_f12_mul_kernel, interpret=interpret,
                             **_f12_io(n_tiles, Np, 2))(
            m_in, np_in, _to_tiles(a, Np), _to_tiles(b, Np))
    return _from_tiles(out, N)


def f12_mul_flat(a, b):
    """(N, 6, 2, 16) x (N, 6, 2, 16) -> (N, 6, 2, 16)."""
    return _f12_mul_flat(a, b, INTERPRET)


@functools.partial(jax.jit, static_argnames="interpret")
def _f12_inv_flat(a, interpret: bool):
    N = a.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    bits_in = jnp.asarray(_pm2_bits_tiles(), dtype=jnp.uint32)
    io = _f12_io(n_tiles, Np, 1)
    io["in_specs"].insert(2, pl.BlockSpec(
        (len(_PM2_BITS), LANES), lambda i: (0, 0),
        memory_space=pltpu.VMEM))
    with enable_x64(False):
        out = pl.pallas_call(_f12_inv_kernel, interpret=interpret, **io)(
            m_in, np_in, bits_in, _to_tiles(a, Np))
    return _from_tiles(out, N)


def f12_inv_flat(a):
    return _f12_inv_flat(a, INTERPRET)


@functools.partial(jax.jit, static_argnames=("n_bits", "interpret"))
def _f12_pow_flat(f, k, n_bits: int, interpret: bool):
    N = f.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    one_in = jnp.asarray(np.asarray(
        params.to_limbs(params.R % params.P), dtype=np.uint32)[:, None], dtype=jnp.uint32)
    kt = _pad_lanes(jnp.transpose(k, (1, 0)), Np)
    io = _f12_io(n_tiles, Np, 1)
    # insert the one-column spec BEFORE the f12 input, append the exponent
    io["in_specs"].insert(2, pl.BlockSpec((NL, 1), lambda i: (0, 0),
                                          memory_space=pltpu.VMEM))
    io["in_specs"].append(pl.BlockSpec((NL, LANES), lambda i: (0, i),
                                       memory_space=pltpu.VMEM))
    with enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_f12_pow_kernel, n_bits=n_bits),
            scratch_shapes=[pltpu.VMEM((n_bits, LANES), jnp.uint32)],
            interpret=interpret, **io)(
            m_in, np_in, one_in, _to_tiles(f, Np), kt)
    return _from_tiles(out, N)


def f12_pow_flat(f, k, n_bits: int = 256):
    """f^k batched: f (N, 6, 2, 16), k (N, 16) plain limbs (LSB-first bits;
    n_bits < 256 truncates for exponents known to be short, e.g. |u| = 63)."""
    return _f12_pow_flat(f, k, n_bits, INTERPRET)


@functools.partial(jax.jit,
                   static_argnames=("n_bits", "wbits", "cyc", "interpret"))
def _f12_wpow_flat(f, k, n_bits: int, wbits: int, cyc: bool,
                   interpret: bool):
    N = f.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    n_win = (n_bits + wbits - 1) // wbits
    m_in, np_in = _mnp()
    one_in = jnp.asarray(np.asarray(
        params.to_limbs(params.R % params.P), dtype=np.uint32)[:, None], dtype=jnp.uint32)
    kt = _pad_lanes(jnp.transpose(k, (1, 0)), Np)
    io = _f12_io(n_tiles, Np, 1)
    io["in_specs"].insert(2, pl.BlockSpec((NL, 1), lambda i: (0, 0),
                                          memory_space=pltpu.VMEM))
    io["in_specs"].append(pl.BlockSpec((NL, LANES), lambda i: (0, i),
                                       memory_space=pltpu.VMEM))
    with enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_f12_wpow_kernel, n_bits=n_bits, wbits=wbits,
                              cyc=cyc),
            scratch_shapes=[pltpu.VMEM((n_win, LANES), jnp.uint32)],
            interpret=interpret, **io)(
            m_in, np_in, one_in, _to_tiles(f, Np), kt)
    return _from_tiles(out, N)


def f12_wpow_flat(f, k, n_bits: int = 256, wbits: int = 3,
                  cyc: bool = False):
    """Windowed f^k batched: f (N, 6, 2, 16), k (N, 16) plain limbs.
    cyc=True uses cyclotomic squarings (requires f ∈ GΦ12 — see kernel)."""
    return _f12_wpow_flat(f, k, n_bits, wbits, cyc, INTERPRET)


def _f12_csqr_kernel(m_ref, np_ref, a_ref, o_ref):
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    F12 = make_fp12(F2)
    _f12_store(o_ref, F12["csqr"](_f12_load(a_ref)))


@functools.partial(jax.jit, static_argnames="interpret")
def _f12_csqr_flat(a, interpret: bool):
    N = a.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    with enable_x64(False):
        out = pl.pallas_call(_f12_csqr_kernel, interpret=interpret,
                             **_f12_io(n_tiles, Np, 1))(
            m_in, np_in, _to_tiles(a, Np))
    return _from_tiles(out, N)


def f12_csqr_flat(a):
    """Cyclotomic squaring, (N, 6, 2, 16) -> (N, 6, 2, 16). Input MUST be
    in GΦ12 (pairing outputs after final exp are)."""
    return _f12_csqr_flat(a, INTERPRET)


@functools.partial(jax.jit, static_argnames="interpret")
def _f12_mulreduce8_flat(g, interpret: bool):
    N = g.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    gt = _pad_lanes(jnp.transpose(g.reshape(N, 8, 12, NL), (1, 2, 3, 0)), Np)
    io = _f12_io(n_tiles, Np, 0)
    io["in_specs"].append(pl.BlockSpec((8, 12, NL, LANES),
                                       lambda i: (0, 0, 0, i),
                                       memory_space=pltpu.VMEM))
    with enable_x64(False):
        out = pl.pallas_call(_f12_mulreduce8_kernel, interpret=interpret,
                             **io)(m_in, np_in, gt)
    return _from_tiles(out, N)


def f12_mulreduce8_flat(g):
    """(N, 8, 6, 2, 16) -> (N, 6, 2, 16): per-row product of 8 values."""
    return _f12_mulreduce8_flat(g, INTERPRET)


def window_digits(k, n_win: int = 64):
    """(..., 16) plain limbs -> (..., n_win) 4-bit window values, LSB-first."""
    outs = []
    for w in range(n_win):
        limb, s = divmod(4 * w, params.LIMB_BITS)
        outs.append((k[..., limb] >> np.uint32(s)) & np.uint32(0xF))
    return jnp.stack(outs, axis=-1)


def gt_pow_fixed(table, k):
    """base^k for a FIXED base via its precomputed window table.

    table: (64, 16, 6, 2, 16) with table[w][j] = base^(j * 16^w); k: (N, 16)
    plain limbs. Gathers one entry per window at the XLA level, then reduces
    the 64 entries with two passes of the 8-way product kernel — 63 Fp12
    muls and zero squarings per element (vs 256 sqr + 256 mul for the
    generic ladder). Used for gtB^t in proof creation and gtB^Zv in
    verification (range_proof.py), where the base e(B, B2) never changes.
    """
    N = k.shape[0]
    digs = window_digits(k)                     # (N, 64)
    g = table[jnp.arange(64)[None, :], digs]    # (N, 64, 6, 2, 16)
    r1 = f12_mulreduce8_flat(g.reshape(N * 8, 8, 6, 2, NL))
    return f12_mulreduce8_flat(r1.reshape(N, 8, 6, 2, NL))


def gt_pow_fixed_multi(tables, base_idx, k):
    """bases[base_idx]^k where every element selects one of a SMALL set of
    fixed bases, each with a precomputed window table.

    tables: (NB, 64, 16, 6, 2, 16) — per-base 4-bit window tables
    (tables[b][w][j] = base_b^(j * 16^w)); base_idx: (N,) int32;
    k: (N, 16) plain limbs. Same 63-mul/zero-squaring reduction as
    gt_pow_fixed, reusing the mulreduce8 kernel. This is the creation-side
    digit pow gtA[i][phi]^(-s v): only ns*u distinct bases exist, so the
    one-time table build (host oracle, cached per signature set) amortizes
    over every proof — ~2.7x fewer Montgomery muls than even the
    cyclotomic windowed pow chain."""
    N = k.shape[0]
    digs = window_digits(k)                     # (N, 64)
    g = tables[base_idx[:, None], jnp.arange(64)[None, :], digs]
    r1 = f12_mulreduce8_flat(g.reshape(N * 8, 8, 6, 2, NL))
    return f12_mulreduce8_flat(r1.reshape(N, 8, 6, 2, NL))


# ---------------------------------------------------------------------------
# Field inversion kernels (Fermat chains; replace the sequential
# Montgomery-trick batch inversion, which scans over the BATCH axis and
# crawls on TPU) + G2 windowed scalar-mult ladder
# ---------------------------------------------------------------------------

def _fp_inv_kernel(m_ref, np_ref, bits_ref, x_ref, o_ref):
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    o_ref[:] = _fermat_inv_rolled(x_ref[:], F2["fp_mul"], bits_ref)


def _inv_bits_spec():
    return pl.BlockSpec((len(_PM2_BITS), LANES), lambda i: (0, 0),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnames="interpret")
def _fp_inv_flat(x, interpret: bool):
    N = x.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    bits_in = jnp.asarray(_pm2_bits_tiles(), dtype=jnp.uint32)
    xt = _pad_lanes(jnp.transpose(x, (1, 0)), Np)
    with enable_x64(False):
        out = pl.pallas_call(
            _fp_inv_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((NL, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                _inv_bits_spec(),
                pl.BlockSpec((NL, LANES), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((NL, LANES), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((NL, Np), jnp.uint32),
            interpret=interpret,
        )(m_in, np_in, bits_in, xt)
    return jnp.transpose(out, (1, 0))[:N]


def fp_inv_flat(x):
    """x^(p-2) batched: (N, 16) Montgomery -> (N, 16) Montgomery."""
    return _fp_inv_flat(x, INTERPRET)


def _f2_inv_kernel(m_ref, np_ref, bits_ref, a_ref, o_ref):
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    a = (a_ref[0], a_ref[1])
    # norm = a0^2 + a1^2; inv via Fermat; out = (a0*ni, -a1*ni)
    n = F2["fp_add"](F2["fp_mul"](a[0], a[0]), F2["fp_mul"](a[1], a[1]))
    acc = _fermat_inv_rolled(n, F2["fp_mul"], bits_ref)
    z = jnp.zeros_like(a[1])
    o_ref[0] = F2["fp_mul"](a[0], acc)
    o_ref[1] = F2["fp_mul"](F2["fp_sub"](z, a[1]), acc)


@functools.partial(jax.jit, static_argnames="interpret")
def _f2_inv_flat(a, interpret: bool):
    N = a.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    m_in, np_in = _mnp()
    bits_in = jnp.asarray(_pm2_bits_tiles(), dtype=jnp.uint32)
    at = _pad_lanes(jnp.transpose(a, (1, 2, 0)), Np)
    with enable_x64(False):
        out = pl.pallas_call(
            _f2_inv_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((NL, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                _inv_bits_spec(),
                pl.BlockSpec((2, NL, LANES), lambda i: (0, 0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((2, NL, LANES), lambda i: (0, 0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((2, NL, Np), jnp.uint32),
            interpret=interpret,
        )(m_in, np_in, bits_in, at)
    return jnp.transpose(out, (2, 0, 1))[:N]


def f2_inv_flat(a):
    """Fp2 inverse batched: (N, 2, 16) Montgomery -> (N, 2, 16)."""
    return _f2_inv_flat(a, INTERPRET)


def _f2_is_zero(a):
    from .pallas_ops import fis_zero

    return fis_zero(a[0]) & fis_zero(a[1])


def make_g2_group(F2):
    """Complete Jacobian group law on the twist (Fp2 tiles); mirrors
    pallas_ops.make_group with Fp2 arithmetic and crypto/g2.py formulas."""

    def sel(cond, p, q):
        c = cond[None, :]
        return tuple((jnp.where(c, a[0], b[0]), jnp.where(c, a[1], b[1]))
                     for a, b in zip(p, q))

    def inf_like(p):
        one = jnp.ones((1,) + p[0][0].shape[1:], jnp.uint32)
        zeros = jnp.zeros((NL - 1,) + p[0][0].shape[1:], jnp.uint32)
        X0 = jnp.concatenate([one, zeros], axis=0)
        zt = jnp.zeros_like(p[0][0])
        return ((X0, zt), (X0, zt), (zt, zt))

    def pdouble(p):
        X, Y, Z = p
        A = F2["sqr"](X)
        Bv = F2["sqr"](Y)
        Cv = F2["sqr"](Bv)
        t = F2["sub"](F2["sqr"](F2["add"](X, Bv)), F2["add"](A, Cv))
        D = F2["add"](t, t)
        E = F2["add"](F2["add"](A, A), A)
        Fv = F2["sqr"](E)
        X3 = F2["sub"](Fv, F2["add"](D, D))
        C2 = F2["add"](Cv, Cv)
        C8 = F2["add"](F2["add"](C2, C2), F2["add"](C2, C2))
        Y3 = F2["sub"](F2["mul"](E, F2["sub"](D, X3)), C8)
        YZ = F2["mul"](Y, Z)
        Z3 = F2["add"](YZ, YZ)
        return (X3, Y3, Z3)

    def padd(p, q):
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        Z1Z1 = F2["sqr"](Z1)
        Z2Z2 = F2["sqr"](Z2)
        U1 = F2["mul"](X1, Z2Z2)
        U2 = F2["mul"](X2, Z1Z1)
        S1 = F2["mul"](Y1, F2["mul"](Z2, Z2Z2))
        S2 = F2["mul"](Y2, F2["mul"](Z1, Z1Z1))
        H = F2["sub"](U2, U1)
        HH = F2["add"](H, H)
        I = F2["sqr"](HH)
        J = F2["mul"](H, I)
        r = F2["sub"](S2, S1)
        r = F2["add"](r, r)
        V = F2["mul"](U1, I)
        X3 = F2["sub"](F2["sub"](F2["sqr"](r), J), F2["add"](V, V))
        SJ = F2["mul"](S1, J)
        Y3 = F2["sub"](F2["mul"](r, F2["sub"](V, X3)), F2["add"](SJ, SJ))
        t1 = F2["add"](Z1, Z2)
        ZZ = F2["sub"](F2["sub"](F2["sqr"](t1), Z1Z1), Z2Z2)
        Z3 = F2["mul"](ZZ, H)
        res = (X3, Y3, Z3)

        p_inf = _f2_is_zero(Z1)
        q_inf = _f2_is_zero(Z2)
        h0 = _f2_is_zero(H)
        r0 = _f2_is_zero(r)
        res = sel(h0 & r0 & ~p_inf & ~q_inf, pdouble(p), res)
        res = sel(h0 & ~r0 & ~p_inf & ~q_inf, inf_like(p), res)
        res = sel(q_inf, p, res)
        res = sel(p_inf, q, res)
        return res

    return pdouble, padd, inf_like


def _g2_scalar_mul_kernel(m_ref, np_ref, p_ref, k_ref, o_ref, dig_ref):
    """Windowed (4-bit) ladder on the twist — the Fp2 analogue of
    pallas_ops._scalar_mul_kernel. p_ref: (6, 16, B) = (X0,X1,Y0,Y1,Z0,Z1)
    Jacobian Montgomery; k_ref: (16, B) plain scalars."""
    F2 = make_fp2(m_ref[:], np_ref[0, 0])
    pdouble, padd, inf_like = make_g2_group(F2)

    P = ((p_ref[0], p_ref[1]), (p_ref[2], p_ref[3]), (p_ref[4], p_ref[5]))
    k = k_ref[:]

    tab = [inf_like(P), P]
    for d in range(2, 16):
        tab.append(pdouble(tab[d // 2]) if d % 2 == 0
                   else padd(tab[d - 1], P))
    # (16, 6, 16, B) stacked coordinate components for per-lane select
    comp = [jnp.stack([t[c][i] for t in tab])
            for c in range(3) for i in range(2)]

    rows = []
    for w in range(63, -1, -1):
        limb, s = divmod(w, 4)
        rows.append((k[limb] >> np.uint32(4 * s)) & np.uint32(0xF))
    dig_ref[:] = jnp.stack(rows)              # (64, B) MSB first

    def select(d):
        accs = [c[0] for c in comp]
        for v in range(1, 16):
            mask = (d == v)[None, :]
            accs = [jnp.where(mask, c[v], a) for c, a in zip(comp, accs)]
        return ((accs[0], accs[1]), (accs[2], accs[3]), (accs[4], accs[5]))

    acc0 = select(dig_ref[0])

    def body(w, acc):
        acc = pdouble(pdouble(pdouble(pdouble(acc))))
        d = dig_ref[pl.ds(w, 1), :][0]
        return padd(acc, select(d))

    acc = jax.lax.fori_loop(jnp.int32(1), jnp.int32(64), body, acc0)
    o_ref[0], o_ref[1] = acc[0]
    o_ref[2], o_ref[3] = acc[1]
    o_ref[4], o_ref[5] = acc[2]


@functools.partial(jax.jit, static_argnames="interpret")
def _g2_scalar_mul_flat(p, k, interpret: bool):
    N = p.shape[0]
    n_tiles = max((N + LANES - 1) // LANES, 1)
    Np = n_tiles * LANES
    pt = _pad_lanes(jnp.transpose(p.reshape(N, 6, NL), (1, 2, 0)), Np)
    kt = _pad_lanes(jnp.transpose(k, (1, 0)), Np)
    m_in, np_in = _mnp()
    with enable_x64(False):
        out = pl.pallas_call(
            _g2_scalar_mul_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((NL, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((6, NL, LANES), lambda i: (0, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((NL, LANES), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((6, NL, LANES), lambda i: (0, 0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((6, NL, Np), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((64, LANES), jnp.uint32)],
            interpret=interpret,
        )(m_in, np_in, pt, kt)
    return jnp.transpose(out, (2, 0, 1))[:N].reshape(N, 3, 2, NL)


def g2_scalar_mul_flat(p, k):
    """k*Q batched: p (N, 3, 2, 16) Jacobian Montgomery, k (N, 16) plain
    scalars -> (N, 3, 2, 16)."""
    return _g2_scalar_mul_flat(p, k, INTERPRET)


# ---------------------------------------------------------------------------
# Full pairing: miller kernel + final exp (kernels + light jnp glue)
# ---------------------------------------------------------------------------

_U_LIMBS = None


def _u_limbs(N):
    global _U_LIMBS
    if _U_LIMBS is None:
        _U_LIMBS = np.asarray(params.to_limbs(params.U), dtype=np.uint32)
    return jnp.broadcast_to(jnp.asarray(_U_LIMBS, dtype=jnp.uint32), (N, NL))


def final_exp_flat(f):
    """Reduced pairing final exponentiation, batched (N, 6, 2, 16).

    Same structure as pairing.final_exp: easy part, then the DSD hard part
    with 3 exponentiations by u (63-bit pow kernel) + Frobenius maps (jnp —
    conjugation and 6 constant Fp2 muls are cheap) + the Olivos chain via
    the mul kernel. After the easy part every operand lives in GΦ12(p)
    (f^((p^6-1)(p^2+1)) kills the rest of the group order), so the u-pows
    and all explicit squarings use cyclotomic squarings — 2x per squaring.
    """
    N = f.shape[0]

    def frob(g, which: int):
        return f12_slotmul_flat(g, f"frob{which}")

    def conj(g):
        return f12_slotmul_flat(g, "conj6")

    mul = f12_mul_flat
    u = _u_limbs(N)

    f1 = mul(conj(f), f12_inv_flat(f))
    f2 = mul(frob(f1, 2), f1)

    fx = f12_wpow_flat(f2, u, n_bits=params.U.bit_length(), cyc=True)
    fx2 = f12_wpow_flat(fx, u, n_bits=params.U.bit_length(), cyc=True)
    fx3 = f12_wpow_flat(fx2, u, n_bits=params.U.bit_length(), cyc=True)

    y0 = mul(mul(frob(f2, 1), frob(f2, 2)), frob(f2, 3))
    y1 = conj(f2)
    y2 = frob(fx2, 2)
    y3 = conj(frob(fx, 1))
    y4 = conj(mul(fx, frob(fx2, 1)))
    y5 = conj(fx2)
    y6 = conj(mul(fx3, frob(fx3, 1)))

    sqr = f12_csqr_flat
    t0 = mul(mul(sqr(y6), y4), y5)
    t1 = mul(mul(y3, y5), t0)
    t0 = mul(t0, y2)
    t1 = mul(sqr(t1), t0)
    t1 = sqr(t1)
    t0b = mul(t1, y1)
    t1 = mul(t1, y0)
    t0b = sqr(t0b)
    return mul(t0b, t1)


def pair_flat(px, py, qx, qy):
    """Full reduced optimal ate pairing, batched flat inputs:
    px, py (N, 16); qx, qy (N, 2, 16) -> (N, 6, 2, 16)."""
    return final_exp_flat(miller_flat(px, py, qx, qy))


__all__ = ["miller_flat", "f12_mul_flat", "f12_inv_flat", "f12_pow_flat",
           "f12_wpow_flat", "f12_csqr_flat", "f12_mulreduce8_flat",
           "f12_slotmul_flat", "final_exp_flat", "pair_flat",
           "fp_inv_flat", "f2_inv_flat", "g2_scalar_mul_flat",
           "gt_pow_fixed", "window_digits"]
