"""bn256 curve parameters for the Drynx-TPU crypto stack.

The reference fixes the whole system's suite to the bn256 pairing curve
(reference: lib/suite.go:10-20, `bn256.NewSuiteG1()`); this module pins the
same curve: the 256-bit Barreto-Naehrig curve used by kyber/golang bn256,

    p = 36u^4 + 36u^3 + 24u^2 + 6u + 1
    n = 36u^4 + 36u^3 + 18u^2 + 6u + 1   (group order)
    u = 6518589491078791937

with E(Fp): y^2 = x^3 + 3 and generator G1 = (1, 2).

Tower choices here are OURS (the framework only needs internal consistency,
not kyber wire compatibility):

  Fp2  = Fp[i]/(i^2 + 1)          (valid: p = 3 mod 4)
  Fp12 = Fp2[w]/(w^6 - XI)        (flat sextic extension; XI verified to be
                                   neither a square nor a cube in Fp2)
  twist E'(Fp2): y^2 = x^3 + 3/XI  (D-type sextic twist; G2 = E'(Fp2)[n])

Limb layout for the device-side (JAX) representation: 256-bit integers as
16 little-endian limbs of 16 bits each, stored in uint32 lanes, Montgomery
form with R = 2^256.
"""

# BN parameter
U = 6518589491078791937

# Field prime and group order (match kyber bn256 / golang.org/x/crypto/bn256).
P = 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1
N = 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1

assert P == 65000549695646603732796438742359905742825358107623003571877145026864184071783
assert N == 65000549695646603732796438742359905742570406053903786389881062969044166799969
assert P % 4 == 3  # so Fp2 = Fp[i]/(i^2+1) is a field

# Curve coefficient and G1 generator (y^2 = x^3 + B).
B = 3
G1_GEN = (1, 2)

# Frobenius trace: #E(Fp) = p + 1 - t = n
TRACE = 6 * U**2 + 1
assert P + 1 - TRACE == N

# Twist curve order over Fp2 for the D-type sextic twist y^2 = x^3 + 3/XI:
# with t2 = t^2 - 2p (trace of E over Fp2) and f2 = sqrt((4p^2 - t2^2)/3),
# #E'(Fp2) = p^2 + 1 - (t2 + 3*f2)/2  (verified empirically; divisible by N).
_T2 = TRACE * TRACE - 2 * P
_F2 = 65000549695646603729472583186153816235393533837839825629408311602454630816845
assert 3 * _F2 * _F2 == 4 * P * P - _T2 * _T2
TWIST_ORDER = P * P + 1 - (_T2 + 3 * _F2) // 2
assert TWIST_ORDER % N == 0
TWIST_COFACTOR = TWIST_ORDER // N

# ---------------------------------------------------------------------------
# Limb layout (device representation)
# ---------------------------------------------------------------------------
LIMB_BITS = 16
NUM_LIMBS = 16  # 256 bits
LIMB_MASK = (1 << LIMB_BITS) - 1

# Montgomery constants, R = 2^256
R = 1 << (LIMB_BITS * NUM_LIMBS)
R_MOD_P = R % P
R2_MOD_P = (R * R) % P
R3_MOD_P = (R * R * R) % P
# -p^-1 mod 2^16 (per-limb Montgomery factor)
NPRIME = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

# Same layout reused for the scalar field (mod N) where needed.
R_MOD_N = R % N
R2_MOD_N = (R * R) % N
NPRIME_N = (-pow(N, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def to_limbs(x: int, num=NUM_LIMBS) -> list:
    """Little-endian 16-bit limb decomposition of a non-negative int."""
    return [(x >> (LIMB_BITS * k)) & LIMB_MASK for k in range(num)]


def from_limbs(limbs) -> int:
    out = 0
    for k, l in enumerate(limbs):
        out |= int(l) << (LIMB_BITS * k)
    return out


# ---------------------------------------------------------------------------
# Fp2 / Fp12 tower constants
# ---------------------------------------------------------------------------
def _fp2_mul(a, b):
    # (a0 + a1 i)(b0 + b1 i) with i^2 = -1
    return ((a[0] * b[0] - a[1] * b[1]) % P, (a[0] * b[1] + a[1] * b[0]) % P)


def _fp2_pow(a, e):
    r = (1, 0)
    while e:
        if e & 1:
            r = _fp2_mul(r, a)
        a = _fp2_mul(a, a)
        e >>= 1
    return r


def _find_xi():
    """Smallest xi = a + i with xi neither square nor cube in Fp2."""
    half = (P * P - 1) // 2
    third = (P * P - 1) // 3
    assert (P * P - 1) % 3 == 0
    for a in range(1, 64):
        xi = (a, 1)
        if _fp2_pow(xi, half) != (1, 0) and _fp2_pow(xi, third) != (1, 0):
            return xi
    raise AssertionError("no xi found")


# Sextic non-residue defining Fp12 = Fp2[w]/(w^6 - XI); also defines the twist.
XI = _find_xi()

# Tate-pairing final exponent, split for efficiency later; exact division holds.
assert (P**12 - 1) % N == 0
FINAL_EXP = (P**12 - 1) // N

__all__ = [
    "U", "P", "N", "B", "G1_GEN", "TRACE", "TWIST_COFACTOR",
    "LIMB_BITS", "NUM_LIMBS", "LIMB_MASK", "R", "R_MOD_P", "R2_MOD_P",
    "R3_MOD_P", "NPRIME", "R_MOD_N", "R2_MOD_N", "NPRIME_N",
    "to_limbs", "from_limbs", "XI", "FINAL_EXP",
]
