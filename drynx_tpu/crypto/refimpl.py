"""Pure-Python (bigint) reference implementation of the bn256 crypto stack.

This is the correctness oracle for every device-side (JAX/Pallas) kernel in
`drynx_tpu.crypto`: each batched limb-tensor op must agree with the functions
here on random inputs.  It is also used host-side for cheap, non-batched work
(key generation, G2 signature setup for range proofs).

Mirrors the capabilities drynx pulls from kyber's bn256 suite
(reference: lib/suite.go:10-20; lib/range/range_proof.go:326-417 uses G1/G2
pairings; lib/proof/structs_proofs.go:498-505 uses Schnorr on G1).

Representation conventions:
  Fp    : int in [0, P)
  Fp2   : tuple (a0, a1) = a0 + a1*i,  i^2 = -1
  Fp12  : tuple of 6 Fp2 coeffs (c0..c5) = sum c_k w^k,  w^6 = XI
  G1    : affine (x, y) ints, or None for the point at infinity
  G2    : affine (x, y) Fp2 pairs on the twist y^2 = x^3 + 3/XI, or None
"""

from . import params
from .params import P, N, B, XI

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------

def fp_inv(a):
    return pow(a, P - 2, P)


def fp_sqrt(a):
    """Square root in Fp (p = 3 mod 4); returns None if a is not a QR."""
    a %= P
    if a == 0:
        return 0
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a else None


# ---------------------------------------------------------------------------
# Fp2 = Fp[i]/(i^2+1)
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fp2_mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P, (a[0] * b[1] + a[1] * b[0]) % P)


def fp2_muls(a, s):
    """Multiply by an Fp scalar."""
    return (a[0] * s % P, a[1] * s % P)


def fp2_sq(a):
    # (a0+a1 i)^2 = (a0^2 - a1^2) + 2 a0 a1 i
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, 2 * a[0] * a[1] % P)


def fp2_inv(a):
    # 1/(a0 + a1 i) = (a0 - a1 i)/(a0^2 + a1^2)
    norm_inv = fp_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * norm_inv % P, -a[1] * norm_inv % P)


def fp2_pow(a, e):
    r = FP2_ONE
    while e:
        if e & 1:
            r = fp2_mul(r, a)
        a = fp2_sq(a)
        e >>= 1
    return r


def fp2_sqrt(a):
    """Square root in Fp2 via the norm method; None if not a QR."""
    if a == FP2_ZERO:
        return FP2_ZERO
    a0, a1 = a
    if a1 == 0:
        r = fp_sqrt(a0)
        if r is not None:
            return (r, 0)
        # sqrt(a0) = sqrt(-a0) * sqrt(-1) = sqrt(-a0) * i  (i^2 = -1)
        r = fp_sqrt(-a0 % P)
        return None if r is None else (0, r)
    alpha = (a0 * a0 + a1 * a1) % P  # norm
    lam = fp_sqrt(alpha)
    if lam is None:
        return None
    inv2 = fp_inv(2)
    delta = (a0 + lam) * inv2 % P
    x0 = fp_sqrt(delta)
    if x0 is None:
        delta = (a0 - lam) * inv2 % P
        x0 = fp_sqrt(delta)
        if x0 is None:
            return None
    x1 = a1 * fp_inv(2 * x0 % P) % P
    cand = (x0, x1)
    return cand if fp2_sq(cand) == (a0 % P, a1 % P) else None


# Twist coefficient b' = 3 / XI
B2 = fp2_muls(fp2_inv(XI), B)

# ---------------------------------------------------------------------------
# Fp12 = Fp2[w]/(w^6 - XI)
# ---------------------------------------------------------------------------

FP12_ONE = (FP2_ONE,) + (FP2_ZERO,) * 5
FP12_ZERO = (FP2_ZERO,) * 6


def fp12_mul(a, b):
    acc = [FP2_ZERO] * 11
    for j in range(6):
        bj = b[j]
        if bj == FP2_ZERO:
            continue
        for k in range(6):
            if a[k] == FP2_ZERO:
                continue
            acc[j + k] = fp2_add(acc[j + k], fp2_mul(a[k], bj))
    out = list(acc[:6])
    for k in range(6, 11):
        out[k - 6] = fp2_add(out[k - 6], fp2_mul(acc[k], XI))
    return tuple(out)


def fp12_sq(a):
    return fp12_mul(a, a)


def fp12_pow(a, e):
    r = FP12_ONE
    while e:
        if e & 1:
            r = fp12_mul(r, a)
        a = fp12_sq(a)
        e >>= 1
    return r


def fp12_csqr(f):
    """Granger-Scott cyclotomic squaring (eprint 2009/565 §3.2) on the flat
    tower — the INT twin of the Mosaic kernel's formulas (pallas_pairing
    make_fp12 f12csqr; parity asserted in tests/test_pairing.py). Valid
    ONLY for f in GΦ12(p); 9 Fp2 squarings vs fp12_sq's 36 Fp2 mults, so
    the host-oracle order-n gate pow halves its squaring bill."""
    f0, f1, f2, f3, f4, f5 = f
    t0 = fp2_sq(f3)
    t1 = fp2_sq(f0)
    t6 = fp2_sub(fp2_sub(fp2_sq(fp2_add(f3, f0)), t0), t1)
    t2 = fp2_sq(f4)
    t3 = fp2_sq(f1)
    t7 = fp2_sub(fp2_sub(fp2_sq(fp2_add(f4, f1)), t2), t3)
    t4 = fp2_sq(f5)
    t5 = fp2_sq(f2)
    t8 = fp2_mul(fp2_sub(fp2_sub(fp2_sq(fp2_add(f5, f2)), t4), t5), XI)
    t0 = fp2_add(fp2_mul(t0, XI), t1)
    t2 = fp2_add(fp2_mul(t2, XI), t3)
    t4 = fp2_add(fp2_mul(t4, XI), t5)

    def out_sub(t, x):            # 3t - 2x
        d = fp2_sub(t, x)
        return fp2_add(fp2_add(d, d), t)

    def out_add(t, x):            # 3t + 2x
        s = fp2_add(t, x)
        return fp2_add(fp2_add(s, s), t)

    return (out_sub(t0, f0), out_add(t8, f1), out_sub(t2, f2),
            out_add(t6, f3), out_sub(t4, f4), out_add(t7, f5))


def fp12_cyc_pow(f, e):
    """f^e via cyclotomic squarings — REQUIRES f in GΦ12 (callers gate)."""
    r = FP12_ONE
    while e:
        if e & 1:
            r = fp12_mul(r, f)
        f = fp12_csqr(f)
        e >>= 1
    return r


def fp12_conj6(a):
    """a^(p^6): conjugation w -> -w (negate odd coefficients)."""
    return tuple(fp2_neg(c) if k % 2 else c for k, c in enumerate(a))


def fp12_inv(a):
    # Norm to Fp6 trick is overkill for an oracle; use Fermat.
    return fp12_pow(a, P**12 - 2)


# ---------------------------------------------------------------------------
# G1: E(Fp) y^2 = x^3 + 3
# ---------------------------------------------------------------------------

def g1_is_on_curve(pt):
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B) % P == 0


def g1_neg(pt):
    return None if pt is None else (pt[0], -pt[1] % P)


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * fp_inv(2 * y1 % P) % P
    else:
        lam = (y2 - y1) * fp_inv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_mul(pt, k):
    k %= N
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = g1_add(acc, add)
        add = g1_add(add, add)
        k >>= 1
    return acc


G1 = params.G1_GEN
assert g1_is_on_curve(G1) and g1_mul(G1, N) is None


# ---------------------------------------------------------------------------
# G2: twist E'(Fp2) y^2 = x^3 + 3/XI, order-n subgroup
# ---------------------------------------------------------------------------

def g2_is_on_curve(pt):
    if pt is None:
        return True
    x, y = pt
    return fp2_sub(fp2_sq(y), fp2_add(fp2_mul(fp2_sq(x), x), B2)) == FP2_ZERO


def g2_neg(pt):
    return None if pt is None else (pt[0], fp2_neg(pt[1]))


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        lam = fp2_mul(fp2_muls(fp2_sq(x1), 3), fp2_inv(fp2_muls(y1, 2)))
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sq(lam), x1), x2)
    y3 = fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_mul_raw(pt, k):
    """Scalar mult WITHOUT mod-N reduction (for order checks)."""
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = g2_add(acc, add)
        add = g2_add(add, add)
        k >>= 1
    return acc


def g2_mul(pt, k):
    return g2_mul_raw(pt, k % N)


def _find_g2_generator():
    """Deterministic generator of E'(Fp2)[n] (hashless small-x search)."""
    for xa in range(1, 1000):
        for xb in (0, 1):
            x = (xa, xb)
            rhs = fp2_add(fp2_mul(fp2_sq(x), x), B2)
            y = fp2_sqrt(rhs)
            if y is None:
                continue
            q = g2_mul_raw((x, y), params.TWIST_COFACTOR)
            if q is not None and g2_mul_raw(q, N) is None:
                return q
    raise AssertionError("no G2 generator found")


G2 = _find_g2_generator()
assert g2_is_on_curve(G2)


# ---------------------------------------------------------------------------
# Pairing: Tate pairing e: G1 x G2 -> GT (Fp12), with denominator elimination.
# ---------------------------------------------------------------------------

def untwist(q):
    """Map a twist point (x, y) in E'(Fp2) to E(Fp12): (x*w^2, y*w^3)."""
    x, y = q
    xq = [FP2_ZERO] * 6
    yq = [FP2_ZERO] * 6
    xq[2] = x
    yq[3] = y
    return tuple(xq), tuple(yq)


def _line_value(t, p_aff, xq12, yq12, tangent):
    """Line through t (and p_aff, or tangent at t), evaluated at untwisted Q.

    All slope arithmetic is in Fp (t, p_aff are G1 points); the evaluated
    value is a sparse Fp12 element. Vertical lines return 1 (denominator
    elimination: values in Fp6 are killed by the final exponentiation).
    """
    xt, yt = t
    if tangent:
        lam = 3 * xt * xt * fp_inv(2 * yt % P) % P
    else:
        xp, yp = p_aff
        if (xt - xp) % P == 0:
            return None  # vertical line: contributes 1
        lam = (yt - yp) * fp_inv((xt - xp) % P) % P
    # l(Q) = yQ - yt - lam*(xQ - xt); yQ = y*w^3, xQ = x*w^2 components.
    out = [FP2_ZERO] * 6
    out[0] = ((lam * xt - yt) % P, 0)
    out[2] = fp2_muls(xq12, -lam % P)
    out[3] = yq12
    return tuple(out)


def miller_loop(p1, q2):
    """f_{N,P}(Q) for P in G1, Q in G2 (untwisted on the fly)."""
    xq, yq = q2  # twist coords in Fp2
    t = p1
    f = FP12_ONE
    for bit in bin(N)[3:]:  # from second-most-significant bit down
        line = _line_value(t, None, xq, yq, tangent=True)
        f = fp12_sq(f)
        if line is not None:
            f = fp12_mul(f, line)
        t = g1_add(t, t)
        if bit == "1":
            line = _line_value(t, p1, xq, yq, tangent=False)
            if line is not None:
                f = fp12_mul(f, line)
            t = g1_add(t, p1)
    return f


def final_exp(f):
    return fp12_pow(f, params.FINAL_EXP)


def pair_tate(p1, q2):
    """Reduced Tate pairing e(P, Q); P in G1, Q in G2 (twist coords)."""
    if p1 is None or q2 is None:
        return FP12_ONE
    return final_exp(miller_loop(p1, q2))


# ---------------------------------------------------------------------------
# Optimal ate pairing (the production pairing; loop length 6u+2 — 65 steps
# instead of the 255-bit Tate loop). Both are non-degenerate bilinear maps
# G1 x G2 -> mu_n; the whole stack (BB signatures, range proofs) only needs
# bilinearity + consistency, so device and oracle both use the ate variant.
# ---------------------------------------------------------------------------

ATE_LOOP = 6 * params.U + 2

# G2 Frobenius constants: untwist (x,y)->(x w^2, y w^3); w^(p-1) = XI^((p-1)/6).
_G12 = fp2_pow(params.XI, (params.P - 1) // 3)   # acts on x
_G13 = fp2_pow(params.XI, (params.P - 1) // 2)   # acts on y
_G22 = fp2_pow(params.XI, (params.P * params.P - 1) // 3)
# XI is a non-square in Fp2, so XI^((p^2-1)/2) = -1: -pi^2(Q) = (x*G22, y).


def twist_frob(q):
    """pi(x, y) = (conj(x)*XI^((p-1)/3), conj(y)*XI^((p-1)/2)) on the twist."""
    x, y = q
    return (fp2_mul((x[0], (-x[1]) % P), _G12),
            fp2_mul((y[0], (-y[1]) % P), _G13))


def _ate_line(t, q, p_aff, tangent):
    """Line through twist points t (and q, or tangent at t), evaluated at
    untwisted coordinates of P in G1: l = yp - lam*xp*w + (lam*xt - yt)*w^3."""
    xt, yt = t
    xp, yp = p_aff
    if tangent:
        lam = fp2_mul(fp2_muls(fp2_sq(xt), 3), fp2_inv(fp2_muls(yt, 2)))
    else:
        xq, yq = q
        if xt == xq:
            return None  # vertical: contributes an Fp2 factor, dies in FE
        lam = fp2_mul(fp2_sub(yt, yq), fp2_inv(fp2_sub(xt, xq)))
    out = [FP2_ZERO] * 6
    out[0] = (yp % P, 0)
    out[1] = fp2_muls(lam, (-xp) % P)
    out[3] = fp2_sub(fp2_mul(lam, xt), yt)
    return tuple(out)


def ate_miller_loop(p1, q2):
    """f_{6u+2,Q}(P) * l_{[6u+2]Q,pi(Q)}(P) * l_{[6u+2]Q+pi(Q),-pi^2(Q)}(P)."""
    t = q2
    f = FP12_ONE
    for bit in bin(ATE_LOOP)[3:]:
        f = fp12_sq(f)
        line = _ate_line(t, None, p1, tangent=True)
        f = fp12_mul(f, line)
        t = g2_add(t, t)
        if bit == "1":
            line = _ate_line(t, q2, p1, tangent=False)
            if line is not None:
                f = fp12_mul(f, line)
            t = g2_add(t, q2)
    q1 = twist_frob(q2)
    neg_q2 = (fp2_mul(q2[0], _G22), q2[1])
    line = _ate_line(t, q1, p1, tangent=False)
    if line is not None:
        f = fp12_mul(f, line)
    t = g2_add(t, q1)
    line = _ate_line(t, neg_q2, p1, tangent=False)
    if line is not None:
        f = fp12_mul(f, line)
    return f


def pair(p1, q2):
    """Reduced optimal ate pairing e(P, Q); P in G1, Q in G2 (twist coords)."""
    if p1 is None or q2 is None:
        return FP12_ONE
    return final_exp(ate_miller_loop(p1, q2))


def gphi12_cofactor_element(q: int = 13):
    """An order-q root of unity in GΦ12's COFACTOR subgroup — the element a
    commit-first RLC forger would inject (q must divide Φ12(p)/n; 13 and
    2749 do for this curve). It passes cyclotomic membership
    (batching.gt_membership_ok) but must fail the order-n gate
    (batching.gt_order_ok); both gate tests derive their adversarial input
    from THIS one construction so the curve fact lives in one place."""
    from . import params

    P_, N_ = params.P, params.N
    phi12 = P_**4 - P_**2 + 1
    assert phi12 % N_ == 0 and (phi12 // N_) % q == 0, \
        f"{q} does not divide the GΦ12 cofactor"
    for seed in (3, 5, 7):
        x = tuple((pow(seed, k + 2, P_), pow(seed + 1, k + 3, P_))
                  for k in range(6))
        g = fp12_pow(x, (P_**12 - 1) // phi12)    # project into GΦ12
        cand = fp12_pow(g, phi12 // q)            # kill the order-n part
        if cand != FP12_ONE:
            assert fp12_pow(cand, q) == FP12_ONE
            return cand
    raise AssertionError(f"no order-{q} element found (prob (1/{q})^3)")


__all__ = [
    "fp_inv", "fp_sqrt",
    "fp2_add", "fp2_sub", "fp2_neg", "fp2_mul", "fp2_muls", "fp2_sq",
    "fp2_inv", "fp2_pow", "fp2_sqrt", "FP2_ZERO", "FP2_ONE", "B2",
    "fp12_mul", "fp12_sq", "fp12_pow", "fp12_csqr", "fp12_cyc_pow",
    "fp12_conj6", "fp12_inv",
    "FP12_ONE", "FP12_ZERO",
    "g1_is_on_curve", "g1_neg", "g1_add", "g1_mul", "G1",
    "g2_is_on_curve", "g2_neg", "g2_add", "g2_mul", "G2",
    "untwist", "miller_loop", "final_exp", "pair", "pair_tate",
    "ate_miller_loop", "twist_frob", "ATE_LOOP",
    "gphi12_cofactor_element",
]
