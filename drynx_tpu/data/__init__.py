"""Datasets + test-data generation (reference data/ directory)."""
from .generator import (  # noqa: F401
    create_random_good_test_data,
    synthetic_classification_csv,
    load_label_csv,
)
