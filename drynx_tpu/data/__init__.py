"""Datasets + test-data generation (reference data/ directory)."""
from ..models.logreg import load_csv as load_label_csv  # noqa: F401
from .generator import (  # noqa: F401
    create_random_good_test_data,
    synthetic_classification_csv,
)
