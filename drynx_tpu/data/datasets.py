"""Dataset generation + cleaning pipeline (SURVEY.md §2.1 #29).

The reference ships medical CSV datasets (Pima, SPECTF, PCS, LBW under
data/ + tmpdata/) and a `clean_data.py` preprocessing script. Those files are
third-party data we do not copy; instead this module generates synthetic
datasets with the SAME shapes, formats, and statistical character (binary
label in column 0, integer/float features, class imbalance), plus a cleaning
pipeline with the same responsibilities as the reference's script: drop rows
with missing/sentinel values, binarize labels, and write the canonical
"label-first CSV" the loaders (models/logreg.py `load_csv`,
reference lib/encoding/logistic_regression.go:1275 LoadData) expect.

CLI:
  python -m drynx_tpu.data.datasets gen   --name pima --out data/pima.csv
  python -m drynx_tpu.data.datasets clean --input raw.csv --out clean.csv \
      --missing -9 --label-true 1
"""
from __future__ import annotations

import argparse
import sys
import zlib

import numpy as np

# (rows, features) and a feature scale profile per reference dataset shape:
# Pima 768x8 (reference data/, LR tests service_test.go:721), SPECTF 267x44
# (:352), PCS ~1500x6 (:1051), LBW 189x9.
SHAPES = {
    "pima":   dict(n=768,  d=8,  pos_frac=0.35, int_features=True),
    "spectf": dict(n=267,  d=44, pos_frac=0.79, int_features=True),
    "pcs":    dict(n=1500, d=6,  pos_frac=0.45, int_features=True),
    "lbw":    dict(n=189,  d=9,  pos_frac=0.31, int_features=True),
}


def generate(name: str, seed: int = 0):
    """Synthetic (X, y) with the named reference dataset's shape: a noisy
    linear-logit model so encrypted training has signal to find."""
    spec = SHAPES[name]
    n, d = spec["n"], spec["d"]
    # crc32, not hash(): str hash is PYTHONHASHSEED-randomized per process,
    # so (name, seed) must map to the same stream in every process (two DPs
    # "generating the same dataset" have to agree).
    rng = np.random.default_rng(seed + zlib.crc32(name.encode()) % (2 ** 16))
    scales = rng.uniform(1.0, 30.0, size=d)
    offsets = rng.uniform(0.0, 50.0, size=d)
    X = np.abs(rng.normal(size=(n, d))) * scales + offsets
    if spec["int_features"]:
        X = np.round(X)
    w = rng.normal(size=d)
    z = (X - X.mean(0)) / (X.std(0) + 1e-12) @ w
    # shift the intercept to hit the target positive fraction
    b = np.quantile(z, 1.0 - spec["pos_frac"])
    y = (z - b + rng.logistic(scale=0.5, size=n) > 0).astype(np.int64)
    return X, y


def write_csv(path: str, X, y, sep: str = ",") -> None:
    """Label-first CSV, integer-formatted where exact (loader format)."""
    X = np.asarray(X)
    y = np.asarray(y, dtype=np.int64)
    rows = np.concatenate([y[:, None].astype(float), X], axis=1)
    fmt = "%d" if np.allclose(rows, np.round(rows)) else "%.6f"
    np.savetxt(path, rows, delimiter=sep, fmt=fmt)


def clean(X, y, missing_sentinels=(), label_true=None):
    """Reference clean_data.py responsibilities: drop rows containing NaN or
    any sentinel value; binarize labels against `label_true` if given."""
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y)
    keep = ~np.isnan(X).any(axis=1)
    for s in missing_sentinels:
        keep &= ~(X == float(s)).any(axis=1)
    X, y = X[keep], y[keep]
    if label_true is not None:
        y = (y == type(y.flat[0])(label_true)).astype(np.int64)
    else:
        y = y.astype(np.int64)
    return X, y


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="drynx-datasets")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("gen", help="generate a synthetic reference-shaped dataset")
    g.add_argument("--name", choices=sorted(SHAPES), required=True)
    g.add_argument("--out", required=True)
    g.add_argument("--seed", type=int, default=0)

    c = sub.add_parser("clean", help="clean a raw label-first CSV")
    c.add_argument("--input", required=True)
    c.add_argument("--out", required=True)
    c.add_argument("--sep", default=",")
    c.add_argument("--missing", type=float, action="append", default=[])
    c.add_argument("--label-true", default=None)

    a = p.parse_args(argv)
    if a.cmd == "gen":
        X, y = generate(a.name, a.seed)
        write_csv(a.out, X, y)
        print(f"wrote {a.out}: {X.shape[0]} rows x {X.shape[1]} features, "
              f"{int(y.sum())} positive", file=sys.stderr)
        return 0
    raw = np.loadtxt(a.input, delimiter=a.sep)
    y, X = raw[:, 0], raw[:, 1:]
    lt = None if a.label_true is None else float(a.label_true)
    X, y = clean(X, y, missing_sentinels=a.missing, label_true=lt)
    write_csv(a.out, X, y, a.sep)
    print(f"wrote {a.out}: {X.shape[0]} rows kept", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
