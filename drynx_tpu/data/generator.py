"""Test-data generation: valid instances of all five proof types + dataset
synthesis/loading.

`create_random_good_test_data` mirrors reference data/data.go:27-107 (used by
proof-collection tests and simulations to exercise VN verification without a
real survey). Dataset helpers produce/load CSVs in the reference's
label-first format (lib/encoding/logistic_regression.go:1275 LoadData).
"""
from __future__ import annotations

import numpy as np


def create_random_good_test_data(cluster, n_values: int = 2, u: int = 4,
                                 l: int = 2, seed: int = 0) -> dict:
    """Build one valid proof of each type against `cluster`'s keys.

    Returns {"range": bytes, "aggregation": bytes, "obfuscation": bytes,
    "keyswitch": bytes, "shuffle": bytes} — each ready for a ProofRequest.
    """
    import jax
    import jax.numpy as jnp
    import pickle

    from ..crypto import curve as C
    from ..crypto import elgamal as eg
    from ..proofs import aggregation as agg_proof
    from ..proofs import keyswitch as ks_proof
    from ..proofs import obfuscation as obf_proof
    from ..proofs import range_proof as rproof
    from ..proofs import shuffle as shuffle_proof

    rng = np.random.default_rng(seed)
    key = jax.random.PRNGKey(seed)
    out = {}

    # range
    sigs = cluster.ensure_range_sigs(u)
    vals = rng.integers(0, u ** l, size=(n_values,)).astype(np.int64)
    key, k1, k2 = jax.random.split(key, 3)
    cts, rs = eg.encrypt_ints(k1, cluster.coll_tbl, vals)
    out["range"] = rproof.RangeProofList(
        n_values=n_values,
        batches=[(np.arange(n_values, dtype=np.int64),
                  rproof.create_range_proofs(
                      k2, vals, rs, cts, sigs, u, l,
                      cluster.coll_tbl.table))]).to_bytes()

    # aggregation
    key, k3 = jax.random.split(key)
    many, _ = eg.encrypt_ints(k3, cluster.coll_tbl,
                              rng.integers(0, 9, size=(3, n_values)))
    agg = eg.ct_add(eg.ct_add(many[0], many[1]), many[2])
    out["aggregation"] = pickle.dumps(
        agg_proof.create_aggregation_proof(many, agg))

    # obfuscation
    key, k4, k5 = jax.random.split(key, 3)
    s = eg.random_scalars(k4, (n_values,))
    out["obfuscation"] = pickle.dumps(
        obf_proof.create_obfuscation_proofs(k5, cts, s))

    # keyswitch
    key, k6, k7 = jax.random.split(key, 3)
    srv_x = jnp.asarray(np.stack([eg.secret_to_limbs(c.secret)
                                  for c in cluster.cns]))
    ks_rs = eg.random_scalars(k6, (len(cluster.cns), n_values))
    from ..crypto import batching as B

    K0 = cts[:, 0]
    u_pts = B.fixed_base_mul(eg.BASE_TABLE.table, ks_rs)
    rQ = B.fixed_base_mul(cluster.client_tbl.table, ks_rs)
    xK = B.g1_scalar_mul(K0[None], srv_x[:, None, :])
    w_pts = B.g1_add(rQ, B.g1_neg(xK))
    out["keyswitch"] = pickle.dumps(ks_proof.create_keyswitch_proofs(
        k7, K0, srv_x, ks_rs, cluster.client_pt, cluster.client_tbl.table,
        u_pts, w_pts))

    # shuffle
    perm = rng.permutation(n_values)
    betas = [int(rng.integers(1, 1 << 62)) for _ in range(n_values)]
    shuffled = jnp.take(cts, jnp.asarray(perm), axis=0)
    from ..crypto import field as F

    rs2 = jnp.asarray(np.stack([F.from_int(b) for b in betas]))
    zero_ct = eg.encrypt_with_tables(
        eg.BASE_TABLE.table, cluster.coll_tbl.table,
        eg.int_to_scalar(jnp.zeros((n_values,), dtype=jnp.int64)), rs2)
    out_cts = eg.ct_add(shuffled, zero_ct)
    pr = shuffle_proof.prove_shuffle(
        cts, out_cts, perm, betas,
        jnp.asarray(C.from_ref(cluster.coll_pub)), rng)
    out["shuffle"] = pickle.dumps((pr, np.asarray(cts), np.asarray(out_cts)))

    return out


def synthetic_classification_csv(path: str, n: int = 200, d: int = 8,
                                 seed: int = 0, sep: str = ",") -> None:
    """Write a label-first CSV shaped like the reference's Pima-format data
    files (label column first, integer-ish features)."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(d,))
    X = rng.normal(loc=4.0, scale=2.0, size=(n, d))
    logits = (X - X.mean(0)) @ w
    y = (logits + rng.logistic(size=n) > 0).astype(int)
    with open(path, "w") as f:
        for i in range(n):
            f.write(sep.join([str(y[i])] + [f"{v:.3f}" for v in X[i]]) + "\n")


__all__ = ["create_random_good_test_data", "synthetic_classification_csv"]
