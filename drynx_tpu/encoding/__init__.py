"""Query-operation encoders: local data -> sufficient-statistics vectors.

TPU-first re-design of the reference's lib/encoding package (dispatcher at
lib/encoding/encode_decode.go:14-233): every operation's local encoding is a
fixed-shape vectorized reduction producing an int64 statistics vector whose
length depends only on the query (never the data), so the whole DP-side
pipeline (encode -> encrypt -> aggregate) is one jittable program.
"""
from . import tiles  # noqa: F401
from .stats import (  # noqa: F401
    GRID_OPS,
    OPS,
    DecryptedVector,
    decode,
    encode_clear,
    encode_clear_tiled,
    encode_clear_tiles,
    output_size,
)
