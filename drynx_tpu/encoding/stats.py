"""Sufficient-statistics encoders/decoders for the 12 statistical query ops.

Reference semantics (lib/encoding/*.go, see SURVEY.md §2.1 #3-13):

  sum        [Σx]                                  sum.go:17-36
  mean       [Σx, N]                               mean.go:17-59
  variance   [Σx, N, Σx²]                          variance.go:17-61
  cosim      [Σa, Σb, Σa², Σb², Σab]               cosim.go:18-70
  bool_OR    [bit]   (zero iff false)              OR_AND.go:23-59
  bool_AND   [1-bit] (agg zero iff all true)       OR_AND.go:76-112
  min        OR-bits  b_i = (i >= local_min)       min_max.go:13-55
  max        AND-bits b_i = (i >= local_max)       min_max.go:87-123
  frequency_count  histogram over [min,max]        frequency_count.go:18-62
  union      OR presence bits over [min,max]       set_union_intersection.go:19
  inter      AND presence bits over [min,max]      set_union_intersection.go:94
  lin_reg    [N, ΣXj, ΣXjXk(uptri), ΣY, ΣXjY]      linear_regression_dims.go:23-110
  r2         [N, ΣY, ΣY², Σ(pred−y)²]              model_evaluation.go:17-81

AND-semantics ops encode the COMPLEMENT bit so that the homomorphic sum is
zero iff every DP's bit is one — the zero/nonzero property survives the
obfuscation protocol's random scalar multiplications (reference
protocols/obfuscation_protocol.go:241-243), exactly like the reference's
proof-mode 0/1 encodings. Non-proof mode scales bits by a local random
nonzero value (OR_AND.go:23-40); here that is the optional `bit_scale`.

Decoding consumes a `DecryptedVector` carrying both integer values (discrete
log) and zero-flags, because OR/AND-family results only need (and after
obfuscation only HAVE) the zero/nonzero bit (unlynx DecryptCheckZero,
reference lib/encoding/OR_AND.go:61,114).
"""
from __future__ import annotations

import dataclasses
from fractions import Fraction

import jax.numpy as jnp
import numpy as np

from . import tiles

# The five ops that encode over the dense [query_min, query_max] value
# grid — the reference's 1k..1M bucket scale axis (TIFS/maxOpti.py).
GRID_OPS = ("min", "max", "frequency_count", "union", "inter")


def grid_buckets(query) -> int:
    """Bucket-grid width of a grid-op query (0 for every other op): the
    ``compilecache.Profile.n_buckets`` axis that adds the bucket-tile
    program set (registry._bucket_schemas) so tiled encode/encrypt/prove
    dispatches hit the warm fast lane. Pure function of the query so
    admission control and the cluster warmup derive the same axis; a
    query without an operation (minimal shape stubs) is non-grid."""
    op = getattr(query, "operation", None)
    if op is None or op.name not in GRID_OPS:
        return 0
    return int(op.query_max) - int(op.query_min) + 1


@dataclasses.dataclass
class DecryptedVector:
    """Decrypted query result: ints where resolvable + zero-flags always."""

    values: np.ndarray   # int64 (nbr_output,) — valid where `found`
    found: np.ndarray    # bool  (nbr_output,)
    is_zero: np.ndarray  # bool  (nbr_output,)


# ---------------------------------------------------------------------------
# Output sizing (reference lib/structs.go:591-641 ChooseOperation)
# ---------------------------------------------------------------------------

def output_size(op: str, query_min: int = 0, query_max: int = 0,
                dims: int = 1) -> int:
    rng = query_max - query_min + 1
    return {
        "sum": 1,
        "mean": 2,
        "variance": 3,
        "cosim": 5,
        "bool_OR": 1,
        "bool_AND": 1,
        "min": rng,
        "max": rng,
        "frequency_count": rng,
        "union": rng,
        "inter": rng,
        "lin_reg": (dims * dims + 5 * dims + 4) // 2,
        "r2": 4,
    }[op]


# ---------------------------------------------------------------------------
# Clear-text local encoders (jit-safe; int64 in/out)
# ---------------------------------------------------------------------------

def _bits_ge(local: jnp.ndarray, lo: int, hi: int) -> jnp.ndarray:
    """(hi-lo+1,) bits b_i = (i >= local) for i in [lo, hi]."""
    grid = jnp.arange(lo, hi + 1, dtype=jnp.int64)
    return (grid >= local).astype(jnp.int64)


def _presence(xs: jnp.ndarray, lo: int, hi: int) -> jnp.ndarray:
    grid = jnp.arange(lo, hi + 1, dtype=jnp.int64)
    return jnp.any(xs[:, None] == grid[None, :], axis=0).astype(jnp.int64)


def encode_clear_tiles(op: str, data, query_min: int = 0, query_max: int = 0,
                       tile: int | None = None, bit_scale=None):
    """Per-tile grid encodings for the GRID_OPS: yields (offset, enc_tile)
    pairs covering [query_min, query_max] in plan_tiles order.

    Each tile's dispatch materializes at most an O(rows x tile) equality
    mask (union / inter / frequency_count) or an O(tile) comparison grid
    (min / max) — never the monolithic O(rows x buckets) mask. The
    concatenation of the tiles is bit-identical to `encode_clear`: every
    grid column's encoding depends only on that column's value and the
    (once-reduced) local min/max, so tiling is pure slicing."""
    if op not in GRID_OPS:
        raise ValueError(f"not a grid op: {op!r}")
    x = jnp.asarray(data, dtype=jnp.int64)
    s = jnp.int64(1) if bit_scale is None else jnp.asarray(bit_scale, jnp.int64)
    plan = tiles.plan_tiles(query_max - query_min + 1, tile)
    # the O(rows) reduction happens ONCE, outside the tile loop
    local = (jnp.min(x) if op == "min"
             else jnp.max(x) if op == "max" else None)
    for a, b in plan.tiles:
        grid = jnp.arange(query_min + a, query_min + b, dtype=jnp.int64)
        if op == "min":
            enc = (grid >= local).astype(jnp.int64) * s
        elif op == "max":
            enc = (1 - (grid >= local).astype(jnp.int64)) * s
        elif op == "frequency_count":
            enc = jnp.sum(x[:, None] == grid[None, :],
                          axis=0).astype(jnp.int64)
        elif op == "union":
            enc = jnp.any(x[:, None] == grid[None, :],
                          axis=0).astype(jnp.int64) * s
        else:  # inter
            enc = (1 - jnp.any(x[:, None] == grid[None, :],
                               axis=0).astype(jnp.int64)) * s
        yield a, enc


def encode_clear_tiled(op: str, data, query_min: int = 0, query_max: int = 0,
                       tile: int | None = None, bit_scale=None):
    """Tiled grid-op encoding, concatenated: bit-identical to
    `encode_clear` with peak mask memory bounded by the tile
    (TilePlan.peak_mask_elems). Tiles are pulled to host as they finish
    so no more than one tile's mask is live at a time."""
    parts = [np.asarray(enc) for _, enc in encode_clear_tiles(
        op, data, query_min, query_max, tile, bit_scale)]
    return jnp.asarray(np.concatenate(parts))


def encode_clear(op: str, data, query_min: int = 0, query_max: int = 0,
                 preds=None, bit_scale=None):
    """Local sufficient statistics for one DP. `data`: int64 (rows,) or
    (rows, cols) for cosim (2 cols) / lin_reg (d features + label last).
    `preds`: model predictions for r2. `bit_scale`: optional random nonzero
    int64 multiplier for OR/AND-family encodings (non-proof mode).

    Grid ops above tiles.TILE_THRESHOLD buckets encode through the
    bucket-tile path by default (bit-identical; bounded peak memory)."""
    if op in GRID_OPS:
        t = tiles.auto_tile(query_max - query_min + 1)
        if t:
            return encode_clear_tiled(op, data, query_min, query_max, t,
                                      bit_scale)
    x = jnp.asarray(data, dtype=jnp.int64)
    s = jnp.int64(1) if bit_scale is None else jnp.asarray(bit_scale, jnp.int64)

    if op == "sum":
        return jnp.sum(x)[None]
    if op == "mean":
        return jnp.stack([jnp.sum(x), jnp.int64(x.shape[0])])
    if op == "variance":
        return jnp.stack([jnp.sum(x), jnp.int64(x.shape[0]), jnp.sum(x * x)])
    if op == "cosim":
        a, b = x[:, 0], x[:, 1]
        return jnp.stack([jnp.sum(a), jnp.sum(b), jnp.sum(a * a),
                          jnp.sum(b * b), jnp.sum(a * b)])
    if op == "bool_OR":
        bit = jnp.any(x != 0).astype(jnp.int64)
        return (bit * s)[None]
    if op == "bool_AND":
        bit = jnp.all(x != 0).astype(jnp.int64)
        return ((1 - bit) * s)[None]
    if op == "min":
        return _bits_ge(jnp.min(x), query_min, query_max) * s
    if op == "max":
        return (1 - _bits_ge(jnp.max(x), query_min, query_max)) * s
    if op == "frequency_count":
        grid = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        return jnp.sum(x[:, None] == grid[None, :], axis=0).astype(jnp.int64)
    if op == "union":
        return _presence(x, query_min, query_max) * s
    if op == "inter":
        return (1 - _presence(x, query_min, query_max)) * s
    if op == "lin_reg":
        X, y = x[:, :-1], x[:, -1]
        d = X.shape[1]
        n = jnp.int64(X.shape[0])
        sx = jnp.sum(X, axis=0)
        outer = X.T @ X  # (d, d)
        iu, ju = np.triu_indices(d)
        sxx = outer[iu, ju]
        sy = jnp.sum(y)[None]
        sxy = X.T @ y
        return jnp.concatenate([n[None], sx, sxx, sy, sxy])
    if op == "r2":
        y = x
        p = jnp.asarray(preds, dtype=jnp.int64)
        err = p - y
        return jnp.stack([jnp.int64(y.shape[0]), jnp.sum(y),
                          jnp.sum(y * y), jnp.sum(err * err)])
    raise ValueError(f"unknown operation {op!r}")


# ---------------------------------------------------------------------------
# Group-by encoders (reference data_collection_protocol.go:157-196: DPs
# encode PER GROUP-BY VALUE and the root adds same-group responses).
#
# TPU-first formulation: the group becomes a leading tensor axis. The static
# group grid (cartesian product of candidate values, reference
# AllPossibleGroups) is known from the query, so every group's statistics are
# computed in ONE pass via a (n_groups, rows) membership mask — no ragged
# per-group subsets, fully jit/vmap-safe. Aggregation then needs no
# "same-group matching" at all: element-wise homomorphic addition along the
# aligned group axis IS the per-group aggregation.
# ---------------------------------------------------------------------------

def group_grid(group_by) -> np.ndarray:
    """Cartesian product of candidate values per group attribute
    (reference AllPossibleGroups): [[vals_attr0], [vals_attr1], ...]
    -> int64 (n_groups, n_attrs)."""
    arrs = [np.asarray(v, dtype=np.int64) for v in group_by]
    mesh = np.meshgrid(*arrs, indexing="ij")
    return np.stack([m.ravel() for m in mesh], axis=-1)


def encode_clear_grouped(op: str, data, groups, grid, query_min: int = 0,
                         query_max: int = 0, preds=None, bit_scale=None):
    """Per-group local sufficient statistics: (n_groups, V).

    data: as encode_clear. groups: int64 (rows, n_attrs) group label per
    record. grid: int64 (n_groups, n_attrs) from group_grid(). Empty groups
    encode the operation's identity (0 contributions / empty-set bits).
    """
    x = jnp.asarray(data, dtype=jnp.int64)
    g = jnp.asarray(groups, dtype=jnp.int64)
    gr = jnp.asarray(grid, dtype=jnp.int64)
    s = jnp.int64(1) if bit_scale is None else jnp.asarray(bit_scale, jnp.int64)
    # (n_groups, rows) membership mask
    mask = jnp.all(g[None, :, :] == gr[:, None, :], axis=-1)
    mi = mask.astype(jnp.int64)

    if op == "sum":
        return (mi @ x)[:, None]
    if op == "mean":
        return jnp.stack([mi @ x, mi.sum(axis=1)], axis=1)
    if op == "variance":
        return jnp.stack([mi @ x, mi.sum(axis=1), mi @ (x * x)], axis=1)
    if op == "cosim":
        a, b = x[:, 0], x[:, 1]
        return jnp.stack([mi @ a, mi @ b, mi @ (a * a), mi @ (b * b),
                          mi @ (a * b)], axis=1)
    if op == "bool_OR":
        bit = jnp.any(mask & (x != 0)[None, :], axis=1).astype(jnp.int64)
        return (bit * s)[:, None]
    if op == "bool_AND":
        # complement bit; empty group = AND over empty set = true -> encode 0
        bit = jnp.all(jnp.where(mask, x != 0, True), axis=1).astype(jnp.int64)
        return ((1 - bit) * s)[:, None]
    if op == "min":
        # empty-group sentinel max+1 -> all bits 0 (contributes nothing to OR)
        local = jnp.min(jnp.where(mask, x[None, :], query_max + 1), axis=1)
        grid_v = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        return (grid_v[None, :] >= local[:, None]).astype(jnp.int64) * s
    if op == "max":
        # empty-group sentinel min-1 -> bits all 1 -> complement 0
        local = jnp.max(jnp.where(mask, x[None, :], query_min - 1), axis=1)
        grid_v = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        bits = (grid_v[None, :] >= local[:, None]).astype(jnp.int64)
        return (1 - bits) * s
    if op == "frequency_count":
        grid_v = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        eq = (x[:, None] == grid_v[None, :]).astype(jnp.int64)
        return mi @ eq
    if op == "union":
        grid_v = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        pres = jnp.any(mask[:, :, None] & (x[:, None] == grid_v)[None],
                       axis=1).astype(jnp.int64)
        return pres * s
    if op == "inter":
        grid_v = jnp.arange(query_min, query_max + 1, dtype=jnp.int64)
        pres = jnp.any(mask[:, :, None] & (x[:, None] == grid_v)[None],
                       axis=1).astype(jnp.int64)
        return (1 - pres) * s
    if op == "lin_reg":
        X, y = x[:, :-1], x[:, -1]
        d = X.shape[1]
        n = mi.sum(axis=1)
        sx = mi @ X
        outer = jnp.einsum("gr,rd,re->gde", mi, X, X)
        iu, ju = np.triu_indices(d)
        sxx = outer[:, iu, ju]
        sy = (mi @ y)[:, None]
        sxy = jnp.einsum("gr,r,rd->gd", mi, y, X)
        return jnp.concatenate([n[:, None], sx, sxx, sy, sxy], axis=1)
    if op == "r2":
        y = x
        p = jnp.asarray(preds, dtype=jnp.int64)
        err = p - y
        return jnp.stack([mi.sum(axis=1), mi @ y, mi @ (y * y),
                          mi @ (err * err)], axis=1)
    raise ValueError(f"unknown operation {op!r} for grouped encoding")


def decode_grouped(op: str, dec: DecryptedVector, grid, query_min: int = 0,
                   query_max: int = 0, dims: int = 1) -> dict:
    """Per-group decode (reference services/api.go:124-128): the decrypted
    vector is (n_groups * V,) group-major; returns {group_tuple: result}.

    Groups with no data decode to None where the op can express that (mean /
    variance / cosim / r2 / lin_reg have an N component; min's all-zero OR
    bits yield None). `max` is the exception: its AND-complement encoding's
    aggregation-neutral element equals a genuine max of query_min, so an
    all-empty group decodes to query_min — the same ambiguity exists in the
    reference's bit encoding (min_max.go:87-123)."""
    grid = np.asarray(grid)
    n_groups = grid.shape[0]
    v = np.asarray(dec.values).reshape(n_groups, -1)
    f = np.asarray(dec.found).reshape(n_groups, -1)
    z = np.asarray(dec.is_zero).reshape(n_groups, -1)
    out = {}
    for gi in range(n_groups):
        sub = DecryptedVector(values=v[gi], found=f[gi], is_zero=z[gi])
        if op == "cosim" and int(v[gi][2]) * int(v[gi][3]) == 0:
            out[tuple(int(t) for t in grid[gi])] = None  # empty/degenerate
            continue
        try:
            r = decode(op, sub, query_min, query_max, dims)
        except ZeroDivisionError:
            r = None  # empty group: mean/variance/r2 undefined
        out[tuple(int(t) for t in grid[gi])] = r
    return out


# ---------------------------------------------------------------------------
# Decoders (host-side; exact rational arithmetic where the reference is exact)
# ---------------------------------------------------------------------------

def _first_nonzero(flags_nonzero, lo: int):
    idx = np.flatnonzero(flags_nonzero)
    return None if idx.size == 0 else lo + int(idx[0])


def decode(op: str, dec: DecryptedVector, query_min: int = 0,
           query_max: int = 0, dims: int = 1):
    v = np.asarray(dec.values, dtype=np.int64)
    nz = ~np.asarray(dec.is_zero)

    if op == "sum":
        return int(v[0])
    if op == "mean":
        return float(v[0]) / float(v[1])
    if op == "variance":
        s, n, ss = (int(v[0]), int(v[1]), int(v[2]))
        mean = s / n
        return ss / n - mean * mean
    if op == "cosim":
        sa, sb, saa, sbb, sab = (int(t) for t in v)
        return sab / (np.sqrt(saa) * np.sqrt(sbb))
    if op == "bool_OR":
        return bool(nz[0])
    if op == "bool_AND":
        return not bool(nz[0])
    if op == "min":
        return _first_nonzero(nz, query_min)
    if op == "max":
        # encoded complement: aggregated zero at i iff every DP max <= i
        return _first_nonzero(~nz, query_min)
    if op == "frequency_count":
        return {query_min + i: int(c) for i, c in enumerate(v)}
    if op == "union":
        return [query_min + i for i in np.flatnonzero(nz)]
    if op == "inter":
        return [query_min + i for i in np.flatnonzero(~nz)]
    base, _, arg = op.partition(":")
    if base in DECODE_MODES:
        return _decode_histogram_mode(base, arg, v, query_min)
    if op == "lin_reg":
        return _decode_linreg(v, dims)
    if op == "r2":
        n, sy, syy, serr = (int(t) for t in v)
        denom = Fraction(syy) - Fraction(sy * sy, n)
        if denom == 0:
            return 0.0
        return float(1 - Fraction(serr) / denom)
    raise ValueError(f"unknown operation {op!r}")


def _decode_linreg(v: np.ndarray, d: int):
    """Solve the normal equations exactly (rational Gaussian elimination,
    mirroring reference linear_regression_dims.go:110-204)."""
    n = int(v[0])
    sx = [int(t) for t in v[1:1 + d]]
    ntri = d * (d + 1) // 2
    sxx_flat = [int(t) for t in v[1 + d:1 + d + ntri]]
    sy = int(v[1 + d + ntri])
    sxy = [int(t) for t in v[2 + d + ntri:2 + 2 * d + ntri]]

    sxx = [[0] * d for _ in range(d)]
    k = 0
    for i in range(d):
        for j in range(i, d):
            sxx[i][j] = sxx[j][i] = sxx_flat[k]
            k += 1

    # Augmented (d+1)x(d+2) system for [b0, b1..bd]
    A = [[Fraction(0)] * (d + 2) for _ in range(d + 1)]
    A[0][0] = Fraction(n)
    for j in range(d):
        A[0][j + 1] = A[j + 1][0] = Fraction(sx[j])
    for i in range(d):
        for j in range(d):
            A[i + 1][j + 1] = Fraction(sxx[i][j])
    A[0][d + 1] = Fraction(sy)
    for i in range(d):
        A[i + 1][d + 1] = Fraction(sxy[i])

    m = d + 1
    for col in range(m):
        piv = next((r for r in range(col, m) if A[r][col] != 0), None)
        if piv is None:
            return None  # singular system
        A[col], A[piv] = A[piv], A[col]
        pv = A[col][col]
        A[col] = [a / pv for a in A[col]]
        for r in range(m):
            if r != col and A[r][col] != 0:
                f = A[r][col]
                A[r] = [a - f * b for a, b in zip(A[r], A[col])]
    return np.asarray([float(A[r][m]) for r in range(m)])


def _decode_histogram_mode(mode: str, arg: str, counts: np.ndarray,
                           query_min: int):
    """Order-statistic decode modes over the ``frequency_count`` grid
    (PR 18 streaming decode modes). The aggregated plaintext is already
    the count-per-grid-value histogram, so quantiles, the median and
    top-k are pure host-side walks over it — no new encoding, no new
    ciphertext layout, and (load-bearing for streaming) they stay exact
    under pane addition/subtraction because the underlying vector does.

    Parameterized via the op string — ``"quantile:0.9"`` / ``"top_k:3"``
    (bare ``"quantile"`` means the median; bare ``"top_k"`` means k=1) —
    which keeps the ``decode(op, dec, ...)`` dispatch signature intact.

    Sparse-grid sentinels mirror the decode_grouped ambiguity table: an
    all-zero histogram has no q-th value (``None``, like ``min``'s empty
    OR bits) and no top values (``[]``, like an empty ``union``) — count
    zeros are *absence*, not observations of zero.
    """
    c = counts.astype(np.int64)
    total = int(c.sum())
    if mode == "top_k":
        k = int(arg) if arg else 1
        if k <= 0:
            raise ValueError(f"top_k needs a positive k, got {k}")
        idx = np.flatnonzero(c > 0)
        # count desc, then grid value asc: a deterministic total order,
        # so streaming advances over identical windows return identical
        # lists regardless of fold grouping
        order = sorted(idx, key=lambda i: (-int(c[i]), int(i)))
        return [query_min + int(i) for i in order[:k]]
    q = 0.5 if mode == "median" else (float(arg) if arg else 0.5)
    if not 0.0 < q <= 1.0:
        raise ValueError(f"quantile q must be in (0, 1], got {q}")
    if total == 0:
        return None
    # lower quantile (inverse CDF): smallest grid value whose cumulative
    # count reaches rank ceil(q * total); q=0.5 is the lower median
    rank = int(np.ceil(q * total))
    cum = np.cumsum(c)
    return query_min + int(np.searchsorted(cum, rank))


OPS = ["sum", "mean", "variance", "cosim", "bool_OR", "bool_AND", "min",
       "max", "frequency_count", "union", "inter", "lin_reg", "r2"]

# Decode-only modes (no encoder entry): they read a frequency_count
# window, so ``encode_clear("frequency_count", ...)`` + ``decode("median",
# ...)`` is the pairing — StreamEngine's ``decode_mode=``.
DECODE_MODES = ("quantile", "median", "top_k")

__all__ = ["OPS", "GRID_OPS", "DECODE_MODES", "grid_buckets",
           "DecryptedVector",
           "encode_clear", "decode",
           "output_size", "group_grid", "encode_clear_grouped",
           "decode_grouped", "encode_clear_tiles", "encode_clear_tiled"]
