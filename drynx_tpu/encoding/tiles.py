"""Bucket-tile planner for the grid-encoded query ops (min / max /
frequency_count / union / inter).

These five ops encode over the dense value grid [query_min, query_max] —
at the reference's published scale axis (TIFS/maxOpti.py: 1k -> 1M
buckets) the monolithic encoders materialize an O(rows x buckets)
equality mask (`_presence`, frequency_count) and downstream a
(n_dps, buckets, 2, 3, 16) ciphertext array (384 MB at 1M buckets) in
ONE dispatch. The tile planner splits the bucket axis into fixed-size
tiles so every dispatch — encode mask, encryption slab, range-proof
commit chunk — is bounded by the tile, while the concatenated result
stays bit-identical to the monolithic path (the encoders are
element-wise over the grid; the range-proof transcripts are per-value
independent, proofs/range_proof.py module docstring).

Tiles are balanced like the proof plane's shard slices (never more than
`tile` wide, sizes within 1 of each other) so a grid that is not a tile
multiple still lands on at most TWO bucket sizes after the bucketed()
power-of-two canonicalization — the compilecache registry enumerates
exactly these sizes (`Profile.n_buckets`, registry._bucket_schemas).
"""
from __future__ import annotations

import dataclasses
import os

# Tile width for the bucket-grid axis. Matches the g1 family's
# max_bucket (crypto/batching.py): a tile of grid bits encrypts and
# range-proves through already-chunk-sized bucketed programs.
DEFAULT_TILE = 4096

# Grids at or below this many buckets stay monolithic: one dispatch of a
# few thousand lanes beats the per-tile dispatch overhead, and every
# existing survey shape (V <= 8192) keeps its exact current program set.
TILE_THRESHOLD = 8192

ENV_TILE = "DRYNX_BUCKET_TILE"


def tile_width() -> int:
    """The configured tile width (env DRYNX_BUCKET_TILE overrides)."""
    try:
        w = int(os.environ.get(ENV_TILE, DEFAULT_TILE))
    except ValueError:
        return DEFAULT_TILE
    return w if w > 0 else DEFAULT_TILE


def auto_tile(n: int) -> int:
    """Tile width to use for an n-wide grid axis: 0 (monolithic) at or
    below TILE_THRESHOLD, the configured tile above it. This is the ONE
    policy point that makes tiling the default at scale."""
    return tile_width() if int(n) > TILE_THRESHOLD else 0


@dataclasses.dataclass(frozen=True)
class TilePlan:
    """Balanced contiguous tiling of an n-wide grid axis.

    tiles are [start, stop) offsets into the axis; every tile is at most
    `tile` wide. peak_mask_elems bounds the largest row-by-grid equality
    mask any single encode dispatch materializes — the quantity the
    65k-bucket acceptance test pins (rows x tile, NOT rows x buckets)."""

    n: int
    tile: int
    tiles: tuple  # ((start, stop), ...)

    @property
    def n_tiles(self) -> int:
        return len(self.tiles)

    @property
    def max_tile_width(self) -> int:
        return max((b - a) for a, b in self.tiles) if self.tiles else 0

    def peak_mask_elems(self, rows: int) -> int:
        """Largest O(rows x grid) mask a tiled encode dispatch builds."""
        return int(rows) * self.max_tile_width

    def covers(self) -> bool:
        """True iff the tiles exactly partition [0, n)."""
        pos = 0
        for a, b in self.tiles:
            if a != pos or b <= a:
                return False
            pos = b
        return pos == self.n


def plan_tiles(n: int, tile: int | None = None) -> TilePlan:
    """Balanced tiling of an n-wide axis into ceil(n / tile) tiles.

    tile=None uses the configured width; tile=0 forces one monolithic
    tile. Balanced (sizes differ by at most 1) so the post-bucketing
    program set is minimal — mirrors proof_plane.shard_slices."""
    n = int(n)
    if tile is None:
        tile = tile_width()
    if n <= 0:
        return TilePlan(n=n, tile=int(tile), tiles=())
    if tile <= 0 or tile >= n:
        return TilePlan(n=n, tile=int(tile), tiles=((0, n),))
    k = -(-n // int(tile))          # ceil: k tiles, each <= tile wide
    base, extra = divmod(n, k)
    out, start = [], 0
    for i in range(k):
        stop = start + base + (1 if i < extra else 0)
        out.append((start, stop))
        start = stop
    return TilePlan(n=n, tile=int(tile), tiles=tuple(out))


def proof_tile_shards(v: int, tile: int) -> int:
    """Shard count that tiles a V-wide proof value axis at `tile`:
    create_range_proofs runs its commit stage through
    _commit_kernel_sharded with this count, so each per-tile dispatch is
    bounded by the tile (and lands on the registry's bucket-grid program
    set). 1 means no tiling."""
    v, tile = int(v), int(tile)
    if tile <= 0 or v <= tile:
        return 1
    return -(-v // tile)


__all__ = ["DEFAULT_TILE", "TILE_THRESHOLD", "ENV_TILE", "TilePlan",
           "plan_tiles", "auto_tile", "tile_width", "proof_tile_shards"]
