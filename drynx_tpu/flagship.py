"""Flagship pipeline: fully-jitted encrypted logistic-regression survey.

This is the TPU equivalent of the reference's north-star workload
(SURVEY.md §3.4; reference services/service_test.go:1082-1130 — Pima, 10 DPs,
K=2, precision 1e0, GD step 0.1): every DP encodes + encrypts its local
approximation tensors, ciphertexts are homomorphically aggregated, the
collective key-switches the aggregate to the querier, the querier decrypts
(discrete-log table) and runs gradient descent — all as ONE jitted program.

The same program builds two ways:
  * single-chip (`build_pipeline`): server/DP loops become batched axes and
    tree reductions on one device — used by bench.py and __graft_entry__.entry.
  * multi-chip (`build_sharded_pipeline`): DPs/servers ride a mesh axis with
    butterfly all-reduces (drynx_tpu.parallel), the ciphertext vector is
    sharded over a second mesh axis — used by __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .crypto import curve as C
from .crypto import elgamal as eg
from .crypto import field as F
from .models import logreg as lr
from .parallel import collective as col


@dataclasses.dataclass
class SurveySetup:
    """Keys + tables for one survey: n_servers CNs, a querier, 10 DPs."""

    server_secrets: np.ndarray    # (n_servers, 16) scalar limbs
    coll_pub_table: jnp.ndarray   # (64, 16, 3, 16) fixed-base table
    query_secret: int
    query_pub_table: jnp.ndarray
    dlog: eg.DecryptionTable

    @classmethod
    def create(cls, n_servers: int = 3, dlog_limit: int = 10000, seed: int = 4):
        rng = np.random.default_rng(seed)
        secrets, pubs = zip(*[eg.keygen(rng) for _ in range(n_servers)])
        coll = col.collective_key(pubs)
        qx, qpub = eg.keygen(rng)
        return cls(
            server_secrets=np.stack([eg.secret_to_limbs(x) for x in secrets]),
            coll_pub_table=eg.pub_table(coll).table,
            query_secret=qx,
            query_pub_table=eg.pub_table(qpub).table,
            dlog=eg.DecryptionTable(limit=dlog_limit),
        )


def _tree_reduce_points(pts):
    """Reduce axis 0 of a point/ct tensor by repeated halving (log2 depth);
    on TPU the whole reduction is one Pallas kernel call."""
    from .crypto import pallas_ops as po

    if po.available() and pts.shape[0] > 1:
        R = pts.shape[0]
        mid = pts.shape[1:-2]
        out = po.point_reduce_flat(pts.reshape((R, -1, 3, 16)))
        return out.reshape(mid + (3, 16))
    n = pts.shape[0]
    while n > 1:
        half = n // 2
        even = pts[: 2 * half : 2]
        odd = pts[1 : 2 * half : 2]
        red = C.add(even, odd)
        if n % 2:
            red = jnp.concatenate([red, pts[-1:]], axis=0)
        pts = red
        n = pts.shape[0]
    return pts[0]


def build_pipeline(setup: SurveySetup, params: lr.LRParams):
    """Single-chip jitted survey step.

    Returns fn(dp_stats, enc_rs, ks_rs) -> (weights, dec_ints, found):
      dp_stats: int64 (n_dps, V) local fixed-point stat vectors
      enc_rs:   uint32 (n_dps, V, 16) encryption blinding scalars
      ks_rs:    uint32 (n_servers, V, 16) key-switch randomness
    """
    base_tbl = eg.BASE_TABLE.table
    coll_tbl = setup.coll_pub_table
    q_tbl = setup.query_pub_table
    srv_x = jnp.asarray(setup.server_secrets)
    qx = jnp.asarray(eg.secret_to_limbs(setup.query_secret))
    dl = setup.dlog
    keys, xs, ysign, vals = dl.keys, dl.xs, dl.ysign, dl.vals

    def fn(dp_stats, enc_rs, ks_rs):
        # DP-side: encrypt every stat of every DP (one big batch; int64
        # plaintexts ride the truncated small-scalar ladder).
        cts = eg.encrypt_ints_with_tables(base_tbl, coll_tbl, dp_stats,
                                          enc_rs)
        # Collective aggregation (CN tree -> on-chip tree reduce).
        agg = _tree_reduce_points(cts)
        # Key switch: per-server contributions (broadcast batch — one big
        # flat batch feeds the Pallas ladder kernel), then reduce.
        kc, cc = col.keyswitch_contribution(
            agg[None], srv_x[:, None, :], ks_rs, q_tbl)
        switched = col.keyswitch_finish(
            agg, _tree_reduce_points(kc), _tree_reduce_points(cc))
        # Querier decrypt + discrete log.
        pts = eg.decrypt_point(switched, qx)
        dec, found = eg._table_lookup(keys, xs, ysign, vals, pts)
        # Gradient descent on the approximated cost.
        Ts = lr.unpack(dec, params)
        w = lr.train(Ts, params)
        return w, dec, found

    return fn


def make_inputs(X, y, params: lr.LRParams, num_dps: int = 10, seed: int = 0):
    """Host-side: per-DP stats + randomness for the pipeline."""
    stats = np.stack([
        np.asarray(lr.encode_clear(*lr.shard_for_dp(X, y, i, num_dps), params))
        for i in range(num_dps)
    ])
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    V = stats.shape[1]
    enc_rs = eg.random_scalars(k1, (num_dps, V))
    return jnp.asarray(stats), enc_rs, k1, k2


def pima_shaped_problem(num_dps: int = 10, n_records: int = 768, d: int = 8,
                        max_iterations: int = 450):
    """Pima-benchmark-shaped problem (reference TIFS/logRegV2.py setting:
    768 records x 10 DPs, 8 features, K=2, 450 iterations).

    Every DP gets DISTINCT rows: one pool of num_dps*n_records records is
    row-sharded i % num_dps (reference GetDataForDataProvider,
    logistic_regression.go:1427-1443) — n_records rows PER DP, i.e. 10x the
    reference's per-DP load, and no two DPs hold the same data."""
    X, y = lr.synthetic_dataset(n=n_records * num_dps, d=d, seed=13)
    p = lr.LRParams(
        k=2, precision=1.0, lambda_=1.0, step=0.1,
        max_iterations=max_iterations, n_features=d,
        n_records=len(y), dtype="float32",
        means=tuple(np.mean(X, 0)), std_devs=tuple(np.std(X, 0)))
    return X, y, p


__all__ = ["SurveySetup", "build_pipeline", "make_inputs",
           "pima_shaped_problem"]
