"""Model families: encrypted-training logistic regression (flagship) and the
linear-regression / model-evaluation paths built on the encoders."""
from . import logreg  # noqa: F401
