"""Encrypted logistic regression — the flagship workload.

Re-design of the reference's largest component
(lib/encoding/logistic_regression.go, 1579 LoC; see SURVEY.md §2.1 #14, §3.4):
the log-loss is approximated by a degree-k polynomial in the margin w·x, so a
DP's whole contribution reduces to the sign-weighted outer-power tensors

    T_j = Σ_i  s_j(y_i) · x_i^{⊗j},   j = 1..k,
    s_j(y) = 2y−1  for odd j,  −1  for even j
    (reference ComputeAllApproxCoefficients, logistic_regression.go:367-403:
     ypart = y − y·(−1)^j − 1 over labels y ∈ {0,1})

computed here as single einsums over the record batch — the reference's
per-record CartesianProduct loops (logistic_regression.go:383-396) become one
MXU-friendly contraction. Training on the querier side is gradient descent on
the polynomial cost (reference Cost/Gradient/FindMinimumWeights,
logistic_regression.go:526-742); the hand-derived symmetric-tensor derivative
is replaced by `jax.grad`, and the whole GD loop is one jitted
`lax.fori_loop` — this function is the framework's flagship jittable step.

Approximation coefficients (reference logistic_regression.go:30-36):
  Taylor  : [−ln 2, −1/2, −1/8, 0, 0.0052]
  MinArea : [−0.714761, −0.5, −0.0976419]   (default, k = 2)
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

TAYLOR_COEFFS = (-math.log(2.0), -0.5, -0.125, 0.0, 0.0052)
MIN_AREA_COEFFS = (-0.714761, -0.5, -0.0976419)


@dataclasses.dataclass
class LRParams:
    """Mirror of the reference's LogisticRegressionParameters
    (lib/structs.go:210-228)."""

    k: int = 2
    precision: float = 1e2       # PrecisionApproxCoefficients
    lambda_: float = 1.0
    step: float = 0.1
    max_iterations: int = 25
    initial_weights: tuple = ()
    n_features: int = 0
    n_records: int = 0
    means: tuple | None = None    # global standardisation, optional
    std_devs: tuple | None = None
    coeffs: tuple = MIN_AREA_COEFFS
    # GD dtype: float64 matches the reference's math everywhere; float32 is
    # the TPU-native choice (f64 is software-emulated on TPU) — use it when
    # training on-device; the decrypted-ints-identical invariant is unaffected
    dtype: str = "float64"

    def num_coeffs(self) -> int:
        dp1 = self.n_features + 1
        return sum(dp1 ** j for j in range(1, self.k + 1))


# ---------------------------------------------------------------------------
# Preprocessing (reference logistic_regression.go:905-1047)
# ---------------------------------------------------------------------------

def standardise(X, means=None, std_devs=None):
    """x' = (x − mean)/std, population std (ddof=0) like the reference's
    montanaflynn/stats.StandardDeviation."""
    X = jnp.asarray(X, dtype=jnp.float64)
    mu = jnp.mean(X, axis=0) if means is None else jnp.asarray(means)
    sd = jnp.std(X, axis=0) if std_devs is None else jnp.asarray(std_devs)
    return (X - mu) / sd


def normalize(X, mins=None, maxs=None):
    X = jnp.asarray(X, dtype=jnp.float64)
    lo = jnp.min(X, axis=0) if mins is None else jnp.asarray(mins)
    hi = jnp.max(X, axis=0) if maxs is None else jnp.asarray(maxs)
    return (X - lo) / (hi - lo)


def augment(X):
    """Prepend the all-ones offset column."""
    X = jnp.asarray(X)
    return jnp.concatenate([jnp.ones((X.shape[0], 1), X.dtype), X], axis=1)


# ---------------------------------------------------------------------------
# DP-side encoding: approximation tensors -> fixed-point int vector
# ---------------------------------------------------------------------------

def _einsum_spec(j: int) -> str:
    idx = "abcdefgh"[:j]
    return "n," + ",".join(f"n{c}" for c in idx) + "->" + idx


def approx_tensors(Xa, y, k: int):
    """T_j for j=1..k as FLAT float arrays. Xa: augmented standardized
    records (n, d+1); y: labels {0,1} (n,)."""
    Xa = jnp.asarray(Xa, dtype=jnp.float64)
    y = jnp.asarray(y, dtype=jnp.float64)
    sign_odd = 2.0 * y - 1.0
    out = []
    for j in range(1, k + 1):
        s = sign_odd if j % 2 == 1 else -jnp.ones_like(y)
        args = [s] + [Xa] * j
        T = jnp.einsum(_einsum_spec(j), *args)
        out.append(T.reshape(-1))
    return out


def encode_clear(X, y, p: LRParams):
    """One DP's packed int64 statistics vector (ready for encryption)."""
    Xs = standardise(X, p.means, p.std_devs)
    Xa = augment(Xs)
    Ts = approx_tensors(Xa, y, p.k)
    packed = jnp.concatenate(Ts)
    return jnp.round(packed * p.precision).astype(jnp.int64)


def unpack(dec_ints, p: LRParams):
    """Decrypted aggregated ints -> per-degree float tensors (rescaled)."""
    dp1 = p.n_features + 1
    vals = jnp.asarray(dec_ints, dtype=jnp.float64) / p.precision
    out, off = [], 0
    for j in range(1, p.k + 1):
        n = dp1 ** j
        out.append(vals[off:off + n])
        off += n
    return out


# ---------------------------------------------------------------------------
# Querier-side training (polynomial cost + autodiff GD, fully jitted)
# ---------------------------------------------------------------------------

def cost(w, Ts, N, lambda_, coeffs):
    """Approximated, l2-regularized mean log-loss (reference Cost,
    logistic_regression.go:526-560 — with the per-degree coefficients
    applied independently, as the reference's Gradient does)."""
    dp1 = w.shape[0]
    c = jnp.zeros((), w.dtype)
    for j, Tf in enumerate(Ts, start=1):
        contr = Tf.reshape((dp1,) * j)
        for _ in range(j):
            contr = jnp.tensordot(contr, w, axes=([0], [0]))
        c = c + coeffs[j] * contr
    c = c / N - coeffs[0]
    reg = jnp.sum(w[1:] * w[1:])
    return c + lambda_ / (2.0 * N) * reg


def closed_form_k1(T1, lambda_, coeffs):
    """k = 1 minimiser (reference ComputeMinimumWeights,
    logistic_regression.go:680-691)."""
    return -coeffs[1] * T1 / lambda_


def train(Ts, p: LRParams):
    """GD on the approximated cost; jitted fori_loop. Returns weights."""
    dp1 = p.n_features + 1
    coeffs = tuple(p.coeffs)
    dt = jnp.dtype(p.dtype)
    Ts = [jnp.asarray(T, dtype=dt) for T in Ts]
    if p.k == 1:
        return closed_form_k1(Ts[0], p.lambda_, coeffs)

    w0 = (jnp.asarray(p.initial_weights, dtype=dt)
          if len(p.initial_weights) else jnp.zeros((dp1,), dt))
    N = float(p.n_records)

    cost_fn = lambda w: cost(w, Ts, N, p.lambda_, coeffs)
    grad_fn = jax.grad(cost_fn)

    def body(_, state):
        w, best_w, best_c = state
        c = cost_fn(w)
        better = c < best_c
        best_w = jnp.where(better, w, best_w)
        best_c = jnp.where(better, c, best_c)
        w = w - p.step * grad_fn(w)
        return (w, best_w, best_c)

    w, best_w, best_c = jax.lax.fori_loop(
        0, p.max_iterations, body, (w0, w0, jnp.asarray(jnp.inf, dt)))
    final_c = cost_fn(w)
    return jnp.where(final_c < best_c, w, best_w)


train_jit = jax.jit(train, static_argnames="p")


# ---------------------------------------------------------------------------
# Prediction + metrics (reference logistic_regression.go:821-899, 1101-1164)
# ---------------------------------------------------------------------------

def sigmoid(z):
    return 1.0 / (1.0 + jnp.exp(-z))


def predict_probs(X, w, means=None, std_devs=None):
    Xa = augment(standardise(X, means, std_devs))
    return sigmoid(Xa @ w)


def predict(X, w, means=None, std_devs=None, threshold=0.5):
    return (predict_probs(X, w, means, std_devs) >= threshold).astype(jnp.int64)


def fold_affine(w, means=None, std_devs=None):
    """Fold standardisation into the weight vector so it acts on RAW records:
    w·[(x−mu)/sd] + w0  ==  w'·x + w0'. Returns (w0', w' (d,))."""
    if (means is None) != (std_devs is None):
        raise ValueError("means and std_devs must be given together")
    w = np.asarray(w, dtype=np.float64)
    w0, wf = float(w[0]), w[1:]
    if means is None:
        return w0, wf
    mu = np.asarray(means, dtype=np.float64)
    sd = np.asarray(std_devs, dtype=np.float64)
    return w0 - float(np.sum(wf * mu / sd)), wf / sd


def predict_homomorphic_ct(cts, w, means=None, std_devs=None,
                           precision=100.0):
    """Encrypted margin ciphertext from per-feature ciphertexts of a RAW
    record (reference PredictHomomorphic, logistic_regression.go:869-899).

    cts: (..., d, 2, 3, 16) — one ciphertext per raw feature value.
    Clear weights are folded with the standardisation and fixed-point scaled;
    the margin is Σ_j round(P·w'_j)·ct_j + Enc₀(round(P·w0')), i.e. scalar
    mults + homomorphic adds only. Decrypt with a dlog table and divide by
    `precision` to recover ≈ w·x_std + w0.
    """
    from ..crypto import elgamal as eg
    from ..crypto import curve as Cv

    w0p, wp = fold_affine(w, means, std_devs)
    w_int = jnp.asarray(np.round(np.asarray(wp) * precision), jnp.int64)
    s = eg.int_to_scalar(w_int)                # (d, 16)
    terms = eg.ct_scalar_mul(cts, s)           # negative-safe mod n

    def body(acc, t):
        return eg.ct_add(acc, t), None

    acc0 = eg.ct_zero(cts.shape[:-4])
    margin, _ = jax.lax.scan(body, acc0, jnp.moveaxis(terms, -4, 0))

    # + Enc₀(w0'): add w0'·B to the C component only (K unchanged).
    w0_int = jnp.asarray(round(w0p * precision), jnp.int64)
    w0B = eg.fixed_base_mul(eg.BASE_TABLE.table, eg.int_to_scalar(w0_int))
    K, Cc = margin[..., 0, :, :], margin[..., 1, :, :]
    return jnp.stack([K, Cv.add(Cc, w0B)], axis=-3)


def predict_homomorphic(cts, w, secret: int, table, means=None,
                        std_devs=None, precision=100.0, threshold=0.5):
    """Full homomorphic prediction: encrypted raw records ->
    (probs, preds, found). `table` must cover the fixed-point margin range
    (|P·(w·x+w0)|); entries with found=False had no dlog-table hit and
    their probs are garbage — callers must check."""
    from ..crypto import elgamal as eg

    mct = predict_homomorphic_ct(cts, w, means, std_devs, precision)
    margin_int, found = eg.decrypt_ints(mct, secret, table)
    probs = sigmoid(jnp.asarray(margin_int, jnp.float64) / precision)
    return probs, (probs >= threshold).astype(jnp.int64), found


def accuracy(pred, actual):
    pred, actual = np.asarray(pred), np.asarray(actual)
    return float(np.mean(pred == actual))


def precision(pred, actual):
    pred, actual = np.asarray(pred), np.asarray(actual)
    tp = int(np.sum((pred == 1) & (actual == 1)))
    fp = int(np.sum((pred == 1) & (actual == 0)))
    return tp / (tp + fp) if tp + fp else 0.0


def recall(pred, actual):
    pred, actual = np.asarray(pred), np.asarray(actual)
    tp = int(np.sum((pred == 1) & (actual == 1)))
    fn = int(np.sum((pred == 0) & (actual == 1)))
    return tp / (tp + fn) if tp + fn else 0.0


def f_score(pred, actual):
    pr, rc = precision(pred, actual), recall(pred, actual)
    return 2 * pr * rc / (pr + rc) if pr + rc else 0.0


def auc(probs, actual):
    """Area under the ROC curve (trapezoidal, like gonum integrate)."""
    probs, actual = np.asarray(probs, float), np.asarray(actual)
    order = np.argsort(-probs, kind="stable")
    lab = actual[order]
    P, Nn = int(lab.sum()), int((1 - lab).sum())
    if P == 0 or Nn == 0:
        return 0.0
    tpr = np.concatenate([[0.0], np.cumsum(lab) / P])
    fpr = np.concatenate([[0.0], np.cumsum(1 - lab) / Nn])
    return float(np.trapezoid(tpr, fpr))


# ---------------------------------------------------------------------------
# Dataset loading + DP sharding (reference logistic_regression.go:1275-1443)
# ---------------------------------------------------------------------------

def load_csv(path, label_column=0, sep=","):
    """CSV -> (X float64 (n, d), y int64 (n,))."""
    raw = np.loadtxt(path, delimiter=sep, ndmin=2)
    y = raw[:, label_column].astype(np.int64)
    X = np.delete(raw, label_column, axis=1)
    return X, y


def shard_for_dp(X, y, dp_id: int, num_dps: int):
    """Row-shard i % num_dps == dp_id (reference GetDataForDataProvider,
    logistic_regression.go:1427-1443)."""
    idx = np.arange(len(y)) % num_dps == dp_id
    return X[idx], y[idx]


def synthetic_dataset(n=768, d=8, seed=0):
    """Pima-shaped synthetic binary-classification data (for benches/tests
    when no CSV is available)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)) * rng.uniform(0.5, 3.0, size=d) + \
        rng.uniform(-2, 2, size=d)
    w_true = rng.normal(size=d + 1)
    z = w_true[0] + ((X - X.mean(0)) / X.std(0)) @ w_true[1:]
    y = (1 / (1 + np.exp(-z)) > rng.uniform(size=n)).astype(np.int64)
    return X, y


__all__ = [
    "TAYLOR_COEFFS", "MIN_AREA_COEFFS", "LRParams",
    "standardise", "normalize", "augment", "approx_tensors", "encode_clear",
    "unpack", "cost", "closed_form_k1", "train", "train_jit",
    "sigmoid", "predict_probs", "predict",
    "fold_affine", "predict_homomorphic_ct", "predict_homomorphic",
    "accuracy", "precision", "recall", "f_score", "auc",
    "load_csv", "shard_for_dp", "synthetic_dataset",
]
