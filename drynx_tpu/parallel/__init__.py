"""Distributed collectives for encrypted tensors over a jax.sharding.Mesh.

TPU-first re-design of the reference's onet tree protocols (SURVEY.md §2.3):
the CN aggregation tree becomes a butterfly all-reduce of EC-point limb
tensors over an ICI mesh axis; sequential per-server key-switching becomes a
single all-reduce of commuting per-server contributions; the obfuscation
protocol's chain of scalar multiplications collapses to one scalar-mult by
the all-reduced product of server scalars.
"""
from .collective import (  # noqa: F401
    allreduce_group_add,
    allreduce_scalar_mul,
    collective_key,
    keyswitch_contribution,
    keyswitch_finish,
    make_mesh,
)
