"""Encrypted collectives: group-law all-reduce, key-switch, obfuscation.

Replaces the reference's protocol layer (SURVEY.md §2.1 #18-19, #21 and the
unlynx CollectiveAggregation / KeySwitching protocols used at
services/service.go:465-616):

* Aggregation: the n-ary CN tree (`GenerateNaryTreeWithRoot(2,...)`,
  services/service.go:676) becomes `allreduce_group_add` — a log2(n)-step
  XOR-butterfly of `ppermute` + Jacobian point adds riding ICI.

* Key-switching: the reference walks servers sequentially, each partially
  decrypting and re-encrypting (unlynx KeySwitchSequence). The per-server
  contributions commute:
      K_new = Σ_i r_i·B,   C_new = C + Σ_i (r_i·Q − x_i·K)
  so one all-reduce of contributions replaces the server chain.

* Obfuscation: each server multiplying every ciphertext by a fresh scalar
  (protocols/obfuscation_protocol.go:241-243) telescopes to ONE scalar-mult
  by ∏_i s_i — computed with a log-step all-reduce in Fn (Montgomery mul as
  the combiner) — preserving exactly the zero/nonzero semantics.

All functions here are designed to run inside `shard_map` over a named mesh
axis; they are pure and jit-safe.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import elgamal as eg
from ..crypto import field as F
from ..crypto import refimpl
from ..crypto.field import FN


def make_mesh(n_devices: int | None = None, axis: str = "srv"):
    """1-D device mesh over the server/DP axis."""
    devs = jax.devices()
    n = n_devices or len(devs)
    return jax.sharding.Mesh(np.asarray(devs[:n]), (axis,))


# ---------------------------------------------------------------------------
# All-reduce with custom combiners (butterfly for 2^k, ring otherwise)
# ---------------------------------------------------------------------------

def _allreduce(x, axis: str, axis_size: int, combine):
    if axis_size == 1:
        return x
    if axis_size & (axis_size - 1) == 0:
        k = 1
        while k < axis_size:
            perm = [(i, i ^ k) for i in range(axis_size)]
            x = combine(x, jax.lax.ppermute(x, axis, perm))
            k *= 2
        return x
    # ring all-reduce: n-1 shifted adds
    acc = x
    cur = x
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for _ in range(axis_size - 1):
        cur = jax.lax.ppermute(cur, axis, perm)
        acc = combine(acc, cur)
    return acc


def allreduce_group_add(ct, axis: str, axis_size: int):
    """All-reduce homomorphic sum of ciphertexts/points over a mesh axis.

    `ct`: any (..., 3, 16) point tensor (ciphertexts (..., 2, 3, 16) work
    because the group add batches over all leading dims).
    """
    return _allreduce(ct, axis, axis_size, C.add)


def allreduce_scalar_mul(s_plain, axis: str, axis_size: int):
    """All-reduce PRODUCT of mod-n scalars (plain limbs in, plain out)."""
    s_mont = F.to_mont(s_plain, FN)
    combine = partial(F.mont_mul, ctx=FN)
    prod = _allreduce(s_mont, axis, axis_size, combine)
    return F.from_mont(prod, FN)


# ---------------------------------------------------------------------------
# Collective key (host-side setup)
# ---------------------------------------------------------------------------

def collective_key(pubs):
    """Sum of server public keys (host affine ints) -> collective pub."""
    acc = None
    for p in pubs:
        acc = refimpl.g1_add(acc, p)
    return acc


# ---------------------------------------------------------------------------
# Key-switching (collective re-encryption to the querier's key)
# ---------------------------------------------------------------------------

def keyswitch_contribution(ct, x_limbs, r_limbs, query_pub_table,
                           base_table=None):
    """One server's key-switch contribution for a (batch of) ciphertext(s).

    ct: (..., 2, 3, 16) under the collective key (replicated across servers).
    x_limbs: this server's secret scalar (16,). r_limbs: fresh randomness,
    shape ct.shape[:-3] + (16,). Returns (K_contrib, C_contrib) points.
    """
    base_table = base_table if base_table is not None else eg.BASE_TABLE.table
    K = ct[..., 0, :, :]
    xK = C.scalar_mul(K, x_limbs)
    rB = eg.fixed_base_mul(base_table, r_limbs)
    rQ = eg.fixed_base_mul(query_pub_table, r_limbs)
    return rB, C.add(rQ, C.neg(xK))


def keyswitch_finish(ct, k_sum, c_sum):
    """Assemble the switched ciphertext from all-reduced contributions."""
    C_new = C.add(ct[..., 1, :, :], c_sum)
    return jnp.stack([k_sum, C_new], axis=-3)


def keyswitch_collective(ct, x_limbs, r_limbs, query_pub_table, axis: str,
                         axis_size: int):
    """Full in-mesh key switch: per-server contribution + all-reduce."""
    kc, cc = keyswitch_contribution(ct, x_limbs, r_limbs, query_pub_table)
    k_sum = allreduce_group_add(kc, axis, axis_size)
    c_sum = allreduce_group_add(cc, axis, axis_size)
    return keyswitch_finish(ct, k_sum, c_sum)


def obfuscate_collective(ct, s_limbs, axis: str, axis_size: int):
    """In-mesh obfuscation: ct * ∏ servers' scalars (zero/nonzero-preserving)."""
    s_prod = allreduce_scalar_mul(s_limbs, axis, axis_size)
    return eg.ct_scalar_mul(ct, s_prod)


__all__ = [
    "make_mesh", "allreduce_group_add", "allreduce_scalar_mul",
    "collective_key", "keyswitch_contribution", "keyswitch_finish",
    "keyswitch_collective", "obfuscate_collective",
]
