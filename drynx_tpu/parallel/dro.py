"""Differential privacy: quantized Laplace noise + distributed results
obfuscation (DRO) via re-randomized shuffling.

Reference semantics (SURVEY.md §2.2): the DRO phase builds a list of
encrypted, quantized Laplace noise values; servers shuffle + re-randomize the
list so no one knows which noise value lands on which result; one noise
ciphertext is added per result at the key-switch root
(reference services/service.go:600-604, 619-665; noise list from unlynx
GenerateNoiseValuesScale at service.go:657).

The noise list is DETERMINISTIC (privacy comes from the secret shuffle, not
from sampling): quantized values 0, ±q, ±2q, ... are repeated proportionally
to the Laplace(mean, b) density until `size` values exist.

TPU-first shuffle: each server applies a secret permutation (device PRNG) and
re-randomizes every ciphertext by adding a fresh encryption of zero — the
composition over servers is the reference's Neff-shuffle pipeline's effect.
The shuffle proof itself lives in drynx_tpu.proofs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import elgamal as eg


def generate_noise_values(size: int, mean: float, b: float, quanta: float,
                          scale: float = 1.0, limit: float = 0.0) -> np.ndarray:
    """Deterministic quantized-Laplace noise list (int64, scaled).

    Mirrors unlynx GenerateNoiseValuesScale as used at reference
    services/service.go:657: values v = mean ± k*quanta, each repeated
    proportionally to exp(-|v-mean|/b); `scale` multiplies values before
    int64 quantization; `limit` (if nonzero) truncates |v| <= limit.
    """
    if size <= 0:
        return np.zeros((0,), dtype=np.int64)
    vals: list[float] = []
    k = 0
    while len(vals) < size:
        for v in ([mean] if k == 0 else [mean + k * quanta, mean - k * quanta]):
            if limit and abs(v) > limit:
                continue
            dens = math.exp(-abs(v - mean) / b)
            rep = max(1, int(round(dens * size * quanta / (2.0 * b))))
            vals.extend([v] * rep)
            if len(vals) >= size:
                break
        k += 1
        if k > 10 * size:  # safety for degenerate params
            break
    out = np.asarray(vals[:size], dtype=np.float64) * scale
    return np.round(out).astype(np.int64)


def encrypt_noise(key, pub_table: eg.FixedBase, noise: np.ndarray):
    """Encrypt the noise list under the collective key."""
    ct, _ = eg.encrypt_ints(key, pub_table, jnp.asarray(noise))
    return ct


def precompute_rerandomization(key, pub_tbl, size: int, base_tbl=None):
    """Precompute the expensive half of a shuffle step: `size` fresh
    encryptions of zero (r·B, r·P) plus their scalars.

    The reference caches exactly this per server across surveys
    (`pre_compute_multiplications.gob`, services/service.go:34,316-317 +
    unlynx PrecomputationWritingForShuffling) — it is what makes the
    1M-element DRO noise lists survivable. Returns (zero_cts, r) usable as
    the `precomp` argument of shuffle_rerandomize.
    """
    base_tbl = base_tbl if base_tbl is not None else eg.BASE_TABLE.table
    r = eg.random_scalars(key, (size,))
    zeros = jnp.zeros((size,), dtype=jnp.int64)
    zero_ct = eg.encrypt_with_tables(base_tbl, pub_tbl,
                                     eg.int_to_scalar(zeros), r)
    return zero_ct, r


def save_precompute(path: str, precomp) -> None:
    """Persist a precomputation (the reference's gob-file equivalent)."""
    zero_ct, r = precomp
    np.savez(path, zero_ct=np.asarray(zero_ct), r=np.asarray(r))


def load_precompute(path: str):
    d = np.load(path)
    return jnp.asarray(d["zero_ct"]), jnp.asarray(d["r"])


def shuffle_rerandomize(key, cts, pub_tbl, base_tbl=None, precomp=None):
    """One server's DRO step: secret permutation + re-randomization.

    cts: (S, 2, 3, 16). Returns (shuffled cts, permutation, rerand scalars)
    — the latter two feed the shuffle proof. `precomp` (from
    precompute_rerandomization) skips the S fixed-base scalar-mults — the
    hot cost at reference noise sizes (10k..1M, TIFS/diffPri.py).
    """
    S = cts.shape[0]
    kperm, krand = jax.random.split(key)
    perm = jax.random.permutation(kperm, S)
    shuffled = jnp.take(cts, perm, axis=0)
    if precomp is not None:
        zero_ct, r = precomp
        assert zero_ct.shape[0] == S, (zero_ct.shape, S)
    else:
        base_tbl = base_tbl if base_tbl is not None else eg.BASE_TABLE.table
        r = eg.random_scalars(krand, (S,))
        zeros = jnp.zeros((S,), dtype=jnp.int64)
        zero_ct = eg.encrypt_with_tables(base_tbl, pub_tbl,
                                         eg.int_to_scalar(zeros), r)
    return eg.ct_add(shuffled, zero_ct), perm, r


def dro_pipeline(key, pub_tbl, size: int, mean: float, b: float,
                 quanta: float, scale: float = 1.0, limit: float = 0.0,
                 n_servers: int = 3):
    """Full noise phase: generate, encrypt, pass through every server's
    shuffle+rerandomize. Returns the final encrypted noise list."""
    noise = generate_noise_values(size, mean, b, quanta, scale, limit)
    key, sub = jax.random.split(key)
    cts = encrypt_noise(sub, pub_tbl, noise)
    for _ in range(n_servers):
        key, sub = jax.random.split(key)
        cts, _, _ = shuffle_rerandomize(sub, cts, pub_tbl.table)
    return cts, noise


__all__ = ["generate_noise_values", "encrypt_noise", "shuffle_rerandomize",
           "precompute_rerandomization", "save_precompute", "load_precompute",
           "dro_pipeline"]
