"""Differential privacy: quantized Laplace noise + distributed results
obfuscation (DRO) via re-randomized shuffling.

Reference semantics (SURVEY.md §2.2): the DRO phase builds a list of
encrypted, quantized Laplace noise values; servers shuffle + re-randomize the
list so no one knows which noise value lands on which result; one noise
ciphertext is added per result at the key-switch root
(reference services/service.go:600-604, 619-665; noise list from unlynx
GenerateNoiseValuesScale at service.go:657).

The noise list is DETERMINISTIC (privacy comes from the secret shuffle, not
from sampling): quantized values 0, ±q, ±2q, ... are repeated proportionally
to the Laplace(mean, b) density until `size` values exist.

TPU-first shuffle: each server applies a secret permutation (device PRNG) and
re-randomizes every ciphertext by adding a fresh encryption of zero — the
composition over servers is the reference's Neff-shuffle pipeline's effect.
The shuffle proof itself lives in drynx_tpu.proofs.

Scale (reference TIFS/diffPri.py: noise lists 10k -> 1M, 81.9 -> 5872 s):
above CHUNK elements the precompute and the shuffle re-randomization run in
fixed-size slabs dispatched over the proof plane's `dp`-axis devices
(parallel/proof_plane.dispatch_shards) instead of one (S, 2, 3, 16)
dispatch. The global permutation stays exact — indices are permuted on the
host and each slab gathers its slice — and every chunked output is
byte-identical to the unchunked path for the same key (all the per-element
crypto is element-wise; tests/test_scale_axes.py asserts it).

API convention: `FixedBase` objects stop at the encryption boundary
(encrypt_noise, dro_pipeline); the shuffle/precompute layer takes raw
(64, 16, 3, 16) limb tables (`FixedBase.table`) and asserts it was not
handed the wrapper — the two used to be silently interchangeable here,
which hid a real type error in dro_pipeline.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import elgamal as eg
from ..resilience.policy import named_lock

# Slab width for chunked precompute / shuffle re-randomization: matches
# the g1 family's max_bucket (crypto/batching.py) and the bucket-grid
# tile (encoding/tiles.py), so slab dispatches land on the same warm
# program sizes.
CHUNK = 4096


def _noise_reps(vs: np.ndarray, mean: float, b: float, quanta: float,
                size: int) -> np.ndarray:
    """Vectorized per-value repetition counts of the density grid.

    np.round matches Python round() (both half-to-even) and np.exp is the
    same libm exp() the scalar loop called — the golden test
    (tests/test_scale_axes.py) pins equality with the reference loop."""
    dens = np.exp(-np.abs(vs - mean) / b)
    return np.maximum(
        1, np.round(dens * size * quanta / (2.0 * b)).astype(np.int64))


def generate_noise_values(size: int, mean: float, b: float, quanta: float,
                          scale: float = 1.0, limit: float = 0.0) -> np.ndarray:
    """Deterministic quantized-Laplace noise list (int64, scaled).

    Mirrors unlynx GenerateNoiseValuesScale as used at reference
    services/service.go:657: values v = mean ± k*quanta, each repeated
    proportionally to exp(-|v-mean|/b); `scale` multiplies values before
    int64 quantization; `limit` (if nonzero) truncates |v| <= limit.

    Vectorized as a NumPy density grid: the interpreted while/extend
    accumulation was O(size) list growth — at the reference's 1M sizes it
    dominated the phase. Output is exactly `_generate_noise_values_ref`'s
    (golden-tested)."""
    if size <= 0:
        return np.zeros((0,), dtype=np.int64)
    total = 0
    vals = np.zeros((0,), dtype=np.float64)
    k_lo, k_hi = 0, 0
    grow = max(64, int(math.isqrt(size)))
    while total < size and k_lo <= 10 * size:
        k_hi = min(k_lo + grow, 10 * size + 1)
        ks = np.arange(k_lo, k_hi, dtype=np.int64)
        # candidate order within the loop: [m] then (m+kq, m-kq) pairs
        vs = np.empty(2 * ks.size, dtype=np.float64)
        vs[0::2] = mean + ks * quanta
        vs[1::2] = mean - ks * quanta
        if k_lo == 0:
            vs = np.concatenate([vs[:1], vs[2:]])  # k=0 contributes once
        if limit:
            vs = vs[np.abs(vs) <= limit]
        if vs.size:
            reps = _noise_reps(vs, mean, b, quanta, size)
            cum = np.cumsum(reps)
            cut = int(np.searchsorted(cum, size - total))
            if cut < vs.size:  # target reached inside this block
                vals = np.concatenate(
                    [vals, np.repeat(vs[:cut + 1], reps[:cut + 1])])
                total += int(cum[cut])
                break
            vals = np.concatenate([vals, np.repeat(vs, reps)])
            total += int(cum[-1])
        k_lo = k_hi
        grow *= 2
    out = vals[:size] * scale
    return np.round(out).astype(np.int64)


def _generate_noise_values_ref(size: int, mean: float, b: float,
                               quanta: float, scale: float = 1.0,
                               limit: float = 0.0) -> np.ndarray:
    """The original interpreted accumulation, kept verbatim as the golden
    reference for the vectorized construction (unit-test only)."""
    if size <= 0:
        return np.zeros((0,), dtype=np.int64)
    vals: list[float] = []
    k = 0
    while len(vals) < size:
        for v in ([mean] if k == 0 else [mean + k * quanta, mean - k * quanta]):
            if limit and abs(v) > limit:
                continue
            dens = math.exp(-abs(v - mean) / b)
            rep = max(1, int(round(dens * size * quanta / (2.0 * b))))
            vals.extend([v] * rep)
            if len(vals) >= size:
                break
        k += 1
        if k > 10 * size:  # safety for degenerate params
            break
    out = np.asarray(vals[:size], dtype=np.float64) * scale
    return np.round(out).astype(np.int64)


def _require_table(tbl, who: str):
    """The shuffle/precompute layer's convention: raw limb tables only."""
    if isinstance(tbl, eg.FixedBase):
        raise TypeError(
            f"{who} takes a raw fixed-base table (FixedBase.table), got a "
            f"FixedBase wrapper — unwrap it at the encryption boundary")
    return tbl


def encrypt_noise(key, pub_table: eg.FixedBase, noise: np.ndarray):
    """Encrypt the noise list under the collective key."""
    if not isinstance(pub_table, eg.FixedBase):
        raise TypeError("encrypt_noise takes the FixedBase wrapper "
                        "(the encryption boundary); got a raw table")
    ct, _ = eg.encrypt_ints(key, pub_table, jnp.asarray(noise))
    return ct


def _chunk_of(size: int, chunk) -> int:
    """Effective slab width: None = auto (CHUNK above CHUNK elements),
    0 = force unchunked, positive = forced width."""
    if chunk is None:
        return CHUNK if size > CHUNK else 0
    return int(chunk)


def slab_widths(size: int, chunk: int | None = None) -> list[int]:
    """Distinct dispatch widths the chunked DRO path uses at ``size``
    (at most two: the slab width and a remainder). The compilecache
    registry certifies the pool programs at exactly these widths
    (compilecache/registry._pool_specs, Profile.n_noise)."""
    if size <= 0:
        return []
    eff = _chunk_of(size, chunk)
    if not eff or eff >= size:
        return [size]
    return sorted({min(a + eff, size) - a for a in range(0, size, eff)})


# Builder-invocation counter: increments on every FRESH precompute (the
# expensive fixed-base pass a warm pool exists to skip). The restart test
# (tests/test_pool.py) asserts it stays flat across a simulated restart
# with a warm pool — the pooled path must never fall through to here.
PRECOMPUTE_CALLS = 0
# Scheduler lanes can precompute concurrently; a bare += here would lose
# increments and flake the restart test's stays-flat assertion.
_PRECOMPUTE_COUNT_LOCK = named_lock("precompute_count_lock")


def _encrypt_zeros_chunked(r, pub_tbl, base_tbl, chunk: int, phase: str):
    """Fresh zero-encryptions for blinding scalars r, in `chunk`-wide slabs
    dispatched over the proof plane (element-wise: slab concatenation is
    byte-identical to one full dispatch)."""
    from . import proof_plane as plane

    size = int(r.shape[0])
    eff = _chunk_of(size, chunk)
    if not eff or eff >= size:
        zeros = jnp.zeros((size,), dtype=jnp.int64)
        return eg.encrypt_with_tables(base_tbl, pub_tbl,
                                      eg.int_to_scalar(zeros), r)

    def stage(i, a, b):
        return a, b, plane.put_shard(r[a:b], i, donate=True)

    def slab(i, a, b, rs):
        zeros = jnp.zeros((b - a,), dtype=jnp.int64)
        return eg.encrypt_with_tables(base_tbl, pub_tbl,
                                      eg.int_to_scalar(zeros), rs)

    slabs = [(a, min(a + eff, size)) for a in range(0, size, eff)]
    parts = plane.dispatch_shards(phase, slab, slabs, prefetch=stage)
    return jnp.concatenate(parts, axis=0)


def precompute_rerandomization(key, pub_tbl, size: int, base_tbl=None,
                               chunk: int | None = None):
    """Precompute the expensive half of a shuffle step: `size` fresh
    encryptions of zero (r·B, r·P) plus their scalars.

    The reference caches exactly this per server across surveys
    (`pre_compute_multiplications.gob`, services/service.go:34,316-317 +
    unlynx PrecomputationWritingForShuffling) — it is what makes the
    1M-element DRO noise lists survivable. Returns (zero_cts, r) usable as
    the `precomp` argument of shuffle_rerandomize.

    Above CHUNK elements the fixed-base mults run in `chunk`-wide slabs
    over the proof-plane devices (byte-identical to one dispatch; the
    scalars r are always drawn in ONE call so chunking never changes
    them). chunk: None = auto, 0 = force monolithic."""
    global PRECOMPUTE_CALLS

    _require_table(pub_tbl, "precompute_rerandomization")
    with _PRECOMPUTE_COUNT_LOCK:
        PRECOMPUTE_CALLS += 1
    base_tbl = base_tbl if base_tbl is not None else eg.BASE_TABLE.table
    r = eg.random_scalars(key, (size,))
    zero_ct = _encrypt_zeros_chunked(r, pub_tbl, base_tbl, chunk,
                                     "DROPrecompute")
    return zero_ct, r


def save_precompute(path: str, precomp) -> None:
    """Persist a precomputation (the reference's gob-file equivalent)."""
    zero_ct, r = precomp
    np.savez(path, zero_ct=np.asarray(zero_ct), r=np.asarray(r))


def load_precompute(path: str):
    d = np.load(path)
    return jnp.asarray(d["zero_ct"]), jnp.asarray(d["r"])


def shuffle_rerandomize(key, cts, pub_tbl, base_tbl=None, precomp=None,
                        chunk: int | None = None):
    """One server's DRO step: secret permutation + re-randomization.

    cts: (S, 2, 3, 16). Returns (shuffled cts, permutation, rerand scalars)
    — the latter two feed the shuffle proof. `precomp` (from
    precompute_rerandomization) skips the S fixed-base scalar-mults — the
    hot cost at reference noise sizes (10k..1M, TIFS/diffPri.py).

    chunk (None = auto above CHUNK, 0 = force monolithic): permute the
    indices on the host, then gather + re-randomize in `chunk`-wide slabs
    over the proof-plane devices instead of one (S, 2, 3, 16) dispatch.
    The permutation and blinding scalars are drawn identically either way
    and ct_add is element-wise, so chunked output is byte-identical to
    unchunked for the same key."""
    _require_table(pub_tbl, "shuffle_rerandomize")
    S = int(cts.shape[0])
    kperm, krand = jax.random.split(key)
    perm = jax.random.permutation(kperm, S)
    if precomp is not None:
        zero_ct, r = precomp
        assert zero_ct.shape[0] == S, (zero_ct.shape, S)
    else:
        base_tbl = base_tbl if base_tbl is not None else eg.BASE_TABLE.table
        r = eg.random_scalars(krand, (S,))
        zero_ct = _encrypt_zeros_chunked(r, pub_tbl, base_tbl, chunk,
                                         "DRORerand")

    eff = _chunk_of(S, chunk)
    if not eff or eff >= S:
        shuffled = jnp.take(cts, perm, axis=0)
        return eg.ct_add(shuffled, zero_ct), perm, r

    from . import proof_plane as plane

    perm_h = np.asarray(perm)

    def stage(i, a, b):
        # exact global permutation: host-permuted indices, per-slab gather
        return plane.put_shard(
            (jnp.take(cts, jnp.asarray(perm_h[a:b]), axis=0),
             zero_ct[a:b]), i, donate=True)

    def slab(i, gathered, zc):
        return eg.ct_add(gathered, zc)

    slabs = [(a, min(a + eff, S)) for a in range(0, S, eff)]
    parts = plane.dispatch_shards("DROShuffle", slab, slabs,
                                  prefetch=stage)
    return jnp.concatenate(parts, axis=0), perm, r


def dro_pipeline(key, pub_tbl: eg.FixedBase, size: int, mean: float,
                 b: float, quanta: float, scale: float = 1.0,
                 limit: float = 0.0, n_servers: int = 3,
                 chunk: int | None = None, pool=None):
    """Full noise phase: generate, encrypt, pass through every server's
    shuffle+rerandomize. Returns the final encrypted noise list.

    ``pool`` (a pool.CryptoPool): each server pass first tries to consume
    ``size`` precomputed zero-encryptions keyed by this public table's
    digest — the reference's gob-cache economics (precompute dominates at
    10k..1M noise sizes; a warm pool leaves only permute+add). A short
    pool falls back to fresh precompute for THAT pass only. Consumption
    is strictly once (pool/store.py); the permutation is drawn from the
    pipeline key either way, so pooled output decrypts identically to the
    fresh-randomness path (tests/test_pool.py pins it)."""
    if not isinstance(pub_tbl, eg.FixedBase):
        raise TypeError("dro_pipeline takes the FixedBase wrapper; pass "
                        "pub_tbl.table only to the shuffle layer")
    noise = generate_noise_values(size, mean, b, quanta, scale, limit)
    key, sub = jax.random.split(key)
    cts = encrypt_noise(sub, pub_tbl, noise)
    digest = None
    if pool is not None:
        from ..pool import store as _ps

        digest = _ps.key_digest(pub_tbl.table)
    S = int(cts.shape[0])
    for _ in range(n_servers):
        key, sub = jax.random.split(key)
        pc = None
        if pool is not None:
            got = pool.try_consume_dro(digest, S)
            if got is not None:
                pc = (jnp.asarray(got[0]), jnp.asarray(got[1]))
        cts, _, _ = shuffle_rerandomize(sub, cts, pub_tbl.table,
                                        precomp=pc, chunk=chunk)
    return cts, noise


__all__ = ["generate_noise_values", "encrypt_noise", "shuffle_rerandomize",
           "precompute_rerandomization", "save_precompute", "load_precompute",
           "dro_pipeline", "slab_widths", "CHUNK"]
