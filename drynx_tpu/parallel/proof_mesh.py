"""Mesh-sharded range-proof verification: the RLC batch check's expensive
work (Miller loops + GT exponentiations) distributed over a device mesh.

The reference's dominant cost is VN range-proof verification (21.73 s per
proofs-on query across 7 VN machines, BASELINE.md timeline row). The TPU
answer is the same shape as every other hot path here: the per-digit pairing
work is a flat batch, so it shards over mesh axes and combines with a
custom GT-multiplication all-reduce. One VN with an n-device slice verifies
n times faster; the randomized accept decision is unchanged.

Checked identity (verify_range_proofs_batch, proofs/range_proof.py):

  finalexp( prod_ij M(r_ij*(c*y_i - Zphi_j*B), V_ij) )
    * prod_ij conj6(a_ij)^r_ij * gtB^(sum_ij r_ij*Zv_ij)  ==  1

The Miller products and conj6(a)^r products reduce per-shard, then the
partials combine with one GT multiplication tree; the single shared final
exponentiation runs once — it is one element, not worth a collective.
Exactness: bit-identical GT total vs the single-device path
(tests/test_proof_mesh.py) — Montgomery F12 multiplication is exact mod-p
with canonical representatives, so any grouping of the partial products
yields identical limb arrays.

Two execution strategies:

  * `rlc_total_shards` (DEFAULT, strategy="chunked") — per-device chunk
    dispatch through the SAME single-device bucketed programs
    (batching.miller / gt_pow64 / gt_reduce_prod) at the per-shard bucket,
    so the compilecache registry covers it (registry._shard_schemas) and
    every backend keeps its normal routing (host-oracle detour on CPU,
    Mosaic kernels on TPU with each shard device_put on its own device and
    async dispatch overlapping the mesh).
  * `rlc_total_sharded` (strategy="spmd") — the original
    jit(shard_map(...)) program with the GT all-reduce riding ICI inside
    one XLA program. Kept for on-chip use (slow-tier test): its body stays
    traceable, so on CPU it cannot take the host-oracle detour and one
    monolithic compile exceeds 90 min on the 1-core box.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import fp12 as F12
from ..crypto import pairing as PAIR
from ..crypto import params
from . import collective as col

try:
    from jax.experimental.shard_map import shard_map
except ImportError:  # newer jax
    from jax import shard_map

from jax.sharding import PartitionSpec as P


def _flatten_pad(n_dev: int, *arrs):
    """Flatten leading (ns, V, l) dims to N, edge-pad N up to a multiple of
    n_dev (padded lanes are masked out of the products)."""
    N = int(np.prod(arrs[0].shape[:3]))
    Np = ((N + n_dev - 1) // n_dev) * n_dev
    out = []
    for a in arrs:
        a = jnp.asarray(a).reshape((N,) + a.shape[3:])
        if Np != N:
            pad = jnp.broadcast_to(a[:1], (Np - N,) + a.shape[1:])
            a = jnp.concatenate([a, pad], axis=0)
        out.append(a)
    mask = (jnp.arange(Np) < N)
    return out, mask, N


def rlc_total_sharded(mesh, proof, sigs_pub, r_int, gtb_pow_s):
    """The RLC check's GT total, computed over `mesh` (all axes flattened).

    proof: a RangeProofBatch; sigs_pub: per-CN affine publics; r_int:
    int64 (ns, V, l) verifier weights; gtb_pow_s: gtB^(sum r*Zv), (6,2,16)
    (one fixed-base power, computed by the caller). Returns the (6, 2, 16)
    GT total — equals F12.one() iff the batch verifies.
    """
    # verification is one flat batch — re-view the same devices as a 1-D
    # mesh so the GT all-reduce runs over a single named axis
    devs = np.asarray(mesh.devices).reshape(-1)
    n_dev = int(devs.size)
    flat_mesh = jax.sharding.Mesh(devs, ("vnshard",))

    ys = jnp.asarray(np.stack([C.from_ref(p) for p in sigs_pub]))
    c, zphi = jnp.asarray(proof.challenge), jnp.asarray(proof.zphi)

    # cheap G1 prep (full batch, unsharded): g1arg = r*(c*y_i - Zphi_j*B)
    from ..crypto import batching as B
    from ..crypto import elgamal as eg

    r = B.int_to_scalar(jnp.asarray(r_int))                    # (ns, V, l, 16)
    cy = B.g1_scalar_mul(ys[:, None, :, :], c[None, :, :])
    nzphiB = B.fixed_base_mul(eg.BASE_TABLE.table, B.fn_neg(zphi))
    g1arg = B.g1_add(cy[:, :, None, :, :], nzphiB[None])       # (ns, V, l, 3, 16)
    g1arg_r = B.g1_scalar_mul64(g1arg, r)   # 62-bit weights: short ladder
    px, py, _ = B.g1_normalize(g1arg_r)
    qx, qy, _ = B.g2_normalize(jnp.asarray(proof.v_pts))
    conj_a = F12.conj6(jnp.asarray(proof.a))

    (px, py, qx, qy, ca, rr), mask, _ = _flatten_pad(
        n_dev, px, py, qx, qy, conj_a, r)

    spec = P("vnshard")

    from ..crypto import pallas_ops as po
    from ..crypto import pallas_pairing as ppair

    def shard(px, py, qx, qy, ca, rr, mask):
        # per-shard Miller loops + conj6(a)^r, masked partial products
        m = PAIR.miller_loop((px, py), (qx, qy))
        if po.available():
            # 63-bit windowed pow — same kernel the single-device verifier
            # uses for the 62-bit RLC weights (batching.gt_pow64); cyc is
            # safe: rlc_prelude gated a through gt_membership_ok
            ar = ppair.f12_wpow_flat(ca, rr, n_bits=63, cyc=True)
        else:
            # 63-bit truncated scan: the weights are 62-bit and the full
            # 256-step graph quadruples the (already heavy) shard compile
            ar = F12.pow_var(ca, rr, n_bits=63)
        one = jnp.broadcast_to(jnp.asarray(F12.one()), m.shape)
        mk = mask[:, None, None, None]
        m = jnp.where(mk, m, one)
        ar = jnp.where(mk, ar, one)

        def prod(x):
            while x.shape[0] > 1:
                half = x.shape[0] // 2
                red = F12.mul(x[: 2 * half : 2], x[1 : 2 * half : 2])
                x = (jnp.concatenate([red, x[-1:]], axis=0)
                     if x.shape[0] % 2 else red)
            return x[0]

        m_p, a_p = prod(m), prod(ar)
        # GT-multiplication all-reduce over the whole mesh (ICI butterfly)
        m_tot = col._allreduce(m_p, "vnshard", n_dev, F12.mul)
        a_tot = col._allreduce(a_p, "vnshard", n_dev, F12.mul)
        return m_tot, a_tot

    f = jax.jit(shard_map(
        shard, mesh=flat_mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec),
        out_specs=(P(), P()), check_rep=False))
    m_tot, a_tot = f(px, py, qx, qy, ca, rr,
                     mask.astype(jnp.uint32))
    fe = PAIR.final_exp(m_tot[None])[0]
    return F12.mul(F12.mul(fe, a_tot), jnp.asarray(gtb_pow_s))


def _g1_prep(proof, sigs_pub, r_int):
    """The cheap full-batch G1/G2 prep shared by both strategies:
    g1arg_r = r*(c*y_i - Zphi_j*B) normalized to affine, plus the affine
    V points and conj6(a). Returns device arrays shaped (ns, V, l, ...)."""
    from ..crypto import batching as B
    from ..crypto import elgamal as eg

    ys = jnp.asarray(np.stack([C.from_ref(p) for p in sigs_pub]))
    c, zphi = jnp.asarray(proof.challenge), jnp.asarray(proof.zphi)

    r = B.int_to_scalar(jnp.asarray(r_int))                    # (ns, V, l, 16)
    cy = B.g1_scalar_mul(ys[:, None, :, :], c[None, :, :])
    nzphiB = B.fixed_base_mul(eg.BASE_TABLE.table, B.fn_neg(zphi))
    g1arg = B.g1_add(cy[:, :, None, :, :], nzphiB[None])       # (ns, V, l, 3, 16)
    g1arg_r = B.g1_scalar_mul64(g1arg, r)   # 62-bit weights: short ladder
    px, py, _ = B.g1_normalize(g1arg_r)
    qx, qy, _ = B.g2_normalize(jnp.asarray(proof.v_pts))
    conj_a = F12.conj6(jnp.asarray(proof.a))
    return px, py, qx, qy, conj_a, r


def rlc_total_shards(proof, sigs_pub, r_int, gtb_pow_s,
                     n_shards: int | None = None,
                     phase: str = "VerifyShard"):
    """The RLC check's GT total via per-device chunk dispatch (the default
    mesh strategy — see module docstring). Bit-identical to the
    single-device `range_proof.rlc_total_single`: the same bucketed
    programs compute the same per-element values, and the partial-product
    regrouping is exact.

    Each VN-role shard runs Miller loops + conj6(a)^r pows over its slice
    of the flattened (ns*V*l) digit batch and reduces locally; partials
    combine with one gt_reduce_prod tree, then the single shared final
    exponentiation and gtB power fold in exactly as on one device.

    phase: SHARD_TIMERS span label — the cross-survey scheduler passes
    "CrossSurveyVerifyShard" so its batched dispatches attribute
    separately from per-survey "VerifyShard" spans.
    """
    from ..crypto import batching as B
    from . import proof_plane as plane

    if n_shards is None:
        n_shards = plane.n_shards()

    px, py, qx, qy, conj_a, r = _g1_prep(proof, sigs_pub, r_int)
    N = int(np.prod(px.shape[:3]))

    def flat(x):
        return jnp.asarray(x).reshape((N,) + x.shape[3:])

    px, py, qx, qy, ca, rr = map(flat, (px, py, qx, qy, conj_a, r))
    slices = plane.shard_slices(N, n_shards)

    def stage_total(i, a, b):
        # input staging: the per-shard slices are one-shot, so their
        # buffers are donated to the upload (reused where the backend
        # can alias); uploads overlap the previous shard's compute
        return plane.put_shard(
            (px[a:b], py[a:b], qx[a:b], qy[a:b], ca[a:b], rr[a:b]), i,
            donate=True)

    def shard_total(i, spx, spy, sqx, sqy, sca, srr):
        m = B.miller(spx, spy, sqx, sqy)
        # 63-bit windowed pow — same program the single-device verifier
        # uses for the 62-bit RLC weights; a passed the prelude's
        # membership/order gates, so the cyclotomic fast path is sound
        ar = B.gt_pow64(sca, srr)
        nl = m.shape[-1]
        return (B.gt_reduce_prod(m.reshape(-1, 6, 2, nl)),
                B.gt_reduce_prod(ar.reshape(-1, 6, 2, nl)))

    parts = plane.dispatch_shards(
        phase, shard_total, [(a, b) for (a, b) in slices],
        prefetch=stage_total)
    # combine partials exactly as the single-device path combines its two
    # full-batch products: final_exp on the Miller product ONLY, then the
    # a-product and the gtB power fold in with plain GT muls
    m_tot = B.gt_reduce_prod(jnp.stack([p[0] for p in parts]))
    a_tot = B.gt_reduce_prod(jnp.stack([p[1] for p in parts]))
    fe = B.final_exp(m_tot[None])
    return B.gt_mul(B.gt_mul(fe, a_tot[None]),
                    jnp.asarray(gtb_pow_s)[None])[0]


def rlc_verify_sharded(proof, sigs_pub, ca_pub_table,
                       rng: np.random.Generator | None = None, *,
                       mesh=None, n_shards: int | None = None,
                       strategy: str = "auto",
                       phase: str = "VerifyShard") -> bool:
    """Mesh-parallel single-verdict verification of a RangeProofBatch —
    the DEFAULT joint-range path whenever the proof plane is enabled
    (proofs/range_proof.py `_safe_batch_verify` routes here).

    Same acceptance predicate as verify_range_proofs_batch (including the
    per-value D equation, the binding Fiat-Shamir challenge recompute and
    the GT membership/order gates, all in the shared rlc_prelude) — only
    the pairing-heavy RLC total is sharded, and it is bit-identical to
    the single-device total, so tamper-rejection semantics are unchanged.

    strategy: "auto"/"chunked" = per-device chunk dispatch (default);
    "spmd" = the monolithic shard_map program (requires `mesh`).
    """
    from ..proofs import range_proof as rp

    # SHARED preamble with the single-device verifier (rlc_prelude keeps
    # the D equation, challenge binding and weight draw in one place)
    pre_ok, r_int, gtb_pow_s = rp.rlc_prelude(
        proof, sigs_pub, ca_pub_table, rng=rng)
    if not pre_ok:
        return False

    if strategy == "spmd":
        if mesh is None:
            raise ValueError("strategy='spmd' needs an explicit mesh")
        total = rlc_total_sharded(mesh, proof, sigs_pub, r_int, gtb_pow_s)
    else:
        total = rlc_total_shards(proof, sigs_pub, r_int, gtb_pow_s,
                                 n_shards=n_shards, phase=phase)
    return bool(np.asarray(F12.eq(total, jnp.asarray(F12.one()))))


__all__ = ["rlc_total_sharded", "rlc_total_shards", "rlc_verify_sharded"]
