"""The mesh proof plane: device-sharding policy for proof creation and
joint-range verification.

Whenever >= 2 devices are visible, the proof pipeline's two flat-batch
hot paths run SHARDED by default:

  * creation — the `dp` axis: each shard of the all-DP digit batch builds
    its `a_ij` GT-table exponentiations locally (proofs/range_proof.py
    `_commit_kernel_sharded`), gathered once per batch before the
    Fiat-Shamir hash;
  * verification — the `vn` axis: each VN-role shard verifies a slice of
    the joint RLC digit batch (parallel/proof_mesh.py `rlc_total_shards`),
    partial GT products combined with one log-tree GT multiplication.

Execution strategy (why this is NOT shard_map): the per-shard work is the
SAME single-device bucketed program set (crypto/batching.py) dispatched
once per shard, so the plane reuses executables the compilecache registry
already covers (at the smaller per-shard buckets — registry._shard_schemas)
instead of minting one giant SPMD program. The monolithic shard_map path
exceeded 90 minutes of XLA CPU compile (tests/test_proof_mesh.py history)
because a shard_map body must stay traceable and therefore cannot take the
host-oracle detour; per-shard dispatch keeps every backend's normal
routing. On an accelerator mesh each shard's inputs are device_put onto
its own device and JAX's async dispatch overlaps the shards; on CPU the
shards execute through the host-native backend sequentially (placement is
skipped — host detours ignore placement, and committed-device mixing
would break the small XLA fn_* programs), so the fake 8-device mesh
exercises sharding SEMANTICS, not speedup. Per-value independence of the
range-proof transcripts makes every sharded result bit-identical to the
single-device path (exact mod-p arithmetic is associative), so the
accept/reject decision cannot drift — tests/test_proof_mesh.py asserts
byte equality.

Policy env DRYNX_PROOF_PLANE: "auto" (default — shard over all visible
devices when >= 2), "off" (single-device everywhere), or an integer shard
count override.
"""
from __future__ import annotations

import os
import time

from ..utils.timers import PhaseTimers

ENV_FLAG = "DRYNX_PROOF_PLANE"

# Async shard pipeline kill-switch: "serial"/"off" restores the
# block-per-shard dispatch loop (the pre-device-path behavior the
# bench_device_path supervisor compares against).
ASYNC_ENV = "DRYNX_ASYNC_DISPATCH"

# Batches smaller than this never shard: the per-shard dispatch overhead
# (host_dispatch flatten + jit cache lookup per shard) would exceed the
# per-element work of a handful of digit proofs.
MIN_ITEMS_PER_SHARD = 1

# Per-shard phase spans ("<Phase>.shard<i>"), folded into the bench
# supervisor record (bench.py) — the observability analogue of the
# per-program CompileStats rows.
SHARD_TIMERS = PhaseTimers()


def _policy() -> str:
    return os.environ.get(ENV_FLAG, "auto").strip().lower()


def device_count() -> int:
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def n_shards() -> int:
    """Shard count the plane runs at: visible devices under "auto", a
    forced count under an integer policy, 1 under "off"."""
    pol = _policy()
    if pol in ("off", "0", "none", "single"):
        return 1
    if pol not in ("", "auto", "on"):
        try:
            return max(1, int(pol))
        except ValueError:
            pass
    return max(1, device_count())


def enabled() -> bool:
    """True iff sharded creation/verification is the default path."""
    return n_shards() >= 2


def placement_on() -> bool:
    """True iff shards are device_put onto distinct mesh devices: only on
    the Pallas (accelerator) backend with a real multi-device mesh. On CPU
    the heavy per-shard families detour to the host backend (placement is
    meaningless) while the small XLA helpers would error on mixed
    committed devices."""
    from ..crypto import pallas_ops as po

    return po.available() and device_count() >= 2


def shard_device(i: int):
    import jax

    devs = jax.devices()
    return devs[i % len(devs)]


def async_on() -> bool:
    """True iff dispatch_shards pipelines: never blocks between enqueues,
    one block_until_ready barrier at the end. DRYNX_ASYNC_DISPATCH=serial
    (or off/0/no) restores the per-shard blocking loop."""
    return os.environ.get(ASYNC_ENV,
                          "").strip().lower() not in ("serial", "off",
                                                      "0", "no")


def _put_leaf(x, dev, donate: bool):
    import jax

    # identity fast-path: already committed to the target device — a
    # device_put here would be a redundant copy on every shard hop
    if getattr(x, "device", None) == dev:
        return x
    if donate:
        try:
            return jax.device_put(x, dev, donate=True)
        except TypeError:       # older jax without the donate kwarg
            pass
    return jax.device_put(x, dev)


def put_shard(tree, i: int, donate: bool = False):
    """Place one shard's arrays on mesh device i (identity off-mesh and
    on single-device hosts). ``donate`` hands the source buffers to the
    transfer — safe only for arrays the caller never reads again (the
    per-shard input slices); backends that cannot alias simply copy."""
    if not placement_on():
        return tree
    import jax

    dev = shard_device(i)
    return jax.tree_util.tree_map(
        lambda x: _put_leaf(x, dev, donate), tree)


def gather(tree):
    """Bring per-shard results back to the lead device for the combine /
    concat ("results gathered once per batch"). Leaves already on the
    lead device pass through untouched — the consumer and producer share
    a device, so there is nothing to move."""
    if not placement_on():
        return tree
    import jax

    dev = shard_device(0)
    return jax.tree_util.tree_map(
        lambda x: x if getattr(x, "device", None) == dev
        else jax.device_put(x, dev), tree)


def shard_slices(n: int, k: int,
                 min_items: int = MIN_ITEMS_PER_SHARD) -> list:
    """Balanced contiguous [start, stop) slices of range(n) over <= k
    shards; never emits an empty shard, never splits below min_items."""
    n, k = int(n), int(k)
    if n <= 0:
        return []
    k = max(1, min(k, n // max(1, min_items)) or 1)
    base, extra = divmod(n, k)
    out, start = [], 0
    for i in range(k):
        stop = start + base + (1 if i < extra else 0)
        out.append((start, stop))
        start = stop
    return out


def record_shard(phase: str, i: int, seconds: float) -> None:
    SHARD_TIMERS.add(f"{phase}.shard{i}", seconds)


def timers_snapshot() -> dict:
    """{"<Phase>.shard<i>": seconds} accumulated this process, plus the
    "<Phase>.<stage>#<host_glue|device_compute>" attribution keys."""
    return {k: round(v, 6) for k, v in SHARD_TIMERS.items()}


def dispatch_shards(phase: str, fn, shard_args: list,
                    prefetch=None) -> list:
    """Dispatch fn(i, *args_i) for every shard as a pipeline.

    Async mode (default): the dispatch thread never blocks between
    enqueues — shard i+1's inputs are ``prefetch``-uploaded right after
    shard i is enqueued (so the upload overlaps shard i's compute on an
    async backend) and one ``block_until_ready`` barrier at the end waits
    for the whole batch. ``DRYNX_ASYNC_DISPATCH=serial`` restores the
    block-per-shard loop (bench comparison / debugging).

    ``prefetch(i, *args_i) -> new_args_i`` is the input-staging stage
    (put_shard uploads, slicing); when given, ``fn`` receives its return
    value instead of the raw args. Prefetch time is attributed as
    host_glue; the barrier as device_compute (on a synchronous backend
    the fn() span itself is the device compute and is attributed so).

    Results are gathered to the lead device. The per-shard span keys
    ("<Phase>.shard<i>" dispatch-start -> outputs-ready and
    "<Phase>.dispatch.shard<i>" for the fn() call) are unchanged."""
    import jax

    serial = not async_on()
    n = len(shard_args)
    # fn spans are pure enqueue cost only when placement puts shards on
    # an async accelerator mesh; on the synchronous host backend the
    # fn() call runs the shard's kernels to completion
    fn_kind = "host_glue" if placement_on() else "device_compute"
    outs, t0s = [], []
    nxt = prefetch(0, *shard_args[0]) if (prefetch and n) else None
    for i, args in enumerate(shard_args):
        cur = nxt if prefetch else args
        t0 = time.perf_counter()
        t0s.append(t0)
        out = fn(i, *cur)
        dt = time.perf_counter() - t0
        record_shard(f"{phase}.dispatch", i, dt)
        SHARD_TIMERS.add_split(f"{phase}.enqueue", fn_kind, dt)
        outs.append(out)
        if prefetch and i + 1 < n:
            tp = time.perf_counter()
            nxt = prefetch(i + 1, *shard_args[i + 1])
            SHARD_TIMERS.add_split(f"{phase}.upload", "host_glue",
                                   time.perf_counter() - tp)
        if serial:
            jax.block_until_ready(out)
            record_shard(phase, i, time.perf_counter() - t0s[i])
    if not serial:
        tb = time.perf_counter()
        jax.block_until_ready(outs)
        tend = time.perf_counter()
        SHARD_TIMERS.add_split(f"{phase}.block", "device_compute",
                               tend - tb)
        for i in range(n):
            record_shard(phase, i, tend - t0s[i])
    return [gather(o) for o in outs]


__all__ = ["enabled", "n_shards", "device_count", "placement_on",
           "shard_slices", "put_shard", "gather", "dispatch_shards",
           "async_on", "record_shard", "timers_snapshot", "SHARD_TIMERS",
           "ENV_FLAG", "ASYNC_ENV"]
