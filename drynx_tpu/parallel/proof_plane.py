"""The mesh proof plane: device-sharding policy for proof creation and
joint-range verification.

Whenever >= 2 devices are visible, the proof pipeline's two flat-batch
hot paths run SHARDED by default:

  * creation — the `dp` axis: each shard of the all-DP digit batch builds
    its `a_ij` GT-table exponentiations locally (proofs/range_proof.py
    `_commit_kernel_sharded`), gathered once per batch before the
    Fiat-Shamir hash;
  * verification — the `vn` axis: each VN-role shard verifies a slice of
    the joint RLC digit batch (parallel/proof_mesh.py `rlc_total_shards`),
    partial GT products combined with one log-tree GT multiplication.

Execution strategy (why this is NOT shard_map): the per-shard work is the
SAME single-device bucketed program set (crypto/batching.py) dispatched
once per shard, so the plane reuses executables the compilecache registry
already covers (at the smaller per-shard buckets — registry._shard_schemas)
instead of minting one giant SPMD program. The monolithic shard_map path
exceeded 90 minutes of XLA CPU compile (tests/test_proof_mesh.py history)
because a shard_map body must stay traceable and therefore cannot take the
host-oracle detour; per-shard dispatch keeps every backend's normal
routing. On an accelerator mesh each shard's inputs are device_put onto
its own device and JAX's async dispatch overlaps the shards; on CPU the
shards execute through the host-native backend sequentially (placement is
skipped — host detours ignore placement, and committed-device mixing
would break the small XLA fn_* programs), so the fake 8-device mesh
exercises sharding SEMANTICS, not speedup. Per-value independence of the
range-proof transcripts makes every sharded result bit-identical to the
single-device path (exact mod-p arithmetic is associative), so the
accept/reject decision cannot drift — tests/test_proof_mesh.py asserts
byte equality.

Policy env DRYNX_PROOF_PLANE: "auto" (default — shard over all visible
devices when >= 2), "off" (single-device everywhere), or an integer shard
count override.
"""
from __future__ import annotations

import os
import time

from ..utils.timers import PhaseTimers

ENV_FLAG = "DRYNX_PROOF_PLANE"

# Batches smaller than this never shard: the per-shard dispatch overhead
# (host_dispatch flatten + jit cache lookup per shard) would exceed the
# per-element work of a handful of digit proofs.
MIN_ITEMS_PER_SHARD = 1

# Per-shard phase spans ("<Phase>.shard<i>"), folded into the bench
# supervisor record (bench.py) — the observability analogue of the
# per-program CompileStats rows.
SHARD_TIMERS = PhaseTimers()


def _policy() -> str:
    return os.environ.get(ENV_FLAG, "auto").strip().lower()


def device_count() -> int:
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def n_shards() -> int:
    """Shard count the plane runs at: visible devices under "auto", a
    forced count under an integer policy, 1 under "off"."""
    pol = _policy()
    if pol in ("off", "0", "none", "single"):
        return 1
    if pol not in ("", "auto", "on"):
        try:
            return max(1, int(pol))
        except ValueError:
            pass
    return max(1, device_count())


def enabled() -> bool:
    """True iff sharded creation/verification is the default path."""
    return n_shards() >= 2


def placement_on() -> bool:
    """True iff shards are device_put onto distinct mesh devices: only on
    the Pallas (accelerator) backend with a real multi-device mesh. On CPU
    the heavy per-shard families detour to the host backend (placement is
    meaningless) while the small XLA helpers would error on mixed
    committed devices."""
    from ..crypto import pallas_ops as po

    return po.available() and device_count() >= 2


def shard_device(i: int):
    import jax

    devs = jax.devices()
    return devs[i % len(devs)]


def put_shard(tree, i: int):
    """Place one shard's arrays on mesh device i (identity off-mesh)."""
    if not placement_on():
        return tree
    import jax

    return jax.device_put(tree, shard_device(i))


def gather(tree):
    """Bring per-shard results back to the lead device for the combine /
    concat ("results gathered once per batch")."""
    if not placement_on():
        return tree
    import jax

    return jax.device_put(tree, shard_device(0))


def shard_slices(n: int, k: int,
                 min_items: int = MIN_ITEMS_PER_SHARD) -> list:
    """Balanced contiguous [start, stop) slices of range(n) over <= k
    shards; never emits an empty shard, never splits below min_items."""
    n, k = int(n), int(k)
    if n <= 0:
        return []
    k = max(1, min(k, n // max(1, min_items)) or 1)
    base, extra = divmod(n, k)
    out, start = [], 0
    for i in range(k):
        stop = start + base + (1 if i < extra else 0)
        out.append((start, stop))
        start = stop
    return out


def record_shard(phase: str, i: int, seconds: float) -> None:
    SHARD_TIMERS.add(f"{phase}.shard{i}", seconds)


def timers_snapshot() -> dict:
    """{"<Phase>.shard<i>": seconds} accumulated this process."""
    return {k: round(v, 6) for k, v in SHARD_TIMERS.items()}


def dispatch_shards(phase: str, fn, shard_args: list) -> list:
    """Dispatch fn(i, *args_i) for every shard, then block in order.

    On an accelerator mesh the dispatches are asynchronous, so shard i+1
    enqueues while shard i computes — the devices overlap; the recorded
    per-shard span is dispatch-start -> outputs-ready (on CPU this is the
    shard's synchronous compute time). Results are gathered to the lead
    device."""
    import jax

    outs, t0s = [], []
    for i, args in enumerate(shard_args):
        t0s.append(time.perf_counter())
        out = fn(i, *args)
        # "<Phase>.dispatch<i>": the fn() call itself. On a synchronous
        # backend (CPU host-oracle detour) this IS shard i's own compute;
        # on an async accelerator it is just the enqueue cost.
        record_shard(f"{phase}.dispatch", i, time.perf_counter() - t0s[i])
        outs.append(out)
    ready = []
    for i, o in enumerate(outs):
        o = jax.block_until_ready(o)
        record_shard(phase, i, time.perf_counter() - t0s[i])
        ready.append(gather(o))
    return ready


__all__ = ["enabled", "n_shards", "device_count", "placement_on",
           "shard_slices", "put_shard", "gather", "dispatch_shards",
           "record_shard", "timers_snapshot", "SHARD_TIMERS", "ENV_FLAG"]
