"""Cross-survey crypto pools: persistent DRO precompute + sig tables.

See store.py for the disk format and the single-consumption claim
protocol (load-bearing privacy), replenish.py for the refill crypto.

Process-wide active pool: both tenants are CONTENT-ADDRESSED (DRO slabs
by collective-key digest, sig tables by A-table digest), so one shared
pool can never serve an artifact to the wrong key/signature set — which
makes a process-global handle safe. ``LocalCluster(pool=...)`` activates
its pool here so deep call sites with no cluster in scope (the sig-table
LRU miss paths in proofs/range_proof.py) can consult the store; setting
``DRYNX_POOL_DIR`` activates one lazily for tooling.
"""
from __future__ import annotations

import os

from .store import (CryptoPool, DoubleConsumption, InsufficientBalance,
                    PoolError, key_digest)
from .epsilon import EpsilonExhausted, EpsilonLedger
from . import replenish

_ACTIVE: CryptoPool | None = None
_ENV_POOLS: dict[str, CryptoPool] = {}


def activate(pool: CryptoPool | None) -> CryptoPool | None:
    """Install ``pool`` as the process-wide active pool (None clears)."""
    global _ACTIVE
    _ACTIVE = pool
    return pool


def active_pool() -> CryptoPool | None:
    """The explicitly-activated pool, else one rooted at $DRYNX_POOL_DIR
    (memoized per path), else None."""
    if _ACTIVE is not None:
        return _ACTIVE
    d = os.environ.get("DRYNX_POOL_DIR")
    if not d:
        return None
    p = _ENV_POOLS.get(d)
    if p is None:
        p = _ENV_POOLS[d] = CryptoPool(d)
    return p


__all__ = ["CryptoPool", "PoolError", "DoubleConsumption",
           "InsufficientBalance", "key_digest", "replenish",
           "activate", "active_pool", "EpsilonLedger", "EpsilonExhausted"]
