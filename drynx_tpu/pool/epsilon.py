"""Per-DP differential-privacy budget accountant (the "epsilon ledger").

Streaming surveys re-ask: every window advance releases another noised
statistic over (mostly) the same rows, and under basic composition each
release spends privacy budget. Without an accountant, "millions of
queries against the same cohort" (ROADMAP item 4) is a privacy bug — the
DiffP noise per release stays constant while the cumulative epsilon grows
without bound. This module makes the spend explicit and durable: a
per-(DP, cohort-digest) budget, charged at admission BEFORE any advance
runs, with the same crash-safe single-spend guarantees the DRO pool's
consumption ledger provides (store.py).

Ledger idiom mirrored from ``CryptoPool``:

  * append-only ``epsilon.jsonl`` journal, every ``consume`` event
    flushed + fsync'd BEFORE the in-memory balance moves — a crash after
    the append never forgets a spend (the conservative direction: budget
    may leak away in a crash window, it can never be double-granted);
  * replay on open skips blank lines and drops a torn final line
    (crash mid-append: the partial event never moved memory either);
  * one named re-entrant lock ("epsilon_ledger_lock") serializes
    check-then-append so two threads racing the last slice of budget
    admit exactly one.

Charging is deliberately conservative: the event is journaled before the
advance executes, so an advance that later fails still consumed budget.
That is privacy-sound (the noise draw and ciphertext delta may have left
the process) and mirrors the DRO pool's discard-don't-reuse stance.

numpy-free and jax-free on purpose — admission control must be able to
reject without touching an accelerator.
"""
from __future__ import annotations

import json
import os

from ..resilience.policy import named_lock
from .store import PoolError

_DET_TRACE = os.environ.get("DRYNX_DET_TRACE", "0") == "1"


class EpsilonExhausted(PoolError):
    """A charge would push a (DP, cohort) past its epsilon budget.

    Raised at admission, before any device work: the caller must treat
    it as 'this cohort's budget is spent', never as 'retry' — budget
    only moves one way."""


# float-comparison slack: budgets and per-advance epsilons are operator
# inputs like 1.0 and 0.01 whose binary sums drift by ULPs; a charge that
# lands exactly AT the budget must admit, one past it must not.
_EPS_SLACK = 1e-9


class EpsilonLedger:
    """One on-disk accountant rooted at ``root``.

    Layout::

        root/epsilon.jsonl     append-only consume-event journal

    ``budget`` is the per-(dp, cohort) cap; None defers to the
    resilience policy default (rp.EPSILON_BUDGET / DRYNX_EPSILON_BUDGET
    resolved at the admission call site). Thread-safe; restart-safe:
    a fresh instance over the same root replays the journal and refuses
    exactly the charges the dead process would have.
    """

    def __init__(self, root: str, budget: float | None = None):
        self.root = os.path.abspath(root)
        self.budget = None if budget is None else float(budget)
        self._lock = named_lock("epsilon_ledger_lock", reentrant=True)
        self._spent: dict[tuple[str, str], float] = {}
        self.counters = {"charges": 0, "rejections": 0}
        os.makedirs(self.root, exist_ok=True)
        self._ledger_path = os.path.join(self.root, "epsilon.jsonl")
        self._replay_ledger()

    # -- ledger ------------------------------------------------------------

    def _replay_ledger(self) -> None:
        if not os.path.exists(self._ledger_path):
            return
        with open(self._ledger_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    # torn final line from a crash mid-append: the event
                    # never moved the in-memory balance in the dead
                    # process either — drop the torn tail
                    continue
                if ev.get("ev") == "consume":
                    k = (str(ev["dp"]), str(ev["cohort"]))
                    self._spent[k] = self._spent.get(k, 0.0) \
                        + float(ev["eps"])

    def _ledger_append(self, ev: dict) -> None:
        line = json.dumps(ev, sort_keys=True)
        if _DET_TRACE:
            # laundered: sort_keys canonicalizes the record bytes
            from ..analysis import dettrace
            dettrace.record("epsilon.journal", line, line.encode(),
                            laundered=True)
        with self._lock:
            with open(self._ledger_path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())

    # -- accountant surface ------------------------------------------------

    def spent(self, dp: str, cohort: str) -> float:
        with self._lock:
            return self._spent.get((str(dp), str(cohort)), 0.0)

    def remaining(self, dp: str, cohort: str,
                  budget: float | None = None) -> float:
        b = self._budget_for(budget)
        return max(0.0, b - self.spent(dp, cohort))

    def check(self, dp: str, cohort: str, eps: float,
              budget: float | None = None) -> bool:
        """Would ``charge`` admit? Read-only (no journal write)."""
        b = self._budget_for(budget)
        with self._lock:
            done = self._spent.get((str(dp), str(cohort)), 0.0)
            return done + float(eps) <= b + _EPS_SLACK

    def charge(self, dp: str, cohort: str, eps: float,
               budget: float | None = None) -> float:
        """Consume ``eps`` from (dp, cohort); returns the new spent total.

        Check-then-journal-then-commit under one lock: the fsync'd
        ``consume`` event lands BEFORE the in-memory balance moves, so a
        crash between them re-plays as spent (never double-granted). A
        charge that would exceed the budget raises ``EpsilonExhausted``
        and journals nothing — rejection is free and repeatable."""
        eps = float(eps)
        if eps < 0:
            raise PoolError(f"negative epsilon charge: {eps}")
        b = self._budget_for(budget)
        k = (str(dp), str(cohort))
        with self._lock:
            done = self._spent.get(k, 0.0)
            if done + eps > b + _EPS_SLACK:
                self.counters["rejections"] += 1
                raise EpsilonExhausted(
                    f"dp={k[0]} cohort={k[1]}: spent {done:.6g} + "
                    f"charge {eps:.6g} exceeds budget {b:.6g}")
            self._ledger_append({"ev": "consume", "dp": k[0],
                                 "cohort": k[1], "eps": eps})
            self._spent[k] = done + eps
            self.counters["charges"] += 1
            return self._spent[k]

    def _budget_for(self, budget: float | None) -> float:
        if budget is not None:
            return float(budget)
        if self.budget is not None:
            return self.budget
        from ..resilience import policy as rp

        env = os.environ.get("DRYNX_EPSILON_BUDGET", "").strip()
        return float(env) if env else rp.EPSILON_BUDGET


__all__ = ["EpsilonLedger", "EpsilonExhausted"]
