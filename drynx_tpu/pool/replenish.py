"""Pool replenishment: compute DRO precompute slabs and deposit them.

This is the crypto half of the pool (store.py stays numpy-only): a
refill step runs ``parallel.dro.precompute_rerandomization`` at the
pool's slab width and deposits the result under the collective-key
digest. The standing server (server/scheduler.py) calls ``refill_slab``
cooperatively on its drain thread — one slab per drain iteration, under
the cluster's proof-device lock, in the encode/verify pipeline gaps —
which is the same pattern its compile lane uses; offline tooling
(scripts/bench_pool.py) calls ``refill_to`` in a loop.
"""
from __future__ import annotations

import numpy as np

from . import store as _store


def refill_slab(pool: _store.CryptoPool, key, pub_tbl_table,
                elems: int | None = None) -> str:
    """Compute + deposit ONE slab of fresh zero-encryptions; returns the
    slab id. ``key`` is a jax PRNG key (caller supplies fresh splits —
    the slab's blinding scalars must never repeat); ``pub_tbl_table`` is
    the RAW collective-key fixed-base table (FixedBase.table)."""
    from ..parallel import dro

    elems = int(elems or pool.slab_elems)
    zero_ct, r = dro.precompute_rerandomization(key, pub_tbl_table, elems)
    digest = _store.key_digest(pub_tbl_table)
    return pool.deposit_dro(digest, np.asarray(zero_ct), np.asarray(r))


def refill_to(pool: _store.CryptoPool, key, pub_tbl_table,
              target_elems: int, max_slabs: int | None = None) -> int:
    """Deposit slabs until the balance covers ``target_elems`` (or
    ``max_slabs`` is hit); returns the number of slabs deposited."""
    import jax

    digest = _store.key_digest(pub_tbl_table)
    n = 0
    while pool.dro_balance(digest) < target_elems:
        if max_slabs is not None and n >= max_slabs:
            break
        key, sub = jax.random.split(key)
        refill_slab(pool, sub, pub_tbl_table)
        n += 1
    return n


__all__ = ["refill_slab", "refill_to"]
