"""Persistent cross-survey crypto-artifact store (the "crypto pool").

The reference amortizes its two heavyweight per-survey setups across
surveys AND processes: DRO shuffle precomputation is gob-persisted per
server (services/service.go:34,316-317 pre_compute_multiplications.gob +
unlynx PrecomputationWritingForShuffling), and the per-signature tables
are built once per signature set. This module is the repo's equivalent,
with two tenants:

  * **DRO precompute slabs** — fixed-width batches of fresh
    zero-encryptions ``(zero_ct (E,2,3,16), r (E,16))`` usable as the
    ``precomp`` argument of ``parallel.dro.shuffle_rerandomize``. Keyed
    by a digest of the collective-key fixed-base table (a slab is only
    valid under the key it was encrypted to) and the slab width.
  * **Sig tables** — ``sig_gt_table`` / ``sig_gt_pow_tables`` arrays
    keyed by the same A-table digests the in-process LRUs use
    (proofs/range_proof.py), so a fresh process skips the pairing batch
    and the ~10 s host pow-table build.

Single consumption is load-bearing CORRECTNESS, not bookkeeping: reusing
a DRO re-randomization mask across two surveys lets a proof observer
subtract the masks and recover both secret permutations — the privacy
the shuffle exists to provide. The claim protocol therefore tombstones a
slab BEFORE its ciphertexts are released:

  1. ``os.rename(slab.npz -> slab.npz.claimed)`` — atomic: exactly one
     claimant (thread OR process) can win; a loser raises
     ``DoubleConsumption``.
  2. append a ``consume`` event to the fsync'd ledger journal — the
     tombstone survives a crash from here on.
  3. only now read the arrays; then unlink the ``.claimed`` file.

Crash windows: a death between 1 and 3 leaves a ``.claimed`` file whose
randomness was never served — reopen deletes it (event ``recover``).
A death mid-write leaves a ``*.tmp`` partial — deposits write tmp +
fsync + ``os.replace``, so a live ``slab_*.npz`` is always complete and
reopen just sweeps the partials. A deposit that crashed after the
``os.replace`` but before its ledger line is simply a live slab with no
deposit event — servable; only ``consume`` events are load-bearing.

numpy-only on purpose: the store must be importable (and auditable) with
no accelerator runtime; the crypto lives in ``pool.replenish`` /
``parallel.dro``.
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import secrets
import struct
import threading

from ..resilience.policy import named_lock
import zipfile

import numpy as np

_DET_TRACE = os.environ.get("DRYNX_DET_TRACE", "0") == "1"
_PROTO_TRACE = os.environ.get("DRYNX_PROTO_TRACE", "0") == "1"


def mmap_enabled() -> bool:
    """``DRYNX_POOL_MMAP=off`` is the kill-switch back to eager slab /
    sig-table reads (full host copies out of np.load)."""
    return os.environ.get("DRYNX_POOL_MMAP",
                          "").strip().lower() not in ("off", "0", "no")


def _npz_members(path: str):
    """{name: (data_offset, dtype, shape, fortran)} for every member of
    an UNCOMPRESSED npz (np.savez default), or None when any member
    can't be mapped (compressed, foreign layout, unexpected header)."""
    out = {}
    with zipfile.ZipFile(path) as z, open(path, "rb") as f:
        for zi in z.infolist():
            if zi.compress_type != zipfile.ZIP_STORED:
                return None
            f.seek(zi.header_offset)
            hdr = f.read(30)
            if len(hdr) != 30 or hdr[:4] != b"PK\x03\x04":
                return None
            # the LOCAL header's name/extra lengths (they can differ
            # from the central directory's)
            fn_len, extra_len = struct.unpack("<HH", hdr[26:30])
            f.seek(zi.header_offset + 30 + fn_len + extra_len)
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dt = np.lib.format.read_array_header_1_0(f)
            elif version == (2, 0):
                shape, fortran, dt = np.lib.format.read_array_header_2_0(f)
            else:
                return None
            name = zi.filename
            if name.endswith(".npy"):
                name = name[:-4]
            out[name] = (f.tell(), dt, shape, fortran)
    return out


def _load_npz_mapped(path: str):
    """Read-only np.memmap per member of an npz, straight over the zip
    at each member's computed data offset — no host materialization;
    device_put can feed directly from the mapping. None on any surprise
    (the caller falls back to the eager np.load copy). A Linux mapping
    survives the file's unlink, so the claim protocol's read-then-unlink
    ordering is unchanged."""
    try:
        members = _npz_members(path)
        if members is None:
            return None
        return {name: np.memmap(path, dtype=dt, mode="r", offset=off,
                                shape=shape,
                                order="F" if fortran else "C")
                for name, (off, dt, shape, fortran) in members.items()}
    except Exception:
        return None


class PoolError(Exception):
    """Base class for pool failures."""


class DoubleConsumption(PoolError):
    """A slab was claimed twice (second claimant, any thread/process).

    This is the error the single-consumption ledger exists to raise:
    the caller must treat it as 'use different randomness', never as
    'retry the same slab'."""


class InsufficientBalance(PoolError):
    """The pool cannot cover the requested element count."""


def key_digest(table) -> str:
    """Content digest of a collective-key fixed-base table (64, 16, 3, 16).

    DRO slabs are zero-encryptions UNDER A SPECIFIC KEY — serving a slab
    encrypted to a different collective key would silently break the
    re-randomization (the ciphertexts would no longer decrypt to the
    survey's plaintexts). Content-addressing by the key table makes the
    mixup structurally impossible."""
    a = np.ascontiguousarray(np.asarray(table))
    return hashlib.sha256(a.tobytes()).hexdigest()[:16]


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_npz(path: str, **arrays) -> None:
    """tmp + fsync + os.replace: a reader never observes a partial file
    under the final name; a crash leaves only a ``*.tmp`` to sweep.

    The tmp name is unique per writer: content-addressed artifacts (fb /
    sig tables) are warmed concurrently by server threads since the
    fan-out went parallel, and two writers sharing one tmp path race —
    the loser's os.replace finds the tmp already moved (observed as
    FileNotFoundError under the DP dispatch fan-out). Distinct tmps make
    concurrent same-digest writes last-writer-wins over identical bytes."""
    tmp = f"{path}.{secrets.token_hex(8)}.tmp"
    inst = None
    if _PROTO_TRACE:
        from ..analysis import prototrace
        inst = prototrace.new_instance("atomic")
        prototrace.record(inst, "open")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        if inst:
            prototrace.record(inst, "write")
        f.flush()
        os.fsync(f.fileno())
        if inst:
            prototrace.record(inst, "fsync")
    if inst:
        prototrace.record(inst, "close")
    os.replace(tmp, path)
    if inst:
        prototrace.record(inst, "rename")
    _fsync_dir(os.path.dirname(path))


class CryptoPool:
    """One on-disk pool rooted at ``root``.

    Layout::

        root/ledger.jsonl                    append-only event journal
        root/dro/<digest>/<E>/slab_<id>.npz  live slab (E elements)
        root/dro/.../slab_<id>.npz.claimed   tombstoned, not yet unlinked
        root/sig/<kind>_<digest>.npz         content-addressed sig tables

    ``slab_elems`` is the width replenishment deposits at (consumers
    accept any width present). Thread-safe; multi-process safe for the
    consumption path (the rename claim is the arbiter — the in-memory
    consumed-set is an accelerator for the restart case, not the lock).
    """

    def __init__(self, root: str, slab_elems: int = 4096):
        self.root = os.path.abspath(root)
        self.slab_elems = int(slab_elems)
        self._lock = named_lock("ledger_lock", reentrant=True)
        self._consumed: set[str] = set()
        # process-local activity counters (lifetime state is the ledger)
        self.counters = {"deposited": 0, "consumed": 0, "recovered": 0,
                         "elements_consumed": 0}
        os.makedirs(os.path.join(self.root, "dro"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "sig"), exist_ok=True)
        self._ledger_path = os.path.join(self.root, "ledger.jsonl")
        self._replay_ledger()
        self._recover()

    # -- ledger ------------------------------------------------------------

    def _replay_ledger(self) -> None:
        if not os.path.exists(self._ledger_path):
            return
        with open(self._ledger_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    # torn final line from a crash mid-append: the claim
                    # rename happened first, so the .claimed sweep below
                    # still tombstones the slab — drop the torn tail
                    continue
                if ev.get("ev") in ("consume", "recover"):
                    self._consumed.add(ev["slab"])

    def _ledger_append(self, ev: dict) -> None:
        line = json.dumps(ev, sort_keys=True)
        if _DET_TRACE:
            # laundered: sort_keys canonicalizes the record bytes
            from ..analysis import dettrace
            dettrace.record("pool.journal", line, line.encode(),
                            laundered=True)
        with self._lock:
            with open(self._ledger_path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
            if _PROTO_TRACE:
                from ..analysis import prototrace
                inst = prototrace.new_instance("journal")
                prototrace.record(inst, "append")
                prototrace.record(inst, "fsync")

    # -- crash recovery ----------------------------------------------------

    def _recover(self) -> None:
        """Sweep crash residue: partial ``*.tmp`` writes are discarded
        (never visible under a live name); orphaned ``*.claimed`` slabs
        were tombstoned but never served — their randomness must not
        re-enter the pool, so they are journaled as ``recover`` and
        deleted."""
        pat = os.path.join(self.root, "dro", "**")
        for p in sorted(glob.glob(pat, recursive=True)):
            if p.endswith(".tmp"):
                os.unlink(p)
            elif p.endswith(".claimed"):
                sid = _slab_id(p[:-len(".claimed")])
                self._ledger_append({"ev": "recover", "slab": sid})
                self._consumed.add(sid)
                self.counters["recovered"] += 1
                os.unlink(p)

    # -- DRO slab tenant ---------------------------------------------------

    def _slab_dir(self, digest: str, elems: int) -> str:
        return os.path.join(self.root, "dro", digest, str(int(elems)))

    def _live_slabs(self, digest: str) -> list[str]:
        pat = os.path.join(self.root, "dro", digest, "*", "slab_*.npz")
        return sorted(glob.glob(pat))

    def deposit_dro(self, digest: str, zero_ct, r) -> str:
        """Persist one precompute slab; returns its slab id.

        Write-then-journal: the atomic replace makes the slab servable,
        the deposit event is informational (see module docstring)."""
        zero_ct = np.asarray(zero_ct)
        r = np.asarray(r)
        if zero_ct.shape[0] != r.shape[0]:
            raise PoolError(f"slab shape mismatch: {zero_ct.shape} vs "
                            f"{r.shape}")
        elems = int(zero_ct.shape[0])
        # drynx: deterministic[random slab ids name fungible randomness]
        sid = secrets.token_hex(8)
        d = self._slab_dir(digest, elems)
        os.makedirs(d, exist_ok=True)
        _atomic_write_npz(os.path.join(d, f"slab_{sid}.npz"),
                          zero_ct=zero_ct, r=r)
        self._ledger_append({"ev": "deposit", "slab": sid,
                             "digest": digest, "elems": elems})
        with self._lock:
            self.counters["deposited"] += 1
        return sid

    def dro_balance(self, digest: str) -> int:
        """Live (unclaimed) elements available under ``digest``."""
        return sum(_slab_elems(p) for p in self._live_slabs(digest))

    def _consume_path(self, path: str, digest: str):
        """The claim protocol (see module docstring): rename tombstone ->
        fsync'd ledger event -> only then read -> unlink."""
        sid = _slab_id(path)
        with self._lock:
            if sid in self._consumed:
                raise DoubleConsumption(
                    f"slab {sid} already consumed (ledger)")
        claimed = f"{path}.claimed"
        try:
            os.rename(path, claimed)
        except FileNotFoundError:
            # the slab existed when enumerated; only a concurrent claim
            # removes a live slab file
            raise DoubleConsumption(
                f"slab {sid} claimed concurrently") from None
        inst = None
        if _PROTO_TRACE:
            from ..analysis import prototrace
            inst = prototrace.new_instance("slab")
            prototrace.record(inst, "claim")
        self._ledger_append({"ev": "consume", "slab": sid,
                             "digest": digest,
                             "elems": _slab_elems(path)})
        if inst:
            prototrace.record(inst, "journal")
        with self._lock:
            self._consumed.add(sid)
            self.counters["consumed"] += 1
            self.counters["elements_consumed"] += _slab_elems(path)
        mapped = _load_npz_mapped(claimed) if mmap_enabled() else None
        if mapped is not None and "zero_ct" in mapped and "r" in mapped:
            # zero-copy serve: the mappings stay valid past the unlink
            # (the inode lives while mapped) and feed device_put without
            # ever materializing a full host copy
            out = (mapped["zero_ct"], mapped["r"])
        else:
            with np.load(claimed) as d:
                out = (d["zero_ct"].copy(), d["r"].copy())
        if inst:
            prototrace.record(inst, "read")
        os.unlink(claimed)
        if inst:
            prototrace.record(inst, "unlink")
        return out

    def consume_slab(self, digest: str, slab_id: str):
        """Consume one specific slab by id (test/diagnostic surface).

        Raises DoubleConsumption if it was ever consumed — in this
        process, by a concurrent thread, or by a previous process (the
        ledger replay covers the restart case)."""
        with self._lock:
            if slab_id in self._consumed:
                raise DoubleConsumption(
                    f"slab {slab_id} already consumed (ledger)")
        for p in self._live_slabs(digest):
            if _slab_id(p) == slab_id:
                return self._consume_path(p, digest)
        raise DoubleConsumption(
            f"slab {slab_id} not live under {digest} (claimed or unknown)")

    def try_consume_dro(self, digest: str, need: int):
        """Claim >= ``need`` elements and return ``(zero_ct, r)`` trimmed
        to exactly ``need``; None when the balance cannot cover it.

        Slabs are consumed whole: the unclaimed tail of the last slab is
        DISCARDED with its tombstone (never re-enters the pool) — the
        safe direction; wasting randomness is cheap, reusing it is a
        privacy break."""
        if need <= 0:
            return None
        if self.dro_balance(digest) < need:
            return None
        zs, rs, got = [], [], 0
        for p in self._live_slabs(digest):
            try:
                z, r = self._consume_path(p, digest)
            except DoubleConsumption:
                continue        # lost a race on this slab; try the next
            zs.append(z)
            rs.append(r)
            got += z.shape[0]
            if got >= need:
                break
        if got < need:
            # the balance shrank under us: everything claimed above is
            # already tombstoned and stays discarded
            raise InsufficientBalance(
                f"pool drained concurrently: got {got} < need {need}")
        if len(zs) == 1:
            # one slab covered the need: serve views of the (possibly
            # mapped) arrays instead of concatenating a fresh copy
            return zs[0][:need], rs[0][:need]
        z = np.concatenate(zs, axis=0)[:need]
        r = np.concatenate(rs, axis=0)[:need]
        return z, r

    def consume_dro(self, digest: str, need: int):
        out = self.try_consume_dro(digest, need)
        if out is None:
            raise InsufficientBalance(
                f"balance {self.dro_balance(digest)} < need {need}")
        return out

    # -- sig-table tenant --------------------------------------------------

    def _sig_path(self, kind: str, digest: str) -> str:
        assert "/" not in kind and "/" not in digest, (kind, digest)
        return os.path.join(self.root, "sig", f"{kind}_{digest}.npz")

    def save_sig(self, kind: str, digest: str, **arrays) -> None:
        """Content-addressed, idempotent: overwriting with the same
        digest rewrites identical bytes."""
        _atomic_write_npz(self._sig_path(kind, digest), **arrays)

    def load_sig(self, kind: str, digest: str):
        """Lazy per-key view of the sig-table npz — None when absent.

        Every caller uses exactly one key (range_proof's gt/pow tables,
        elgamal's fb table), so the old eager {k: copy for all keys}
        materialized arrays nobody read. Arrays load (mapped when
        DRYNX_POOL_MMAP is on) on first access and cache per key."""
        p = self._sig_path(kind, digest)
        if not os.path.exists(p):
            return None
        return SigTables(p)

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        pat = os.path.join(self.root, "dro", "*", "*", "slab_*.npz")
        live = glob.glob(pat)
        return {
            "slabs_live": len(live),
            "elements_live": sum(_slab_elems(p) for p in live),
            "slab_elems": self.slab_elems,
            **self.counters,
        }


class SigTables:
    """Lazy mapping over one sig-table npz: each array is read on first
    access only (np.memmap when DRYNX_POOL_MMAP is on, else an eager
    per-member np.load read) and cached. Supports the dict surface the
    sig-table callers use: ``d[key]``, ``in``, ``keys()``, iteration."""

    def __init__(self, path: str):
        self._path = path
        self._cache: dict = {}
        self._names = None

    def keys(self):
        if self._names is None:
            with zipfile.ZipFile(self._path) as z:
                self._names = [n[:-4] if n.endswith(".npy") else n
                               for n in z.namelist()]
        return list(self._names)

    def __contains__(self, k) -> bool:
        return k in self.keys()

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self.keys())

    def __getitem__(self, k):
        if k in self._cache:
            return self._cache[k]
        a = None
        if mmap_enabled():
            mapped = _load_npz_mapped(self._path)
            if mapped is not None:
                a = mapped[k]
        if a is None:
            with np.load(self._path) as d:
                a = d[k].copy()
        self._cache[k] = a
        return a


def _slab_id(path: str) -> str:
    stem = os.path.basename(path)
    assert stem.startswith("slab_") and stem.endswith(".npz"), path
    return stem[len("slab_"):-len(".npz")]


def _slab_elems(path: str) -> int:
    # width is the parent directory name (root/dro/<digest>/<E>/slab_*.npz)
    return int(os.path.basename(os.path.dirname(path)))


__all__ = ["CryptoPool", "PoolError", "DoubleConsumption",
           "InsufficientBalance", "key_digest", "SigTables",
           "mmap_enabled"]
