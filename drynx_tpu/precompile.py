"""AOT kernel precompile CLI: fill the persistent XLA cache before a bench.

A cold proofs-on process pays every kernel's trace+lower+compile lazily,
inside the timed survey. This CLI drives the compilecache registry
serially on the main thread instead, so `bench.py` (or any survey entry
point) starts with a warm `.jax_cache` and reaches its timed window in
minutes:

    python -m drynx_tpu.precompile              # TPU: trace+lower+compile
    python -m drynx_tpu.precompile --dry-run    # CPU-safe: trace/lower only
    python -m drynx_tpu.precompile --list       # enumerate, no tracing

--dry-run is also the registry's structural self-check (scripts/check.sh
`precompile` tier): it traces + lowers every program the current backend
would dispatch and exits nonzero if any fails. Shape knobs (--n-dps,
--values, ...) default to the flagship bench profile.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m drynx_tpu.precompile",
        description="AOT-precompile the proofs-on survey program set")
    ap.add_argument("--dry-run", action="store_true",
                    help="trace + lower only (no backend compile; CPU-safe)")
    ap.add_argument("--list", action="store_true",
                    help="print the program registry and exit (no tracing)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-program stderr rows")
    ap.add_argument("--n-cns", type=int, default=3)
    ap.add_argument("--n-dps", type=int, default=10)
    ap.add_argument("--values", type=int, default=9,
                    help="V: output values per DP (bench logreg: 9)")
    ap.add_argument("--range-u", type=int, default=16)
    ap.add_argument("--range-l", type=int, default=5)
    ap.add_argument("--dlog-limit", type=int, default=10000)
    ap.add_argument("--shards", type=int, default=None,
                    help="proof-plane shard count; adds the per-shard "
                         "program set (default: the plane's own policy — "
                         "visible devices, DRYNX_PROOF_PLANE override)")
    ap.add_argument("--queue", type=int, default=1,
                    help="cross-survey batch width (drynx_tpu/server); "
                         ">1 adds the cross-survey verify program set at "
                         "queue-concatenated batch sizes")
    ap.add_argument("--buckets", type=int, default=0,
                    help="bucket-grid width of a grid-op survey (min/max/"
                         "frequency_count/union/inter); above the tile "
                         "threshold adds the bucket-tile program set at "
                         "tile-derived shard sizes")
    ap.add_argument("--noise", type=int, default=0,
                    help="DRO noise-list size of a diffp survey; > 0 adds "
                         "the pool/slab program set (precompute refill + "
                         "shuffle) at dro.slab_widths chunk widths")
    ap.add_argument("--panes", type=int, default=0,
                    help="streaming-survey window width in panes "
                         "(service/streaming); > 1 adds the pane-delta "
                         "program set: raw ct_add/ct_sub at the window "
                         "shape plus the first advance's pane-stack fold")
    args = ap.parse_args(argv)

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from drynx_tpu import compilecache as cc

    n_shards = args.shards
    if n_shards is None:
        from drynx_tpu.parallel import proof_plane as plane

        n_shards = plane.n_shards()

    profile = cc.Profile(n_cns=args.n_cns, n_dps=args.n_dps,
                         n_values=args.values, u=args.range_u,
                         l=args.range_l, dlog_limit=args.dlog_limit,
                         n_shards=n_shards, n_queue=max(1, args.queue),
                         n_buckets=max(0, args.buckets),
                         n_noise=max(0, args.noise),
                         n_pane=max(0, args.panes))

    if args.list:
        specs = cc.build_registry(profile)
        w = max(len(s.name) for s in specs)
        for s in specs:
            on = "dispatched" if s.dispatched() else "skipped"
            print(f"{s.name:<{w}}  {s.kind:<8} {s.phase:<18} {on}")
        print(f"-- {len(specs)} programs "
              f"(backend: {jax.default_backend()})")
        return 0

    cc.trace_guard()
    cc.CompileStats.echo = not args.quiet
    if not args.dry_run:
        # feed the repo-local persistent cache (skipped for dry-run: the
        # CPU test suite keeps it off — see utils/cache.py)
        from drynx_tpu.utils.cache import enable_compilation_cache

        cache_dir = enable_compilation_cache()
        print(f"[precompile] persistent cache: {cache_dir}",
              file=sys.stderr, flush=True)
    print(f"[precompile] backend: {jax.default_backend()}",
          file=sys.stderr, flush=True)

    stats = cc.precompile(profile,
                          mode="lower" if args.dry_run else "compile")
    print(stats.table())
    return 1 if stats.count("error") else 0


if __name__ == "__main__":
    sys.exit(main())
