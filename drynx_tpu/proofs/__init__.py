"""Zero-knowledge proof layer: range, obfuscation, aggregation, key-switch,
shuffle proofs + the signed proof-request envelope.

Mirrors the capabilities of the reference's lib/range, lib/obfuscation,
lib/proof and the unlynx aggregation/keyswitch/shuffle proofs (SURVEY.md
§2.1 #15-17, §2.2), re-designed for TPU: proofs over batches of values are
fixed-shape limb tensors and every verification equation is a batched jitted
kernel; only Fiat-Shamir hashing runs host-side.
"""
from . import encoding  # noqa: F401
