"""Aggregation proof: the published aggregate equals the homomorphic sum of
the published inputs.

Replaces unlynx AggregationListProofCreation/Verification (used by the
reference at lib/proof/structs_proofs.go:188-264; hook at
services/service.go:533-558). As in unlynx, the proof is transparent — it
publishes inputs + output and verification recomputes the sum — but here the
recomputation is one batched tree reduction on device instead of a per-element
goroutine loop.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from . import encoding as enc


@dataclasses.dataclass
class AggregationProofBatch:
    """Inputs (n_contrib, V, 2, 3, 16) + claimed aggregate (V, 2, 3, 16)."""

    inputs: jnp.ndarray
    aggregate: jnp.ndarray

    def to_bytes(self) -> bytes:
        n, V = int(self.inputs.shape[0]), int(self.inputs.shape[1])
        head = np.asarray([n, V], dtype=np.int64).tobytes()
        return head + (np.ascontiguousarray(enc.ct_bytes(self.inputs)).tobytes()
                       + np.ascontiguousarray(
                           enc.ct_bytes(self.aggregate)).tobytes())


def create_aggregation_proof(inputs, aggregate) -> AggregationProofBatch:
    return AggregationProofBatch(inputs=jnp.asarray(inputs, dtype=jnp.uint32),
                                 aggregate=jnp.asarray(aggregate, dtype=jnp.uint32))


def verify_aggregation_proof(proof: AggregationProofBatch) -> np.ndarray:
    """Returns bool (V,): recomputed tree-reduced sum == claimed aggregate."""
    from ..crypto import batching as B

    acc = B.tree_reduce_add(proof.inputs, B.ct_add)
    ok = C.eq(acc, jnp.asarray(proof.aggregate, dtype=jnp.uint32))  # (V, 2)
    return np.asarray(jnp.all(ok, axis=-1))


def verify_aggregation_list(proof: AggregationProofBatch,
                            threshold: float) -> bool:
    import math

    V = int(proof.inputs.shape[1])
    nbr = math.ceil(threshold * V)
    if nbr == 0:
        return True
    sub = AggregationProofBatch(inputs=proof.inputs[:, :nbr],
                                aggregate=proof.aggregate[:nbr])
    return bool(np.all(verify_aggregation_proof(sub)))


__all__ = ["AggregationProofBatch", "create_aggregation_proof",
           "verify_aggregation_proof", "verify_aggregation_list"]
