"""Canonical byte serialization for group elements + Fiat-Shamir hashing.

The reference relies on kyber's MarshalBinary for hashing/signing (e.g.
lib/range/range_proof.go:350-375 hashes B ‖ commit ‖ ΣY with sha3-512;
lib/proof/structs_proofs.go:117 Schnorr-signs marshaled payloads). This
framework defines its own canonical encoding, built directly from limb
tensors with vectorized numpy (no bigint round trips):

  scalar / Fp element : 32 bytes big-endian
  G1 point            : x ‖ y (64 B), infinity = all-zero
  G2 point            : x0 ‖ x1 ‖ y0 ‖ y1 (128 B), infinity = all-zero
  GT element          : 6 Fp2 coeffs = 384 B

All *_bytes functions accept batched device arrays and return uint8 numpy
arrays with a trailing byte axis, so a (V, ...) batch hashes V messages with
one device→host transfer.
"""
from __future__ import annotations

import hashlib

import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import fp2 as F2
from ..crypto import g2 as G2
from ..crypto import field as F
from ..crypto.field import FN, FP
from ..crypto.params import LIMB_BITS, NUM_LIMBS


def limbs_to_bytes(limbs) -> np.ndarray:
    """(..., 16) uint32 little-endian limbs -> (..., 32) uint8 big-endian."""
    a = np.asarray(limbs).astype(np.uint32)
    rev = a[..., ::-1]  # most-significant limb first
    hi = (rev >> 8).astype(np.uint8)
    lo = (rev & 0xFF).astype(np.uint8)
    return np.stack([hi, lo], axis=-1).reshape(a.shape[:-1] + (2 * NUM_LIMBS,))


def bytes_to_limbs(b) -> np.ndarray:
    """(..., 32) uint8 big-endian -> (..., 16) uint32 limbs."""
    a = np.asarray(b, dtype=np.uint8).reshape(
        np.asarray(b).shape[:-1] + (NUM_LIMBS, 2))
    limbs = (a[..., 0].astype(np.uint32) << 8) | a[..., 1].astype(np.uint32)
    return limbs[..., ::-1].copy()


def scalar_bytes(s_limbs) -> np.ndarray:
    return limbs_to_bytes(s_limbs)


def g1_bytes(pts) -> np.ndarray:
    """Jacobian Montgomery G1 (..., 3, 16) -> canonical (..., 64) uint8.

    Uses the BUCKETED normalize/from_mont kernels: the raw jnp path
    re-traces the 256-step Fermat inverse for every distinct tensor shape
    (challenges + serialization hit many shapes per survey)."""
    from ..crypto import batching as B

    x_m, y_m, inf = B.g1_normalize(jnp.asarray(pts, dtype=jnp.uint32))
    x = np.asarray(B.from_mont_p(x_m))
    y = np.asarray(B.from_mont_p(y_m))
    out = np.concatenate([limbs_to_bytes(x), limbs_to_bytes(y)], axis=-1)
    out[np.asarray(inf)] = 0
    return out


def g2_bytes(pts) -> np.ndarray:
    """Jacobian Montgomery G2 (..., 3, 2, 16) -> canonical (..., 128) uint8."""
    from ..crypto import batching as B

    x_m, y_m, inf = B.g2_normalize(jnp.asarray(pts, dtype=jnp.uint32))
    plain = np.asarray(B.from_mont_p(
        jnp.stack([x_m, y_m], axis=-3)))         # (..., 2, 2, 16)
    parts = [plain[..., 0, 0, :], plain[..., 0, 1, :],
             plain[..., 1, 0, :], plain[..., 1, 1, :]]
    out = np.concatenate([limbs_to_bytes(p) for p in parts], axis=-1)
    out[np.asarray(inf)] = 0
    return out


def gt_bytes(f) -> np.ndarray:
    """GT element (..., 6, 2, 16) Montgomery -> (..., 384) uint8."""
    from ..crypto import batching as B

    a = np.asarray(B.from_mont_p(jnp.asarray(f, dtype=jnp.uint32)))  # (..., 6, 2, 16)
    b = limbs_to_bytes(a)  # (..., 6, 2, 32)
    return b.reshape(b.shape[:-3] + (6 * 2 * 2 * NUM_LIMBS,))


def ct_bytes(cts) -> np.ndarray:
    """ElGamal ciphertexts (..., 2, 3, 16) -> (..., 128) uint8."""
    b = g1_bytes(cts)  # (..., 2, 64)
    return b.reshape(b.shape[:-2] + (128,))


def hash_to_scalar(*chunks, batch_shape=()) -> np.ndarray:
    """sha3-512 over concatenated canonical bytes -> mod-n scalar limbs.

    Each chunk is a uint8 array either of shape (k,) (shared prefix) or
    batch_shape + (k,) (per-element). Returns limbs batch_shape + (16,).
    Mirrors the reference's sha3.New512 + Scalar.SetBytes Fiat-Shamir
    (lib/range/range_proof.go:348-375).
    """
    from ..crypto import params

    if not batch_shape:
        h = hashlib.sha3_512()
        for c in chunks:
            h.update(np.ascontiguousarray(c).tobytes())
        v = int.from_bytes(h.digest(), "big") % params.N
        return F.from_int(v)

    flat = int(np.prod(batch_shape))
    exp = []
    for c in chunks:
        c = np.ascontiguousarray(c)
        if c.shape[:-1] == tuple(batch_shape):
            exp.append(c.reshape(flat, -1))
        else:
            exp.append(np.broadcast_to(c, (flat,) + c.shape).reshape(flat, -1))
    out = np.zeros((flat, NUM_LIMBS), dtype=np.uint32)
    for i in range(flat):
        h = hashlib.sha3_512()
        for c in exp:
            h.update(c[i].tobytes())
        v = int.from_bytes(h.digest(), "big") % params.N
        out[i] = F.from_int(v)
    return out.reshape(tuple(batch_shape) + (NUM_LIMBS,))


__all__ = ["limbs_to_bytes", "bytes_to_limbs", "scalar_bytes", "g1_bytes",
           "g2_bytes", "gt_bytes", "ct_bytes", "hash_to_scalar"]
