"""Key-switch ZK proof: a server proves its contribution
(U, W) = (r·B, r·Q − x·K) is correct w.r.t. its public key Y = x·B.

Replaces the unlynx KeySwitchListProofCreation/Verification used by the
reference (lib/proof/structs_proofs.go:420-492; protocol hook at
services/service.go:566-616). One proof batch covers every (server,
ciphertext) pair: tensors are (ns, V, ...) and verification is one batched
kernel.

Sigma protocol per (server i, value j), with K the original ciphertext's
randomness component and Q the target (querier) public key:
  commit    A1 = wr·B, A2 = wr·Q − wx·K, A3 = wx·B     (wr, wx fresh)
  challenge c = H(K ‖ U ‖ W ‖ Y ‖ Q ‖ A1 ‖ A2 ‖ A3)
  response  zr = wr + c·r,  zx = wx + c·x
  verify    zr·B == A1 + c·U
            zr·Q − zx·K == A2 + c·W
            zx·B == A3 + c·Y
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import elgamal as eg
from . import encoding as enc


@dataclasses.dataclass
class KeySwitchProofBatch:
    """(ns, V) key-switch contribution proofs."""

    orig_k: jnp.ndarray   # (V, 3, 16) original ciphertext K components
    u_pts: jnp.ndarray    # (ns, V, 3, 16) contributions rB
    w_pts: jnp.ndarray    # (ns, V, 3, 16) contributions rQ − xK
    ys: jnp.ndarray       # (ns, 3, 16) server publics
    q_pt: jnp.ndarray     # (3, 16) target public key
    a1: jnp.ndarray       # (ns, V, 3, 16)
    a2: jnp.ndarray       # (ns, V, 3, 16)
    a3: jnp.ndarray       # (ns, V, 3, 16)
    challenge: jnp.ndarray  # (ns, V, 16)
    zr: jnp.ndarray       # (ns, V, 16)
    zx: jnp.ndarray       # (ns, V, 16)
    # NOTE: unlike RangeProofBatch there is deliberately NO wire-byte cache
    # field — the batch travels as pickle, where a cached dict would be
    # attacker-controlled (bytes disagreeing with the tensors) and would
    # bloat every prover->VN message. Everything that needs the canonical
    # encoding re-derives it from the tensors via _wire_dict.

    def to_bytes(self) -> bytes:
        ns, V = int(self.u_pts.shape[0]), int(self.u_pts.shape[1])
        head = np.asarray([ns, V], dtype="<i8").tobytes()
        w = _wire_dict(self)
        parts = [w["k"], w["u"], w["w"], w["ys"], w["q"], w["a1"], w["a2"],
                 w["a3"],
                 enc.scalar_bytes(self.challenge), enc.scalar_bytes(self.zr),
                 enc.scalar_bytes(self.zx)]
        return head + b"".join(np.ascontiguousarray(p).tobytes()
                               for p in parts)


def _wire_dict(pb: "KeySwitchProofBatch") -> dict:
    """THE one definition of the canonical transcript encoding — creation,
    to_bytes and verification all call this so the Fiat-Shamir hash can
    never desynchronize between them."""
    return {"k": enc.g1_bytes(pb.orig_k), "u": enc.g1_bytes(pb.u_pts),
            "w": enc.g1_bytes(pb.w_pts), "ys": enc.g1_bytes(pb.ys),
            "q": enc.g1_bytes(pb.q_pt), "a1": enc.g1_bytes(pb.a1),
            "a2": enc.g1_bytes(pb.a2), "a3": enc.g1_bytes(pb.a3)}


def _challenge_from_wire(w: dict, ns: int, V: int) -> jnp.ndarray:
    kb = np.broadcast_to(w["k"], (ns, V, 64))
    yb = np.broadcast_to(w["ys"][:, None, :], (ns, V, 64))
    qb = np.broadcast_to(w["q"], (ns, V, 64))
    return jnp.asarray(enc.hash_to_scalar(
        kb, w["u"], w["w"], yb, qb, w["a1"], w["a2"], w["a3"],
        batch_shape=(ns, V)), dtype=jnp.uint32)


def _commit_kernel(orig_k, q_tbl, wr, wx):
    """Built from the SHARED bucketed primitives (crypto/batching.py):
    a monolithic jit here duplicated four 256-step ladder graphs into a
    fresh program per (ns, V) shape — XLA's CPU compiler aborted under the
    accumulated load of a full-suite run, and every new survey shape paid
    a fresh compile. The bucketed kernels are compiled once per size
    bucket, shared with every other proof path."""
    from ..crypto import batching as B

    base = eg.BASE_TABLE.table
    a1 = B.fixed_base_mul(base, wr)
    a2 = B.g1_add(B.fixed_base_mul(q_tbl, wr),
                  B.g1_neg(B.g1_scalar_mul(orig_k, wx)))
    a3 = B.fixed_base_mul(base, wx)
    return a1, a2, a3


def _response_kernel(wr, wx, c, r, x):
    from ..crypto import batching as B

    zr = B.fn_add(wr, B.fn_mul_plain(c, r))
    zx = B.fn_add(wx, B.fn_mul_plain(c, x))
    return zr, zx


def create_keyswitch_proofs(key, orig_k, srv_x, ks_rs, q_pt, q_tbl,
                            u_pts, w_pts) -> KeySwitchProofBatch:
    """orig_k: (V, 3, 16); srv_x: (ns, 16) secrets; ks_rs: (ns, V, 16) the
    key-switch randomness; q_pt/q_tbl: target pub point + fixed-base table;
    u_pts/w_pts: (ns, V, 3, 16) the contributions actually produced by
    parallel.keyswitch_contribution."""
    ns, V = ks_rs.shape[0], ks_rs.shape[1]
    k1, k2 = jax.random.split(key)
    wr = eg.random_scalars(k1, (ns, V))
    wx = eg.random_scalars(k2, (ns, V))
    a1, a2, a3 = _commit_kernel(orig_k, q_tbl, wr, wx)
    base = eg.BASE_TABLE.table
    ys = eg.fixed_base_mul(base, jnp.asarray(srv_x, dtype=jnp.uint32))
    # build the batch FIRST, then hash via the shared _wire_dict (computed
    # transiently — see the no-cache NOTE on the dataclass)
    pb = KeySwitchProofBatch(orig_k=jnp.asarray(orig_k, dtype=jnp.uint32), u_pts=u_pts,
                             w_pts=w_pts, ys=ys, q_pt=jnp.asarray(q_pt, dtype=jnp.uint32),
                             a1=a1, a2=a2, a3=a3,
                             challenge=jnp.zeros((ns, V, 16), jnp.uint32),
                             zr=jnp.zeros((ns, V, 16), jnp.uint32),
                             zx=jnp.zeros((ns, V, 16), jnp.uint32))
    c = _challenge_from_wire(_wire_dict(pb), ns, V)
    zr, zx = _response_kernel(wr, wx, c, jnp.asarray(ks_rs, dtype=jnp.uint32),
                              jnp.asarray(srv_x, dtype=jnp.uint32)[:, None, :])
    pb.challenge, pb.zr, pb.zx = c, zr, zx
    return pb


def _verify_kernel(orig_k, u_pts, w_pts, ys, q_tbl, a1, a2, a3, c, zr, zx):
    """Shared bucketed primitives — see _commit_kernel's note."""
    from ..crypto import batching as B

    base = eg.BASE_TABLE.table
    ok1 = B.g1_eq(B.fixed_base_mul(base, zr),
                  B.g1_add(a1, B.g1_scalar_mul(u_pts, c)))
    lhs2 = B.g1_add(B.fixed_base_mul(q_tbl, zr),
                    B.g1_neg(B.g1_scalar_mul(orig_k, zx)))
    ok2 = B.g1_eq(lhs2, B.g1_add(a2, B.g1_scalar_mul(w_pts, c)))
    ok3 = B.g1_eq(B.fixed_base_mul(base, zx),
                  B.g1_add(a3, B.g1_scalar_mul(ys[:, None], c)))
    return jnp.asarray(ok1, dtype=jnp.bool_) & jnp.asarray(ok2, dtype=jnp.bool_) & jnp.asarray(ok3, dtype=jnp.bool_)


def verify_keyswitch_proofs(proof: KeySwitchProofBatch, q_tbl) -> np.ndarray:
    """Returns bool (ns, V); recomputes the challenge.

    Re-encodes the hashed tensors itself (_wire_dict) — there is no wire
    cache on this batch to trust; see the dataclass NOTE. (RangeProofBatch
    CAN trust its cache: from_bytes derives tensors and cache from one
    buffer.)"""
    ok = np.asarray(_verify_kernel(
        proof.orig_k, proof.u_pts, proof.w_pts, proof.ys, q_tbl, proof.a1,
        proof.a2, proof.a3, proof.challenge, proof.zr, proof.zx))
    ns, V = int(proof.u_pts.shape[0]), int(proof.u_pts.shape[1])
    want = np.asarray(_challenge_from_wire(_wire_dict(proof), ns, V))
    return ok & np.all(np.asarray(proof.challenge) == want, axis=-1)


def verify_keyswitch_list(proof: KeySwitchProofBatch, q_tbl,
                          threshold: float) -> bool:
    """Threshold-sampled verification over the value axis (reference samples
    whole proofs at structs_proofs.go:471)."""
    import math

    V = int(proof.u_pts.shape[1])
    nbr = math.ceil(threshold * V)
    if nbr == 0:
        return True
    sub = KeySwitchProofBatch(
        orig_k=proof.orig_k[:nbr], u_pts=proof.u_pts[:, :nbr],
        w_pts=proof.w_pts[:, :nbr], ys=proof.ys, q_pt=proof.q_pt,
        a1=proof.a1[:, :nbr], a2=proof.a2[:, :nbr], a3=proof.a3[:, :nbr],
        challenge=proof.challenge[:, :nbr], zr=proof.zr[:, :nbr],
        zx=proof.zx[:, :nbr])
    return bool(np.all(verify_keyswitch_proofs(sub, q_tbl)))


__all__ = ["KeySwitchProofBatch", "create_keyswitch_proofs",
           "verify_keyswitch_proofs", "verify_keyswitch_list"]
