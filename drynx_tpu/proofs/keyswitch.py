"""Key-switch ZK proof: a server proves its contribution
(U, W) = (r·B, r·Q − x·K) is correct w.r.t. its public key Y = x·B.

Replaces the unlynx KeySwitchListProofCreation/Verification used by the
reference (lib/proof/structs_proofs.go:420-492; protocol hook at
services/service.go:566-616). One proof batch covers every (server,
ciphertext) pair: tensors are (ns, V, ...) and verification is one batched
kernel.

Sigma protocol per (server i, value j), with K the original ciphertext's
randomness component and Q the target (querier) public key:
  commit    A1 = wr·B, A2 = wr·Q − wx·K, A3 = wx·B     (wr, wx fresh)
  challenge c = H(K ‖ U ‖ W ‖ Y ‖ Q ‖ A1 ‖ A2 ‖ A3)
  response  zr = wr + c·r,  zx = wx + c·x
  verify    zr·B == A1 + c·U
            zr·Q − zx·K == A2 + c·W
            zx·B == A3 + c·Y
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import elgamal as eg
from ..crypto import field as F
from ..crypto.field import FN
from . import encoding as enc


@dataclasses.dataclass
class KeySwitchProofBatch:
    """(ns, V) key-switch contribution proofs."""

    orig_k: jnp.ndarray   # (V, 3, 16) original ciphertext K components
    u_pts: jnp.ndarray    # (ns, V, 3, 16) contributions rB
    w_pts: jnp.ndarray    # (ns, V, 3, 16) contributions rQ − xK
    ys: jnp.ndarray       # (ns, 3, 16) server publics
    q_pt: jnp.ndarray     # (3, 16) target public key
    a1: jnp.ndarray       # (ns, V, 3, 16)
    a2: jnp.ndarray       # (ns, V, 3, 16)
    a3: jnp.ndarray       # (ns, V, 3, 16)
    challenge: jnp.ndarray  # (ns, V, 16)
    zr: jnp.ndarray       # (ns, V, 16)
    zx: jnp.ndarray       # (ns, V, 16)

    def to_bytes(self) -> bytes:
        ns, V = int(self.u_pts.shape[0]), int(self.u_pts.shape[1])
        head = np.asarray([ns, V], dtype=np.int64).tobytes()
        parts = [enc.g1_bytes(self.orig_k), enc.g1_bytes(self.u_pts),
                 enc.g1_bytes(self.w_pts), enc.g1_bytes(self.ys),
                 enc.g1_bytes(self.q_pt), enc.g1_bytes(self.a1),
                 enc.g1_bytes(self.a2), enc.g1_bytes(self.a3),
                 enc.scalar_bytes(self.challenge), enc.scalar_bytes(self.zr),
                 enc.scalar_bytes(self.zx)]
        return head + b"".join(np.ascontiguousarray(p).tobytes()
                               for p in parts)


def _challenge(orig_k, u_pts, w_pts, ys, q_pt, a1, a2, a3) -> jnp.ndarray:
    ns, V = u_pts.shape[0], u_pts.shape[1]
    kb = np.broadcast_to(enc.g1_bytes(orig_k), (ns, V, 64))
    yb = np.broadcast_to(enc.g1_bytes(ys)[:, None, :], (ns, V, 64))
    qb = np.broadcast_to(enc.g1_bytes(q_pt), (ns, V, 64))
    return jnp.asarray(enc.hash_to_scalar(
        kb, enc.g1_bytes(u_pts), enc.g1_bytes(w_pts), yb, qb,
        enc.g1_bytes(a1), enc.g1_bytes(a2), enc.g1_bytes(a3),
        batch_shape=(ns, V)))


@jax.jit
def _commit_kernel(orig_k, q_tbl, wr, wx):
    base = eg.BASE_TABLE.table
    a1 = eg.fixed_base_mul(base, wr)
    a2 = C.add(eg.fixed_base_mul(q_tbl, wr),
               C.neg(C.scalar_mul(orig_k, wx)))
    a3 = eg.fixed_base_mul(base, wx)
    return a1, a2, a3


@jax.jit
def _response_kernel(wr, wx, c, r, x):
    cm = F.to_mont(c, FN)
    zr = F.add(wr, F.mont_mul(cm, r, FN), FN)
    zx = F.add(wx, F.mont_mul(cm, x, FN), FN)
    return zr, zx


def create_keyswitch_proofs(key, orig_k, srv_x, ks_rs, q_pt, q_tbl,
                            u_pts, w_pts) -> KeySwitchProofBatch:
    """orig_k: (V, 3, 16); srv_x: (ns, 16) secrets; ks_rs: (ns, V, 16) the
    key-switch randomness; q_pt/q_tbl: target pub point + fixed-base table;
    u_pts/w_pts: (ns, V, 3, 16) the contributions actually produced by
    parallel.keyswitch_contribution."""
    ns, V = ks_rs.shape[0], ks_rs.shape[1]
    k1, k2 = jax.random.split(key)
    wr = eg.random_scalars(k1, (ns, V))
    wx = eg.random_scalars(k2, (ns, V))
    a1, a2, a3 = _commit_kernel(orig_k, q_tbl, wr, wx)
    base = eg.BASE_TABLE.table
    ys = eg.fixed_base_mul(base, jnp.asarray(srv_x))
    c = _challenge(orig_k, u_pts, w_pts, ys, q_pt, a1, a2, a3)
    zr, zx = _response_kernel(wr, wx, c, jnp.asarray(ks_rs),
                              jnp.asarray(srv_x)[:, None, :])
    return KeySwitchProofBatch(orig_k=jnp.asarray(orig_k), u_pts=u_pts,
                               w_pts=w_pts, ys=ys, q_pt=jnp.asarray(q_pt),
                               a1=a1, a2=a2, a3=a3, challenge=c, zr=zr, zx=zx)


@jax.jit
def _verify_kernel(orig_k, u_pts, w_pts, ys, q_tbl, a1, a2, a3, c, zr, zx):
    base = eg.BASE_TABLE.table
    ok1 = C.eq(eg.fixed_base_mul(base, zr),
               C.add(a1, C.scalar_mul(u_pts, c)))
    lhs2 = C.add(eg.fixed_base_mul(q_tbl, zr),
                 C.neg(C.scalar_mul(orig_k, zx)))
    ok2 = C.eq(lhs2, C.add(a2, C.scalar_mul(w_pts, c)))
    ok3 = C.eq(eg.fixed_base_mul(base, zx),
               C.add(a3, C.scalar_mul(ys[:, None], c)))
    return ok1 & ok2 & ok3


def verify_keyswitch_proofs(proof: KeySwitchProofBatch, q_tbl) -> np.ndarray:
    """Returns bool (ns, V); recomputes the challenge."""
    ok = np.asarray(_verify_kernel(
        proof.orig_k, proof.u_pts, proof.w_pts, proof.ys, q_tbl, proof.a1,
        proof.a2, proof.a3, proof.challenge, proof.zr, proof.zx))
    want = np.asarray(_challenge(proof.orig_k, proof.u_pts, proof.w_pts,
                                 proof.ys, proof.q_pt, proof.a1, proof.a2,
                                 proof.a3))
    return ok & np.all(np.asarray(proof.challenge) == want, axis=-1)


def verify_keyswitch_list(proof: KeySwitchProofBatch, q_tbl,
                          threshold: float) -> bool:
    """Threshold-sampled verification over the value axis (reference samples
    whole proofs at structs_proofs.go:471)."""
    import math

    V = int(proof.u_pts.shape[1])
    nbr = math.ceil(threshold * V)
    if nbr == 0:
        return True
    sub = KeySwitchProofBatch(
        orig_k=proof.orig_k[:nbr], u_pts=proof.u_pts[:, :nbr],
        w_pts=proof.w_pts[:, :nbr], ys=proof.ys, q_pt=proof.q_pt,
        a1=proof.a1[:, :nbr], a2=proof.a2[:, :nbr], a3=proof.a3[:, :nbr],
        challenge=proof.challenge[:, :nbr], zr=proof.zr[:, :nbr],
        zx=proof.zx[:, :nbr])
    return bool(np.all(verify_keyswitch_proofs(sub, q_tbl)))


__all__ = ["KeySwitchProofBatch", "create_keyswitch_proofs",
           "verify_keyswitch_proofs", "verify_keyswitch_list"]
