"""Obfuscation ZK proof: "the same secret scalar s multiplies both ElGamal
components" (K' = s·K, C' = s·C).

The reference builds this with kyber's proof DSL (proof.Rep/And,
lib/obfuscation/obfuscation_proof.go:36-44) one ciphertext at a time inside a
goroutine fan-out (:62-77). Here one proof object covers a whole ciphertext
vector: commitments, challenges and responses are (V, ...) limb tensors and
both create and verify are two batched device kernels around one host-side
Fiat-Shamir hash.

Sigma protocol per value:
  commit   A1 = w·K, A2 = w·C            (w fresh random)
  challenge c = H(K ‖ C ‖ K' ‖ C' ‖ A1 ‖ A2)
  response  z = w + c·s
  verify    z·K == A1 + c·K'  and  z·C == A2 + c·C'
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..crypto import elgamal as eg
from . import encoding as enc


@dataclasses.dataclass
class ObfuscationProofBatch:
    """Mirrors PublishedListObfuscationProof (obfuscation_proof.go:20-33)
    with the ciphertext axis batched."""

    orig: jnp.ndarray     # (V, 2, 3, 16)
    obf: jnp.ndarray      # (V, 2, 3, 16)
    a1: jnp.ndarray       # (V, 3, 16) commitment w·K
    a2: jnp.ndarray       # (V, 3, 16) commitment w·C
    challenge: jnp.ndarray  # (V, 16)
    z: jnp.ndarray        # (V, 16)

    def to_bytes(self) -> bytes:
        V = int(self.orig.shape[0])
        head = np.asarray([V], dtype=np.int64).tobytes()
        parts = [enc.ct_bytes(self.orig), enc.ct_bytes(self.obf),
                 enc.g1_bytes(self.a1), enc.g1_bytes(self.a2),
                 enc.scalar_bytes(self.challenge), enc.scalar_bytes(self.z)]
        return head + b"".join(np.ascontiguousarray(p).tobytes()
                               for p in parts)


def _commit_kernel(ct, w):
    # shared bucketed primitives: a monolithic jit here re-compiled the
    # 256-step ladder graphs per V shape (see keyswitch._commit_kernel)
    from ..crypto import batching as B

    K, Cc = ct[..., 0, :, :], ct[..., 1, :, :]
    return B.g1_scalar_mul(K, w), B.g1_scalar_mul(Cc, w)


def _response_kernel(w, c, s):
    from ..crypto import batching as B

    return B.fn_add(w, B.fn_mul_plain(c, s))


def _challenge(orig, obf, a1, a2) -> jnp.ndarray:
    return jnp.asarray(enc.hash_to_scalar(
        enc.ct_bytes(orig), enc.ct_bytes(obf), enc.g1_bytes(a1),
        enc.g1_bytes(a2), batch_shape=orig.shape[:-3]), dtype=jnp.uint32)


def create_obfuscation_proofs(key, ct, s) -> ObfuscationProofBatch:
    """ct: (V, 2, 3, 16) pre-obfuscation; s: (V, 16) the secret scalars.
    (Reference ObfuscationProofCreation, obfuscation_proof.go:47-59.)"""
    obf = eg.ct_scalar_mul(ct, s)
    w = eg.random_scalars(key, ct.shape[:-3])
    a1, a2 = _commit_kernel(ct, w)
    c = _challenge(ct, obf, a1, a2)
    z = _response_kernel(w, c, s)
    return ObfuscationProofBatch(orig=jnp.asarray(ct, dtype=jnp.uint32), obf=obf, a1=a1, a2=a2,
                                 challenge=c, z=z)


def _verify_kernel(orig, obf, a1, a2, c, z):
    from ..crypto import batching as B

    K, Cc = orig[..., 0, :, :], orig[..., 1, :, :]
    Kp, Cp = obf[..., 0, :, :], obf[..., 1, :, :]
    ok1 = B.g1_eq(B.g1_scalar_mul(K, z),
                  B.g1_add(a1, B.g1_scalar_mul(Kp, c)))
    ok2 = B.g1_eq(B.g1_scalar_mul(Cc, z),
                  B.g1_add(a2, B.g1_scalar_mul(Cp, c)))
    return jnp.asarray(ok1, dtype=jnp.bool_) & jnp.asarray(ok2, dtype=jnp.bool_)


def verify_obfuscation_proofs(proof: ObfuscationProofBatch) -> np.ndarray:
    """Returns bool (V,). Recomputes the Fiat-Shamir challenge.
    (Reference ObfuscationProofVerification, obfuscation_proof.go:80-91.)"""
    ok = np.asarray(_verify_kernel(proof.orig, proof.obf, proof.a1, proof.a2,
                                   proof.challenge, proof.z))
    want = np.asarray(_challenge(proof.orig, proof.obf, proof.a1, proof.a2))
    return ok & np.all(np.asarray(proof.challenge) == want, axis=-1)


def verify_obfuscation_list(proof: ObfuscationProofBatch,
                            threshold: float) -> bool:
    """Threshold-sampled verification over the value axis (reference
    ObfuscationListProofVerification, obfuscation_proof.go:94-110)."""
    import math

    V = int(proof.orig.shape[0])
    nbr = math.ceil(threshold * V)
    if nbr == 0:
        return True
    sub = ObfuscationProofBatch(
        orig=proof.orig[:nbr], obf=proof.obf[:nbr], a1=proof.a1[:nbr],
        a2=proof.a2[:nbr], challenge=proof.challenge[:nbr], z=proof.z[:nbr])
    return bool(np.all(verify_obfuscation_proofs(sub)))


__all__ = ["ObfuscationProofBatch", "create_obfuscation_proofs",
           "verify_obfuscation_proofs", "verify_obfuscation_list"]
