"""Batched CCS-style ZK range proofs with Boneh–Boyen digit signatures.

Reference semantics (lib/range/range_proof.go): a DP proves its ElGamal
plaintext σ ∈ [0, u^l) by base-u digit decomposition (ToBase :584). Each CN
publishes BB signatures A[k] = (x+k)^{-1}·B2 for k<u (InitRangeProofSignature
:270-288); the proof blinds the digit signatures (V = v·A[φ] :392-394),
commits D = Σ u^j s_j·B + m·P, and answers challenge c with Zphi, Zv, Zr;
the verifier checks
  D  == c·C + Zr·P + Σ u^j·Zphi_j·B                       (:519-529)
  a  == e(c·y − Zphi_j·B, V_ij) · e(B,B2)^{Zv_ij}         (:538-546)
(the reference's three pairings per digit collapse to ONE pairing + one GT
exponentiation here — same equation, shared bilinearity).

Fiat-Shamir binding: the reference hashes only c = sha3-512(B ‖ C ‖ ΣY)
(:348-375) and its verifier trusts the transmitted challenge — so a forger
can fix c FIRST, choose Zphi/Zr/Zv/V freely, and *derive* D and a from the
two verifier equations; every check passes for a ciphertext encrypting
anything. This implementation closes that hole: the challenge is
  c = sha3-512(B ‖ C2 ‖ ΣY ‖ u ‖ l ‖ D ‖ V_pts ‖ a)
i.e. it binds ALL prover commitments (proper sigma-protocol Fiat-Shamir:
commit, then hash, then respond), and verification REQUIRES the recomputed
challenge to match. Deriving D or a post-hoc now changes c, which changes
the equations they must satisfy — a hash-fixed-point search.

TPU design: one proof BATCH covers a whole ciphertext vector (V values):
digits, responses and blinded signatures are (ns, V, l, ...) limb tensors;
the pairings run as one batched Miller-loop scan. Host work is only the
Fiat-Shamir hash.
"""
from __future__ import annotations

import dataclasses
import secrets
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import elgamal as eg
from ..crypto import field as F
from ..crypto import fp12 as F12
from ..crypto import g2 as G2
from ..crypto import pairing as PAIR
from ..resilience.policy import named_lock
from ..crypto import params, refimpl
from ..crypto.field import FN, FP
from . import encoding as enc

# ---------------------------------------------------------------------------
# Signature initialization (per CN, host-side — rare, key-lifetime event)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RangeSig:
    """One server's digit-signature set for base u (PublishSignature)."""

    secret: int
    public: tuple           # host affine G1 ints (y = x·B)
    A: np.ndarray           # (u, 3, 2, 16) G2 Jacobian Montgomery limbs
    gt: Optional[np.ndarray] = None   # (u, 6, 2, 16) e(B, A[k]) cache

    @property
    def u(self) -> int:
        return self.A.shape[0]


def sig_gt_table(sigs: list["RangeSig"]) -> jnp.ndarray:
    """(ns, u, 6, 2, 16): gtA[i][k] = e(B, A_i[k]), computed once per
    signature set (u*ns pairings) and cached on the RangeSig objects.

    This is the prover-side shortcut the fixed digit-signature structure
    allows: a_ij = e(-s_j B, v_ij A_i[phi_j]) * gtB^t = gtA[i][phi_j]^(-s_j
    v_ij) * gtB^t — one GT exponentiation instead of a Miller loop + final
    exp per digit (the reference pairs every element,
    range_proof.go:396-404)."""
    from ..crypto import batching as B

    # module-level LRU keyed by a digest of the A-table bytes: the TCP path
    # rebuilds RangeSig objects from the wire for every survey, so
    # instance-level caching alone would recompute the "one-time" table
    # each survey. Bounded + hashed keys so a long-lived node serving many
    # signature sets doesn't grow without limit.
    import hashlib

    def _key(sg):
        return hashlib.sha256(sg.A.tobytes()).digest()

    for sg in sigs:
        if sg.gt is None:
            hit = _GT_TABLE_CACHE.pop(_key(sg), None)
            if hit is not None:
                _GT_TABLE_CACHE[_key(sg)] = hit   # refresh LRU order
                sg.gt = hit

    # second chance behind the LRU: the persistent sig-table store (the
    # active crypto pool) — a fresh process against known signatures
    # reloads instead of re-pairing (same digest key as the LRU)
    store = _sig_store()
    if store is not None:
        for sg in sigs:
            if sg.gt is None:
                d = store.load_sig("gt", _key(sg).hex())
                if d is not None:
                    sg.gt = d["gt"]
                    _GT_TABLE_CACHE[_key(sg)] = sg.gt

    missing = [sg for sg in sigs if sg.gt is None]
    if missing:
        with _SIG_COUNT_LOCK:
            SIG_BUILD_COUNTS["gt_table"] += 1
        A_all = jnp.asarray(np.stack([sg.A for sg in missing]), dtype=jnp.uint32)
        qx, qy, _ = B.g2_normalize(A_all)
        bx = jnp.asarray(F.to_mont(jnp.asarray(
            F.from_int(params.G1_GEN[0]), dtype=jnp.uint32), FP), dtype=jnp.uint32)
        by = jnp.asarray(F.to_mont(jnp.asarray(
            F.from_int(params.G1_GEN[1]), dtype=jnp.uint32), FP), dtype=jnp.uint32)
        gt = np.asarray(B.pair(bx, by, qx, qy))
        for i, sg in enumerate(missing):
            sg.gt = gt[i]
            _GT_TABLE_CACHE[_key(sg)] = gt[i]
            if store is not None:
                store.save_sig("gt", _key(sg).hex(), gt=np.asarray(gt[i]))
        while len(_GT_TABLE_CACHE) > _GT_TABLE_CACHE_MAX:
            _GT_TABLE_CACHE.pop(next(iter(_GT_TABLE_CACHE)))
    return jnp.asarray(np.stack([sg.gt for sg in sigs]), dtype=jnp.uint32)


_GT_TABLE_CACHE: dict = {}
_GT_TABLE_CACHE_MAX = 32

_GT_POW_TABLE_CACHE: dict = {}
_GT_POW_TABLE_MAX = 4           # ~38 MB each at ns=3, u=16

# Builder-invocation counters: bumped only by REAL builds (the pairing
# batch / the ~10 s host pow-table loop), never by LRU or store hits.
# The restart test (tests/test_pool.py) asserts they stay flat when a
# fresh process reloads from the persistent sig-table store.
SIG_BUILD_COUNTS = {"gt_table": 0, "pow_table": 0}
# Verify workers build sig tables concurrently; dict += is read-modify-
# write, so the counters are bumped under a named lock.
_SIG_COUNT_LOCK = named_lock("sig_count_lock")


def _sig_store():
    """The persistent sig-table store, if a crypto pool is active
    (content-addressed by A-table digest — safe to share process-wide)."""
    from .. import pool as pool_mod

    return pool_mod.active_pool()


def sig_gt_pow_tables(sigs: list["RangeSig"]) -> np.ndarray:
    """(ns*u, 64, 16, 6, 2, 16): 4-bit window tables of every digit-signature
    GT base gtA[i][k] = e(B, A_i[k]), flattened base-major (i*u + k).

    With these, creation's dominant kernel — gtA[i][phi]^(-s v) over every
    digit — becomes a gather + two mulreduce8 passes (63 GT muls, ZERO
    squarings), vs ~258 squarings + 86 muls for the windowed ladder. The
    build runs on the HOST oracle (~10 s for ns=3, u=16) once per signature
    set and is LRU-cached by the A-table digest, so every survey against
    the same signatures reuses it (same pattern as sig_gt_table)."""
    import hashlib

    from ..crypto import host_oracle as ho

    key = hashlib.sha256(b"".join(sg.A.tobytes() for sg in sigs)).digest()
    hit = _GT_POW_TABLE_CACHE.pop(key, None)
    if hit is not None:
        _GT_POW_TABLE_CACHE[key] = hit          # refresh LRU order
        return hit

    store = _sig_store()
    if store is not None:
        d = store.load_sig("pow", key.hex())
        if d is not None:
            T = d["T"]
            _GT_POW_TABLE_CACHE[key] = T
            while len(_GT_POW_TABLE_CACHE) > _GT_POW_TABLE_MAX:
                _GT_POW_TABLE_CACHE.pop(next(iter(_GT_POW_TABLE_CACHE)))
            return T

    with _SIG_COUNT_LOCK:
        SIG_BUILD_COUNTS["pow_table"] += 1
    gtA = np.asarray(sig_gt_table(sigs))        # (ns, u, 6, 2, 16)
    ns, u = gtA.shape[0], gtA.shape[1]
    T = np.empty((ns * u, 64, 16, 6, 2, 16), np.uint32)
    for b in range(ns * u):
        cur = ho._fp12_to_ref(gtA[b // u, b % u])
        for w in range(64):
            row = refimpl.FP12_ONE
            T[b, w, 0] = ho._fp12_from_ref(row)
            for j in range(1, 16):
                row = refimpl.fp12_mul(row, cur)
                T[b, w, j] = ho._fp12_from_ref(row)
            for _ in range(4):
                cur = refimpl.fp12_sq(cur)
    _GT_POW_TABLE_CACHE[key] = T                # host numpy (tracer safety)
    if store is not None:
        store.save_sig("pow", key.hex(), T=T)
    while len(_GT_POW_TABLE_CACHE) > _GT_POW_TABLE_MAX:
        _GT_POW_TABLE_CACHE.pop(next(iter(_GT_POW_TABLE_CACHE)))
    return T


_GT_POW_TABLE_DEV: dict = {}


def _sig_gt_pow_tables_dev(sigs: list["RangeSig"]) -> jnp.ndarray:
    """Device copy of sig_gt_pow_tables, memoized by the same digest so the
    ~38 MB table is uploaded ONCE per signature set, not per creation call.
    Safe to cache: created eagerly (outside any trace), so it is a concrete
    Array, not a tracer."""
    import hashlib

    key = hashlib.sha256(b"".join(sg.A.tobytes() for sg in sigs)).digest()
    dev = _GT_POW_TABLE_DEV.get(key)
    if dev is None:
        dev = jnp.asarray(sig_gt_pow_tables(sigs), dtype=jnp.uint32)
        _GT_POW_TABLE_DEV[key] = dev
        while len(_GT_POW_TABLE_DEV) > _GT_POW_TABLE_MAX:
            _GT_POW_TABLE_DEV.pop(next(iter(_GT_POW_TABLE_DEV)))
    return dev


_GT_POW_MULTI = None


def _gt_pow_multi(tables, base_idx, k):
    """Bucketed gt_pow_fixed_multi (TPU path only — callers gate)."""
    from ..crypto import batching as B
    from ..crypto import pallas_pairing as pp

    global _GT_POW_MULTI
    if _GT_POW_MULTI is None:
        _GT_POW_MULTI = B.bucketed(pp.gt_pow_fixed_multi, (-1, 0, 1), 3,
                                   min_bucket=32, max_bucket=2048,
                                   name="gt_pow_fixed_multi")
    return _GT_POW_MULTI(tables, base_idx, k)


def init_range_sig(u: int, rng: np.random.Generator) -> RangeSig:
    """BB signatures A[k] = (x+k)^{-1}·B2, k in [0, u)
    (reference InitRangeProofSignature, range_proof.go:270-288)."""
    x, pub = eg.keygen(rng)
    pts = []
    for k in range(u):
        inv = pow((x + k) % params.N, params.N - 2, params.N)
        pts.append(G2.from_ref(refimpl.g2_mul(refimpl.G2, inv)))
    return RangeSig(secret=x, public=pub, A=np.stack(pts))


def to_base(n, b: int, l: int) -> np.ndarray:
    """Base-b digits, little-endian, padded to l (reference ToBase :584)."""
    n = np.asarray(n, dtype=np.int64)
    digits = np.zeros(n.shape + (l,), dtype=np.int32)
    cur = n.copy()
    for j in range(l):
        digits[..., j] = cur % b
        cur //= b
    return digits


# ---------------------------------------------------------------------------
# Proof container
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RangeProofBatch:
    """Proofs for V values against ns servers, base u, l digits.

    Mirrors RangeProofData (range_proof.go:32-39) with the value axis
    batched: Challenge->challenge, Zr->zr, D->d, Zphi->zphi, Zv->zv, V->v_pts,
    A->a.
    """

    commit: jnp.ndarray      # (V, 2, 3, 16) the ciphertexts themselves
    challenge: jnp.ndarray   # (V, 16)
    zr: jnp.ndarray          # (V, 16)
    d: jnp.ndarray           # (V, 3, 16)
    zphi: jnp.ndarray        # (V, l, 16)
    zv: jnp.ndarray          # (ns, V, l, 16)
    v_pts: jnp.ndarray       # (ns, V, l, 3, 2, 16)
    a: jnp.ndarray           # (ns, V, l, 6, 2, 16)
    u: int
    l: int
    # canonical-byte cache for the Fiat-Shamir transcript + serialization:
    # {'commit': (V,128), 'd': (V,64), 'v': (ns,V,l,128), 'a': (ns,V,l,384)}
    # uint8 numpy. Filled at creation (the bytes ARE the wire format) and at
    # from_bytes (the received wire bytes) so neither side pays a second
    # normalize/from_mont device pass to re-derive them. Hashing the wire
    # bytes is the standard FS practice (bind the message as transmitted):
    # decode(bytes) -> point is deterministic, so binding the bytes binds
    # the commitments at least as strongly as re-encoding would.
    # INVARIANT: when set, `wire` MUST be the canonical encoding of the
    # tensors above. create_range_proofs and from_bytes maintain this; any
    # code building a MODIFIED batch (e.g. dataclasses.replace in tests)
    # must pass wire=None so verification re-derives the bytes — a stale
    # cache would make the challenge binding vacuous for that object (the
    # wire attack surface itself cannot diverge: from_bytes decodes tensors
    # and cache from the same buffer).
    wire: Optional[dict] = None

    @property
    def n_values(self) -> int:
        return int(self.commit.shape[0])

    @property
    def n_servers(self) -> int:
        return int(self.zv.shape[0])

    def wire_bytes(self) -> dict:
        """The canonical commitment bytes (compute-if-missing)."""
        if self.wire is None:
            self.wire = _range_wire_dict(self.commit, self.d, self.v_pts,
                                         self.a)
        return self.wire

    def to_bytes(self) -> bytes:
        """Canonical serialization (RangeProof.ToBytes, :92-146)."""
        head = np.asarray([self.u, self.l, self.n_values, self.n_servers],
                          dtype="<i8").tobytes()
        w = self.wire_bytes()
        parts = [
            w["commit"], enc.scalar_bytes(self.challenge),
            enc.scalar_bytes(self.zr), w["d"],
            enc.scalar_bytes(self.zphi), enc.scalar_bytes(self.zv),
            w["v"], w["a"],
        ]
        return head + b"".join(np.ascontiguousarray(p).tobytes()
                               for p in parts)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "RangeProofBatch":
        u, l, V, ns = np.frombuffer(buf[:32], dtype="<i8")
        u, l, V, ns = int(u), int(l), int(V), int(ns)
        off = 32

        def take(shape, nbytes):
            nonlocal off
            flat = np.frombuffer(buf[off:off + nbytes], dtype=np.uint8)
            off += nbytes
            return flat.reshape(shape)

        commit_b = take((V, 2, 64), V * 128)
        commit = _g1_from_bytes(commit_b).reshape(V, 2, 3, params.NUM_LIMBS)
        challenge = enc.bytes_to_limbs(take((V, 32), V * 32))
        zr = enc.bytes_to_limbs(take((V, 32), V * 32))
        d_b = take((V, 64), V * 64)
        d = _g1_from_bytes(d_b)
        zphi = enc.bytes_to_limbs(take((V, l, 32), V * l * 32))
        zv = enc.bytes_to_limbs(take((ns, V, l, 32), ns * V * l * 32))
        v_b = take((ns, V, l, 128), ns * V * l * 128)
        v_pts = _g2_from_bytes(v_b)
        a_b = take((ns, V, l, 384), ns * V * l * 384)
        a = _gt_from_bytes(a_b)
        wire = {"commit": commit_b.reshape(V, 128).copy(), "d": d_b.copy(),
                "v": v_b.copy(), "a": a_b.copy()}
        return cls(jnp.asarray(commit, dtype=jnp.uint32), jnp.asarray(challenge, dtype=jnp.uint32),
                   jnp.asarray(zr, dtype=jnp.uint32), jnp.asarray(d, dtype=jnp.uint32), jnp.asarray(zphi, dtype=jnp.uint32),
                   jnp.asarray(zv, dtype=jnp.uint32), jnp.asarray(v_pts, dtype=jnp.uint32), jnp.asarray(a, dtype=jnp.uint32), u, l,
                   wire=wire)


def _g1_from_bytes(b: np.ndarray) -> np.ndarray:
    """(..., 64) canonical bytes -> (..., 3, 16) Jacobian Montgomery."""
    from ..crypto import batching as B

    x = enc.bytes_to_limbs(b[..., :32])
    y = enc.bytes_to_limbs(b[..., 32:])
    inf = np.all(b == 0, axis=-1)
    xm = np.asarray(B.to_mont_p(jnp.asarray(x, dtype=jnp.uint32)))
    ym = np.asarray(B.to_mont_p(jnp.asarray(y, dtype=jnp.uint32)))
    one = np.broadcast_to(np.asarray(FP.one_mont), xm.shape).copy()
    one[inf] = 0
    ym = ym.copy()
    ym[inf] = np.asarray(FP.one_mont)  # match infinity() convention (z=0)
    xm = xm.copy()
    xm[inf] = np.asarray(FP.one_mont)
    return np.stack([xm, ym, one], axis=-2)


def _g2_from_bytes(b: np.ndarray) -> np.ndarray:
    """(..., 128) -> (..., 3, 2, 16) Jacobian Montgomery."""
    from ..crypto import batching as B

    comps = [enc.bytes_to_limbs(b[..., 32 * k:32 * (k + 1)]) for k in range(4)]
    inf = np.all(b == 0, axis=-1)
    xm = np.stack([np.asarray(B.to_mont_p(jnp.asarray(c, dtype=jnp.uint32)))
                   for c in comps[:2]], axis=-2)
    ym = np.stack([np.asarray(B.to_mont_p(jnp.asarray(c, dtype=jnp.uint32)))
                   for c in comps[2:]], axis=-2)
    zm = np.zeros_like(xm)
    zm[..., 0, :] = np.asarray(FP.one_mont)
    zm[inf] = 0
    # infinity convention from g2.from_ref: x=y=(1,0) Montgomery, z=0
    one_fp2 = np.zeros_like(xm[inf])
    if one_fp2.size:
        one_fp2[..., 0, :] = np.asarray(FP.one_mont)
        xm[inf] = one_fp2
        ym[inf] = one_fp2
    return np.stack([xm, ym, zm], axis=-3)


def _gt_from_bytes(b: np.ndarray) -> np.ndarray:
    """(..., 384) -> (..., 6, 2, 16) Montgomery."""
    from ..crypto import batching as B

    limbs = enc.bytes_to_limbs(b.reshape(b.shape[:-1] + (12, 32)))
    return np.asarray(B.to_mont_p(jnp.asarray(limbs, dtype=jnp.uint32))).reshape(
        b.shape[:-1] + (6, 2, params.NUM_LIMBS))


# ---------------------------------------------------------------------------
# Shared constants
# ---------------------------------------------------------------------------

_GT_B = None


def gt_base():
    """e(B, B2) — the pairing of both generators, device constant.

    Memoized as HOST numpy (a jnp value cached from inside a jit trace
    would be a leaked tracer — see pairing._twist_frob_consts)."""
    global _GT_B
    if _GT_B is None:
        _GT_B = np.asarray(F12.from_ref(refimpl.pair(refimpl.G1,
                                                     refimpl.G2)))
    return jnp.asarray(_GT_B, dtype=jnp.uint32)


_GT_B_TABLE = None
_GT_POW_GTB = None


def gt_base_table() -> jnp.ndarray:
    """4-bit window table of gtB powers: T[w][j] = gtB^(j * 16^w),
    (64, 16, 6, 2, 16). One-time host build (~1.2k oracle Fp12 muls),
    cached for the process; lets every gtB^k collapse to 63 GT muls
    (pallas_pairing.gt_pow_fixed) with no squarings."""
    global _GT_B_TABLE
    if _GT_B_TABLE is None:
        base = refimpl.pair(refimpl.G1, refimpl.G2)
        T = np.empty((64, 16, 6, 2, 16), np.uint32)
        cur = base
        for w in range(64):
            row = refimpl.FP12_ONE
            T[w, 0] = F12.from_ref(row)
            for j in range(1, 16):
                row = refimpl.fp12_mul(row, cur)
                T[w, j] = F12.from_ref(row)
            for _ in range(4):
                cur = refimpl.fp12_mul(cur, cur)
        _GT_B_TABLE = T  # host numpy; converted per use (tracer safety)
    return jnp.asarray(_GT_B_TABLE, dtype=jnp.uint32)


def gt_pow_gtb(k):
    """gtB^k batched over any leading shape of k (..., 16) plain limbs."""
    from ..crypto import batching as B
    from ..crypto import pallas_ops as po
    from ..crypto import pallas_pairing as pp

    if not po.available():
        return B.gt_pow(gt_base(), k)
    global _GT_POW_GTB
    if _GT_POW_GTB is None:
        tab = gt_base_table()
        _GT_POW_GTB = B.bucketed(
            lambda kk: pp.gt_pow_fixed(tab, kk), (1,), 3, min_bucket=32,
            max_bucket=2048, name="gt_pow_gtb")
    return _GT_POW_GTB(k)


def aot_register_bucketed(build_gtb_table: bool = False) -> None:
    """Force-build the LAZY bucketed wrappers so BUCKETED_OPS enumerates
    them (the precompile registry, drynx_tpu/compilecache). Both wrappers
    are memoized module globals, so the runtime paths above reuse the
    exact objects registered here — no duplicate traces.

    build_gtb_table: also build gt_pow_gtb, whose closure captures the
    gtB window table (a ~1.2k-mul HOST build) — only worth paying when
    the Pallas path will actually dispatch it (it is TPU-only)."""
    from ..crypto import batching as B
    from ..crypto import pallas_pairing as pp

    global _GT_POW_MULTI, _GT_POW_GTB
    if _GT_POW_MULTI is None:
        _GT_POW_MULTI = B.bucketed(pp.gt_pow_fixed_multi, (-1, 0, 1), 3,
                                   min_bucket=32, max_bucket=2048,
                                   name="gt_pow_fixed_multi")
    if build_gtb_table and _GT_POW_GTB is None:
        tab = gt_base_table()
        _GT_POW_GTB = B.bucketed(
            lambda kk: pp.gt_pow_fixed(tab, kk), (1,), 3, min_bucket=32,
            max_bucket=2048, name="gt_pow_gtb")


def prewarm_sig_tables(sigs: list["RangeSig"],
                       pow_tables: bool | None = None) -> None:
    """Build the per-signature GT tables OUTSIDE the timed survey path.

    sig_gt_table (one pairing batch) and — on the Pallas path —
    sig_gt_pow_tables (~10 s host build at ns=3, u=16) used to be built
    lazily inside create_range_proofs, landing their one-time cost in the
    middle of the timed proofs window. Both are LRU-cached by the A-table
    digest, so calling this at signature setup (LocalCluster
    ensure_range_sigs) makes the in-survey lookups pure cache hits."""
    from ..crypto import pallas_ops as po

    sig_gt_table(sigs)
    if pow_tables is None:
        pow_tables = po.available()
    if pow_tables:
        _sig_gt_pow_tables_dev(sigs)


def _upow_mont(u: int, l: int) -> jnp.ndarray:
    """[u^j mod n for j<l] in Montgomery form, (l, 16)."""
    rows = [F.from_int((pow(u, j, params.N) * params.R) % params.N)
            for j in range(l)]
    return jnp.asarray(np.stack(rows), dtype=jnp.uint32)


def _weighted_sum_mod_n(s_plain, upow_m):
    """Σ_j u^j · s_j mod n. s_plain (..., l, 16), upow_m (l, 16) Montgomery."""
    from ..crypto import batching as B

    prod = B.fn_mont_mul(s_plain, upow_m)  # plain·mont = plain product
    acc = prod[..., 0, :]
    for j in range(1, prod.shape[-2]):
        acc = B.fn_add(acc, prod[..., j, :])
    return acc


_BASE_B = None


def _g1_gen_bytes() -> np.ndarray:
    """Canonical bytes of the G1 generator — pure host, memoized (this used
    to be a device normalize dispatch on EVERY challenge computation)."""
    global _BASE_B
    if _BASE_B is None:
        _BASE_B = _g1_bytes_host(refimpl.G1)
    return _BASE_B


def _range_wire_dict(commit, d, v_pts, a) -> dict:
    """THE one definition of the canonical commitment encoding — creation,
    wire_bytes and the device-tensor challenge path all call this so the
    Fiat-Shamir transcript can never desynchronize between them."""
    return {"commit": enc.ct_bytes(jnp.asarray(commit, dtype=jnp.uint32)),
            "d": enc.g1_bytes(jnp.asarray(d, dtype=jnp.uint32)),
            "v": enc.g2_bytes(jnp.asarray(v_pts, dtype=jnp.uint32)),
            "a": enc.gt_bytes(jnp.asarray(a, dtype=jnp.uint32))}


def _g1_bytes_host(pt) -> np.ndarray:
    """Canonical 64-byte encoding of a host affine int pair (no device);
    None (infinity) encodes all-zero, matching enc.g1_bytes."""
    if pt is None:
        return np.zeros(64, dtype=np.uint8)
    x, y = int(pt[0]), int(pt[1])
    return np.frombuffer(x.to_bytes(32, "big") + y.to_bytes(32, "big"),
                         dtype=np.uint8)


def challenge_from_wire(wire: dict, sum_y_bytes: np.ndarray,
                        u: int, l: int) -> np.ndarray:
    """Per-value Fiat-Shamir challenge from the CANONICAL WIRE BYTES:

      c = sha3-512(B ‖ C2 ‖ ΣY ‖ u ‖ l ‖ D ‖ V_pts[·,v,·] ‖ a[·,v,·])

    The reference hashes only (B ‖ commit ‖ ΣY) (range_proof.go:348-375),
    which lets a forger derive D and a AFTER fixing c (see module
    docstring). Binding D, the blinded signatures V and the pairing
    commitments a makes the transcript a proper sigma-protocol
    Fiat-Shamir transform. Pure host work: byte slicing + sha3.
    """
    # explicit little-endian so the transcript is canonical across hosts
    # (all other hashed inputs go through explicit byte encoders)
    ul = np.frombuffer(np.asarray([u, l], dtype="<i8").tobytes(),
                       dtype=np.uint8)
    V = wire["commit"].shape[0]
    c2 = wire["commit"].reshape(V, 128)[:, 64:]              # (V, 64)
    d_b = wire["d"]                                          # (V, 64)
    v_b = np.moveaxis(wire["v"], 0, 1)
    v_b = np.ascontiguousarray(v_b).reshape(V, -1)           # (V, ns*l*128)
    a_b = np.moveaxis(wire["a"], 0, 1)
    a_b = np.ascontiguousarray(a_b).reshape(V, -1)           # (V, ns*l*384)
    return enc.hash_to_scalar(_g1_gen_bytes(), c2, sum_y_bytes, ul, d_b,
                              v_b, a_b, batch_shape=(V,))


def proof_challenge(cts, sum_y_bytes: np.ndarray, d, v_pts, a,
                    u: int, l: int) -> np.ndarray:
    """Challenge from DEVICE tensors: canonicalizes to bytes, then hashes
    (see challenge_from_wire). Kept for callers without a byte cache."""
    return challenge_from_wire(_range_wire_dict(cts, d, v_pts, a),
                               sum_y_bytes, u, l)


def sum_publics_bytes(sigs: list[RangeSig]) -> np.ndarray:
    acc = None
    for s in sigs:
        acc = refimpl.g1_add(acc, s.public)
    return _g1_bytes_host(acc)


# ---------------------------------------------------------------------------
# Creation
# ---------------------------------------------------------------------------


def _commit_kernel(digits, s, t, m, v, A_tab, ca_tbl, u: int, l: int,
                   gtA=None, gtA_pow=None):
    """Commitment stage of proof creation (independent of the challenge),
    built from bucketed primitives (each compiles once per size bucket —
    see crypto/batching.py).

    digits (V, l) int32; s, t, m (V, l, 16); v (ns, V, l, 16);
    A_tab (ns, u, 3, 2, 16); ca_tbl: collective-key fixed-base table.
    Returns D (V, 3, 16), m_tot (V, 16), V_pts, a.
    """
    from ..crypto import batching as B
    from ..crypto import pallas_ops as po

    # On the (tunneled) TPU backend, enqueueing this whole chain of large
    # programs asynchronously has crashed the worker ("kernel fault"); the
    # same ops run reliably with a sync between stages. No-op elsewhere.
    sync = jax.block_until_ready if po.available() else (lambda x: x)

    base_tbl = eg.BASE_TABLE.table
    upow_m = _upow_mont(u, l)

    # D = (Σ u^j s_j)·B + (Σ m_j)·P
    w = _weighted_sum_mod_n(s, upow_m)
    m_tot = m[..., 0, :]
    for j in range(1, l):
        m_tot = B.fn_add(m_tot, m[..., j, :])
    D = B.g1_add(B.fixed_base_mul(base_tbl, w),
                 B.fixed_base_mul(ca_tbl, m_tot))
    sync(D)

    # V_ij = v_ij · A_i[φ_j]  — gather digit signatures, blind in G2
    A_sel = A_tab[:, digits]                               # (ns, V, l, 3, 2, 16)
    V_pts = B.g2_scalar_mul(A_sel, v)
    sync(V_pts)

    # a_ij = e(−s_j·B, V_ij) · gtB^{t_j}. With the per-signature GT table
    # (sig_gt_table) the pairing collapses to gtA[i][φ_j]^(−s_j·v_ij):
    # e(−sB, vA[φ]) = e(B, A[φ])^(−sv) by bilinearity. With per-base window
    # tables (sig_gt_pow_tables) the pow itself collapses to a gather + 63
    # GT muls, no squarings (gt_pow_fixed_multi).
    if gtA_pow is not None:
        ns_srv = v.shape[0]
        sv = B.fn_mul_plain(s, v)                          # (ns, V, l, 16)
        base_idx = (jnp.arange(ns_srv, dtype=jnp.int32)[:, None, None] * u
                    + digits[None].astype(jnp.int32))      # (ns, V, l)
        gt1 = _gt_pow_multi(gtA_pow, base_idx, B.fn_neg(sv))
    elif gtA is not None:
        gt_sel = gtA[:, digits]                            # (ns, V, l, 6,2,16)
        sv = B.fn_mul_plain(s, v)                          # (ns, V, l, 16)
        gt1 = B.gt_pow(gt_sel, B.fn_neg(sv))
    else:
        neg_s = B.fn_neg(s)
        nsB = B.fixed_base_mul(base_tbl, neg_s)            # (V, l, 3, 16)
        px, py, _ = B.g1_normalize(nsB)
        qx, qy, _ = B.g2_normalize(V_pts)
        sync(qx)
        gt1 = B.pair(px, py, qx, qy)                       # (ns, V, l, 6,2,16)
    sync(gt1)
    gt2 = gt_pow_gtb(t)                                    # (V, l, 6, 2, 16)
    a = B.gt_mul(gt1, gt2)

    return D, m_tot, V_pts, a


def _commit_kernel_sharded(digits, s, t, m, v, A_tab, ca_tbl, u: int, l: int,
                           gtA=None, gtA_pow=None, n_shards: int | None = None):
    """Mesh-sharded commitment stage: the value axis V is the `dp` axis
    (create_range_proof_lists_batched flattens n_dps*V onto it), so each DP
    shard builds its slice's a_ij GT-table exponentiations locally through
    the SAME per-shard `_commit_kernel` programs, and the commitments are
    gathered once per batch before the Fiat-Shamir hash.

    Bit-identical to one `_commit_kernel` call: proofs are per-value
    independent, the bucketed programs pad inactive lanes away, and the
    challenge hash runs over the gathered (concatenated) commitments —
    tests/test_proof_mesh.py asserts byte-equal payloads."""
    from ..parallel import proof_plane as plane

    if n_shards is None:
        n_shards = plane.n_shards()
    V = int(digits.shape[0])
    slices = plane.shard_slices(V, n_shards)
    if len(slices) <= 1:
        return _commit_kernel(digits, s, t, m, v, A_tab, ca_tbl, u, l,
                              gtA=gtA, gtA_pow=gtA_pow)

    def stage_commit(i, a, b):
        # only the per-shard slices are committed to shard i's device; the
        # shared tables (base/ca/A/gtA) stay uncommitted and follow the
        # committed operands onto each shard's device. The slices are
        # one-shot, so their buffers are donated to the upload; staging
        # overlaps the previous shard's compute (dispatch_shards).
        return plane.put_shard(
            (digits[a:b], s[a:b], t[a:b], m[a:b], v[:, a:b]), i,
            donate=True)

    def shard_commit(i, sd, ss, st, sm, sv):
        return _commit_kernel(sd, ss, st, sm, sv, A_tab, ca_tbl, u, l,
                              gtA=gtA, gtA_pow=gtA_pow)

    parts = plane.dispatch_shards(
        "CreateShard", shard_commit, [(a, b) for (a, b) in slices],
        prefetch=stage_commit)
    D = jnp.concatenate([p[0] for p in parts], axis=0)
    m_tot = jnp.concatenate([p[1] for p in parts], axis=0)
    V_pts = jnp.concatenate([p[2] for p in parts], axis=1)
    a_out = jnp.concatenate([p[3] for p in parts], axis=1)
    return D, m_tot, V_pts, a_out


def _response_kernel(digits, c, rs, s, t, m_tot, v):
    """Response stage: given the bound challenge c, compute
    Zphi_j = s_j − c·φ_j, Zr = Σm − c·r, Zv_ij = t_j − c·v_ij."""
    from ..crypto import batching as B

    phi = eg.int_to_scalar(digits.astype(jnp.int64))      # (V, l, 16)
    c_l = c[..., None, :]
    zphi = B.fn_sub(s, B.fn_mul_plain(c_l, phi))
    zr = B.fn_sub(m_tot, B.fn_mul_plain(c, rs))
    zv = B.fn_sub(t, B.fn_mul_plain(c_l, v))
    return zphi, zr, zv


def create_range_proofs(key, secrets, rs, cts, sigs: list[RangeSig],
                        u: int, l: int, ca_pub_table,
                        use_gt_table: bool = True,
                        shard: bool | None = None,
                        tile: int | None = None) -> RangeProofBatch:
    """Create proofs for V values at once.

    secrets: int64 (V,) plaintexts; rs: (V, 16) encryption blinding scalars;
    cts: (V, 2, 3, 16) their ciphertexts under the collective key;
    ca_pub_table: fixed-base table of the collective key P.
    (Reference CreatePredicateRangeProofForAllServ, range_proof.go:320-407.)

    use_gt_table: compute a_ij via the cached e(B, A[k]) table (one GT
    exponentiation per digit) instead of a pairing per digit — u*ns one-time
    pairings amortized over every proof against these signatures.

    shard: split the commitment stage over the proof-plane devices along
    the value (`dp`) axis; None = shard iff the plane is enabled
    (parallel/proof_plane.py — the default on a >= 2-device mesh).
    Transcripts are bit-identical either way.

    tile: cap every commit-stage dispatch at `tile` values — the
    bucket-tile path for grid-encoded surveys (encoding/tiles.py), where
    V reaches the reference's 1k..1M bucket axis and a single dispatch
    would materialize the whole (ns, V, l, 6, 2, 16) GT tensor at once.
    None = auto (tiles above tiles.TILE_THRESHOLD, the default at
    scale); 0 = never tile. The per-value randomness is drawn in the
    SAME four full-size calls either way and the Fiat-Shamir challenge
    is hashed per value from the gathered commitments, so the tiled
    transcripts are byte-identical to the monolithic path.
    """
    from ..encoding import tiles as _tiles

    V = int(np.asarray(secrets).shape[0])
    ns = len(sigs)
    digits = jnp.asarray(to_base(np.asarray(secrets), u, l), dtype=jnp.int32)  # (V, l)

    ks = jax.random.split(key, 4)
    s = eg.random_scalars(ks[0], (V, l))
    t = eg.random_scalars(ks[1], (V, l))
    m = eg.random_scalars(ks[2], (V, l))
    v = eg.random_scalars(ks[3], (ns, V, l))
    A_tab = jnp.asarray(np.stack([sg.A for sg in sigs]), dtype=jnp.uint32)   # (ns, u, 3, 2, 16)
    gtA = sig_gt_table(sigs) if use_gt_table else None
    # per-base window tables make the digit pow squaring-free on the Mosaic
    # path; the CPU/oracle path keeps the direct pow (no table build cost)
    from ..crypto import pallas_ops as po

    gtA_pow = (_sig_gt_pow_tables_dev(sigs)
               if use_gt_table and po.available() else None)

    # commit -> Fiat-Shamir (binds D, V_pts, a) -> respond. The canonical
    # commitment bytes are computed ONCE here and cached on the batch: they
    # are both the hash input and the wire format (to_bytes reuses them).
    from ..parallel import proof_plane as plane

    if shard is None:
        shard = plane.enabled()
    if tile is None:
        tile = _tiles.auto_tile(V)
    # shard count = max(plane policy, tile chunking): each per-tile
    # dispatch is bounded by the tile AND lands on a plane device
    n_shards = max(plane.n_shards() if shard else 1,
                   _tiles.proof_tile_shards(V, tile) if tile else 1)
    if n_shards > 1:
        D, m_tot, V_pts, a = _commit_kernel_sharded(
            digits, s, t, m, v, A_tab, ca_pub_table, u, l, gtA=gtA,
            gtA_pow=gtA_pow, n_shards=n_shards)
    else:
        D, m_tot, V_pts, a = _commit_kernel(
            digits, s, t, m, v, A_tab, ca_pub_table, u, l, gtA=gtA,
            gtA_pow=gtA_pow)
    wire = _range_wire_dict(cts, D, V_pts, a)
    c = jnp.asarray(challenge_from_wire(wire, sum_publics_bytes(sigs), u, l), dtype=jnp.uint32)
    zphi, zr, zv = _response_kernel(digits, c, jnp.asarray(rs, dtype=jnp.uint32), s, t,
                                    m_tot, v)
    return RangeProofBatch(commit=jnp.asarray(cts, dtype=jnp.uint32), challenge=c, zr=zr, d=D,
                           zphi=zphi, zv=zv, v_pts=V_pts, a=a, u=u, l=l,
                           wire=wire)


# ---------------------------------------------------------------------------
# Verification
# ---------------------------------------------------------------------------


def _verify_kernel(commit, c, zr, d, zphi, zv, v_pts, a, ys, ca_tbl,
                   u: int, l: int):
    """Batched verification. ys: (ns, 3, 16) server publics. Returns (V,)."""
    from ..crypto import batching as B
    from ..crypto import pallas_ops as po

    sync = jax.block_until_ready if po.available() else (lambda x: x)

    base_tbl = eg.BASE_TABLE.table
    upow_m = _upow_mont(u, l)

    # Dp = c·C2 + Zr·P + (Σ u^j Zphi_j)·B  ==  D   (range_proof.go:519-529)
    C2 = commit[..., 1, :, :]
    wz = _weighted_sum_mod_n(zphi, upow_m)
    Dp = B.g1_add(B.g1_scalar_mul(C2, c),
                  B.g1_add(B.fixed_base_mul(ca_tbl, zr),
                           B.fixed_base_mul(base_tbl, wz)))
    d_ok = B.g1_eq(Dp, d)                                  # (V,)
    sync(d_ok)

    # a'_ij = e(c·y_i − Zphi_j·B, V_ij) · gtB^{Zv_ij}  (:538-546)
    cy = B.g1_scalar_mul(ys[:, None, :, :], c[None, :, :])  # (ns, V, 3, 16)
    nzphiB = B.fixed_base_mul(base_tbl, B.fn_neg(zphi))    # (V, l, 3, 16)
    g1arg = B.g1_add(cy[:, :, None, :, :], nzphiB[None])   # (ns, V, l, 3, 16)
    px, py, _ = B.g1_normalize(g1arg)
    qx, qy, _ = B.g2_normalize(v_pts)
    sync(qx)
    gt1 = B.pair(px, py, qx, qy)
    sync(gt1)
    ap = B.gt_mul(gt1, gt_pow_gtb(zv))
    a_ok = jnp.all(F12.eq(ap, a), axis=(0, -1))            # (V,)

    return d_ok & a_ok


def verify_range_proofs(proof: RangeProofBatch, sigs_pub, ca_pub_table,
                        check_challenge: bool = True) -> np.ndarray:
    """Verify a proof batch against server publics (host affine int pairs).

    Returns bool (V,). (Reference RangeProofVerification :504-565; unlike
    it — which trusts the transmitted challenge — the recomputed
    Fiat-Shamir challenge over D ‖ V_pts ‖ a MUST match; this is the
    soundness-critical binding, see module docstring.)
    """
    ys = jnp.asarray(np.stack([C.from_ref(p) for p in sigs_pub]), dtype=jnp.uint32)
    ok = np.asarray(_verify_kernel(
        proof.commit, proof.challenge, proof.zr, proof.d, proof.zphi,
        proof.zv, proof.v_pts, proof.a, ys, ca_pub_table,
        proof.u, proof.l))
    if check_challenge:
        ok = ok & _challenge_ok(proof, sigs_pub)
    return ok


def _challenge_ok(proof: RangeProofBatch, sigs_pub) -> np.ndarray:
    """Recompute c = H(B ‖ C2 ‖ ΣY ‖ u ‖ l ‖ D ‖ V ‖ a) from the
    TRANSMITTED commitments and require equality with the transmitted
    challenge — a forger deriving D or a post-hoc changes c. Uses the
    wire-byte cache (pure host hashing; zero device work on the verifier)."""
    acc = None
    for p in sigs_pub:
        acc = refimpl.g1_add(acc, p)
    want = challenge_from_wire(proof.wire_bytes(), _g1_bytes_host(acc),
                               proof.u, proof.l)
    return np.all(np.asarray(proof.challenge) == want, axis=-1)


def verify_range_proofs_batch(proof: RangeProofBatch, sigs_pub, ca_pub_table,
                              check_challenge: bool = True,
                              rng: np.random.Generator | None = None) -> bool:
    """Single-verdict verification of a whole batch via a random linear
    combination in the exponent — ONE shared final exponentiation and ONE
    fixed-base gtB power for all ns*V*l digit proofs (vs one full reduced
    pairing + one 256-bit GT exponentiation each in the per-value path).

    Checks prod_ij [ e(r_ij*(c*y_i - Zphi_j*B), V_ij) * conj6(a_ij)^r_ij ]
           * gtB^(sum_ij r_ij*Zv_ij)  ==  1
    with verifier-secret 62-bit weights r_ij.

    Soundness (REQUIRES check_challenge=True — the service path always
    passes it): the Fiat-Shamir hash binds a (and D, V) BEFORE c is known,
    so the per-digit factor f_ij = e(c*y_i - Zphi_j*B, V_ij) *
    gtB^Zv_ij * conj6(a_ij) is fully determined by the transcript. For
    honest (cyclotomic, satisfying) a, conj6(a) = a^-1 and every f_ij = 1.
    A forged transcript has some f_ij != 1, and the check passes only if
    sum_ij r_ij * x_ij = 0 in the exponent lattice (x_ij = dlog of f_ij in
    the subgroup it generates): probability <= 2^-62 per independent r,
    unless f_ij has small order d (then 1/d). Two small-order routes are
    closed separately: (1) choosing a AFTER c (round-2 state) fails
    deterministically at the challenge recompute, since a is hashed into c
    (regression-tested by test_rlc_small_order_forgery_rejected); (2) a
    COMMIT-FIRST forger who sets a' = a_honest * eps BEFORE hashing, with
    eps a root of unity in GΦ12's cofactor subgroup (this curve's cofactor
    is divisible by 13 and 2749, so eps of order 13 exists), passes the
    challenge binding and the D equation and would survive the draw with
    probability 1/13 — that route is killed by rlc_prelude's order-n gate
    gt_order_ok (a^n == 1 via frob1(a) == a^(t-1)), which forces every
    wire a into the order-n subgroup where the only subgroup orders are 1
    and n (regression-tested by test_rlc_cofactor_forgery_rejected).

    The D-equation and Fiat-Shamir challenge are still checked per value
    (cheap G1 work). Returns one bool for the batch.
    """
    pre_ok, r_int, gtb_pow_s = rlc_prelude(
        proof, sigs_pub, ca_pub_table, rng=rng,
        check_challenge=check_challenge)
    if not pre_ok:
        return False  # D equation / challenge binding failed — deterministic

    total = rlc_total_single(proof, sigs_pub, r_int, gtb_pow_s)
    return bool(np.asarray(F12.eq(total, jnp.asarray(F12.one(), dtype=jnp.uint32))))


def rlc_total_single(proof: RangeProofBatch, sigs_pub, r_int, gtb_pow_s):
    """The RLC check's (6, 2, 16) GT total on ONE device — equals F12.one()
    iff the batch verifies under weights r_int. The pure single-device
    fallback of the proof plane: parallel/proof_mesh.rlc_total_shards
    computes the same total per-shard and MUST stay bit-identical to this
    (tests/test_proof_mesh.py asserts array equality under a shared
    weight draw)."""
    from ..crypto import batching as B
    from ..crypto import pallas_ops as po

    sync = jax.block_until_ready if po.available() else (lambda x: x)
    ys = jnp.asarray(np.stack([C.from_ref(p) for p in sigs_pub]), dtype=jnp.uint32)
    c, zphi = proof.challenge, proof.zphi
    base_tbl = eg.BASE_TABLE.table
    r = B.int_to_scalar(jnp.asarray(r_int, dtype=jnp.int64))               # (ns, V, l, 16)

    # r·(c·y_i − Zphi_j·B), then Miller only (final exp shared).
    # g1_scalar_mul64: the RLC weights are 62-bit, so the weighting ladder
    # runs 16 windows instead of 64
    cy = B.g1_scalar_mul(ys[:, None, :, :], c[None, :, :])
    nzphiB = B.fixed_base_mul(base_tbl, B.fn_neg(zphi))
    g1arg = B.g1_add(cy[:, :, None, :, :], nzphiB[None])  # (ns, V, l, 3, 16)
    g1arg_r = B.g1_scalar_mul64(g1arg, r)
    px, py, _ = B.g1_normalize(g1arg_r)
    qx, qy, _ = B.g2_normalize(proof.v_pts)
    sync(qx)
    m = B.miller(px, py, qx, qy)                          # (ns, V, l, 6,2,16)
    sync(m)
    ar = B.gt_pow64(F12.conj6(jnp.asarray(proof.a, dtype=jnp.uint32)), r)
    sync(ar)

    # final-exp ONLY the Miller product (the a^r factors are already in GT —
    # re-exponentiating them by h = (p^12-1)/n would scale their exponents
    # by h mod n != 1 and break the identity)
    fe = B.final_exp(B.gt_reduce_prod(
        m.reshape(-1, 6, 2, params.NUM_LIMBS))[None])
    Pa = B.gt_reduce_prod(ar.reshape(-1, 6, 2, params.NUM_LIMBS))

    # gtB^(Σ r·Zv) comes from the shared prelude (one fixed-base power)
    return B.gt_mul(B.gt_mul(fe, Pa[None]), gtb_pow_s[None])[0]


def rlc_prelude(proof: RangeProofBatch, sigs_pub, ca_pub_table,
                rng: np.random.Generator | None = None,
                check_challenge: bool = True, with_gtb_pow: bool = True):
    """The RLC verifiers' shared acceptance preamble — kept in ONE place so
    the single-device path (verify_range_proofs_batch) and the mesh-sharded
    path (parallel/proof_mesh.rlc_verify_sharded) cannot drift apart on the
    soundness-critical checks:

      * per-value D equation  D == c*C2 + Zr*P + (sum u^j Zphi_j)*B
      * binding Fiat-Shamir challenge recompute over D ‖ V ‖ a
      * GΦ12 membership of every wire-provided a (gt_membership_ok —
        required before the cyclotomic-squaring pow chains touch them)
      * order-n membership of every a (gt_order_ok, frob1(a) == a^(t-1)):
        GΦ12 alone leaves the cofactor subgroup open, and this curve's
        cofactor is divisible by 13 — a commit-first forger injecting a
        13th root of unity into a would otherwise survive the RLC draw
        with probability 1/13 (round-4 advisor finding)
      * verifier-secret 62-bit RLC weights r
      * [with_gtb_pow] gtB^(sum_ij r_ij*Zv_ij), the one fixed-base power

    Returns (pre_ok, r_int, gtb_pow_s) with gtb_pow_s None unless
    requested."""
    from ..crypto import batching as B

    base_tbl = eg.BASE_TABLE.table
    u, l = proof.u, proof.l
    ns, V = len(sigs_pub), proof.n_values
    upow_m = _upow_mont(u, l)

    C2 = jnp.asarray(proof.commit, dtype=jnp.uint32)[..., 1, :, :]
    wz = _weighted_sum_mod_n(proof.zphi, upow_m)
    Dp = B.g1_add(B.g1_scalar_mul(C2, proof.challenge),
                  B.g1_add(B.fixed_base_mul(ca_pub_table, proof.zr),
                           B.fixed_base_mul(base_tbl, wz)))
    ok = bool(np.all(np.asarray(B.g1_eq(Dp, proof.d))))
    if check_challenge:
        ok = ok and bool(np.all(_challenge_ok(proof, sigs_pub)))
    ok = ok and B.gt_membership_ok(proof.a) and B.gt_order_ok(proof.a)

    if rng is None:
        rng = np.random.default_rng(
            np.frombuffer(secrets.token_bytes(16), dtype=np.uint64))
    r_int = rng.integers(1, 1 << 62, size=(ns, V, l), dtype=np.int64)

    gtb_pow_s = None
    if with_gtb_pow:
        r = B.int_to_scalar(jnp.asarray(r_int, dtype=jnp.int64))
        rs_zv = B.fn_mul_plain(r, jnp.asarray(proof.zv, dtype=jnp.uint32)).reshape(
            -1, params.NUM_LIMBS)
        S = B.tree_reduce_add(rs_zv, B.fn_add, axis=0)
        gtb_pow_s = gt_pow_gtb(S[None])[0]
    return ok, r_int, gtb_pow_s




# ---------------------------------------------------------------------------
# Mixed-range proof lists (per-value (u, l) specs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RangeProofList:
    """Per-DP proof payload for an output vector with PER-VALUE range specs
    (reference creates/verifies each output with its own (u,l):
    lib/range/range_proof.go:320-407, lib/structs.go:446-533). Values sharing
    a spec are batched into one RangeProofBatch — the TPU grouping — with the
    output indices each batch covers. Indices whose spec is (0,0) carry no
    proof (reference: zero ranges mean 'unproved')."""

    n_values: int
    batches: list                      # [(int64 idx array, RangeProofBatch)]

    def to_bytes(self) -> bytes:
        head = np.asarray([self.n_values, len(self.batches)],
                          dtype="<i8").tobytes()
        parts = [head]
        for idx, pb in self.batches:
            blob = pb.to_bytes()
            idx = np.asarray(idx, dtype="<i8")
            parts.append(np.asarray([idx.size, len(blob)],
                                    dtype="<i8").tobytes())
            parts.append(idx.tobytes())
            parts.append(blob)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "RangeProofList":
        n_values, n_batches = np.frombuffer(buf[:16], dtype="<i8")
        off = 16
        batches = []
        for _ in range(int(n_batches)):
            n_idx, n_blob = np.frombuffer(buf[off:off + 16], dtype="<i8")
            off += 16
            idx = np.frombuffer(buf[off:off + 8 * int(n_idx)], dtype="<i8")
            off += 8 * int(n_idx)
            pb = RangeProofBatch.from_bytes(buf[off:off + int(n_blob)])
            off += int(n_blob)
            batches.append((idx.copy(), pb))
        return cls(n_values=int(n_values), batches=batches)


def group_ranges(ranges) -> dict:
    """{(u, l): [output indices]} for nonzero specs, insertion-ordered."""
    spec_to_idx: dict = {}
    for i, (u, l) in enumerate(ranges):
        if u == 0 and l == 0:
            continue
        spec_to_idx.setdefault((int(u), int(l)), []).append(i)
    return spec_to_idx


def create_range_proof_list(key, secrets, rs, cts, ranges,
                            sigs_by_u: dict, ca_pub_table,
                            tile: int | None = None) -> RangeProofList:
    """Create the per-DP mixed-range payload.

    ranges: [(u, l)] per output index; sigs_by_u: {u: [RangeSig per CN]}.
    tile: forwarded to create_range_proofs (None = auto bucket-tiling
    above the threshold — the grid-op scale path).
    """
    secrets = np.asarray(secrets)
    batches = []
    for (u, l), idx in group_ranges(ranges).items():
        key, sub = jax.random.split(key)
        ia = np.asarray(idx, dtype=np.int64)
        pb = create_range_proofs(
            sub, secrets[ia], jnp.asarray(rs, dtype=jnp.uint32)[ia], jnp.asarray(cts, dtype=jnp.uint32)[ia],
            sigs_by_u[u], u, l, ca_pub_table, tile=tile)
        batches.append((ia, pb))
    return RangeProofList(n_values=len(ranges), batches=batches)


def _slice_batch(pb: RangeProofBatch, sel: np.ndarray) -> RangeProofBatch:
    """Sub-batch along the value axis (proofs are per-value independent)."""
    wire = None
    if pb.wire is not None:
        ns = np.asarray(sel)
        wire = {"commit": pb.wire["commit"].reshape(
                    pb.n_values, 128)[ns],
                "d": pb.wire["d"][ns], "v": pb.wire["v"][:, ns],
                "a": pb.wire["a"][:, ns]}
    sel = jnp.asarray(sel)  # drynx: noqa[implicit-dtype]  (generic index array)
    return RangeProofBatch(
        commit=jnp.asarray(pb.commit, dtype=jnp.uint32)[sel], challenge=pb.challenge[sel],
        zr=pb.zr[sel], d=pb.d[sel], zphi=pb.zphi[sel],
        zv=pb.zv[:, sel], v_pts=pb.v_pts[:, sel], a=pb.a[:, sel],
        u=pb.u, l=pb.l, wire=wire)


def create_range_proof_lists_batched(key, secrets_2d, rs_2d, cts_2d, ranges,
                                     sigs_by_u: dict,
                                     ca_pub_table,
                                     tile: int | None = None) -> list:
    """All DPs' payloads in ONE device-batched creation (the single-chip
    harness path: n_dps DPs share the chip, so their per-value-independent
    proofs vectorize into one kernel chain instead of n_dps serialized
    ones — the reference's DPs parallelize the same work across machines,
    data_collection_protocol.go:279-347).

    secrets_2d: (n_dps, V); rs_2d: (n_dps, V, 16); cts_2d: (n_dps, V, 2, 3,
    16); ranges: per-output (u, l) specs (shared by every DP). Returns
    [RangeProofList] per DP, each byte-compatible with per-DP creation
    (same per-value transcripts — the Fiat-Shamir challenge hash is
    per-value, so batching does not change any proof)."""
    secrets_2d = np.asarray(secrets_2d)
    n_dps, V = secrets_2d.shape
    flat_ranges = list(ranges) * n_dps
    big = create_range_proof_list(
        key, secrets_2d.reshape(-1), jnp.asarray(rs_2d, dtype=jnp.uint32).reshape(-1, 16),
        jnp.asarray(cts_2d, dtype=jnp.uint32).reshape(-1, 2, 3, 16), flat_ranges, sigs_by_u,
        ca_pub_table, tile=tile)
    out = []
    for d in range(n_dps):
        batches = []
        for ia, pb in big.batches:
            ia = np.asarray(ia)
            mine = (ia // V) == d
            if not np.any(mine):
                continue
            local_idx = (ia[mine] % V).astype(np.int64)
            batches.append((local_idx, _slice_batch(pb, np.nonzero(mine)[0])))
        out.append(RangeProofList(n_values=V, batches=batches))
    return out


def _batch_shapes_ok(pb: RangeProofBatch, ns_expected: int) -> bool:
    """Tensor-shape consistency for a WIRE-DECODED batch: from_bytes trusts
    the payload's own (u, l, V, ns) header, so a malicious DP can ship a
    structurally-'valid' object whose ns disagrees with the published
    signature roster or whose tensors disagree with each other — the joint
    concat/broadcast would then raise and (before this guard) poison honest
    neighbours' verdicts via the flush-level catch-all."""
    NLb = params.NUM_LIMBS
    try:
        ns, l, V = pb.n_servers, int(pb.l), pb.n_values
        return (ns == ns_expected and l >= 1 and V >= 1
                and tuple(pb.commit.shape) == (V, 2, 3, NLb)
                and tuple(pb.challenge.shape) == (V, NLb)
                and tuple(pb.zr.shape) == (V, NLb)
                and tuple(pb.d.shape) == (V, 3, NLb)
                and tuple(pb.zphi.shape) == (V, l, NLb)
                and tuple(pb.zv.shape) == (ns, V, l, NLb)
                and tuple(pb.v_pts.shape) == (ns, V, l, 3, 2, NLb)
                and tuple(pb.a.shape) == (ns, V, l, 6, 2, NLb))
    except Exception:
        return False


def _list_structure_ok(lst: RangeProofList, ranges,
                       sigs_pub_by_u: dict) -> bool:
    """Coverage check: every output index with a nonzero (u, l) spec must be
    covered by exactly one batch carrying that exact spec (a prover cannot
    substitute a looser range), every batch's base must have published
    signatures, and every batch's ns/tensor shapes must be self-consistent
    (see _batch_shapes_ok)."""
    want = group_ranges(ranges)
    covered = {}
    for ia, pb in lst.batches:
        sigs = sigs_pub_by_u.get(pb.u)
        if sigs is None:
            return False
        if not _batch_shapes_ok(pb, len(sigs)):
            return False
        if len(np.asarray(ia)) != pb.n_values:
            return False
        for i in ia:
            if int(i) in covered:
                return False
            covered[int(i)] = (pb.u, pb.l)
    for (u, l), idx in want.items():
        for i in idx:
            if covered.get(i) != (u, l):
                return False
    return set(covered) == {i for idx in want.values() for i in idx}


def _safe_batch_verify(pb: RangeProofBatch, sigs_pub, ca_pub_table) -> bool:
    """The joint-range verification routing point, with exception
    containment.

    Routing: whenever the proof plane is enabled (>= 2 visible devices,
    parallel/proof_plane.py), the DEFAULT path is the mesh-sharded verifier
    — VN-role devices each verify a proof shard, combined by one GT
    product. Its accept/reject decision is bit-identical to the
    single-device verifier (same rlc_prelude, same per-element programs,
    exact GT arithmetic), so the soundness semantics cannot differ. A
    sharded-path FAILURE (an exception, not a False verdict) falls back to
    the single-device verifier: a plane bug must not reject honest
    payloads.

    Containment: a payload that still manages to crash the kernels
    (despite _batch_shapes_ok) is a FAILED verification for ITSELF — the
    exception must never propagate to the flush-level catch-all, which
    would mark every sampled payload BM_FALSE and poison honest DPs'
    audit entries."""
    try:
        from ..parallel import proof_plane as plane

        if plane.enabled():
            from ..parallel import proof_mesh as pm

            try:
                return pm.rlc_verify_sharded(pb, sigs_pub, ca_pub_table)
            except Exception:
                import traceback

                from ..utils import log

                log.warn("sharded verify raised — falling back to the "
                         "single-device verifier: "
                         + traceback.format_exc(limit=8))
        return verify_range_proofs_batch(pb, sigs_pub, ca_pub_table)
    except Exception:
        import traceback

        from ..utils import log

        log.warn("range batch verify raised (payload rejected): "
                 + traceback.format_exc(limit=8))
        return False


def verify_range_proof_list(lst: RangeProofList, ranges,
                            sigs_pub_by_u: dict, ca_pub_table) -> bool:
    """Verify a mixed-range payload against the QUERY's specs (structure +
    every batch's RLC check)."""
    if not _list_structure_ok(lst, ranges, sigs_pub_by_u):
        return False
    for ia, pb in lst.batches:
        if not _safe_batch_verify(pb, sigs_pub_by_u[pb.u], ca_pub_table):
            return False
    return True


def _concat_batches(pbs: list) -> RangeProofBatch:
    """Concatenate same-spec batches along the value axis."""
    u, l = pbs[0].u, pbs[0].l
    assert all(pb.u == u and pb.l == l for pb in pbs)
    cat = lambda xs, ax: jnp.concatenate([jnp.asarray(x, dtype=jnp.uint32) for x in xs], ax)
    wire = None
    if all(pb.wire is not None for pb in pbs):
        wire = {"commit": np.concatenate(
                    [pb.wire["commit"].reshape(pb.n_values, 128)
                     for pb in pbs], 0),
                "d": np.concatenate([pb.wire["d"] for pb in pbs], 0),
                "v": np.concatenate([pb.wire["v"] for pb in pbs], 1),
                "a": np.concatenate([pb.wire["a"] for pb in pbs], 1)}
    return RangeProofBatch(
        commit=cat([pb.commit for pb in pbs], 0),
        challenge=cat([pb.challenge for pb in pbs], 0),
        zr=cat([pb.zr for pb in pbs], 0),
        d=cat([pb.d for pb in pbs], 0),
        zphi=cat([pb.zphi for pb in pbs], 0),
        zv=cat([pb.zv for pb in pbs], 1),
        v_pts=cat([pb.v_pts for pb in pbs], 1),
        a=cat([pb.a for pb in pbs], 1), u=u, l=l, wire=wire)


def verify_range_proof_payloads_joint(datas: list, ranges,
                                      sigs_pub_by_u: dict,
                                      ca_pub_table) -> list[bool]:
    """Joint verification from RAW payload bytes: each payload deserializes
    in its own guard so one malformed (malicious) payload fails only
    itself — never its honest neighbours."""
    lists: list = []
    idx: list = []
    out = [False] * len(datas)
    for i, d in enumerate(datas):
        try:
            lists.append(RangeProofList.from_bytes(d))
            idx.append(i)
        except Exception:
            from ..utils import log

            log.warn(f"range payload {i}: malformed bytes, rejected")
    if lists:
        for i, ok in zip(idx, verify_range_proof_lists_joint(
                lists, ranges, sigs_pub_by_u, ca_pub_table)):
            out[i] = ok
    return out


def verify_range_proof_lists_joint(lists: list, ranges, sigs_pub_by_u: dict,
                                   ca_pub_table) -> list[bool]:
    """Joint verification of MANY payloads (one per DP): structural checks
    per payload, then ONE RLC batch verification per (u, l) spec over the
    concatenation of every structurally-valid payload's values — a VN
    verifying 10 DPs' proofs pays one shared final exponentiation instead
    of 10 (sound: the RLC weights are drawn across the whole concatenation,
    and each per-value transcript is independent). On a joint failure,
    falls back to per-payload verification so honest payloads are not
    penalized for a neighbour's forgery. Returns one bool per payload."""
    ok_struct = [_list_structure_ok(lst, ranges, sigs_pub_by_u)
                 for lst in lists]
    idx_valid = [i for i, ok in enumerate(ok_struct) if ok]
    if not idx_valid:
        return ok_struct

    by_spec: dict = {}
    for i in idx_valid:
        for _ia, pb in lists[i].batches:
            by_spec.setdefault((pb.u, pb.l), []).append(pb)
    joint_ok = all(
        _safe_batch_verify(_concat_batches(pbs), sigs_pub_by_u[u],
                           ca_pub_table)
        for (u, _l), pbs in by_spec.items())
    if joint_ok:
        return ok_struct
    return [ok_struct[i] and verify_range_proof_list(
        lists[i], ranges, sigs_pub_by_u, ca_pub_table)
        for i in range(len(lists))]


def verify_cross_survey_payloads_joint(payloads_by_sid: dict,
                                       expected_by_sid: dict,
                                       sigs_pub_by_u: dict,
                                       ca_pub_table) -> dict:
    """Joint verification across SURVEYS: the lists_joint algebra one level
    up. Every queued survey's structurally-valid batches at the same
    (u, l) spec concatenate along the value axis into ONE RLC batch check —
    one shared final exponentiation for the whole queue, not one per survey
    (sound for the same reason as within-survey batching: the RLC weights
    are drawn across the whole concatenation and per-value transcripts are
    independent; bit-identity of the GT algebra is asserted by
    tests/test_server.py).

    Isolation ladder on a joint failure: fall back to PER-SURVEY joint
    verification (verify_range_proof_lists_joint), which itself falls back
    to per-payload — so one tampered survey in the batch costs one retry
    level, never its neighbours' verdicts. A survey with expected=None
    (the CN no longer knows it) verifies all-False.

    Returns {survey_id: [bool per payload, in input order]}."""
    lists_by_sid: dict = {}
    out = {sid: [False] * len(datas)
           for sid, datas in payloads_by_sid.items()}
    for sid, datas in payloads_by_sid.items():
        if expected_by_sid.get(sid) is None:
            continue
        entries = []
        for i, d in enumerate(datas):
            try:
                entries.append((i, RangeProofList.from_bytes(d)))
            except Exception:
                from ..utils import log

                log.warn(f"survey {sid} range payload {i}: malformed "
                         f"bytes, rejected")
        lists_by_sid[sid] = entries

    ok_struct: dict = {}
    by_spec: dict = {}
    for sid, entries in lists_by_sid.items():
        ranges = expected_by_sid[sid]
        ok_struct[sid] = {
            i: _list_structure_ok(lst, ranges, sigs_pub_by_u)
            for i, lst in entries}
        for i, lst in entries:
            if not ok_struct[sid][i]:
                continue
            for _ia, pb in lst.batches:
                by_spec.setdefault((pb.u, pb.l), []).append(pb)

    joint_ok = all(
        _safe_batch_verify(_concat_batches(pbs), sigs_pub_by_u[u],
                           ca_pub_table)
        for (u, _l), pbs in by_spec.items())
    for sid, entries in lists_by_sid.items():
        if joint_ok:
            for i, _lst in entries:
                out[sid][i] = ok_struct[sid][i]
        else:
            ranges = expected_by_sid[sid]
            verdicts = verify_range_proof_lists_joint(
                [lst for _i, lst in entries], ranges, sigs_pub_by_u,
                ca_pub_table)
            for (i, _lst), ok in zip(entries, verdicts):
                out[sid][i] = ok
    return out


__all__ = ["RangeSig", "init_range_sig", "sig_gt_table", "to_base",
           "RangeProofBatch",
           "RangeProofList", "group_ranges", "create_range_proofs",
           "create_range_proof_list", "create_range_proof_lists_batched",
           "verify_range_proofs", "verify_range_proofs_batch",
           "verify_range_proof_list", "verify_range_proof_lists_joint",
           "verify_range_proof_payloads_joint",
           "verify_cross_survey_payloads_joint", "rlc_prelude",
           "rlc_total_single", "proof_challenge", "gt_base",
           "gt_base_table", "gt_pow_gtb", "sum_publics_bytes"]
