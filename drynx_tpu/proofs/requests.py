"""Signed proof-request envelopes + VN-side verification with bitmap codes.

Mirrors the reference's lib/proof/structs_proofs.go: every proof (range,
aggregation, obfuscation, shuffle, key-switch) is serialized, Schnorr-signed
by its sender (:117), and shipped to the VNs; a VN verifies the signature and
then — with probability `sample` (rand <= sample, :160,240,317,394,471) —
the payload itself, recording one of the bitmap codes (:22-27):

  BM_FALSE = 0   proof received and verification FAILED
  BM_TRUE  = 1   proof received and verified
  BM_RECVD = 2   proof received, payload verification skipped (sampling)
  BM_BADSIG = 4  signature check failed (payload never inspected)
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Optional

import numpy as np

from . import schnorr

BM_FALSE = 0
BM_TRUE = 1
BM_RECVD = 2
BM_BADSIG = 4

PROOF_TYPES = ("range", "shuffle", "aggregation", "obfuscation", "keyswitch")


@dataclasses.dataclass
class ProofRequest:
    """One signed proof envelope (reference ProofRequest :35-108)."""

    proof_type: str          # one of PROOF_TYPES
    survey_id: str
    sender_id: str
    differ_info: str         # disambiguates several proofs from one sender
    round_id: int
    data: bytes              # serialized proof payload
    signature: schnorr.Signature

    def signed_payload(self) -> bytes:
        return _payload(self.proof_type, self.survey_id, self.sender_id,
                        self.differ_info, self.round_id, self.data)

    def storage_key(self) -> str:
        """bbolt key layout (proof_collection_protocol.go:318-330)."""
        return "/".join([self.survey_id, self.proof_type, self.sender_id,
                         self.differ_info])


def _payload(proof_type: str, survey_id: str, sender_id: str,
             differ_info: str, round_id: int, data: bytes) -> bytes:
    h = hashlib.sha3_256()
    for part in (proof_type.encode(), survey_id.encode(), sender_id.encode(),
                 differ_info.encode(), round_id.to_bytes(8, "big")):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    h.update(data)
    return h.digest()


def new_proof_request(proof_type: str, survey_id: str, sender_id: str,
                      differ_info: str, round_id: int, data: bytes,
                      sender_secret: int) -> ProofRequest:
    """Serialize-and-sign (reference New*ProofRequest :110,188,265,342,420)."""
    if proof_type not in PROOF_TYPES:
        raise ValueError(f"unknown proof type {proof_type!r}")
    sig = schnorr.sign(sender_secret,
                       _payload(proof_type, survey_id, sender_id,
                                differ_info, round_id, data))
    return ProofRequest(proof_type=proof_type, survey_id=survey_id,
                        sender_id=sender_id, differ_info=differ_info,
                        round_id=round_id, data=data, signature=sig)


def verify_proof_request(req: ProofRequest, sender_pub,
                         sample: float,
                         verify_payload: Optional[Callable[[bytes, str], bool]],
                         rng: np.random.Generator) -> int:
    """VN-side verification -> bitmap code (reference VerifyProof family
    :135-492: signature check, then `rand.Float64() <= sample` gates the
    payload verification). `verify_payload(data, survey_id)` — the survey id
    lets the verifier fetch the query's expected parameters (e.g. per-value
    range specs, lib/structs.go:446-533)."""
    if not verify_signature(req, sender_pub):
        return BM_BADSIG
    if verify_payload is None or float(rng.random()) > sample:
        return BM_RECVD
    try:
        ok = verify_payload(req.data, req.survey_id)
    except Exception:
        # a malformed/malicious payload is a FAILED verification, not a
        # crash: the proof must still be counted so the survey's expected-
        # proof counter drains and the (dirty) audit block can commit.
        # Log it — an honest deployment hitting a verifier bug would
        # otherwise be indistinguishable from a malicious prover.
        import traceback

        from ..utils import log

        log.warn(f"verify_payload raised for {req.storage_key()}: "
                 f"{traceback.format_exc(limit=8)}")
        ok = False
    return BM_TRUE if ok else BM_FALSE


def verify_signature(req: ProofRequest, sender_pub) -> bool:
    """Signature-only check (reference VerifyProofSignature :498-505)."""
    return schnorr.verify(sender_pub, req.signed_payload(), req.signature)


__all__ = ["BM_FALSE", "BM_TRUE", "BM_RECVD", "BM_BADSIG", "PROOF_TYPES",
           "ProofRequest", "new_proof_request", "verify_proof_request",
           "verify_signature"]
