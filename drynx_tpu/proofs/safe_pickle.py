"""Restricted unpickling for network-received proof payloads.

VNs deserialize proof bodies sent by the very parties they exist to distrust
(reference threat model: malicious DPs/CNs caught by ZK proofs). A plain
`pickle.loads` on attacker-controlled bytes is remote code execution — a
crafted `__reduce__` payload runs arbitrary callables during load. This
module allows only the value types proofs legitimately contain: numpy / jax
array machinery and the proof dataclasses of this package.

(The range-proof payload has its own fixed-layout byte codec and never goes
through pickle; aggregation/obfuscation/keyswitch/shuffle bodies use this.)
"""
from __future__ import annotations

import io
import pickle

_ALLOWED_MODULE_ROOTS = ("numpy", "jax", "jaxlib", "drynx_tpu")
# module -> names (exact) for the few stdlib pieces object pickling needs
_ALLOWED_EXACT = {
    "builtins": {"complex", "frozenset", "list", "set", "tuple", "dict",
                 "bytearray"},
    "copyreg": {"_reconstructor"},
    "collections": {"OrderedDict"},
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        root = module.split(".")[0]
        if root in _ALLOWED_MODULE_ROOTS:
            return super().find_class(module, name)
        if name in _ALLOWED_EXACT.get(module, ()):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"proof payload references forbidden global {module}.{name}")


def safe_loads(data: bytes):
    """pickle.loads restricted to proof-shaped content; raises
    pickle.UnpicklingError on anything else."""
    return _RestrictedUnpickler(io.BytesIO(data)).load()


__all__ = ["safe_loads"]
