"""Schnorr signatures on bn256 G1 with sha3-512 Fiat-Shamir.

The reference Schnorr-signs every proof request payload and VNs check the
signature before (sampled) payload verification (kyber sign/schnorr used at
lib/proof/structs_proofs.go:117,498-505). Signing is a rare host-side event
(once per proof request); verification is offered both host-side and as a
batched device kernel for VN bulk checking.

Scheme: R = k·B, c = H(R ‖ pub ‖ msg) mod n, s = k + c·x;
verify: s·B == R + c·P.
"""
from __future__ import annotations

import dataclasses
import secrets

import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import elgamal as eg
from ..crypto import field as F
from ..crypto import params, refimpl
from . import encoding as enc


@dataclasses.dataclass(frozen=True)
class Signature:
    r_bytes: bytes  # canonical G1 point (64 B)
    s_bytes: bytes  # canonical scalar (32 B)

    def to_bytes(self) -> bytes:
        return self.r_bytes + self.s_bytes

    @classmethod
    def from_bytes(cls, b: bytes) -> "Signature":
        return cls(bytes(b[:64]), bytes(b[64:96]))


def _point_bytes_host(pt) -> bytes:
    """Host affine int pair (or None) -> canonical 64 bytes."""
    if pt is None:
        return b"\x00" * 64
    x, y = pt
    return int(x).to_bytes(32, "big") + int(y).to_bytes(32, "big")


def _challenge(r_bytes: bytes, pub_bytes: bytes, msg: bytes) -> int:
    import hashlib

    h = hashlib.sha3_512()
    h.update(r_bytes)
    h.update(pub_bytes)
    h.update(msg)
    return int.from_bytes(h.digest(), "big") % params.N


# secret -> affine public point. A host g1_mul is ~100 ms of pure-Python
# field inversions; a long-lived sender (stream engines sign one envelope
# per sealed pane) would otherwise pay it on every signature.
_PUB_CACHE: dict[int, tuple] = {}


def _pub_for(secret: int):
    pub = _PUB_CACHE.get(secret)
    if pub is None:
        pub = _PUB_CACHE[secret] = refimpl.g1_mul(refimpl.G1, secret)
    return pub


def sign(secret: int, msg: bytes, k: int | None = None) -> Signature:
    """Schnorr-sign msg with secret scalar. Host-side (rare path)."""
    if k is None:
        k = secrets.randbelow(params.N - 1) + 1
    R = refimpl.g1_mul(refimpl.G1, k)
    # the public key is public by construction (dlog hides the scalar)
    pub = _pub_for(secret)  # drynx: declassify[secret]
    r_bytes = _point_bytes_host(R)
    c = _challenge(r_bytes, _point_bytes_host(pub), msg)
    # the Schnorr response is public by construction: c is bound to the
    # commitment, so s reveals neither k nor the secret scalar
    s = (k + c * secret) % params.N  # drynx: declassify[secret]
    return Signature(r_bytes, s.to_bytes(32, "big"))


def verify(pub, msg: bytes, sig: Signature) -> bool:
    """Host-side verification. pub: affine int pair."""
    s = int.from_bytes(sig.s_bytes, "big")
    c = _challenge(sig.r_bytes, _point_bytes_host(pub), msg)
    rx = int.from_bytes(sig.r_bytes[:32], "big")
    ry = int.from_bytes(sig.r_bytes[32:], "big")
    R = None if (rx == 0 and ry == 0) else (rx, ry)
    lhs = refimpl.g1_mul(refimpl.G1, s)
    rhs = refimpl.g1_add(R, refimpl.g1_mul(pub, c))
    return lhs == rhs


def verify_batch(pubs, msgs: list[bytes], sigs: list[Signature]) -> np.ndarray:
    """Batched device verification of many signatures (VN bulk path).

    pubs: list of affine int pairs. Returns bool (n,).
    """
    n = len(sigs)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    cs = np.zeros((n, params.NUM_LIMBS), dtype=np.uint32)
    ss = np.zeros_like(cs)
    Rs = np.zeros((n, 3, params.NUM_LIMBS), dtype=np.uint32)
    Ps = np.zeros_like(Rs)
    for i, (p, m, sg) in enumerate(zip(pubs, msgs, sigs)):
        c = _challenge(sg.r_bytes, _point_bytes_host(p), m)
        cs[i] = F.from_int(c)
        ss[i] = enc.bytes_to_limbs(np.frombuffer(sg.s_bytes, dtype=np.uint8))
        rx = int.from_bytes(sg.r_bytes[:32], "big")
        ry = int.from_bytes(sg.r_bytes[32:], "big")
        Rs[i] = C.from_ref(None if rx == 0 and ry == 0 else (rx, ry))
        Ps[i] = C.from_ref(p)
    lhs = eg.fixed_base_mul(eg.BASE_TABLE.table, jnp.asarray(ss, dtype=jnp.uint32))
    rhs = C.add(jnp.asarray(Rs, dtype=jnp.uint32), C.scalar_mul(jnp.asarray(Ps, dtype=jnp.uint32), jnp.asarray(cs, dtype=jnp.uint32)))
    return np.asarray(C.eq(lhs, rhs))


__all__ = ["Signature", "sign", "verify", "verify_batch"]
