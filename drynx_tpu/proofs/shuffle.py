"""Verifiable shuffle proof for the DRO (re-randomizing shuffle) phase.

Capability parity with the reference's Neff-shuffle proofs (unlynx
ShuffleProofCreation/Verification via kyber's shuffle, used at
lib/proof/structs_proofs.go:342-418 and services/service.go:488-496). The
protocol here is the standard Neff-style argument re-derived from first
principles:

Statement: output ElGamal pairs (Ā_j, B̄_j) are a permutation π +
re-encryption of inputs (A_i, B_i) under generators (G, H):
    Ā_j = A_{π(j)} + β_{π(j)}·G,   B̄_j = B_{π(j)} + β_{π(j)}·H.

Proof (Fiat–Shamir, all challenges hashed from the transcript):
 1. Random public exponents e_1..e_k are derived from (inputs, outputs).
 2. The prover publishes Γ = γ·G and Y_j = γ·e_{π(j)}·G (blinded permuted
    exponents) and proves, via a SimpleShuffle (product-equality ILMPP
    chain), that {log Y_j} = {γ·e_i} as multisets.
 3. A generalized Schnorr proof ties the exponents to the ciphertexts:
    knowledge of (y_j = log Y_j, γ, s) with
        Σ_j y_j·Ā_j − γ·Σ_i e_i·A_i − s·G = 0
        Σ_j y_j·B̄_j − γ·Σ_i e_i·B_i − s·H = 0
    (where s = γ·Σ_j e_{π(j)}·β_{π(j)}). By Schwartz–Zippel over the
    random e_i, both together imply the shuffle statement.

Scalar arithmetic (mod-n chains, inverses) runs host-side with Python ints
(k values, cheap); every point operation is a batched device kernel.

ILMPP (iterated log-multiplication proof), proving Π log X_i = Π log Y_i for
known logs: commitments A_1 = θ_1·Y_1, A_i = θ_{i-1}·X_i + θ_i·Y_i,
A_m = θ_{m-1}·X_m; responses r_i = θ_i + (−1)^i·c·Π_{j≤i}(x_j/y_j); checks
A_1 = r_1·Y_1 + c·X_1, A_i = r_{i-1}·X_i + r_i·Y_i,
A_m = r_{m-1}·X_m + (−1)^m·c·Y_m.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import curve as C
from ..crypto import elgamal as eg
from ..crypto import field as F
from ..crypto import params, refimpl
from . import encoding as enc

N = params.N


# ---------------------------------------------------------------------------
# Batched point helpers
# ---------------------------------------------------------------------------

def _msm(points, scalars_int) -> jnp.ndarray:
    """Multi-scalar multiplication Σ k_i·P_i (batch scalar-mul + tree sum).

    points (k, 3, 16); scalars_int: list/array of python ints mod n.
    """
    from ..crypto import batching as B

    ks = jnp.asarray(np.stack([F.from_int(s % N) for s in scalars_int]), dtype=jnp.uint32)
    prods = B.g1_scalar_mul(points, ks)
    return B.tree_reduce_add(prods, B.g1_add)


def _base_muls(scalars_int) -> jnp.ndarray:
    ks = jnp.asarray(np.stack([F.from_int(s % N) for s in scalars_int]), dtype=jnp.uint32)
    return eg.fixed_base_mul(eg.BASE_TABLE.table, ks)


def _hash_points_to_scalars(count: int, *point_arrays) -> list[int]:
    """Derive `count` mod-n scalars from canonical bytes of point tensors."""
    import hashlib

    h0 = hashlib.sha3_256()
    for pa in point_arrays:
        h0.update(np.ascontiguousarray(enc.g1_bytes(pa)).tobytes())
    seed = h0.digest()
    out = []
    for i in range(count):
        h = hashlib.sha3_512()
        h.update(seed)
        h.update(i.to_bytes(8, "big"))
        out.append(int.from_bytes(h.digest(), "big") % N)
    return out


# ---------------------------------------------------------------------------
# ILMPP
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ILMPPProof:
    commits: jnp.ndarray    # (m, 3, 16)
    responses: list[int]    # m-1 scalars
    challenge: int


def _rand_scalar(rng: np.random.Generator) -> int:
    """Uniform nonzero scalar mod n (512 random bits reduced — bias 2^-256).

    Short/structured nonces are a real break here: a lattice or kangaroo
    attack on z = θ + c·x with θ below ~n^(1/2) recovers the witness and,
    for the shuffle, the permutation."""
    return int.from_bytes(rng.bytes(64), "little") % (N - 1) + 1


def ilmpp_prove(xs: list[int], ys: list[int], X, Y, rng) -> ILMPPProof:
    """xs, ys: known logs (Π xs == Π ys mod n); X, Y: (m, 3, 16) points."""
    m = len(xs)
    thetas = [_rand_scalar(rng) for _ in range(m - 1)]
    # commitments
    A = [None] * m
    scal_x = [0] + thetas            # coefficient of X_i in A_i
    scal_y = thetas + [0]            # coefficient of Y_i in A_i
    Ax = C.scalar_mul(X, jnp.asarray(np.stack(
        [F.from_int(s % N) for s in scal_x]), dtype=jnp.uint32))
    Ay = C.scalar_mul(Y, jnp.asarray(np.stack(
        [F.from_int(s % N) for s in scal_y]), dtype=jnp.uint32))
    commits = C.add(Ax, Ay)

    c = _hash_points_to_scalars(1, X, Y, commits)[0]

    # responses r_i = θ_i + (−1)^i·c·Π_{j≤i}(x_j/y_j)
    responses = []
    prod = 1
    sign = 1
    for i in range(m - 1):
        prod = prod * xs[i] % N * pow(ys[i], N - 2, N) % N
        sign = -sign
        r = (thetas[i] + sign * c * prod) % N
        responses.append(r)
    return ILMPPProof(commits=commits, responses=responses, challenge=c)


def ilmpp_verify(proof: ILMPPProof, X, Y) -> bool:
    m = int(X.shape[0])
    if len(proof.responses) != m - 1:
        return False
    c = _hash_points_to_scalars(1, X, Y, proof.commits)[0]
    if c != proof.challenge:
        return False
    r = proof.responses
    # recompute expected commitments: A_1 = r_1·Y_1 + c·X_1;
    # A_i = r_{i-1}·X_i + r_i·Y_i; A_m = r_{m-1}·X_m + (−1)^m·c·Y_m
    sign_m = 1 if m % 2 == 0 else -1
    scal_x = [c] + r[: m - 1]
    scal_y = r[: m - 1] + [sign_m * c]
    Ax = C.scalar_mul(X, jnp.asarray(np.stack(
        [F.from_int(s % N) for s in scal_x]), dtype=jnp.uint32))
    Ay = C.scalar_mul(Y, jnp.asarray(np.stack(
        [F.from_int(s % N) for s in scal_y]), dtype=jnp.uint32))
    expect = C.add(Ax, Ay)
    return bool(np.all(np.asarray(C.eq(expect, proof.commits))))


# ---------------------------------------------------------------------------
# Full shuffle proof
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShuffleProof:
    gamma_pt: jnp.ndarray    # Γ = γ·G (3, 16)
    y_pts: jnp.ndarray       # (k, 3, 16) blinded permuted exponents
    ilmpp: ILMPPProof        # product-equality argument over 2k elements
    t_pts: jnp.ndarray       # (k, 3, 16) Schnorr commitments for y_j
    t_gamma: jnp.ndarray     # (3, 16)
    t_a: jnp.ndarray         # (3, 16)
    t_b: jnp.ndarray         # (3, 16)
    z: list[int]             # k responses for y_j
    z_gamma: int
    z_s: int
    challenge: int

    def to_bytes(self) -> bytes:
        k = int(self.y_pts.shape[0])
        head = np.asarray([k], dtype=np.int64).tobytes()
        parts = [enc.g1_bytes(self.gamma_pt), enc.g1_bytes(self.y_pts),
                 enc.g1_bytes(self.ilmpp.commits), enc.g1_bytes(self.t_pts),
                 enc.g1_bytes(self.t_gamma), enc.g1_bytes(self.t_a),
                 enc.g1_bytes(self.t_b)]
        scal = np.asarray(
            self.ilmpp.responses + [self.ilmpp.challenge] + self.z
            + [self.z_gamma, self.z_s, self.challenge], dtype=object)
        sb = b"".join(int(s).to_bytes(32, "big") for s in scal)
        return head + b"".join(np.ascontiguousarray(p).tobytes()
                               for p in parts) + sb


def _derive_exponents(in_cts, out_cts) -> list[int]:
    k = int(in_cts.shape[0])
    return _hash_points_to_scalars(
        k, in_cts.reshape(-1, 3, in_cts.shape[-1]),
        out_cts.reshape(-1, 3, out_cts.shape[-1]))


def prove_shuffle(in_cts, out_cts, perm, betas_int, h_pt,
                  rng: np.random.Generator) -> ShuffleProof:
    """in_cts/out_cts: (k, 2, 3, 16) ElGamal pairs with
    out[j] = in[perm[j]] + Enc_{betas[j]}(0); betas_int: k python-int
    re-encryption scalars indexed by OUTPUT position (matching
    parallel.dro.shuffle_rerandomize); h_pt: (3, 16) the public key H."""
    k = int(in_cts.shape[0])
    perm = np.asarray(perm)
    e = _derive_exponents(in_cts, out_cts)
    gamma = _rand_scalar(rng)

    y = [gamma * e[int(perm[j])] % N for j in range(k)]   # logs of Y_j
    y_pts = _base_muls(y)
    gamma_pt = _base_muls([gamma])[0]

    # SimpleShuffle via ILMPP over 2k: (e_i·G ‖ Γ×k) vs (Y_j ‖ G×k)
    e_pts = _base_muls(e)
    ones = jnp.broadcast_to(jnp.asarray(C.from_ref(refimpl.G1), dtype=jnp.uint32),
                            (k, 3, e_pts.shape[-1]))
    gammas = jnp.broadcast_to(gamma_pt, (k, 3, e_pts.shape[-1]))
    X_seq = jnp.concatenate([e_pts, gammas], axis=0)
    Y_seq = jnp.concatenate([y_pts, ones], axis=0)
    xs = e + [gamma] * k
    ys = y + [1] * k
    ilmpp = ilmpp_prove(xs, ys, X_seq, Y_seq, rng)

    # generalized Schnorr for ciphertext consistency
    A_in, B_in = in_cts[:, 0], in_cts[:, 1]
    A_out, B_out = out_cts[:, 0], out_cts[:, 1]
    SA = _msm(A_in, e)
    SB = _msm(B_in, e)
    s = gamma * sum(e[int(perm[j])] * betas_int[j] % N
                    for j in range(k)) % N

    th = [_rand_scalar(rng) for _ in range(k + 2)]
    th_y, (th_g, th_s) = th[:k], th[k:]
    t_pts = _base_muls(th_y)
    t_gamma = _base_muls([th_g])[0]
    t_a = C.add(_msm(A_out, th_y),
                C.neg(C.add(C.scalar_mul(SA, jnp.asarray(F.from_int(th_g), dtype=jnp.uint32)),
                            _base_muls([th_s])[0])))
    t_b = C.add(_msm(B_out, th_y),
                C.neg(C.add(C.scalar_mul(SB, jnp.asarray(F.from_int(th_g), dtype=jnp.uint32)),
                            C.scalar_mul(h_pt, jnp.asarray(F.from_int(th_s), dtype=jnp.uint32)))))

    c = _hash_points_to_scalars(
        1, y_pts, gamma_pt[None], t_pts, t_gamma[None], t_a[None],
        t_b[None])[0]
    z = [(th_y[j] + c * y[j]) % N for j in range(k)]
    z_gamma = (th_g + c * gamma) % N
    z_s = (th_s + c * s) % N
    return ShuffleProof(gamma_pt=gamma_pt, y_pts=y_pts, ilmpp=ilmpp,
                        t_pts=t_pts, t_gamma=t_gamma, t_a=t_a, t_b=t_b,
                        z=z, z_gamma=z_gamma, z_s=z_s, challenge=c)


def verify_shuffle(proof: ShuffleProof, in_cts, out_cts, h_pt) -> bool:
    k = int(in_cts.shape[0])
    if int(proof.y_pts.shape[0]) != k or len(proof.z) != k:
        return False
    e = _derive_exponents(in_cts, out_cts)

    # 1. SimpleShuffle part
    e_pts = _base_muls(e)
    nl = e_pts.shape[-1]
    ones = jnp.broadcast_to(jnp.asarray(C.from_ref(refimpl.G1), dtype=jnp.uint32), (k, 3, nl))
    gammas = jnp.broadcast_to(proof.gamma_pt, (k, 3, nl))
    X_seq = jnp.concatenate([e_pts, gammas], axis=0)
    Y_seq = jnp.concatenate([proof.y_pts, ones], axis=0)
    if not ilmpp_verify(proof.ilmpp, X_seq, Y_seq):
        return False

    # 2. generalized Schnorr part
    c = _hash_points_to_scalars(
        1, proof.y_pts, proof.gamma_pt[None], proof.t_pts,
        proof.t_gamma[None], proof.t_a[None], proof.t_b[None])[0]
    if c != proof.challenge:
        return False

    z_pts = _base_muls(proof.z)
    rhs_y = C.add(proof.t_pts, C.scalar_mul(proof.y_pts,
                                            jnp.asarray(F.from_int(c), dtype=jnp.uint32)))
    if not bool(np.all(np.asarray(C.eq(z_pts, rhs_y)))):
        return False
    if not bool(np.all(np.asarray(C.eq(
            _base_muls([proof.z_gamma])[0],
            C.add(proof.t_gamma, C.scalar_mul(proof.gamma_pt,
                                              jnp.asarray(F.from_int(c), dtype=jnp.uint32))))))):
        return False

    A_in, B_in = in_cts[:, 0], in_cts[:, 1]
    A_out, B_out = out_cts[:, 0], out_cts[:, 1]
    SA = _msm(A_in, e)
    SB = _msm(B_in, e)
    zg = jnp.asarray(F.from_int(proof.z_gamma), dtype=jnp.uint32)
    lhs_a = C.add(_msm(A_out, proof.z),
                  C.neg(C.add(C.scalar_mul(SA, zg),
                              _base_muls([proof.z_s])[0])))
    lhs_b = C.add(_msm(B_out, proof.z),
                  C.neg(C.add(C.scalar_mul(SB, zg),
                              C.scalar_mul(h_pt, jnp.asarray(
                                  F.from_int(proof.z_s), dtype=jnp.uint32)))))
    # relation points are the identity, so lhs == t + c·0 = t
    ok_a = bool(np.all(np.asarray(C.eq(lhs_a, proof.t_a))))
    ok_b = bool(np.all(np.asarray(C.eq(lhs_b, proof.t_b))))
    return ok_a and ok_b


__all__ = ["ILMPPProof", "ilmpp_prove", "ilmpp_verify", "ShuffleProof",
           "prove_shuffle", "verify_shuffle"]
