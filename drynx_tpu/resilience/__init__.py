"""Control-plane resilience: fault injection, retry policy, quorum knobs.

The reference Drynx stack targets *Byzantine* faults with ZK proofs but
leaves crash/availability faults unhandled — a survey is one-shot and a
failed node aborts it (SURVEY.md §"Failure detection"). This package is
the availability half: a seeded deterministic fault-injection layer for
the TCP control plane (:mod:`.faults`), and one place where every retry,
backoff, and timeout number lives (:mod:`.policy`). The lint rule
``hardcoded-timeout`` (drynx_tpu/analysis/rules.py) keeps it that way:
bare timeout/retry literals outside this package fail CI.

Quorum semantics (the third leg — degraded surveys over the DPs/VNs that
actually answered) live where the survey runs: ``service/node.py``
(`_h_survey_query`, `_h_end_verification`) and ``service/service.py``
(LocalCluster), parameterized by ``SurveyQuery.min_dp_quorum`` /
``SurveyQuery.vn_quorum``. ROBUSTNESS.md documents the whole model.
"""
from .faults import FaultPlan, FaultSpec, fault_plan, set_fault_plan
from .policy import DEFAULT_POLICY, RetryPolicy, is_idempotent

__all__ = ["FaultPlan", "FaultSpec", "fault_plan", "set_fault_plan",
           "RetryPolicy", "DEFAULT_POLICY", "is_idempotent"]
