"""Seeded, deterministic fault injection for the TCP control plane.

A :class:`FaultPlan` is a list of :class:`FaultSpec` rules plus a seed.
``service/transport.py`` consults the process-global plan at four hook
points — the same module-global pattern as its ``LinkModel``:

  where="connect"  client side, before the TCP connect     (refuse, delay)
  where="request"  client side, around sending one frame   (drop, delay,
                                                            corrupt,
                                                            close_mid_frame)
  where="reply"    server side, around sending the reply   (same kinds —
                                                            "the peer died
                                                            mid-answer")
  where="node"     server side, the whole node             (kill, pause)

Determinism: every draw is keyed, not streamed. A link-level event draws
from ``np.random.default_rng((seed, spec_idx, name_key(target), seq))``
where ``seq`` is that (spec, target)'s own invocation counter — so whether
a probabilistic spec fires depends only on the plan seed, the target node,
and how many times *that node* hit the hook, never on the global arrival
order of traffic. The concurrent fan-out (service/node.py) interleaves
RPCs across worker threads nondeterministically; per-node keying keeps
the 17 chaos scenarios and the kill-DP soak seed-reproducible anyway.
``count`` caps are per-(spec, target) for the same reason (a global cap
would be consumed by whichever thread arrived first); ``spec.fired``
remains the total across targets. Node-level verdicts are keyed per
(spec, node) and memoized so "is dp3 dead?" never flips mid-run. Two runs
with the same plan seed take identical per-node fault decisions whatever
the traffic interleaving (asserted in tests/test_resilience.py and
tests/test_net_plane.py).

No transport import here (transport imports *us*); no jax import either —
like the analysis package, chaos tooling must work when the accelerator
stack is broken.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import threading
from typing import Optional

import numpy as np

from .policy import named_lock


def _name_key(name: str) -> int:
    """Stable 64-bit key for a node name (``hash()`` is salted per
    process, useless for cross-run determinism)."""
    return int.from_bytes(
        hashlib.blake2s(name.encode(), digest_size=8).digest(), "big")

KINDS = ("refuse", "drop", "delay", "close_mid_frame", "corrupt",
         "kill", "pause")
WHERES = ("connect", "request", "reply", "node")


@dataclasses.dataclass
class FaultSpec:
    """One fault rule. ``target`` is an fnmatch pattern over node names
    ("dp3", "dp*", "*"); ``mtype`` filters by message type for
    request/reply hooks ("*" = any). ``prob`` gates each firing through
    the spec's seeded stream; ``count`` caps total firings (None =
    unlimited). ``delay_s`` parameterizes delay/pause."""

    where: str
    kind: str
    target: str = "*"
    mtype: str = "*"
    prob: float = 1.0
    count: Optional[int] = None
    delay_s: float = 0.0
    fired: int = 0     # mutated under the plan lock

    def __post_init__(self):
        if self.where not in WHERES:
            raise ValueError(f"unknown fault hook {self.where!r}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind in ("kill", "pause") and self.where != "node":
            raise ValueError(f"{self.kind!r} is a node-level fault")

    def matches(self, target: str, mtype: str) -> bool:
        return (fnmatch.fnmatchcase(target, self.target)
                and (self.mtype == "*" or self.mtype == mtype))


class FaultPlan:
    """A seeded set of fault rules + an explicit kill set.

    Thread-safe: transport handler threads and client threads consult the
    plan concurrently; all draw/counter state mutates under one lock.
    """

    def __init__(self, seed: int = 0, specs=()):
        self.seed = int(seed)
        self.specs: list[FaultSpec] = []
        self._killed: set[str] = set()
        self._node_verdicts: dict[tuple[int, str], bool] = {}
        self._seq: dict[tuple[int, str], int] = {}       # draw counters
        self._fired_by: dict[tuple[int, str], int] = {}  # per-target caps
        self._lock = named_lock("faultplan_lock")
        for s in specs:
            self.add(s)

    def add(self, spec: FaultSpec) -> FaultSpec:
        with self._lock:
            self.specs.append(spec)
        return spec

    # -- node-level state ------------------------------------------------
    def kill(self, name: str) -> None:
        """Hard-kill: the node's server closes every connection without
        answering, and clients refuse to dial it."""
        with self._lock:
            self._killed.add(name)

    def revive(self, name: str) -> None:
        with self._lock:
            self._killed.discard(name)

    def killed(self, name: str) -> bool:
        with self._lock:
            if name in self._killed:
                return True
            return self._node_verdict(name, "kill") is not None

    def node_fault(self, name: str) -> Optional[FaultSpec]:
        """The node-level spec (kill or pause) applying to ``name``, if
        any. Verdicts are drawn once per (spec, node) and memoized — a
        node is dead or alive for the whole run, never flapping."""
        with self._lock:
            if name in self._killed:
                return FaultSpec(where="node", kind="kill", target=name)
            for kind in ("kill", "pause"):
                s = self._node_verdict(name, kind)
                if s is not None:
                    return s
        return None

    def _node_verdict(self, name: str, kind: str) -> Optional[FaultSpec]:
        # caller holds the lock
        for i, s in enumerate(self.specs):
            if s.where != "node" or s.kind != kind:
                continue
            if not s.matches(name, "*"):
                continue
            key = (i, name)
            if key not in self._node_verdicts:
                self._node_verdicts[key] = (
                    s.prob >= 1.0
                    or float(np.random.default_rng(
                        (self.seed, i, _name_key(name))).random()) < s.prob)
            if self._node_verdicts[key]:
                return s
        return None

    # -- link-level draws ------------------------------------------------
    def pick(self, where: str, target: str,
             mtype: str = "*") -> Optional[FaultSpec]:
        """First matching link-level spec that fires for this event, with
        its counter consumed. Draws are keyed on (plan seed, spec index,
        target node, that pair's own event counter): the verdict for
        "dp3's second connect" is the same whether dp3 dialed second or
        sixth, so concurrent fan-out cannot perturb a seeded schedule."""
        with self._lock:
            for i, s in enumerate(self.specs):
                if s.where != where or s.where == "node":
                    continue
                if not s.matches(target, mtype):
                    continue
                key = (i, target)
                if (s.count is not None
                        and self._fired_by.get(key, 0) >= s.count):
                    continue
                seq = self._seq.get(key, 0)
                self._seq[key] = seq + 1
                fires = (s.prob >= 1.0
                         or float(np.random.default_rng(
                             (self.seed, i, _name_key(target),
                              seq)).random()) < s.prob)
                if fires:
                    self._fired_by[key] = self._fired_by.get(key, 0) + 1
                    s.fired += 1
                    return s
        return None

    def describe(self) -> str:
        with self._lock:
            rows = [f"{s.where}/{s.kind} target={s.target} mtype={s.mtype} "
                    f"p={s.prob} fired={s.fired}" for s in self.specs]
            if self._killed:
                rows.append(f"killed={sorted(self._killed)}")
        return f"FaultPlan(seed={self.seed}): " + ("; ".join(rows) or "empty")


# Process-global active plan, mirroring transport's LinkModel pattern.
# None (the default) means every hook is a no-op.
_PLAN: Optional[FaultPlan] = None


def fault_plan() -> Optional[FaultPlan]:
    return _PLAN


def set_fault_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN
    _PLAN = plan


__all__ = ["FaultSpec", "FaultPlan", "fault_plan", "set_fault_plan",
           "KINDS", "WHERES"]
